"""The simulation driver: the full scenario -> year pipeline as one
jitted, shardable device program per model year.

Replaces the reference's driver loop (reference dgen_model.py:242-463):
per year it (1) applies the 13 on_frame trajectory mutations, (2) sizes
every agent through the bill/cashflow/dispatch hot loop, (3) runs the
max-market-share -> Bass-diffusion market step with historical
anchoring, (4) allocates integer battery adopters, and (5) aggregates
state-hourly net load — but where the reference round-trips a pandas
frame through a spawn pool and Postgres (dgen_model.py:309-384), here a
whole model year is ONE compiled XLA program over the HBM-resident
agent table, and the cross-year carry (the reference's
``market_last_year_df`` handoff, diffusion_functions_elec.py:136-156)
is a small pytree threaded between year invocations.

Sharding: pass a :class:`jax.sharding.Mesh` and the driver lays the
agent axis over it (NamedSharding); the only cross-device traffic is
the state x sector segment reductions (tiny psums over ICI), matching
the reference's per-state GCP-Batch sharding (SURVEY.md §2.6) but
within one program. True multi-process (jax.distributed) runs place
global arrays from each process's addressable shards and persist via
collective orbax saves + per-process export shards.

Scale: ``RunConfig.agent_chunk`` streams the agent axis through the
sizing engine in fixed chunks (lax.scan), bounding peak HBM to one
chunk — the measured single-chip path for ~1M-agent national
populations past the ~50k whole-table ceiling. Runs with no per-year
host consumer additionally pipeline year steps on device and drain
once at the end.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.models.agents import AgentTable, ProfileBank, pad_table
from dgen_tpu.models.market import (
    MarketState,
    allocate_battery_adopters,
    anchor_to_observed,
    diffusion_step,
    initial_market_shares,
    max_market_share,
)
from dgen_tpu.models.scenario import ScenarioInputs, apply_year
from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops import dispatch as dispatch_ops
from dgen_tpu.ops import sizing as sizing_ops
from dgen_tpu.ops.tariff import NET_BILLING, TariffBank
from dgen_tpu.parallel.mesh import agent_spec
from dgen_tpu.resilience.faults import corrupt_point, corrupt_rows, fault_point
from dgen_tpu.utils import timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


# ---------------------------------------------------------------------------
# Carry and per-year outputs
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SimCarry:
    """Cross-year device state: the reference's ``market_last_year_df``
    plus the battery-adopter cumulative it tracks alongside
    (dgen_model.py:420-427)."""

    market: MarketState
    batt_adopters_cum: jax.Array  # [N]

    @staticmethod
    def zeros(n: int) -> "SimCarry":
        return SimCarry(
            market=MarketState.zeros(n),
            batt_adopters_cum=jnp.zeros(n, dtype=jnp.float32),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class YearOutputs:
    """Per-agent results for one model year (the dense analogue of the
    columns the reference writes to ``agent_outputs`` per year,
    dgen_model.py:441-463)."""

    # sizing / economics (financial_functions.py:522-565)
    system_kw: jax.Array
    npv: jax.Array
    payback_period: jax.Array
    cash_flow: jax.Array                  # [N, Y+1]
    energy_value_pv_only: jax.Array       # [N, Y] nominal bill savings
    first_year_bill_with_system: jax.Array
    first_year_bill_without_system: jax.Array
    batt_kw: jax.Array
    batt_kwh: jax.Array
    # market step (diffusion_functions_elec.py:24-156)
    max_market_share: jax.Array
    market_share: jax.Array
    new_adopters: jax.Array
    number_of_adopters: jax.Array
    new_system_kw: jax.Array
    system_kw_cum: jax.Array
    market_value: jax.Array
    # storage attachment (attachment_rate_functions.py:58-148)
    new_batt_adopters: jax.Array
    batt_adopters_cum: jax.Array
    batt_kw_cum: jax.Array
    batt_kwh_cum: jax.Array
    # avoided-emissions accounting (reference apply_carbon_intensities,
    # elec.py:595: the intensity column rides along to agent_outputs)
    carbon_intensity_t_per_kwh: jax.Array
    avoided_co2_t: jax.Array              # cum fleet production x intensity
    # state-hourly aggregate (attachment_rate_functions.py:151-201);
    # shape [n_states, 8760] MW, or [0, 0] when hourly export is off
    state_hourly_net_mw: jax.Array


# ---------------------------------------------------------------------------
# The year step
# ---------------------------------------------------------------------------

def build_econ_inputs(
    table: AgentTable,
    profiles: ProfileBank,
    tariffs: TariffBank,
    ya,
    nem_allowed: jax.Array,
    incentives,
    rate_switch: bool = False,
) -> sizing_ops.AgentEconInputs:
    """Assemble the per-agent economics environment for one year.

    Gathers the 8760 banks (replacing the reference's per-agent SQL
    profile fetches, agent_mutation/elec.py:508-558), applies the retail
    price multiplier to the tariff (elec.py:29
    ``apply_elec_price_multiplier_and_escalator`` scales agent prices),
    and forces net billing where the NEM policy gate has closed
    (elec.py:449-505 ``get_nem_settings``/``filter_nem_year``).
    """
    mult = ya.elec_price_multiplier

    def gather(idx, gate_metering=True):
        at = jax.vmap(lambda k: bill_ops.gather_tariff(tariffs, k))(idx)
        metering = at.metering
        if gate_metering:
            metering = jnp.where(
                nem_allowed > 0, at.metering,
                jnp.full_like(at.metering, NET_BILLING),
            )
        return at._replace(
            price=at.price * mult[:, None, None],
            sell_price=at.sell_price * mult[:, None],
            metering=metering,
        )

    at = gather(table.tariff_idx)
    # DG-rate switch on adoption (reference apply_rate_switch,
    # agent_mutation/elec.py:838): with-system bills price on the
    # switched tariff wherever the SIZED kW lands in the switch window
    # (selected per candidate in ops.sizing). The switched rate keeps
    # its own bank metering ungated — the reference forces NEM on for a
    # taken switch (elec.py:852 sets the limit to 1e6) — while
    # out-of-window candidates fall back to the gated original tariff.
    # ``rate_switch`` is static (decided host-side) so no-switch
    # populations skip the second gather entirely.
    at_w = (
        gather(table.tariff_switch_idx, gate_metering=False)
        if rate_switch else None
    )

    # int8 quantized banks (RunConfig.quant_banks): gather the CODES
    # and fold the per-agent load multiplier into the gathered dequant
    # scale instead of the stream — the [N, 8760] hot-loop streams stay
    # one byte per hour end to end (ops.sizing dequantizes only at the
    # f32 precision floors)
    quant = profiles.load_scale is not None
    if quant:
        load = profiles.load[table.load_idx]
        load_scale = (
            profiles.load_scale[table.load_idx] * ya.load_kwh_per_customer
        )
        gen_per_kw = profiles.solar_cf[table.cf_idx]
        gen_scale = profiles.solar_cf_scale[table.cf_idx]
    else:
        # multipliers are cast to the bank dtype BEFORE the product so
        # bf16 profile banks (RunConfig.bf16_banks) stay bf16 through
        # the gathered [N, 8760] streams — a f32 multiplier would
        # silently promote them and forfeit the halved HBM footprint
        # (no-op for the default f32 banks)
        bdt = profiles.load.dtype
        load = profiles.load[table.load_idx] * \
            ya.load_kwh_per_customer[:, None].astype(bdt)
        gen_per_kw = profiles.solar_cf[table.cf_idx]
        load_scale = gen_scale = None
    # Net-billing sell rate = this year's wholesale price x retail
    # multiplier (reference financial_functions.py:182; wholesale
    # itself is merged per year, elec.py:608)
    ts_sell = (
        profiles.wholesale[table.region_idx]
        * (mult * ya.wholesale_multiplier)[:, None].astype(
            profiles.wholesale.dtype)
    )

    # NEM system-size limit caps the sizing bracket while NEM is active;
    # agents with a DG-rate switch are exempt — the switch forces NEM on
    # regardless of size (reference elec.py:852 sets the limit to 1e6)
    has_switch = table.switch_min_kw < 1e29
    nem_kw_cap = jnp.where(
        (nem_allowed > 0) & jnp.logical_not(has_switch),
        table.nem_kw_limit, 1e30,
    )

    return sizing_ops.AgentEconInputs(
        load=load,
        gen_per_kw=gen_per_kw,
        ts_sell=ts_sell,
        tariff=at,
        tariff_w=at_w,
        fin=ya.fin,
        inc=incentives,
        load_kwh_per_customer=ya.load_kwh_per_customer,
        elec_price_escalator=ya.elec_price_escalator,
        pv_degradation=ya.pv_degradation,
        system_capex_per_kw=ya.system_capex_per_kw,
        system_capex_per_kw_combined=ya.system_capex_per_kw_combined,
        batt_capex_per_kwh_combined=ya.batt_capex_per_kwh_combined,
        cap_cost_multiplier=ya.cap_cost_multiplier,
        value_of_resiliency_usd=ya.value_of_resiliency,
        one_time_charge=table.one_time_charge,
        nem_kw_cap=nem_kw_cap,
        switch_min_kw=table.switch_min_kw,
        switch_max_kw=table.switch_max_kw,
        batt_rt_eff=ya.batt_rt_eff,
        load_scale=load_scale,
        gen_scale=gen_scale,
    )


#: Conservative upper bound on any state's cumulative installed kW a run
#: can reach (f32 segment sums of per-agent kW; a national all-sector
#: total is ~1e9 kW, and the data plane's "no cap" sentinel is >= 1e29).
#: The static all-NEM proof evaluates the gate AT this bound, which makes
#: it sound for every reachable capacity; ``debug_invariants`` re-checks
#: the bound against the live state totals each year.
STATE_KW_BOUND = np.float32(1e28)


def _nem_allowed_arrays(
    state_idx, nem_first_year, nem_sunset_year, nem_kw_limit,
    cap_row, year, state_kw_last,
):
    """The single NEM availability predicate — three gates, all from the
    reference's NEM machine (agent_mutation/elec.py:449-505): the state
    cumulative-capacity cap (vs LAST step's installed kW), the per-agent
    availability window (``filter_nem_year``, elec.py:449-454), and a
    positive per-agent system-kW limit (the reference's fillna(0) = no
    NEM, elec.py:119).

    Backend-polymorphic (operators + fancy indexing only): the traced
    year step calls it with jax arrays and the host-side static proof
    (:func:`nem_gate_never_closes`) calls it with numpy — both paths
    evaluate the SAME gates, so they cannot drift apart.
    """
    cap_gate = (state_kw_last < cap_row)[state_idx]
    window = (nem_first_year <= year) & (year <= nem_sunset_year)
    return cap_gate & window & (nem_kw_limit > 0)


def starting_state_kw(table: AgentTable, inputs: ScenarioInputs) -> jax.Array:
    """[n_states] installed PV kW BEFORE the first model year — the
    base-year capacity the year-1 NEM cap gate compares against
    (reference calc_state_capacity_by_year, agent_mutation/elec.py:788
    seeds from the starting capacities). Derived purely from the group
    layout (starting_kw is [G] = state x sector), so it is row-subset
    invariant: the serving engine evaluates it for gathered agent
    buckets against the SAME state totals as a full run's first year.
    """
    group_state = jnp.arange(table.n_groups, dtype=jnp.int32) // table.n_sectors
    return jax.ops.segment_sum(inputs.starting_kw, group_state, table.n_states)


def compute_nem_allowed(
    table: AgentTable,
    inputs: ScenarioInputs,
    year_idx: jax.Array,
    state_kw_last: jax.Array,
) -> jax.Array:
    """[N] float32 mask: 1 where net metering remains available
    (:func:`_nem_allowed_arrays` on the traced year-step inputs)."""
    return _nem_allowed_arrays(
        table.state_idx, table.nem_first_year, table.nem_sunset_year,
        table.nem_kw_limit, inputs.nem_cap_kw[year_idx],
        inputs.years[year_idx], state_kw_last,
    ).astype(jnp.float32)


def nem_gate_never_closes(
    state_idx: np.ndarray,
    nem_cap_kw: np.ndarray,
    nem_first_year: np.ndarray,
    nem_sunset_year: np.ndarray,
    nem_kw_limit: np.ndarray,
    years: List[int],
) -> bool:
    """Host-side static proof that :func:`compute_nem_allowed` returns
    1 for every given agent in every model year, derived by evaluating
    the SAME predicate (:func:`_nem_allowed_arrays`) with numpy inputs:
    one pass per model year with every state pinned at
    :data:`STATE_KW_BOUND` installed kW (the worst reachable capacity).
    Used to statically drop net-billing bill paths
    (``Simulation._net_billing``)."""
    caps = np.asarray(nem_cap_kw)                  # [n_years, n_states]
    state_idx = np.asarray(state_idx)
    first = np.asarray(nem_first_year)
    sunset = np.asarray(nem_sunset_year)
    limit = np.asarray(nem_kw_limit)
    worst = np.full(caps.shape[1], STATE_KW_BOUND, np.float32)
    return all(
        bool(np.all(_nem_allowed_arrays(
            state_idx, first, sunset, limit,
            caps[yi], np.float32(yr), worst,
        )))
        for yi, yr in enumerate(years)
    )


# ---------------------------------------------------------------------------
# Agent-axis chunking (the streaming year step)
# ---------------------------------------------------------------------------
#
# The whole-table year step materializes ~a dozen [N, 8760] f32
# intermediates — ~0.3-0.5 MB per agent at peak, a ~50k-agent ceiling on
# a 16 GB chip. National populations (the reference runs ~M agents by
# sharding states across batch tasks, submit_all.sh:8-46) instead stream
# the agent axis through the sizing engine in fixed chunks via lax.scan:
# XLA reuses one chunk's buffers across iterations, so peak HBM is one
# chunk's intermediates plus the small [N] per-agent outputs. The market
# step (pure [N] vectors) still runs whole-table.
#
# Chunk layout is shard-aware: under a d-device mesh the agent axis is
# laid out shard-major ([d, L] local blocks), so chunks are built as
# [d, K, c] -> [K, d*c] — every chunk holds each device's NEXT c local
# rows and no cross-device resharding is needed between chunks.

#: Live f32 [8760]-hour intermediates per agent at the sizing engine's
#: peak (load/gen/sell/bucket, net profiles, dispatch traces — XLA
#: reuses buffers, so this is the measured envelope, not the op count):
#: calibrated against the v5e whole-table wall (32k agents fit a 16 GB
#: chip, 65k does not -> true footprint is 250-490 KB/agent; 10 hour
#: arrays + the [r_pad, B_PAD] kernel outputs model that window).
#: Per-configuration deltas (validated by the end-of-run modeled-vs-
#: actual peak log and tests/test_hbm_model.py's hardware grid):
_LIVE_HOUR_ARRAYS = 10
_LIVE_HOUR_ARRAYS_HOURLY = 3   # keep_hourly net profiles (with_hourly)
#: rate-switch runs feed the fused pair kernel two extra month-padded
#: (sell, period) streams and keep a second [r_pad, B_PAD] output live
_LIVE_HOUR_ARRAYS_RATE_SWITCH = 2
#: statically-proven all-NEM runs never build per-candidate hour grids
#: (linear identity only): load/gen/sell/period for linear_sums plus
#: dispatch traces
_LIVE_HOUR_ARRAYS_ALL_NEM = 6
#: under bf16 profile banks, the bank-derived streams (load/gen/sell +
#: their month-padded repacks) ride at 2 bytes/hour; this many of the
#: envelope's hour arrays stay 4-byte — the int32 period stream plus
#: the f32 dispatch trace (the SOC recursion upcasts; ops.sizing)
_LIVE_HOUR_ARRAYS_F32 = 2
#: under int8 quantized banks, the load/gen code streams + their month
#: repacks ride at ONE byte/hour (sell keeps the bank float dtype)
_LIVE_HOUR_ARRAYS_QUANT = 4
_HBM_RESERVE_FRAC = 0.2        # compiler scratch / fragmentation
#: persistent whole-table bytes per agent row ([N] outputs/carry, ~50
#: f32 fields) — shared with the sweep planner's global-budget checks
#: (sweep.plan) so the two byte models cannot drift
_PERSISTENT_ROW_BYTES = 50 * 4
#: the smallest lane-aligned streaming chunk the year step runs at
_CHUNK_FLOOR_ROWS = 128


def default_hbm_bytes() -> Optional[int]:
    """Per-device accelerator memory in bytes, or None when unknown
    (non-TPU backends — auto-chunking then stays off and tests on
    virtual CPU meshes keep whole-table semantics)."""
    if jax.default_backend() != "tpu":
        return None
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # tunneled/virtual devices may not expose stats
        pass
    return 16 * 1024**3  # v5e/v6e-class default


def _per_agent_step_bytes(
    *,
    sizing_iters: int,
    econ_years: int,
    with_hourly: bool,
    net_billing: bool = True,
    rate_switch: bool = False,
    bank_bf16: bool = False,
    bank_quant: bool = False,
) -> int:
    """Modeled peak HBM bytes per agent of one streaming-chunk step —
    the single footprint model shared by the chunk chooser and the
    end-of-run modeled-vs-actual validation log.

    ``bank_bf16`` (RunConfig.bf16_banks): the bank-derived hour streams
    ride at 2 bytes, an f32 floor (:data:`_LIVE_HOUR_ARRAYS_F32`, plus
    the keep_hourly net profiles, which downstream state aggregation
    consumes in f32) stays at 4, and the [r_pad, B_PAD] candidate sums
    are stored at bank precision too (billpallas._sums_out_dtype:
    bf16 in -> bf16 out) — the default configuration models ~1.8x
    fewer bytes per agent, and the auto chunk grows to match.

    ``bank_quant`` (RunConfig.quant_banks): the load/gen-derived
    streams (:data:`_LIVE_HOUR_ARRAYS_QUANT`, the gathered codes plus
    their month repacks) drop to ONE byte per hour; the sell stream
    keeps the bank float dtype (2 with bf16, else 4), the f32 floor
    grows by the dequantized dispatch-load copy, and the candidate
    sums store f32 (int8 in -> f32 out). Models roughly half the
    bf16 per-agent bytes in the default configuration — the auto
    chunk roughly doubles again.
    """
    from dgen_tpu.ops.billpallas import B_PAD, H_PAD, _round8

    r_pad = _round8(max(sizing_iters, 4) * econ_years)
    if not net_billing:
        hour_arrays = _LIVE_HOUR_ARRAYS_ALL_NEM
        kernel_outs = 0          # no bucket-sums kernel at all
    else:
        hour_arrays = _LIVE_HOUR_ARRAYS
        kernel_outs = 2
        if rate_switch:
            hour_arrays += _LIVE_HOUR_ARRAYS_RATE_SWITCH
            kernel_outs += 1     # second tariff's [r_pad, B_PAD] sums
    f32_floor = _LIVE_HOUR_ARRAYS_F32
    if bank_quant:
        f32_floor += 1           # the dequantized dispatch-load copy
    if with_hourly:
        hour_arrays += _LIVE_HOUR_ARRAYS_HOURLY
        f32_floor += _LIVE_HOUR_ARRAYS_HOURLY
    f32_floor = min(f32_floor, hour_arrays)
    bank_b = 2 if bank_bf16 else 4
    if bank_quant:
        one_b = min(_LIVE_HOUR_ARRAYS_QUANT, hour_arrays - f32_floor)
        hour_bytes = (
            4 * f32_floor + 1 * one_b
            + bank_b * (hour_arrays - f32_floor - one_b)
        )
        # int8 alone -> f32 sums; composed with bf16 banks the sums
        # store at the bf16 sell stream's precision (_sums_out_dtype)
        out_bytes = 2 if bank_bf16 else 4
    elif bank_bf16:
        hour_bytes = 4 * f32_floor + 2 * (hour_arrays - f32_floor)
        out_bytes = 2
    else:
        hour_bytes = 4 * hour_arrays
        out_bytes = 4
    return hour_bytes * H_PAD + out_bytes * kernel_outs * r_pad * B_PAD


def auto_agent_chunk(
    n_local: int,
    *,
    sizing_iters: int,
    econ_years: int,
    with_hourly: bool,
    hbm_bytes: Optional[int],
    net_billing: bool = True,
    rate_switch: bool = False,
    bank_bf16: bool = False,
    bank_quant: bool = False,
) -> int:
    """Derive the per-device streaming chunk from the HBM budget.

    Returns 0 (whole-table) when the population fits, else the largest
    lane-aligned (multiple-of-128) chunk whose working set fits. The
    reference's operator never chooses memory shapes — the batch yamls
    fix the machine per state bin (batch_job_yamls/
    dgen-batch-job-small-states.yaml:25,73-75); here the driver knows
    the per-agent footprint and does the same job in-process.
    """
    if not hbm_bytes or n_local <= 0:
        return 0
    per_agent = _per_agent_step_bytes(
        sizing_iters=sizing_iters, econ_years=econ_years,
        with_hourly=with_hourly, net_billing=net_billing,
        rate_switch=rate_switch, bank_bf16=bank_bf16,
        bank_quant=bank_quant,
    )
    budget = int(hbm_bytes * (1.0 - _HBM_RESERVE_FRAC))
    # persistent whole-table state ([N] outputs/carry, ~50 f32 fields)
    budget -= n_local * _PERSISTENT_ROW_BYTES
    fit = budget // per_agent
    if n_local <= fit:
        return 0
    floor = _CHUNK_FLOOR_ROWS
    return max(floor, int(fit // floor) * floor)


def _n_chunks(n: int, d: int, chunk: int) -> int:
    """Number of scan chunks (1 = whole-table path). Trace-time."""
    if not chunk:
        return 1
    if n % d:
        raise ValueError(f"{n} agents do not shard over {d} devices")
    local = n // d
    if local <= chunk:
        return 1
    if local % chunk:
        raise ValueError(
            f"per-device agent count {local} is not a multiple of "
            f"agent_chunk {chunk}; pad the table (models.agents.pad_table)"
        )
    return local // chunk


def _to_chunks(x: jax.Array, d: int, K: int) -> jax.Array:
    """[N, ...] -> [K, N//K, ...] keeping each device's rows local."""
    n = x.shape[0]
    c = n // (d * K)
    if d == 1:
        return x.reshape((K, c) + x.shape[1:])
    y = x.reshape((d, K, c) + x.shape[1:])
    y = jnp.moveaxis(y, 0, 1)
    return y.reshape((K, d * c) + x.shape[1:])


def _from_chunks(y: jax.Array, d: int, K: int) -> jax.Array:
    """Inverse of :func:`_to_chunks` on scan-stacked outputs."""
    n = y.shape[0] * y.shape[1]
    if d == 1:
        return y.reshape((n,) + y.shape[2:])
    c = y.shape[1] // d
    z = y.reshape((K, d, c) + y.shape[2:])
    z = jnp.moveaxis(z, 1, 0)
    return z.reshape((n,) + y.shape[2:])


def _constrain_chunked(mesh: Mesh, a: jax.Array) -> jax.Array:
    """Pin a [K, C, ...] chunked leaf to P(None, <agent axes>, ...) —
    dim 1 (the per-chunk agent rows) shards over every mesh axis
    (hosts x devices grids included, parallel.mesh.agent_spec)."""
    spec = agent_spec(mesh, a.ndim, axis=1)
    return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))


def _cluster_slice(x: jax.Array, d: int, local: int, off: int,
                   seg: int) -> jax.Array:
    """Slice one cluster's per-device segment out of a cluster-major
    [N, ...] leaf: rows [off, off+seg) of EACH device's ``local`` rows,
    re-flattened so the result keeps one contiguous block per device
    (the shape the agent sharding expects)."""
    if d == 1:
        return x[off:off + seg]
    y = x.reshape((d, local) + x.shape[1:])
    y = y[:, off:off + seg]
    return y.reshape((d * seg,) + x.shape[1:])


def _cluster_concat(parts: list, d: int) -> jax.Array:
    """Inverse of :func:`_cluster_slice` over all clusters: concatenate
    per-cluster [d*seg_c, ...] results back into the cluster-major
    device layout (each device's segments contiguous again)."""
    if d == 1:
        return jnp.concatenate(parts, axis=0)
    segs = [p.reshape((d, p.shape[0] // d) + p.shape[1:]) for p in parts]
    cat = jnp.concatenate(segs, axis=1)
    return cat.reshape((cat.shape[0] * cat.shape[1],) + cat.shape[2:])


def _size_clustered(
    table: AgentTable,
    profiles: ProfileBank,
    ya,
    nem_allowed: jax.Array,
    cluster,
    cluster_banks,
    cluster_tidx: jax.Array,
    *,
    econ_years: int,
    sizing_iters: int,
    keep_hourly: bool,
    sizing_impl: str,
    mesh: Optional[Mesh],
    n_dev: int,
    agent_chunk: int,
    net_billing: bool,
    daylight,
    pack_once: bool,
    soft_tau: Optional[float],
):
    """Cluster-batched sizing: run the engine once per tariff cluster
    at the cluster's TIGHT pad widths (ops.tariffcluster) against its
    shared compact rate bank — single-period clusters statically skip
    the TOU period scatter, single-tier clusters the tier clip, and
    flat/NEM clusters route to the linear program via their proven
    per-cluster ``net_billing`` flag. The table is already laid out
    cluster-major within each device shard, so every slice is a static
    per-device block and the concatenated result is in table order."""
    local = cluster.local_len
    parts = []
    for spec, bank in zip(cluster.clusters, cluster_banks):
        sl = partial(_cluster_slice, d=n_dev, local=local,
                     off=spec.offset, seg=spec.seg_len)
        tbl_c, ya_c, nem_c, tidx_c = jax.tree.map(
            sl, (table, ya, nem_allowed, cluster_tidx)
        )
        tbl_c = dataclasses.replace(
            tbl_c, tariff_idx=tidx_c, tariff_switch_idx=tidx_c
        )
        # a globally-False flag (a pinned sweep group / an all-NEM run)
        # wins over the per-cluster proof; True per-cluster flags stay
        # exact either way (False is only a compile-time skip)
        nb_c = net_billing and spec.net_billing
        n_chunks_c = _n_chunks(n_dev * spec.seg_len, n_dev, agent_chunk)

        def _size_one(tbl_i, ya_i, nem_i, hourly, nb=nb_c, bank=bank,
                      n_per=spec.n_periods):
            envs_i = build_econ_inputs(
                tbl_i, profiles, bank, ya_i, nem_i, tbl_i.incentives,
                rate_switch=False,
            )
            return sizing_ops.size_agents(
                envs_i, n_periods=n_per, n_years=econ_years,
                n_iters=sizing_iters, keep_hourly=hourly,
                impl=sizing_impl, mesh=mesh, net_billing=nb,
                daylight=daylight, pack_once=pack_once, soft_tau=soft_tau,
            )

        if n_chunks_c > 1:
            xs = jax.tree.map(
                lambda a: _to_chunks(a, n_dev, n_chunks_c),
                (tbl_c, ya_c, nem_c),
            )
            if mesh is not None:
                xs = jax.tree.map(partial(_constrain_chunked, mesh), xs)

            def _chunk(_, xs_i):
                t_i, y_i, m_i = xs_i
                return None, _size_one(t_i, y_i, m_i, False)

            _, res_k = jax.lax.scan(_chunk, None, xs)
            res_c = jax.tree.map(
                lambda a: _from_chunks(a, n_dev, n_chunks_c), res_k
            )
        else:
            if mesh is not None:
                def _pin_seg(a):
                    return jax.lax.with_sharding_constraint(
                        a, NamedSharding(mesh, agent_spec(mesh, a.ndim))
                    )

                tbl_c, ya_c, nem_c = jax.tree.map(
                    _pin_seg, (tbl_c, ya_c, nem_c))
            res_c = _size_one(tbl_c, ya_c, nem_c, keep_hourly)
        parts.append(res_c)
    return jax.tree.map(
        lambda *ps: _cluster_concat(list(ps), n_dev), *parts
    )


def year_step_impl(
    table: AgentTable,
    profiles: ProfileBank,
    tariffs: TariffBank,
    inputs: ScenarioInputs,
    carry: SimCarry,
    year_idx: jax.Array,
    *,
    n_periods: int,
    econ_years: int,
    sizing_iters: int,
    first_year: bool,
    with_hourly: bool,
    storage_enabled: bool,
    year_step_len: float,
    sizing_impl: str = "auto",
    rate_switch: bool = False,
    mesh: Optional[Mesh] = None,
    agent_chunk: int = 0,
    net_billing: bool = True,
    daylight=None,
    pack_once: bool = False,
    soft_tau: Optional[float] = None,
    anchor: bool = True,
    cluster=None,
    cluster_banks=None,
    cluster_tidx: Optional[jax.Array] = None,
) -> tuple[SimCarry, YearOutputs]:
    """One model year as a single device program.

    ``daylight``: optional billpallas.DaylightLayout (a hashable STATIC
    host constant, like the month layout it compacts) — the sizing
    search's import kernels run daylight-compacted; None keeps the
    full-hour oracle path. ``pack_once``: gather the month-positional
    candidate streams once per sizing call (RunConfig.pack_once).
    ``soft_tau``: the differentiable smooth-boundary twin
    (RunConfig.soft_boundaries -> :mod:`dgen_tpu.grad`): soft
    import/export splits and tier clips inside sizing, an unrounded
    payback, and linear interpolation through the max-market-share
    table instead of the round-to-decile gather — so the whole year
    step is differentiable w.r.t. scenario leaves. ``None`` (default)
    traces the bit-exact hard program. ``cluster``: optional STATIC
    ops.tariffcluster.ClusterLayout — the table is laid out
    cluster-major per device shard and sizing runs once per tariff
    cluster at tight pad widths against the traced ``cluster_banks``
    (compact TariffBanks) indexed by ``cluster_tidx`` ([N] local rows);
    requires ``rate_switch=False``. ``anchor=False`` (static) drops
    the historical-anchoring blend entirely — the calibration rollout
    (:mod:`dgen_tpu.grad.calibrate`) fits the UNanchored model to
    observations, and the anchor rescale's tiny-denominator guards
    produce 0/0 tangents under linearization.

    Mirrors the reference's per-year sequence (dgen_model.py:242-438):
    trajectory application -> sizing -> max market share -> (initial
    shares | diffusion) -> anchoring -> battery allocation -> carry.
    """
    n_states = table.n_states
    n_groups = table.n_groups
    g = table.group_idx

    ya = apply_year(table, inputs, year_idx)

    # --- NEM gate on last year's state cumulative capacity; in the
    # first year that is the starting installed capacity, not the
    # (zeroed) carry (reference calc_state_capacity_by_year,
    # agent_mutation/elec.py:788) ---
    if first_year:
        state_kw_last = starting_state_kw(table, inputs)
    else:
        state_kw_last = jax.ops.segment_sum(
            carry.market.system_kw_cum, table.state_idx, n_states
        )
    nem_allowed = compute_nem_allowed(table, inputs, year_idx, state_kw_last)

    n_dev = int(mesh.devices.size) if mesh is not None else 1
    n_chunks = _n_chunks(table.n_agents, n_dev, agent_chunk)

    if cluster is not None:
        if rate_switch:
            raise ValueError(
                "cluster layouts cannot price rate-switch runs: a "
                "base/switch tariff pair can straddle two clusters"
            )
        # --- cluster-batched sizing: one program per tariff cluster at
        # the cluster's tight pad widths (ops.tariffcluster); hourly
        # profiles stay dropped when the global layout chunks (the
        # remat branch below rebuilds them) ---
        res = _size_clustered(
            table, profiles, ya, nem_allowed, cluster, cluster_banks,
            cluster_tidx,
            econ_years=econ_years, sizing_iters=sizing_iters,
            keep_hourly=with_hourly and n_chunks == 1,
            sizing_impl=sizing_impl, mesh=mesh, n_dev=n_dev,
            agent_chunk=agent_chunk, net_billing=net_billing,
            daylight=daylight, pack_once=pack_once, soft_tau=soft_tau,
        )
    elif n_chunks > 1:
        # --- streaming hot loop: scan agent chunks through the sizing
        # engine; XLA reuses one chunk's [C, 8760] buffers so peak HBM
        # stays bounded regardless of N ---
        xs = jax.tree.map(
            lambda a: _to_chunks(a, n_dev, n_chunks),
            (table, ya, nem_allowed),
        )
        if mesh is not None:
            xs = jax.tree.map(partial(_constrain_chunked, mesh), xs)

        def _size_chunk(_, xs_c):
            tbl_c, ya_c, nem_c = xs_c
            envs_c = build_econ_inputs(
                tbl_c, profiles, tariffs, ya_c, nem_c, tbl_c.incentives,
                rate_switch=rate_switch,
            )
            res_c = sizing_ops.size_agents(
                envs_c, n_periods=n_periods, n_years=econ_years,
                n_iters=sizing_iters, keep_hourly=False, impl=sizing_impl,
                mesh=mesh, net_billing=net_billing, daylight=daylight,
                pack_once=pack_once, soft_tau=soft_tau,
            )
            return None, res_c

        _, res_k = jax.lax.scan(_size_chunk, None, xs)
        res = jax.tree.map(
            lambda a: _from_chunks(a, n_dev, n_chunks), res_k
        )
    else:
        envs = build_econ_inputs(
            table, profiles, tariffs, ya, nem_allowed, table.incentives,
            rate_switch=rate_switch,
        )

        # --- hot loop: size every agent (financial_functions.py:291) ---
        res = sizing_ops.size_agents(
            envs, n_periods=n_periods, n_years=econ_years,
            n_iters=sizing_iters, keep_hourly=with_hourly, impl=sizing_impl,
            mesh=mesh, net_billing=net_billing, daylight=daylight,
            pack_once=pack_once, soft_tau=soft_tau,
        )

    # --- market step ---
    mms = max_market_share(
        res.payback_period, table.sector_idx, inputs.mms_table,
        interp=soft_tau is not None,
    ) * table.mask

    if first_year:
        mstate = initial_market_shares(
            inputs.starting_kw, inputs.starting_batt_kw,
            inputs.starting_batt_kwh, g, ya.developable_agent_weight,
            res.system_kw, n_groups,
        )
        # starting batt capacity -> adopter count at this year's sized
        # batt_kw; agents sized to ~0 kW get 0 adopters, not a blow-up
        batt_adopters_prev = jnp.where(
            res.batt_kw > 1e-6, mstate.batt_kw_cum / jnp.maximum(res.batt_kw, 1e-6), 0.0
        )
    else:
        mstate = carry.market
        batt_adopters_prev = carry.batt_adopters_cum

    out = diffusion_step(
        mstate, mms, res.system_kw, ya.system_capex_per_kw,
        ya.developable_agent_weight,
        inputs.bass_p[g], inputs.bass_q[g], inputs.teq_yr1[g],
        is_first_year=first_year, year_step=year_step_len,
    )

    # --- historical anchoring (blend; anchor_years_mask selects) ---
    if anchor:
        am = inputs.anchor_years_mask[year_idx]
        kw_anch, adopt_anch, share_anch = anchor_to_observed(
            out.system_kw_cum, g, inputs.observed_kw[year_idx],
            (table.sector_idx == 0), ya.developable_agent_weight, n_groups,
        )
        kw_cum = am * kw_anch + (1.0 - am) * out.system_kw_cum
        adopters = am * adopt_anch + (1.0 - am) * out.number_of_adopters
        share = am * share_anch + (1.0 - am) * out.market_share
    else:
        kw_cum = out.system_kw_cum
        adopters = out.number_of_adopters
        share = out.market_share
    new_adopters = jnp.maximum(adopters - mstate.adopters_cum, 0.0)
    new_kw = jnp.maximum(kw_cum - mstate.system_kw_cum, 0.0)

    # --- integer battery-adopter allocation ---
    if storage_enabled:
        new_batt = allocate_battery_adopters(
            new_adopters, g, inputs.attachment_rate, table.agent_id, n_groups
        ) * table.mask
    else:
        new_batt = jnp.zeros_like(new_adopters)
    batt_adopters_cum = batt_adopters_prev + new_batt
    batt_kw_cum = mstate.batt_kw_cum + new_batt * res.batt_kw
    batt_kwh_cum = mstate.batt_kwh_cum + new_batt * res.batt_kwh

    # --- state-hourly aggregate (attachment_rate_functions.py:177-201):
    # mix baseline / PV-only / PV+batt profiles by adopter counts ---
    if with_hourly:
        # integer allocation can grant a battery unit to an agent whose
        # fractional adopter count is below 1; cap the battery-profile
        # weight at the agent's adopter count so households aren't
        # counted twice in the mix
        batt_mix = jnp.minimum(batt_adopters_cum, adopters)
        pv_only = jnp.maximum(adopters - batt_mix, 0.0)
        base_cnt = jnp.maximum(ya.customers_in_bin - adopters, 0.0)
        if n_chunks > 1:
            # the sizing scan dropped the per-agent hourly profiles;
            # rematerialize them chunk-by-chunk (one extra dispatch per
            # chunk — FLOPs traded for HBM, the jax.checkpoint pattern)
            # and accumulate the state segment sum in the scan carry
            xs_h = jax.tree.map(
                lambda a: _to_chunks(a, n_dev, n_chunks),
                (
                    table.load_idx, table.cf_idx, table.state_idx,
                    table.mask, ya.load_kwh_per_customer, ya.batt_rt_eff,
                    res.system_kw, res.batt_kw, res.batt_kwh,
                    base_cnt, pv_only, batt_mix,
                ),
            )
            if mesh is not None:
                xs_h = jax.tree.map(partial(_constrain_chunked, mesh), xs_h)

            def _hourly_chunk(acc, xs_c):
                (li, ci, st, mk, lkpc, rt, kw, bkw, bkwh,
                 b_cnt, p_only, b_mix) = xs_c
                if profiles.load_scale is not None:
                    # int8 quantized banks: rematerialize the f32
                    # profiles via the per-row dequant scales (the
                    # keep_hourly floor stays f32, ops.sizing rule)
                    load = profiles.load[li].astype(jnp.float32) * (
                        profiles.load_scale[li] * lkpc
                    )[:, None]
                    gen = profiles.solar_cf[ci].astype(jnp.float32) * (
                        profiles.solar_cf_scale[ci]
                        * kw * sizing_ops.INV_EFF
                    )[:, None]
                else:
                    load = profiles.load[li] * lkpc[:, None]
                    gen = profiles.solar_cf[ci] * (
                        kw * sizing_ops.INV_EFF
                    )[:, None]
                dr = jax.vmap(dispatch_ops.dispatch_battery)(
                    load, gen, bkw, bkwh, rt
                )
                base_p, pv_p, batt_p = sizing_ops.net_hourly_profiles(
                    load, gen, dr.system_out
                )
                net_c = (
                    b_cnt[:, None] * base_p
                    + p_only[:, None] * pv_p
                    + b_mix[:, None] * batt_p
                ) * mk[:, None]
                return acc + jax.ops.segment_sum(net_c, st, n_states), None

            acc0 = jnp.zeros(
                (n_states, profiles.hours), dtype=jnp.float32
            )
            state_hourly, _ = jax.lax.scan(_hourly_chunk, acc0, xs_h)
            state_hourly = state_hourly / 1000.0  # kW -> MW
        else:
            net = (
                base_cnt[:, None] * res.baseline_net_hourly
                + pv_only[:, None] * res.adopter_net_hourly_pvonly
                + batt_mix[:, None] * res.adopter_net_hourly_with_batt
            ) * table.mask[:, None]
            state_hourly = jax.ops.segment_sum(
                net, table.state_idx, n_states
            ) / 1000.0  # kW -> MW
        if mesh is not None:
            # the exporter reads this [S, H] aggregate from process 0
            # only; pin it replicated so GSPMD cannot shard it and leave
            # rows non-addressable mid-export
            state_hourly = jax.lax.with_sharding_constraint(
                state_hourly, NamedSharding(mesh, P())
            )
    else:
        state_hourly = jnp.zeros((0, 0), dtype=jnp.float32)

    new_market = MarketState(
        market_share=share,
        max_market_share=mms,
        adopters_cum=adopters,
        market_value=out.market_value,
        system_kw_cum=kw_cum,
        batt_kw_cum=batt_kw_cum,
        batt_kwh_cum=batt_kwh_cum,
        initial_adopters=mstate.initial_adopters,
        initial_market_share=mstate.initial_market_share,
    )
    carbon_t = inputs.carbon_intensity_t_per_kwh[year_idx][table.state_idx]

    new_carry = SimCarry(market=new_market, batt_adopters_cum=batt_adopters_cum)

    outputs = YearOutputs(
        system_kw=res.system_kw,
        npv=res.npv,
        payback_period=res.payback_period,
        cash_flow=res.cash_flow,
        energy_value_pv_only=res.energy_value_pv_only,
        first_year_bill_with_system=res.first_year_bill_with_system,
        first_year_bill_without_system=res.first_year_bill_without_system,
        batt_kw=res.batt_kw,
        batt_kwh=res.batt_kwh,
        max_market_share=mms,
        market_share=share,
        new_adopters=new_adopters,
        number_of_adopters=adopters,
        new_system_kw=new_kw,
        system_kw_cum=kw_cum,
        market_value=out.market_value,
        new_batt_adopters=new_batt,
        batt_adopters_cum=batt_adopters_cum,
        batt_kw_cum=batt_kw_cum,
        batt_kwh_cum=batt_kwh_cum,
        carbon_intensity_t_per_kwh=carbon_t,
        avoided_co2_t=kw_cum * res.naep * carbon_t,
        state_hourly_net_mw=state_hourly,
    )
    if mesh is not None:
        # pin every [N]-leading result back to the agent sharding: the
        # integer battery allocation sorts the WHOLE table, and GSPMD
        # would otherwise leave everything downstream of that sort
        # replicated — N live copies of per-agent state per device and
        # non-addressable rows under multi-host (dgenlint J8)
        n = table.n_agents

        def _pin(x):
            if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] == n:
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, agent_spec(mesh, x.ndim))
                )
            return x

        new_carry, outputs = jax.tree.map(_pin, (new_carry, outputs))
    return new_carry, outputs


#: names of year_step's compile-time arguments — shared with the sweep
#: engine (dgen_tpu.sweep.driver), whose vmapped program jits the same
#: impl over a scenario axis with the same static set
YEAR_STEP_STATIC_ARGNAMES = (
    "n_periods", "econ_years", "sizing_iters", "first_year",
    "with_hourly", "storage_enabled", "year_step_len", "sizing_impl",
    "rate_switch", "mesh", "agent_chunk", "net_billing", "daylight",
    "pack_once", "soft_tau", "anchor", "cluster",
)

#: the jitted one-year program. The cross-year carry is threaded
#: linearly (every caller rebinds it), so XLA may alias the update in
#: place instead of holding two copies of the [N]-leaf market state per
#: year (dgenlint L7). ``year_step_impl`` stays reachable un-jitted so
#: the sweep engine can vmap it over a scenario axis inside its own jit
#: (donation of an inner jit's argument would be ignored under that
#: trace).
year_step = partial(
    jax.jit,
    static_argnames=YEAR_STEP_STATIC_ARGNAMES,
    donate_argnames=("carry",),
)(year_step_impl)


def table_static_cache(table: AgentTable, tariffs: TariffBank) -> dict:
    """The scenario-invariant half of :func:`run_static_flags` — the
    rate-switch predicate, the any-net-billing-tariff predicate (an
    O(N log N) np.unique over the agent tariff indices), and the
    keep-masked NEM columns. A sweep computes this once and reuses it
    across its S per-scenario flag evaluations."""
    keep0 = np.asarray(table.mask) > 0
    rate_switch = bool(np.any(
        np.asarray(table.tariff_switch_idx)
        != np.asarray(table.tariff_idx)
    ))
    metering = np.asarray(tariffs.metering)
    used = np.unique(np.concatenate([
        np.asarray(table.tariff_idx)[keep0],
        np.asarray(table.tariff_switch_idx)[keep0],
    ]))
    return {
        "rate_switch": rate_switch,
        "any_nb_tariff": bool(np.any(metering[used] == NET_BILLING)),
        "state_idx": np.asarray(table.state_idx)[keep0],
        "nem_first_year": np.asarray(table.nem_first_year)[keep0],
        "nem_sunset_year": np.asarray(table.nem_sunset_year)[keep0],
        "nem_kw_limit": np.asarray(table.nem_kw_limit)[keep0],
    }


def run_static_flags(
    table: AgentTable,
    tariffs: TariffBank,
    inputs: ScenarioInputs,
    years: List[int],
    table_cache: Optional[dict] = None,
) -> tuple[bool, bool]:
    """(rate_switch, net_billing): the two host-decided compile-time
    predicates of a run, computed from the UNPADDED semantics (padding
    only adds masked rows and partitioning only reorders, so the
    predicates are invariant).

    ``rate_switch``: any agent's post-adoption DG rate differs from its
    base tariff (skips the second tariff gather + bill structure when
    False). ``net_billing``: whether net-billing bills can EVER price —
    any referenced net-billing tariff, or a NEM gate that can close
    (build_econ_inputs forces NET_BILLING at runtime when it does);
    False statically skips the hourly bucket-sums kernel and prices
    bills by the linear NEM identity. Shared by Simulation.__init__ and
    the sweep planner (scenarios whose flags differ cannot share one
    compiled program); the planner passes a precomputed
    :func:`table_static_cache` so only the per-scenario NEM-gate proof
    reruns per member.
    """
    tc = table_cache or table_static_cache(table, tariffs)
    net_billing = tc["any_nb_tariff"] or not nem_gate_never_closes(
        tc["state_idx"],
        np.asarray(inputs.nem_cap_kw),
        tc["nem_first_year"],
        tc["nem_sunset_year"],
        tc["nem_kw_limit"],
        years,
    )
    return tc["rate_switch"], net_billing


# ---------------------------------------------------------------------------
# Host-side driver
# ---------------------------------------------------------------------------

def _corrupt_bank_rows(profiles: ProfileBank) -> ProfileBank:
    """The ``bank_corrupt_row`` fault payload (kind ``corrupt``): NaN
    one deterministic load-bank row — or, under int8 quantized banks,
    that row's f32 dequant scale (codes cannot hold a NaN).  Models a
    bad bank file at load time (hit #1, Simulation construction — the
    quarantine validator's case) and silent in-memory data corruption
    mid-run (later hits, before a year step — the health sentinel's
    case)."""
    row = int(corrupt_rows()[0]) % int(np.asarray(profiles.load).shape[0])
    if profiles.load_scale is not None:
        sc = np.array(np.asarray(profiles.load_scale))
        sc[row] = np.nan
        return dataclasses.replace(profiles, load_scale=jnp.asarray(sc))
    arr = np.array(np.asarray(profiles.load))
    arr[row] = np.nan
    return dataclasses.replace(profiles, load=jnp.asarray(arr))


@dataclasses.dataclass
class SimResults:
    """Host-side stacked run outputs: dict of [n_years, ...] numpy
    arrays keyed by YearOutputs field, plus the year list."""

    years: List[int]
    agent: Dict[str, np.ndarray]          # per-agent fields [Y, N, ...]
    state_hourly_net_mw: Optional[np.ndarray]  # [Y, n_states, 8760]

    def summary(self, mask: np.ndarray) -> Dict[str, np.ndarray]:
        """National per-year aggregates (the headline adoption curves)."""
        m = mask[None, :]
        return {
            "adopters": (self.agent["number_of_adopters"] * m).sum(axis=1),
            "system_kw_cum": (self.agent["system_kw_cum"] * m).sum(axis=1),
            "batt_kwh_cum": (self.agent["batt_kwh_cum"] * m).sum(axis=1),
            "new_adopters": (self.agent["new_adopters"] * m).sum(axis=1),
        }


class Simulation:
    """Scenario runner (the analogue of reference dgen_model.main(),
    dgen_model.py:50, minus the Postgres plumbing).

    Parameters
    ----------
    table, profiles, tariffs : the ingested population and banks.
    inputs : ScenarioInputs (all year-dependent trajectories).
    scenario : ScenarioConfig.
    run_config : RunConfig (block/pad/search iteration settings).
    mesh : optional jax Mesh; agent axis is sharded over it.
    with_hourly : also aggregate state-hourly net load (more HBM).
    """

    def __init__(
        self,
        table: AgentTable,
        profiles: ProfileBank,
        tariffs: TariffBank,
        inputs: ScenarioInputs,
        scenario: ScenarioConfig,
        run_config: Optional[RunConfig] = None,
        mesh: Optional[Mesh] = None,
        with_hourly: bool = False,
        econ_years: int = 25,
        quarantine=None,
    ) -> None:
        self.scenario = scenario
        self.run_config = run_config or RunConfig()
        self.mesh = mesh
        self.with_hourly = with_hourly
        self.econ_years = econ_years
        self.years = list(scenario.model_years)
        if len(self.years) != inputs.n_years:
            raise ValueError(
                f"inputs cover {inputs.n_years} years but scenario has "
                f"{len(self.years)}"
            )

        # resilience fault site (kind ``corrupt``): a profile-bank row
        # going bad at LOAD time — hit #1 lands here, BEFORE validation,
        # which must catch it; later hits fire in Simulation.step
        # (mid-run corruption only the health sentinel catches)
        if corrupt_point("bank_corrupt_row"):
            profiles = _corrupt_bank_rows(profiles)

        # --- bad-data quarantine (resilience.quarantine): load-time
        # validation of the table/banks, containment of malformed rows
        # as inert padding (mask 0 -> exact-zero contributions), plus
        # any by-fiat quarantine (an explicit report, or the
        # supervisor's sentinel escalation via rc.quarantine_ids).
        # Runs FIRST: downstream host logic (run_static_flags'
        # metering[tariff_idx] indexing, the daylight layout, int8
        # quantization) assumes in-range references and finite banks.
        # Clean inputs pass through untouched (object identity), so the
        # default-on validation changes nothing for healthy runs and
        # the compiled programs (J5/J6 fingerprints) never move — the
        # quarantine mask IS the existing AgentTable.mask data plane.
        rc = self.run_config
        self.quarantine_report = None
        if rc.validate_enabled or rc.quarantine_ids or quarantine is not None:
            from dgen_tpu.resilience.quarantine import (
                QuarantineReport,
                apply_quarantine,
                validate_population,
            )

            rep = (
                validate_population(table, profiles, tariffs)
                if rc.validate_enabled
                else QuarantineReport(
                    n_agents=int(np.sum(np.asarray(table.mask) > 0)))
            )
            if quarantine is not None:
                rep.merge(quarantine)
            if rc.quarantine_ids:
                rep.add_ids(rc.quarantine_ids, "config:quarantine_ids")
            if not rep.is_clean:
                table, profiles = apply_quarantine(table, profiles, rep)
            self.quarantine_report = rep

        # per-run health-sentinel state (models.health); populated by
        # the year loops when RunConfig.sentinel_enabled
        self.health_report: Optional[dict] = None
        self._health_breaches: Dict[int, list] = {}

        # static flags, computed BEFORE chunking/partitioning (the HBM
        # chunk model needs them); see run_static_flags
        self._rate_switch, self._net_billing = run_static_flags(
            table, tariffs, inputs, self.years
        )
        #: optional label prefixed to this run's timer names (utils.
        #: timing ctx) — the sweep engine sets it per scenario so S
        #: scenarios' year_step timings report separately
        self.timing_ctx: Optional[str] = None
        #: optional shared io.hostio.HostIOPool — the sweep engine sets
        #: it so S per-scenario pipelines reuse one thread pair
        self._hostio_pool = None
        #: io.hostio.HostPipeline.stats() of the last run's async
        #: host-IO pipeline (None when the run serialized)
        self.hostio_stats: Optional[dict] = None

        # daylight-compacted candidate kernels (config-gated; the
        # full-hour path stays the default parity oracle): the layout
        # is built host-side from the f32 generation bank BEFORE any
        # bf16 conversion — bf16 rounding can only send tiny positives
        # to zero, so the f32 union mask over-covers, never under-covers.
        # Built whenever the config asks (not gated on _net_billing): an
        # all-NEM program simply ignores it, and with_inputs siblings
        # whose NEM gate CAN close (sweep groups) inherit a live layout
        # instead of silently running full-hour kernels.
        self._daylight = None
        if self.run_config.daylight_compact:
            from dgen_tpu.ops import billpallas

            self._daylight = billpallas.daylight_layout(
                np.asarray(profiles.solar_cf)
            )
            if self._daylight is None:
                logger.info(
                    "daylight_compact requested but the generation bank "
                    "has no compactable night hours; full-hour kernels"
                )
            else:
                logger.info(
                    "daylight-compacted kernels: %d of %d month-padded "
                    "lanes (%.2fx fewer candidate lane-ops)",
                    self._daylight.n_lanes, billpallas.H_MONTHS,
                    billpallas.H_MONTHS / self._daylight.n_lanes,
                )

        # int8 quantized banks (config-gated): the load/gen streams
        # shrink to one byte per hour with per-row f32 dequant scales;
        # kernels fold the scales into the candidate grid and upcast +
        # accumulate in f32 (ops.billpallas._quant_fold). Quantized
        # AFTER the daylight layout (built from the f32 bank) and
        # BEFORE any bf16 conversion — exact zeros stay exact zeros,
        # so the night-lane premise survives.
        if self.run_config.quant_banks:
            from dgen_tpu.models.agents import quantize_rows

            lq, ls = quantize_rows(np.asarray(profiles.load))
            cq, cs = quantize_rows(np.asarray(profiles.solar_cf))
            profiles = dataclasses.replace(
                profiles,
                load=jnp.asarray(lq), solar_cf=jnp.asarray(cq),
                load_scale=jnp.asarray(ls),
                solar_cf_scale=jnp.asarray(cs),
            )
            logger.info(
                "int8 quantized profile banks: load/gen streams at "
                "1 byte/hour (+%d per-row f32 scales)",
                ls.size + cs.size,
            )

        # bf16 profile banks (config-gated): halve the HBM-resident
        # banks AND the gathered O(N*8760) per-agent streams; kernels
        # upcast to f32 on read (ops.billpallas). Applied per STREAM
        # field — int8 code banks pass through untouched and the f32
        # dequant scales deliberately stay full precision
        if self.run_config.bf16_banks:
            def _to_bf16(x):
                x = jnp.asarray(x)
                if jnp.issubdtype(x.dtype, jnp.floating):
                    return x.astype(jnp.bfloat16)
                return x

            profiles = dataclasses.replace(
                profiles,
                load=_to_bf16(profiles.load),
                solar_cf=_to_bf16(profiles.solar_cf),
                wholesale=_to_bf16(profiles.wholesale),
            )

        # state-local shard layout (the reference's per-state task
        # binning, SURVEY.md §2.6); results are keyed by agent_id and
        # invariant under the reordering
        chunk = self.run_config.agent_chunk
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        if chunk is None:
            # operator picked no memory shape: derive the streaming
            # chunk from the device HBM budget (0 = whole table fits)
            chunk = auto_agent_chunk(
                table.n_agents // n_dev,
                sizing_iters=self.run_config.sizing_iters,
                econ_years=econ_years,
                with_hourly=with_hourly,
                hbm_bytes=default_hbm_bytes(),
                net_billing=self._net_billing,
                rate_switch=self._rate_switch,
                bank_bf16=self.run_config.bf16_banks,
                bank_quant=self.run_config.quant_banks,
            )
            if chunk:
                logger.info(
                    "auto agent_chunk: %d rows/device (population %d "
                    "exceeds the whole-table HBM envelope)",
                    chunk, table.n_agents,
                )
        self.partition = None
        # host row-origin map, composed through every host-side row
        # permutation below (state partition, chunk padding, cluster
        # layout): final row -> row of the INPUT table, -1 for padding
        # rows created along the way. Side arrays aligned with the
        # input table (the ensemble's cohort entry years) ride it;
        # agent_id cannot serve (padding rows carry the fill id 0,
        # ambiguous with real agent 0).
        origin = np.arange(table.n_agents, dtype=np.int64)
        if (
            mesh is not None and mesh.devices.size > 1
            and self.run_config.partition_by_state
        ):
            from dgen_tpu.parallel.mesh import mesh_shape_of
            from dgen_tpu.parallel.partition import partition_table

            pad_mult = self.run_config.agent_pad_multiple
            if chunk:
                # per-shard length must divide into agent chunks
                pad_mult = int(np.lcm(pad_mult, chunk))
            # 2-D hosts x devices grids pack hierarchically: whole
            # states stay host-local, so the straddle psums ride ICI
            # within a host row instead of DCN across it
            table, self.partition = partition_table(
                table, int(mesh.devices.size), pad_mult,
                mesh_shape=mesh_shape_of(mesh),
            )
            # the partition drops mask-0 rows and re-pads per shard;
            # gather_rows is its exact origin record
            origin = np.asarray(self.partition.gather_rows)
            logger.info(
                "partitioned %d agents into %d state-local shards of %d "
                "(mesh %dx%d)",
                int(np.sum(np.asarray(table.mask))), mesh.devices.size,
                self.partition.shard_len, *mesh_shape_of(mesh),
            )
        elif chunk:
            # keep the lane-alignment invariant alongside chunk
            # divisibility (the partition branch does the same via lcm)
            table = pad_table(
                table,
                int(np.lcm(self.run_config.agent_pad_multiple,
                           chunk * n_dev)),
            )
            if table.n_agents > len(origin):
                origin = np.concatenate([
                    origin,
                    np.full(table.n_agents - len(origin), -1, np.int64),
                ])

        # --- tariff-clustered layout (config-gated; ops.tariffcluster):
        # canonicalize the compiled bank into structural clusters, then
        # re-permute each device shard cluster-major so sizing runs one
        # program per cluster at tight pad widths. Layered AFTER the
        # state partition / chunk padding (rows never move across
        # devices, so the straddle-psum locality of partition_by_state
        # survives) and BEFORE host attribute capture (exporters key on
        # the clustered order's agent_id, results stay order-invariant).
        self._cluster_layout = None
        self._cluster_banks = None
        self._cluster_tidx = None
        self._cluster_host = None
        if self.run_config.cluster_tariffs and self._rate_switch:
            logger.info(
                "cluster_tariffs requested but rate switching is live "
                "(base/switch pairs can straddle clusters); running the "
                "unclustered program"
            )
        elif self.run_config.cluster_tariffs:
            from dgen_tpu.ops import tariffcluster

            pad_mult = int(np.lcm(
                self.run_config.agent_pad_multiple, chunk or 1
            ))
            plan = tariffcluster.analyze_bank(tariffs)
            layout, gather, valid, ctidx = tariffcluster.plan_layout(
                plan,
                np.asarray(table.tariff_idx),
                np.asarray(table.mask),
                n_dev,
                pad_mult,
            )
            n_old = table.n_agents

            def _cluster_gather(x):
                x = np.asarray(x)
                if x.ndim >= 1 and x.shape[0] == n_old:
                    return x[gather]
                return x

            table = jax.tree.map(_cluster_gather, table)
            table = dataclasses.replace(
                table,
                mask=np.asarray(table.mask) * valid,
            )
            origin = np.where(valid > 0, origin[gather], -1)
            self._cluster_host = dict(
                cid=layout.cluster_of_rows(),
                real=np.asarray(table.mask) > 0,
                state_idx=np.asarray(table.state_idx),
                nem_first_year=np.asarray(table.nem_first_year),
                nem_sunset_year=np.asarray(table.nem_sunset_year),
                nem_kw_limit=np.asarray(table.nem_kw_limit),
            )
            self._cluster_banks = tariffcluster.banks_for_layout(
                plan, layout
            )
            self._cluster_tidx = jnp.asarray(ctidx)
            self._cluster_layout = layout
            self._cluster_layout = layout.with_flags(
                self._cluster_flags(inputs)
            )
            logger.info(
                "tariff clusters: %d signatures over %d tariffs, "
                "segments %s rows/device (was %d rows/device global-pad)",
                layout.n_clusters, tariffs.n_tariffs,
                [c.seg_len for c in self._cluster_layout.clusters],
                n_old // n_dev,
            )

        # streaming year step: only engage when the table is actually
        # larger than one chunk per device
        self._agent_chunk = (
            chunk if chunk and table.n_agents // n_dev > chunk else 0
        )

        # host-side attributes, captured BEFORE device placement:
        # exporters key their rows on these, and fetching them back from
        # a globally-sharded table would fail under true multi-host
        self.host_agent_id = np.asarray(table.agent_id)
        self.host_mask = np.asarray(table.mask)
        #: [n_agents] final row -> INPUT-table row (-1 = padding): the
        #: composed host permutation record (see ``origin`` above) —
        #: dgen_tpu.ensemble aligns cohort entry years through it
        self.host_row_origin = origin
        # state_idx too: the end-of-run STATE_KW_BOUND check maps each
        # process's addressable carry rows back to states by GLOBAL row
        # index, which only the host copy can serve under multi-host
        self.host_state_idx = np.asarray(table.state_idx)
        # _rate_switch (skip the second tariff gather + bill structure
        # when no agent's post-adoption DG rate differs) and
        # _net_billing (whether net-billing bills can EVER price: any
        # net-billing tariff in use, or a NEM gate that can close —
        # build_econ_inputs forces NET_BILLING at runtime when it does;
        # False statically skips the hourly bucket-sums kernel and
        # prices bills by the linear NEM identity) were computed above,
        # before chunking, because the HBM chunk model depends on them.

        if mesh is not None:
            shard = NamedSharding(mesh, agent_spec(mesh))
            repl = NamedSharding(mesh, P())

            def put(x, sharding):
                # multi-process (jax.distributed over a global mesh):
                # device_put of host data to a sharding spanning remote
                # devices raises, so build the global array from each
                # process's addressable shards instead — every process
                # holds the identical host copy (deterministic build),
                # so the callback just slices it
                if jax.process_count() > 1:
                    h = np.asarray(x)
                    return jax.make_array_from_callback(
                        h.shape, sharding, lambda idx: h[idx]
                    )
                return jax.device_put(x, sharding)

            def place_agent_axis(x):
                # shard leading (agent) axis; leave small leaves replicated
                if hasattr(x, "ndim") and x.ndim >= 1 and (
                    x.shape[0] == table.n_agents
                ):
                    return put(
                        x, NamedSharding(mesh, agent_spec(mesh, x.ndim)),
                    )
                return put(x, repl)

            table = jax.tree.map(place_agent_axis, table)
            profiles = jax.tree.map(lambda x: put(x, repl), profiles)
            tariffs = jax.tree.map(lambda x: put(x, repl), tariffs)
            inputs = jax.tree.map(lambda x: put(x, repl), inputs)
            if self._cluster_tidx is not None:
                # compact per-cluster indices ride the agent axis; the
                # tight shared banks are small — replicate them
                self._cluster_tidx = put(
                    self._cluster_tidx,
                    NamedSharding(mesh, agent_spec(mesh, 1)),
                )
                self._cluster_banks = tuple(
                    jax.tree.map(lambda x: put(x, repl), b)
                    for b in self._cluster_banks
                )
            self._shard = shard
            self._put = put
        else:
            self._shard = None
            self._put = None

        self.table = table
        self.profiles = profiles
        self.tariffs = tariffs
        self.inputs = inputs

    def step_kwargs(self, first_year: bool) -> dict:
        """The full :func:`year_step` argument set this run compiles
        under — every static (compile-time) knob plus the traced-shape
        controls. Public contract shared by the sweep driver (which
        overrides ``net_billing``/``mesh`` per scenario group), bench,
        and the program auditor (``dgen_tpu.lint.prog``), so the
        program that gets AUDITED is byte-for-byte the program that
        RUNS."""
        # Under a >1-device mesh the bucket-sums engine runs per-shard
        # via shard_map (billpallas._maybe_shard_agents), so the Pallas
        # kernel stays live on multi-chip TPU meshes.
        return dict(
            n_periods=self.tariffs.max_periods,
            econ_years=self.econ_years,
            sizing_iters=self.run_config.sizing_iters,
            first_year=first_year,
            with_hourly=self.with_hourly,
            storage_enabled=self.scenario.storage_enabled,
            year_step_len=float(self.scenario.year_step),
            sizing_impl=(
                "pallas_stream" if self.run_config.stream_segments
                else "auto"
            ),
            rate_switch=self._rate_switch,
            mesh=self.mesh,
            agent_chunk=self._agent_chunk,
            net_billing=self._net_billing,
            daylight=self._daylight,
            pack_once=self.run_config.pack_once,
            soft_tau=self.run_config.soft_tau_static,
            cluster=self._cluster_layout,
        )

    #: legacy private alias — internal call sites (and tests that
    #: monkeypatch the instance attribute) resolve through this name
    _step_kwargs = step_kwargs

    def step_operands(self) -> dict:
        """The traced (non-static) operands that ride alongside a
        cluster layout — the compact shared banks and the per-row
        compact tariff indices. Empty when the run is unclustered, so
        call sites can always splat it into :func:`year_step`."""
        if self._cluster_layout is None:
            return {}
        return dict(
            cluster_banks=self._cluster_banks,
            cluster_tidx=self._cluster_tidx,
        )

    def _cluster_flags(self, inputs: ScenarioInputs) -> tuple:
        """Per-cluster net-billing flags for the current scenario: a
        net-metered cluster prices by the linear identity only when its
        own members' NEM gate provably never closes (the per-cluster
        refinement of :func:`run_static_flags` — a whole-run ``True``
        often splits into mostly-``False`` clusters)."""
        h = self._cluster_host
        caps = np.asarray(inputs.nem_cap_kw)
        flags = []
        for ci, spec in enumerate(
            self._cluster_layout.clusters if self._cluster_layout
            else ()
        ):
            if spec.metering == NET_BILLING:
                flags.append(True)
                continue
            sel = (h["cid"] == ci) & h["real"]
            flags.append(not nem_gate_never_closes(
                h["state_idx"][sel],
                caps,
                h["nem_first_year"][sel],
                h["nem_sunset_year"][sel],
                h["nem_kw_limit"][sel],
                self.years,
            ))
        return tuple(flags)

    def _hbm_check(self) -> Optional[dict]:
        """Modeled-vs-actual device memory: compare the chunk model's
        predicted step working set against the device's observed peak
        (memory_stats), so a mis-modeled configuration is VISIBLE in
        the logs instead of discovered as a year-1 OOM on a national
        run.  Returns the record (also kept as ``self.hbm_check``);
        None when the backend exposes no stats."""
        if jax.default_backend() != "tpu":
            return None
        try:
            stats = jax.devices()[0].memory_stats() or {}
        except Exception:  # noqa: BLE001 — tunneled devices may not expose
            stats = {}
        # tunneled/virtual devices report no stats: still record the
        # model's prediction (peak None) so operators see what was
        # assumed; on a real TPU VM the comparison is live
        peak = stats.get("peak_bytes_in_use")
        n_dev = int(self.mesh.devices.size) if self.mesh is not None else 1
        n_local = self.table.n_agents // n_dev
        rows = self._agent_chunk or n_local
        per_agent = _per_agent_step_bytes(
            sizing_iters=self.run_config.sizing_iters,
            econ_years=self.econ_years,
            with_hourly=self.with_hourly,
            net_billing=self._net_billing,
            rate_switch=self._rate_switch,
            bank_bf16=self.run_config.bf16_banks,
            bank_quant=self.run_config.quant_banks,
        )
        modeled = rows * per_agent + n_local * _PERSISTENT_ROW_BYTES
        rec = {
            "modeled_step_bytes": int(modeled),
            "device_peak_bytes": int(peak) if peak else None,
            "peak_over_model": round(peak / modeled, 3) if peak else None,
            "agent_chunk": self._agent_chunk,
        }
        self.hbm_check = rec
        if not peak:
            logger.info(
                "HBM model: modeled step %.2f GB (device reports no "
                "memory stats; comparison unavailable)", modeled / 2**30,
            )
            return rec
        logger.info(
            "HBM model: modeled step %.2f GB vs device peak %.2f GB "
            "(peak/model %.2f; peak includes persistent banks)",
            modeled / 2**30, peak / 2**30, rec["peak_over_model"],
        )
        if peak > modeled * 3 and self._agent_chunk:
            logger.warning(
                "device peak is %.1fx the chunk model — the footprint "
                "model under-counts this configuration (net_billing=%s "
                "rate_switch=%s with_hourly=%s); a larger population "
                "may OOM at the chosen chunk",
                rec["peak_over_model"], self._net_billing,
                self._rate_switch, self.with_hourly,
            )
        return rec

    def _check_state_kw_bound(self, carry: SimCarry, context: str) -> None:
        """Raise if any state's cumulative capacity reaches
        STATE_KW_BOUND — the value at which the static all-NEM proof
        (the compile-time skip of the net-billing bill path) would stop
        being sound.  Host-side check on fetched carry data.

        Multi-process runs check each process's ADDRESSABLE shard rows:
        per-agent kW is nonnegative, so any shard's per-state partial
        sums lower-bound the global totals — a partial that reaches the
        bound proves the global total has too, and every row is covered
        by whichever process holds it (no cross-host gather needed).
        """
        arr = carry.market.system_kw_cum
        if getattr(arr, "is_fully_addressable", True) is not False:
            kw = np.asarray(jax.device_get(arr))
            sidx = self.host_state_idx
        else:
            rows, starts = [], []
            seen = set()
            for s in arr.addressable_shards:
                sl = s.index[0] if s.index else slice(None)
                start = sl.start or 0
                if start in seen:   # in-host replication: one copy
                    continue
                seen.add(start)
                data = np.asarray(s.data)
                rows.append(data)
                stop = sl.stop if sl.stop is not None else arr.shape[0]
                starts.append(np.arange(start, stop))
            kw = np.concatenate(rows)
            sidx = self.host_state_idx[np.concatenate(starts)]
        state_kw = np.zeros(self.table.n_states, np.float64)
        np.add.at(state_kw, sidx, kw)
        if not np.all(state_kw < STATE_KW_BOUND):
            raise AssertionError(
                f"{context}: state capacity exceeds STATE_KW_BOUND; "
                "the static all-NEM kernel skip is unsound for this run"
            )

    def _sanitize_restored_carry(self, carry: SimCarry) -> SimCarry:
        """Zero quarantined rows of a restored cross-year carry: a
        checkpoint written BEFORE a mid-run quarantine still holds the
        offending agents' market state, and the state-capacity segment
        sums read the carry directly (not mask-gated) — resuming
        without this would let a contained agent keep contributing.
        No-op (object identity) when nothing is quarantined."""
        rep = getattr(self, "quarantine_report", None)
        if rep is None or not rep.n_quarantined:
            return carry
        q = np.isin(self.host_agent_id, np.asarray(rep.ids))
        if not q.any():
            return carry
        keep = jnp.asarray((~q).astype(np.float32))
        if self._shard is not None:
            keep = self._put(keep, self._shard)
        n = self.table.n_agents

        def _zero(x):
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] == n:
                return x * keep.reshape((n,) + (1,) * (x.ndim - 1))
            return x

        return jax.tree.map(_zero, carry)

    def _health_verdict(self, year: int, yi: int, summary_host,
                        outs, escalate: bool) -> None:
        """Host-side sentinel verdict for one year's fetched summary:
        record breaches, attribute offending agents from the year's
        device outputs (when still referenced), and raise
        ``HealthBreachError`` under escalation — the supervisor's
        quarantine loop consumes the attributed ids."""
        from dgen_tpu.models import health as health_mod

        breaches = health_mod.check_host(summary_host)
        if not breaches:
            return
        self._health_breaches[int(year)] = breaches
        if outs is not None:
            err = health_mod.breach_error(
                year, yi, breaches, outs,
                self.host_agent_id, self.host_mask,
            )
        else:
            err = health_mod.HealthBreachError(year, yi, breaches)
        if escalate:
            raise err
        logger.warning("health sentinel: %s", err)

    def with_inputs(
        self,
        inputs: ScenarioInputs,
        net_billing: Optional[bool] = None,
        timing_ctx: Optional[str] = None,
    ) -> "Simulation":
        """A sibling runner driving different ScenarioInputs over THIS
        simulation's already-placed table, profile banks, tariffs and
        chunk/partition layout — the sweep engine's scenario-major
        loop: every sibling shares the same static year_step arguments,
        so S scenarios execute the one compiled program pair and the
        multi-GB banks are uploaded exactly once.

        ``inputs`` must cover the same year grid. ``net_billing``
        overrides the recomputed flag (the sweep planner pins it per
        scenario group so a mixed group cannot split the executable);
        passing True for an all-NEM scenario is numerically exact —
        False is only ever a compile-time skip of the bucket-sums
        kernel. The daylight layout is inherited as-is (it depends only
        on the shared generation bank)."""
        import copy

        if len(self.years) != inputs.n_years:
            raise ValueError(
                f"inputs cover {inputs.n_years} years but this "
                f"simulation has {len(self.years)}"
            )
        pinned = net_billing is not None
        if net_billing is None:
            _, net_billing = run_static_flags(
                self.table, self.tariffs, inputs, self.years
            )
        # per-cluster flags track the scenario too: a pinned group flag
        # pins every cluster the same way (True is exact, False means
        # the planner PROVED no member scenario can close a gate), an
        # unpinned sibling re-proves each cluster's gate on host
        cluster = self._cluster_layout
        if cluster is not None:
            cluster = (
                cluster.pin_net_billing(net_billing) if pinned
                else cluster.with_flags(self._cluster_flags(inputs))
            )
        if self.mesh is not None:
            repl = NamedSharding(self.mesh, P())
            inputs = jax.tree.map(lambda x: self._put(x, repl), inputs)
        sib = copy.copy(self)
        sib.inputs = inputs
        sib._net_billing = net_billing
        sib._cluster_layout = cluster
        sib.timing_ctx = timing_ctx
        return sib

    def init_carry(self) -> SimCarry:
        carry = SimCarry.zeros(self.table.n_agents)
        if self._shard is not None:
            carry = jax.tree.map(
                lambda x: self._put(x, self._shard), carry
            )
        return carry

    def step(
        self, carry: SimCarry, year_idx: int, first_year: bool
    ) -> tuple[SimCarry, YearOutputs]:
        # resilience drill hook: the per-year device program dispatch.
        # An ``oom``-kind fault here raises the RESOURCE_EXHAUSTED
        # error a real chunk-scan OOM surfaces with, so the
        # supervisor's chunk-halving degradation is testable on CPU.
        fault_point("year_step")
        # resilience fault site (kind ``corrupt``): silent mid-run data
        # corruption — a profile-bank row flips to NaN between year
        # steps (same data shapes/dtypes, so the compiled program is
        # untouched).  Only the health sentinel's breach -> attribute
        # -> quarantine escalation can catch this.
        if corrupt_point("bank_corrupt_row"):
            profiles = _corrupt_bank_rows(self.profiles)
            if self._put is not None:
                repl = NamedSharding(self.mesh, P())
                profiles = jax.tree.map(
                    lambda x: self._put(x, repl), profiles)
            self.profiles = profiles
        return year_step(
            self.table, self.profiles, self.tariffs, self.inputs, carry,
            jnp.asarray(year_idx, dtype=jnp.int32),
            **self._step_kwargs(first_year),
            **self.step_operands(),
        )

    def run(
        self,
        callback: Optional[Callable[[int, int, YearOutputs], None]] = None,
        collect: bool = True,
        checkpoint_dir: Optional[str] = None,
        resume: bool = False,
        resume_year: Optional[int] = None,
        should_stop: Optional[Callable[[int, int], bool]] = None,
    ) -> SimResults:
        """Run every model year; returns stacked host results.

        ``callback(year, year_idx, outputs)`` fires after each year with
        the device outputs (use for exports — the analogue of the
        reference's per-year pickle + ``agent_outputs`` append,
        dgen_model.py:459-462).

        ``checkpoint_dir`` saves the cross-year carry after every year
        (orbax); with ``resume=True`` the run restarts after the last
        checkpointed year — the working version of the reference's
        vestigial ``resume_year`` stub (SURVEY.md §5).  ``resume_year``
        pins the restart to a SPECIFIC checkpointed year instead of the
        latest — the resilience supervisor passes the crash-consistent
        frontier here so a resumed run re-exports exactly the years
        whose artifacts are not durably on disk (later checkpoints are
        overwritten as those years re-run).

        Host consumers (collection, export callbacks, checkpoint saves)
        run on the background host-IO pipeline by default
        (``dgen_tpu.io.hostio``): the driver keeps dispatching year
        steps back to back while a worker thread fetches each finished
        year and ordered stages write it out — bit-identical results,
        with the host IO overlapped against device compute.
        ``RunConfig.async_host_io=False`` (env ``DGEN_TPU_ASYNC_IO=0``)
        restores the serialized per-year path, which also remains in
        force for ``debug_invariants`` and profiling.  Multi-process
        (jax.distributed) runs ride the pipeline by default too: each
        process's pipeline writes only its own addressable shard, so
        the per-shard export/checkpoint semantics are preserved
        (byte-parity proven by the gang tests) — only ``collect=True``
        still serializes there (collection fetches the full global
        arrays).

        ``should_stop(year, year_idx)`` is evaluated after each
        completed year (exports dispatched, checkpoint issued); True
        ends the run early with the completed years' results — the
        gang worker's synchronized SIGTERM/emergency-checkpoint
        barrier runs through this hook, so every process of a
        jax.distributed gang must call it the same number of times
        (it may contain collectives).
        """
        start_idx = 0
        carry = self.init_carry()
        if resume:
            if not checkpoint_dir:
                raise ValueError("resume=True requires checkpoint_dir")
            from dgen_tpu.io import checkpoint as ckpt

            last = (
                resume_year if resume_year is not None
                else ckpt.latest_year(checkpoint_dir)
            )
            if last is not None and last not in self.years:
                # silently restarting from scratch would also overwrite
                # the existing (incompatible) checkpoints
                raise ValueError(
                    f"checkpointed year {last} is not on this scenario's "
                    f"year grid {self.years}; refusing to resume"
                )
            if last is not None:
                # a mesh run restores straight onto its sharding (no
                # full-array host copy — multi-host safe)
                _, restored = ckpt.restore_year(
                    checkpoint_dir, self.table.n_agents, last,
                    sharding=self._shard,
                )
                carry = self._sanitize_restored_carry(restored)
                start_idx = self.years.index(last) + 1
                logger.info("resuming after year %d (index %d)", last, start_idx)

        agent_fields = [
            f.name for f in dataclasses.fields(YearOutputs)
            if f.name != "state_hourly_net_mw"
        ]

        ckpt_writer = None
        if checkpoint_dir is not None:
            from dgen_tpu.io import checkpoint as ckpt

            ckpt_writer = ckpt.Writer(checkpoint_dir)

        debug = self.run_config.debug_invariants

        # opt-in device trace (xprof/tensorboard-consumable), the
        # device-level analogue of the reference's cProfile prof.dat
        # (SURVEY.md §5): traces the first post-compile year step
        profile_dir = os.environ.get("DGEN_TPU_PROFILE")

        # background host-IO pipeline (io.hostio): the default for any
        # run with a host consumer — single- AND multi-process, since
        # every process's pipeline writes only its own addressable
        # shard (byte-parity proven at the 1M scale before the default
        # flipped; DGEN_TPU_ASYNC_IO=0 is the kill switch).
        # debug_invariants and profiling need per-year host sync, and
        # multi-process collect=True still serializes (collection
        # fetches full GLOBAL arrays).
        async_io = (
            self.run_config.async_io_enabled
            and not debug and not profile_dir
            and (jax.process_count() == 1 or not collect)
            and (collect or callback is not None or ckpt_writer is not None)
        )
        self.hostio_stats = None
        self._stop_idx: Optional[int] = None
        self.health_report = None
        self._health_breaches = {}
        try:
            if async_io:
                carry, collected, hourly = self._run_years_async(
                    carry, start_idx, callback, collect, ckpt_writer,
                    agent_fields, should_stop,
                )
            else:
                carry, collected, hourly = self._run_years_sync(
                    carry, start_idx, callback, collect, ckpt_writer,
                    agent_fields, debug, profile_dir, should_stop,
                )
        finally:
            # in the finally: a mid-run exception must not abandon
            # orbax's background save threads without
            # wait_until_finished (io.checkpoint.Writer.close)
            if ckpt_writer is not None:
                ckpt_writer.close()
        self._hbm_check()
        if not self._net_billing and not debug:
            # always-on soundness check for the static all-NEM skip:
            # system_kw_cum is monotone, so one end-of-run bound check
            # covers every year's gate evaluation at the cost of a
            # single host fetch (the per-year variant runs under
            # debug). Multi-process runs check their own addressable
            # shard rows — nonnegative per-agent kW makes the per-shard
            # partials a sound lower bound on the global state totals,
            # and the shards jointly cover every row.
            self._check_state_kw_bound(carry, "end of run")
        agent = (
            {k: np.stack(v) for k, v in collected.items()}
            if collect and collected[agent_fields[0]] else {}
        )
        end_idx = (
            self._stop_idx if self._stop_idx is not None
            else len(self.years)
        )
        if self.run_config.sentinel_enabled:
            self.health_report = {
                "breaches": {
                    int(y): b for y, b in self._health_breaches.items()
                },
                "clean": not self._health_breaches,
            }
        return SimResults(
            years=self.years[start_idx:end_idx],
            agent=agent,
            state_hourly_net_mw=np.stack(hourly) if hourly else None,
        )

    def _run_years_async(
        self,
        carry: SimCarry,
        start_idx: int,
        callback,
        collect: bool,
        ckpt_writer,
        agent_fields: List[str],
        should_stop=None,
    ) -> tuple[SimCarry, Dict[str, list], List[np.ndarray]]:
        """The async host-IO year loop (io.hostio.HostPipeline): years
        are dispatched back to back exactly like the no-consumer
        pipelined path, and every host consumer — result collection,
        export callbacks, checkpoint saves — runs on the pipeline's
        worker threads against one batched device fetch per year.

        The cross-year carry is snapshotted (a device-side copy queued
        right behind the step that produced it) BEFORE the next
        iteration's step donates its buffers, so checkpoint saves read
        stable data.  Pipeline depth is bounded by the same ~2 GB
        in-flight-outputs envelope as the no-consumer drain model, and
        the ``finally`` drain preserves the serialized path's crash
        semantics: the last completed year's export is flushed exactly
        once, worker errors surface instead of masking (or being
        masked by) the loop's own failure."""
        from dgen_tpu.io import hostio

        consumers: list = []
        # the health sentinel stage comes FIRST: a breached year must
        # be detected before its export/checkpoint consumers run, so
        # it is never marked complete in the manifest and the
        # supervisor's resume frontier re-runs it after quarantine
        if self.run_config.sentinel_enabled:
            consumers.append(hostio.HealthConsumer(
                mask=self.table.mask,
                agent_ids_host=self.host_agent_id,
                mask_host=self.host_mask,
                escalate=bool(self.run_config.sentinel_escalate),
                breaches_out=self._health_breaches,
            ))
        collector = None
        if collect:
            collector = hostio.CollectConsumer(
                agent_fields, self.with_hourly)
            consumers.append(collector)
        if callback is not None:
            consumers.append(hostio.consumer_for_callback(callback))
        if ckpt_writer is not None:
            # multi-process carries are global arrays: orbax saves them
            # collectively from DEVICE shards (a host fetch would raise
            # on non-addressable data)
            consumers.append(
                hostio.CheckpointConsumer(ckpt_writer)
                if jax.process_count() == 1
                else hostio.DeviceCheckpointConsumer(ckpt_writer)
            )

        pipeline = None
        guard = None
        loop_failed = False
        try:
            for yi, year in enumerate(self.years):
                if yi < start_idx:
                    continue
                if (
                    self.run_config.guard_retrace and guard is None
                    and yi - start_idx >= 2
                ):
                    from dgen_tpu.lint.guard import RetraceGuard

                    guard = RetraceGuard(
                        context="steady-state retrace guard"
                    ).start()
                t0 = time.time()
                with timing.timer("year_step", ctx=self.timing_ctx):
                    carry, outs = self.step(carry, yi, first_year=(yi == 0))
                if pipeline is None:
                    pipeline = hostio.pipeline_for(
                        consumers, outs,
                        carry=carry if ckpt_writer is not None else None,
                        timing_ctx=self.timing_ctx,
                        pool=self._hostio_pool,
                    )
                snap = (hostio.snapshot_carry(carry)
                        if ckpt_writer is not None else None)
                pipeline.submit(year, yi, outs, carry=snap)
                logger.info(
                    "year %d (%d/%d) %.2fs (queued)", year, yi + 1,
                    len(self.years), time.time() - t0,
                )
                if guard is not None:
                    guard.check(f"year {year}")
                if should_stop is not None and should_stop(year, yi):
                    logger.info(
                        "cooperative stop after year %d (%d/%d)",
                        year, yi + 1, len(self.years),
                    )
                    self._stop_idx = yi + 1
                    break
        except BaseException:
            loop_failed = True
            raise
        finally:
            if guard is not None:
                guard.stop()
            if pipeline is not None:
                # flush every queued year (the last completed year's
                # export included) without masking a loop failure
                self.hostio_stats = pipeline.drain(failed=loop_failed)
        with timing.timer("device_drain", ctx=self.timing_ctx):
            jax.block_until_ready(carry.market.market_share)
            float(jnp.sum(carry.batt_adopters_cum))
        if collector is not None:
            return carry, collector.collected, collector.hourly
        return carry, {k: [] for k in agent_fields}, []

    def _run_years_sync(
        self,
        carry: SimCarry,
        start_idx: int,
        callback,
        collect: bool,
        ckpt_writer,
        agent_fields: List[str],
        debug: bool,
        profile_dir: Optional[str],
        should_stop=None,
    ) -> tuple[SimCarry, Dict[str, list], List[np.ndarray]]:
        """The serialized year loop: the no-consumer pipelined path,
        plus the per-year host-sync parity oracle for the async
        pipeline (``RunConfig.async_host_io=False``, debug runs,
        profiling, multi-process shard writes)."""
        collected: Dict[str, list] = {k: [] for k in agent_fields}
        hourly: List[np.ndarray] = []
        if debug:
            from dgen_tpu.utils import invariants

        # always-on numerical-health sentinel (models.health): the
        # per-year fused summary is dispatched right behind the step;
        # on the serialized path it rides the existing per-year sync,
        # on the deferred-callback path it is checked just before that
        # year's export flushes, and on the no-consumer pipelined path
        # the summaries are batch-fetched once at the end (still
        # detected, attribution deferred to the supervised re-entry)
        sentinel = self.run_config.sentinel_enabled
        escalate = bool(self.run_config.sentinel_escalate)
        if sentinel:
            from dgen_tpu.models import health as health_mod
        queued_health: List[tuple] = []
        pending_health = None          # (year, yi, summary, outs)

        profiled = False

        # per-year host sync is only needed when something consumes the
        # year's results on host (checkpoints, collection, invariants,
        # tracing). Otherwise years are DISPATCHED back to back and the
        # device pipelines them — the per-step host/dispatch overhead
        # (~40% of wall time at 8k agents through a remote tunnel) is
        # paid once per run instead of once per year.
        #
        # A callback alone (the export path) does NOT force sync:
        # callbacks are deferred ONE year, invoked after the next year's
        # step is dispatched, so the host-side fetch/write of year N
        # overlaps the device executing year N+1 — at 1M agents the
        # exports were ~half the full-run wall when serialized. The
        # callback's own device_get throttles lookahead to one year.
        sync_per_year = bool(
            ckpt_writer is not None or collect or debug or profile_dir
        )
        defer_callback = callback is not None and not sync_per_year
        pending_cb = None                    # (year, yi, outs)
        # pipelined mode still bounds in-flight years: every queued
        # step's YearOutputs buffers stay live until it executes, so an
        # unthrottled queue holds queue-depth x per-year-outputs of
        # extra HBM (~380 MB/year at 1M agents). Drain often enough to
        # cap that at hostio.QUEUE_HBM_BYTES (~2 GB) — the SAME envelope
        # the async pipeline bounds its queue depth with; at small
        # populations this never triggers.
        sync_every: Optional[int] = None

        # steady-state retrace guard (lint.guard): the first two
        # executed years compile the first_year=True/False program
        # pair; from the third on, a fresh XLA compile means a static
        # argument or shape is churning and the one-program-per-year
        # contract is broken — fail the run there, with the year named
        guard = None

        # the deferred-callback flush lives in a finally: year N's
        # results exist on device once its step ran, and a failure while
        # dispatching year N+1 must not lose year N's export
        loop_failed = False   # own-loop failure flag; NOT sys.exc_info()
        # (a caller invoking run() inside an active except handler would
        # make exc_info a false positive and re-swallow flush failures)
        try:
            for yi, year in enumerate(self.years):
                if yi < start_idx:
                    continue
                if (
                    self.run_config.guard_retrace and guard is None
                    and yi - start_idx >= 2
                ):
                    from dgen_tpu.lint.guard import RetraceGuard

                    guard = RetraceGuard(
                        context="steady-state retrace guard"
                    ).start()
                t0 = time.time()
                # trace the second executed step (post-compile) — or the
                # only step when the run has just one
                trace_now = profile_dir and not profiled and (
                    yi == start_idx + 1
                    or (yi == start_idx and len(self.years) - start_idx == 1)
                )
                if trace_now:
                    jax.profiler.start_trace(profile_dir)
                try:
                    with timing.timer("year_step", ctx=self.timing_ctx):
                        prev_carry = carry
                        carry, outs = self.step(carry, yi, first_year=(yi == 0))
                        if sync_per_year:
                            jax.block_until_ready(carry.market.market_share)
                        else:
                            if sync_every is None:
                                from dgen_tpu.io import hostio

                                sync_every = hostio.depth_for_bytes(
                                    hostio.tree_bytes(outs)
                                )
                            if (yi - start_idx) % sync_every == sync_every - 1:
                                jax.block_until_ready(carry.market.market_share)
                finally:
                    if trace_now:
                        jax.profiler.stop_trace()
                        profiled = True
                        logger.info("device trace written to %s", profile_dir)
                if debug:
                    # the reference runs its dataframe invariants after
                    # every on_frame transform (agents.py:149-262); here the
                    # carry pytree is checked after every year step
                    invariants.check_transform(
                        prev_carry, carry, context=f"year {year} carry"
                    )
                    invariants.check_finite(
                        carry, context=f"year {year} carry"
                    )
                    invariants.check_finite(
                        outs, context=f"year {year} outputs"
                    )
                    if not self._net_billing:
                        # the static all-NEM proof evaluated the cap gate at
                        # STATE_KW_BOUND; it stays sound only while the live
                        # state totals remain under that bound
                        self._check_state_kw_bound(carry, f"year {year}")
                summary = None
                if sentinel:
                    summary = health_mod.health_summary(
                        outs, self.table.mask)
                    if sync_per_year:
                        # serialized oracle path: the tiny [C, 2]
                        # verdict rides this year's existing host sync
                        self._health_verdict(
                            year, yi,
                            jax.device_get(summary),  # dgenlint: disable=L9
                            outs, escalate,
                        )
                    elif not defer_callback:
                        queued_health.append((year, yi, summary))
                logger.info("year %d (%d/%d) %.2fs%s", year, yi + 1,
                            len(self.years), time.time() - t0,
                            "" if sync_per_year else " (queued)")
                if callback is not None:
                    if defer_callback:
                        if pending_cb is not None:
                            # hand off before invoking: if the exporter
                            # raises partway, the finally flush must not
                            # re-write the same year's partition on top
                            # of partially-written parquet parts
                            prev, pending_cb = pending_cb, None
                            prev_h, pending_health = pending_health, None
                            if prev_h is not None and prev_h[2] is not None:
                                # the deferred year's health verdict
                                # gates its export: a breached year is
                                # never flushed to parquet
                                self._health_verdict(
                                    prev_h[0], prev_h[1],
                                    jax.device_get(prev_h[2]),  # dgenlint: disable=L9
                                    prev_h[3], escalate,
                                )
                            callback(*prev)
                        pending_cb = (year, yi, outs)
                        pending_health = (year, yi, summary, outs)
                        # let the exporter enqueue its device-side
                        # transfer prep (e.g. compact quantization) NOW,
                        # right behind this year's step — at callback
                        # time those ops would queue behind the NEXT
                        # year's step and serialize the pipeline
                        prep = getattr(callback, "prepare", None)
                        if prep is not None:
                            prep(year, yi, outs)
                    else:
                        callback(year, yi, outs)
                if ckpt_writer is not None:
                    ckpt_writer.save(year, carry)
                if collect:
                    # ONE batched device_get per year: per-leaf np.asarray
                    # costs a full host round trip each (~130 ms through a
                    # remote tunnel), turning collection into the dominant
                    # cost of small runs
                    to_fetch = {k: getattr(outs, k) for k in agent_fields}
                    if self.with_hourly:
                        to_fetch["_hourly"] = outs.state_hourly_net_mw
                    # serialized parity-oracle path: the sync fetch IS
                    # the point here (async runs route through hostio)
                    host = jax.device_get(to_fetch)  # dgenlint: disable=L9
                    for k in agent_fields:
                        collected[k].append(host[k])
                    if self.with_hourly:
                        hourly.append(host["_hourly"])
                if guard is not None:
                    guard.check(f"year {year}")
                if should_stop is not None and should_stop(year, yi):
                    # the year's exports and checkpoint save were
                    # already issued above; every gang process reaches
                    # this barrier once per year, so they all agree on
                    # the same stop year (the synchronized emergency-
                    # checkpoint contract)
                    logger.info(
                        "cooperative stop after year %d (%d/%d)",
                        year, yi + 1, len(self.years),
                    )
                    self._stop_idx = yi + 1
                    break

        except BaseException:
            loop_failed = True
            raise
        finally:
            if guard is not None:
                guard.stop()
            if pending_cb is not None:
                # flush the deferred trailing callback (the final year
                # on success; the last completed year on failure)
                try:
                    if (
                        pending_health is not None
                        and pending_health[2] is not None
                    ):
                        # health verdict gates the flush: a breached
                        # trailing year must surface, not export
                        self._health_verdict(
                            pending_health[0], pending_health[1],
                            jax.device_get(pending_health[2]),  # dgenlint: disable=L9
                            pending_health[3], escalate,
                        )
                    callback(*pending_cb)
                except Exception:  # noqa: BLE001
                    if not loop_failed:
                        # success path: a failed final-year export must
                        # surface, not return a silently truncated run
                        raise
                    # failure path: don't mask the original error with
                    # the flush failure
                    logger.exception("deferred year export failed")
                pending_cb = None
        if not sync_per_year:
            # drain the queued year pipeline before returning; the
            # scalar fetch (not just block_until_ready) guarantees the
            # chain really executed even on remote-tunnel platforms
            # with lazy readiness semantics
            with timing.timer("device_drain", ctx=self.timing_ctx):
                jax.block_until_ready(carry.market.market_share)
                float(jnp.sum(carry.batt_adopters_cum))
        if queued_health:
            # no-consumer pipelined path: one batched fetch of every
            # queued year's verdict at the end — detection is still
            # guaranteed, per-agent attribution happens on the
            # supervised re-entry (the device outputs are gone)
            hosts = jax.device_get([s for _, _, s in queued_health])
            for (qy, qyi, _), h in zip(queued_health, hosts):
                self._health_verdict(qy, qyi, h, None, escalate)
        return carry, collected, hourly
