"""National-scale synthetic table generator.

:mod:`dgen_tpu.io.synth` builds the small audit/test worlds in one shot
— every column materialized by one RNG stream, fine up to ~100k rows.
The pod-scale path needs more than that:

* **1M/10M-row worlds in O(chunk) host memory**: columns are generated
  in fixed :data:`NationalSpec.gen_chunk` row blocks, each block from
  its own counter-seeded RNG, so the transient working set is one
  block regardless of table size (the output columns themselves are
  the table).
* **Byte-determinism independent of materialization**: block ``i``
  always draws from ``SeedSequence((seed, i))``, so generating the
  whole table, generating it range by range, or having each gang
  worker generate ONLY its shard (``rows=``) all produce identical
  bytes — the multi-process analogue of the reference's
  identical-pickle-everywhere contract, without shipping a 10M-row
  pickle to every host.
* **State-stratified strata**: rows are laid out state-major with
  per-state counts allocated from census-scale population shares
  (largest-remainder, so strata are exact and deterministic), the
  shape a national run's whole-state device partitioning
  (parallel.partition) expects.
* **Scale-ready bank formats**: worlds save as standard agent packages
  (:mod:`dgen_tpu.io.package`) whose load/solar DGPB banks are written
  int8-quantized with per-row f32 scale sidecars (store dtype code 2,
  the at-rest companion of ``RunConfig.quant_banks``), plus a hashed
  ``world.json`` manifest so a generated world can be re-verified
  against its spec bit-for-bit.

CLI: ``python -m dgen_tpu.models.synth`` (generate / verify / smoke —
docs/userguide.md "National-scale synthetic runs").
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Optional, Tuple

import numpy as np

from dgen_tpu.io.synth import (
    N_STATES,
    STATE_IDX,
    STATES,
    SynthPopulation,
    make_load_profiles,
    make_solar_cf_profiles,
    make_tariff_specs,
    make_wholesale_prices,
)
from dgen_tpu.models.agents import AgentTable, ProfileBank, build_agent_table
from dgen_tpu.ops.tariff import NET_BILLING, NET_METERING, compile_tariffs

#: approximate 2020-census population shares (percent) over the
#: contiguous-US + DC modeling universe (io.synth.STATES) — the strata
#: weights a national table is stratified by. Values need not sum to
#: 100; they are normalized over the spec's state subset.
STATE_SHARES: Dict[str, float] = {
    "AL": 1.51, "AR": 0.91, "AZ": 2.16, "CA": 11.91, "CO": 1.74,
    "CT": 1.09, "DC": 0.21, "DE": 0.30, "FL": 6.49, "GA": 3.23,
    "IA": 0.96, "ID": 0.55, "IL": 3.86, "IN": 2.04, "KS": 0.88,
    "KY": 1.36, "LA": 1.40, "MA": 2.12, "MD": 1.86, "ME": 0.41,
    "MI": 3.03, "MN": 1.72, "MO": 1.85, "MS": 0.89, "MT": 0.33,
    "NC": 3.15, "ND": 0.23, "NE": 0.59, "NH": 0.42, "NJ": 2.80,
    "NM": 0.64, "NV": 0.94, "NY": 6.08, "OH": 3.55, "OK": 1.19,
    "OR": 1.28, "PA": 3.91, "RI": 0.33, "SC": 1.54, "SD": 0.27,
    "TN": 2.08, "TX": 8.77, "UT": 0.98, "VA": 2.60, "VT": 0.19,
    "WA": 2.32, "WI": 1.77, "WV": 0.54, "WY": 0.17,
}

#: rows per generation block — the byte-determinism unit (part of the
#: seed contract: changing it changes the RNG stream, like the seed)
GEN_CHUNK = 131072

#: tariff corpus selectors: "mixed" is the full io.synth corpus
#: (net-billing + TOU tariffs keep the bucket-sums kernel compiled in);
#: "nem" restricts to the net-metering subset, so run_static_flags
#: proves net_billing=False and the year step compiles the linear-NEM
#: program — the throughput protocol the scaling bench runs
#: (docs/perf.md "Scaling curves")
TARIFF_MIXES = ("mixed", "nem")


@dataclasses.dataclass(frozen=True)
class NationalSpec:
    """Seed contract for a national synthetic world: every field
    participates in determinism (two equal specs generate
    byte-identical tables and banks, however materialized)."""

    n_agents: int
    seed: int = 0
    states: Tuple[str, ...] = tuple(STATES)
    sector_weights: Tuple[float, float, float] = (0.7, 0.2, 0.1)
    tariff_mix: str = "mixed"
    n_regions: int = 10
    rate_switch_frac: float = 0.0
    gen_chunk: int = GEN_CHUNK
    #: bank corpus sizes (the national corpora are richer than the
    #: io.synth defaults: more archetypes per sector, finer latitude
    #: grading)
    load_profiles_per_sector: int = 8
    n_cf_profiles: int = 16
    #: dynamic-population schedule (dgen_tpu.ensemble.cohorts): this
    #: fraction of rows is future construction, entering uniformly over
    #: ``cohort_years`` instead of being alive at the start.
    #: :func:`generate_table` reserves those rows masked;
    #: :func:`generate_entry_years` hands the aligned entry vector to
    #: the ensemble driver. 0.0 (the default) draws NO extra RNG, so
    #: every pre-cohort world regenerates byte-identically.
    cohort_frac: float = 0.0
    cohort_years: Tuple[int, int] = (2026, 2040)

    def __post_init__(self) -> None:
        if self.n_agents < 1:
            raise ValueError("n_agents must be >= 1")
        if self.gen_chunk < 1:
            raise ValueError("gen_chunk must be >= 1")
        if self.tariff_mix not in TARIFF_MIXES:
            raise ValueError(
                f"tariff_mix {self.tariff_mix!r} not in {TARIFF_MIXES}")
        unknown = [s for s in self.states if s not in STATE_IDX]
        if unknown:
            raise ValueError(f"unknown states {unknown}")
        if abs(sum(self.sector_weights) - 1.0) > 1e-6:
            raise ValueError("sector_weights must sum to 1")
        if not (0.0 <= self.cohort_frac < 1.0):
            raise ValueError(
                f"cohort_frac must be in [0, 1), got {self.cohort_frac}")
        y0, y1 = self.cohort_years
        if y0 > y1 or y0 < 1:
            raise ValueError(
                f"cohort_years must be an ascending positive pair, "
                f"got {self.cohort_years}")

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["states"] = list(self.states)
        d["sector_weights"] = list(self.sector_weights)
        d["cohort_years"] = list(self.cohort_years)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "NationalSpec":
        d = dict(d)
        d["states"] = tuple(d["states"])
        d["sector_weights"] = tuple(d["sector_weights"])
        if "cohort_years" in d:
            d["cohort_years"] = tuple(d["cohort_years"])
        return cls(**d)


def state_counts(spec: NationalSpec) -> np.ndarray:
    """Exact per-state row counts: largest-remainder allocation of
    ``n_agents`` over the normalized population shares (ties broken by
    state order, so the strata are deterministic)."""
    w = np.asarray([STATE_SHARES[s] for s in spec.states], np.float64)
    w = w / w.sum()
    exact = w * spec.n_agents
    base = np.floor(exact).astype(np.int64)
    short = spec.n_agents - int(base.sum())
    order = np.argsort(-(exact - base), kind="stable")
    base[order[:short]] += 1
    return base


def _state_bounds(spec: NationalSpec) -> np.ndarray:
    """[n_spec_states] cumulative row bounds of the state-major layout."""
    return np.cumsum(state_counts(spec))


#: the documented residential cluster-shape distribution of a
#: ``tariff_mix="mixed"`` world (ops.tariffcluster's structural keys):
#: flat (1 period, 1 tier), tiered (1 period, 2 tiers), TOU (2
#: periods, 1 tier), TOU+tiers (2 periods, 2 tiers). Weights follow
#: the heavy collapse of real URDB corpora — mostly flat/tiered, a
#: TOU band, a thin TOU+tiered tail. Pools index
#: :func:`make_national_tariffs`'s "mixed" corpus order; stamped into
#: ``world.json`` by :func:`save_world` with the realized histogram.
MIXED_SHAPE_CLASSES = ("flat", "tiered", "tou", "tou_tiered")
MIXED_SHAPE_WEIGHTS = (0.35, 0.30, 0.25, 0.10)
MIXED_SHAPE_POOLS = ((0, 1), (2,), (3, 4), (6,))


def make_national_tariffs(mix: str) -> list:
    """The tariff corpus for a mix (raw spec dicts, io.package-ready).

    ``"nem"`` keeps only the net-metering specs of the synthetic corpus
    — with the table's default always-open NEM window this statically
    drops the bucket-sums kernel (models.simulation.run_static_flags),
    the cheapest honest national protocol.

    ``"mixed"`` is the full io.synth corpus plus a TOU+tiered
    residential net-billing rate (the fourth shape class of
    :data:`MIXED_SHAPE_POOLS`), inserted BEFORE the DG rate — the DG
    rate must stay last (``_chunk_columns`` resolves it as
    ``n_tariffs - 1``). The audit corpus (io.synth.make_tariff_specs)
    is deliberately untouched: program fingerprints key on its shapes.
    """
    specs = make_tariff_specs()
    if mix == "mixed":
        wkday = np.zeros((12, 24), dtype=int)
        wkday[:, 16:21] = 1
        tou_tiered = {
            "price": [[0.11, 0.17], [0.26, 0.33]],
            "tier_cap": [600.0, 1e38],
            "e_wkday_12by24": wkday,
            "e_wkend_12by24": np.zeros((12, 24), dtype=int),
            "fixed_charge": 10.0, "metering": NET_BILLING,
        }
        return specs[:-1] + [tou_tiered] + specs[-1:]
    return [s for s in specs if s.get("metering") == NET_METERING]


def _chunk_columns(spec: NationalSpec, ci: int, bounds: np.ndarray,
                   n_tariffs: int, res_tariffs: np.ndarray,
                   com_tariffs: np.ndarray, ind_tariff: int) -> dict:
    """All columns of generation block ``ci`` (full block, before any
    range slicing) — one counter-seeded RNG per block."""
    lo = ci * spec.gen_chunk
    hi = min(lo + spec.gen_chunk, spec.n_agents)
    n = hi - lo
    rng = np.random.default_rng(np.random.SeedSequence((spec.seed, ci)))

    # state-major strata: block rows map to states by the cumulative
    # bounds, no RNG involved (strata stay exact under sharding)
    local_state = np.searchsorted(bounds, np.arange(lo, hi), side="right")
    local_state = local_state.astype(np.int32)
    global_state = np.asarray(
        [STATE_IDX[s] for s in spec.states], np.int32)[local_state]

    # normalized before the draw: __post_init__ accepts weights to a
    # 1e-6 tolerance, Generator.choice demands ~1.5e-8 — a spec that
    # validates must also generate
    w = np.asarray(spec.sector_weights, np.float64)
    sector = rng.choice(3, size=n, p=w / w.sum()).astype(np.int32)
    lps = spec.load_profiles_per_sector
    load_idx = (sector * lps + rng.integers(0, lps, n)).astype(np.int32)
    cf_idx = np.clip(
        (global_state.astype(np.int64) * spec.n_cf_profiles) // N_STATES
        + rng.integers(-1, 2, n),
        0, spec.n_cf_profiles - 1,
    ).astype(np.int32)
    region_idx = (global_state % spec.n_regions).astype(np.int32)

    load_kwh = np.where(
        sector == 0,
        np.exp(rng.uniform(np.log(4e3), np.log(1.5e4), n)),
        np.where(
            sector == 1,
            np.exp(rng.uniform(np.log(3e4), np.log(4e5), n)),
            np.exp(rng.uniform(np.log(4e5), np.log(4e6), n)),
        ),
    ).astype(np.float32)
    customers = np.exp(
        rng.uniform(np.log(50.0), np.log(5000.0), n)).astype(np.float32)
    developable = rng.uniform(0.2, 0.95, n).astype(np.float32)

    if isinstance(res_tariffs, tuple):
        # mixed worlds: seeded two-draw scheme — a shape class by the
        # documented weights (MIXED_SHAPE_WEIGHTS), then a uniform
        # member of the class pool; the wide-range draw + modulo keeps
        # the RNG call count independent of the pool sizes, so adding
        # a tariff to a pool never shifts later columns' draws
        pool_arr, pool_len, wts = res_tariffs
        shape_cls = rng.choice(len(pool_len), size=n, p=wts)
        member = rng.integers(0, 1 << 62, n)
        res_draw = pool_arr[shape_cls, member % pool_len[shape_cls]]
    else:
        # nem worlds: the original single uniform draw (byte-frozen:
        # gang shards and world manifests pin this call sequence)
        res_draw = res_tariffs[rng.integers(0, len(res_tariffs), n)]
    tariff_idx = np.where(
        sector == 0,
        res_draw,
        np.where(
            sector == 1,
            com_tariffs[rng.integers(0, len(com_tariffs), n)],
            ind_tariff,
        ),
    ).astype(np.int32)
    switch = (rng.random(n) < spec.rate_switch_frac) & (sector == 0)
    dg_rate = n_tariffs - 1   # the corpus' DG rate is always last
    tariff_switch_idx = np.where(switch, dg_rate, tariff_idx).astype(np.int32)
    one_time_charge = np.where(
        switch, rng.uniform(100.0, 800.0, n), 0.0).astype(np.float32)

    # cohort entry draws come LAST, and only when cohort_frac > 0: the
    # call sequence above is byte-frozen (gang shards and world
    # manifests pin it), and a zero cohort_frac consuming no RNG is
    # what keeps pre-cohort worlds regenerating bit-identically
    if spec.cohort_frac > 0.0:
        y0, y1 = spec.cohort_years
        is_cohort = rng.random(n) < spec.cohort_frac
        entry_year = np.where(
            is_cohort, rng.integers(y0, y1 + 1, n), 0,
        ).astype(np.float32)
    else:
        entry_year = np.zeros(n, np.float32)

    return dict(
        state_idx=global_state,
        sector_idx=sector,
        region_idx=region_idx,
        tariff_idx=tariff_idx,
        tariff_switch_idx=tariff_switch_idx,
        load_idx=load_idx,
        cf_idx=cf_idx,
        customers_in_bin=customers,
        load_kwh_per_customer_in_bin=load_kwh,
        developable_frac=developable,
        one_time_charge=one_time_charge,
        entry_year=entry_year,
    )


#: generated column order (fixed: world manifests hash in this order;
#: entry_year is appended at the END so manifests of pre-cohort worlds
#: — which recorded only the first 11 — still verify clean)
COLUMNS = (
    "state_idx", "sector_idx", "region_idx", "tariff_idx",
    "tariff_switch_idx", "load_idx", "cf_idx", "customers_in_bin",
    "load_kwh_per_customer_in_bin", "developable_frac", "one_time_charge",
    "entry_year",
)


def _tariff_pools(spec: NationalSpec) -> tuple:
    """(n_tariffs, res_pool, com_pool, ind_tariff) for a mix — index
    pools into :func:`make_national_tariffs`'s corpus order."""
    n = len(make_national_tariffs(spec.tariff_mix))
    if spec.tariff_mix == "nem":
        # corpus: [flat NEM, tiered NEM, commercial TOU NEM, DG rate]
        return n, np.asarray([0, 1], np.int32), \
            np.asarray([1, 2], np.int32), 2
    # full corpus + TOU+tiered (make_national_tariffs "mixed" order):
    # residential draws follow the documented cluster-shape
    # distribution — the pool triple (padded 2-D pools, lengths,
    # weights) selects the weighted branch in _chunk_columns
    width = max(len(p) for p in MIXED_SHAPE_POOLS)
    pool_arr = np.zeros((len(MIXED_SHAPE_POOLS), width), np.int32)
    pool_len = np.zeros(len(MIXED_SHAPE_POOLS), np.int64)
    for i, p in enumerate(MIXED_SHAPE_POOLS):
        pool_arr[i, :len(p)] = p
        pool_len[i] = len(p)
    res = (pool_arr, pool_len,
           np.asarray(MIXED_SHAPE_WEIGHTS, np.float64))
    return n, res, np.asarray([1, 3, 5], np.int32), 5


def generate_columns(
    spec: NationalSpec,
    start: int = 0,
    stop: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Columns for absolute rows ``[start, stop)`` — byte-identical to
    the same slice of a whole-table materialization, whatever blocks
    the request spans (each covering block is generated in full from
    its own RNG and sliced)."""
    stop = spec.n_agents if stop is None else stop
    if not (0 <= start <= stop <= spec.n_agents):
        raise ValueError(
            f"row range [{start}, {stop}) outside [0, {spec.n_agents})")
    bounds = _state_bounds(spec)
    n_tariffs, res_p, com_p, ind_t = _tariff_pools(spec)
    out = {c: [] for c in COLUMNS}
    first = start // spec.gen_chunk
    last = max((stop - 1) // spec.gen_chunk, first) if stop > start else first
    for ci in range(first, last + 1):
        if stop == start:
            break
        cols = _chunk_columns(spec, ci, bounds, n_tariffs, res_p, com_p,
                              ind_t)
        lo = ci * spec.gen_chunk
        a = max(start - lo, 0)
        b = min(stop - lo, spec.gen_chunk)
        for c in COLUMNS:
            out[c].append(cols[c][a:b])
    return {
        c: (np.concatenate(v) if v else
            np.empty(0, np.int32 if c.endswith("idx") else np.float32))
        for c, v in out.items()
    }


def _hash_columns(cols: Dict[str, np.ndarray]) -> Dict[str, str]:
    """Per-column sha256 over the columns' raw bytes. Hashing whole
    columns and hashing them block-by-block walk the identical byte
    stream, so these digests match :func:`column_hashes` exactly."""
    return {
        c: hashlib.sha256(
            np.ascontiguousarray(cols[c]).tobytes()).hexdigest()
        for c in COLUMNS
    }


def column_hashes(spec: NationalSpec) -> Dict[str, str]:
    """Per-column sha256 of the whole table's bytes, accumulated block
    by block (O(chunk) memory — the world-manifest fingerprint)."""
    bounds = _state_bounds(spec)
    n_tariffs, res_p, com_p, ind_t = _tariff_pools(spec)
    hashers = {c: hashlib.sha256() for c in COLUMNS}
    n_blocks = (spec.n_agents + spec.gen_chunk - 1) // spec.gen_chunk
    for ci in range(n_blocks):
        cols = _chunk_columns(spec, ci, bounds, n_tariffs, res_p, com_p,
                              ind_t)
        for c in COLUMNS:
            hashers[c].update(np.ascontiguousarray(cols[c]).tobytes())
    return {c: h.hexdigest() for c, h in hashers.items()}


def _reserve_cohort_rows(table: AgentTable,
                         entry_year: np.ndarray) -> AgentTable:
    """Zero the mask on rows with a future entry year: cohort rows ship
    "reserved" — placed and padded with everyone else, but invisible to
    a plain Simulation until the ensemble driver's per-year mask update
    flips them alive (dgen_tpu.ensemble.cohorts)."""
    import jax.numpy as jnp

    alive = np.array(table.mask, dtype=np.float32)
    alive[:len(entry_year)] *= (entry_year == 0.0).astype(np.float32)
    return dataclasses.replace(table, mask=jnp.asarray(alive))


def generate_table(
    spec: NationalSpec,
    rows: Optional[Tuple[int, int]] = None,
    pad_multiple: int = 128,
) -> AgentTable:
    """Build the :class:`AgentTable` for the whole world, or — with
    ``rows=(start, stop)`` — for one shard of it (a gang worker
    generating only its slice). Shard tables carry GLOBAL agent ids,
    so shard exports concatenate into exactly the whole-table rows.

    Cohort rows (``spec.cohort_frac > 0``) come back masked; pair with
    :func:`generate_entry_years` to schedule their entry."""
    start, stop = rows if rows is not None else (0, spec.n_agents)
    cols = generate_columns(spec, start, stop)
    entry_year = cols.pop("entry_year")
    table = build_agent_table(
        n_states=N_STATES,
        pad_multiple=pad_multiple,
        agent_id=np.arange(start, stop, dtype=np.int64),
        **cols,
    )
    if spec.cohort_frac > 0.0:
        table = _reserve_cohort_rows(table, entry_year)
    return table


def generate_entry_years(
    spec: NationalSpec,
    rows: Optional[Tuple[int, int]] = None,
    pad_multiple: int = 128,
) -> np.ndarray:
    """[N_padded] f32 entry-year vector aligned row-for-row with
    :func:`generate_table`'s output (same pad rule): ``0.0`` = alive at
    start, a calendar year = cohort entry, ``COHORT_NEVER`` on padding
    rows — exactly the ``entry_year=`` operand
    :class:`dgen_tpu.ensemble.EnsembleSimulation` takes."""
    from dgen_tpu.ensemble.cohorts import COHORT_NEVER
    from dgen_tpu.models.agents import pad_to_multiple

    start, stop = rows if rows is not None else (0, spec.n_agents)
    entry = generate_columns(spec, start, stop)["entry_year"]
    n = stop - start
    out = np.full(pad_to_multiple(max(n, 1), pad_multiple),
                  COHORT_NEVER, dtype=np.float32)
    out[:n] = entry
    return out


def generate_banks(spec: NationalSpec) -> ProfileBank:
    """The world's f32 profile banks (shared [rows, 8760] corpora —
    tiny next to the table; quantization happens at save time or under
    ``RunConfig.quant_banks``)."""
    import jax.numpy as jnp

    return ProfileBank(
        load=jnp.asarray(make_load_profiles(
            n_per_sector=spec.load_profiles_per_sector, seed=spec.seed)),
        solar_cf=jnp.asarray(make_solar_cf_profiles(
            spec.n_cf_profiles, seed=spec.seed + 1)),
        wholesale=jnp.asarray(make_wholesale_prices(
            spec.n_regions, seed=spec.seed + 2)),
    )


def generate_world(
    spec: NationalSpec,
    rows: Optional[Tuple[int, int]] = None,
    pad_multiple: int = 128,
) -> SynthPopulation:
    """Table (whole or shard) + banks + compiled tariffs."""
    return SynthPopulation(
        table=generate_table(spec, rows=rows, pad_multiple=pad_multiple),
        profiles=generate_banks(spec),
        tariffs=compile_tariffs(make_national_tariffs(spec.tariff_mix)),
        n_regions=spec.n_regions,
    )


# ---------------------------------------------------------------------------
# On-disk worlds: standard agent packages + a hashed world manifest
# ---------------------------------------------------------------------------

WORLD_MANIFEST = "world.json"

_BANK_FILES = ("load_profiles.dgpb", "solar_cf.dgpb", "wholesale.dgpb")

#: package artifacts hashed as-written (agents.parquet is the file the
#: Simulation actually loads rows from — it must be covered too)
_PKG_FILES = ("agents.parquet", "tariffs.json", "meta.json")


def _file_sha256(path: str) -> str:
    # one streaming file-hash convention repo-wide (the run manifest's)
    from dgen_tpu.resilience.manifest import _sha256_file

    return _sha256_file(path)


def save_world(
    spec: NationalSpec,
    out_dir: str,
    quant_banks: bool = True,
) -> dict:
    """Materialize + persist a world as an agent package
    (:func:`dgen_tpu.io.package.load_population` loads it unchanged).

    ``quant_banks`` (default) re-writes the load/solar DGPB banks
    int8-quantized with per-row f32 scale sidecars (store dtype code 2)
    — 4x smaller at rest, dequantized transparently on read; wholesale
    stays f32 (it is never quantized in HBM either). Returns the
    ``world.json`` manifest (spec + column/bank hashes) it wrote.
    """
    import os

    from dgen_tpu.io import package
    from dgen_tpu.resilience.atomic import atomic_write_json

    # one generation pass: the same columns feed the table AND the
    # manifest hashes (block-wise and whole-column hashing walk the
    # identical byte stream, so verify_world's streamed column_hashes
    # reproduce these digests)
    cols = generate_columns(spec)
    col_hashes = _hash_columns(cols)
    # cohort rows are saved ALIVE: save_population keeps only mask > 0
    # rows, and the package must carry the full potential population.
    # The entry schedule is not a package column — it re-derives
    # bit-exactly from the manifest spec (generate_entry_years), which
    # the "cohorts" manifest block below points loaders at.
    entry_year = cols.pop("entry_year")
    table = build_agent_table(
        n_states=N_STATES, pad_multiple=128,
        agent_id=np.arange(spec.n_agents, dtype=np.int64), **cols,
    )
    profiles = generate_banks(spec)
    package.save_population(
        out_dir, table, profiles,
        make_national_tariffs(spec.tariff_mix), list(spec.states),
        quant_banks=quant_banks,
    )
    manifest = {
        "format": 1,
        "spec": spec.to_json(),
        "quant_banks": bool(quant_banks),
        "columns": col_hashes,
        "banks": {
            f: _file_sha256(os.path.join(out_dir, f)) for f in _BANK_FILES
        },
        "files": {
            f: _file_sha256(os.path.join(out_dir, f)) for f in _PKG_FILES
        },
    }
    if spec.cohort_frac > 0.0:
        sel = entry_year > 0.0
        ys, cs = np.unique(entry_year[sel].astype(np.int64),
                           return_counts=True)
        manifest["cohorts"] = {
            "cohort_frac": float(spec.cohort_frac),
            "cohort_years": list(spec.cohort_years),
            "n_cohort_rows": int(sel.sum()),
            "entry_histogram": {
                str(int(y)): int(c) for y, c in zip(ys, cs)
            },
        }
    if spec.tariff_mix == "mixed":
        # the documented cluster-shape distribution + what the seed
        # actually realized (residential rows only; commercial and
        # industrial draws are pool-uniform as before)
        t = cols["tariff_idx"][cols["sector_idx"] == 0]
        manifest["tariff_shape_mix"] = {
            "classes": list(MIXED_SHAPE_CLASSES),
            "weights": list(MIXED_SHAPE_WEIGHTS),
            "pools": [list(p) for p in MIXED_SHAPE_POOLS],
            "residential_histogram": {
                name: int(np.isin(t, pool).sum())
                for name, pool in zip(MIXED_SHAPE_CLASSES,
                                      MIXED_SHAPE_POOLS)
            },
        }
    atomic_write_json(os.path.join(out_dir, WORLD_MANIFEST), manifest)
    return manifest


def verify_world(world_dir: str) -> list:
    """Re-derive the world from its manifest spec and compare hashes.

    Returns a list of problem strings (empty = clean): a changed
    generator, a tampered bank file, or a stale manifest all surface
    here — the generation analogue of the run manifest's verify.
    """
    import json
    import os

    path = os.path.join(world_dir, WORLD_MANIFEST)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable {WORLD_MANIFEST}: {e}"]
    problems = []
    try:
        spec = NationalSpec.from_json(manifest["spec"])
    except (KeyError, TypeError, ValueError) as e:
        return [f"bad spec in {WORLD_MANIFEST}: {e}"]
    fresh = column_hashes(spec)
    for c, want in manifest.get("columns", {}).items():
        got = fresh.get(c)
        if got != want:
            problems.append(
                f"column {c}: generated {got} != recorded {want}")
    for kind, key in (("bank", "banks"), ("file", "files")):
        for f, want in manifest.get(key, {}).items():
            fp = os.path.join(world_dir, f)
            if not os.path.exists(fp):
                problems.append(f"{kind} {f}: missing")
            elif _file_sha256(fp) != want:
                problems.append(f"{kind} {f}: content hash mismatch")
    return problems


def shard_rows(spec: NationalSpec, shard: int, n_shards: int,
               pad_multiple: int = 1) -> Tuple[int, int]:
    """Contiguous row range of shard ``shard`` of ``n_shards`` (even
    split, remainder to the early shards; ``pad_multiple`` rounds the
    boundaries so each shard's table pads independently)."""
    if not (0 <= shard < n_shards):
        raise ValueError(f"shard {shard} outside [0, {n_shards})")
    base = spec.n_agents // n_shards
    rem = spec.n_agents % n_shards
    if pad_multiple > 1 and base < pad_multiple:
        # rounding spans smaller than one pad unit would silently
        # empty the early shards and pile every row onto the last
        raise ValueError(
            f"cannot split {spec.n_agents} rows into {n_shards} shards "
            f"at pad_multiple={pad_multiple}: each shard spans ~{base} "
            f"rows, fewer than one pad unit — grow the table, use "
            f"fewer shards, or drop the pad rounding")
    start = shard * base + min(shard, rem)
    stop = start + base + (1 if shard < rem else 0)
    if pad_multiple > 1:
        start = (start // pad_multiple) * pad_multiple
        if stop != spec.n_agents:
            stop = (stop // pad_multiple) * pad_multiple
    return start, stop


# ---------------------------------------------------------------------------
# CLI: generate / verify / smoke
# ---------------------------------------------------------------------------

def _spec_from_args(args) -> NationalSpec:
    y0, y1 = (int(v) for v in args.cohort_years.split(":"))
    return NationalSpec(
        n_agents=args.agents,
        seed=args.seed,
        states=tuple(args.states.split(",")) if args.states else tuple(STATES),
        tariff_mix=args.tariff_mix,
        n_regions=args.regions,
        rate_switch_frac=args.rate_switch_frac,
        gen_chunk=args.gen_chunk,
        cohort_frac=args.cohort_frac,
        cohort_years=(y0, y1),
    )


def _smoke(args) -> int:
    """check.sh gate: generate a small national world, step two model
    years through the production 2-D placement path on a forced
    hosts x devices CPU mesh, and verify the run manifest — so the
    generator and the mesh promotion cannot rot between bench rounds."""
    import json
    import os
    import tempfile
    import time

    from dgen_tpu.parallel.mesh import parse_mesh_shape
    from dgen_tpu.utils import compat

    h, d = parse_mesh_shape(args.mesh)
    compat.set_cpu_device_count(h * d)

    import jax

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io.export import RunExporter
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation
    from dgen_tpu.parallel.mesh import make_mesh
    from dgen_tpu.resilience.manifest import RunManifest

    if len(jax.devices()) < h * d:
        print(f"smoke: cannot force {h * d} CPU devices "
              f"(got {len(jax.devices())})")
        return 2

    spec = _spec_from_args(args)
    t0 = time.time()
    world = generate_world(spec)
    gen_s = time.time() - t0

    cfg = ScenarioConfig(name="synth-smoke", start_year=2014,
                         end_year=2016, anchor_years=())
    inputs = scen.uniform_inputs(
        cfg, n_groups=world.table.n_groups, n_regions=spec.n_regions)
    run_dir = args.out or tempfile.mkdtemp(prefix="dgen-synth-smoke-")
    mesh = make_mesh(shape=(h, d))
    sim = Simulation(
        world.table, world.profiles, world.tariffs, inputs, cfg,
        RunConfig(sizing_iters=4), mesh=mesh,
    )
    manifest = RunManifest(run_dir)
    exporter = RunExporter(
        run_dir, agent_id=sim.host_agent_id, mask=sim.host_mask,
        manifest=manifest,
        meta={"smoke": {"mesh": args.mesh, "agents": spec.n_agents}},
    )
    t0 = time.time()
    res = sim.run(callback=exporter, collect=False,
                  checkpoint_dir=os.path.join(run_dir, "ckpt"))
    run_s = time.time() - t0
    report = manifest.verify()
    ok = report.ok and len(res.years) == len(cfg.model_years)
    print(json.dumps({
        "smoke": "ok" if ok else "FAILED",
        "agents": spec.n_agents,
        "mesh": f"{h}x{d}",
        "years": [int(y) for y in res.years],
        "generate_s": round(gen_s, 2),
        "run_s": round(run_s, 2),
        "manifest_ok": report.ok,
        "manifest": report.to_json(),
        "run_dir": run_dir,
    }))
    return 0 if ok else 1


def main(argv=None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(
        prog="python -m dgen_tpu.models.synth",
        description="national-scale synthetic world generator "
                    "(docs/userguide.md 'National-scale synthetic runs')",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    def world_args(sp):
        sp.add_argument("--agents", type=int, default=10_240)
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--states", default="",
                        help="comma list (default: all 49)")
        sp.add_argument("--tariff-mix", choices=TARIFF_MIXES,
                        default="mixed")
        sp.add_argument("--regions", type=int, default=10)
        sp.add_argument("--rate-switch-frac", type=float, default=0.0)
        sp.add_argument("--gen-chunk", type=int, default=GEN_CHUNK)
        sp.add_argument("--cohort-frac", type=float, default=0.0,
                        help="fraction of rows reserved as future-"
                             "construction cohorts (dgen_tpu.ensemble)")
        sp.add_argument("--cohort-years", default="2026:2040",
                        help="y0:y1 entry-year range for cohort rows")

    g = sub.add_parser(
        "generate", help="materialize a world as an agent package "
        "(+ hashed world.json manifest)")
    world_args(g)
    g.add_argument("--out", required=True)
    g.add_argument("--no-quant-banks", action="store_true",
                   help="keep the DGPB banks f32 instead of int8+scales")

    v = sub.add_parser(
        "verify", help="re-derive a saved world from its manifest spec "
        "and compare hashes")
    v.add_argument("world_dir")

    s = sub.add_parser(
        "smoke", help="generate a small world, step 2 years on a forced "
        "hosts x devices CPU mesh, verify the run manifest (check.sh)")
    world_args(s)
    s.set_defaults(tariff_mix="nem")
    s.add_argument("--mesh", default="1x8", help="HxD (default 1x8)")
    s.add_argument("--out", default="",
                   help="run dir (default: a fresh temp dir)")

    args = p.parse_args(argv)
    if args.cmd == "generate":
        spec = _spec_from_args(args)
        manifest = save_world(
            spec, args.out, quant_banks=not args.no_quant_banks)
        print(json.dumps({
            "world": args.out, "agents": spec.n_agents,
            "states": len(spec.states),
            "quant_banks": manifest["quant_banks"],
        }))
        return 0
    if args.cmd == "verify":
        problems = verify_world(args.world_dir)
        for prob in problems:
            print(f"verify: {prob}")
        print(json.dumps({"world": args.world_dir,
                          "clean": not problems,
                          "problems": len(problems)}))
        return 0 if not problems else 1
    return _smoke(args)


if __name__ == "__main__":
    raise SystemExit(main())
