"""Market step: max-market-share, Bass diffusion, historical anchoring,
and integer storage-attachment allocation — all as vectorized segment
ops over the agent axis.

Replaces (reference file:line):
  * ``calc_max_market_share``            financial_functions.py:1264
  * ``calc_diffusion_solar``             diffusion_functions_elec.py:24
  * ``bass_diffusion`` / ``calc_equiv_time``  diffusion_functions_elec.py:323,343
  * historical anchoring                 diffusion_functions_elec.py:99-133
  * ``_allocate_battery_adopters_integer``  attachment_rate_functions.py:58

The reference implements these as pandas merges and per-group Python
loops; here every step is a dense gather / segment_sum / segment-aware
sort so the whole market update jits as one device program. Agent group
membership (state x sector) is a precomputed ``group_idx`` with a static
group count, so state-level reductions are ``segment_sum``s (and under
sharding, psums — see dgen_tpu.parallel).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from dgen_tpu.config import PAYBACK_GRID_N, PAYBACK_GRID_STEP


# ---------------------------------------------------------------------------
# Max market share
# ---------------------------------------------------------------------------

def max_market_share(
    payback_period: jax.Array,
    sector_idx: jax.Array,
    mms_table: jax.Array,
    interp: bool = False,
) -> jax.Array:
    """Look up max market share from the payback curve.

    ``mms_table``: [n_sectors, PAYBACK_GRID_N] tabulated on the 0.1-year
    payback grid. The reference discretizes payback to an integer
    factor (x100) and merges against its lookup table
    (financial_functions.py:1290-1307); a gather is the dense analogue.

    ``interp=True`` (the differentiable twin, dgen_tpu.grad) replaces
    the round-to-grid snap with linear interpolation between the two
    bracketing table rows: the gradient of share w.r.t. payback is the
    table's local slope instead of zero-a.e., and the gradient w.r.t.
    ``mms_table`` itself spreads over both rows (what the calibration
    elasticity rides). Values differ from the hard lookup by at most
    half a grid step of curve movement.
    """
    if interp:
        from dgen_tpu.grad.smooth import lerp_lookup

        return lerp_lookup(
            mms_table[sector_idx], payback_period / PAYBACK_GRID_STEP
        )
    idx = jnp.clip(
        jnp.round(payback_period / PAYBACK_GRID_STEP).astype(jnp.int32),
        0,
        PAYBACK_GRID_N - 1,
    )
    return mms_table[sector_idx, idx]


# ---------------------------------------------------------------------------
# Bass diffusion
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MarketState:
    """Cross-year carry per agent (the reference's ``market_last_year``
    handoff frame, diffusion_functions_elec.py:136-156)."""

    market_share: jax.Array          # [N]
    max_market_share: jax.Array      # [N]
    adopters_cum: jax.Array          # [N]
    market_value: jax.Array          # [N]
    system_kw_cum: jax.Array         # [N]
    batt_kw_cum: jax.Array           # [N]
    batt_kwh_cum: jax.Array          # [N]
    initial_adopters: jax.Array      # [N]
    initial_market_share: jax.Array  # [N]

    @staticmethod
    def zeros(n: int) -> "MarketState":
        # one buffer PER FIELD: the year step donates the carry, and
        # XLA rejects donating the same buffer through two parameters —
        # a single aliased zeros array would fail any first_year=False
        # step on a fresh carry
        n_fields = len(dataclasses.fields(MarketState))
        return MarketState(
            *(jnp.zeros(n, dtype=jnp.float32) for _ in range(n_fields))
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DiffusionOutputs:
    """Per-agent per-year adoption results."""

    market_share: jax.Array
    new_market_share: jax.Array
    new_adopters: jax.Array
    new_system_kw: jax.Array
    new_market_value: jax.Array
    number_of_adopters: jax.Array
    system_kw_cum: jax.Array
    market_value: jax.Array


def bass_new_adopt_fraction(p: jax.Array, q: jax.Array, teq2: jax.Array) -> jax.Array:
    """Cumulative Bass adoption fraction at equivalent time ``teq2``
    (reference diffusion_functions_elec.py:336-337)."""
    f = jnp.exp(-(p + q) * teq2)
    return (1.0 - f) / (1.0 + (q / p) * f)


def equivalent_time(
    market_share_last_year: jax.Array,
    mms: jax.Array,
    p: jax.Array,
    q: jax.Array,
) -> jax.Array:
    """Invert the Bass curve to find where last year's share sits
    (reference diffusion_functions_elec.py:343-372)."""
    mms_fz = jnp.where(mms == 0.0, 1e-9, mms)
    ratio = jnp.where(
        market_share_last_year > mms_fz, 0.0, market_share_last_year / mms_fz
    )
    return jnp.log((1.0 - ratio) / (1.0 + ratio * (q / p))) / (-(p + q))


def _bass_floored_share(
    market_share_last: jax.Array,
    mms: jax.Array,
    bass_p: jax.Array,
    bass_q: jax.Array,
    teq_yr1: jax.Array,
    is_first_year: bool,
    year_step: float,
) -> jax.Array:
    """The Bass solve shared by the solar and tech-choice paths: invert
    to equivalent time, step forward, take the new cumulative share,
    floored at last year's (reference diffusion_functions_elec.py:75
    and :290)."""
    teq = equivalent_time(market_share_last, mms, bass_p, bass_q)
    teq2 = teq + (teq_yr1 if is_first_year else year_step)
    bass_ms = mms * bass_new_adopt_fraction(bass_p, bass_q, teq2)
    return jnp.maximum(market_share_last, bass_ms)


def diffusion_step(
    state: MarketState,
    mms: jax.Array,
    system_kw: jax.Array,
    system_capex_per_kw: jax.Array,
    developable_agent_weight: jax.Array,
    bass_p: jax.Array,
    bass_q: jax.Array,
    teq_yr1: jax.Array,
    is_first_year: bool,
    year_step: float = 2.0,
) -> DiffusionOutputs:
    """One Bass-diffusion solve (reference
    diffusion_functions_elec.py:24-96 ``calc_diffusion_solar``; battery
    flows deferred to :func:`allocate_battery_adopters`)."""
    msly = state.market_share
    market_share = _bass_floored_share(
        msly, mms, bass_p, bass_q, teq_yr1, is_first_year, year_step)
    new_ms = market_share - msly
    # zero the step where share already exceeds the (possibly shrunken)
    # max market share (reference diffusion_functions_elec.py:77)
    new_ms = jnp.where(market_share > mms, 0.0, new_ms)

    new_adopters = new_ms * developable_agent_weight
    new_system_kw = new_adopters * system_kw
    new_market_value = new_adopters * system_kw * system_capex_per_kw

    return DiffusionOutputs(
        market_share=market_share,
        new_market_share=new_ms,
        new_adopters=new_adopters,
        new_system_kw=new_system_kw,
        new_market_value=new_market_value,
        number_of_adopters=state.adopters_cum + new_adopters,
        system_kw_cum=state.system_kw_cum + new_system_kw,
        market_value=state.market_value + new_market_value,
    )


def diffusion_step_tech_choice(
    market_share_last: jax.Array,      # [N, T]
    adopters_cum_last: jax.Array,      # [N, T]
    capacity_cum_last: jax.Array,      # [N, T]
    market_value_last: jax.Array,      # [N, T]
    selected: jax.Array,               # [N, T] 1.0 for the chosen tech
    mms: jax.Array,                    # [N, T]
    system_kw: jax.Array,              # [N, T]
    system_capex_per_kw: jax.Array,    # [N, T]
    developable_agent_weight: jax.Array,  # [N]
    bass_p: jax.Array,                 # [N, T]
    bass_q: jax.Array,                 # [N, T]
    teq_yr1: jax.Array,                # [N, T]
    is_first_year: bool,
    year_step: float = 2.0,
) -> dict:
    """The reference's legacy multi-technology diffusion solve
    (``calc_diffusion``, diffusion_functions_elec.py:162-245 — the
    wind-era tech-choice path its solar driver no longer calls, kept
    here for the same multi-tech scenarios).  Agents carry one row per
    candidate technology; ``selected`` marks this year's chosen option.

    Semantics mirrored exactly:

      * Bass share floored at last year's (elec.py:290 then :206);
      * diffusion share zeroed for NON-selected techs (:203) — their
        share holds at last year's via the floor;
      * tech-choice cap: the selected tech's share is capped at
        ``1 - sum(unselected shares)`` within the agent (:209-227), so
        total share never exceeds 1;
      * the new-share step zeroes where share exceeds the (possibly
        shrunken) max market share (:230-231);
      * adopters/capacity/value flows gated on a nonzero system size
        (:234-236) and accumulated onto last year's (:239-241).

    Returns the dict of [N, T] outputs plus the carry fields for the
    next solve year (the reference's ``market_last_year`` frame).
    """
    sel = selected.astype(market_share_last.dtype)
    diffusion_ms = _bass_floored_share(
        market_share_last, mms, bass_p, bass_q, teq_yr1, is_first_year,
        year_step)                                          # elec.py:290
    diffusion_ms = diffusion_ms * sel                       # elec.py:203
    market_share = jnp.maximum(diffusion_ms, market_share_last)

    # cap the SELECTED tech at 1 - (sum of unselected shares) per agent
    unselected_sum = jnp.sum(
        market_share * (1.0 - sel), axis=1, keepdims=True
    )
    cap = 1.0 - unselected_sum
    market_share = jnp.where(
        sel > 0, jnp.minimum(market_share, cap), market_share
    )

    new_ms = market_share - market_share_last
    new_ms = jnp.where(market_share > mms, 0.0, new_ms)

    w = developable_agent_weight[:, None]
    new_adopters = jnp.where(system_kw == 0.0, 0.0, new_ms * w)
    new_capacity = new_adopters * system_kw
    new_value = new_adopters * system_kw * system_capex_per_kw

    return {
        "market_share": market_share,
        "new_market_share": new_ms,
        "new_adopters": new_adopters,
        "new_capacity_kw": new_capacity,
        "new_market_value": new_value,
        "number_of_adopters": adopters_cum_last + new_adopters,
        "installed_capacity_kw": capacity_cum_last + new_capacity,
        "market_value": market_value_last + new_value,
    }


# ---------------------------------------------------------------------------
# Historical anchoring
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_groups",))
def anchor_to_observed(
    system_kw_cum: jax.Array,
    group_idx: jax.Array,
    observed_group_kw: jax.Array,
    sector_is_res: jax.Array,
    developable_agent_weight: jax.Array,
    n_groups: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Rescale modeled cumulative PV to observed deployment in anchor
    years (reference diffusion_functions_elec.py:99-133).

    Returns (system_kw_cum, number_of_adopters, market_share), all
    recomputed from the observed state x sector totals. Adopter counts
    use the reference's per-system heuristic (5 kW res / 100 kW non-res,
    :126).
    """
    group_kw = jax.ops.segment_sum(system_kw_cum, group_idx, n_groups)
    # only developable agents can carry anchored capacity — this also
    # keeps padding rows (weight 0) out of the zero-modeled fallback
    # split, so results are invariant under padded reorderings
    countable = (developable_agent_weight > 0.0).astype(system_kw_cum.dtype)
    group_count = jax.ops.segment_sum(countable, group_idx, n_groups)
    per_agent_group_kw = group_kw[group_idx]
    per_agent_count = jnp.maximum(group_count[group_idx], 1.0)
    scale = jnp.where(
        per_agent_group_kw == 0.0,
        countable / per_agent_count,
        system_kw_cum / jnp.maximum(per_agent_group_kw, 1e-30),
    )
    anchored_kw = scale * observed_group_kw[group_idx]
    adopters = jnp.where(sector_is_res, anchored_kw / 5.0, anchored_kw / 100.0)
    share = jnp.where(
        developable_agent_weight == 0.0,
        0.0,
        adopters / jnp.maximum(developable_agent_weight, 1e-30),
    )
    return anchored_kw, adopters, share


# ---------------------------------------------------------------------------
# Integer battery-adopter allocation (largest remainders, on device)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_groups",))
def allocate_battery_adopters(
    new_adopters: jax.Array,
    group_idx: jax.Array,
    attachment_rate: jax.Array,
    agent_order_key: jax.Array,
    n_groups: int,
) -> jax.Array:
    """Largest-remainders integer allocation of battery adopters within
    each state x sector group (reference
    attachment_rate_functions.py:58-148).

    ``attachment_rate``: [n_groups] in [0, 1].
    ``agent_order_key``: [N] deterministic tiebreak (agent id), matching
    the reference's sort on (fraction desc, agent_id asc).

    Device-native formulation: instead of a per-group Python loop, one
    global sort on the composite key (group, -frac, id) plus a
    segment-rank gives each agent its within-group remainder rank; the
    top ``remainder[g]`` ranks in each group win the extra unit.
    """
    n = new_adopters.shape[0]
    r = jnp.clip(attachment_rate, 0.0, 1.0)[group_idx]

    f = r * jnp.maximum(new_adopters, 0.0)
    base = jnp.floor(f)
    frac = f - base

    group_target = jnp.round(
        jax.ops.segment_sum(f, group_idx, n_groups)
    )
    group_base = jax.ops.segment_sum(base, group_idx, n_groups)
    remainder = jnp.maximum(group_target - group_base, 0.0)  # [G]

    # sort agents by (group asc, frac desc, id asc)
    order = jnp.lexsort((agent_order_key, -frac, group_idx))
    sorted_group = group_idx[order]
    # rank within group: position minus the group's first position
    pos = jnp.arange(n)
    group_start = jax.ops.segment_min(pos, sorted_group, n_groups)
    rank_in_group = pos - group_start[sorted_group]
    wins_sorted = rank_in_group < remainder[sorted_group]
    wins = jnp.zeros(n, dtype=jnp.float32).at[order].set(
        wins_sorted.astype(jnp.float32)
    )
    return base + wins


# ---------------------------------------------------------------------------
# Initial market shares (first model year)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_groups",))
def initial_market_shares(
    starting_group_kw: jax.Array,
    starting_group_batt_kw: jax.Array,
    starting_group_batt_kwh: jax.Array,
    group_idx: jax.Array,
    developable_agent_weight: jax.Array,
    system_kw: jax.Array,
    n_groups: int,
) -> MarketState:
    """Apportion state x sector starting capacity to agents by
    developable weight (reference agent_mutation/elec.py:701
    ``estimate_initial_market_shares``)."""
    group_weight = jax.ops.segment_sum(
        developable_agent_weight, group_idx, n_groups
    )
    w_frac = developable_agent_weight / jnp.maximum(group_weight[group_idx], 1e-30)

    kw_cum = w_frac * starting_group_kw[group_idx]
    batt_kw_cum = w_frac * starting_group_batt_kw[group_idx]
    batt_kwh_cum = w_frac * starting_group_batt_kwh[group_idx]
    adopters = kw_cum / jnp.maximum(system_kw, 1e-9)
    share = jnp.where(
        developable_agent_weight == 0.0,
        0.0,
        jnp.clip(adopters / jnp.maximum(developable_agent_weight, 1e-30), 0.0, 1.0),
    )
    return MarketState(
        market_share=share,
        max_market_share=share,
        adopters_cum=adopters,
        market_value=jnp.zeros_like(share),
        system_kw_cum=kw_cum,
        batt_kw_cum=batt_kw_cum,
        batt_kwh_cum=batt_kwh_cum,
        initial_adopters=adopters,
        initial_market_share=share,
    )
