"""The columnar agent table: HBM-resident struct-of-arrays population.

The reference wraps a pandas DataFrame (index = agent_id) in an
``Agents`` container and funnels every transformation through
``on_frame`` / ``chunk_on_row`` (reference agents.py:12,120-147). That
dispatch seam is where its CPU process-pool parallelism lives. Here the
population is a frozen pytree of dense arrays with a fixed schema —
"transforms" are pure functions returning new pytrees, vmap/shard_map
provide the parallelism, and the invariant harness
(dgen_tpu.utils.invariants) replaces the runtime dataframe tests.

Ragged structures the reference keeps in object cells are compiled to
indices into shared banks at ingest (SURVEY.md §7 design stance):
``tariff_dict`` -> ``tariff_idx`` into a TariffBank; 8760 load/solar
profiles -> ``load_idx`` / ``cf_idx`` into a ProfileBank; nested
incentive frames -> fixed-width IncentiveParams leaves.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import SECTORS
from dgen_tpu.ops.cashflow import IncentiveParams
from dgen_tpu.resilience.faults import corrupt_point, corrupt_rows


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AgentTable:
    """Static per-agent attributes (the reference's ``cols_base``
    columns that survive the per-year column reset,
    dgen_model.py:245-248). All arrays share the leading agent axis N;
    N is padded (``mask``) to a lane-friendly multiple.
    """

    agent_id: jax.Array        # [N] int32
    mask: jax.Array            # [N] float32, 1 = real agent, 0 = padding
    state_idx: jax.Array       # [N] int32
    sector_idx: jax.Array      # [N] int32 (0 res, 1 com, 2 ind)
    group_idx: jax.Array       # [N] int32 = state_idx * n_sectors + sector_idx
    region_idx: jax.Array      # [N] int32 census-division / BA for trajectories
    tariff_idx: jax.Array      # [N] int32 into TariffBank
    #: post-adoption DG rate (reference agent_mutation/elec.py:838
    #: ``apply_rate_switch``); equals tariff_idx when no switch applies
    tariff_switch_idx: jax.Array  # [N] int32 into TariffBank
    load_idx: jax.Array        # [N] int32 into ProfileBank.load
    cf_idx: jax.Array          # [N] int32 into ProfileBank.solar_cf
    customers_in_bin: jax.Array            # [N] f32
    load_kwh_per_customer_in_bin: jax.Array  # [N] f32 (base year)
    developable_frac: jax.Array            # [N] f32
    #: one-time interconnection charge, applied only when the DG-rate
    #: switch takes effect (reference elec.py:850-860)
    one_time_charge: jax.Array             # [N] f32
    #: NEM availability (reference apply_export_tariff_params,
    #: elec.py:92-119): system-kW limit (0 = no NEM; while NEM is
    #: active it caps the sizing bracket) + the policy window years
    #: (reference filter_nem_year, elec.py:449-454)
    nem_kw_limit: jax.Array                # [N] f32
    nem_first_year: jax.Array              # [N] f32
    nem_sunset_year: jax.Array             # [N] f32
    #: DG-rate switch window: the switch to ``tariff_switch_idx``
    #: applies only when the SIZED kW lands in
    #: [switch_min_kw, switch_max_kw) (reference elec.py:844-845)
    switch_min_kw: jax.Array               # [N] f32
    switch_max_kw: jax.Array               # [N] f32
    incentives: IncentiveParams            # leaves [N, 2]

    n_states: int = dataclasses.field(metadata=dict(static=True), default=51)

    @property
    def n_agents(self) -> int:
        return self.agent_id.shape[0]

    @property
    def n_sectors(self) -> int:
        return len(SECTORS)

    @property
    def n_groups(self) -> int:
        return self.n_states * self.n_sectors

    def developable_agent_weight(self, customers: jax.Array) -> jax.Array:
        """Developable customer weight (reference
        agent_mutation/elec.py:414 ``calculate_developable_customers_and_load``)."""
        return self.developable_frac * customers * self.mask


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ProfileBank:
    """Shared 8760 profile banks; agents index into these instead of the
    reference's per-agent SQL fetches (agent_mutation/elec.py:508-558 —
    its biggest serial bottleneck, SURVEY.md §7)."""

    load: jax.Array       # [L, 8760] normalized to sum 1.0
    solar_cf: jax.Array   # [S, 8760] kWh per kW_dc per hour
    wholesale: jax.Array  # [R, 8760] $/kWh wholesale price by region
    #: int8 quantized banks (RunConfig.quant_banks): per-row f32
    #: dequant factors for ``load`` / ``solar_cf`` when those carry
    #: int8 codes (real value = scale[row] * code); None = unquantized.
    #: The wholesale/sell stream is never quantized (it mixes with f32
    #: tariff TOU prices per agent; see billpallas.sell_rate_hourly).
    load_scale: jax.Array = None
    solar_cf_scale: jax.Array = None

    @property
    def hours(self) -> int:
        return self.load.shape[1]


def quantize_rows(bank) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8 quantization of a [R, 8760] profile bank:
    ``codes = rint(x / scale)`` with ``scale = rowmax(|x|) / 127``
    (all-zero rows get scale 1.0, so dequantization is exact zero).
    Exact zeros stay exact zeros — the daylight-compaction premise
    (gen == 0 off-daylight) survives quantization."""
    x = np.asarray(bank, np.float32)
    amax = np.max(np.abs(x), axis=1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(
        np.rint(x / scale[:, None]), -127, 127
    ).astype(np.int8)
    return q, scale


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


#: padding fills that keep masked rows inert in the sizing kernels
#: (mirrors build_agent_table's pads): no NEM cap pressure, switch
#: window never entered, sunset far in the future
_PAD_FILLS = {
    "nem_kw_limit": 1e30,
    "nem_sunset_year": 9999.0,
    "switch_min_kw": 1e30,
    "switch_max_kw": 1e30,
}


def pad_table(table: AgentTable, multiple: int) -> AgentTable:
    """Re-pad an existing table so N is a multiple of ``multiple``.

    Used by the driver's chunked year step (the agent axis must divide
    evenly into chunks) — new rows carry mask 0 and the same inert
    fills as :func:`build_agent_table`'s padding.
    """
    n = table.n_agents
    n_new = pad_to_multiple(n, multiple)
    if n_new == n:
        return table
    pad = n_new - n

    def extend(x, fill=0):
        tail = jnp.full((pad,) + x.shape[1:], fill, dtype=x.dtype)
        return jnp.concatenate([jnp.asarray(x), tail], axis=0)

    repl = {}
    for f in dataclasses.fields(AgentTable):
        if f.name in ("incentives", "n_states"):
            continue
        repl[f.name] = extend(
            getattr(table, f.name), _PAD_FILLS.get(f.name, 0)
        )
    inc = jax.tree.map(extend, table.incentives)
    return dataclasses.replace(table, incentives=inc, **repl)


def build_agent_table(
    *,
    state_idx: np.ndarray,
    sector_idx: np.ndarray,
    region_idx: np.ndarray,
    tariff_idx: np.ndarray,
    load_idx: np.ndarray,
    cf_idx: np.ndarray,
    customers_in_bin: np.ndarray,
    load_kwh_per_customer_in_bin: np.ndarray,
    developable_frac: np.ndarray,
    n_states: int,
    agent_id: np.ndarray | None = None,
    incentives: IncentiveParams | None = None,
    tariff_switch_idx: np.ndarray | None = None,
    one_time_charge: np.ndarray | None = None,
    nem_kw_limit: np.ndarray | None = None,
    nem_first_year: np.ndarray | None = None,
    nem_sunset_year: np.ndarray | None = None,
    switch_min_kw: np.ndarray | None = None,
    switch_max_kw: np.ndarray | None = None,
    pad_multiple: int = 128,
) -> AgentTable:
    """Assemble + pad an :class:`AgentTable` from host arrays.

    Padding agents carry mask 0, zero customers/load, and point at
    index 0 of every bank so gathers stay in-bounds; every kernel output
    is masked before aggregation.

    ``agent_id``: stable per-row ids (default ``arange(n)``). Shard
    generation (models.synth: each gang worker materializing only its
    row range) passes the GLOBAL row ids here so per-shard exports key
    identically to a whole-table run.
    """
    n = int(state_idx.shape[0])

    # resilience fault site (kind ``corrupt``): malformed rows entering
    # the agent table at ingest — a NaN customer count and an
    # out-of-range tariff reference on the deterministic
    # DGEN_TPU_FAULT_CORRUPT_ROWS rows.  Load-time validation
    # (resilience.quarantine) must quarantine exactly these rows; with
    # validation off they poison their whole state (the drill's
    # counterfactual).
    if corrupt_point("ingest_corrupt_row") and n:
        rows = [int(r) % n for r in corrupt_rows()]
        customers_in_bin = np.array(
            np.asarray(customers_in_bin), dtype=np.float64)
        customers_in_bin[rows[0]] = np.nan
        if len(rows) > 1:
            tariff_idx = np.array(np.asarray(tariff_idx), dtype=np.int64)
            tariff_idx[rows[1]] = 2 ** 24

    n_pad = pad_to_multiple(max(n, 1), pad_multiple)

    def pad_i(a, fill=0):
        out = np.full(n_pad, fill, dtype=np.int32)
        out[:n] = np.asarray(a, dtype=np.int32)
        return jnp.asarray(out)

    def pad_f(a, fill=0.0):
        out = np.full(n_pad, fill, dtype=np.float32)
        out[:n] = np.asarray(a, dtype=np.float32)
        return jnp.asarray(out)

    mask = np.zeros(n_pad, dtype=np.float32)
    mask[:n] = 1.0

    n_sectors = len(SECTORS)
    group = np.asarray(state_idx, np.int32) * n_sectors + np.asarray(sector_idx, np.int32)

    if incentives is None:
        z2 = jnp.zeros((n_pad, 2), dtype=jnp.float32)
        incentives = IncentiveParams(
            cbi_usd_p_w=z2, cbi_max_usd=z2, ibi_frac=z2, ibi_max_usd=z2,
            pbi_usd_p_kwh=z2, pbi_years=jnp.zeros((n_pad, 2), dtype=jnp.int32),
            pbi_decay=z2,
        )
    else:
        def pad2(a, dtype):
            out = np.zeros((n_pad, 2), dtype=dtype)
            if a is not None:
                out[:n] = np.asarray(a)
            return jnp.asarray(out)

        incentives = IncentiveParams(
            cbi_usd_p_w=pad2(incentives.cbi_usd_p_w, np.float32),
            cbi_max_usd=pad2(incentives.cbi_max_usd, np.float32),
            ibi_frac=pad2(incentives.ibi_frac, np.float32),
            ibi_max_usd=pad2(incentives.ibi_max_usd, np.float32),
            pbi_usd_p_kwh=pad2(incentives.pbi_usd_p_kwh, np.float32),
            pbi_years=pad2(incentives.pbi_years, np.int32),
            pbi_decay=pad2(incentives.pbi_decay, np.float32),
        )

    if tariff_switch_idx is None:
        tariff_switch_idx = np.asarray(tariff_idx)
    if one_time_charge is None:
        one_time_charge = np.zeros(n, dtype=np.float32)
    # NEM defaults: unlimited NEM, window always open — the behavior of
    # populations with no compiled NEM policy data
    if nem_kw_limit is None:
        nem_kw_limit = np.full(n, 1e30, dtype=np.float32)
    if nem_first_year is None:
        nem_first_year = np.zeros(n, dtype=np.float32)
    if nem_sunset_year is None:
        nem_sunset_year = np.full(n, 9999.0, dtype=np.float32)
    # switch-window defaults: agents WITH a distinct DG rate switch at
    # any size (the pre-size-conditioning behavior); agents without one
    # never enter the window
    has_switch = np.asarray(tariff_switch_idx) != np.asarray(tariff_idx)
    if switch_min_kw is None:
        switch_min_kw = np.where(has_switch, 0.0, 1e30).astype(np.float32)
    if switch_max_kw is None:
        switch_max_kw = np.full(n, 1e30, dtype=np.float32)

    return AgentTable(
        agent_id=pad_i(np.arange(n) if agent_id is None else agent_id),
        mask=jnp.asarray(mask),
        state_idx=pad_i(state_idx),
        sector_idx=pad_i(sector_idx),
        group_idx=pad_i(group),
        region_idx=pad_i(region_idx),
        tariff_idx=pad_i(tariff_idx),
        tariff_switch_idx=pad_i(tariff_switch_idx),
        load_idx=pad_i(load_idx),
        cf_idx=pad_i(cf_idx),
        customers_in_bin=pad_f(customers_in_bin),
        load_kwh_per_customer_in_bin=pad_f(load_kwh_per_customer_in_bin),
        developable_frac=pad_f(developable_frac),
        one_time_charge=pad_f(one_time_charge),
        nem_kw_limit=pad_f(nem_kw_limit, fill=1e30),
        nem_first_year=pad_f(nem_first_year),
        nem_sunset_year=pad_f(nem_sunset_year, fill=9999.0),
        switch_min_kw=pad_f(switch_min_kw, fill=1e30),
        switch_max_kw=pad_f(switch_max_kw, fill=1e30),
        incentives=incentives,
        n_states=n_states,
    )
