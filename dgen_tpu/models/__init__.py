"""Model layer: the columnar agent table, scenario inputs, the market
(diffusion/attachment) step, the multi-year driver, and the
national-scale synthetic table generator (``models.synth``)."""

from dgen_tpu.models import agents, market, scenario, simulation  # noqa: F401
