"""Model layer: the columnar agent table, scenario inputs, the market
(diffusion/attachment) step, and the multi-year driver."""

from dgen_tpu.models import agents, market, scenario, simulation  # noqa: F401
