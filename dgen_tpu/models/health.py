"""The always-on numerical-health sentinel: cheap fused on-device
reductions over each year's outputs, checked on the host-IO path.

``RunConfig.debug_invariants`` already catches nonfinite state — but it
forces a per-year host sync, so nobody runs it in production, which is
exactly when silent data corruption (a flipped HBM bank row, a bad
ingest batch that escaped validation) strikes.  The sentinel closes
that gap the way extreme-scale ABM platforms do (per-step sanity
monitors as a prerequisite for trusting scaled runs):

* :func:`health_summary` — ONE small jitted program per year computing,
  for each monitored ``YearOutputs`` leaf, the nonfinite count and the
  gross bound-breach count (bills/NPV/market-share per leaf).  The
  result is a [C, 2] int32 array — a few hundred bytes that ride the
  existing batched host fetch (``io.hostio.HealthConsumer``), so the
  async pipeline's overlap is untouched (unlike ``debug_invariants``).
* :func:`check_host` — the host-side verdict over the fetched summary.
* :func:`breach_error` — per-agent attribution: the breached
  *per-agent* leaves (sizing outputs are pure functions of one agent's
  own data, so their bad rows are root causes, not group-level smear)
  are fetched and scanned for offending rows, producing a
  :class:`HealthBreachError` that names the year, the leaves, and the
  offending agent ids — the supervisor's quarantine escalation
  consumes exactly those ids (``RunConfig.quarantine_ids``).

Bounds are deliberately loose (orders of magnitude beyond any
reachable value): the sentinel exists to catch poison — NaN/inf and
1e30-style garbage — not to police modeling choices.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: (YearOutputs leaf, lower, upper): nonfinite always counts; finite
#: values outside [lower, upper] count as bound breaches.
HEALTH_CHECKS: Tuple[Tuple[str, float, float], ...] = (
    ("npv", -1e14, 1e14),
    ("payback_period", -1e-3, 1e3),
    ("system_kw", -1e-3, 1e9),
    ("batt_kw", -1e-3, 1e9),
    ("batt_kwh", -1e-3, 1e10),
    ("first_year_bill_with_system", -1e12, 1e12),
    ("first_year_bill_without_system", -1e12, 1e12),
    ("cash_flow", -1e14, 1e14),
    ("max_market_share", -1e-3, 10.0),
    ("market_share", -1e-3, 10.0),
    ("number_of_adopters", -1e-3, 1e12),
    ("system_kw_cum", -1e-3, 1e13),
)

#: leaves whose values are per-agent pure functions of that agent's own
#: inputs (the sizing/bill engine) — a bad row there is a ROOT CAUSE.
#: Market-step leaves mix agents through group aggregates, so their
#: breaches smear across the group and are only used for attribution
#: when no per-agent leaf breached.
ATTRIBUTION_LEAVES = frozenset((
    "npv", "payback_period", "system_kw", "batt_kw", "batt_kwh",
    "first_year_bill_with_system", "first_year_bill_without_system",
    "cash_flow",
))

#: attribution cap: more offending rows than this and the report is
#: truncated (the error says so) — quarantining cannot outrun a
#: wholesale-corrupt input, and validation owns that case
MAX_ATTRIBUTED = 4096


class HealthBreachError(RuntimeError):
    """A sentinel breach: nonfinite or out-of-bounds values in a model
    year's outputs.  ``agent_ids`` (when attribution succeeded) are the
    offending agents' stable ids — the supervisor quarantines exactly
    these and re-runs the year from the last checkpoint."""

    def __init__(
        self,
        year: int,
        year_idx: int,
        breaches: List[dict],
        agent_rows: Sequence[int] = (),
        agent_ids: Sequence[int] = (),
        truncated: bool = False,
    ) -> None:
        leaves = ", ".join(
            f"{b['leaf']} (nonfinite={b['nonfinite']}, "
            f"out_of_bounds={b['out_of_bounds']})"
            for b in breaches
        )
        ids = list(agent_ids)
        super().__init__(
            f"numerical-health breach at year {year}: {leaves}"
            + (
                f"; attributed to {len(ids)} agent(s) "
                f"{ids[:8]}{'...' if len(ids) > 8 else ''}"
                + (" [truncated]" if truncated else "")
                if ids else "; unattributed"
            )
        )
        self.year = int(year)
        self.year_idx = int(year_idx)
        self.breaches = list(breaches)
        self.agent_rows = tuple(int(r) for r in agent_rows)
        self.agent_ids = tuple(int(a) for a in ids)
        self.truncated = bool(truncated)


# ---------------------------------------------------------------------------
# The on-device summary
# ---------------------------------------------------------------------------

@jax.jit
def _summary_impl(leaves: Dict[str, jax.Array],
                  mask: jax.Array) -> jax.Array:
    """[C, 2] int32: per HEALTH_CHECKS row, (nonfinite count,
    finite-but-out-of-bounds count) over MASKED-IN agents — padding
    rows are inert by construction but not semantically meaningful, so
    they never count.  One fused reduction program — compiled once per
    output shape, dispatched right behind the year step so the tiny
    result rides the year's batched host fetch."""
    keep = mask > 0
    rows = []
    for name, lo, hi in HEALTH_CHECKS:
        x = leaves[name]
        k = keep if x.ndim == 1 else keep[:, None]
        finite = jnp.isfinite(x)
        nonf = jnp.sum(
            (~finite & k).astype(jnp.int32), dtype=jnp.int32)
        oob = jnp.sum(
            (finite & ((x < lo) | (x > hi)) & k).astype(jnp.int32),
            dtype=jnp.int32,
        )
        rows.append(jnp.stack([nonf, oob]))
    return jnp.stack(rows)


def health_summary(outs, mask: jax.Array) -> jax.Array:
    """Dispatch the fused health reductions over one year's outputs
    (``mask``: the agent table's [N] real-row mask)."""
    return _summary_impl(
        {name: getattr(outs, name) for name, _, _ in HEALTH_CHECKS},
        mask,
    )


def check_host(summary) -> List[dict]:
    """Host verdict over a fetched summary: the breached checks as
    ``[{"leaf", "nonfinite", "out_of_bounds"}, ...]`` (empty = clean)."""
    s = np.asarray(summary)
    out = []
    for (name, _, _), (nonf, oob) in zip(HEALTH_CHECKS, s):
        if nonf or oob:
            out.append({
                "leaf": name,
                "nonfinite": int(nonf),
                "out_of_bounds": int(oob),
            })
    return out


# ---------------------------------------------------------------------------
# Attribution
# ---------------------------------------------------------------------------

def _host_leaf(arr):
    """(values, global_row_idx) of the process-locally addressable part
    of a per-agent leaf; idx None = every row is local (the
    single-controller case)."""
    if getattr(arr, "is_fully_addressable", True) is not False:
        return np.asarray(jax.device_get(arr)), None
    rows, idx = [], []
    seen = set()
    for s in arr.addressable_shards:
        sl = s.index[0] if s.index else slice(None)
        start = sl.start or 0
        if start in seen:
            continue
        seen.add(start)
        stop = sl.stop if sl.stop is not None else arr.shape[0]
        rows.append(np.asarray(s.data))
        idx.append(np.arange(start, stop))
    return np.concatenate(rows), np.concatenate(idx)


def _leaf_of(outs, name):
    """A leaf by name from either a YearOutputs-shaped object or a
    ``{name: device array}`` ref dict (the async HealthConsumer stashes
    only the attribution leaves, not the full outputs); None = absent."""
    if isinstance(outs, dict):
        return outs.get(name)
    return getattr(outs, name, None)


def attribute(outs, breaches: List[dict], mask_host: np.ndarray
              ) -> Tuple[np.ndarray, bool]:
    """Offending agent rows (global row indices, sorted) for a breach:
    the union of bad MASKED-IN rows across the breached per-agent
    leaves (ATTRIBUTION_LEAVES), falling back to every breached leaf
    when no per-agent leaf breached.  Returns ``(rows, truncated)``."""
    names = [b["leaf"] for b in breaches
             if b["leaf"] in ATTRIBUTION_LEAVES]
    if not names:
        names = [b["leaf"] for b in breaches]
    bounds = {name: (lo, hi) for name, lo, hi in HEALTH_CHECKS}
    keep = np.asarray(mask_host) > 0
    bad_rows: set = set()
    for name in names:
        lo, hi = bounds[name]
        leaf = _leaf_of(outs, name)
        if leaf is None:
            continue
        vals, idx = _host_leaf(leaf)
        flat = vals.reshape(vals.shape[0], -1)
        finite = np.isfinite(flat)
        bad = (~finite) | (
            finite & ((flat < lo) | (flat > hi))
        )
        local = np.flatnonzero(bad.any(axis=1))
        if idx is not None:
            local = idx[local]
        local = local[keep[local]]
        bad_rows.update(int(r) for r in local)
    rows = np.asarray(sorted(bad_rows), dtype=np.int64)
    truncated = rows.size > MAX_ATTRIBUTED
    return rows[:MAX_ATTRIBUTED], truncated


def breach_error(year, year_idx, breaches: List[dict], outs,
                 agent_ids_host: np.ndarray,
                 mask_host: np.ndarray) -> HealthBreachError:
    """Build the attributed :class:`HealthBreachError` for a breached
    year: per-chunk/per-leaf narrowing to offending rows, then row ->
    stable agent id via the host id copy (placed row order)."""
    try:
        if outs is None:
            rows, truncated = np.asarray([], dtype=np.int64), False
        else:
            rows, truncated = attribute(outs, breaches, mask_host)
    except Exception:  # noqa: BLE001 — attribution is best-effort
        rows, truncated = np.asarray([], dtype=np.int64), False
    ids = (
        np.asarray(agent_ids_host)[rows] if rows.size else
        np.asarray([], dtype=np.int64)
    )
    return HealthBreachError(
        year, year_idx, breaches,
        agent_rows=rows.tolist(), agent_ids=ids.tolist(),
        truncated=truncated,
    )
