"""Scenario inputs: every year-dependent trajectory and market parameter
as small dense arrays, gathered per agent per year.

Replaces the reference's per-year pandas merges (the 13 ``on_frame``
mutations at dgen_model.py:252-292 backed by agent_mutation/elec.py) and
the Excel-workbook -> Postgres input plumbing (SURVEY.md §2.5). A
trajectory keyed (year, sector) in the reference becomes a
``[n_years, n_sectors]`` array here; applying it to agents is one gather
on ``(year_idx, sector_idx)``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import (
    BASS_DEFAULTS,
    PAYBACK_GRID_N,
    SECTORS,
    ScenarioConfig,
)
from dgen_tpu.models.agents import AgentTable
from dgen_tpu.ops.cashflow import FinanceParams, MACRS_5


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ScenarioInputs:
    """All year-dependent model inputs. Axes: Y = model years,
    S = sectors (res/com/ind), G = state x sector groups, R = regions
    (census divisions / balancing areas), K = anchor years.
    """

    # --- technology & price trajectories (reference input_data/*) ---
    pv_capex_per_kw: jax.Array            # [Y, S] (pv_prices)
    pv_om_per_kw: jax.Array               # [Y, S]
    pv_degradation: jax.Array             # [Y, S] (pv_tech_performance)
    batt_capex_per_kwh: jax.Array         # [Y, S] (batt_prices)
    batt_capex_per_kw: jax.Array          # [Y, S]
    #: [Y, S] battery round-trip efficiency + lifetime trajectories
    #: (batt_tech_performance; reference apply_batt_tech_performance,
    #: elec.py:319). Lifetime is carried for parity but feeds no cost:
    #: the reference zeroes battery replacement in the hot loop
    #: (financial_functions.py:126,207 om_batt_replacement_cost=[0]).
    batt_eff: jax.Array
    batt_lifetime_yrs: jax.Array
    pv_capex_per_kw_combined: jax.Array   # [Y, S] (pv_plus_batt_prices)
    batt_capex_per_kwh_combined: jax.Array  # [Y, S]
    load_growth: jax.Array                # [Y, R, S] multiplier vs base year
    elec_price_multiplier: jax.Array      # [Y, R, S] retail price vs base year
    elec_price_escalator: jax.Array       # [Y, R, S] forward CAGR (clipped ±1%/yr)
    #: [Y, R] wholesale price trajectory relative to the base-year
    #: profile bank (the reference merges wholesale $/kWh per YEAR,
    #: apply_wholesale_elec_prices elec.py:608; the hourly shape lives
    #: in ProfileBank.wholesale, this scales it per model year)
    wholesale_multiplier: jax.Array
    # --- financing (financing_terms + itc schedule) ---
    loan_term_yrs: jax.Array              # [Y, S] int32
    loan_interest_rate: jax.Array         # [Y, S]
    down_payment_fraction: jax.Array      # [Y, S]
    real_discount_rate: jax.Array         # [Y, S]
    tax_rate: jax.Array                   # [Y, S]
    itc_fraction: jax.Array               # [Y, S]
    #: [Y, S, D] depreciation schedule fractions (depreciation_schedules
    #: CSVs; reference apply_depreciation_schedule, elec.py:157)
    deprec_sch: jax.Array
    # --- market ---
    bass_p: jax.Array                     # [G]
    bass_q: jax.Array                     # [G]
    teq_yr1: jax.Array                    # [G]
    mms_table: jax.Array                  # [S, PAYBACK_GRID_N]
    attachment_rate: jax.Array            # [G] storage attachment in [0,1]
    starting_kw: jax.Array                # [G] base-year installed PV kW
    starting_batt_kw: jax.Array           # [G]
    starting_batt_kwh: jax.Array          # [G]
    # --- historical anchoring (diffusion_functions_elec.py:99) ---
    anchor_years_mask: jax.Array          # [Y] 1.0 where year is an anchor year
    observed_kw: jax.Array                # [Y, G] observed cumulative PV kW
    # --- NEM policy state machine (agent_mutation/elec.py:449-505) ---
    #: [Y, n_states] installed-PV-kW cap under which net metering remains
    #: available; 0 encodes a sunset year (NEM off), 1e30 = no cap. The
    #: gate compares against the *previous* year's state cumulative
    #: capacity (reference calc_state_capacity_by_year, elec.py:788).
    nem_cap_kw: jax.Array
    #: [Y] calendar model years (f32), for the per-agent NEM
    #: availability-window gate (reference filter_nem_year, elec.py:449)
    years: jax.Array
    # --- misc ---
    #: [Y, G] $ per agent (reference merges VOR per state x sector,
    #: apply_value_of_resiliency elec.py:287; the shipped vor_FY20 CSV
    #: keys on state_abbr + sector_abbr)
    value_of_resiliency: jax.Array
    cap_cost_multiplier: jax.Array        # [Y, S]
    #: [Y, n_states] grid carbon intensity tCO2/kWh (reference
    #: apply_carbon_intensities, elec.py:595) — an output passthrough
    #: for avoided-emissions accounting
    carbon_intensity_t_per_kwh: jax.Array
    inflation: jax.Array                  # scalar

    @property
    def n_years(self) -> int:
        return self.pv_capex_per_kw.shape[0]


class ScenarioStackError(ValueError):
    """Scenarios cannot share one device program: a static field (a
    leaf's shape or dtype) differs between members. The message names
    the offending field."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ScenarioStack:
    """S :class:`ScenarioInputs` stacked along a leading scenario axis.

    ``inputs`` holds the same pytree structure as one scenario but with
    every leaf shaped ``[S, ...]`` — scenarios differ only in these
    small trajectory arrays, never in the multi-GB profile banks, so a
    whole policy sweep adds O(S x Y x G) bytes to a run, not O(S x
    N x 8760). Built with :func:`stack_scenarios`, which validates that
    the static configuration (every leaf's shape and dtype — year grid,
    group/region/state counts) agrees across members.
    """

    inputs: ScenarioInputs   # every leaf [S, ...]

    @property
    def n_scenarios(self) -> int:
        return self.inputs.pv_capex_per_kw.shape[0]

    @property
    def n_years(self) -> int:
        return self.inputs.pv_capex_per_kw.shape[1]

    def scenario(self, i: int) -> ScenarioInputs:
        """Unstack member ``i`` (host-side convenience; the sweep
        engine slices on device instead)."""
        return jax.tree.map(lambda leaf: leaf[i], self.inputs)


def validate_scenario_statics(members: Sequence[ScenarioInputs]) -> None:
    """Check that S scenarios share one static configuration: every
    leaf must agree in shape and dtype across members (scenarios in a
    stack share a compiled program, so the year grid and the
    group/region/state axis sizes must match exactly). Raises
    :class:`ScenarioStackError` naming the offending field. Shared by
    :func:`stack_scenarios` and the sweep planner
    (dgen_tpu.sweep.plan)."""
    members = list(members)
    if not members:
        raise ScenarioStackError("cannot stack zero scenarios")
    ref = members[0]
    for f in dataclasses.fields(ScenarioInputs):
        ref_leaf = jnp.asarray(getattr(ref, f.name))
        for i, m in enumerate(members[1:], start=1):
            leaf = jnp.asarray(getattr(m, f.name))
            if leaf.shape != ref_leaf.shape:
                raise ScenarioStackError(
                    f"scenario {i} field '{f.name}' has shape "
                    f"{leaf.shape} but scenario 0 has {ref_leaf.shape}; "
                    "scenarios in one stack must share the static grid "
                    "(years / groups / regions / states)"
                )
            if leaf.dtype != ref_leaf.dtype:
                raise ScenarioStackError(
                    f"scenario {i} field '{f.name}' has dtype "
                    f"{leaf.dtype} but scenario 0 has {ref_leaf.dtype}"
                )


def stack_scenarios(members: Sequence[ScenarioInputs]) -> ScenarioStack:
    """Stack S scenarios into one :class:`ScenarioStack` (static
    configuration validated by :func:`validate_scenario_statics`; a
    mismatch raises :class:`ScenarioStackError` naming the field)."""
    members = list(members)
    validate_scenario_statics(members)
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *members)
    return ScenarioStack(inputs=stacked)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class YearAgentInputs:
    """Per-agent values for ONE model year (the result of applying all
    trajectories — the dense analogue of the reference's 13 on_frame
    mutations for the year)."""

    load_kwh_per_customer: jax.Array
    customers_in_bin: jax.Array
    developable_agent_weight: jax.Array
    elec_price_multiplier: jax.Array
    elec_price_escalator: jax.Array
    pv_degradation: jax.Array
    batt_rt_eff: jax.Array
    wholesale_multiplier: jax.Array
    system_capex_per_kw: jax.Array
    system_capex_per_kw_combined: jax.Array
    batt_capex_per_kwh_combined: jax.Array
    cap_cost_multiplier: jax.Array
    value_of_resiliency: jax.Array
    fin: FinanceParams


def apply_year(
    table: AgentTable, inputs: ScenarioInputs, year_idx: jax.Array
) -> YearAgentInputs:
    """Gather all year-y trajectory values onto the agent axis.

    Load growth follows the reference's sector split
    (agent_mutation/elec.py:396-406): residential growth scales kWh per
    customer; commercial/industrial growth scales customer count.
    """
    s = table.sector_idx
    r = table.region_idx
    g = table.group_idx

    growth = inputs.load_growth[year_idx, r, s]
    is_res = (s == 0).astype(jnp.float32)
    load_kwh = table.load_kwh_per_customer_in_bin * jnp.where(is_res > 0, growth, 1.0)
    customers = table.customers_in_bin * jnp.where(is_res > 0, 1.0, growth)

    fin = FinanceParams(
        down_payment_fraction=inputs.down_payment_fraction[year_idx, s],
        loan_interest_rate=inputs.loan_interest_rate[year_idx, s],
        loan_term_yrs=inputs.loan_term_yrs[year_idx, s],
        real_discount_rate=inputs.real_discount_rate[year_idx, s],
        inflation_rate=jnp.broadcast_to(inputs.inflation, s.shape),
        tax_rate=inputs.tax_rate[year_idx, s],
        itc_fraction=inputs.itc_fraction[year_idx, s],
        is_commercial=(s != 0).astype(jnp.float32),
        om_per_year=jnp.zeros_like(load_kwh),  # reference zeroes O&M in the hot loop
        deprec_sch=inputs.deprec_sch[year_idx, s],
    )

    return YearAgentInputs(
        load_kwh_per_customer=load_kwh,
        customers_in_bin=customers,
        developable_agent_weight=table.developable_agent_weight(customers),
        elec_price_multiplier=inputs.elec_price_multiplier[year_idx, r, s],
        elec_price_escalator=inputs.elec_price_escalator[year_idx, r, s],
        pv_degradation=inputs.pv_degradation[year_idx, s],
        batt_rt_eff=inputs.batt_eff[year_idx, s],
        wholesale_multiplier=inputs.wholesale_multiplier[year_idx, r],
        system_capex_per_kw=inputs.pv_capex_per_kw[year_idx, s],
        system_capex_per_kw_combined=inputs.pv_capex_per_kw_combined[year_idx, s],
        batt_capex_per_kwh_combined=inputs.batt_capex_per_kwh_combined[year_idx, s],
        cap_cost_multiplier=inputs.cap_cost_multiplier[year_idx, s],
        value_of_resiliency=inputs.value_of_resiliency[year_idx, g],
        fin=fin,
    )


def federal_itc_schedule(years: Sequence[int]) -> np.ndarray:
    """[Y, 3] statutory federal ITC fractions for host-owned systems.

    The reference reads ITC options from its scenario workbook
    (``itc_options`` merged at agent_mutation/elec.py:348
    ``apply_financial_params``); absent a workbook this is the
    residential/commercial statute the workbook encodes: 30% through
    2019, 26% 2020-21, 30% 2022-2032 (IRA), 26% 2033, 22% 2034, then
    0% res / 10% com+ind.
    """
    out = np.zeros((len(years), len(SECTORS)), dtype=np.float32)
    for i, y in enumerate(years):
        if y <= 2019:
            frac = (0.30, 0.30, 0.30)
        elif y <= 2021:
            frac = (0.26, 0.26, 0.26)
        elif y <= 2032:
            frac = (0.30, 0.30, 0.30)
        elif y == 2033:
            frac = (0.26, 0.26, 0.26)
        elif y == 2034:
            frac = (0.22, 0.22, 0.22)
        else:
            frac = (0.0, 0.10, 0.10)
        out[i] = frac
    return out


def escalator_from_multipliers(mult: np.ndarray, years: np.ndarray,
                               year_cap: int = 2040,
                               clip: float = 0.01) -> np.ndarray:
    """Price escalator per model year, reference semantics
    (agent_mutation/elec.py:63-79): the escalator for model year ``y``
    is the CAGR of the multiplier from ``min(y, 2040)`` to the
    trajectory's FINAL year, clipped to ±1%/yr.

    ``mult``: [Y, ...] multiplier trajectory on the model-year grid
    (the reference evaluates against its full 2050 trajectory; here the
    grid is whatever the scenario covers).
    """
    years = np.asarray(years)
    out = np.zeros_like(mult)
    final_idx = len(years) - 1
    for i, y in enumerate(years):
        yc = min(int(y), year_cap)
        j = max(0, int(np.searchsorted(years, yc, side="right")) - 1)
        span = max(float(years[final_idx] - years[j]), 1.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            cagr = (
                mult[final_idx] / np.maximum(mult[j], 1e-9)
            ) ** (1.0 / span) - 1.0
        out[i] = np.clip(np.nan_to_num(cagr), -clip, clip)
    return out


def uniform_inputs(
    config: ScenarioConfig,
    n_groups: int,
    n_regions: int,
    overrides: Dict[str, object] | None = None,
    n_states: int | None = None,
) -> ScenarioInputs:
    """Build flat/constant scenario inputs (testing + synthetic runs).

    Values default to the reference's shipped mid-case trajectories'
    rough magnitudes; every field can be overridden. ``n_states``
    defaults to ``n_groups // len(SECTORS)`` (the AgentTable group
    layout); pass it explicitly for populations that deviate.
    """
    years = np.asarray(config.model_years)
    Y, S, G, R = len(years), len(config.sectors), n_groups, n_regions
    n_st = n_states if n_states is not None else max(G // len(SECTORS), 1)
    f = np.float32

    def yz(v):
        return jnp.full((Y, S), v, dtype=f)

    # simple declining capex trajectory (ATB-like shape)
    decline = np.linspace(1.0, 0.45, Y, dtype=f)[:, None]
    pv_capex = jnp.asarray(3000.0 * decline * np.ones((1, S), f))
    batt_capex_kwh = jnp.asarray(900.0 * decline * np.ones((1, S), f))

    # Max-market-share curve: smooth decay in payback (res faster than
    # com/ind), tabulated on the 0.1yr grid — same shape family as the
    # reference's NEMS-derived curves.
    pb = np.arange(PAYBACK_GRID_N, dtype=f) * 0.1
    curves = []
    for s_i in range(S):
        halflife = 4.0 if s_i == 0 else 6.0
        curves.append(np.exp(-pb / halflife))
    mms_np = np.stack(curves)
    # the 30.1 never-payback sentinel is exactly 0 — the reference UNION
    # ALLs a 0-share row at metric_value=30.1 (data_functions.py:399-410)
    # so agents whose cashflow never pays back cannot adopt
    mms_np[:, -1] = 0.0
    mms = jnp.asarray(mms_np)

    anchor_mask = np.isin(years, np.asarray(config.anchor_years)).astype(f)

    vals = dict(
        pv_capex_per_kw=pv_capex,
        pv_om_per_kw=yz(15.0),
        pv_degradation=yz(0.005),
        batt_capex_per_kwh=batt_capex_kwh,
        batt_capex_per_kw=yz(1000.0),
        batt_eff=yz(0.9216),
        batt_lifetime_yrs=yz(10.0),
        pv_capex_per_kw_combined=pv_capex * 1.05,
        batt_capex_per_kwh_combined=batt_capex_kwh * 0.95,
        load_growth=jnp.ones((Y, R, S), dtype=f),
        elec_price_multiplier=jnp.ones((Y, R, S), dtype=f),
        elec_price_escalator=jnp.zeros((Y, R, S), dtype=f),
        wholesale_multiplier=jnp.ones((Y, R), dtype=f),
        loan_term_yrs=jnp.full((Y, S), 20, dtype=jnp.int32),
        loan_interest_rate=yz(0.05),
        down_payment_fraction=yz(1.0),
        real_discount_rate=yz(0.027),
        tax_rate=yz(0.257),
        itc_fraction=yz(0.30),
        deprec_sch=jnp.broadcast_to(
            jnp.asarray(MACRS_5), (Y, S, MACRS_5.shape[0])
        ),
        bass_p=jnp.full(G, BASS_DEFAULTS[0], dtype=f),
        bass_q=jnp.full(G, BASS_DEFAULTS[1], dtype=f),
        teq_yr1=jnp.full(G, BASS_DEFAULTS[2], dtype=f),
        mms_table=mms,
        attachment_rate=jnp.zeros(G, dtype=f),
        starting_kw=jnp.zeros(G, dtype=f),
        starting_batt_kw=jnp.zeros(G, dtype=f),
        starting_batt_kwh=jnp.zeros(G, dtype=f),
        anchor_years_mask=jnp.asarray(anchor_mask),
        observed_kw=jnp.zeros((Y, G), dtype=f),
        nem_cap_kw=jnp.full((Y, n_st), 1e30, dtype=f),
        years=jnp.asarray(years.astype(f)),
        value_of_resiliency=jnp.zeros((Y, G), dtype=f),
        cap_cost_multiplier=yz(1.0),
        carbon_intensity_t_per_kwh=jnp.zeros((Y, n_st), dtype=f),
        inflation=jnp.asarray(config.annual_inflation, dtype=f),
    )
    if overrides:
        vals.update(overrides)
    return ScenarioInputs(**vals)
