"""dgen-tpu: TPU-native agent-based market-adoption framework.

A ground-up JAX/XLA re-design of the capabilities of NREL dGen
(reference: tsgsteele/dgen, see SURVEY.md): annual simulation of
rooftop-solar + behind-the-meter storage adoption by customer agents.

Architecture (TPU-first, not a port):
  - The agent population is a columnar pytree of dense arrays resident in
    HBM (``dgen_tpu.models.agents.AgentTable``), not a pandas DataFrame.
  - The per-agent economics hot loop (utility-bill engine, battery
    dispatch, multi-year cashflow, NPV-optimal sizing search) — which the
    reference runs one agent at a time through PySAM/SSC C++ modules
    (reference financial_functions.py:96-568) — is a set of fused,
    ``jax.vmap``-ed kernels in ``dgen_tpu.ops``.
  - The market step (Bass diffusion, max-market-share, storage
    attachment) is vectorized with segment reductions in
    ``dgen_tpu.models.market``.
  - Scale-out is ``jax.sharding.Mesh`` + ``shard_map`` over the agent
    axis (``dgen_tpu.parallel``), replacing the reference's
    one-GCP-Batch-task-per-state sharding (submit_all.sh).
  - Host I/O (ingest, profile store, checkpoints) stays off the device
    path in ``dgen_tpu.io``, replacing the reference's per-agent Postgres
    round trips (agent_mutation/elec.py:508-558).
  - National-scale populations stream through the year step in fixed
    agent chunks (``RunConfig.agent_chunk`` — a ``lax.scan`` that bounds
    peak HBM to one chunk), and post-run analyses the adoption loop
    skips (demand charges) live in ``dgen_tpu.analysis``.
"""

__version__ = "0.1.0"

from dgen_tpu import (  # noqa: F401
    analysis,
    config,
    io,
    models,
    ops,
    parallel,
    sweep,
    utils,
)
