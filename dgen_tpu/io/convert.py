"""Convert the reference's agent population into an agent package.

The reference distributes its population as a pandas pickle whose rows
carry object cells — a ``tariff_dict`` per agent, profile keys that
resolve through per-agent Postgres SQL (reference
input_data_functions.py:389 ``import_agent_file``,
agent_mutation/elec.py:508-558) — none of which can live on a TPU
device path. This module runs ONCE, offline, and compiles that pickle
into the dense package format of :mod:`dgen_tpu.io.package`:

  * raw/stringified ``tariff_dict`` cells are parsed, deduplicated and
    compiled into a TariffBank spec list
    (semantics: financial_functions.py:655 ``_parse_tariff_dict`` and
    :962 ``normalize_tariff``);
  * known-bad tariff ids are reassigned before compilation (the
    converter-time analogue of agent_mutation/elec.py:868
    ``reassign_agent_tariffs``; bad ids at :993);
  * per-agent profile keys — (bldg_id, sector_abbr, state_abbr) for
    load, (solar_re_9809_gid, tilt, azimuth) for solar CF — are
    resolved against profile tables, deduplicated into shared banks and
    replaced by integer bank indices;
  * the optional state-incentive table is compiled to top-2 fixed-width
    slots per agent (financial_functions.py:1014 ``process_incentives``
    consumes exactly two CBI/PBI/IBI rows).

The output directory round-trips through
:func:`dgen_tpu.io.package.load_population` into the pytrees the
Simulation consumes.
"""

from __future__ import annotations

import ast
import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd

from dgen_tpu.config import SECTORS
from dgen_tpu.io import package
from dgen_tpu.io.reference_inputs import CENSUS_DIVISIONS
from dgen_tpu.models.agents import build_agent_table, ProfileBank
from dgen_tpu.ops.cashflow import IncentiveParams
from dgen_tpu.ops.tariff import (
    BIG_CAP, NET_BILLING, NET_METERING, compile_tariffs,
)
from dgen_tpu.utils.timing import fn_timer

#: tariff ids the reference replaces wholesale (agent_mutation/elec.py:993)
BAD_TARIFF_IDS = (4145, 7111, 8498, 10953, 10954, 12003)

HOURS = 8760


# ---------------------------------------------------------------------------
# tariff_dict parsing + conversion
# ---------------------------------------------------------------------------

def parse_tariff_dict(raw: Any) -> Dict[str, Any]:
    """Coerce a pickle cell into a tariff dict.

    The reference tolerates dicts, JSON-ish strings and Python-literal
    strings with embedded nan/none (financial_functions.py:655
    ``_parse_tariff_dict``); the converter must accept the same inputs
    since pickles in the wild carry all three.
    """
    if isinstance(raw, dict):
        return raw
    if not isinstance(raw, str):
        return {}
    s = raw.replace("'", '"')
    s = re.sub(r"\b(nan|none|null)\b", "null", s, flags=re.IGNORECASE)
    try:
        return json.loads(s)
    except json.JSONDecodeError:
        try:
            out = ast.literal_eval(raw)
            return out if isinstance(out, dict) else {}
        except Exception:
            return {}


def _metering_code(td: Dict[str, Any]) -> int:
    """Reference metering codes 0=NM / 1,2=net-billing-style -> bank codes.

    The reference forces net billing globally (FORCE_NET_BILLING,
    financial_functions.py:37,590); the converter preserves the raw
    option and leaves forcing to the scenario config, which owns that
    policy switch in this framework.
    """
    mo = int(td.get("ur_metering_option", 0) or 0)
    return NET_METERING if mo == 0 else NET_BILLING


def reference_tariff_to_spec(td: Dict[str, Any]) -> Dict[str, Any]:
    """One reference ``tariff_dict`` -> one compiler spec dict.

    Handles both shapes found in agent pickles: the legacy URDB-style
    e_* fields ([T][P] prices/levels + 0-based 12x24 schedules) and the
    already-normalized PySAM fields (``ur_ec_tou_mat`` rows
    [period(1..P), tier(1..T), max_usage, unit, price, sell] with
    1-based 12x24 schedules) — the same two shapes
    financial_functions.py:962 ``normalize_tariff`` accepts. Demand
    charges are excluded from the ENERGY spec, matching the reference's
    global SKIP_DEMAND_CHARGES=True (financial_functions.py:35); they
    are preserved separately by
    :func:`reference_tariff_to_demand_spec` for analysis runs.
    """
    spec: Dict[str, Any] = {
        "fixed_charge": float(
            td.get("ur_monthly_fixed_charge", td.get("fixed_charge", 0.0))
            or 0.0),
        "metering": _metering_code(td),
    }

    ec_tou = td.get("ur_ec_tou_mat")
    if ec_tou:
        rows = np.asarray(ec_tou, dtype=np.float64)
        periods = rows[:, 0].astype(int)
        tiers = rows[:, 1].astype(int)
        P, T = int(periods.max()), int(tiers.max())
        price = np.zeros((P, T))
        caps = np.full(T, BIG_CAP)
        for r in rows:
            p, t = int(r[0]) - 1, int(r[1]) - 1
            price[p, t] = r[4]
            caps[t] = min(caps[t], r[2]) if r[2] > 0 else caps[t]
        spec["price"] = price.tolist()
        spec["tier_cap"] = caps.tolist()
        # ur schedules are 1-based; the compiler wants 0-based
        for src, dst in (("ur_ec_sched_weekday", "e_wkday_12by24"),
                         ("ur_ec_sched_weekend", "e_wkend_12by24")):
            sched = td.get(src)
            if sched is not None:
                spec[dst] = (np.asarray(sched, dtype=np.int64) - 1).clip(
                    0).tolist()
        return spec

    for key in ("e_prices", "e_levels", "e_wkday_12by24", "e_wkend_12by24"):
        if td.get(key) is not None:
            spec[key] = td[key]
    if "e_prices" not in spec:
        # degenerate/empty dict -> inert flat tariff so compilation
        # never fails on a malformed cell (the reference's parser
        # likewise degrades to {} and PySAM defaults)
        spec["price"] = [[0.1]]
    return spec


def reference_tariff_to_demand_spec(
    td: Dict[str, Any],
) -> Optional[Dict[str, Any]]:
    """Demand-charge fields of one ``tariff_dict`` -> a JSON-able demand
    spec, or None when the tariff has no demand charges.

    The hot loop drops these on purpose (SKIP_DEMAND_CHARGES parity,
    financial_functions.py:35); this hook preserves them for analysis
    runs through :mod:`dgen_tpu.ops.demand`. Both shapes found in agent
    pickles are accepted — legacy ``d_flat_*`` [T][12] / ``d_tou_*``
    [T][P] arrays with 0-based ``d_wkday_12by24`` schedules (the URDB
    repackaging of tariff_functions.py:213-268) and PySAM
    ``ur_dc_flat_mat`` / ``ur_dc_tou_mat`` rows
    [period(1..P), tier(1..T), max_kW, price] with 1-based schedules
    (financial_functions.py:793 ``_build_ur_dc_from_d_parts``).

    Spec keys mirror :func:`dgen_tpu.ops.demand.compile_demand_tariff`
    kwargs plus the two 12x24 window schedules (expanded to the hourly
    map at bank-compile time).
    """
    def dense_from_mat(mat):
        rows = np.asarray(mat, dtype=np.float64)
        if rows.ndim != 2 or rows.shape[1] < 4 or not rows.size:
            return None, None
        # junk guard: every row's period/tier index must be a sane
        # 1-based URDB index — a malformed row (e.g. a max_kW landed in
        # the tier column, or a 0/negative index that would wrap the
        # dense fill below) makes the tariff's demand charges
        # unpriceable rather than silently mis-binned
        pcol, tcol = rows[:, 0], rows[:, 1]
        if not (
            np.all((1 <= pcol) & (pcol <= 64) & (pcol == np.floor(pcol)))
            and np.all((1 <= tcol) & (tcol <= 64) & (tcol == np.floor(tcol)))
        ):
            return None, None
        P = int(pcol.max())
        T = int(tcol.max())
        prices = np.zeros((T, P))
        levels = np.full((T, P), BIG_CAP)
        for r in rows:
            p, t = int(r[0]) - 1, int(r[1]) - 1
            prices[t, p] = r[3]
            if r[2] > 0:
                levels[t, p] = min(levels[t, p], r[2])
        return prices, levels

    def pick(prices_key, levels_key, mat_key):
        pr, lv = td.get(prices_key), td.get(levels_key)
        if pr is not None and np.asarray(pr, np.float64).size:
            pr = np.asarray(pr, np.float64)
            lv = (np.asarray(lv, np.float64) if lv is not None
                  else np.full(pr.shape, BIG_CAP))
        elif td.get(mat_key):
            pr, lv = dense_from_mat(td[mat_key])
        else:
            return None, None
        if pr is None or not np.any(pr > 0):
            return None, None
        return pr.tolist(), lv.tolist()

    out: Dict[str, Any] = {}
    fp, fl = pick("d_flat_prices", "d_flat_levels", "ur_dc_flat_mat")
    if fp is not None:
        out["d_flat_prices"], out["d_flat_levels"] = fp, fl
    tp, tl = pick("d_tou_prices", "d_tou_levels", "ur_dc_tou_mat")
    if tp is not None:
        out["d_tou_prices"], out["d_tou_levels"] = tp, tl
    if not out:
        return None

    if "d_tou_prices" in out:
        wkday = td.get("d_wkday_12by24")
        wkend = td.get("d_wkend_12by24")
        if wkday is None and td.get("ur_dc_sched_weekday") is not None:
            # ur schedules are 1-based (financial_functions.py:823)
            wkday = (np.asarray(td["ur_dc_sched_weekday"], np.int64)
                     - 1).clip(0).tolist()
            # key may be present-but-None (parse_tariff_dict rewrites
            # nan/none to JSON null), so .get's default is not enough
            raw_we = td.get("ur_dc_sched_weekend")
            if raw_we is None:
                raw_we = td["ur_dc_sched_weekday"]
            wkend = (np.asarray(raw_we, np.int64) - 1).clip(0).tolist()
        if wkday is not None:
            out["d_wkday_12by24"] = np.asarray(wkday, np.int64).tolist()
            out["d_wkend_12by24"] = np.asarray(
                wkend if wkend is not None else wkday, np.int64).tolist()
    return out


def _canonical_key(spec: Dict[str, Any]) -> str:
    return json.dumps(spec, sort_keys=True)


# ---------------------------------------------------------------------------
# bad-tariff reassignment
# ---------------------------------------------------------------------------

def reassign_bad_tariffs(
    df: pd.DataFrame,
    bad_ids: Sequence[int] = BAD_TARIFF_IDS,
) -> pd.DataFrame:
    """Replace known-bad tariffs before compilation.

    The reference swaps six corrupt URDB ids for hardcoded per-state
    defaults pulled from its Postgres tariff store
    (agent_mutation/elec.py:868-988). Without that store, the converter
    reassigns each bad-tariff agent to the modal good tariff of its
    (state_abbr, sector_abbr) cell, falling back to the sector's modal
    tariff, then to any good tariff — preserving the invariant the
    reference cares about (no agent sizes against a corrupt rate) with
    a data-driven default.
    """
    bad = df["tariff_id"].isin(list(bad_ids))
    if not bad.any():
        return df
    good = df[~bad]
    if good.empty:
        raise ValueError("every agent has a bad tariff id; cannot reassign")

    df = df.copy()

    # vectorized modal lookup: one groupby per fallback level instead of
    # a per-bad-row scan (national pickles carry ~1e6 rows)
    first_mode = lambda s: s.mode().iloc[0]
    modal_ss = good.groupby(["state_abbr", "sector_abbr"])["tariff_id"] \
        .agg(first_mode)
    modal_s = good.groupby("sector_abbr")["tariff_id"].agg(first_mode)
    modal_any = first_mode(good["tariff_id"])
    # representative dict per good tariff id (ids key a shared tariff
    # table in the reference, so same-id rows carry the same dict)
    rep = good.drop_duplicates("tariff_id").set_index("tariff_id")[
        "tariff_dict"]

    bad_rows = df.loc[bad]
    key = pd.MultiIndex.from_frame(bad_rows[["state_abbr", "sector_abbr"]])
    tid = modal_ss.reindex(key).to_numpy(object)
    fb = modal_s.reindex(bad_rows["sector_abbr"]).to_numpy(object)
    tid = np.where(pd.isna(tid), fb, tid)
    tid = np.where(pd.isna(tid), modal_any, tid)
    df.loc[bad, "tariff_id"] = pd.array(
        tid, dtype=df["tariff_id"].dtype)
    df.loc[bad, "tariff_dict"] = rep.reindex(tid).to_numpy(object)
    return df


# ---------------------------------------------------------------------------
# profile resolution
# ---------------------------------------------------------------------------

def _as_frame(src: Union[str, pd.DataFrame]) -> pd.DataFrame:
    if isinstance(src, pd.DataFrame):
        return src
    if str(src).endswith(".parquet"):
        return pd.read_parquet(src)
    return pd.read_pickle(src)


def _profile_bank(
    df: pd.DataFrame,
    key_cols: Sequence[str],
    value_col: str,
    used_keys: Sequence[Tuple],
    scale: float = 1.0,
    normalize_sum: bool = False,
) -> Tuple[np.ndarray, Dict[Tuple, int]]:
    """Dedup profiles by key into an [n, 8760] bank + key->row map.

    O(rows) dict build from plain tuples — no per-row pandas Series
    (iterrows at national scale was the converter's wall-clock sink).
    """
    lut: Dict[Tuple, int] = {}
    # restrict to the USED keys before touching the value column: each
    # cell is an 8760-element object, and materializing the whole column
    # (a national table carries ~1e5 distinct profiles) costs GBs of
    # Python lists at peak — the key->position map is ints only, and
    # only referenced rows are ever converted (last occurrence wins,
    # matching the former dict(zip(...)) semantics)
    need = set(used_keys)
    row_pos: Dict[Tuple, int] = {}
    for i, k in enumerate(
        df[list(key_cols)].itertuples(index=False, name=None)
    ):
        if k in need:
            row_pos[k] = i
    values = df[value_col]
    rows = []
    for k in used_keys:
        if k in lut:
            continue
        if k not in row_pos:
            raise KeyError(f"profile key {k!r} not found in profile table "
                           f"(keys {list(key_cols)})")
        arr = np.asarray(values.iloc[row_pos[k]], dtype=np.float64).ravel()
        if arr.size != HOURS:
            raise ValueError(f"profile {k!r} has {arr.size} hours != {HOURS}")
        arr = arr * scale
        if normalize_sum:
            s = arr.sum()
            arr = arr / s if s > 0 else np.full(HOURS, 1.0 / HOURS)
        lut[k] = len(rows)
        rows.append(arr.astype(np.float32))
    return np.stack(rows), lut


# ---------------------------------------------------------------------------
# incentives
# ---------------------------------------------------------------------------

def compile_incentives(
    state_incentives: Optional[pd.DataFrame],
    state_abbr: pd.Series,
    sector_abbr: pd.Series,
) -> Optional[IncentiveParams]:
    """Reference state-incentive rows -> top-2 per-agent slots.

    Row schema follows the reference table consumed by
    agent_mutation/elec.py:656 ``apply_state_incentives`` /
    financial_functions.py:1014 ``process_incentives``: state_abbr,
    sector_abbr, cbi_usd_p_w, ibi_pct, pbi_usd_p_kwh,
    max_incentive_usd, incentive_duration_yrs. The reference fills
    missing duration/max with 5 yrs / $10k (:1025).
    """
    if state_incentives is None or state_incentives.empty:
        return None
    si = state_incentives.fillna(
        value={"incentive_duration_yrs": 5.0, "max_incentive_usd": 10000.0})

    # compile top-2 slots once per (state, sector) CELL — at most
    # n_states x 3 of them — then gather per agent, instead of walking
    # the agent axis in Python (the national pickle has ~1e6 rows)
    cells: Dict[Tuple, Dict[str, np.ndarray]] = {}
    for (st, sec), g in si.groupby(["state_abbr", "sector_abbr"]):
        c = {k: np.zeros(2, np.float32)
             for k in ("cbi_usd_p_w", "cbi_max_usd", "ibi_frac",
                       "ibi_max_usd", "pbi_usd_p_kwh")}
        c["pbi_years"] = np.zeros(2, np.int32)
        cbi = g[g.get("cbi_usd_p_w", pd.Series(dtype=float)).notna()] \
            .sort_values("cbi_usd_p_w", ascending=False)
        for s, (_, row) in enumerate(cbi.head(2).iterrows()):
            c["cbi_usd_p_w"][s] = row["cbi_usd_p_w"]
            c["cbi_max_usd"][s] = row["max_incentive_usd"]
        if "ibi_pct" in g:
            ibi = g[g["ibi_pct"].notna()].sort_values(
                "ibi_pct", ascending=False)
            for s, (_, row) in enumerate(ibi.head(2).iterrows()):
                c["ibi_frac"][s] = row["ibi_pct"]
                c["ibi_max_usd"][s] = row["max_incentive_usd"]
        if "pbi_usd_p_kwh" in g:
            pbi = g[g["pbi_usd_p_kwh"].notna()].sort_values(
                "pbi_usd_p_kwh", ascending=False)
            for s, (_, row) in enumerate(pbi.head(2).iterrows()):
                c["pbi_usd_p_kwh"][s] = row["pbi_usd_p_kwh"]
                c["pbi_years"][s] = int(row["incentive_duration_yrs"])
        cells[(st, sec)] = c

    n = len(state_abbr)
    if not cells:
        # rows exist but none form a (state, sector) group (NaN keys are
        # dropped by groupby) — same all-zero result as no matches
        zero = {k: np.zeros((n, 2), np.float32)
                for k in ("cbi_usd_p_w", "cbi_max_usd", "ibi_frac",
                          "ibi_max_usd", "pbi_usd_p_kwh")}
        return IncentiveParams(
            pbi_years=np.zeros((n, 2), np.int32), **zero)

    keys = list(cells)
    cell_idx = {k: i for i, k in enumerate(keys)}
    # stacked [n_cells + 1, 2] tables; the last row is the all-zero
    # no-incentive cell agents without a matching row gather from
    def stacked(name, dtype):
        z = np.zeros((1, 2), dtype)
        return np.concatenate(
            [np.stack([cells[k][name] for k in keys]).astype(dtype), z])

    agent_cell = np.asarray([
        cell_idx.get((st, sec), len(keys))
        for st, sec in zip(state_abbr, sector_abbr)
    ])
    out = {k: stacked(k, np.float32)[agent_cell]
           for k in ("cbi_usd_p_w", "cbi_max_usd", "ibi_frac",
                     "ibi_max_usd", "pbi_usd_p_kwh")}
    return IncentiveParams(
        pbi_years=stacked("pbi_years", np.int32)[agent_cell], **out)


# ---------------------------------------------------------------------------
# the converter
# ---------------------------------------------------------------------------

def _col(df: pd.DataFrame, name: str, default=None):
    """Column with the reference's ``*_initial`` fallback convention
    (apply_load_growth rewrites the non-initial columns every year,
    elec.py:396-406, so pickles may carry either)."""
    if name in df.columns:
        return df[name]
    if f"{name}_initial" in df.columns:
        return df[f"{name}_initial"]
    if default is not None:
        return pd.Series(np.full(len(df), default), index=df.index)
    raise KeyError(f"agent frame missing required column {name!r}")


def _developable_frac(df: pd.DataFrame) -> np.ndarray:
    """Developable fraction per agent.

    This fork weights by raw customers (elec.py:418
    ``developable_agent_weight = customers_in_bin`` -> frac 1.0); older
    pickles carry ``pct_of_bldgs_developable`` on a 0-100 scale, which
    is detected and rescaled.
    """
    if "pct_of_bldgs_developable" not in df.columns:
        return np.ones(len(df), np.float32)
    v = df["pct_of_bldgs_developable"].to_numpy(np.float32)
    if np.nanmax(v, initial=0.0) > 1.0:
        v = v / 100.0
    return np.clip(np.nan_to_num(v, nan=1.0), 0.0, 1.0)


@fn_timer()
def from_reference_pickle(
    agents: Union[str, pd.DataFrame],
    out_dir: str,
    load_profiles: Union[str, pd.DataFrame],
    solar_profiles: Union[str, pd.DataFrame],
    wholesale_by_region: Optional[Dict[str, np.ndarray]] = None,
    state_incentives: Optional[pd.DataFrame] = None,
    states: Optional[Sequence[str]] = None,
    bad_tariff_ids: Sequence[int] = BAD_TARIFF_IDS,
    nem_state_by_sector: Optional[pd.DataFrame] = None,
    nem_utility_by_sector: Optional[pd.DataFrame] = None,
) -> package.Population:
    """Compile a reference-format agent pickle into a package at
    ``out_dir`` and return the loaded :class:`Population`.

    Parameters mirror what the reference pipeline resolves at load
    time: ``load_profiles`` replaces the per-agent SQL of
    elec.py:508 (columns bldg_id/sector_abbr/state_abbr +
    ``consumption_hourly``), ``solar_profiles`` replaces elec.py:535
    (solar_re_9809_gid/tilt/azimuth + ``cf`` at the reference's 1e6
    scale offset), ``wholesale_by_region`` maps census division -> an
    [8760] $/kWh sell-rate profile (flat arrays accepted).
    """
    df = _as_frame(agents)
    if df.index.name == "agent_id":
        df = df.reset_index()

    required = ("state_abbr", "sector_abbr", "tariff_id", "tariff_dict",
                "bldg_id", "solar_re_9809_gid", "tilt", "azimuth")
    missing = [c for c in required if c not in df.columns]
    if missing:
        raise ValueError(f"agent frame missing columns: {missing}")

    if states:
        df = df[df["state_abbr"].isin(list(states))].reset_index(drop=True)
        if df.empty:
            raise ValueError("state filter removed every agent "
                             "(reference input_data_functions.py:436)")
    df = reassign_bad_tariffs(df, bad_tariff_ids)

    state_list = sorted(df["state_abbr"].unique()) if states is None \
        else list(states)
    st_idx = {s: i for i, s in enumerate(state_list)}
    sec_idx = {s: i for i, s in enumerate(SECTORS)}
    cd_idx = {c: i for i, c in enumerate(CENSUS_DIVISIONS)}

    # --- tariffs: parse, convert, dedup ---
    # parse once per UNIQUE tariff_id, not per agent: ids key a shared
    # tariff table in the reference (reassign_agent_tariffs swaps by id,
    # elec.py:868), so same-id rows carry the same dict; a national
    # pickle has ~1e6 agents over a few thousand tariffs
    specs: List[Dict[str, Any]] = []
    spec_lut: Dict[str, int] = {}
    tids = df["tariff_id"].to_numpy()
    uniq_tids, first_pos, inv = np.unique(
        tids, return_index=True, return_inverse=True)
    spec_of_uid = np.zeros(len(uniq_tids), np.int32)
    raw_dicts = df["tariff_dict"].to_numpy(object)
    for u, pos in enumerate(first_pos):
        td = parse_tariff_dict(raw_dicts[pos])
        spec = reference_tariff_to_spec(td)
        # demand charges ride along as a sub-spec: inert for the hot
        # loop (normalize_tariff_spec ignores the key; SKIP_DEMAND_
        # CHARGES parity) but compiled on demand for analysis runs via
        # ops.demand.compile_demand_bank
        dspec = reference_tariff_to_demand_spec(td)
        if dspec is not None:
            spec["demand"] = dspec
        key = _canonical_key(spec)
        if key not in spec_lut:
            spec_lut[key] = len(specs)
            specs.append(spec)
        spec_of_uid[u] = spec_lut[key]
    tariff_idx = spec_of_uid[inv].astype(np.int32)

    # --- profiles: dedup into banks ---
    load_keys = [tuple(r) for r in
                 df[["bldg_id", "sector_abbr", "state_abbr"]].itertuples(
                     index=False)]
    load_bank, load_lut = _profile_bank(
        _as_frame(load_profiles),
        ("bldg_id", "sector_abbr", "state_abbr"), "consumption_hourly",
        load_keys, normalize_sum=True)
    load_idx = np.asarray([load_lut[k] for k in load_keys], np.int32)

    cf_keys = [tuple(r) for r in
               df[["solar_re_9809_gid", "tilt", "azimuth"]].itertuples(
                   index=False)]
    # reference stores CF at a 1e6 scale offset (elec.py:546-551,
    # financial_functions.py:350 divides by 1e-6-implied offset)
    cf_bank, cf_lut = _profile_bank(
        _as_frame(solar_profiles),
        ("solar_re_9809_gid", "tilt", "azimuth"), "cf",
        cf_keys, scale=1e-6)
    cf_idx = np.asarray([cf_lut[k] for k in cf_keys], np.int32)

    # --- regions + wholesale sell-rate bank ---
    if "census_division_abbr" in df.columns:
        region_idx = np.asarray(
            [cd_idx.get(c, 0) for c in df["census_division_abbr"]], np.int32)
        region_names = list(CENSUS_DIVISIONS)
    else:
        region_idx = np.zeros(len(df), np.int32)
        region_names = ["ALL"]
    wholesale = np.zeros((len(region_names), HOURS), np.float32)
    if wholesale_by_region:
        for r, name in enumerate(region_names):
            prof = wholesale_by_region.get(name)
            if prof is None:
                continue
            arr = np.asarray(prof, dtype=np.float32).ravel()
            wholesale[r] = arr if arr.size == HOURS else np.full(
                HOURS, float(arr.mean()), np.float32)

    incentives = compile_incentives(
        state_incentives, df["state_abbr"], df["sector_abbr"])

    # --- per-agent NEM policy (utility overrides state, elec.py:92-119);
    # without tables, keep the unlimited-NEM defaults ---
    nem_fields: Dict[str, np.ndarray] = {}
    if nem_state_by_sector is not None or nem_utility_by_sector is not None:
        from dgen_tpu.io.nem import resolve_agent_nem_policy

        eia = df["eia_id"].astype(str).tolist() if "eia_id" in df.columns \
            else None
        nem_fields = resolve_agent_nem_policy(
            nem_state_by_sector, nem_utility_by_sector,
            agent_state=df["state_abbr"].tolist(),
            agent_sector=df["sector_abbr"].tolist(),
            agent_eia_id=eia,
        )

    table = build_agent_table(
        state_idx=np.asarray([st_idx[s] for s in df["state_abbr"]], np.int32),
        sector_idx=np.asarray([sec_idx[s] for s in df["sector_abbr"]],
                              np.int32),
        region_idx=region_idx,
        tariff_idx=tariff_idx,
        load_idx=load_idx,
        cf_idx=cf_idx,
        customers_in_bin=_col(df, "customers_in_bin").to_numpy(np.float32),
        load_kwh_per_customer_in_bin=_col(
            df, "load_kwh_per_customer_in_bin").to_numpy(np.float32),
        developable_frac=_developable_frac(df),
        n_states=len(state_list),
        incentives=incentives,
        **nem_fields,
    )

    import jax.numpy as jnp
    profiles = ProfileBank(load=jnp.asarray(load_bank),
                           solar_cf=jnp.asarray(cf_bank),
                           wholesale=jnp.asarray(wholesale))
    package.save_population(out_dir, table, profiles, specs, state_list)
    return package.Population(
        table=table, profiles=profiles, tariffs=compile_tariffs(specs),
        states=state_list, tariff_specs=specs,
    )
