"""Reference-schema results writeback (interop).

The reference lands its results in three Postgres tables with a column
contract its analysis notebooks consume directly
(docs/source/overview.rst:28-54; Notebooks/analysis_of_model_results
reads state_abbr/year/system_kw/npv/payback_period/market_share/
number_of_adopters/customers_in_bin/... from ``agent_outputs``):

  * ``agent_outputs``          — wide per-(agent, year) frame
                                 (dgen_model.py:441-463 writes the agent
                                 df minus a drop list)
  * ``agent_finance_series``   — narrow (agent_id, year, scenario_case)
                                 rows with 25-element arrays
                                 (finance_series_export.py:9-66)
  * ``state_hourly_agg``       — (state_abbr, year, n_hours, net_sum MW)
                                 (attachment_rate_functions.py:151-205)

This module maps a dgen-tpu run directory (io.export parquet surfaces
plus the ``agents.parquet`` static frame) onto those exact names and
shapes so existing reference tooling consumes a TPU run unchanged:
CSV files (one per table, Postgres COPY-compatible; array cells are
JSON lists, the CSV rendering of the reference's JSONB columns) and —
when sqlalchemy + a URL are given — direct ``to_sql`` appends.

Column notes (documented divergences, not silent gaps):
  * ``first_year_elec_bill_savings`` is derived (without - with), the
    same arithmetic the notebooks apply.
  * ``agent_finance_series.cf_energy_value`` carries the real series
    when the run exported it (full-precision runs; compact runs drop
    the energy_value column to halve the device->host transfer, and
    the writeback then zero-fills exactly like the reference's own
    ``_norm25`` does for malformed cells, finance_series_export.py:17).
  * ``utility_bill_w_sys`` / ``utility_bill_wo_sys`` are zero-filled:
    the TPU engine folds bill trajectories into the cash-flow series
    and keeps only first-year bills per agent-year (agent_outputs
    carries both) — zero-fill is the reference exporter's own behavior
    for absent cells, not an invented trajectory.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np
import pandas as pd

from dgen_tpu.io.export import load_surface

#: agent_outputs columns (reference names) in write order — the rename
#: map doubles as the roundtrip test's schema contract
AGENT_OUTPUTS_RENAME: Dict[str, str] = {
    # ours -> reference
    "agent_id": "agent_id",
    "year": "year",
    "state_abbr": "state_abbr",
    "sector_abbr": "sector_abbr",
    "customers_in_bin": "customers_in_bin",
    "developable_agent_weight": "developable_agent_weight",
    "system_kw": "system_kw",
    "npv": "npv",
    "payback_period": "payback_period",
    "max_market_share": "max_market_share",
    "market_share": "market_share",
    "new_adopters": "new_adopters",
    "number_of_adopters": "number_of_adopters",
    "new_system_kw": "new_system_kw",
    "system_kw_cum": "system_kw_cum",
    "market_value": "market_value",
    "first_year_bill_with_system": "first_year_elec_bill_with_system",
    "first_year_bill_without_system": "first_year_elec_bill_without_system",
    "batt_kw": "batt_kw",
    "batt_kwh": "batt_kwh",
    "new_batt_adopters": "batt_adopters_added_this_year",
    "batt_adopters_cum": "batt_adopters_cum",
    "batt_kw_cum": "batt_kw_cum",
    "batt_kwh_cum": "batt_kwh_cum",
    "carbon_intensity_t_per_kwh": "lrmer_co2e",
    "avoided_co2_t": "avoided_tons",
}

FINANCE_SERIES_COLUMNS = (
    "agent_id", "year", "scenario_case",
    "cf_energy_value", "utility_bill_w_sys", "utility_bill_wo_sys",
)

STATE_HOURLY_COLUMNS = ("state_abbr", "year", "n_hours", "net_sum")


def _norm25(a: np.ndarray) -> list:
    """25-length float list (pad/truncate, non-finite -> 0) — the
    reference's own normalization (finance_series_export.py:9-20)."""
    a = np.asarray(a, dtype=float).ravel()
    if a.size < 25:
        a = np.pad(a, (0, 25 - a.size))
    elif a.size > 25:
        a = a[:25]
    return [float(v) for v in np.where(np.isfinite(a), a, 0.0)]


def reference_agent_outputs(run_dir: str) -> pd.DataFrame:
    """The reference-named wide agent_outputs frame for a run dir."""
    ao = load_surface(run_dir, "agent_outputs")
    static_path = os.path.join(run_dir, "agents.parquet")
    if os.path.exists(static_path):
        ao = ao.merge(pd.read_parquet(static_path), on="agent_id",
                      how="left", validate="many_to_one")
    else:
        for col in ("state_abbr", "sector_abbr", "customers_in_bin",
                    "developable_agent_weight"):
            ao[col] = np.nan
    out = pd.DataFrame(
        {ref: ao[ours] for ours, ref in AGENT_OUTPUTS_RENAME.items()
         if ours in ao.columns}
    )
    # derived exactly as the notebooks derive it
    out["first_year_elec_bill_savings"] = (
        out["first_year_elec_bill_without_system"]
        - out["first_year_elec_bill_with_system"]
    )
    return out


def reference_finance_series(run_dir: str) -> pd.DataFrame:
    fs = load_surface(run_dir, "finance_series")
    n = len(fs)
    zeros = [0.0] * 25
    if "energy_value" in fs.columns:
        cf_ev = [_norm25(v) for v in fs["energy_value"]]
    else:   # compact run: zero-fill, the reference's own absent-cell rule
        cf_ev = [zeros] * n
    return pd.DataFrame({
        "agent_id": fs["agent_id"],
        "year": fs["year"],
        "scenario_case": "pv_only",
        "cf_energy_value": cf_ev,
        "utility_bill_w_sys": [zeros] * n,
        "utility_bill_wo_sys": [zeros] * n,
    })


def reference_state_hourly(run_dir: str) -> pd.DataFrame:
    sh = load_surface(run_dir, "state_hourly")
    return pd.DataFrame({
        "state_abbr": sh["state"],
        "year": sh["year"],
        "n_hours": [len(v) for v in sh["net_load_mw"]],
        "net_sum": [list(map(float, v)) for v in sh["net_load_mw"]],
    })


def _csv_ready(df: pd.DataFrame) -> pd.DataFrame:
    """JSON-encode list cells (the CSV rendering of JSONB columns)."""
    out = df.copy()
    for col in out.columns:
        if len(out) and isinstance(out[col].iloc[0], list):
            out[col] = out[col].map(json.dumps)
    return out


def write_reference_tables(
    run_dir: str,
    out_dir: str,
    postgres_url: Optional[str] = None,
    schema: Optional[str] = None,
) -> Dict[str, str]:
    """Emit the three reference tables for a run; returns table->path.

    CSVs always; Postgres additionally when ``postgres_url`` is given
    (requires sqlalchemy, an optional dependency — the reference's
    hard one, data_functions.py)."""
    os.makedirs(out_dir, exist_ok=True)
    tables = {
        "agent_outputs": reference_agent_outputs(run_dir),
        "agent_finance_series": reference_finance_series(run_dir),
        "state_hourly_agg": reference_state_hourly(run_dir),
    }
    from dgen_tpu.resilience.atomic import atomic_write

    paths = {}
    for name, df in tables.items():
        path = os.path.join(out_dir, f"{name}.csv")
        ready = _csv_ready(df)
        atomic_write(path, lambda tmp, d=ready: d.to_csv(tmp, index=False))
        paths[name] = path
    if postgres_url:
        import sqlalchemy

        engine = sqlalchemy.create_engine(postgres_url)
        with engine.begin() as conn:
            for name, df in tables.items():
                _csv_ready(df).to_sql(
                    name, conn, schema=schema, if_exists="append",
                    index=False,
                )
    return paths


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="Write a run's results in the reference's table "
                    "schema (agent_outputs / agent_finance_series / "
                    "state_hourly_agg)")
    ap.add_argument("run_dir")
    ap.add_argument("out_dir")
    ap.add_argument("--postgres-url", default=None)
    ap.add_argument("--schema", default=None)
    args = ap.parse_args(argv)
    paths = write_reference_tables(
        args.run_dir, args.out_dir, postgres_url=args.postgres_url,
        schema=args.schema,
    )
    for name, path in paths.items():
        print(f"{name}: {path}")


if __name__ == "__main__":
    main()
