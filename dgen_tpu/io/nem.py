"""NEM policy machine: compile reference-format net-metering policy
data into the dense gates the device pipeline consumes.

The reference re-derives NEM availability every model year from four
tables (reference agent_mutation/elec.py:459 ``get_nem_settings``):
state capacity limits (``nem_state_limits_2019``), state x sector and
utility x sector scenario attributes (``nem_scenario_bau_2019`` /
``..._by_utility_2019``, reference data_functions.py:648-733), plus
per-state peak demand and solar CF during the peak period
(``peak_demand_mw.csv`` / ``cf_during_peak_demand.csv`` read every year
at dgen_model.py:253-254). Here the whole machine is compiled ONCE at
ingest into:

  * ``nem_cap_kw [Y, n_states]`` — the installed-capacity ceiling under
    which NEM stays open (:func:`compile_state_nem_caps`), consumed by
    the year step's cumulative-capacity gate.
  * per-agent ``nem_kw_limit`` / ``nem_first_year`` / ``nem_sunset_year``
    (:func:`resolve_agent_nem_policy`) — the system-size limit and
    availability window after utility-overrides-state resolution
    (reference elec.py:92-119 ``apply_export_tariff_params``),
    consumed as a sizing-bracket cap + metering gate.

Divergences from the reference, on purpose:
  * The capacity gate compares against the PREVIOUS model step's state
    cumulative (the reference's ``max_reference_year='previous'``
    branch, elec.py:466); the 'current' variant is indistinguishable in
    practice because ``state_capacity_by_year`` is always built from
    last year's outputs before sizing runs (dgen_model.py:257-260).
  * A state absent from ``state_limits`` — or outside its
    [first_year, sunset_year] window — carries NO capacity cap (the
    reference's left-merge keeps such states with null caps and every
    null-cap filter passes, elec.py:470-478).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd

#: "no limit" sentinel, well inside float32
NO_CAP = 1e30


def _num(df: pd.DataFrame, col: str) -> pd.Series:
    if col not in df.columns:
        return pd.Series(np.nan, index=df.index)
    return pd.to_numeric(df[col], errors="coerce")


def compile_state_nem_caps(
    state_limits: pd.DataFrame,
    peak_demand_mw: pd.DataFrame,
    cf_during_peak: pd.DataFrame,
    years: Sequence[int],
    states: Sequence[str],
    res_load_multiplier: Optional[np.ndarray] = None,
) -> np.ndarray:
    """[Y, n_states] float32 NEM capacity cap in kW.

    Per (year, state), within the state-limits row's availability
    window, the cap is the tighter of (reference elec.py:474-478):

      * ``max_cum_capacity_mw`` (absolute MW ceiling), and
      * ``max_pct_cum_capacity``% of peak demand converted to nameplate
        MW via the solar CF during the peak-demand period:
        ``max_pct/100 * peak_demand_mw(year) / cf_peak`` (elec.py:477).

    ``peak_demand_mw(year)`` scales the 2014 base by the residential
    load-growth multiplier, the reference's peak-demand tracking
    (calc_state_capacity_by_year, elec.py:813-814);
    ``res_load_multiplier [Y, n_states]`` defaults to 1.0.
    """
    ny, ns = len(years), len(states)
    caps = np.full((ny, ns), NO_CAP, dtype=np.float32)
    if state_limits is None or len(state_limits) == 0:
        return caps

    st_idx = {s: i for i, s in enumerate(states)}
    peak = {
        str(r["state_abbr"]): float(r["peak_demand_mw_2014"])
        for _, r in peak_demand_mw.iterrows()
    } if peak_demand_mw is not None else {}
    cf = {
        str(r["state_abbr"]): float(r["solar_cf_during_peak_demand_period"])
        for _, r in cf_during_peak.iterrows()
    } if cf_during_peak is not None else {}

    first = _num(state_limits, "first_year").fillna(-np.inf)
    sunset = _num(state_limits, "sunset_year").fillna(np.inf)
    max_mw = _num(state_limits, "max_cum_capacity_mw")
    max_pct = _num(state_limits, "max_pct_cum_capacity")

    for row_i, row in state_limits.iterrows():
        s = str(row["state_abbr"])
        if s not in st_idx:
            continue
        si = st_idx[s]
        for yi, y in enumerate(years):
            if not (first[row_i] <= y <= sunset[row_i]):
                continue  # caps don't apply outside the window
            cap = NO_CAP
            if np.isfinite(max_mw[row_i]):
                cap = min(cap, float(max_mw[row_i]) * 1000.0)
            if np.isfinite(max_pct[row_i]) and s in peak and cf.get(s, 0.0) > 0:
                mult = (
                    float(res_load_multiplier[yi, si])
                    if res_load_multiplier is not None else 1.0
                )
                mw = (float(max_pct[row_i]) / 100.0) * peak[s] * mult / cf[s]
                cap = min(cap, mw * 1000.0)
            caps[yi, si] = cap
    return caps


def resolve_agent_nem_policy(
    state_by_sector: pd.DataFrame,
    utility_by_sector: Optional[pd.DataFrame],
    agent_state: Sequence[str],
    agent_sector: Sequence[str],
    agent_eia_id: Optional[Sequence] = None,
) -> dict:
    """Per-agent NEM attributes after utility-overrides-state resolution.

    Reference semantics (elec.py:92-119 ``apply_export_tariff_params``):
    an agent whose (eia_id, sector, state) matches a utility row takes
    that row's ``nem_system_kw_limit``; otherwise the (state, sector)
    row applies; otherwise the limit is 0 — NO net metering (the
    reference's ``fillna(0)``, elec.py:119). The availability window
    [first_year, sunset_year] rides along from whichever row won
    (reference filter_nem_year, elec.py:449-454, applied per year).

    Returns dict of float32 [N] arrays: ``nem_kw_limit``,
    ``nem_first_year``, ``nem_sunset_year``.
    """
    n = len(agent_state)
    limit = np.zeros(n, dtype=np.float32)
    first = np.zeros(n, dtype=np.float32)
    sunset = np.full(n, 9999.0, dtype=np.float32)

    def norm_id(v) -> str:
        # eia ids arrive as int64 from CSVs but float64 ('1234.0') from
        # NaN-bearing pickle columns; normalize so they match
        try:
            return str(int(float(v)))
        except (TypeError, ValueError):
            return str(v)

    def index_rows(df, keys):
        out = {}
        if df is None or len(df) == 0:
            return out
        lim = _num(df, "nem_system_kw_limit").fillna(0.0)
        fy = _num(df, "first_year").fillna(-np.inf)
        sy = _num(df, "sunset_year").fillna(np.inf)
        for i, row in df.iterrows():
            k = tuple(
                norm_id(row[c]) if c == "eia_id" else str(row[c])
                for c in keys
            )
            # first row wins, matching the reference's drop_duplicates
            # (elec.py:101-102)
            out.setdefault(k, (float(lim[i]), float(fy[i]), float(sy[i])))
        return out

    state_rows = index_rows(state_by_sector, ["state_abbr", "sector_abbr"])
    util_rows = index_rows(
        utility_by_sector, ["eia_id", "sector_abbr", "state_abbr"]
    )

    for i in range(n):
        hit = None
        if agent_eia_id is not None and util_rows:
            hit = util_rows.get(
                (norm_id(agent_eia_id[i]), str(agent_sector[i]),
                 str(agent_state[i]))
            )
        if hit is None:
            hit = state_rows.get((str(agent_state[i]), str(agent_sector[i])))
        if hit is None:
            continue  # limit 0 = no NEM
        lim, fy, sy = hit
        limit[i] = np.float32(min(lim, NO_CAP)) if lim > 0 else 0.0
        first[i] = max(fy, 0.0) if np.isfinite(fy) else 0.0
        sunset[i] = min(sy, 9999.0) if np.isfinite(sy) else 9999.0
    return {
        "nem_kw_limit": limit,
        "nem_first_year": first,
        "nem_sunset_year": sunset,
    }
