"""Build a full :class:`ScenarioInputs` from a reference-format
``input_data/`` directory.

This is the TPU framework's replacement for the reference's
Excel-workbook -> Postgres -> 13-pandas-merges input pipeline
(SURVEY.md §2.5): every trajectory CSV the reference ships is parsed
straight to dense device arrays on the model-year grid by
``dgen_tpu.io.ingest``, and this module assembles them into one
scenario pytree.

Sourced per reference table (reference file -> field):
  * pv_prices/*                -> pv_capex_per_kw, pv_om_per_kw
  * pv_tech_performance/*      -> pv_degradation
  * batt_prices/*              -> batt_capex_per_kwh / _per_kw
  * pv_plus_batt_prices/*      -> *_combined fields
  * financing_terms/*          -> FinanceParams trajectories
  * load_growth/*              -> load_growth [Y, R, S]
  * elec_prices/*              -> elec_price_multiplier + escalator
  * wholesale_electricity_prices/* -> flat hourly sell-rate base [R]
  * batt_tech_performance/*    -> batt_eff, batt_lifetime_yrs
  * depreciation_schedules/*   -> deprec_sch [Y, S, D]
  * carbon_intensities/*       -> carbon_intensity_t_per_kwh [Y, states]
  * installed_capacity_mw_by_state_sector.csv -> starting_kw [G]
  * observed_deployment_by_state_sector_*.csv -> observed_kw [Y, G]
  * ohm_attachment_rates.csv   -> attachment_rate [G]
  * peak_demand_mw.csv + cf_during_peak_demand.csv (+ exported
    nem_state_limits.csv)      -> nem_cap_kw [Y, states]
  * itc_schedule.csv (optional) -> itc_fraction (else federal statute)
  * value_of_resiliency/*      -> value_of_resiliency [Y, G]
  * max_market_curves.csv (optional drop-in) -> mms_table
  * bass_params.csv (optional drop-in)       -> bass_p/q, teq_yr1

Bass p/q/teq and the max-market-share curves live only in the
reference's Postgres dump, not its input_data CSVs; they are accepted
here as exported drop-ins (``max_market_curves.csv`` /
``bass_params.csv``, schemas mirroring data_functions.py:279,370).
Absent those, the synthetic :func:`uniform_inputs` defaults remain and
``meta["market_curves"]`` says so. ITC fraction likewise comes from the
scenario workbook; the default schedule here mirrors the federal
statute (see ``itc_schedule.csv``).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import SECTORS, ScenarioConfig
from dgen_tpu.io import ingest
from dgen_tpu.io.ingest import _read_csv
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.scenario import ScenarioInputs
from dgen_tpu.utils.timing import fn_timer

#: census divisions (the reference's load-growth region key)
CENSUS_DIVISIONS = ("NE", "MA", "ENC", "WNC", "SA", "ESC", "WSC", "MTN", "PAC")

#: standard US Census Bureau state -> division assignment (the
#: reference resolves this via its county table's
#: census_division_abbr column, absent from the OS release; a state's
#: counties all share its division, so the division IS the per-state
#: key). Division abbrs match CENSUS_DIVISIONS; note division "NE"
#: (New England) vs state "NE" (Nebraska) are distinct namespaces.
STATE_CENSUS_DIVISION = {
    **{s: "NE" for s in ("CT", "MA", "ME", "NH", "RI", "VT")},
    **{s: "MA" for s in ("NJ", "NY", "PA")},
    **{s: "ENC" for s in ("IL", "IN", "MI", "OH", "WI")},
    **{s: "WNC" for s in ("IA", "KS", "MN", "MO", "ND", "NE", "SD")},
    **{s: "SA" for s in ("DC", "DE", "FL", "GA", "MD", "NC", "SC",
                         "VA", "WV")},
    **{s: "ESC" for s in ("AL", "KY", "MS", "TN")},
    **{s: "WSC" for s in ("AR", "LA", "OK", "TX")},
    **{s: "MTN" for s in ("AZ", "CO", "ID", "MT", "NM", "NV", "UT",
                          "WY")},
    **{s: "PAC" for s in ("AK", "CA", "HI", "OR", "WA")},
}


def load_pv_plus_batt_prices(
    path: str, model_years: Sequence[int]
) -> Dict[str, np.ndarray]:
    """pv_plus_batt_prices CSV -> combined-system cost trajectories
    [Y, 3] (res/nonres columns duplicated to com+ind, the reference's
    stacked_sectors shaper convention)."""
    out = {}
    for field, key in (
        ("system_capex_per_kw", "pv_capex_per_kw_combined"),
        ("batt_capex_per_kwh", "batt_capex_per_kwh_combined"),
    ):
        out[key] = ingest.load_stacked_sectors(
            path, field, model_years, nonres_suffix=True
        )
    return out


def load_starting_capacities(
    path: str, start_year: int, states: Sequence[str]
) -> np.ndarray:
    """installed_capacity_mw_by_state_sector.csv -> starting PV kW [G]
    at the scenario start year (reference
    agent_mutation/elec.py:621 ``get_state_starting_capacities``)."""
    rows = _read_csv(path)
    st_idx = {s: i for i, s in enumerate(states)}
    sec_idx = {s: i for i, s in enumerate(SECTORS)}
    g = len(states) * len(SECTORS)
    # use the closest year at or before start_year present in the file
    years = sorted({int(float(r["year"])) for r in rows})
    usable = [y for y in years if y <= start_year] or years[:1]
    pick = usable[-1]
    out = np.zeros(g, dtype=np.float32)
    for r in rows:
        if int(float(r["year"])) != pick:
            continue
        st, sec = r.get("state_abbr", ""), r.get("sector_abbr", "")
        if st in st_idx and sec in sec_idx:
            gi = st_idx[st] * len(SECTORS) + sec_idx[sec]
            out[gi] = float(r["observed_capacity_mw"]) * 1000.0
    return out


def load_wholesale(
    path: str, model_years: Sequence[int], base_year: int
) -> Tuple[List[str], np.ndarray, np.ndarray]:
    """(ba names, base $/kWh [n_bas], multiplier [Y, n_bas]) from one
    parse of the wholesale CSV (ba, <year columns>).

    The reference feeds annual wholesale prices as the net-billing sell
    rate, re-merged every model year (financial_functions.py:182,372;
    apply_wholesale_elec_prices elec.py:608). Here the base-year level
    seeds the profile bank and the multiplier (1.0 at base) rescales it
    per model year.
    """
    rows = _read_csv(path)
    bas: List[str] = []
    base_vals = np.zeros(len(rows), np.float32)
    mult = np.ones((len(model_years), len(rows)), np.float32)
    for bi, r in enumerate(rows):
        bas.append(r["ba"])
        years = sorted(int(c) for c in r.keys() if c.isdigit())
        pick = max([y for y in years if y <= base_year] or years[:1])
        base = float(r[str(pick)])
        base_vals[bi] = base
        if base <= 0:
            continue
        years_avail = np.asarray(years)
        vals = np.asarray([float(r[str(y)]) for y in years], np.float32)
        traj = ingest._year_grid_interp(years_avail, vals, model_years)
        mult[:, bi] = traj / base
    return bas, base_vals, mult


def load_wholesale_base(
    path: str, base_year: int
) -> Tuple[List[str], np.ndarray]:
    """(ba names, base-year $/kWh) — see :func:`load_wholesale`."""
    bas, base_vals, _ = load_wholesale(path, [base_year], base_year)
    return bas, base_vals


def wholesale_profile_bank(
    meta: Dict[str, object],
    input_root: Optional[str] = None,
) -> np.ndarray:
    """[R, 8760] $/kWh wholesale sell-rate bank from a scenario's meta.

    The reference feeds a FLAT annual wholesale price as the
    net-billing sell rate (one scalar per agent-year,
    financial_functions.py:182,372) — so the flat default here is
    reference-faithful, not a simplification. When the input root
    carries a ``wholesale_hourly_shape.csv`` (column ``shape``, 8760
    rows, or one column per region name), the flat base is modulated by
    that normalized hourly shape instead, giving the TS-sell bill path
    real intra-day/seasonal structure the reference cannot express.
    """
    base = np.asarray(meta["wholesale_base_usd_per_kwh"], dtype=np.float32)
    r = len(base)
    out = np.broadcast_to(base[:, None], (r, 8760)).copy()

    if input_root:
        path = os.path.join(input_root, "wholesale_hourly_shape.csv")
        if os.path.exists(path):
            rows = _read_csv(path)
            if len(rows) != 8760:
                raise ValueError(
                    f"wholesale_hourly_shape.csv has {len(rows)} rows, "
                    "expected 8760"
                )
            regions = list(meta.get("regions", []))
            cols = rows[0].keys()
            for ri, name in enumerate(regions):
                col = name if name in cols else (
                    "shape" if "shape" in cols else None)
                if col is None:
                    continue
                shape = np.asarray([float(r_[col]) for r_ in rows],
                                   dtype=np.float32)
                shape /= max(float(shape.mean()), 1e-9)
                out[ri] = base[ri] * shape
    return out


@fn_timer()
def scenario_inputs_from_reference(
    input_root: str,
    config: ScenarioConfig,
    states: Sequence[str],
    region_kind: str = "census_division",
    overrides: Optional[Dict[str, object]] = None,
    prefer: Optional[Dict[str, str]] = None,
) -> Tuple[ScenarioInputs, Dict[str, object]]:
    """(ScenarioInputs, meta) from a reference input_data directory.

    ``region_kind`` picks what the agent ``region_idx`` axis means:
      * "census_division" (9 regions): load growth is regional
        (reference resolution); retail-price trajectories are averaged
        over ReEDS BAs onto every region.
      * "ba": retail prices are per ReEDS BA (reference resolution);
        load growth is the national mean.

    ``meta`` carries the region list and the per-region flat wholesale
    sell rate base [R] ($/kWh) for ProfileBank construction.

    ``prefer`` maps family keys to filename substrings (the scenario
    workbook's per-family trajectory selections, io.workbook /
    ingest.discover_reference_inputs); unmatched preferences fall back
    to the built-in defaults.
    """
    prefer = prefer or {}
    files = ingest.discover_reference_inputs(input_root, prefer=prefer)
    years = list(config.model_years)
    n_states = len(states)
    g = n_states * len(SECTORS)

    def _pick_csv(dirname: str, key: str, default_substr: str) -> Optional[str]:
        d = os.path.join(input_root, dirname)
        if not os.path.isdir(d):
            return None
        cands = sorted(f for f in os.listdir(d) if f.endswith(".csv"))
        if not cands:
            return None
        for substr in (prefer.get(key), default_substr):
            if substr:
                hit = [c for c in cands if substr.lower() in c.lower()]
                if hit:
                    return os.path.join(d, hit[-1])
        return os.path.join(d, cands[-1])

    wholesale_path = _pick_csv(
        "wholesale_electricity_prices", "wholesale", "Mid_Case")

    bas: List[str] = []
    wholesale_base = np.zeros(0, np.float32)
    wholesale_traj = None
    if wholesale_path:
        bas, wholesale_base, wholesale_traj = load_wholesale(
            wholesale_path, years, config.start_year)

    if region_kind == "census_division":
        regions = list(CENSUS_DIVISIONS)
    elif region_kind == "ba":
        regions = bas or list(CENSUS_DIVISIONS)
    else:
        raise ValueError(f"unknown region_kind {region_kind!r}")
    n_regions = len(regions)

    ov: Dict[str, object] = {}

    # --- cost / tech trajectories ---
    if "pv_prices" in files:
        ov["pv_capex_per_kw"] = jnp.asarray(ingest.load_stacked_sectors(
            files["pv_prices"], "system_capex_per_kw", years))
        ov["pv_om_per_kw"] = jnp.asarray(ingest.load_stacked_sectors(
            files["pv_prices"], "system_om_per_kw", years))
    if "pv_tech" in files:
        ov["pv_degradation"] = jnp.asarray(ingest.load_stacked_sectors(
            files["pv_tech"], "pv_degradation_factor", years))
    if "batt_tech" in files:
        bt = ingest.load_batt_tech(files["batt_tech"], years)
        ov["batt_eff"] = jnp.asarray(bt["batt_eff"])
        ov["batt_lifetime_yrs"] = jnp.asarray(bt["batt_lifetime_yrs"])
    if "deprec" in files:
        ov["deprec_sch"] = jnp.asarray(
            ingest.load_depreciation_schedules(files["deprec"], years))
    if "batt_prices" in files:
        ov["batt_capex_per_kwh"] = jnp.asarray(ingest.load_stacked_sectors(
            files["batt_prices"], "batt_capex_per_kwh", years,
            nonres_suffix=True))
        ov["batt_capex_per_kw"] = jnp.asarray(ingest.load_stacked_sectors(
            files["batt_prices"], "batt_capex_per_kw", years,
            nonres_suffix=True))
    pb_path = _pick_csv("pv_plus_batt_prices", "pv_plus_batt", "mid")
    if pb_path:
        pb = load_pv_plus_batt_prices(pb_path, years)
        ov["pv_capex_per_kw_combined"] = jnp.asarray(
            pb["pv_capex_per_kw_combined"])
        ov["batt_capex_per_kwh_combined"] = jnp.asarray(
            pb["batt_capex_per_kwh_combined"])

    # --- wholesale trajectory -> per-year sell-rate multiplier ---
    if wholesale_traj is not None and len(bas):
        if region_kind == "ba":
            ov["wholesale_multiplier"] = jnp.asarray(wholesale_traj)
        else:
            ov["wholesale_multiplier"] = jnp.asarray(np.broadcast_to(
                wholesale_traj.mean(axis=1, keepdims=True),
                (len(years), n_regions)).copy())

    # --- carbon intensities (elec.py:595 passthrough) ---
    c_path = _pick_csv("carbon_intensities", "carbon", "")
    if c_path:
        ov["carbon_intensity_t_per_kwh"] = jnp.asarray(
            ingest.load_carbon_intensities(c_path, years, states))

    # --- ITC schedule: an itc_schedule.csv in the input root (columns
    # itc_fraction_res/com/ind by year — the workbook's itc_options
    # analogue, reference elec.py:348) wins; otherwise the statutory
    # federal schedule ---
    itc_path = os.path.join(input_root, "itc_schedule.csv")
    if os.path.exists(itc_path):
        ov["itc_fraction"] = jnp.asarray(ingest.load_stacked_sectors(
            itc_path, "itc_fraction", years))
        itc_source = "ingested"
    else:
        ov["itc_fraction"] = jnp.asarray(scen.federal_itc_schedule(years))
        itc_source = "federal_statute_default"

    # --- financing ---
    if "financing" in files:
        fin = ingest.load_financing_terms(files["financing"], years)
        ov["loan_term_yrs"] = jnp.asarray(fin["loan_term_yrs"].astype(np.int32))
        ov["loan_interest_rate"] = jnp.asarray(fin["loan_interest_rate"])
        ov["down_payment_fraction"] = jnp.asarray(fin["down_payment_fraction"])
        ov["real_discount_rate"] = jnp.asarray(fin["real_discount_rate"])
        ov["tax_rate"] = jnp.asarray(fin["tax_rate"])

    # --- regional trajectories ---
    if "load_growth" in files:
        lg = ingest.load_load_growth(files["load_growth"], years,
                                     CENSUS_DIVISIONS)
        if region_kind == "census_division":
            ov["load_growth"] = jnp.asarray(lg)
        else:
            ov["load_growth"] = jnp.asarray(
                np.broadcast_to(lg.mean(axis=1, keepdims=True),
                                (len(years), n_regions, len(SECTORS))).copy())
    if "elec_prices" in files and bas:
        ep = ingest.load_elec_prices(files["elec_prices"], years, bas,
                                     base_year=config.start_year)
        if region_kind == "ba":
            mult = ep
        else:
            mult = np.broadcast_to(
                ep.mean(axis=1, keepdims=True),
                (len(years), n_regions, len(SECTORS))).copy()
        ov["elec_price_multiplier"] = jnp.asarray(mult)
        esc = scen.escalator_from_multipliers(mult, np.asarray(years))
        ov["elec_price_escalator"] = jnp.asarray(esc.astype(np.float32))

    def _opt(name: str) -> Optional[str]:
        for d in (input_root, os.path.join(input_root, os.pardir, "python")):
            p = os.path.join(d, name)
            if os.path.exists(p):
                return p
        return None

    # --- value of resiliency (apply_value_of_resiliency, elec.py:287;
    # shipped vor_FY20 CSV keys on state_abbr + sector_abbr) ---
    v_path = _pick_csv("value_of_resiliency", "vor", "mid")
    if v_path:
        vor_g = ingest.load_value_of_resiliency(v_path, states)
        ov["value_of_resiliency"] = jnp.asarray(np.broadcast_to(
            vor_g[None, :], (len(years), g)).copy())

    # --- market curves: CSV drop-ins for the reference's Postgres-only
    # tables (max_market_curves_to_model, data_functions.py:370;
    # input_solar_bass_params, data_functions.py:279). Absent these the
    # synthetic uniform_inputs defaults remain — flagged in meta so run
    # outputs cannot be mistaken for dGen adoption numbers. ---
    market_curves = {"mms": "synthetic_default", "bass": "synthetic_default"}
    mmc_path = _opt("max_market_curves.csv")
    if mmc_path:
        ov["mms_table"] = jnp.asarray(ingest.load_max_market_curves(mmc_path))
        market_curves["mms"] = "ingested"
    bp_path = _opt("bass_params.csv")
    if bp_path:
        bp = ingest.load_bass_params(bp_path, states)
        ov["bass_p"] = jnp.asarray(bp["bass_p"])
        ov["bass_q"] = jnp.asarray(bp["bass_q"])
        ov["teq_yr1"] = jnp.asarray(bp["teq_yr1"])
        market_curves["bass"] = "ingested"
        if bp["missing"]:
            import logging

            logging.getLogger("dgen_tpu").warning(
                "bass_params.csv: %d of %d state x sector groups have no "
                "row (keeping synthetic defaults there)", bp["missing"], g,
            )

    # --- market data ---
    if "observed" in files:
        ov["observed_kw"] = jnp.asarray(ingest.load_observed_deployment(
            files["observed"], years, states))
    if "attachment" in files:
        per_state = ingest.load_attachment_rates(files["attachment"], states)
        ov["attachment_rate"] = jnp.asarray(
            ingest.state_attachment_to_groups(per_state))
    cap_path = os.path.join(input_root,
                            "installed_capacity_mw_by_state_sector.csv")
    if os.path.exists(cap_path):
        ov["starting_kw"] = jnp.asarray(load_starting_capacities(
            cap_path, config.start_year, states))

    # --- NEM capacity caps (agent_mutation/elec.py:459-478) ---
    # peak_demand_mw.csv + cf_during_peak_demand.csv ship with the
    # reference next to dgen_model.py (read there every model year,
    # dgen_model.py:253-254); the state-limits table lives in its
    # Postgres dump and is accepted here as an exported
    # nem_state_limits.csv in the input root.
    sl_path = _opt("nem_state_limits.csv")
    pk_path = _opt("peak_demand_mw.csv")
    cfp_path = _opt("cf_during_peak_demand.csv")
    nem_caps_source = "uncapped_default"
    if sl_path and pk_path and cfp_path:
        nem_caps_source = "ingested"
        import pandas as pd

        from dgen_tpu.io.nem import compile_state_nem_caps

        # residential load multiplier for peak-demand growth (reference
        # elec.py:813-814 averages county res growth per state; a
        # state's counties share its census division, so each state
        # takes its OWN division's growth — the division-mean fallback
        # covers only states outside the standard assignment)
        res_mult = None
        if "load_growth" in ov and region_kind == "census_division":
            lg = np.asarray(ov["load_growth"])            # [Y, R, S]
            cd_of = {c: i for i, c in enumerate(regions)}
            fallback = lg[:, :, 0].mean(axis=1)           # [Y]
            res_mult = np.empty((len(years), n_states), np.float32)
            for si, s in enumerate(states):
                cd = STATE_CENSUS_DIVISION.get(s)
                res_mult[:, si] = (
                    lg[:, cd_of[cd], 0] if cd in cd_of else fallback
                )
        elif "load_growth" in ov:
            # BA-keyed regions don't map to states; keep the mean proxy
            lg = np.asarray(ov["load_growth"])
            res_mult = np.broadcast_to(
                lg[:, :, 0].mean(axis=1, keepdims=True),
                (len(years), n_states),
            ).copy()
        ov["nem_cap_kw"] = jnp.asarray(compile_state_nem_caps(
            pd.read_csv(sl_path), pd.read_csv(pk_path),
            pd.read_csv(cfp_path), years, states, res_mult,
        ))

    if overrides:
        ov.update(overrides)

    inputs = scen.uniform_inputs(config, n_groups=g, n_regions=n_regions,
                                 overrides=ov)
    meta = {
        "regions": regions,
        "bas": bas,
        "wholesale_base_usd_per_kwh": (
            wholesale_base if region_kind == "ba" and len(wholesale_base)
            else np.full(n_regions,
                         float(wholesale_base.mean()) if len(wholesale_base)
                         else 0.04, np.float32)
        ),
        "files": files,
        "market_curves": market_curves,
        # provenance for the other two drop-ins (market_curves carries
        # mms/bass): stamped into every run's meta.json so synthetic
        # defaults are never mistaken for ingested policy data
        "data_sources": {
            "itc": itc_source,
            "nem_caps": nem_caps_source,
        },
    }
    return inputs, meta
