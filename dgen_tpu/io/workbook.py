"""Reference scenario-workbook (.xlsm) reader — stdlib only.

The reference's input artifact is an Excel macro workbook whose named
ranges the loader pushes into Postgres (excel/excel_functions.py:21
``load_scenario``; excel/table_range_lkup.csv maps the 14 run ranges).
In the shipped workbooks (dgen_os/excel/input_sheet_final.xlsm,
2024_input_sheet.xlsm) those ranges are SELECTOR cells on the
'Main - Scenario Options' sheet: each names the trajectory preset (or,
when the value cell says "User Defined", the user table in the next
column) for one input family, plus the main options column
(scenario name / technology / region / markets / end year / seed).

openpyxl is not available in this image, and an .xlsx/.xlsm is just a
zip of XML — so this module parses workbook.xml (defined names),
sharedStrings.xml and the referenced sheets directly with
zipfile + xml.etree (values only, like openpyxl's ``data_only=True``:
formula cells carry their cached <v>).

Consumption path:
  * :func:`read_scenario` -> a :class:`WorkbookScenario` (labels,
    values, per-family selections)
  * :func:`scenario_from_workbook` -> (ScenarioConfig, build info):
    states, sector weights, storage flag, and the ``prefer`` mapping
    that drives io.reference_inputs' per-family CSV selection
  * :func:`export_drop_ins` -> scenario_options.csv + selections.json
    (+ any rectangular range as its own CSV) for operators who want the
    workbook contents as plain files
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import re
import zipfile
import xml.etree.ElementTree as ET
from contextlib import nullcontext as _nullcontext
from typing import Dict, List, Optional, Tuple

_NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
_NS_REL = ("{http://schemas.openxmlformats.org/officeDocument/2006/"
           "relationships}")

#: named range -> io.reference_inputs / ingest family key
#: (excel/table_range_lkup.csv rows with run=TRUE)
SELECTOR_FAMILIES = {
    "load_growth_user_defined": "load_growth",
    "elec_prices_user_defined": "elec_prices",
    "wholesale_elec_prices_user_defined": "wholesale",
    "pv_price_traj_user_defined": "pv_prices",
    "pv_tech_traj_user_defined": "pv_tech",
    "batt_price_traj_user_defined": "batt_prices",
    "batt_tech_traj_user_defined": "batt_tech",
    "pv_plus_batt_price_traj_user_defined": "pv_plus_batt",
    "financing_terms_user_defined": "financing",
    "deprec_sch_user_defined": "deprec",
    "carbon_intensities_user_defined": "carbon",
    "value_of_resiliency_user_defined": "vor",
}

US_STATE_ABBR = {
    "alabama": "AL", "alaska": "AK", "arizona": "AZ", "arkansas": "AR",
    "california": "CA", "colorado": "CO", "connecticut": "CT",
    "delaware": "DE", "district of columbia": "DC", "florida": "FL",
    "georgia": "GA", "hawaii": "HI", "idaho": "ID", "illinois": "IL",
    "indiana": "IN", "iowa": "IA", "kansas": "KS", "kentucky": "KY",
    "louisiana": "LA", "maine": "ME", "maryland": "MD",
    "massachusetts": "MA", "michigan": "MI", "minnesota": "MN",
    "mississippi": "MS", "missouri": "MO", "montana": "MT",
    "nebraska": "NE", "nevada": "NV", "new hampshire": "NH",
    "new jersey": "NJ", "new mexico": "NM", "new york": "NY",
    "north carolina": "NC", "north dakota": "ND", "ohio": "OH",
    "oklahoma": "OK", "oregon": "OR", "pennsylvania": "PA",
    "rhode island": "RI", "south carolina": "SC", "south dakota": "SD",
    "tennessee": "TN", "texas": "TX", "utah": "UT", "vermont": "VT",
    "virginia": "VA", "washington": "WA", "west virginia": "WV",
    "wisconsin": "WI", "wyoming": "WY",
}

#: ISO/RTO region names the reference workbook accepts -> state lists
ISO_STATES = {
    "ercot": ["TX"],
    "caiso": ["CA"],
    "isone": ["CT", "MA", "ME", "NH", "RI", "VT"],
    "iso-ne": ["CT", "MA", "ME", "NH", "RI", "VT"],
    "nyiso": ["NY"],
}


def _col_to_idx(col: str) -> int:
    i = 0
    for ch in col:
        i = i * 26 + (ord(ch) - ord("A") + 1)
    return i


def _idx_to_col(i: int) -> str:
    out = ""
    while i:
        i, rem = divmod(i - 1, 26)
        out = chr(ord("A") + rem) + out
    return out


def _split_ref(ref: str) -> Tuple[str, int]:
    m = re.match(r"\$?([A-Z]+)\$?(\d+)$", ref)
    if not m:
        raise ValueError(f"bad cell ref {ref!r}")
    return m.group(1), int(m.group(2))


class _Workbook:
    """Values-only view over an .xlsx/.xlsm zip (context manager)."""

    def __init__(self, path: str) -> None:
        self.z = zipfile.ZipFile(path)
        self.strings = self._shared_strings()
        self.sheet_files = self._sheet_files()
        self._cells: Dict[str, Dict[Tuple[int, str], object]] = {}

    def close(self) -> None:
        self.z.close()

    def __enter__(self) -> "_Workbook":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _shared_strings(self) -> List[str]:
        try:
            data = self.z.read("xl/sharedStrings.xml")
        except KeyError:
            return []
        root = ET.parse(io.BytesIO(data)).getroot()
        return [
            "".join(t.text or "" for t in si.iter(f"{_NS}t"))
            for si in root.findall(f"{_NS}si")
        ]

    def _sheet_files(self) -> Dict[str, str]:
        wb = ET.parse(io.BytesIO(self.z.read("xl/workbook.xml"))).getroot()
        rels = ET.parse(io.BytesIO(
            self.z.read("xl/_rels/workbook.xml.rels"))).getroot()
        targets = {
            rel.get("Id"): rel.get("Target")
            for rel in rels
        }
        out = {}
        for sh in wb.iter(f"{_NS}sheet"):
            t = targets.get(sh.get(f"{_NS_REL}id"))
            if t:
                out[sh.get("name")] = (
                    t if t.startswith("xl/") else f"xl/{t.lstrip('/')}")
        return out

    @staticmethod
    def _is_multi_area(target: str) -> bool:
        """True for multi-area targets ('Sheet1!$A$1,Sheet1!$B$2') —
        commas INSIDE a quoted sheet name ('Summary, FY24'!$A$1) are
        not area separators and must not trigger the skip."""
        in_quote = False
        for ch in target:
            if ch == "'":
                in_quote = not in_quote
            elif ch == "," and not in_quote:
                return True
        return False

    def defined_names(self) -> Dict[str, Tuple[str, str]]:
        """{name: (sheet, cell_range)}; broken (#REF!) and multi-area
        names (rsplit would mangle the sheet and a later lookup would
        KeyError) are skipped."""
        wb = ET.parse(io.BytesIO(self.z.read("xl/workbook.xml"))).getroot()
        out = {}
        for dn in wb.iter(f"{_NS}definedName"):
            target = (dn.text or "").strip()
            if ("#REF!" in target or "!" not in target
                    or self._is_multi_area(target)):
                continue
            sheet, ref = target.rsplit("!", 1)
            out[dn.get("name")] = (sheet.strip("'"), ref)
        return out

    def sheet_cells(self, sheet: str) -> Dict[Tuple[int, str], object]:
        """{(row, col): value} for one sheet, cached, values-only."""
        if sheet in self._cells:
            return self._cells[sheet]
        path = self.sheet_files[sheet]
        cells: Dict[Tuple[int, str], object] = {}
        for _, el in ET.iterparse(io.BytesIO(self.z.read(path))):
            if el.tag != f"{_NS}c":
                continue
            ref = el.get("r")
            if not ref:
                continue
            col, row = _split_ref(ref)
            v = el.find(f"{_NS}v")
            if v is None or v.text is None:
                el.clear()
                continue
            val: object = v.text
            t = el.get("t")
            if t == "s":
                val = self.strings[int(v.text)]
            elif t != "str":
                try:
                    f = float(v.text)
                    val = int(f) if f == int(f) else f
                except ValueError:
                    pass
            cells[(row, col)] = val
            el.clear()
        self._cells[sheet] = cells
        return cells

    def range_values(self, sheet: str, ref: str) -> List[List[object]]:
        """Rectangular values (rows of columns) for A1:B2-style refs."""
        if ":" in ref:
            tl, br = ref.split(":")
        else:
            tl = br = ref
        c0, r0 = _split_ref(tl)
        c1, r1 = _split_ref(br)
        cells = self.sheet_cells(sheet)
        return [
            [
                cells.get((r, _idx_to_col(ci)))
                for ci in range(_col_to_idx(c0), _col_to_idx(c1) + 1)
            ]
            for r in range(r0, r1 + 1)
        ]


@dataclasses.dataclass(frozen=True)
class WorkbookScenario:
    """Decoded 'Main - Scenario Options' contents."""

    options: Dict[str, object]        # label -> value (self-describing)
    selections: Dict[str, str]        # family key -> trajectory name
    agent_file: Optional[str]
    path: str

    @property
    def name(self) -> str:
        return str(self.options.get("Scenario Name", "workbook"))

    @property
    def end_year(self) -> int:
        return int(self.options.get("Analysis End Year", 2050))

    @property
    def storage_enabled(self) -> bool:
        return "storage" in str(self.options.get("Technology", "")).lower()

    @property
    def region(self) -> str:
        return str(self.options.get("Region to Analyze", "National")).strip()

    @property
    def markets(self) -> str:
        return str(self.options.get("Markets", "All")).strip()

    @property
    def seed(self) -> int:
        try:
            return int(self.options.get("Random Generator Seed", 0))
        except (TypeError, ValueError):
            return 0


def read_named_ranges(
    path: str, names: Optional[List[str]] = None, _wb=None
) -> Dict[str, object]:
    """{name: scalar or rows} for the workbook's defined names
    (single-cell ranges collapse to their value)."""
    ctx = _Workbook(path) if _wb is None else _nullcontext(_wb)
    with ctx as wb:
        dn = wb.defined_names()
        out: Dict[str, object] = {}
        for name, (sheet, ref) in dn.items():
            if names is not None and name not in names:
                continue
            rows = wb.range_values(sheet, ref)
            if len(rows) == 1 and len(rows[0]) == 1:
                out[name] = rows[0][0]
            else:
                out[name] = rows
        return out


def read_scenario(path: str, _wb=None) -> WorkbookScenario:
    """Decode the Main-sheet scenario options + the 14 run selectors.

    The options column (named range ``scenario_options_main``) is
    positionally defined in the reference's Postgres schema; here the
    sheet is self-describing — the label column sits immediately LEFT
    of the value column and the user-defined table column immediately
    RIGHT (input_sheet_final.xlsm layout C/D/E), so labels are read
    from the sheet rather than hard-coded.
    """
    ctx = _Workbook(path) if _wb is None else _nullcontext(_wb)
    with ctx as wb:
        return _read_scenario(wb, path)


def _read_scenario(wb: _Workbook, path: str) -> WorkbookScenario:
    dn = wb.defined_names()
    if "scenario_options_main" not in dn:
        raise ValueError(f"{path}: no scenario_options_main named range")
    sheet, ref = dn["scenario_options_main"]
    tl, br = (ref.split(":") + [ref])[:2]
    vcol, r0 = _split_ref(tl)
    _, r1 = _split_ref(br)
    lcol = _idx_to_col(_col_to_idx(vcol) - 1)
    cells = wb.sheet_cells(sheet)

    options: Dict[str, object] = {}
    for r in range(r0, r1 + 1):
        label = cells.get((r, lcol))
        if label is None:
            continue
        options[str(label).strip()] = cells.get((r, vcol))

    selections: Dict[str, str] = {}
    agent_file = None
    for range_name, family in SELECTOR_FAMILIES.items():
        if range_name not in dn:
            continue
        s_sheet, s_ref = dn[range_name]
        col, row = _split_ref(s_ref.split(":")[0])
        sc = wb.sheet_cells(s_sheet)
        # the named range points at the USER-table column; when that
        # cell is empty the scenario chose a named preset, which lives
        # one column left (the workbook's Value column)
        val = sc.get((row, col))
        if val is None or not str(val).strip():
            val = sc.get((row, _idx_to_col(_col_to_idx(col) - 1)))
        if val is not None and "user defined" in str(val).lower():
            val = sc.get((row, _idx_to_col(_col_to_idx(col) + 1)))
        if val is not None and str(val).strip():
            selections[family] = str(val).strip()
    if "agent_file_user_defined" in dn:
        s_sheet, s_ref = dn["agent_file_user_defined"]
        col, row = _split_ref(s_ref.split(":")[0])
        agent_file = wb.sheet_cells(s_sheet).get((row, col))
        if agent_file is not None:
            agent_file = str(agent_file)

    return WorkbookScenario(
        options=options, selections=selections,
        agent_file=agent_file, path=path,
    )


def resolve_states(region: str) -> Optional[List[str]]:
    """Workbook region string -> state list (None = national)."""
    r = region.strip().lower()
    if r in ("national", "united states", "usa", "us", ""):
        return None
    if r in ISO_STATES:
        return list(ISO_STATES[r])
    if r in US_STATE_ABBR:
        return [US_STATE_ABBR[r]]
    if len(region) == 2 and region.upper() in US_STATE_ABBR.values():
        return [region.upper()]
    raise ValueError(f"workbook region {region!r} not recognized")


def resolve_sector_weights(markets: str) -> Tuple[float, float, float]:
    m = markets.strip().lower()
    if "only residential" in m:
        return (1.0, 0.0, 0.0)
    if "only commercial" in m:
        return (0.0, 1.0, 0.0)
    if "only industrial" in m:
        return (0.0, 0.0, 1.0)
    return (0.7, 0.2, 0.1)


def scenario_from_workbook(path: str, start_year: int = 2014):
    """(ScenarioConfig, info) from a workbook: the bridge from the
    reference's input artifact to a runnable configuration.

    ``info`` carries states (None = national), sector_weights, seed,
    agent_file provenance, and ``prefer`` — the per-family trajectory
    selections consumed by io.reference_inputs (unmatched selections
    fall back to defaults there, mirroring how the reference treats a
    missing Postgres preset as an error the operator resolves)."""
    from dgen_tpu.config import ScenarioConfig

    ws = read_scenario(path)
    cfg = ScenarioConfig(
        name=re.sub(r"\W+", "_", ws.name).strip("_") or "workbook",
        start_year=start_year,
        end_year=max(ws.end_year, start_year + 2),
        storage_enabled=ws.storage_enabled,
        anchor_years=(),
    )
    info = {
        "states": resolve_states(ws.region),
        "sector_weights": resolve_sector_weights(ws.markets),
        "seed": ws.seed,
        "agent_file": ws.agent_file,
        "prefer": dict(ws.selections),
        "workbook": os.path.basename(path),
    }
    return cfg, info


def export_drop_ins(path: str, out_dir: str) -> Dict[str, str]:
    """Write the workbook's contents as plain files:
    scenario_options.csv (label,value), selections.json (per-family
    trajectory choices), and any rectangular named range from the run
    mapping as <name>.csv. Returns {artifact: path}."""
    import csv

    from dgen_tpu.resilience.atomic import atomic_write, atomic_write_json

    os.makedirs(out_dir, exist_ok=True)
    out: Dict[str, str] = {}
    with _Workbook(path) as wb:
        ws = _read_scenario(wb, path)

        opt_path = os.path.join(out_dir, "scenario_options.csv")

        def _write_options(tmp: str) -> None:
            with open(tmp, "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(["option", "value"])
                for k, v in ws.options.items():
                    w.writerow([k, "" if v is None else v])

        atomic_write(opt_path, _write_options)
        out["scenario_options"] = opt_path

        sel_path = os.path.join(out_dir, "selections.json")
        atomic_write_json(
            sel_path,
            {"selections": ws.selections, "agent_file": ws.agent_file,
             "workbook": os.path.basename(path)},
            indent=1,
        )
        out["selections"] = sel_path

        ranges = read_named_ranges(
            path, names=list(SELECTOR_FAMILIES) + ["scenario_options_main"],
            _wb=wb,
        )
        for name, val in ranges.items():
            if isinstance(val, list) and name != "scenario_options_main":
                p = os.path.join(out_dir, f"{name}.csv")

                def _write_range(tmp: str, rows=val) -> None:
                    with open(tmp, "w", newline="") as f:
                        w = csv.writer(f)
                        for row in rows:
                            w.writerow(
                                ["" if c is None else c for c in row])

                atomic_write(p, _write_range)
                out[name] = p
    return out
