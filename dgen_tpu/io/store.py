"""Python face of the native profile store (native/profile_store.cpp).

Large dense matrices — the 8760-hour load and solar-CF profile banks,
agent attribute blocks — live in flat DGPB1 binary files. Reads are one
``mmap`` in C++ (zero copy until first touch); CSV ingestion parses on
all cores once and persists the binary bank every later run reuses.
This replaces the reference's per-agent Postgres profile fetches
(reference agent_mutation/elec.py:508-558, its serial bottleneck per
SURVEY.md §7).

The shared library is built on demand with g++ (no pybind11 in this
environment — plain C ABI via ctypes). ``HAVE_NATIVE`` is False when no
compiler is available; the pure-NumPy fallbacks keep everything
working, just slower on ingest.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import ml_dtypes  # ships with jax; bf16 <-> numpy bridge
import numpy as np

#: DGPB1 dtype codes (header bytes [6:8)); bf16 banks (code 1) halve
#: the disk and mmap footprint of the 8760-hour profile banks and int8
#: quantized banks (code 2, per-row f32 scale sidecar appended after
#: the payload) quarter it — the at-rest companions of
#: RunConfig.bf16_banks / RunConfig.quant_banks
_CODE_TO_DTYPE = {
    0: np.dtype(np.float32),
    1: np.dtype(ml_dtypes.bfloat16),
    2: np.dtype(np.int8),
}
_DTYPE_TO_CODE = {v: k for k, v in _CODE_TO_DTYPE.items()}
_INT8_CODE = 2

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native",
                    "profile_store.cpp")
_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(_SRC)),
                         "libdgen_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False
HAVE_NATIVE = False

_MAGIC = b"DGPB1\x00"
_HEADER = 24


def _build() -> bool:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return False
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-pthread", "-o", _LIB_PATH, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed, HAVE_NATIVE
    if _lib is not None:
        return _lib
    if _load_failed:  # don't re-attempt a failing compile on every call
        return None
    src_ok = os.path.exists(_SRC)
    stale = (
        src_ok and os.path.exists(_LIB_PATH)
        and os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
    )
    if (not os.path.exists(_LIB_PATH) or stale) and not _build():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        _load_failed = True
        return None
    lib.dg_last_error.restype = ctypes.c_char_p
    lib.dg_store_write.restype = ctypes.c_int
    lib.dg_store_write.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
        ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.dg_store_write2.restype = ctypes.c_int
    lib.dg_store_write2.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
    ]
    lib.dg_store_dtype.restype = ctypes.c_int
    lib.dg_store_dtype.argtypes = [ctypes.c_void_p]
    lib.dg_store_scales.restype = ctypes.c_void_p
    lib.dg_store_scales.argtypes = [ctypes.c_void_p]
    lib.dg_store_open.restype = ctypes.c_void_p
    lib.dg_store_open.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dg_store_data.restype = ctypes.POINTER(ctypes.c_float)
    lib.dg_store_data.argtypes = [ctypes.c_void_p]
    lib.dg_store_close.argtypes = [ctypes.c_void_p]
    lib.dg_csv_shape.restype = ctypes.c_int
    lib.dg_csv_shape.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dg_csv_parse.restype = ctypes.c_int
    lib.dg_csv_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_int,
    ]
    _lib = lib
    HAVE_NATIVE = True
    return lib


def _err(lib) -> str:
    return lib.dg_last_error().decode()


def _resolve_dtype(data: np.ndarray, dtype: Optional[str]) -> np.dtype:
    if dtype is None:
        d = np.dtype(data.dtype)
        return d if d in _DTYPE_TO_CODE else np.dtype(np.float32)
    if dtype in ("f32", "float32"):
        return np.dtype(np.float32)
    if dtype in ("bf16", "bfloat16"):
        return np.dtype(ml_dtypes.bfloat16)
    if dtype in ("int8", "i8"):
        return np.dtype(np.int8)
    raise ValueError(
        f"unsupported bank dtype {dtype!r} (f32 | bf16 | int8)")


def write_bank(path: str, data: np.ndarray,
               dtype: Optional[str] = None,
               scales: Optional[np.ndarray] = None) -> None:
    """Persist a row-major matrix as a DGPB1 bank file.

    ``dtype``: None keeps the array's own dtype (f32 unless it is
    already bf16/int8); "bf16" converts on write — half the disk/mmap
    bytes at ~3 significant digits, the at-rest companion of
    ``RunConfig.bf16_banks``; "int8" quantizes on write (symmetric
    per-row codes + a f32 per-row scale sidecar appended after the
    payload — dtype code 2, the at-rest companion of
    ``RunConfig.quant_banks``); "f32" forces full precision.

    ``scales``: required when ``data`` is ALREADY int8 codes (the
    [rows] f32 dequant factors to persist alongside); ignored —
    derived by quantization — for float inputs written as "int8".
    """
    target = _resolve_dtype(np.asarray(data), dtype)
    if np.asarray(data).ndim != 2:
        raise ValueError("bank must be 2-D [rows, cols]")
    if target == np.dtype(np.int8):
        if np.asarray(data).dtype == np.int8:
            if scales is None:
                raise ValueError(
                    "int8 bank data needs its per-row f32 scales "
                    "(write_bank(..., scales=...))"
                )
            data = np.ascontiguousarray(data, dtype=np.int8)
            scales = np.ascontiguousarray(scales, dtype=np.float32)
        else:
            from dgen_tpu.models.agents import quantize_rows

            data, scales = quantize_rows(np.asarray(data))
        if scales.shape != (data.shape[0],):
            raise ValueError(
                f"scales must be [rows]={data.shape[0]}, "
                f"got {scales.shape}"
            )
        payload = data.tobytes() + scales.astype("<f4").tobytes()
    else:
        if scales is not None:
            raise ValueError("scales only apply to int8 banks")
        data = np.ascontiguousarray(data, dtype=target)
        payload = None
    code = _DTYPE_TO_CODE[np.dtype(target)]
    lib = _load()
    from dgen_tpu.resilience.atomic import atomic_write

    # both branches publish via atomic_write (temp sibling + one
    # os.replace): a bank file is a run artifact, and a killed
    # converter must not leave a truncated DGPB at the published path
    if lib is not None:
        # the native writer takes one contiguous body (payload, plus
        # the int8 scale sidecar when present)
        body = (
            np.frombuffer(payload, dtype=np.uint8)
            if payload is not None else data
        )

        def _write_native(tmp_path: str) -> None:
            rc = lib.dg_store_write2(
                tmp_path.encode(), body.ctypes.data_as(ctypes.c_void_p),
                data.shape[0], data.shape[1], code,
            )
            if rc != 0:
                raise IOError(f"native write failed: {_err(lib)}")

        atomic_write(path, _write_native)
        return

    def _write(tmp_path: str) -> None:
        with open(tmp_path, "wb") as f:
            f.write(_MAGIC)
            f.write(code.to_bytes(2, "little"))
            f.write(int(data.shape[0]).to_bytes(8, "little"))
            f.write(int(data.shape[1]).to_bytes(8, "little"))
            f.write(payload if payload is not None else data.tobytes())

    atomic_write(path, _write)


def read_bank_raw(path: str) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Load a DGPB1 bank in its STORED representation: (array, scales)
    with ``scales`` the [rows] f32 sidecar for int8 banks (dtype code
    2) and None otherwise. Native path: one mmap + zero-copy view
    (copied into owned arrays before the handle closes). This is the
    device-path loader — ``RunConfig.quant_banks`` runs consume the
    codes + scales directly."""
    lib = _load()
    if lib is not None:
        rows = ctypes.c_uint64()
        cols = ctypes.c_uint64()
        h = lib.dg_store_open(path.encode(), ctypes.byref(rows),
                              ctypes.byref(cols))
        if not h:
            raise IOError(f"native open failed: {_err(lib)}")
        try:
            dt = _CODE_TO_DTYPE[int(lib.dg_store_dtype(ctypes.c_void_p(h)))]
            ptr = lib.dg_store_data(ctypes.c_void_p(h))
            n = rows.value * cols.value
            buf = ctypes.cast(
                ptr, ctypes.POINTER(ctypes.c_uint8 * (n * dt.itemsize))
            ).contents
            arr = (
                np.frombuffer(buf, dtype=dt)
                .reshape(rows.value, cols.value).copy()
            )
            scales = None
            sptr = lib.dg_store_scales(ctypes.c_void_p(h))
            if sptr:
                sbuf = ctypes.cast(
                    sptr, ctypes.POINTER(ctypes.c_uint8 * (rows.value * 4))
                ).contents
                # bytewise copy: the sidecar starts right after an
                # arbitrary-length payload, so it is not 4-aligned
                scales = np.frombuffer(
                    bytes(sbuf), dtype="<f4"
                ).copy()
        finally:
            lib.dg_store_close(ctypes.c_void_p(h))
        return arr, scales
    with open(path, "rb") as f:
        head = f.read(_HEADER)
        if head[:6] != _MAGIC:
            raise IOError("bad magic (not a DGPB1 file)")
        code = int.from_bytes(head[6:8], "little")
        if code not in _CODE_TO_DTYPE:
            raise IOError(f"unsupported dtype code {code}")
        dt = _CODE_TO_DTYPE[code]
        rows = int.from_bytes(head[8:16], "little")
        cols = int.from_bytes(head[16:24], "little")
        data = np.frombuffer(f.read(rows * cols * dt.itemsize), dtype=dt)
        scales = None
        if code == _INT8_CODE:
            raw = f.read(rows * 4)
            if len(raw) != rows * 4:
                raise IOError("truncated int8 scale sidecar")
            scales = np.frombuffer(raw, dtype="<f4").copy()
    return data.reshape(rows, cols).copy(), scales


def read_bank(path: str) -> np.ndarray:
    """Load a DGPB1 bank in its stored dtype (f32 or bf16); int8
    quantized banks (dtype code 2) come back DEQUANTIZED to f32
    (``scale[row] * code``), so every float consumer keeps working —
    use :func:`read_bank_raw` for the codes + scale sidecar."""
    arr, scales = read_bank_raw(path)
    if scales is not None:
        return arr.astype(np.float32) * scales[:, None]
    return arr


def csv_to_bank(
    csv_path: str,
    bank_path: Optional[str] = None,
    skip_header: bool = True,
    skip_cols: int = 0,
    n_threads: int = 0,
) -> np.ndarray:
    """Parse a numeric CSV into an f32 matrix (all cores, native) and
    optionally persist it as a bank file.

    ``skip_cols`` drops leading id columns; ``n_threads=0`` uses every
    hardware thread.
    """
    lib = _load()
    if lib is not None:
        rows = ctypes.c_uint64()
        cols = ctypes.c_uint64()
        if lib.dg_csv_shape(csv_path.encode(), int(skip_header),
                            ctypes.byref(rows), ctypes.byref(cols)) != 0:
            raise IOError(f"csv shape scan failed: {_err(lib)}")
        out_cols = cols.value - skip_cols
        if out_cols <= 0:
            raise ValueError("skip_cols leaves no data columns")
        out = np.empty((rows.value, out_cols), dtype=np.float32)
        rc = lib.dg_csv_parse(
            csv_path.encode(), int(skip_header), skip_cols,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.value, out_cols, n_threads,
        )
        if rc != 0:
            raise IOError(f"csv parse failed: {_err(lib)}")
    else:
        usecols = None
        if skip_cols:
            # skip id columns BEFORE parsing (they may be non-numeric)
            with open(csv_path) as f:
                first = f.readline()
            n_cols = first.count(",") + 1
            if n_cols - skip_cols <= 0:
                raise ValueError("skip_cols leaves no data columns")
            usecols = range(skip_cols, n_cols)
        out = np.loadtxt(
            csv_path, delimiter=",", skiprows=1 if skip_header else 0,
            dtype=np.float32, ndmin=2, usecols=usecols,
        )
    if bank_path:
        write_bank(bank_path, out)
    return out


def bank_available() -> bool:
    """True when the native library is built/loadable."""
    return _load() is not None
