"""Per-year checkpoint / resume via orbax.

The reference checkpoints by pickling the full agent DataFrame every
model year (``agent_df_{year}.pkl``, reference dgen_model.py:459) and
exposes a ``resume_year`` CLI stub that nothing consumes
(utility_functions.py:318-355, SURVEY.md §5 — resume is vestigial
there). Here resume is real: the only cross-year state is the
:class:`~dgen_tpu.models.simulation.SimCarry` pytree (the
``market_last_year_df`` analogue), so a checkpoint is one small orbax
save per year and a restore is one restore + re-entering the year loop
at the right index.

Multi-host: carries are saved AS the (possibly globally-sharded)
jax.Arrays — orbax writes each process's addressable shards
collectively, so jax.distributed runs checkpoint without any host
gather of non-addressable data. Restoring onto a mesh passes the
target sharding (``restore_year(..., sharding=)``) so shards land
directly on their devices.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from dgen_tpu.models.simulation import SimCarry
from dgen_tpu.resilience.faults import fault_point
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


def scenario_dir(directory: str, scenario: Optional[str]) -> str:
    """Per-scenario checkpoint subdirectory of a sweep run: scenario
    ``s`` of a sweep under ``directory`` checkpoints into
    ``directory/scn=<s>/``, so a killed sweep resumes at (scenario,
    year) rather than restarting every scenario. ``None`` keeps the
    flat single-run layout."""
    if scenario is None:
        return directory
    return os.path.join(directory, f"scn={scenario}")


def member_dir(directory: str, member: int) -> str:
    """Per-ensemble-member checkpoint subdirectory: member ``m`` of a
    loop-mode ensemble (dgen_tpu.ensemble) checkpoints into
    ``directory/mem=<m>/``, so a killed ensemble resumes at (member,
    year) — the member-axis analogue of :func:`scenario_dir`."""
    return os.path.join(directory, f"mem={int(member):03d}")


def _mgr(directory: str) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(create=True, max_to_keep=None),
    )


class Writer:
    """Per-run checkpoint writer holding ONE orbax manager (creating a
    manager per save re-scans the directory and restarts worker threads
    every year). ``force=True`` overwrites an existing step — without
    it orbax silently skips the save and a later resume would restore
    stale carries from a previous run into the same directory.

    ``scenario`` selects the per-scenario subdirectory layout
    (:func:`scenario_dir`) used by sweep runs."""

    def __init__(self, directory: str, scenario: Optional[str] = None
                 ) -> None:
        self._mgr = _mgr(scenario_dir(directory, scenario))

    def save(self, year: int, carry: SimCarry) -> None:
        # resilience drill hook: a ``kill`` here models a process dying
        # mid-checkpoint — orbax's commit protocol must leave the
        # previous steps restorable and the torn one invisible
        fault_point("ckpt_save")
        if year in self._mgr.all_steps():
            # drop the stale step: this orbax version refuses to save
            # over an existing step (StepAlreadyExistsError) rather than
            # overwriting, and skipping would resurrect a previous
            # run's carry on resume
            self._mgr.delete(year)
        # leaves go in as live (possibly globally-sharded) jax.Arrays:
        # orbax persists each process's addressable shards, which is
        # what makes multi-host checkpointing work without a host fetch
        self._mgr.save(year, args=ocp.args.StandardSave(carry), force=True)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "Writer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def save_year(directory: str, year: int, carry: SimCarry,
              scenario: Optional[str] = None) -> None:
    """One-shot save (prefer :class:`Writer` inside run loops)."""
    with Writer(directory, scenario=scenario) as w:
        w.save(year, carry)


def latest_year(directory: str, scenario: Optional[str] = None
                ) -> Optional[int]:
    directory = scenario_dir(directory, scenario)
    if not os.path.isdir(directory):
        return None
    with _mgr(directory) as mgr:
        step = mgr.latest_step()
    return int(step) if step is not None else None


def valid_years(directory: str, scenario: Optional[str] = None
                ) -> list[int]:
    """Ascending committed checkpoint years of a run directory (orbax
    lists only steps whose commit completed — a killed mid-write save
    never appears here)."""
    directory = scenario_dir(directory, scenario)
    if not os.path.isdir(directory):
        return []
    with _mgr(directory) as mgr:
        steps = list(mgr.all_steps())
    return sorted(int(s) for s in steps)


def latest_valid_year(
    directory: str,
    n_agents: int,
    max_year: Optional[int] = None,
    sharding=None,
    scenario: Optional[str] = None,
    n_scenarios: Optional[int] = None,
) -> Optional[int]:
    """The newest checkpointed year that actually RESTORES (walking
    back past corrupt/torn steps), optionally capped at ``max_year`` —
    the supervisor passes the manifest's export frontier there so a
    resume never skips over years whose artifacts are missing.
    ``None`` when nothing restorable exists.

    Each candidate is validated by a full restore (a try-restore is
    the only check orbax guarantees), and the caller's own resume then
    restores the chosen year again — two restores of a small carry on
    the rare recovery path, traded for zero trust in metadata."""
    for y in reversed(valid_years(directory, scenario=scenario)):
        if max_year is not None and y > max_year:
            continue
        try:
            restore_year(
                directory, n_agents, y, sharding=sharding,
                scenario=scenario, n_scenarios=n_scenarios,
            )
        except Exception as e:  # noqa: BLE001 — any failure = not valid
            logger.warning(
                "checkpoint year %d under %s does not restore (%r); "
                "walking back", y, directory, e,
            )
            continue
        return y
    return None


def restore_year(
    directory: str,
    n_agents: int,
    year: Optional[int] = None,
    sharding=None,
    scenario: Optional[str] = None,
    n_scenarios: Optional[int] = None,
) -> Tuple[int, SimCarry]:
    """(year, carry) for ``year`` (default: latest checkpointed year).

    ``sharding``: a jax Sharding to restore each leaf onto (pass the
    run's agent-axis NamedSharding for mesh/multi-host runs — shards
    are read straight to their devices, no full-array host copy).
    ``scenario`` reads a sweep's per-scenario subdirectory;
    ``n_scenarios`` restores a STACKED carry (every leaf ``[S, ...]``
    — the sweep engine's vmapped lockstep checkpoint).
    """
    directory = scenario_dir(directory, scenario)
    with _mgr(directory) as mgr:
        step = year if year is not None else mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        zeros = SimCarry.zeros(n_agents)
        if n_scenarios is not None:
            zeros = jax.tree.map(
                lambda x: jax.numpy.broadcast_to(
                    x, (n_scenarios,) + x.shape
                ),
                zeros,
            )
        if sharding is not None:
            leaf_sharding = sharding
            if n_scenarios is not None:
                # a stacked carry prepends the scenario axis, so the
                # caller's agent-axis spec must shift one dim right
                # (scenario axis replicated) or it would partition
                # scenarios across the agent mesh axis
                from jax.sharding import NamedSharding, PartitionSpec

                if not isinstance(sharding, NamedSharding):
                    raise TypeError(
                        "restore_year(n_scenarios=..., sharding=...) "
                        "requires a NamedSharding so the agent-axis "
                        "spec can shift past the leading scenario axis"
                    )
                leaf_sharding = NamedSharding(
                    sharding.mesh, PartitionSpec(None, *sharding.spec)
                )
            template = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=leaf_sharding
                ),
                zeros,
            )
        else:
            template = jax.tree.map(np.asarray, zeros)
        restored = mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
    carry = jax.tree.map(jax.numpy.asarray, restored)
    return int(step), carry
