"""Trajectory/market-data ingest from the reference's input_data CSV
formats into :class:`ScenarioInputs` arrays.

The reference ingests these CSVs into Postgres tables and merges them
onto the agent frame per year (input_data_functions.py:215
``import_table`` + the shapers at :272-560). Here each loader parses the
same on-disk schema directly to dense [year, ...] arrays on the model
year grid (nearest-year forward fill past the trajectory's end).

Supported formats (all observed under reference dgen_os/input_data/):
  * "stacked sector" files: ``year,<field>_res,<field>_com,<field>_ind``
    (pv_prices, pv_tech_performance, batt_prices via res/nonres,
    financing_terms via res/nonres).
  * load_growth: ``year,load_growth_res,load_growth_com,load_growth_ind,
    census_division_abbr``.
  * elec_prices: ``ba,year,elec_price_res,elec_price_com,elec_price_ind``.
  * observed deployment: ``state_abbr,sector_abbr,year,observed_solar_mw,...``.
  * attachment rates: ``state_abbr,metric,q2_24,...`` paired
    attachment_rate / install_volume rows (attachment_rate_functions.py:7).
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from dgen_tpu.config import (
    BASS_DEFAULTS,
    PAYBACK_GRID_N,
    PAYBACK_GRID_STEP,
    SECTORS,
)
from dgen_tpu.resilience.faults import fault_point


def _read_csv(path: str) -> List[Dict[str, str]]:
    # resilience drill hook: a transient input-read failure (network
    # filesystem flake) — retryable by the supervisor, never fatal
    fault_point("ingest", path=path)
    with open(path, newline="", encoding="utf-8-sig") as f:
        return list(csv.DictReader(f))


def _year_grid_interp(years_avail: np.ndarray, values: np.ndarray,
                      model_years: Sequence[int]) -> np.ndarray:
    """Sample a [Ya, ...] trajectory onto the model-year grid with
    nearest-neighbor-in-past semantics (forward fill; clamp at ends)."""
    out = []
    for y in model_years:
        i = int(np.searchsorted(years_avail, y, side="right")) - 1
        i = max(0, min(i, len(years_avail) - 1))
        out.append(values[i])
    return np.asarray(out)


def load_stacked_sectors(
    path: str,
    field: str,
    model_years: Sequence[int],
    nonres_suffix: bool = False,
) -> np.ndarray:
    """[Y, 3] array for ``<field>_res/_com/_ind`` (or ``_res/_nonres``
    when ``nonres_suffix``, duplicated to com+ind as the reference's
    stacked_sectors shaper does for batt prices / financing)."""
    rows = _read_csv(path)
    years = np.asarray([int(float(r["year"])) for r in rows])
    if nonres_suffix:
        cols = [f"{field}_res", f"{field}_nonres", f"{field}_nonres"]
    else:
        cols = [f"{field}_{s}" for s in SECTORS]
    vals = np.asarray([[float(r[c]) for c in cols] for r in rows], dtype=np.float32)
    order = np.argsort(years)
    return _year_grid_interp(years[order], vals[order], model_years).astype(np.float32)


def load_batt_tech(path: str, model_years: Sequence[int]) -> Dict[str, np.ndarray]:
    """batt_tech_performance CSV -> {"batt_eff": [Y, 3],
    "batt_lifetime_yrs": [Y, 3]} (columns ``batt_eff_res/com/ind`` +
    ``batt_lifetime_yrs_*``; reference apply_batt_tech_performance,
    agent_mutation/elec.py:319)."""
    return {
        "batt_eff": load_stacked_sectors(path, "batt_eff", model_years),
        "batt_lifetime_yrs": load_stacked_sectors(
            path, "batt_lifetime_yrs", model_years),
    }


def load_depreciation_schedules(
    path: str, model_years: Sequence[int], n_frac: int = 6
) -> np.ndarray:
    """depreciation_schedules CSV -> [Y, 3, D] fractions.

    Reference shape: one row per (year, sector_abbr) with columns
    ``1..D`` (agent_mutation/elec.py:157 ``apply_depreciation_schedule``
    merges the resulting list per agent). Sectors absent from the file
    (typically res) take the com schedule — depreciation only reaches
    non-commercial agents through ``is_commercial`` gating anyway.
    """
    rows = _read_csv(path)
    frac_cols = [str(i) for i in range(1, n_frac + 1)]
    by_sector: Dict[str, Dict[int, np.ndarray]] = {}
    for r in rows:
        sec = r.get("sector_abbr", "com")
        vals = np.asarray([float(r.get(c, 0.0) or 0.0) for c in frac_cols],
                          dtype=np.float32)
        by_sector.setdefault(sec, {})[int(float(r["year"]))] = vals
    if not by_sector:
        raise ValueError(f"no depreciation schedule rows in {path}")
    fallback = by_sector.get("com") or next(iter(by_sector.values()))
    out = np.zeros((len(model_years), len(SECTORS), n_frac), np.float32)
    for si, sec in enumerate(SECTORS):
        sched = by_sector.get(sec, fallback)
        years_avail = np.asarray(sorted(sched))
        vals = np.stack([sched[y] for y in sorted(sched)])
        out[:, si, :] = _year_grid_interp(years_avail, vals, model_years)
    # every schedule must distribute ~the full basis or none of it (the
    # reference ships all-zero res rows = no depreciation); files in
    # other semantics (e.g. the reference's deprec_sch_FY24.csv rows are
    # remaining-basis factors summing to ~4.9) would silently multiply
    # depreciation several-fold
    sums = out.sum(axis=-1)
    bad = (np.abs(sums - 1.0) > 0.05) & (np.abs(sums) > 0.05)
    if np.any(bad):
        raise ValueError(
            f"depreciation schedule rows in {path} sum to "
            f"{float(sums[bad].min()):.3f}..{float(sums[bad].max()):.3f}, "
            "expected ~1.0 (year-fraction schedule) or 0 (no "
            "depreciation); refusing to ingest"
        )
    return out


def load_carbon_intensities(
    path: str, model_years: Sequence[int], states: Sequence[str]
) -> np.ndarray:
    """carbon_intensities CSV (state_abbr + one column per year,
    tCO2/kWh) -> [Y, n_states] (reference apply_carbon_intensities,
    agent_mutation/elec.py:595, ingested via melt_year at
    dgen_model.py:215-216)."""
    rows = _read_csv(path)
    st_idx = {s: i for i, s in enumerate(states)}
    out = np.zeros((len(model_years), len(states)), np.float32)
    seen = set()
    for r in rows:
        s = r.get("state_abbr", "")
        if s not in st_idx:
            continue
        seen.add(s)
        year_cols = sorted(int(c) for c in r.keys() if c.isdigit())
        years_avail = np.asarray(year_cols)
        vals = np.asarray([float(r[str(y)]) for y in year_cols], np.float32)
        out[:, st_idx[s]] = _year_grid_interp(years_avail, vals, model_years)
    missing = [s for s in states if s not in seen]
    if missing:
        # the reference's left-merge would surface these as NaN
        # (elec.py:595); here they stay 0 — say so instead of silently
        # zeroing the emissions output
        import logging

        logging.getLogger("dgen_tpu").warning(
            "carbon_intensities: no rows for states %s (intensity 0)",
            missing,
        )
    return out


def load_financing_terms(path: str, model_years: Sequence[int]) -> Dict[str, np.ndarray]:
    """financing_terms CSV -> dict of [Y, 3] arrays (+ economic lifetime)."""
    out = {}
    for field in ("loan_term_yrs", "loan_interest_rate", "down_payment_fraction",
                  "real_discount_rate", "tax_rate"):
        out[field] = load_stacked_sectors(path, field, model_years, nonres_suffix=True)
    rows = _read_csv(path)
    out["economic_lifetime_yrs"] = int(float(rows[0]["economic_lifetime_yrs"]))
    return out


def load_load_growth(
    path: str,
    model_years: Sequence[int],
    regions: Sequence[str],
) -> np.ndarray:
    """load_growth CSV -> [Y, R, 3] multiplier array.

    The reference stores growth as a delta vs the base year per census
    division x sector; multiplier = 1 + growth.
    """
    rows = _read_csv(path)
    region_idx = {r: i for i, r in enumerate(regions)}
    by_region: Dict[int, Dict[int, List[float]]] = {}
    for r in rows:
        reg = r.get("census_division_abbr", "")
        if reg not in region_idx:
            continue
        y = int(float(r["year"]))
        by_region.setdefault(region_idx[reg], {})[y] = [
            1.0 + float(r[f"load_growth_{s}"]) for s in SECTORS
        ]
    Y, R, S = len(model_years), len(regions), len(SECTORS)
    out = np.ones((Y, R, S), dtype=np.float32)
    for reg_i, by_year in by_region.items():
        ys = np.asarray(sorted(by_year))
        vals = np.asarray([by_year[y] for y in ys], dtype=np.float32)
        out[:, reg_i, :] = _year_grid_interp(ys, vals, model_years)
    return out


def load_elec_prices(
    path: str,
    model_years: Sequence[int],
    bas: Sequence[str],
    base_year: Optional[int] = None,
) -> np.ndarray:
    """elec_prices CSV -> [Y, R, 3] retail price multiplier vs the base
    year (reference input_data_functions.py:450
    ``process_elec_price_trajectories`` normalizes to the 2016-equivalent
    base)."""
    rows = _read_csv(path)
    ba_idx = {b: i for i, b in enumerate(bas)}
    by_ba: Dict[int, Dict[int, List[float]]] = {}
    for r in rows:
        ba = r.get("ba", "")
        if ba not in ba_idx:
            continue
        y = int(float(r["year"]))
        by_ba.setdefault(ba_idx[ba], {})[y] = [
            float(r[f"elec_price_{s}"]) for s in SECTORS
        ]
    Y, R, S = len(model_years), len(bas), len(SECTORS)
    out = np.ones((Y, R, S), dtype=np.float32)
    for ba_i, by_year in by_ba.items():
        ys = np.asarray(sorted(by_year))
        vals = np.asarray([by_year[y] for y in ys], dtype=np.float32)
        b_year = base_year or int(ys[0])
        base = by_year.get(b_year, vals[0].tolist())
        traj = _year_grid_interp(ys, vals, model_years)
        out[:, ba_i, :] = traj / np.maximum(np.asarray(base, np.float32), 1e-9)
    return out


def load_observed_deployment(
    path: str,
    model_years: Sequence[int],
    states: Sequence[str],
) -> np.ndarray:
    """observed_deployment CSV -> [Y, G] cumulative observed PV kW,
    G = state x sector groups (reference
    diffusion_functions_elec.py:115-122 consumes observed_solar_mw)."""
    rows = _read_csv(path)
    st_idx = {s: i for i, s in enumerate(states)}
    sec_idx = {s: i for i, s in enumerate(SECTORS)}
    Y = len(model_years)
    G = len(states) * len(SECTORS)
    out = np.zeros((Y, G), dtype=np.float32)
    year_pos = {y: i for i, y in enumerate(model_years)}
    for r in rows:
        st = r.get("state_abbr", "")
        sec = r.get("sector_abbr", "")
        y = int(float(r["year"]))
        if st not in st_idx or sec not in sec_idx or y not in year_pos:
            continue
        g = st_idx[st] * len(SECTORS) + sec_idx[sec]
        out[year_pos[y], g] = float(r["observed_solar_mw"]) * 1000.0
    return out


def load_attachment_rates(path: str, states: Sequence[str]) -> np.ndarray:
    """ohm_attachment_rates CSV -> [n_states] install-volume-weighted
    average attachment rate (reference attachment_rate_functions.py:7-55).
    Falls back to the simple mean when volumes are missing/zero; clipped
    to [0, 1]; missing states get 0."""
    rows = _read_csv(path)
    qcols = [c for c in (rows[0].keys() if rows else []) if c.startswith("q")]
    rates: Dict[str, List[float]] = {}
    vols: Dict[str, List[float]] = {}
    for r in rows:
        st = r["state_abbr"].strip("﻿ ")
        vals = []
        for c in qcols:
            try:
                vals.append(float(r[c]))
            except (TypeError, ValueError):
                vals.append(np.nan)
        if r["metric"] == "attachment_rate":
            rates[st] = vals
        elif r["metric"] == "install_volume":
            vols[st] = vals
    out = np.zeros(len(states), dtype=np.float32)
    for i, st in enumerate(states):
        if st not in rates:
            continue
        rv = np.asarray(rates[st], dtype=float)
        wv = np.asarray(vols.get(st, [0.0] * len(rv)), dtype=float)
        wv = np.nan_to_num(wv)
        wsum = wv.sum()
        if wsum > 0:
            avg = np.nansum(rv * wv) / wsum
        else:
            avg = np.nanmean(rv)
        out[i] = float(np.clip(np.nan_to_num(avg), 0.0, 1.0))
    return out


def load_value_of_resiliency(path: str, states: Sequence[str]) -> np.ndarray:
    """value_of_resiliency CSV -> [G] $ per agent, G = state x sector.

    Schema per the reference's shipped ``vor_FY20_mid.csv``: one row per
    (state_abbr, sector_abbr) with ``value_of_resiliency_usd`` (merged
    onto agents by ``apply_value_of_resiliency``, agent_mutation/
    elec.py:287 — state+sector keyed, year-independent). Missing
    (state, sector) pairs stay 0 (the reference's left-merge NaN ->
    the kernel's no-VOR case; residential typically has no row)."""
    rows = _read_csv(path)
    st_idx = {s: i for i, s in enumerate(states)}
    sec_idx = {s: i for i, s in enumerate(SECTORS)}
    out = np.zeros(len(states) * len(SECTORS), dtype=np.float32)
    for r in rows:
        st, sec = r.get("state_abbr", ""), r.get("sector_abbr", "")
        if st in st_idx and sec in sec_idx:
            gi = st_idx[st] * len(SECTORS) + sec_idx[sec]
            out[gi] = float(r["value_of_resiliency_usd"])
    return out


def load_max_market_curves(path: str) -> np.ndarray:
    """max_market_curves CSV -> [S, PAYBACK_GRID_N] on the 0.1-yr grid.

    Schema mirrors the reference's ``max_market_curves_to_model`` view
    (data_functions.py:392-410): ``metric_value`` (payback years),
    ``sector_abbr``, ``max_market_share``, plus optional ``metric`` /
    ``business_model`` filters (kept: payback_period / host_owned, the
    rows the host-owned hot loop consumes). Curves are interpolated to
    tenths of a year and the 30.1 never-payback sentinel is pinned to
    exactly 0 (the reference's UNION ALL row, data_functions.py:399)."""
    rows = _read_csv(path)
    sec_idx = {s: i for i, s in enumerate(SECTORS)}
    pts: Dict[int, List[tuple]] = {i: [] for i in range(len(SECTORS))}
    for r in rows:
        if r.get("metric", "payback_period") != "payback_period":
            continue
        if r.get("business_model", "host_owned") != "host_owned":
            continue
        sec = r.get("sector_abbr", "")
        if sec not in sec_idx:
            continue
        pts[sec_idx[sec]].append(
            (float(r["metric_value"]), float(r["max_market_share"]))
        )
    grid = np.arange(PAYBACK_GRID_N, dtype=np.float64) * PAYBACK_GRID_STEP
    out = np.zeros((len(SECTORS), PAYBACK_GRID_N), dtype=np.float32)
    for si, p in pts.items():
        if not p:
            raise ValueError(
                f"{path}: no host_owned payback_period rows for sector "
                f"{SECTORS[si]!r}"
            )
        p.sort()
        xs = np.asarray([x for x, _ in p])
        ys = np.asarray([y for _, y in p])
        out[si] = np.interp(grid, xs, ys).astype(np.float32)
    out[:, -1] = 0.0  # the 30.1 sentinel row (data_functions.py:399-410)
    return out


def load_bass_params(
    path: str, states: Sequence[str],
    defaults: tuple = BASS_DEFAULTS,
) -> Dict[str, np.ndarray]:
    """bass_params CSV -> {"bass_p", "bass_q", "teq_yr1"} each [G].

    Schema mirrors the reference's ``input_solar_bass_params`` table
    (data_functions.py:300-306): state_abbr, p, q, teq_yr1, sector_abbr
    (+ optional ``tech``, filtered to solar when present). Groups with
    no row keep the synthetic defaults (and are reported by the caller
    via the returned ``missing`` count)."""
    rows = _read_csv(path)
    st_idx = {s: i for i, s in enumerate(states)}
    sec_idx = {s: i for i, s in enumerate(SECTORS)}
    g = len(states) * len(SECTORS)
    p = np.full(g, defaults[0], dtype=np.float32)
    q = np.full(g, defaults[1], dtype=np.float32)
    teq = np.full(g, defaults[2], dtype=np.float32)
    seen = np.zeros(g, dtype=bool)
    for r in rows:
        if r.get("tech", "solar") not in ("solar", ""):
            continue
        st, sec = r.get("state_abbr", ""), r.get("sector_abbr", "")
        if st not in st_idx or sec not in sec_idx:
            continue
        gi = st_idx[st] * len(SECTORS) + sec_idx[sec]
        p[gi] = float(r["p"])
        q[gi] = float(r["q"])
        teq[gi] = float(r["teq_yr1"])
        seen[gi] = True
    return {
        "bass_p": p, "bass_q": q, "teq_yr1": teq,
        "missing": int((~seen).sum()),
    }


def state_attachment_to_groups(per_state: np.ndarray, n_sectors: int = 3) -> np.ndarray:
    """[n_states] -> [G] by repeating across sectors (the reference
    merges the state-level rate onto every sector, dgen_model.py:408)."""
    return np.repeat(per_state, n_sectors).astype(np.float32)


def discover_reference_inputs(
    root: str, prefer: Optional[Dict[str, str]] = None
) -> Dict[str, str]:
    """Locate reference-format input files under an input_data directory.

    ``prefer`` maps family keys (pv_prices, elec_prices, financing, ...)
    to a filename substring — the scenario workbook's per-family
    trajectory selection (io.workbook) — which beats the built-in
    default substring; an unmatched preference falls back to the
    default rather than failing the whole ingest."""
    prefer = prefer or {}

    def first(sub: str, want: Optional[str]) -> Optional[str]:
        """Match ``want`` as a substring; None when unmatched (so the
        caller can chain fallbacks); ``want=None`` = alphabetical first."""
        d = os.path.join(root, sub)
        if not os.path.isdir(d):
            return None
        names = sorted(n for n in os.listdir(d) if n.endswith(".csv"))
        if not names:
            return None
        if want:
            for n in names:
                if want.lower() in n.lower():
                    return os.path.join(d, n)
            return None
        return os.path.join(d, names[0])

    out = {}
    for key, sub, default in (
        ("pv_prices", "pv_prices", "mid"),
        ("pv_tech", "pv_tech_performance", "FY19"),
        ("batt_prices", "batt_prices", "mid"),
        ("financing", "financing_terms", "FY19"),
        ("load_growth", "load_growth", None),
        ("elec_prices", "elec_prices", "Mid_Case"),
        ("batt_tech", "batt_tech_performance", "FY19"),
        ("deprec", "depreciation_schedules", "FY19"),
    ):
        p = (first(sub, prefer.get(key)) or first(sub, default)
             or first(sub, None))
        if p:
            out[key] = p
    for key, name in (
        ("observed", "observed_deployment_by_state_sector_2023.csv"),
        ("attachment", "ohm_attachment_rates.csv"),
    ):
        p = os.path.join(root, name)
        if os.path.exists(p):
            out[key] = p
    return out
