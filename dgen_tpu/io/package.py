"""Agent-package format: a self-contained on-disk population.

The reference distributes its population as an out-of-band pandas
pickle plus per-agent Postgres profile rows (reference
input_data_functions.py:389 ``import_agent_file``; agent generation is
unsupported in the OS release, :444). The TPU framework's equivalent is
a directory package:

    <pkg>/agents.parquet      per-agent attributes (one row per agent)
    <pkg>/load_profiles.dgpb  [L, 8760] normalized load shapes (store)
    <pkg>/solar_cf.dgpb       [S, 8760] PV CF profiles (store)
    <pkg>/wholesale.dgpb      [R, 8760] $/kWh sell-rate profiles
    <pkg>/tariffs.json        list of tariff spec dicts (ops.tariff)
    <pkg>/meta.json           states, n_states, format version

``save_population`` / ``load_population`` roundtrip the exact pytree
the Simulation consumes; a converter from the reference's pickle format
runs offline once (agents.parquet column names below mirror the
reference's agent columns where they exist).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np
import pandas as pd

from dgen_tpu.io import store
from dgen_tpu.models.agents import AgentTable, ProfileBank, build_agent_table
from dgen_tpu.ops.tariff import TariffBank, compile_tariffs
from dgen_tpu.utils.timing import fn_timer

FORMAT_VERSION = 1

#: agents.parquet schema (reference agent-pickle column analogue)
AGENT_COLUMNS = (
    "state_idx", "sector_idx", "region_idx", "tariff_idx",
    "tariff_switch_idx", "load_idx", "cf_idx", "customers_in_bin",
    "load_kwh_per_customer_in_bin", "developable_frac", "one_time_charge",
)

#: optional per-agent policy columns (absent in format-1 packages
#: written before the NEM machine / size-conditioned switch; defaults
#: from build_agent_table apply on load)
POLICY_COLUMNS = (
    "nem_kw_limit", "nem_first_year", "nem_sunset_year",
    "switch_min_kw", "switch_max_kw",
)


#: IncentiveParams leaves serialized as agents.parquet columns
#: (``<leaf>_<slot>`` for the two incentive slots); pbi_decay is
#: optional on load (absent in packages written before decay support)
INCENTIVE_LEAVES = (
    "cbi_usd_p_w", "cbi_max_usd", "ibi_frac", "ibi_max_usd",
    "pbi_usd_p_kwh", "pbi_years", "pbi_decay",
)


@dataclasses.dataclass(frozen=True)
class Population:
    table: AgentTable
    profiles: ProfileBank
    tariffs: TariffBank
    states: List[str]
    tariff_specs: List[dict]


@fn_timer()
def save_population(
    pkg_dir: str,
    table: AgentTable,
    profiles: ProfileBank,
    tariff_specs: Sequence[dict],
    states: Sequence[str],
    quant_banks: bool = False,
) -> None:
    """Write a population package (unpadded rows only).

    ``quant_banks`` writes the load/solar DGPB banks int8-quantized
    with per-row f32 scale sidecars (store dtype code 2, the at-rest
    companion of ``RunConfig.quant_banks``) — 4x smaller, dequantized
    transparently by :func:`load_population`; wholesale stays f32 (it
    is never quantized in HBM either).
    """
    os.makedirs(pkg_dir, exist_ok=True)
    keep = np.asarray(table.mask) > 0

    cols = {
        c: np.asarray(getattr(table, c))[keep]
        for c in AGENT_COLUMNS + POLICY_COLUMNS
    }
    for leaf in INCENTIVE_LEAVES:
        vals = np.asarray(getattr(table.incentives, leaf))[keep]  # [n, 2]
        for slot in range(vals.shape[1]):
            cols[f"{leaf}_{slot}"] = vals[:, slot]
    pd.DataFrame(cols).to_parquet(os.path.join(pkg_dir, "agents.parquet"))

    bank_dtype = "int8" if quant_banks else None
    store.write_bank(os.path.join(pkg_dir, "load_profiles.dgpb"),
                     np.asarray(profiles.load), dtype=bank_dtype)
    store.write_bank(os.path.join(pkg_dir, "solar_cf.dgpb"),
                     np.asarray(profiles.solar_cf), dtype=bank_dtype)
    store.write_bank(os.path.join(pkg_dir, "wholesale.dgpb"),
                     np.asarray(profiles.wholesale))

    def jsonable(spec: dict) -> dict:
        out = {}
        for k, v in spec.items():
            out[k] = v.tolist() if isinstance(v, np.ndarray) else v
        return out

    from dgen_tpu.resilience.atomic import atomic_write_json

    atomic_write_json(
        os.path.join(pkg_dir, "tariffs.json"),
        [jsonable(s) for s in tariff_specs],
    )
    atomic_write_json(
        os.path.join(pkg_dir, "meta.json"),
        {
            "format_version": FORMAT_VERSION,
            "states": list(states),
            "n_states": int(table.n_states),
            "n_agents": int(keep.sum()),
        },
    )


@fn_timer()
def load_population(pkg_dir: str, pad_multiple: int = 128) -> Population:
    """Load a package into the device pytrees the Simulation consumes."""
    with open(os.path.join(pkg_dir, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"package format {meta.get('format_version')} != {FORMAT_VERSION}"
        )

    df = pd.read_parquet(os.path.join(pkg_dir, "agents.parquet"))
    missing = set(AGENT_COLUMNS) - set(df.columns)
    if missing:
        raise ValueError(f"agents.parquet missing columns: {sorted(missing)}")

    incentives = None
    core = [l for l in INCENTIVE_LEAVES if l != "pbi_decay"]
    if all(f"{leaf}_0" in df.columns for leaf in core):
        from dgen_tpu.ops.cashflow import IncentiveParams

        def leaf(name, dtype):
            if f"{name}_0" not in df.columns:
                return None
            return np.stack(
                [df[f"{name}_0"].to_numpy(), df[f"{name}_1"].to_numpy()],
                axis=1,
            ).astype(dtype)

        incentives = IncentiveParams(
            cbi_usd_p_w=leaf("cbi_usd_p_w", np.float32),
            cbi_max_usd=leaf("cbi_max_usd", np.float32),
            ibi_frac=leaf("ibi_frac", np.float32),
            ibi_max_usd=leaf("ibi_max_usd", np.float32),
            pbi_usd_p_kwh=leaf("pbi_usd_p_kwh", np.float32),
            pbi_years=leaf("pbi_years", np.int32),
            pbi_decay=leaf("pbi_decay", np.float32),
        )

    policy = {
        c: df[c].to_numpy(np.float32)
        for c in POLICY_COLUMNS if c in df.columns
    }
    table = build_agent_table(
        incentives=incentives,
        **policy,
        state_idx=df["state_idx"].to_numpy(),
        sector_idx=df["sector_idx"].to_numpy(),
        region_idx=df["region_idx"].to_numpy(),
        tariff_idx=df["tariff_idx"].to_numpy(),
        tariff_switch_idx=df["tariff_switch_idx"].to_numpy(),
        one_time_charge=df["one_time_charge"].to_numpy(),
        load_idx=df["load_idx"].to_numpy(),
        cf_idx=df["cf_idx"].to_numpy(),
        customers_in_bin=df["customers_in_bin"].to_numpy(),
        load_kwh_per_customer_in_bin=df["load_kwh_per_customer_in_bin"].to_numpy(),
        developable_frac=df["developable_frac"].to_numpy(),
        n_states=int(meta["n_states"]),
        pad_multiple=pad_multiple,
    )
    profiles = ProfileBank(
        load=jnp.asarray(store.read_bank(
            os.path.join(pkg_dir, "load_profiles.dgpb"))),
        solar_cf=jnp.asarray(store.read_bank(
            os.path.join(pkg_dir, "solar_cf.dgpb"))),
        wholesale=jnp.asarray(store.read_bank(
            os.path.join(pkg_dir, "wholesale.dgpb"))),
    )
    with open(os.path.join(pkg_dir, "tariffs.json")) as f:
        specs = json.load(f)
    tariffs = compile_tariffs(specs)
    return Population(
        table=table, profiles=profiles, tariffs=tariffs,
        states=list(meta["states"]), tariff_specs=specs,
    )
