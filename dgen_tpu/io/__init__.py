"""Host-side I/O: trajectory ingest, synthetic population generation,
the binary columnar profile store, and checkpointing. Replaces the
reference's Postgres data plane (SURVEY.md §2.5) — nothing here runs on
the device path."""

from dgen_tpu.io import (  # noqa: F401
    checkpoint,
    export,
    ingest,
    package,
    reference_inputs,
    store,
    synth,
    workbook,
)
