"""Memory-mapped columnar tables: one binary blob + a JSON header.

The serving answer surface (:mod:`dgen_tpu.serve.surface`) needs a
read-path with three properties the parquet exporter cannot give it:

* **zero-deserialization reads** — a replica answering the default
  question must index straight into page-cache-backed memory, not
  decode a column chunk per request;
* **one physical copy per machine** — N replica processes mmap the
  same file, so the kernel's page cache shares the bytes (the same
  cross-process-sharing argument as ``utils/compilecache.py``);
* **crash-consistent, content-hashed publication** — a surface is a
  run artifact like any other: temp+rename writes
  (:mod:`dgen_tpu.resilience.atomic`), per-column sha256 in the
  header, and a verify path that names truncation or tamper.

Layout on disk (a directory)::

    <dir>/table.bin    column blobs, back to back, 64-byte aligned
    <dir>/table.json   header: format tag, per-column dtype/shape/
                       offset/nbytes/sha256, content hash, user meta

The header is written LAST: a killed writer leaves a bin without a
header (refused as missing), never a header naming bytes that are not
there.  ``content_hash`` is a sha256 over the ordered per-column
hashes, so two tables with identical columns hash identically
regardless of write order or user meta.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Mapping, Optional

import numpy as np

from dgen_tpu.resilience.atomic import atomic_write, atomic_write_json

FORMAT = "dgen-mmap-table-v1"

_BIN = "table.bin"
_HEADER = "table.json"

#: column blobs start on 64-byte boundaries (cache-line / SIMD
#: friendly, and keeps any future dtype alignment-safe)
_ALIGN = 64


class MmapTableError(RuntimeError):
    """A table directory is missing, malformed, truncated, or fails
    its content-hash verification; the message names the reason."""


def _aligned(offset: int) -> int:
    return (offset + _ALIGN - 1) // _ALIGN * _ALIGN


def write_table(
    dir_path: str,
    columns: Mapping[str, np.ndarray],
    meta: Optional[dict] = None,
) -> dict:
    """Persist ``columns`` (name -> ndarray, any shapes/dtypes) as a
    memory-mappable table at ``dir_path``; returns the written header.

    Both files land via temp+rename; the header lands last and is the
    commit point.  ``meta`` rides in the header verbatim (the answer
    surface keeps its provenance stamp there).
    """
    if not columns:
        raise ValueError("write_table: no columns")
    os.makedirs(dir_path, exist_ok=True)
    cols = {}
    offset = 0
    order = list(columns)
    blobs = []
    for name in order:
        arr = np.ascontiguousarray(columns[name])
        blob = arr.tobytes()
        offset = _aligned(offset)
        cols[name] = {
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "offset": offset,
            "nbytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(),
        }
        blobs.append((offset, blob))
        offset += len(blob)

    def _write_bin(tmp: str) -> None:
        with open(tmp, "wb") as f:
            for off, blob in blobs:
                f.seek(off)
                f.write(blob)

    atomic_write(os.path.join(dir_path, _BIN), _write_bin)
    content = hashlib.sha256(
        "".join(cols[n]["sha256"] for n in order).encode()
    ).hexdigest()
    header = {
        "format": FORMAT,
        "columns": cols,
        "column_order": order,
        "content_hash": content,
        "total_bytes": offset,
        "meta": dict(meta or {}),
    }
    atomic_write_json(os.path.join(dir_path, _HEADER), header)
    return header


class MmapTable:
    """Read-only view over a written table: ``columns[name]`` is a
    zero-copy ndarray view into one shared ``np.memmap``.

    Construction validates the header shape and that the bin holds
    every byte the header names (truncation check); :meth:`verify`
    additionally re-hashes the blobs (tamper check).
    """

    def __init__(self, dir_path: str) -> None:
        self.dir = dir_path
        hpath = os.path.join(dir_path, _HEADER)
        bpath = os.path.join(dir_path, _BIN)
        if not os.path.isfile(hpath):
            raise MmapTableError(f"missing header {hpath}")
        if not os.path.isfile(bpath):
            raise MmapTableError(f"missing data file {bpath}")
        try:
            with open(hpath) as f:
                self.header = json.load(f)
        except (OSError, ValueError) as e:
            raise MmapTableError(f"unreadable header {hpath}: {e}") from e
        if self.header.get("format") != FORMAT:
            raise MmapTableError(
                f"unknown table format {self.header.get('format')!r} "
                f"(expected {FORMAT})"
            )
        size = os.path.getsize(bpath)
        need = max(
            (c["offset"] + c["nbytes"]
             for c in self.header["columns"].values()),
            default=0,
        )
        if size < need:
            raise MmapTableError(
                f"{bpath} truncated: {size} bytes on disk, header "
                f"names {need}"
            )
        self._mm = np.memmap(bpath, dtype=np.uint8, mode="r")
        self.columns: Dict[str, np.ndarray] = {}
        for name, c in self.header["columns"].items():
            raw = self._mm[c["offset"]:c["offset"] + c["nbytes"]]
            try:
                self.columns[name] = raw.view(
                    np.dtype(c["dtype"])).reshape(tuple(c["shape"]))
            except (TypeError, ValueError) as e:
                # a damaged header (garbage dtype, shape/nbytes
                # mismatch) is the same verdict as a damaged blob:
                # refused with the reason named, never a raw ValueError
                raise MmapTableError(
                    f"column '{name}' header is invalid "
                    f"(dtype={c['dtype']!r}, shape={c['shape']!r}): {e}"
                ) from e

    @property
    def meta(self) -> dict:
        return self.header.get("meta", {})

    @property
    def content_hash(self) -> str:
        return self.header["content_hash"]

    def verify(self) -> None:
        """Re-hash every column blob against the header (the deep
        check ``resilience verify`` runs on other artifacts); raises
        :class:`MmapTableError` naming the first mismatching column."""
        for name, c in self.header["columns"].items():
            raw = self._mm[c["offset"]:c["offset"] + c["nbytes"]]
            got = hashlib.sha256(raw.tobytes()).hexdigest()
            if got != c["sha256"]:
                raise MmapTableError(
                    f"column '{name}' content hash mismatch (on-disk "
                    f"{got[:12]} != header {c['sha256'][:12]}): the "
                    "table bytes were damaged after publication"
                )

    def close(self) -> None:
        # np.memmap holds the fd via its base mmap; dropping refs is
        # enough, but an explicit close keeps teardown deterministic
        self.columns = {}
        self._mm = None
