"""Synthetic population + profile generation.

The reference consumes a pre-generated national agent pickle that is
distributed out-of-band (agent generation is explicitly unsupported in
the OS release, reference input_data_functions.py:444) plus per-agent
8760 profiles from Postgres. Neither ships with the repo, so the
framework includes a deterministic synthetic generator producing
populations with the same statistical shape: state x sector bins of
customer clusters, archetypal hourly load shapes, latitude-graded solar
capacity-factor profiles, and a TOU/flat tariff mix.

Used by tests, benchmarks, and the quickstart; real agent dumps load
through dgen_tpu.io.store / ingest instead. Pod-scale (1M/10M-row)
worlds come from :mod:`dgen_tpu.models.synth` — a chunk-deterministic,
state-stratified generator that reuses this module's profile/tariff
corpora (docs/userguide.md "National-scale synthetic runs").
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

from dgen_tpu.config import SECTORS, ScenarioConfig
from dgen_tpu.models.agents import AgentTable, ProfileBank, build_agent_table
from dgen_tpu.ops.tariff import HOURS, NET_BILLING, NET_METERING, TariffBank, compile_tariffs

import jax.numpy as jnp

#: contiguous-US state abbreviations + DC (the reference's modeling
#: universe, states.csv)
STATES = (
    "AL AR AZ CA CO CT DC DE FL GA IA ID IL IN KS KY LA MA MD ME MI MN MO MS "
    "MT NC ND NE NH NJ NM NV NY OH OK OR PA RI SC SD TN TX UT VA VT WA WI WV WY"
).split()
STATE_IDX = {s: i for i, s in enumerate(STATES)}
N_STATES = len(STATES)


def _daily_shape(kind: str) -> np.ndarray:
    h = np.arange(24)
    if kind == "res":
        # morning + evening peaks
        shape = (
            0.6
            + 0.5 * np.exp(-0.5 * ((h - 7.5) / 1.8) ** 2)
            + 1.0 * np.exp(-0.5 * ((h - 19.0) / 2.5) ** 2)
        )
    elif kind == "com":
        # business-hours plateau
        shape = 0.5 + 1.0 / (1.0 + np.exp(-(h - 8.0))) / (1.0 + np.exp(h - 18.0))
    else:
        shape = np.ones(24)
    return shape / shape.sum()


def make_load_profiles(n_per_sector: int = 4, seed: int = 0) -> np.ndarray:
    """[3 * n_per_sector, 8760] normalized (sum=1) load shapes; profile
    index layout: sector-major (res block, com block, ind block)."""
    rng = np.random.default_rng(seed)
    day = np.arange(HOURS) // 24
    seasonal_summer = 1.0 + 0.35 * np.cos(2 * np.pi * (day - 200) / 365.0)
    seasonal_winter = 1.0 + 0.35 * np.cos(2 * np.pi * (day - 20) / 365.0)

    profiles = []
    for s, kind in enumerate(SECTORS):
        base_day = _daily_shape(kind)
        for k in range(n_per_sector):
            jitter = 1.0 + 0.1 * rng.standard_normal(24)
            d = np.clip(base_day * jitter, 1e-4, None)
            d = d / d.sum()
            season = seasonal_summer if k % 2 == 0 else seasonal_winter  # [8760]
            prof = np.tile(d, 365) * season
            prof = np.clip(prof, 1e-9, None)
            profiles.append(prof / prof.sum())
    return np.asarray(profiles, dtype=np.float32)


def make_solar_cf_profiles(n_profiles: int = 8, seed: int = 1) -> np.ndarray:
    """[n_profiles, 8760] PV kWh per kW_dc per hour; annual NAEP graded
    from ~1100 (northern) to ~1900 (southwest)."""
    rng = np.random.default_rng(seed)
    h = np.arange(HOURS)
    hod = h % 24
    day = h // 24
    day_len = 12.0 + 2.5 * np.sin(2 * np.pi * (day - 80) / 365.0)  # hours
    sunrise = 12.0 - day_len / 2
    sunset = 12.0 + day_len / 2
    daylight = (hod >= sunrise) & (hod <= sunset)
    bell = np.sin(np.pi * np.clip((hod - sunrise) / np.maximum(day_len, 1e-3), 0, 1))
    seasonal = 0.75 + 0.25 * np.sin(2 * np.pi * (day - 80) / 365.0)

    out = []
    for k in range(n_profiles):
        target_naep = 1100.0 + 800.0 * k / max(n_profiles - 1, 1)
        cloud = np.clip(1.0 - 0.3 * rng.random(365), 0.2, 1.0)[day]
        prof = np.where(daylight, bell, 0.0) * seasonal * cloud
        prof = prof * (target_naep / prof.sum())
        out.append(prof)
    return np.asarray(out, dtype=np.float32)


def make_wholesale_prices(n_regions: int, seed: int = 2) -> np.ndarray:
    """[R, 8760] $/kWh wholesale price shapes (duck-curve-ish)."""
    rng = np.random.default_rng(seed)
    hod = np.arange(HOURS) % 24
    base = 0.03 + 0.02 * np.exp(-0.5 * ((hod - 19) / 2.5) ** 2) - 0.012 * np.exp(
        -0.5 * ((hod - 13) / 2.5) ** 2
    )
    out = []
    for r in range(n_regions):
        scale = 0.8 + 0.4 * rng.random()
        out.append(np.clip(base * scale, 0.001, None))
    return np.asarray(out, dtype=np.float32)


def make_tariff_specs() -> list:
    """The synthetic tariff corpus as raw spec dicts (flat, tiered, TOU
    under both metering styles, plus a CA-NEM3-style TOU-sell tariff) —
    exposed separately so populations can be packaged with their tariff
    definitions (io.package)."""
    specs = []
    # 0: flat NEM
    specs.append({"price": [[0.12]], "fixed_charge": 10.0, "metering": NET_METERING})
    # 1: flat net billing
    specs.append({"price": [[0.13]], "fixed_charge": 8.0, "metering": NET_BILLING})
    # 2: 2-tier NEM (tier cap 500 kWh/month)
    specs.append({
        "price": [[0.10, 0.16]], "tier_cap": [500.0, 1e38],
        "fixed_charge": 12.0, "metering": NET_METERING,
    })
    # 3: TOU 2-period net billing (peak 16-21)
    wkday = np.zeros((12, 24), dtype=int)
    wkday[:, 16:21] = 1
    specs.append({
        "price": [[0.10], [0.24]], "e_wkday_12by24": wkday,
        "e_wkend_12by24": np.zeros((12, 24), dtype=int),
        "fixed_charge": 11.0, "metering": NET_BILLING,
    })
    # 4: CA-NEM3-style: TOU buy with sell = 0.25 x buy
    specs.append({
        "price": [[0.13], [0.32]], "e_wkday_12by24": wkday,
        "e_wkend_12by24": wkday, "fixed_charge": 9.0,
        "metering": NET_BILLING, "sell_frac_of_buy": 0.25,
    })
    # 5: commercial TOU NEM
    specs.append({
        "price": [[0.09], [0.18]], "e_wkday_12by24": wkday,
        "e_wkend_12by24": np.zeros((12, 24), dtype=int),
        "fixed_charge": 40.0, "metering": NET_METERING,
    })
    # 6: DG rate for post-adoption switching (reference
    # apply_rate_switch, agent_mutation/elec.py:838): NEM with a higher
    # fixed charge and slightly lower volumetric price
    specs.append({
        "price": [[0.115]], "fixed_charge": 18.0, "metering": NET_METERING,
    })
    return specs


def make_tariff_bank(seed: int = 3) -> TariffBank:
    """Compiled synthetic tariff corpus (see :func:`make_tariff_specs`)."""
    return compile_tariffs(make_tariff_specs())


@dataclasses.dataclass(frozen=True)
class SynthPopulation:
    table: AgentTable
    profiles: ProfileBank
    tariffs: TariffBank
    n_regions: int


def generate_population(
    n_agents: int,
    states: Optional[Sequence[str]] = None,
    seed: int = 0,
    pad_multiple: int = 128,
    sector_weights: Tuple[float, float, float] = (0.7, 0.2, 0.1),
    n_regions: int = 10,
    rate_switch_frac: float = 0.0,
) -> SynthPopulation:
    """Deterministic synthetic population over the given states.

    Agent attributes follow the reference's magnitudes: residential
    ~4-15 MWh/yr per customer, commercial ~30-400 MWh, industrial up to
    ~4 GWh; bin customer counts log-uniform; developable fraction in
    [0.2, 0.95].
    """
    states = list(states or STATES)
    rng = np.random.default_rng(seed)

    state_idx = rng.integers(0, len(states), n_agents)
    global_state_idx = np.asarray(
        [STATE_IDX[s] for s in states], dtype=np.int64
    )[state_idx]
    sector_idx = rng.choice(3, size=n_agents, p=np.asarray(sector_weights))

    load_profiles = make_load_profiles()
    cf_profiles = make_solar_cf_profiles()
    n_per_sector = load_profiles.shape[0] // 3
    load_idx = sector_idx * n_per_sector + rng.integers(0, n_per_sector, n_agents)
    # solar resource graded by state position (proxy for latitude)
    cf_idx = np.clip(
        ((global_state_idx * cf_profiles.shape[0]) // N_STATES
         + rng.integers(-1, 2, n_agents)),
        0, cf_profiles.shape[0] - 1,
    )
    region_idx = global_state_idx % n_regions

    load_kwh = np.where(
        sector_idx == 0,
        np.exp(rng.uniform(np.log(4e3), np.log(1.5e4), n_agents)),
        np.where(
            sector_idx == 1,
            np.exp(rng.uniform(np.log(3e4), np.log(4e5), n_agents)),
            np.exp(rng.uniform(np.log(4e5), np.log(4e6), n_agents)),
        ),
    )
    customers = np.exp(rng.uniform(np.log(50.0), np.log(5000.0), n_agents))
    developable = rng.uniform(0.2, 0.95, n_agents)

    tariffs = make_tariff_bank()
    # residential agents prefer tariffs 0-4; commercial 1/3/5; industrial 5
    tariff_idx = np.where(
        sector_idx == 0,
        rng.integers(0, 5, n_agents),
        np.where(sector_idx == 1, rng.choice([1, 3, 5], n_agents), 5),
    )

    # a fraction of residential agents switch to the DG rate (tariff 6)
    # on adoption, paying a one-time interconnection charge
    switch = (rng.random(n_agents) < rate_switch_frac) & (sector_idx == 0)
    tariff_switch_idx = np.where(switch, 6, tariff_idx)
    one_time_charge = np.where(
        switch, rng.uniform(100.0, 800.0, n_agents), 0.0
    ).astype(np.float32)

    table = build_agent_table(
        state_idx=global_state_idx,
        sector_idx=sector_idx,
        region_idx=region_idx,
        tariff_idx=tariff_idx,
        load_idx=load_idx,
        cf_idx=cf_idx,
        customers_in_bin=customers,
        load_kwh_per_customer_in_bin=load_kwh,
        developable_frac=developable,
        n_states=N_STATES,
        tariff_switch_idx=tariff_switch_idx,
        one_time_charge=one_time_charge,
        pad_multiple=pad_multiple,
    )
    profiles = ProfileBank(
        load=jnp.asarray(load_profiles),
        solar_cf=jnp.asarray(cf_profiles),
        wholesale=jnp.asarray(make_wholesale_prices(n_regions)),
    )
    return SynthPopulation(table=table, profiles=profiles, tariffs=tariffs,
                           n_regions=n_regions)
