"""Background host-IO pipeline: overlap exports, checkpoints and result
collection with device compute.

The year loop already pipelines device steps back to back — but only
when nothing on the host consumes the per-year outputs. Every
production path (``collect=True``, a :class:`~dgen_tpu.io.export.
RunExporter` callback, orbax checkpoints) used to flip the driver into
a fully serialized mode: block on year N, synchronous ``device_get``,
parquet writes, orbax save, then dispatch year N+1 — the per-step
host/dispatch overhead (~40% of wall through a remote tunnel) paid
every year, and exports ~half the full-run wall at 1M agents.

:class:`HostPipeline` takes every host consumer off the device critical
path, the async-checkpoint/prefetch shape of serious training stacks:

.. code-block:: text

    main thread   step N ── step N+1 ── step N+2 ── …   (dispatch only)
                     │ submit(N)
    fetch thread     └─> device_get(N)  ─> device_get(N+1) ─> …
                            │ (one batched D2H; GIL released)
    io thread               └─> collect ─ parquet ─ orbax   (ordered)

* The driver dispatches year N+1 immediately, then :meth:`HostPipeline.
  submit`\\ s year N.  ``submit`` runs each consumer's
  :meth:`~HostConsumer.device_payload` on the MAIN thread (dispatch-only
  device work — e.g. the exporter's int16 quantization — lands on the
  device queue right behind the step that produced the year) and never
  fetches.
* A **fetch stage** runs the single batched :func:`jax.device_get` of
  the year's payloads on a worker thread: the GIL is released during
  the D2H copy, so the main thread keeps dispatching.
* Ordered **downstream stages** consume the host arrays on a second
  worker thread: result collection, parquet writes, orbax saves.  Both
  stages are single-threaded executors, so years complete strictly in
  submission order.
* **Depth is bounded** (:func:`depth_for_bytes`, the same ~2 GB
  in-flight-``YearOutputs`` envelope the no-consumer pipelined path
  drains at): ``submit`` blocks when ``max_in_flight`` years are
  queued, which bounds both the live device buffers and the fetched
  host copies.
* **Worker exceptions surface** on the next ``submit`` or at
  :meth:`~HostPipeline.drain`, never silently.  A ``finally`` drain
  preserves the serialized path's crash semantics: the last completed
  year's export is flushed exactly once, and a year whose write failed
  partway is not re-written.

Donation/snapshot rule: the jitted year step donates the cross-year
carry, so its buffers die the moment year N+1 is dispatched.  Anything
the pipeline must read from the carry (checkpoint saves) is snapshotted
by the driver — a device-side ``jnp.copy`` tree, queued behind the
producing step — BEFORE the next dispatch, and the snapshot rides the
batched fetch.  ``YearOutputs`` leaves are not donated and need no
snapshot.

The serialized per-year path survives as the bit-exact parity oracle
behind ``RunConfig.async_host_io=False`` (env kill switch
``DGEN_TPU_ASYNC_IO=0``) and is still forced by ``debug_invariants``
and ``DGEN_TPU_PROFILE`` runs, which need per-year host sync anyway.
Multi-process (jax.distributed) runs ride the pipeline by default like
single-process ones — each process's pipeline only ever touches its own
addressable shards — except ``collect=True``, whose global-array
fetches always serialize.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from dgen_tpu.resilience.faults import fault_point
from dgen_tpu.utils import timing
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: in-flight per-year device/host bytes the pipeline depth is derived
#: from — the same envelope the no-consumer pipelined path's
#: ``sync_every`` drain model uses (models.simulation)
QUEUE_HBM_BYTES = int(2e9)


def depth_for_bytes(per_year_bytes: int,
                    budget: int = QUEUE_HBM_BYTES) -> int:
    """Max in-flight years for the pipeline: every queued year keeps its
    device ``YearOutputs`` buffers (until its fetch completes) and its
    fetched host copy (until its consumers finish) live, so depth x
    per-year bytes rides the same ~2 GB envelope the no-consumer path
    drains at.  Depth 1 still overlaps one full year: the driver
    dispatches year N+1 before submitting year N."""
    return max(1, int(budget // max(per_year_bytes, 1)))


def tree_bytes(tree) -> int:
    """Total leaf bytes of a pytree — the per-year unit both in-flight
    models (:func:`depth_for_bytes` here, the no-consumer path's
    ``sync_every`` in models.simulation) budget against."""
    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(tree))


def pipeline_for(consumers, outs, carry=None, *,
                 timing_ctx: Optional[str] = None,
                 pool: Optional["HostIOPool"] = None) -> "HostPipeline":
    """Build a :class:`HostPipeline` sized from the first executed
    year's outputs (every year is the same shape).  Pass ``carry`` when
    checkpointing: each queued year then also pins its carry snapshot
    (device copy + fetched host copy) until the save completes, so the
    depth budget must count it or checkpointed runs ride ~2x the
    documented in-flight envelope."""
    per_year = tree_bytes(outs)
    if carry is not None:
        per_year += tree_bytes(carry)
    return HostPipeline(
        consumers, max_in_flight=depth_for_bytes(per_year),
        timing_ctx=timing_ctx, pool=pool,
    )


def snapshot_carry(carry):
    """Device-side copy of the cross-year carry, queued behind the step
    that produced it — taken BEFORE the next dispatch, because the
    jitted year step donates the live carry's buffers (see the
    donation/snapshot rule in the module docstring)."""
    return jax.tree.map(jnp.copy, carry)


class HostIOPool:
    """The pipeline's two single-thread stages (fetch, io), shareable
    across pipelines: a sweep's per-scenario pipelines reuse one pair
    instead of spawning two threads per scenario."""

    def __init__(self) -> None:
        self.fetch = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dgen-hostio-fetch")
        self.io = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dgen-hostio-io")

    def close(self) -> None:
        self.fetch.shutdown(wait=True)
        self.io.shutdown(wait=True)


# ---------------------------------------------------------------------------
# Consumers
# ---------------------------------------------------------------------------
#
# A consumer implements:
#   name            payload key in the batched fetch
#   timer_name      utils.timing bucket its consume stage records under
#   needs_device    True -> consume() also receives the year's device
#                   ``outs`` (the pipeline then holds the device refs
#                   until the consume stage finishes)
#   device_payload(year, year_idx, outs, carry) -> pytree | None
#                   MAIN thread, dispatch-only: device arrays to ride
#                   the batched fetch (None = nothing to fetch)
#   consume(year, year_idx, host, outs)
#                   io thread, strictly ordered by submission
#   finalize(stats, failed)
#                   at drain (main thread), success or failure


class HealthConsumer:
    """The always-on numerical-health sentinel stage (models.health).

    The per-year fused summary reductions are dispatched at submit time
    (main thread, right behind the producing step) and the tiny [C, 2]
    verdict rides the batched fetch — zero extra host syncs, which is
    exactly why the sentinel works under the async pipeline while
    ``debug_invariants`` cannot.  Breaches are checked on the io thread
    BEFORE any export/checkpoint consumer runs (the driver lists this
    stage first), so a breached year is never flushed to parquet or
    marked complete in the manifest — the supervisor's resume frontier
    re-runs it after quarantining the attributed agents.

    Only the ATTRIBUTION leaves' device refs are stashed per queued
    year (pruned at consume), so attribution on the failure path never
    requires pinning the year's full ``YearOutputs`` — the pipeline's
    depth budget stays honest on HBM-tight configs."""

    name = "health"
    timer_name = "health_check"
    needs_device = False

    def __init__(self, mask, agent_ids_host, mask_host,
                 escalate: bool,
                 breaches_out: Optional[Dict[int, list]] = None) -> None:
        self._mask = mask                      # placed device mask
        self._agent_ids = agent_ids_host
        self._mask_host = mask_host
        self.escalate = bool(escalate)
        self.breaches = (
            breaches_out if breaches_out is not None else {}
        )
        self.years_checked = 0
        self._leaves: Dict[int, dict] = {}     # year_idx -> device refs

    def device_payload(self, year, year_idx, outs, carry):
        from dgen_tpu.models import health as health_mod

        self._leaves[int(year_idx)] = {
            name: getattr(outs, name)
            for name in sorted(health_mod.ATTRIBUTION_LEAVES)
        }
        return health_mod.health_summary(outs, self._mask)

    def consume(self, year, year_idx, host, outs) -> None:
        from dgen_tpu.models import health as health_mod

        self.years_checked += 1
        refs = self._leaves.pop(int(year_idx), None)
        b = health_mod.check_host(host)
        if not b:
            return
        self.breaches[int(year)] = b
        err = health_mod.breach_error(
            year, year_idx, b, refs, self._agent_ids, self._mask_host,
        )
        if self.escalate:
            raise err
        logger.warning("health sentinel: %s", err)

    def finalize(self, stats, failed) -> None:
        self._leaves.clear()


class CollectConsumer:
    """Result collection: the async analogue of the serialized loop's
    per-year batched ``device_get`` + append."""

    name = "collect"
    timer_name = "collect_host"
    needs_device = False

    def __init__(self, agent_fields: Sequence[str],
                 with_hourly: bool) -> None:
        self.agent_fields = list(agent_fields)
        self.with_hourly = with_hourly
        self.collected: Dict[str, list] = {k: [] for k in self.agent_fields}
        self.hourly: List[Any] = []

    def device_payload(self, year, year_idx, outs, carry):
        payload = {k: getattr(outs, k) for k in self.agent_fields}
        if self.with_hourly:
            payload["_hourly"] = outs.state_hourly_net_mw
        return payload

    def consume(self, year, year_idx, host, outs) -> None:
        for k in self.agent_fields:
            self.collected[k].append(host[k])
        if self.with_hourly:
            self.hourly.append(host["_hourly"])

    def finalize(self, stats, failed) -> None:
        pass


class ExportConsumer:
    """A :class:`~dgen_tpu.io.export.RunExporter` stage: quantization is
    dispatched at submit time (main thread, right behind the producing
    step — the old ``prepare()`` pre-dispatch contract), the batched
    fetch rides the pipeline's fetch stage, and only the parquet writes
    run here."""

    name = "export"
    timer_name = "export_write"
    needs_device = False

    def __init__(self, exporter) -> None:
        self.exporter = exporter

    def device_payload(self, year, year_idx, outs, carry):
        return self.exporter.device_payload(year, year_idx, outs)

    def consume(self, year, year_idx, host, outs) -> None:
        self.exporter.write_host(year, year_idx, host)

    def finalize(self, stats, failed) -> None:
        # per-year host-IO walls + async provenance into meta.json —
        # runs on the failure path too, so a crashed run still stamps
        # the years it completed
        self.exporter.stamp_hostio(stats)


class CheckpointConsumer:
    """An orbax :class:`~dgen_tpu.io.checkpoint.Writer` stage.  The
    driver hands ``submit`` a device-side carry SNAPSHOT (taken before
    the next step donates the live carry's buffers); the batched fetch
    brings it to host and the save runs here.  ``Writer.close`` stays
    with the driver's ``finally`` — after the drain, so every queued
    save has been issued."""

    name = "ckpt"
    timer_name = "ckpt_save"
    needs_device = False

    def __init__(self, writer) -> None:
        self.writer = writer

    def device_payload(self, year, year_idx, outs, carry):
        return carry

    def consume(self, year, year_idx, host, outs) -> None:
        self.writer.save(year, host)

    def finalize(self, stats, failed) -> None:
        pass


class DeviceCheckpointConsumer:
    """The multi-process checkpoint stage: orbax saves GLOBAL arrays
    (each process contributes its addressable shards collectively), so
    the carry snapshot must stay a DEVICE array — a ``jax.device_get``
    of a non-fully-addressable carry would raise.  The snapshot is
    stashed at submit time and saved, still on device, by the io
    thread; every process's io thread issues saves in the same year
    order, so the collective rendezvous lines up."""

    name = "ckpt_device"
    timer_name = "ckpt_save"
    # needs_device keeps consume() firing with no fetched payload; the
    # pipeline holding the year's outs alongside is the (small) price
    needs_device = True

    def __init__(self, writer) -> None:
        self.writer = writer
        self._snaps: Dict[int, Any] = {}

    def device_payload(self, year, year_idx, outs, carry):
        self._snaps[int(year_idx)] = carry
        return None

    def consume(self, year, year_idx, host, outs) -> None:
        self.writer.save(year, self._snaps.pop(int(year_idx)))

    def finalize(self, stats, failed) -> None:
        self._snaps.clear()


class CallbackConsumer:
    """An arbitrary user callback, run unchanged on the io thread: its
    own device fetches overlap device compute, just not batched with
    the other consumers.  The ``prepare(year, yi, outs)`` pre-dispatch
    hook (if the callback has one) fires at submit time on the main
    thread, preserving the old deferred-callback contract."""

    name = "callback"
    timer_name = "callback_host"
    needs_device = True

    def __init__(self, cb) -> None:
        self.cb = cb

    def device_payload(self, year, year_idx, outs, carry):
        prep = getattr(self.cb, "prepare", None)
        if prep is not None:
            prep(year, year_idx, outs)
        return None

    def consume(self, year, year_idx, host, outs) -> None:
        self.cb(year, year_idx, outs)

    def finalize(self, stats, failed) -> None:
        # an exporter driven through the generic stage (the
        # multi-process path) still stamps the pipeline's provenance
        stamp = getattr(self.cb, "stamp_hostio", None)
        if stamp is not None:
            stamp(stats)


def consumer_for_callback(cb):
    """The pipeline stage for a run callback: exporters implementing the
    split fetch/write protocol (``device_payload`` + ``write_host``)
    get the batched-fetch fast path; anything else — including
    exporters on MULTI-PROCESS runs, whose per-shard ``__call__`` path
    must do its own addressable-shard reads — runs as-is on the io
    thread."""
    if (
        hasattr(cb, "device_payload") and hasattr(cb, "write_host")
        and jax.process_count() == 1
    ):
        return ExportConsumer(cb)
    return CallbackConsumer(cb)


# ---------------------------------------------------------------------------
# The pipeline
# ---------------------------------------------------------------------------

class _Item:
    __slots__ = ("year", "year_idx", "payloads", "outs", "done",
                 "fetch_s", "consume_s")

    def __init__(self, year, year_idx, payloads, outs) -> None:
        self.year = year
        self.year_idx = year_idx
        self.payloads = payloads
        self.outs = outs
        self.done: Future = Future()
        self.fetch_s = 0.0
        self.consume_s = 0.0


class HostPipeline:
    """Bounded FIFO pipeline of per-year host-IO work (module
    docstring has the full contract).

    Parameters
    ----------
    consumers : ordered stage list (Collect/Export/Checkpoint/Callback
        consumers, or anything implementing the same protocol).
    max_in_flight : queue depth bound (:func:`depth_for_bytes`).
    timing_ctx : utils.timing context label for the stage timers
        (``d2h_fetch`` / ``export_write`` / ``ckpt_save`` / …).
    pool : optional shared :class:`HostIOPool`; the pipeline owns (and
        closes at drain) a private pool when None.
    """

    def __init__(
        self,
        consumers: Sequence[Any],
        *,
        max_in_flight: int = 1,
        timing_ctx: Optional[str] = None,
        pool: Optional[HostIOPool] = None,
    ) -> None:
        self.consumers = list(consumers)
        self.timing_ctx = timing_ctx
        self.max_in_flight = max(1, int(max_in_flight))
        self._own_pool = pool is None
        self.pool = pool if pool is not None else HostIOPool()
        self._slots = threading.BoundedSemaphore(self.max_in_flight)
        self._lock = threading.Lock()
        self._items: List[_Item] = []
        self._error: Optional[BaseException] = None
        self._error_year: Optional[int] = None
        self._error_year_idx: Optional[int] = None
        self._in_flight = 0
        self.max_observed_depth = 0
        self.host_blocked_s = 0.0
        self._fetch_s = 0.0
        self._consume_s = 0.0
        self._needs_device = any(
            getattr(c, "needs_device", False) for c in self.consumers
        )
        self._drained = False

    # -- error plumbing -------------------------------------------------
    def _record_error(self, year, exc: BaseException,
                      year_idx: Optional[int] = None) -> None:
        """Keep the error of the EARLIEST failed year — the one the
        crash semantics are defined against.  The fetch stage runs
        ahead of the io stage, so a year-7 fetch error can be recorded
        while year 5's write is still in flight; if that write then
        fails, year 5's error must win (and gate years >= 5), not be
        dropped.  A superseded error is logged, never swallowed."""
        with self._lock:
            if self._error is None or (
                year_idx is not None
                and self._error_year_idx is not None
                and year_idx < self._error_year_idx
            ):
                dropped, dropped_year = self._error, self._error_year
                self._error = exc
                self._error_year = year
                self._error_year_idx = year_idx
            else:
                dropped, dropped_year = exc, year
        if dropped is not None:
            logger.error(
                "host-IO pipeline error for year %s: %r (year %s's "
                "error wins)", dropped_year, dropped, self._error_year)

    def _raise_if_failed(self) -> None:
        if self._error is not None:
            raise self._error

    def _should_run(self, item: "_Item") -> bool:
        """Years strictly BEFORE the errored year still run their
        stages: the serialized oracle would have completed them before
        any failed-year work started, and the documented crash
        semantics promise the last completed year's export.  The
        errored year itself and everything after it are skipped."""
        with self._lock:
            if self._error is None:
                return True
            if self._error_year_idx is None:
                return False
            return item.year_idx < self._error_year_idx

    # -- submit (main thread) -------------------------------------------
    def submit(self, year: int, year_idx: int, outs,
               carry=None) -> None:
        """Queue year ``year``'s host consumers.  Blocks while
        ``max_in_flight`` years are already queued (the HBM bound);
        raises any earlier worker exception instead of queueing more
        work on top of a dead pipeline."""
        self._raise_if_failed()
        # acquire the slot BEFORE materializing device payloads: the
        # copies device_payload dispatches (quantized outputs, pinned
        # snapshots) count against the same ~2 GB envelope the depth
        # was budgeted for — building them first would put up to
        # (depth + 1) years' bytes in flight on HBM-tight configs.
        # Blocking here dispatches nothing, so the payload ops still
        # land right behind this year's step in the device queue.
        t0 = time.perf_counter()
        self._slots.acquire()
        self.host_blocked_s += time.perf_counter() - t0
        payloads = {}
        try:
            for c in self.consumers:
                p = c.device_payload(year, year_idx, outs, carry)
                if p is not None:
                    payloads[c.name] = p
        except BaseException:
            self._slots.release()
            raise
        with self._lock:
            self._in_flight += 1
            self.max_observed_depth = max(
                self.max_observed_depth, self._in_flight)
        item = _Item(year, year_idx, payloads,
                     outs if self._needs_device else None)
        # one record per submitted model YEAR of one run (tens), read
        # back by drain() — a batch-driver ledger, not request-keyed
        # serving state
        self._items.append(item)   # dgenlint: disable=L12
        try:
            self.pool.fetch.submit(self._fetch_job, item)
        except BaseException as e:  # pool torn down under us
            self._record_error(year, e, year_idx)
            self._finish(item)
            raise

    # -- fetch stage (fetch thread) -------------------------------------
    def _fetch_job(self, item: _Item) -> None:
        host = None
        try:
            # resilience drill hook: a fetch worker dying mid-year must
            # surface via _record_error at submit/drain, never hang the
            # driver (the supervisor then retries/resumes the run)
            fault_point("hostio_fetch")
            if item.payloads and self._should_run(item):
                t0 = time.perf_counter()
                with timing.timer("d2h_fetch", ctx=self.timing_ctx):
                    host = jax.device_get(item.payloads)
                item.fetch_s = time.perf_counter() - t0
                with self._lock:
                    self._fetch_s += item.fetch_s
        except BaseException as e:  # noqa: BLE001 — surfaced at submit/drain
            self._record_error(item.year, e, item.year_idx)
            host = None
        item.payloads = None   # device buffers release here
        try:
            self.pool.io.submit(self._io_job, item, host)
        except BaseException as e:
            self._record_error(item.year, e, item.year_idx)
            self._finish(item)

    # -- consume stage (io thread) --------------------------------------
    def _io_job(self, item: _Item, host) -> None:
        try:
            # resilience drill hook: the ordered consume worker
            # (collect/parquet/orbax) dying mid-year
            fault_point("hostio_io")
            if self._should_run(item):
                t0 = time.perf_counter()
                for c in self.consumers:
                    payload = None if host is None else host.get(c.name)
                    if payload is None and not c.needs_device:
                        continue
                    with timing.timer(c.timer_name, ctx=self.timing_ctx):
                        c.consume(item.year, item.year_idx, payload,
                                  item.outs)
                item.consume_s = time.perf_counter() - t0
                with self._lock:
                    self._consume_s += item.consume_s
        except BaseException as e:  # noqa: BLE001 — surfaced at submit/drain
            self._record_error(item.year, e, item.year_idx)
        finally:
            self._finish(item)

    def _finish(self, item: _Item) -> None:
        item.outs = None
        with self._lock:
            self._in_flight -= 1
        self._slots.release()
        if not item.done.done():
            item.done.set_result(None)

    # -- drain (main thread, from a finally) ----------------------------
    def drain(self, failed: bool = False) -> Dict[str, Any]:
        """Wait for every queued year and finalize the consumers.  On
        the success path the earliest failed year's worker exception
        re-raises here (or at an earlier ``submit``); with
        ``failed=True`` (the driver's loop already raised) it is
        logged instead, so the original error is not masked.  Closes
        an owned pool.  Returns :meth:`stats`."""
        if self._drained:
            return self.stats()
        self._drained = True
        t0 = time.perf_counter()
        for item in self._items:
            item.done.result()
        self.host_blocked_s += time.perf_counter() - t0
        if self._own_pool:
            self.pool.close()
        finalize_err: Optional[BaseException] = None
        for c in self.consumers:
            try:
                c.finalize(self.stats(), failed or self._error is not None)
            except BaseException as e:  # noqa: BLE001
                if finalize_err is None:
                    finalize_err = e
        if self._error is not None:
            if failed:
                logger.error(
                    "host-IO pipeline failed for year %s: %r (original "
                    "loop error wins)", self._error_year, self._error)
            else:
                if finalize_err is not None:
                    # the worker error wins the raise; don't drop the
                    # finalize failure silently
                    logger.error(
                        "host-IO finalize failed: %r", finalize_err)
                raise self._error
        if finalize_err is not None:
            if failed:
                logger.error("host-IO finalize failed: %r", finalize_err)
            else:
                raise finalize_err
        return self.stats()

    # -- observability --------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Pipeline observability record: per-year host-IO wall (fetch +
        consume seconds), stage totals, the wall the MAIN thread spent
        blocked on the pipeline (full submits + drain), and
        ``overlap_efficiency`` = the fraction of host-IO wall hidden
        behind device compute (1 - blocked/host_io)."""
        years = {
            int(i.year): round(i.fetch_s + i.consume_s, 4)
            for i in self._items
            if i.done.done() and (i.fetch_s or i.consume_s)
        }
        host_io = self._fetch_s + self._consume_s
        if host_io > 0:
            overlap = 1.0 - min(self.host_blocked_s, host_io) / host_io
        else:
            overlap = 1.0
        return {
            "years": years,
            "d2h_fetch_s": round(self._fetch_s, 4),
            "consume_s": round(self._consume_s, 4),
            "host_io_s": round(host_io, 4),
            "host_blocked_s": round(self.host_blocked_s, 4),
            "overlap_efficiency": round(overlap, 4),
            "max_depth": self.max_observed_depth,
            "depth_bound": self.max_in_flight,
        }
