"""URDB tooling: rate-record parsing, bulk download, tariff design.

The reference ships three deprecated-but-shipped tariff utilities in
``tariff_functions.py``: the ``Tariff`` class's URDB-record repackaging
(tariff_functions.py:230-330), the bulk URDB API downloader
(``download_tariffs_from_urdb``, tariff_functions.py:944), and
``design_tariff_for_portfolio`` (tariff_functions.py:1133) which builds
a tariff extracting a target $/kWh from a load portfolio. This module
provides their dgen-tpu equivalents, emitting the framework's SPEC
dicts (compilable by ``ops.tariff.normalize_tariff_spec`` and
``ops.demand.compile_demand_bank``) instead of a Python rate object:

* :func:`urdb_rate_to_specs` — one raw URDB API record (the JSON shape
  with ``energyratestructure`` period x tier dicts and 12x24
  schedules) -> ``(energy_spec, demand_spec | None)``.
* :func:`download_tariffs_from_urdb` — paginated API pull; the HTTP
  fetch is injectable so offline environments (and tests) can supply
  records from disk.
* :func:`design_tariff_for_portfolio` — vectorized over the portfolio
  ([N, 8760] loads + weights; the reference iterates buildings through
  pandas) and returns specs plus the achieved revenue split.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dgen_tpu.ops.tariff import (
    BIG_CAP,
    NET_METERING,
    expand_schedule_8760,
    hour_month_map,
)

URDB_API_URL = "https://api.openei.org/utility_rates"


def _rate_matrix(structure: List[List[dict]]) -> Tuple[np.ndarray, np.ndarray]:
    """URDB [period][tier] dicts -> (prices [T, P], levels [T, P]);
    price = rate + adj, missing caps unbounded — the reference's
    repackaging rule (tariff_functions.py:278-307)."""
    n_periods = len(structure)
    n_tiers = max((len(p) for p in structure), default=1)
    prices = np.zeros((n_tiers, n_periods))
    levels = np.full((n_tiers, n_periods), BIG_CAP)
    for p, period in enumerate(structure):
        for t, tier in enumerate(period):
            prices[t, p] = float(tier.get("rate", 0.0) or 0.0) + float(
                tier.get("adj", 0.0) or 0.0)
            mx = tier.get("max")
            if mx is not None and float(mx) > 0:
                levels[t, p] = float(mx)
    return prices, levels


def _schedule(record: dict, key: str, n_periods: int) -> Optional[np.ndarray]:
    """12x24 period schedule, with the reference's out-of-range rule:
    periods past the price table fall back to period 0
    (tariff_functions.py:318-323)."""
    sched = record.get(key)
    if sched is None:
        return None
    # np.array (copy), NOT np.asarray: when the record already holds an
    # int64 ndarray, asarray aliases it and the in-place remap below
    # would silently mutate the caller's data
    m = np.array(sched, np.int64)
    m[m >= n_periods] = 0
    return m


def urdb_rate_to_specs(
    record: Dict[str, Any],
) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]]]:
    """One raw URDB API rate record -> (energy_spec, demand_spec).

    The energy spec carries the framework's legacy-layout keys
    (``e_prices`` [T][P] + 0-based 12x24 schedules — URDB schedules are
    already 0-based); the demand spec mirrors
    ``convert.reference_tariff_to_demand_spec``'s key set (flat prices
    per month via ``flatdemandmonths``, TOU structure + schedules), or
    None when the record prices no demand. Metering defaults to net
    metering, the reference's assumption for URDB pulls.
    """
    # .get defaults don't cover explicit JSON nulls (the API emits them)
    fixed = record.get("fixedmonthlycharge")
    if fixed is None:
        fixed = record.get("fixedchargefirstmeter")
    energy: Dict[str, Any] = {
        "fixed_charge": float(fixed or 0.0),
        "metering": int(record.get("metering") or NET_METERING),
    }
    es = record.get("energyratestructure")
    if es:
        prices, levels = _rate_matrix(es)
        energy["e_prices"] = prices.tolist()
        energy["e_levels"] = levels.tolist()
        n_p = prices.shape[1]
        for key, dst in (("energyweekdayschedule", "e_wkday_12by24"),
                         ("energyweekendschedule", "e_wkend_12by24")):
            sched = _schedule(record, key, n_p)
            if sched is not None:
                energy[dst] = sched.tolist()
    else:
        energy["price"] = [[0.1]]   # blank tariff -> inert flat rate

    demand: Dict[str, Any] = {}
    fd = record.get("flatdemandstructure")
    if fd:
        prices, levels = _rate_matrix(fd)          # [T, n_constructs]
        # .get default does not cover an explicit JSON null; np.array
        # copies so the in-place remap never mutates the record's own
        # ndarray (same aliasing hazard as _schedule), and the explicit
        # None/empty check replaces a truthiness test that raised on
        # ndarray-valued records
        fdm = record.get("flatdemandmonths")
        months = (
            np.array(fdm, np.int64) if fdm is not None and len(fdm)
            else np.zeros(12, np.int64)
        )
        months[months >= prices.shape[1]] = 0
        # per-month columns, the d_flat_* layout (tariff_functions.py:250)
        demand["d_flat_prices"] = prices[:, months].tolist()
        demand["d_flat_levels"] = levels[:, months].tolist()
    ds = record.get("demandratestructure")
    if ds:
        prices, levels = _rate_matrix(ds)
        if np.any(prices > 0):
            demand["d_tou_prices"] = prices.tolist()
            demand["d_tou_levels"] = levels.tolist()
            n_p = prices.shape[1]
            for key, dst in (("demandweekdayschedule", "d_wkday_12by24"),
                             ("demandweekendschedule", "d_wkend_12by24")):
                sched = _schedule(record, key, n_p)
                if sched is not None:
                    demand[dst] = sched.tolist()
    if demand and not np.any(
        np.asarray(demand.get("d_flat_prices", 0.0)) > 0
    ) and "d_tou_prices" not in demand:
        demand = {}
    return energy, (demand or None)


def download_tariffs_from_urdb(
    api_key: str,
    sector: Optional[str] = None,
    utility: Optional[str] = None,
    limit: int = 500,
    fetch: Optional[Callable[[str], bytes]] = None,
) -> List[Dict[str, Any]]:
    """Bulk-pull URDB rate records (reference
    tariff_functions.py:944-1000). Paginates until a short page.

    ``fetch`` is injectable (url -> response bytes); the default uses
    urllib, which requires network egress — in sealed environments pass
    a loader that reads saved API responses from disk.
    """
    from urllib.parse import urlencode

    if fetch is None:
        from urllib.request import urlopen

        fetch = lambda url: urlopen(url, timeout=60).read()  # noqa: S310

    records: List[Dict[str, Any]] = []
    offset = 0
    while True:
        params = {
            "version": 8, "format": "json", "api_key": api_key,
            "detail": "full", "limit": limit, "offset": offset,
        }
        if sector:
            params["sector"] = sector
        if utility:
            params["ratesforutility"] = utility
        url = f"{URDB_API_URL}?{urlencode(params)}"
        page = json.loads(fetch(url)).get("items", [])
        records.extend(page)
        if len(page) < limit:
            return records
        offset += limit


def design_tariff_for_portfolio(
    loads: np.ndarray,                 # [N, 8760] kW
    weights: np.ndarray,               # [N] customers represented
    avg_rev: float,                    # target $/kWh over the portfolio
    peak_hour_indices: Sequence[int],  # hours-of-day that are on-peak
    summer_month_indices: Sequence[int],
    rev_f_d: Sequence[float],          # [frac of rev, tou frac, flat frac]
    rev_f_e: Sequence[float],          # [frac of rev, peak frac, offpeak frac]
    rev_f_fixed: Sequence[float],      # [frac of rev]
) -> Dict[str, Any]:
    """Design a 2-period TOU + demand + fixed tariff extracting
    ``avg_rev`` $/kWh from the weighted portfolio (reference
    tariff_functions.py:1133-1256, vectorized over agents).

    Returns {"energy_spec", "demand_spec", "charges", "revenue_check"}:
    the two framework spec dicts plus the solved charge levels and the
    achieved revenue decomposition (the reference returns a Tariff
    object and leaves verification to a bill_calculator loop).

    Divergences from the reference, both deliberate:

    * the peak/off-peak windows use THIS framework's calendar
      (``expand_schedule_8760``, Jan-1 = Monday) rather than the
      reference's hard-coded Sunday-start — so ``revenue_check`` holds
      exactly under the framework's own bill engine for the emitted
      spec, which is the point of designing a tariff here;
    * the ``rev_f_e`` element order follows the reference's CODE
      (index 1 = peak, index 2 = off-peak,
      tariff_functions.py:1227-1228), not its docstring, which states
      the opposite — same docstring-vs-code resolution as the payback
      sentinel (ops/cashflow.py).
    """
    loads = np.asarray(loads, np.float64)
    weights = np.asarray(weights, np.float64)
    n, H = loads.shape
    if H != 8760:
        raise ValueError(f"loads must be [N, 8760], got {loads.shape}")

    wkday = np.zeros((12, 24), np.int64)
    wkend = np.zeros((12, 24), np.int64)
    for h in peak_hour_indices:
        wkday[np.asarray(summer_month_indices, np.int64), h] = 1
    period_8760 = np.asarray(expand_schedule_8760(wkday, wkend))
    month_idx = np.asarray(hour_month_map())

    # per-agent per-(month, period) maxes and sums, vectorized
    peak_d = np.zeros(n)     # sum over months of on-peak max kW
    flat_d = np.zeros(n)     # sum over months of all-hours max kW
    peak_e = np.zeros(n)     # annual on-peak kWh
    off_e = np.zeros(n)      # annual off-peak kWh
    on = period_8760 == 1
    for m in range(12):
        in_m = month_idx == m
        lm = loads[:, in_m]
        on_m = on[in_m]
        peak_d += np.max(
            np.where(on_m[None, :], lm, 0.0), axis=1)
        flat_d += np.max(lm, axis=1)
        peak_e += np.sum(np.where(on_m[None, :], lm, 0.0), axis=1)
        off_e += np.sum(np.where(on_m[None, :], 0.0, lm), axis=1)

    total_kwh = float(np.sum(weights * (peak_e + off_e)))
    norm_rev = total_kwh * float(avg_rev)
    rev = {
        "d_tou": norm_rev * rev_f_d[0] * rev_f_d[1],
        "d_flat": norm_rev * rev_f_d[0] * rev_f_d[2],
        # reference CODE order: [1] = peak, [2] = off-peak
        # (tariff_functions.py:1227-1228; its docstring says the
        # opposite — see the function docstring above)
        "e_peak": norm_rev * rev_f_e[0] * rev_f_e[1],
        "e_off": norm_rev * rev_f_e[0] * rev_f_e[2],
        "fixed": norm_rev * rev_f_fixed[0],
    }

    def _safe(num, den):
        return float(num / den) if den > 0 else 0.0

    charges = {
        "d_tou_peak": _safe(rev["d_tou"], np.sum(weights * peak_d)),
        "d_flat": _safe(rev["d_flat"], np.sum(weights * flat_d)),
        "e_peak": _safe(rev["e_peak"], np.sum(weights * peak_e)),
        "e_offpeak": _safe(rev["e_off"], np.sum(weights * off_e)),
        "fixed_monthly": _safe(rev["fixed"], np.sum(weights) * 12.0),
    }

    energy_spec = {
        # price [P, T]: period 0 off-peak, period 1 on-peak, one tier
        "price": [[charges["e_offpeak"]], [charges["e_peak"]]],
        "e_wkday_12by24": wkday.tolist(),
        "e_wkend_12by24": wkend.tolist(),
        "fixed_charge": charges["fixed_monthly"],
        "metering": NET_METERING,
    }
    demand_spec = {
        "d_flat_prices": [[charges["d_flat"]] * 12],
        "d_flat_levels": [[BIG_CAP] * 12],
        "d_tou_prices": [[0.0, charges["d_tou_peak"]]],
        "d_tou_levels": [[BIG_CAP, BIG_CAP]],
        "d_wkday_12by24": wkday.tolist(),
        "d_wkend_12by24": wkend.tolist(),
    }
    achieved = (
        charges["e_peak"] * np.sum(weights * peak_e)
        + charges["e_offpeak"] * np.sum(weights * off_e)
        + charges["d_tou_peak"] * np.sum(weights * peak_d)
        + charges["d_flat"] * np.sum(weights * flat_d)
        + charges["fixed_monthly"] * 12.0 * np.sum(weights)
    )
    return {
        "energy_spec": energy_spec,
        "demand_spec": demand_spec,
        "charges": charges,
        "revenue_check": {
            "target_usd": norm_rev,
            "achieved_usd": float(achieved),
            "avg_rev_per_kwh": _safe(achieved, total_kwh),
        },
    }
