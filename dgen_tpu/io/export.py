"""Per-year run outputs: parquet-based equivalents of the reference's
result tables.

The reference writes three result surfaces per model year into its
Postgres output schema (SURVEY.md §2.5): the wide ``agent_outputs``
table (dgen_model.py:441-463), the state-hourly net-load aggregate
``state_hourly_agg`` (attachment_rate_functions.py:151-201), and the
25-element per-agent cashflow/bill arrays in ``agent_finance_series``
(finance_series_export.py:22). Here each becomes a partitioned parquet
dataset under the run directory — the TPU path's data plane is files,
not a database (SURVEY.md §2.6: no per-agent SQL round trips) — and a
loader on the other side reassembles cross-year frames.

Layout:
    <run_dir>/agent_outputs/year=<Y>.parquet
    <run_dir>/state_hourly/year=<Y>.parquet     (hour-major long format)
    <run_dir>/finance_series/year=<Y>.parquet
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence

import numpy as np
import pandas as pd

#: YearOutputs fields exported to agent_outputs (the reference drops
#: its heavy intermediate columns before writing, dgen_model.py:441-456;
#: hourly arrays and cashflow get their own surfaces here).
AGENT_OUTPUT_FIELDS = (
    "system_kw", "npv", "payback_period", "max_market_share",
    "market_share", "new_adopters", "number_of_adopters",
    "new_system_kw", "system_kw_cum", "market_value",
    "first_year_bill_with_system", "first_year_bill_without_system",
    "batt_kw", "batt_kwh", "new_batt_adopters", "batt_adopters_cum",
    "batt_kw_cum", "batt_kwh_cum",
    "carbon_intensity_t_per_kwh", "avoided_co2_t",
)


def _dir(run_dir: str, name: str) -> str:
    d = os.path.join(run_dir, name)
    os.makedirs(d, exist_ok=True)
    return d


class RunExporter:
    """Host-side per-year writer, used as a Simulation.run callback.

    ``mask`` drops padding agents; ``agent_id`` restores stable ids.
    """

    def __init__(
        self,
        run_dir: str,
        agent_id: np.ndarray,
        mask: np.ndarray,
        state_names: Optional[Sequence[str]] = None,
        finance_series: bool = True,
        meta: Optional[Dict[str, object]] = None,
    ) -> None:
        self.run_dir = run_dir
        self.keep = np.asarray(mask) > 0
        self.agent_id = np.asarray(agent_id)[self.keep]
        self.state_names = list(state_names) if state_names else None
        self.finance_series = finance_series
        os.makedirs(run_dir, exist_ok=True)
        # provenance stamp: ``meta`` (notably market_curves:
        # synthetic_default vs ingested, from scenario ingest) is written
        # up front so a run's outputs carry their own caveats
        self.meta = {"n_agents": int(self.keep.sum()), **(meta or {})}
        with open(os.path.join(run_dir, "meta.json"), "w") as f:
            json.dump(self.meta, f, indent=2, default=str)

    def _check_state_names(self, n_states: int) -> None:
        if self.state_names is not None and len(self.state_names) != n_states:
            raise ValueError(
                f"state_names has {len(self.state_names)} entries but the "
                f"hourly aggregate covers {n_states} states"
            )

    def __call__(self, year: int, year_idx: int, outs) -> None:
        self.write_agent_outputs(year, outs)
        if self.finance_series:
            self.write_finance_series(year, outs)
        hourly = np.asarray(outs.state_hourly_net_mw)
        if hourly.size:
            self.write_state_hourly(year, hourly)

    # --- agent_outputs (reference dgen_model.py:460-462) ---
    def write_agent_outputs(self, year: int, outs) -> None:
        cols: Dict[str, np.ndarray] = {"agent_id": self.agent_id}
        for f in AGENT_OUTPUT_FIELDS:
            cols[f] = np.asarray(getattr(outs, f))[self.keep]
        df = pd.DataFrame(cols)
        df.insert(1, "year", year)
        df.to_parquet(
            os.path.join(_dir(self.run_dir, "agent_outputs"),
                         f"year={year}.parquet")
        )

    # --- agent_finance_series (reference finance_series_export.py:22) ---
    def write_finance_series(self, year: int, outs) -> None:
        cf = np.asarray(outs.cash_flow)[self.keep]          # [n, Y+1]
        ev = np.asarray(outs.energy_value_pv_only)[self.keep]  # [n, Y]
        df = pd.DataFrame({
            "agent_id": self.agent_id,
            "year": year,
            "cash_flow": list(cf),
            "energy_value": list(ev),
        })
        df.to_parquet(
            os.path.join(_dir(self.run_dir, "finance_series"),
                         f"year={year}.parquet")
        )

    # --- state_hourly_agg (reference attachment_rate_functions.py:151) ---
    def write_state_hourly(self, year: int, hourly: np.ndarray) -> None:
        n_states, hours = hourly.shape
        self._check_state_names(n_states)
        names = (
            self.state_names if self.state_names
            else [str(i) for i in range(n_states)]
        )
        # wide format: one row per state, hourly MW as a list column
        df = pd.DataFrame({
            "state": names,
            "year": year,
            "net_load_mw": list(hourly.astype(np.float32)),
        })
        df.to_parquet(
            os.path.join(_dir(self.run_dir, "state_hourly"),
                         f"year={year}.parquet")
        )


def load_surface(run_dir: str, name: str) -> pd.DataFrame:
    """Reassemble a cross-year frame from a run's parquet partitions."""
    d = os.path.join(run_dir, name)
    parts = sorted(
        os.path.join(d, f) for f in os.listdir(d) if f.endswith(".parquet")
    )
    if not parts:
        raise FileNotFoundError(f"no parquet partitions under {d}")
    return pd.concat([pd.read_parquet(p) for p in parts], ignore_index=True)
