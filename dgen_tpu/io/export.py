"""Per-year run outputs: parquet-based equivalents of the reference's
result tables.

The reference writes three result surfaces per model year into its
Postgres output schema (SURVEY.md §2.5): the wide ``agent_outputs``
table (dgen_model.py:441-463), the state-hourly net-load aggregate
``state_hourly_agg`` (attachment_rate_functions.py:151-201), and the
25-element per-agent cashflow/bill arrays in ``agent_finance_series``
(finance_series_export.py:22). Here each becomes a partitioned parquet
dataset under the run directory — the TPU path's data plane is files,
not a database (SURVEY.md §2.6: no per-agent SQL round trips) — and a
loader on the other side reassembles cross-year frames.

Layout:
    <run_dir>/agent_outputs/year=<Y>.parquet
    <run_dir>/state_hourly/year=<Y>.parquet     (hour-major long format)
    <run_dir>/finance_series/year=<Y>.parquet

Multi-host: each process writes its OWN addressable shard rows as
``year=<Y>-p<proc>.parquet`` partitions (replicated surfaces like the
state-hourly aggregate are written by process 0 only), so a
jax.distributed run persists every surface with zero cross-host
gathers; :func:`load_surface` concatenates the parts. The reference
gets the same property from per-task Postgres writes
(dgen_model.py:459-462).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
import pandas as pd


def _host_rows(arr) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """(rows, global_row_idx) of the process-locally addressable part of
    a per-agent array; idx None means all rows are local (the
    single-controller case, or a fully replicated leaf)."""
    # duck-typed (not isinstance) so the multi-host path is unit-testable
    # from a single-controller test process
    if (
        getattr(arr, "is_fully_addressable", True) is False
    ):
        if arr.is_fully_replicated:
            return np.asarray(arr), None
        # distinct local shards, deduped (replication within a host
        # yields repeated index windows)
        seen: Dict[int, tuple[int, np.ndarray]] = {}
        for s in arr.addressable_shards:
            sl = s.index[0] if s.index else slice(None)
            start = sl.start or 0
            stop = sl.stop if sl.stop is not None else arr.shape[0]
            if start not in seen:
                seen[start] = (stop, np.asarray(s.data))
        starts = sorted(seen)
        rows = np.concatenate([seen[s][1] for s in starts], axis=0)
        idx = np.concatenate(
            [np.arange(s, seen[s][0]) for s in starts]
        )
        return rows, idx
    return np.asarray(arr), None

#: YearOutputs fields exported to agent_outputs (the reference drops
#: its heavy intermediate columns before writing, dgen_model.py:441-456;
#: hourly arrays and cashflow get their own surfaces here).
AGENT_OUTPUT_FIELDS = (
    "system_kw", "npv", "payback_period", "max_market_share",
    "market_share", "new_adopters", "number_of_adopters",
    "new_system_kw", "system_kw_cum", "market_value",
    "first_year_bill_with_system", "first_year_bill_without_system",
    "batt_kw", "batt_kwh", "new_batt_adopters", "batt_adopters_cum",
    "batt_kw_cum", "batt_kwh_cum",
    "carbon_intensity_t_per_kwh", "avoided_co2_t",
)


def _dir(run_dir: str, name: str) -> str:
    d = os.path.join(run_dir, name)
    os.makedirs(d, exist_ok=True)
    return d


class RunExporter:
    """Host-side per-year writer, used as a Simulation.run callback.

    ``mask`` drops padding agents; ``agent_id`` restores stable ids.
    """

    def __init__(
        self,
        run_dir: str,
        agent_id: np.ndarray,
        mask: np.ndarray,
        state_names: Optional[Sequence[str]] = None,
        finance_series: bool = True,
        meta: Optional[Dict[str, object]] = None,
    ) -> None:
        self.run_dir = run_dir
        self.keep = np.asarray(mask) > 0
        self._ids_full = np.asarray(agent_id)
        self.agent_id = self._ids_full[self.keep]
        self.state_names = list(state_names) if state_names else None
        self.finance_series = finance_series
        os.makedirs(run_dir, exist_ok=True)
        # provenance stamp: ``meta`` (notably market_curves:
        # synthetic_default vs ingested, from scenario ingest) is written
        # up front so a run's outputs carry their own caveats
        self.meta = {"n_agents": int(self.keep.sum()), **(meta or {})}
        if jax.process_index() == 0:
            with open(os.path.join(run_dir, "meta.json"), "w") as f:
                json.dump(self.meta, f, indent=2, default=str)

    def _part_name(self, year: int) -> str:
        """Per-year parquet partition name; multi-host runs write one
        part per process."""
        if jax.process_count() > 1:
            return f"year={year}-p{jax.process_index()}.parquet"
        return f"year={year}.parquet"

    def _local(self, arr) -> tuple[np.ndarray, np.ndarray]:
        """(rows, ids): this process's real-agent rows of a per-agent
        field, with their stable agent ids."""
        (rows,), ids = self._local_fields([arr])
        return rows, ids

    def _local_fields(self, arrs) -> tuple[list, np.ndarray]:
        """(rows per field, ids): the fast path reuses the first field's
        shard index for follow-up fields; any field whose sharding
        differs (GSPMD may replicate one YearOutputs leaf while sharding
        its siblings) is realigned onto the first field's agent ids via
        its own index instead of being mis-sliced."""
        if not any(
            getattr(a, "is_fully_addressable", True) is False for a in arrs
        ):
            # single-controller: ONE batched transfer for all fields
            # (per-leaf np.asarray costs a host round trip each)
            host = jax.device_get(list(arrs))
            return [h[self.keep] for h in host], self.agent_id
        first, idx = _host_rows(arrs[0])
        if idx is None:
            sel, ids = self.keep, self.agent_id
        else:
            sel = self.keep[idx]
            ids = self._ids_full[idx][sel]
        out = [first[sel]]
        for a in arrs[1:]:
            rows, a_idx = _host_rows(a)
            if (a_idx is None and idx is None) or (
                a_idx is not None and idx is not None
                and np.array_equal(a_idx, idx)
            ):
                out.append(rows[sel])
                continue
            # this leaf carries a DIFFERENT sharding than the first one
            # (GSPMD propagation can replicate one output while sharding
            # another): align on the leaf's OWN index, then reorder onto
            # the first leaf's agent ids
            a_sel = self.keep if a_idx is None else self.keep[a_idx]
            a_ids = (
                self.agent_id if a_idx is None
                else self._ids_full[a_idx][a_sel]
            )
            rows = rows[a_sel]
            if not np.array_equal(a_ids, ids):
                pos = {int(g): i for i, g in enumerate(a_ids)}
                try:
                    rows = rows[np.asarray(
                        [pos[int(g)] for g in ids], dtype=np.intp
                    )]
                except KeyError as e:
                    raise ValueError(
                        "per-agent output leaves carry incompatible "
                        "shardings: a follow-up leaf's locally "
                        f"addressable rows lack agent id {e} present in "
                        "the first leaf's window; pin YearOutputs leaves "
                        "to one sharding in year_step"
                    ) from e
            out.append(rows)
        return out, ids

    def _check_state_names(self, n_states: int) -> None:
        if self.state_names is not None and len(self.state_names) != n_states:
            raise ValueError(
                f"state_names has {len(self.state_names)} entries but the "
                f"hourly aggregate covers {n_states} states"
            )

    def __call__(self, year: int, year_idx: int, outs) -> None:
        self.write_agent_outputs(year, outs)
        if self.finance_series:
            self.write_finance_series(year, outs)
        # the state aggregate is replicated across hosts; one writer
        if (
            getattr(outs.state_hourly_net_mw, "size", 0)
            and jax.process_index() == 0
        ):
            self.write_state_hourly(
                year, np.asarray(outs.state_hourly_net_mw)
            )

    # --- agent_outputs (reference dgen_model.py:460-462) ---
    def write_agent_outputs(self, year: int, outs) -> None:
        rows, ids = self._local_fields(
            [getattr(outs, f) for f in AGENT_OUTPUT_FIELDS]
        )
        cols = dict(zip(AGENT_OUTPUT_FIELDS, rows))
        df = pd.DataFrame({"agent_id": ids, "year": year, **cols})
        df.to_parquet(
            os.path.join(_dir(self.run_dir, "agent_outputs"),
                         self._part_name(year))
        )

    # --- agent_finance_series (reference finance_series_export.py:22) ---
    def write_finance_series(self, year: int, outs) -> None:
        (cf, ev), ids = self._local_fields(
            [outs.cash_flow, outs.energy_value_pv_only]  # [n,Y+1],[n,Y]
        )
        df = pd.DataFrame({
            "agent_id": ids,
            "year": year,
            "cash_flow": list(cf),
            "energy_value": list(ev),
        })
        df.to_parquet(
            os.path.join(_dir(self.run_dir, "finance_series"),
                         self._part_name(year))
        )

    # --- state_hourly_agg (reference attachment_rate_functions.py:151) ---
    def write_state_hourly(self, year: int, hourly: np.ndarray) -> None:
        n_states, hours = hourly.shape
        self._check_state_names(n_states)
        names = (
            self.state_names if self.state_names
            else [str(i) for i in range(n_states)]
        )
        # wide format: one row per state, hourly MW as a list column
        df = pd.DataFrame({
            "state": names,
            "year": year,
            "net_load_mw": list(hourly.astype(np.float32)),
        })
        df.to_parquet(
            os.path.join(_dir(self.run_dir, "state_hourly"),
                         f"year={year}.parquet")
        )


def load_surface(run_dir: str, name: str) -> pd.DataFrame:
    """Reassemble a cross-year frame from a run's parquet partitions."""
    d = os.path.join(run_dir, name)
    parts = sorted(
        os.path.join(d, f) for f in os.listdir(d) if f.endswith(".parquet")
    )
    if not parts:
        raise FileNotFoundError(f"no parquet partitions under {d}")
    return pd.concat([pd.read_parquet(p) for p in parts], ignore_index=True)
