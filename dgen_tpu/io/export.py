"""Per-year run outputs: parquet-based equivalents of the reference's
result tables.

The reference writes three result surfaces per model year into its
Postgres output schema (SURVEY.md §2.5): the wide ``agent_outputs``
table (dgen_model.py:441-463), the state-hourly net-load aggregate
``state_hourly_agg`` (attachment_rate_functions.py:151-201), and the
25-element per-agent cashflow/bill arrays in ``agent_finance_series``
(finance_series_export.py:22). Here each becomes a partitioned parquet
dataset under the run directory — the TPU path's data plane is files,
not a database (SURVEY.md §2.6: no per-agent SQL round trips) — and a
loader on the other side reassembles cross-year frames.

Layout:
    <run_dir>/agent_outputs/year=<Y>.parquet
    <run_dir>/state_hourly/year=<Y>.parquet     (hour-major long format)
    <run_dir>/finance_series/year=<Y>.parquet

Multi-host: each process writes its OWN addressable shard rows as
``year=<Y>-p<proc>.parquet`` partitions (replicated surfaces like the
state-hourly aggregate are written by process 0 only), so a
jax.distributed run persists every surface with zero cross-host
gathers; :func:`load_surface` concatenates the parts. The reference
gets the same property from per-task Postgres writes
(dgen_model.py:459-462).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
import pandas as pd

from dgen_tpu.resilience.atomic import atomic_to_parquet, atomic_write_json
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: parquet codec: zstd beats the pyarrow default (snappy) ~2x on these
#: numeric tables at equal write speed
_PARQUET_COMPRESSION = "zstd"


def _quantize_i16(xs):
    """Device-side symmetric int16 quantization of a list of float
    arrays: per-array scale = max|x|/32766, q = round(x/scale).

    The device->host link is the export bottleneck (a remote tunnel
    moves ~6 MB/s; even PCIe fetches cost real seconds at national
    scale), so the transfer is halved ON DEVICE and the f32 values are
    reconstructed host-side as q * scale.  Error is bounded by
    max|x|/65532 per element — absolute, not relative, which is the
    right shape for the downstream aggregates (sums over agents).
    Jitted once per pytree structure; arrays are ARGUMENTS, never
    closed over (a captured device array bakes into the HLO).

    Non-finite elements are zeroed (a single inf/NaN would otherwise
    poison the whole column's scale), and the zeroed count per array
    rides back with the transfer: RunExporter accumulates it and
    stamps the per-run total into meta.json as ``nonfinite_zeroed``,
    so silently-repaired data is visible in the run's provenance
    instead of only in a debug run's invariant failure.
    """
    import jax.numpy as jnp

    qs, scales, nonfinite = [], [], []
    for x in xs:
        # a single non-finite element must not poison the whole column
        # (scale would become inf/NaN); zero it like the reference's
        # own _norm25 rule for malformed cells — counted, see above
        bad = ~jnp.isfinite(x)
        nonfinite.append(jnp.sum(bad, dtype=jnp.int32))
        x = jnp.where(bad, 0.0, x)
        # 2-D series ([n_agents, n_years]) get PER-COLUMN scales: the
        # year-0 capex column is orders of magnitude larger than the
        # out-year cash flows and a global max would waste the range
        if x.ndim > 1:
            m = jnp.max(jnp.abs(x), axis=0, keepdims=True)
        else:
            m = jnp.max(jnp.abs(x))
        scale = jnp.where(m > 0, m, 1.0).astype(jnp.float32) / 32766.0
        qs.append(
            jnp.clip(jnp.round(x / scale), -32766, 32766).astype(jnp.int16)
        )
        scales.append(scale)
    return qs, scales, nonfinite


_quantize_i16_jit = jax.jit(_quantize_i16)


def _host_rows(arr) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """(rows, global_row_idx) of the process-locally addressable part of
    a per-agent array; idx None means all rows are local (the
    single-controller case, or a fully replicated leaf)."""
    # duck-typed (not isinstance) so the multi-host path is unit-testable
    # from a single-controller test process
    if (
        getattr(arr, "is_fully_addressable", True) is False
    ):
        if arr.is_fully_replicated:
            return np.asarray(arr), None
        # distinct local shards, deduped (replication within a host
        # yields repeated index windows)
        seen: Dict[int, tuple[int, np.ndarray]] = {}
        for s in arr.addressable_shards:
            sl = s.index[0] if s.index else slice(None)
            start = sl.start or 0
            stop = sl.stop if sl.stop is not None else arr.shape[0]
            if start not in seen:
                seen[start] = (stop, np.asarray(s.data))
        starts = sorted(seen)
        rows = np.concatenate([seen[s][1] for s in starts], axis=0)
        idx = np.concatenate(
            [np.arange(s, seen[s][0]) for s in starts]
        )
        return rows, idx
    return np.asarray(arr), None

#: YearOutputs fields exported to agent_outputs (the reference drops
#: its heavy intermediate columns before writing, dgen_model.py:441-456;
#: hourly arrays and cashflow get their own surfaces here).
AGENT_OUTPUT_FIELDS = (
    "system_kw", "npv", "payback_period", "max_market_share",
    "market_share", "new_adopters", "number_of_adopters",
    "new_system_kw", "system_kw_cum", "market_value",
    "first_year_bill_with_system", "first_year_bill_without_system",
    "batt_kw", "batt_kwh", "new_batt_adopters", "batt_adopters_cum",
    "batt_kw_cum", "batt_kwh_cum",
    "carbon_intensity_t_per_kwh", "avoided_co2_t",
)

#: fields NEVER quantized under compact transfer: cumulative series
#: (whose year-over-year diffs downstream checks expect to stay
#: monotone at f32 precision) and the cumulative adopter count
_EXACT_FIELDS = frozenset(
    f for f in AGENT_OUTPUT_FIELDS if f.endswith("_cum")
) | {"number_of_adopters"}

#: quantization mask in AGENT_OUTPUT_FIELDS order (shared by the
#: deferred prepare() dispatch and the write-time fallback)
_AGENT_OUTPUT_QUANT = tuple(
    f not in _EXACT_FIELDS for f in AGENT_OUTPUT_FIELDS
)


def _dir(run_dir: str, name: str) -> str:
    d = os.path.join(run_dir, name)
    os.makedirs(d, exist_ok=True)
    return d


# ---------------------------------------------------------------------------
# Provenance stamps
# ---------------------------------------------------------------------------
#
# One definition of "which code / which configuration produced this
# answer", shared by every surface that claims provenance: RunExporter
# stamps it into each run's meta.json, and the serving front-end
# (dgen_tpu.serve.server) returns the same stamp from /healthz so an
# operator can tie a live query endpoint to the exact tree and config
# it is answering from.

@functools.lru_cache(maxsize=8)
def git_sha(root: Optional[str] = None) -> Optional[str]:
    """Short commit sha of the running checkout (None when the tree is
    not a git checkout or git is unavailable). Cached: exporters and
    health probes must not fork a subprocess per call."""
    import subprocess

    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=5,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def config_hash(*configs) -> Optional[str]:
    """12-hex digest over the given config objects (dataclasses are
    serialized field-by-field, dicts as-is) — two processes answering
    from the same configuration produce the same hash regardless of
    field order. None when no configs are given."""
    if not configs:
        return None
    blob = json.dumps(
        [
            dataclasses.asdict(c) if dataclasses.is_dataclass(c) else c
            for c in configs
        ],
        sort_keys=True, default=str,
    )
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:12]


def provenance_stamp(*configs) -> Dict[str, object]:
    """The shared provenance record: git sha, config hash (when configs
    are given), and the live backend shape."""
    return {
        "git_sha": git_sha(),
        "config_hash": config_hash(*configs),
        "jax_backend": jax.default_backend(),
        "n_devices": jax.device_count(),
    }


class RunExporter:
    """Host-side per-year writer, used as a Simulation.run callback.

    ``mask`` drops padding agents; ``agent_id`` restores stable ids.
    """

    def __init__(
        self,
        run_dir: str,
        agent_id: np.ndarray,
        mask: np.ndarray,
        state_names: Optional[Sequence[str]] = None,
        finance_series: bool = True,
        meta: Optional[Dict[str, object]] = None,
        compact: Optional[bool] = None,
        static_frame: Optional[pd.DataFrame] = None,
        manifest=None,
    ) -> None:
        self.run_dir = run_dir
        # crash-consistent artifact ledger (resilience.manifest.
        # RunManifest): every landed partition is content-hash
        # recorded and each year marked complete once its surfaces are
        # all on disk — the supervisor's resume frontier. Multi-host
        # runs must pass a per-process SHARD ledger (RunManifest with
        # shard=process_index): each process records only its own
        # parts and the coordinator-side GangManifest merge decides
        # completeness.  A single-controller ledger on a multi-process
        # run would claim completeness it cannot see, so it is dropped.
        if (
            manifest is not None and jax.process_count() > 1
            and getattr(manifest, "shard", None) is None
        ):
            manifest = None
        self._manifest = manifest
        self.keep = np.asarray(mask) > 0
        self._ids_full = np.asarray(agent_id)
        self.agent_id = self._ids_full[self.keep]
        self.state_names = list(state_names) if state_names else None
        self.finance_series = finance_series
        # compact transfer: int16-quantize the bulky float surfaces on
        # device before the host fetch and drop the energy_value detail
        # column (DGEN_TPU_EXPORT_COMPACT=0 restores full-precision f32
        # and the column). Cumulative fields stay exact either way.
        if compact is None:
            compact = os.environ.get(
                "DGEN_TPU_EXPORT_COMPACT", "1"
            ).lower() not in ("0", "off", "false")
        self.compact = bool(compact)
        self._prepared: Dict[int, dict] = {}   # year_idx -> dispatched
        # compact quantization zeroes non-finite elements before
        # scaling (see _quantize_i16); the running count is stamped
        # into meta.json after every year so a run that silently
        # repaired data says so in its provenance (0 = clean run;
        # counts cover the quantized surfaces, which are the only
        # place the zeroing happens).  The per-leaf breakdown rides
        # the ``quarantine`` meta block and every increment is logged
        # at WARNING with the offending year + leaf — zeroing a
        # symptom must never again be silent.
        self._nonfinite_zeroed = 0
        self._nonfinite_by_field: Dict[str, int] = {}
        os.makedirs(run_dir, exist_ok=True)
        # provenance stamp: ``meta`` (notably market_curves:
        # synthetic_default vs ingested, from scenario ingest) is written
        # up front so a run's outputs carry their own caveats; the
        # git-sha/backend stamp is the same record /healthz serves
        # (provenance_stamp), so run artifacts and live query endpoints
        # attribute themselves identically
        self.meta = {**provenance_stamp(),
                     "n_agents": int(self.keep.sum()),
                     "export_compact": self.compact,
                     # quantization applies only on the single-controller
                     # fast path; multi-host shard writes stay full f32
                     # even under compact (which then only drops the
                     # energy_value column)
                     "export_quantized": bool(
                         self.compact and jax.process_count() == 1),
                     "nonfinite_zeroed": 0,
                     # flipped (with per-year host_io_wall stamps) by
                     # stamp_hostio when the async pipeline drives this
                     # exporter (io.hostio)
                     "async_io": False,
                     **(meta or {})}
        self._meta_dirty = False
        if jax.process_index() == 0:
            self._write_meta()
            if static_frame is not None:
                # once per run: the static join keys refschema needs
                atomic_to_parquet(
                    static_frame,
                    os.path.join(run_dir, "agents.parquet"),
                    compression=_PARQUET_COMPRESSION,
                )
                if self._manifest is not None:
                    self._manifest.record_run_artifact("agents.parquet")

    def _part_name(self, year: int) -> str:
        """Per-year parquet partition name; multi-host runs write one
        part per process."""
        if jax.process_count() > 1:
            return f"year={year}-p{jax.process_index()}.parquet"
        return f"year={year}.parquet"

    def _local(self, arr) -> tuple[np.ndarray, np.ndarray]:
        """(rows, ids): this process's real-agent rows of a per-agent
        field, with their stable agent ids."""
        (rows,), ids = self._local_fields([arr])
        return rows, ids

    @staticmethod
    def _quant_dispatch(arrs, quant):
        """Enqueue the on-device quantization of the True-masked fields;
        returns (qs, scales, rest, nonfinite) device arrays WITHOUT
        fetching.  Used at prepare()/device_payload() time so the ops
        land on the device queue right behind the step that produced
        ``arrs`` — dispatching them at callback time instead would
        queue them behind the NEXT year's step and serialize the export
        pipeline against device compute (measured: 1M-agent exports
        1492 s vs ~130 s prepared).  With no True fields (full-
        precision mode) this is the identity bundle — the fields ride
        ``rest`` untouched."""
        q_in = [a for a, q in zip(arrs, quant) if q]
        if q_in:
            qs, scales, nonfinite = _quantize_i16_jit(q_in)
        else:
            qs, scales, nonfinite = [], [], []
        rest = [a for a, q in zip(arrs, quant) if not q]
        return qs, scales, rest, nonfinite

    def _host_reconstruct(self, host_prepared, quant,
                          names=None, year=None) -> list:
        """Host-side tail of the transfer: reassemble per-field host
        arrays in original order from a FETCHED (qs, scales, rest,
        nonfinite) bundle, f32-reconstructing the quantized fields and
        accumulating the nonfinite-zeroed provenance count.  ``names``
        (the field names in ``quant`` order) and ``year`` feed the
        WARNING log + per-leaf breakdown when anything was zeroed."""
        h_q, h_s, h_rest, h_nf = host_prepared
        self._nonfinite_zeroed += int(sum(int(c) for c in h_nf))
        if names is not None and any(int(c) for c in h_nf):
            q_names = [f for f, q in zip(names, quant) if q]
            for f, c in zip(q_names, h_nf):
                if int(c):
                    self._nonfinite_by_field[f] = (
                        self._nonfinite_by_field.get(f, 0) + int(c)
                    )
                    logger.warning(
                        "export: zeroed %d non-finite value(s) in "
                        "'%s'%s before int16 quantization — upstream "
                        "data is producing poison (see the quarantine "
                        "meta block / health sentinel)",
                        int(c), f,
                        "" if year is None else f" at year {year}",
                    )
        qi = iter(zip(h_q, h_s))
        ri = iter(h_rest)
        out = []
        for q in quant:
            if q:
                qv, s = next(qi)
                out.append(qv.astype(np.float32) * s)
            else:
                out.append(next(ri))
        return out

    def _ao_quant(self) -> tuple:
        return (_AGENT_OUTPUT_QUANT if self.compact
                else (False,) * len(AGENT_OUTPUT_FIELDS))

    def _fin_quant(self) -> tuple:
        return (True,) if self.compact else (False, False)

    def _local_fields(self, arrs, quant=None, prepared=None,
                      names=None, year=None
                      ) -> tuple[list, np.ndarray]:
        """(rows per field, ids): the fast path reuses the first field's
        shard index for follow-up fields; any field whose sharding
        differs (GSPMD may replicate one YearOutputs leaf while sharding
        its siblings) is realigned onto the first field's agent ids via
        its own index instead of being mis-sliced.

        ``quant``: optional per-field bools — True fields travel
        device->host int16-quantized (compact mode, single-controller
        fast path only; multi-host shard writes never cross a tunnel)
        and are reconstructed to f32 here.  ``prepared``: the
        already-dispatched (qs, scales, rest) from :meth:`prepare`."""
        if not any(
            getattr(a, "is_fully_addressable", True) is False for a in arrs
        ):
            # single-controller: ONE batched transfer for all fields
            # (per-leaf np.asarray costs a host round trip each)
            if prepared is None:
                if not (self.compact and quant is not None):
                    quant = (False,) * len(arrs)   # identity bundle
                prepared = self._quant_dispatch(arrs, quant)
            host = self._host_reconstruct(
                jax.device_get(list(prepared)), quant,
                names=names, year=year)
            return [h[self.keep] for h in host], self.agent_id
        first, idx = _host_rows(arrs[0])
        if idx is None:
            sel, ids = self.keep, self.agent_id
        else:
            sel = self.keep[idx]
            ids = self._ids_full[idx][sel]
        out = [first[sel]]
        for a in arrs[1:]:
            rows, a_idx = _host_rows(a)
            if (a_idx is None and idx is None) or (
                a_idx is not None and idx is not None
                and np.array_equal(a_idx, idx)
            ):
                out.append(rows[sel])
                continue
            # this leaf carries a DIFFERENT sharding than the first one
            # (GSPMD propagation can replicate one output while sharding
            # another): align on the leaf's OWN index, then reorder onto
            # the first leaf's agent ids
            a_sel = self.keep if a_idx is None else self.keep[a_idx]
            a_ids = (
                self.agent_id if a_idx is None
                else self._ids_full[a_idx][a_sel]
            )
            rows = rows[a_sel]
            if not np.array_equal(a_ids, ids):
                pos = {int(g): i for i, g in enumerate(a_ids)}
                try:
                    rows = rows[np.asarray(
                        [pos[int(g)] for g in ids], dtype=np.intp
                    )]
                except KeyError as e:
                    raise ValueError(
                        "per-agent output leaves carry incompatible "
                        "shardings: a follow-up leaf's locally "
                        f"addressable rows lack agent id {e} present in "
                        "the first leaf's window; pin YearOutputs leaves "
                        "to one sharding in year_step"
                    ) from e
            out.append(rows)
        return out, ids

    def _check_state_names(self, n_states: int) -> None:
        if self.state_names is not None and len(self.state_names) != n_states:
            raise ValueError(
                f"state_names has {len(self.state_names)} entries but the "
                f"hourly aggregate covers {n_states} states"
            )

    def prepare(self, year: int, year_idx: int, outs) -> None:
        """Dispatch the compact-transfer quantization for a year whose
        export callback is DEFERRED (Simulation.run calls this when it
        stashes the callback): the quantize ops execute right after the
        step that produced ``outs``, so the later callback only
        transfers ready arrays instead of waiting behind the next
        year's device step.  No-op for full-precision or multi-host
        runs."""
        if not self.compact:
            return
        ao = [getattr(outs, f) for f in AGENT_OUTPUT_FIELDS]
        fin = [outs.cash_flow] if self.finance_series else []
        if any(
            getattr(a, "is_fully_addressable", True) is False
            for a in ao + fin
        ):
            return   # multi-host shard writes never quantize
        pre = {"agent_outputs": self._quant_dispatch(
            ao, _AGENT_OUTPUT_QUANT)}
        if self.finance_series:
            pre["finance"] = self._quant_dispatch(fin, (True,))
        self._prepared[int(year_idx)] = pre

    def __call__(self, year: int, year_idx: int, outs) -> None:
        pre = self._prepared.pop(int(year_idx), {})
        self.write_agent_outputs(
            year, outs, prepared=pre.get("agent_outputs"))
        if self.finance_series:
            self.write_finance_series(
                year, outs, prepared=pre.get("finance"))
        # the state aggregate is replicated across hosts; one writer
        if (
            getattr(outs.state_hourly_net_mw, "size", 0)
            and jax.process_index() == 0
        ):
            self.write_state_hourly(
                year, np.asarray(outs.state_hourly_net_mw)
            )
        self._flush_meta()
        self._mark_year_complete(year)

    # --- the async host-IO pipeline's split fetch/write protocol ------
    # (io.hostio.ExportConsumer; __call__ above stays the serialized
    # parity oracle and the multi-host path)

    def device_payload(self, year: int, year_idx: int, outs):
        """Device-side export bundle for one year: quantization (or the
        full-precision identity bundle) is DISPATCHED here on the main
        thread — right behind the step that produced ``outs`` — and
        the single batched ``jax.device_get`` happens on the pipeline's
        fetch thread.  Returns None when any leaf is not fully
        addressable: multi-host shard writes keep the synchronous
        per-shard path."""
        ao = [getattr(outs, f) for f in AGENT_OUTPUT_FIELDS]
        fin = (
            ([outs.cash_flow] if self.compact
             else [outs.cash_flow, outs.energy_value_pv_only])
            if self.finance_series else []
        )
        if any(
            getattr(a, "is_fully_addressable", True) is False
            for a in ao + fin
        ):
            return None
        pre = self._prepared.pop(int(year_idx), {})
        payload = {
            "ao": pre.get("agent_outputs")
            or self._quant_dispatch(ao, self._ao_quant()),
        }
        if self.finance_series:
            payload["fin"] = (
                pre.get("finance")
                or self._quant_dispatch(fin, self._fin_quant())
            )
        if getattr(outs.state_hourly_net_mw, "size", 0):
            payload["hourly"] = outs.state_hourly_net_mw
        return payload

    def write_host(self, year: int, year_idx: int, host) -> None:
        """Write stage of the pipeline: the host-array tail of
        write_agent_outputs / write_finance_series / write_state_hourly
        over a fetched :meth:`device_payload` bundle.  Byte-identical
        parquet to the serialized path (same reconstruction, masking
        and frame layout)."""
        rows = [
            h[self.keep]
            for h in self._host_reconstruct(
                host["ao"], self._ao_quant(),
                names=AGENT_OUTPUT_FIELDS, year=year)
        ]
        self._write_ao_frame(year, rows, self.agent_id)
        if self.finance_series:
            f_rows = [
                h[self.keep]
                for h in self._host_reconstruct(
                    host["fin"], self._fin_quant(),
                    names=("cash_flow", "energy_value_pv_only"),
                    year=year)
            ]
            ev = None if self.compact else f_rows[1]
            self._write_fin_frame(year, f_rows[0], ev, self.agent_id)
        if host.get("hourly") is not None and jax.process_index() == 0:
            self.write_state_hourly(year, np.asarray(host["hourly"]))
        self._flush_meta()
        self._mark_year_complete(year)

    def stamp_hostio(self, stats: Dict[str, object]) -> None:
        """Stamp the async pipeline's provenance into meta.json:
        ``async_io`` plus the per-year ``host_io_wall`` (d2h fetch +
        write seconds) and overlap stats the pipeline measured
        (io.hostio.HostPipeline.stats)."""
        self.meta["async_io"] = True
        self.meta["host_io_wall"] = {
            str(y): w for y, w in stats.get("years", {}).items()
        }
        for k in ("host_io_s", "host_blocked_s", "overlap_efficiency"):
            if k in stats:
                self.meta[k] = stats[k]
        self._meta_dirty = True
        self._flush_meta()

    def _write_meta(self) -> None:
        """meta.json write via temp file + os.replace (resilience.
        atomic): a killed async writer can never leave truncated JSON
        behind."""
        atomic_write_json(
            os.path.join(self.run_dir, "meta.json"),
            self.meta, indent=2, default=str,
        )

    def stamp_meta(self, **kv: object) -> None:
        """Merge extra provenance into meta.json and publish it (the
        supervisor stamps its recovery report here)."""
        self.meta.update(kv)
        self._meta_dirty = True
        self._flush_meta()

    def stamp_quarantine(self, summary: Dict[str, object]) -> None:
        """Merge a quarantine-report summary (resilience.quarantine)
        into the ``quarantine`` meta block — MERGED, not replaced, so
        the exporter's own ``nonfinite_zeroed_by_field`` breakdown and
        the load-time containment record coexist."""
        block = dict(self.meta.get("quarantine") or {})
        block.update(summary)
        self.meta["quarantine"] = block
        self._meta_dirty = True
        self._flush_meta()

    def _record(self, year: int, relpath: str) -> None:
        if self._manifest is not None:
            self._manifest.record_artifact(year, relpath)

    def _mark_year_complete(self, year: int) -> None:
        if self._manifest is not None:
            self._manifest.mark_year_complete(year)

    def _flush_meta(self) -> None:
        """Re-stamp meta.json when the provenance counters changed
        (per-run provenance; process 0 owns the file)."""
        if (
            jax.process_index() != 0
            or (self.meta.get("nonfinite_zeroed") == self._nonfinite_zeroed
                and not self._meta_dirty)
        ):
            return
        self.meta["nonfinite_zeroed"] = int(self._nonfinite_zeroed)
        if self._nonfinite_by_field:
            # per-leaf breakdown beside the load-time quarantine record
            block = dict(self.meta.get("quarantine") or {})
            block["nonfinite_zeroed_by_field"] = dict(
                self._nonfinite_by_field)
            self.meta["quarantine"] = block
        self._meta_dirty = False
        self._write_meta()

    # --- agent_outputs (reference dgen_model.py:460-462) ---
    def _write_ao_frame(self, year: int, rows, ids) -> None:
        cols = dict(zip(AGENT_OUTPUT_FIELDS, rows))
        df = pd.DataFrame({"agent_id": ids, "year": year, **cols})
        rel = os.path.join("agent_outputs", self._part_name(year))
        atomic_to_parquet(
            df,
            os.path.join(_dir(self.run_dir, "agent_outputs"),
                         self._part_name(year)),
            compression=_PARQUET_COMPRESSION,
        )
        self._record(year, rel)

    def write_agent_outputs(self, year: int, outs, prepared=None) -> None:
        rows, ids = self._local_fields(
            [getattr(outs, f) for f in AGENT_OUTPUT_FIELDS],
            quant=_AGENT_OUTPUT_QUANT,
            prepared=prepared,
            names=AGENT_OUTPUT_FIELDS, year=year,
        )
        self._write_ao_frame(year, rows, ids)

    # --- agent_finance_series (reference finance_series_export.py:22) ---
    def _write_fin_frame(self, year: int, cf, ev, ids) -> None:
        data = {
            "agent_id": ids,
            "year": year,
            "cash_flow": list(cf),
        }
        if ev is not None:
            data["energy_value"] = list(ev)
        df = pd.DataFrame(data)
        rel = os.path.join("finance_series", self._part_name(year))
        atomic_to_parquet(
            df,
            os.path.join(_dir(self.run_dir, "finance_series"),
                         self._part_name(year)),
            compression=_PARQUET_COMPRESSION,
        )
        self._record(year, rel)

    def write_finance_series(self, year: int, outs, prepared=None) -> None:
        if self.compact:
            # energy_value is the detail column analysts rarely read and
            # HALF this surface's bytes; compact runs drop it (the
            # cash-flow series, the surface's point, stays)
            (cf,), ids = self._local_fields(
                [outs.cash_flow], quant=(True,),   # [n, Y+1]
                prepared=prepared,
                names=("cash_flow",), year=year,
            )
            ev = None
        else:
            (cf, ev), ids = self._local_fields(
                [outs.cash_flow, outs.energy_value_pv_only]  # [n,Y+1],[n,Y]
            )
        self._write_fin_frame(year, cf, ev, ids)

    # --- state_hourly_agg (reference attachment_rate_functions.py:151) ---
    def write_state_hourly(self, year: int, hourly: np.ndarray) -> None:
        n_states, hours = hourly.shape
        self._check_state_names(n_states)
        names = (
            self.state_names if self.state_names
            else [str(i) for i in range(n_states)]
        )
        # wide format: one row per state, hourly MW as a list column
        df = pd.DataFrame({
            "state": names,
            "year": year,
            "net_load_mw": list(hourly.astype(np.float32)),
        })
        rel = os.path.join("state_hourly", f"year={year}.parquet")
        atomic_to_parquet(
            df,
            os.path.join(_dir(self.run_dir, "state_hourly"),
                         f"year={year}.parquet"),
            compression=_PARQUET_COMPRESSION,
        )
        self._record(year, rel)


#: sector index -> the reference's sector_abbr vocabulary
SECTOR_ABBR = ("res", "com", "ind")


def static_frame_from_table(table, states: Optional[Sequence[str]] = None
                            ) -> pd.DataFrame:
    """Per-agent STATIC attributes as a host frame (real agents only):
    the join keys and weights the reference carries on every
    agent_outputs row (state_abbr, sector_abbr, customers_in_bin,
    developable_agent_weight) but that never change year over year —
    persisted once per run as ``agents.parquet`` so a run directory is
    self-contained for the reference-schema writeback (io.refschema)."""
    keep = np.asarray(table.mask) > 0
    st = np.asarray(table.state_idx)[keep]
    sec = np.asarray(table.sector_idx)[keep]
    customers = np.asarray(table.customers_in_bin)[keep]
    dev = np.asarray(
        table.developable_agent_weight(table.customers_in_bin)
    )[keep]
    state_abbr = (
        np.asarray(states, dtype=object)[st] if states is not None
        else st.astype(str)
    )
    return pd.DataFrame({
        "agent_id": np.asarray(table.agent_id)[keep],
        "state_abbr": state_abbr,
        "sector_abbr": np.asarray(SECTOR_ABBR, dtype=object)[sec],
        "customers_in_bin": customers,
        "developable_agent_weight": dev,
    })


def load_surface(run_dir: str, name: str) -> pd.DataFrame:
    """Reassemble a cross-year frame from a run's parquet partitions."""
    d = os.path.join(run_dir, name)
    parts = sorted(
        os.path.join(d, f) for f in os.listdir(d) if f.endswith(".parquet")
    )
    if not parts:
        raise FileNotFoundError(f"no parquet partitions under {d}")
    return pd.concat([pd.read_parquet(p) for p in parts], ignore_index=True)
