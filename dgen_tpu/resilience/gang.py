"""GangSupervisor: a preemption-safe jax.distributed worker gang.

The multi-process analogue of the serving fleet's
:class:`~dgen_tpu.serve.fleet.ReplicaSupervisor` — with one decisive
difference: serving replicas are independent, a simulation gang is
**all-or-nothing**.  P workers share one ``jax.distributed``
coordinator and one global mesh; a single preempted host leaves every
peer wedged inside a collective.  jax.distributed gangs are not
elastic mid-run, so recovery is always:

1. **detect** — per-worker liveness (exit codes) plus per-worker
   heartbeat files (a worker that is alive but has stopped completing
   years is STALLED: wedged device, paging storm — only staleness
   catches it);
2. **tear down** — SIGKILL the WHOLE gang (peers blocked in dead
   collectives cannot drain; the crash-consistent artifact layer is
   what makes this safe);
3. **relaunch from the manifest frontier** — the coordinator-side
   merge of the per-process shard ledgers
   (:class:`~dgen_tpu.resilience.manifest.GangManifest`) names the
   last year EVERY process durably exported; the relaunched workers
   resume from the newest checkpoint at or below it
   (:func:`dgen_tpu.parallel.elastic.resume_year_for`), re-exporting
   exactly the missing years;
4. **bounded** — restarts ride the resilience layer's
   :class:`~dgen_tpu.resilience.supervisor.RetryPolicy` backoff with a
   crash-loop breaker; when the breaker trips and
   :class:`~dgen_tpu.config.GangConfig.shrink_plan` names a smaller
   gang, the run resumes **elastically** at P′ workers — the orbax
   checkpoint written at P is re-placed under the new mesh's
   NamedSharding (:mod:`dgen_tpu.parallel.elastic`) instead of the run
   dying with the lost host.

SIGTERM to the supervisor (a preemption notice) triggers a
**synchronized emergency checkpoint**: the signal is forwarded to
every worker, whose per-year stop barrier
(:class:`~dgen_tpu.resilience.gangworker.StopFlag`) makes all P
processes agree on the save year — every shard exports and checkpoints
through the same year, then exits cleanly.

This module imports no jax: supervision is pure process/file/socket
work and must stay responsive while workers compile or wedge.

Scope: workers are spawned as LOCAL child processes — the
single-machine multi-process shape (CPU/gloo drills, CI, a single TPU
host).  A gang spanning machines plugs a remote launcher into
``cmd_for`` (an ssh/scheduler wrapper argv; heartbeats/portfiles then
need a shared filesystem) or keeps its cluster scheduler's task-level
restart and reuses the same manifest-frontier + elastic-restore
recovery from there.

Worker env contract (consumed by :mod:`dgen_tpu.resilience.gangworker`
via :func:`dgen_tpu.parallel.launch.initialize_multihost`)::

    DGEN_COORDINATOR       host:port of process 0's coordinator
    DGEN_NUM_PROCESSES     P
    DGEN_PROCESS_ID        0..P-1
    DGEN_PLATFORM          jax platform pin (cpu for test gangs)
    DGEN_CPU_DEVICES       devices per worker (cpu gangs)
    DGEN_GANG_DIR          heartbeat / done-file / log directory
    DGEN_RUN_DIR           export + shard-ledger directory
    DGEN_GANG_FRONTIER     manifest frontier year ("" = from scratch)
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from dgen_tpu.config import GangConfig
from dgen_tpu.resilience import faults as faults_mod
from dgen_tpu.resilience.atomic import atomic_write_json
from dgen_tpu.resilience.manifest import GangManifest
from dgen_tpu.resilience.supervisor import RetryPolicy
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: gang-level outcome states
COMPLETE = "complete"     # every worker exited 0, all years run
PREEMPTED = "preempted"   # clean synchronized stop before the last year
DIED = "died"             # a worker death/stall tore the gang down


# -- heartbeats / done files (shared with gangworker) ------------------------

def heartbeat_path(gang_dir: str, index: int) -> str:
    return os.path.join(gang_dir, f"worker-{index}.hb.json")


def done_path(gang_dir: str, index: int) -> str:
    return os.path.join(gang_dir, f"worker-{index}.done.json")


def write_heartbeat(path: str, **info) -> None:
    """One atomic heartbeat write (workers call this per completed
    year).  The supervisor reads freshness off the file mtime, so the
    content is diagnostics, not protocol."""
    # resilience drill hook: a ``hang`` here models a stalled-not-dead
    # worker — the heartbeat goes stale while the process stays alive,
    # and only the supervisor's staleness check can catch it
    faults_mod.fault_point("gang_heartbeat_stall")
    atomic_write_json(path, {"t": time.time(), **info})


def read_json(path: str) -> Optional[dict]:
    try:
        import json

        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port for the gang coordinator.  (Bind-and-release
    has a theoretical reuse race; each attempt draws a fresh port, so
    a collision costs one retry, not the run.)"""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def default_worker_cmd(extra_args: Sequence[str] = ()) -> Callable:
    """The standard gang worker command (all configuration rides env)."""

    def cmd_for(index: int, n_processes: int) -> List[str]:
        return [
            sys.executable, "-m", "dgen_tpu.resilience.gangworker",
            *extra_args,
        ]

    return cmd_for


# -- report ------------------------------------------------------------------

@dataclasses.dataclass
class GangAttempt:
    attempt: int
    processes: int
    frontier: Optional[int]
    outcome: str                 # COMPLETE / PREEMPTED / DIED
    reason: Optional[str] = None   # death/stall detail
    worker: Optional[int] = None
    exit_code: Optional[int] = None
    wall_s: float = 0.0


@dataclasses.dataclass
class GangReport:
    """What a supervised gang run cost — stamped into bench payloads
    (``DGEN_TPU_BENCH_GANG``) and the coordinator manifest's notes."""

    processes_initial: int = 0
    processes_final: int = 0
    attempts: List[GangAttempt] = dataclasses.field(default_factory=list)
    restarts: int = 0
    shrinks: List[str] = dataclasses.field(default_factory=list)
    #: wall seconds from the first gang death to the final clean exit
    recovery_wall_s: float = 0.0
    succeeded: bool = False
    preempted: bool = False
    #: last completed model year (from the workers' done files)
    completed_through: Optional[int] = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["recovery_wall_s"] = round(self.recovery_wall_s, 4)
        for a in d["attempts"]:
            a["wall_s"] = round(a["wall_s"], 4)
        return d


class GangCrashLoop(RuntimeError):
    """The gang died more than ``max_restarts`` times inside the
    breaker window at every process count the shrink plan allows."""

    def __init__(self, msg: str, report: GangReport) -> None:
        super().__init__(msg)
        self.gang_report = report


# -- the supervisor ----------------------------------------------------------

class GangSupervisor:
    """Launch, monitor, and restart a P-process simulation gang
    (module docstring has the recovery contract).

    Parameters
    ----------
    run_dir : export directory (per-process shard ledgers + parquet
        shards land here; the resume frontier is derived from it).
    years : the scenario's model-year grid (frontier computation and
        the post-run checkpoint recording need it).
    cmd_for : ``(index, n_processes) -> argv``; default
        :func:`default_worker_cmd`.  Tests substitute stubs.
    config / policy : :class:`~dgen_tpu.config.GangConfig` knobs and
        the restart backoff schedule.
    env_for : optional ``(index, attempt) -> dict`` of EXTRA worker
        env (drills arm per-worker fault specs on attempt 0 only).
        ``DGEN_TPU_FAULTS`` is stripped from the inherited environment
        either way.
    worker_env : env shared by every worker every attempt (population
        size, end year, ...).
    """

    def __init__(
        self,
        run_dir: str,
        years: Sequence[int],
        cmd_for: Optional[Callable[[int, int], List[str]]] = None,
        config: Optional[GangConfig] = None,
        policy: Optional[RetryPolicy] = None,
        env_for: Optional[Callable[[int, int], Optional[dict]]] = None,
        worker_env: Optional[Dict[str, str]] = None,
        gang_dir: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        seed: int = 0,
    ) -> None:
        self.run_dir = run_dir
        self.years = [int(y) for y in years]
        self.config = config or GangConfig()
        self.policy = policy or RetryPolicy()
        self._cmd_for = cmd_for or default_worker_cmd()
        self._env_for = env_for
        self.worker_env = dict(worker_env or {})
        self.gang_dir = gang_dir or tempfile.mkdtemp(prefix="dgen-gang-")
        os.makedirs(self.gang_dir, exist_ok=True)
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            run_dir, "checkpoints")
        self._rng = random.Random(seed)
        self._procs: List[subprocess.Popen] = []
        self._stop_requested = False

    # -- SIGTERM drain --------------------------------------------------

    def request_stop(self) -> None:
        """Forward a preemption notice: SIGTERM every worker (their
        per-year stop barrier synchronizes the emergency checkpoint)
        and stop restarting.  Safe from a signal handler."""
        self._stop_requested = True
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass

    def install_sigterm_drain(self) -> None:
        """Route the supervisor process's own SIGTERM to
        :meth:`request_stop` (the CLI arms this)."""
        signal.signal(signal.SIGTERM, lambda *_: self.request_stop())

    # -- spawning -------------------------------------------------------

    def _spawn_gang(self, n_processes: int, attempt: int,
                    frontier: Optional[int]) -> None:
        port = free_port(self.config.coordinator_host)
        dpp = self.config.devices_for(n_processes)
        self._procs = []
        for i in range(n_processes):
            # stale liveness files from the previous incarnation must
            # not satisfy this attempt's checks
            for path in (heartbeat_path(self.gang_dir, i),
                         done_path(self.gang_dir, i)):
                if os.path.exists(path):
                    os.unlink(path)
            env = os.environ.copy()
            # a spec meant for the supervisor must not leak into every
            # worker; drills arm per-worker specs through env_for
            env.pop("DGEN_TPU_FAULTS", None)
            if self.config.platform == "cpu":
                # the legacy host-platform device-count flag would
                # fight DGEN_CPU_DEVICES on CPU test gangs; real-TPU
                # gangs keep the operator's XLA tuning flags
                env.pop("XLA_FLAGS", None)
            env.update({
                "DGEN_COORDINATOR":
                    f"{self.config.coordinator_host}:{port}",
                "DGEN_NUM_PROCESSES": str(n_processes),
                "DGEN_PROCESS_ID": str(i),
                "DGEN_GANG_DIR": self.gang_dir,
                "DGEN_RUN_DIR": self.run_dir,
                "DGEN_GANG_FRONTIER":
                    "" if frontier is None else str(frontier),
                "PYTHONUNBUFFERED": "1",
            })
            if self.config.platform:
                env["DGEN_PLATFORM"] = self.config.platform
                if self.config.platform == "cpu":
                    env["DGEN_CPU_DEVICES"] = str(dpp)
                    env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
            env.update(self.worker_env)
            extra = self._env_for(i, attempt) if self._env_for else None
            if extra:
                env.update({k: str(v) for k, v in extra.items()})
            log_path = os.path.join(self.gang_dir, f"worker-{i}.log")
            # append-only diagnostics, not a run artifact: a torn tail
            # is exactly what a SIGKILLed worker's log should show
            logf = open(log_path, "ab")  # dgenlint: disable=L11
            try:
                self._procs.append(subprocess.Popen(
                    self._cmd_for(i, n_processes),
                    stdout=logf, stderr=subprocess.STDOUT, env=env,
                ))
            finally:
                logf.close()   # the child holds its own fd now
        logger.info(
            "gang attempt %d: %d workers x %d device(s), coordinator "
            ":%d, frontier=%s", attempt, n_processes, dpp, port,
            frontier,
        )

    def _teardown(self) -> None:
        """SIGKILL every live worker.  Peers of a dead worker are
        blocked inside dead collectives — there is nothing to drain;
        the crash-consistent artifact layer makes this safe."""
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in self._procs:
            try:
                p.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                logger.warning("gang: worker pid %d unkillable", p.pid)

    # -- monitoring -----------------------------------------------------

    def _resume_plan(self) -> Optional[int]:
        """One manifest pass per (re)launch: compute the merged resume
        frontier (deep verify — a torn frontier artifact must pull the
        resume back, so the hashing here is the safety property), then
        prune every part and ledger record BEYOND it on the same
        loaded ledgers.  Without the prune a dead epoch's partial
        shards survive an elastic P -> P' relaunch — duplicate rows
        under load_surface, and mixed epoch stamps that wedge the
        merged completeness check forever.  None = fresh directory,
        start from scratch."""
        try:
            gm = GangManifest(self.run_dir)
        except OSError:
            return None
        if not gm.shards:
            return None
        frontier = gm.frontier(self.years)
        removed = gm.prune_after(frontier)
        if removed:
            logger.info(
                "gang: pruned %d stale artifact(s) beyond frontier %s",
                len(removed), frontier,
            )
        return frontier

    #: a worker is stalled when its heartbeat is older than
    #: max(stall_timeout_s, this factor x the slowest year-over-year
    #: heartbeat gap observed across the gang) — so a gang whose
    #: steady-state years are simply long is not killed as stalled;
    #: before any gap is measured the bound is boot_timeout_s
    STALL_GRACE_FACTOR = 3.0

    def _monitor(self, n_processes: int, attempt: int,
                 frontier: Optional[int]) -> GangAttempt:
        """Watch one gang incarnation to its outcome."""
        t0 = time.monotonic()
        spawn_t = time.monotonic()
        # worker -> {mtime, has_year, gap}: heartbeat files are parsed
        # only when their mtime changes (staleness itself is pure stat)
        hb_state: Dict[int, dict] = {}
        # False even when a stop is already pending: THIS incarnation's
        # workers still need their SIGTERM forwarded (request_stop is
        # idempotent), or the synchronized emergency checkpoint the
        # stop exists for would never run
        sigterm_sent = False
        drain_deadline: Optional[float] = None
        rec = GangAttempt(
            attempt=attempt, processes=n_processes, frontier=frontier,
            outcome=DIED,
        )
        while True:
            now = time.monotonic()
            if self._stop_requested and not sigterm_sent:
                self.request_stop()   # forward to this incarnation
                sigterm_sent = True
            if sigterm_sent and drain_deadline is None:
                drain_deadline = now + self.config.drain_timeout_s

            rcs = [p.poll() for p in self._procs]
            bad = [
                (i, rc) for i, rc in enumerate(rcs)
                if rc is not None and rc != 0
            ]
            if bad:
                i, rc = bad[0]
                rec.outcome, rec.reason = DIED, "worker_exit"
                rec.worker, rec.exit_code = i, rc
                rec.wall_s = time.monotonic() - t0
                self._teardown()
                return rec
            if all(rc == 0 for rc in rcs):
                dones = [read_json(done_path(self.gang_dir, i))
                         for i in range(n_processes)]
                preempted = any(
                    d is not None and d.get("preempted") for d in dones)
                rec.outcome = PREEMPTED if preempted else COMPLETE
                rec.wall_s = time.monotonic() - t0
                rec.exit_code = 0
                return rec

            # liveness by heartbeat: boot grace until the first YEAR
            # heartbeat (distributed bring-up + first compile), then a
            # staleness bound scaled to the gang's own observed year
            # cadence (STALL_GRACE_FACTOR) with stall_timeout_s as the
            # floor — a long steady-state year is not a stall
            measured = max(
                (s["gap"] for s in hb_state.values()
                 if s.get("gap") is not None),
                default=None,
            )
            stall_bound = (
                max(self.config.stall_timeout_s,
                    self.STALL_GRACE_FACTOR * measured)
                if measured is not None
                else max(self.config.stall_timeout_s,
                         self.config.boot_timeout_s)
            )
            for i, rc in enumerate(rcs):
                if rc is not None:
                    continue
                hb = heartbeat_path(self.gang_dir, i)
                try:
                    st = os.stat(hb)
                except OSError:
                    st = None
                state = hb_state.setdefault(
                    i, {"mtime": None, "has_year": False, "gap": None})
                if st is not None and st.st_mtime != state["mtime"]:
                    doc = read_json(hb)
                    has_year = bool(doc and doc.get("year") is not None)
                    if (
                        has_year and state["has_year"]
                        and state["mtime"] is not None
                    ):
                        gap = st.st_mtime - state["mtime"]
                        state["gap"] = max(state["gap"] or 0.0, gap)
                    state["mtime"] = st.st_mtime
                    state["has_year"] = state["has_year"] or has_year
                if state["has_year"]:
                    age = time.time() - state["mtime"]
                    if age > stall_bound:
                        rec.outcome, rec.reason = DIED, "heartbeat_stall"
                        rec.worker = i
                        rec.wall_s = time.monotonic() - t0
                        self._teardown()
                        return rec
                elif now - spawn_t > self.config.boot_timeout_s:
                    rec.outcome, rec.reason = DIED, "boot_timeout"
                    rec.worker = i
                    rec.wall_s = time.monotonic() - t0
                    self._teardown()
                    return rec

            if drain_deadline is not None and now > drain_deadline:
                # workers did not finish the synchronized stop in time
                rec.outcome, rec.reason = DIED, "drain_timeout"
                rec.wall_s = time.monotonic() - t0
                self._teardown()
                return rec
            time.sleep(self.config.poll_interval_s)

    # -- the run loop ---------------------------------------------------

    def run(self) -> GangReport:
        """Drive the gang to completion (or a clean preemption stop),
        restarting from the manifest frontier on every death, shrinking
        per the plan when the crash-loop breaker trips.  Raises
        :class:`GangCrashLoop` (report attached) when the budget is
        spent.  No exit path leaks workers: any exception —
        KeyboardInterrupt in a backoff sleep, a partial spawn failure,
        a crash-loop raise — tears the live gang down on the way out
        (jax.distributed workers otherwise sit forever waiting for
        peers that will never come)."""
        try:
            return self._run_loop()
        finally:
            self._teardown()

    def _run_loop(self) -> GangReport:
        cfg = self.config
        report = GangReport(
            processes_initial=cfg.n_processes,
            processes_final=cfg.n_processes,
        )
        plan = [cfg.n_processes, *cfg.shrink_plan]
        plan_idx = 0
        deaths: deque = deque(maxlen=256)
        attempt = 0
        t_first_death: Optional[float] = None
        while True:
            n_proc = plan[plan_idx]
            report.processes_final = n_proc
            frontier = self._resume_plan()
            self._spawn_gang(n_proc, attempt, frontier)
            rec = self._monitor(n_proc, attempt, frontier)
            report.attempts.append(rec)
            if rec.outcome in (COMPLETE, PREEMPTED):
                report.succeeded = True
                report.preempted = rec.outcome == PREEMPTED
                if t_first_death is not None:
                    report.recovery_wall_s = (
                        time.monotonic() - t_first_death
                    )
                dones = [read_json(done_path(self.gang_dir, i))
                         for i in range(n_proc)]
                through = [
                    d.get("completed_through") for d in dones
                    if d is not None
                    and d.get("completed_through") is not None
                ]
                report.completed_through = (
                    min(through) if through else None
                )
                self._finalize(report)
                return report
            # a death/stall: breaker bookkeeping, then backoff/relaunch
            now = time.monotonic()
            if t_first_death is None:
                t_first_death = now
            deaths.append(now)
            logger.warning(
                "gang death (attempt %d, %s worker=%s rc=%s); frontier "
                "was %s", attempt, rec.reason, rec.worker, rec.exit_code,
                frontier,
            )
            if self._stop_requested:
                raise GangCrashLoop(
                    "gang did not drain cleanly after stop request",
                    report,
                )
            window = [t for t in deaths
                      if now - t <= cfg.restart_window_s]
            if len(window) > cfg.max_restarts:
                if plan_idx + 1 < len(plan):
                    plan_idx += 1
                    # fresh slate at P': clear the death window so the
                    # shrunk gang relaunches promptly (first-retry
                    # backoff) instead of inheriting the pre-shrink
                    # window's near-maximum exponential wait
                    deaths.clear()
                    window = []
                    msg = (
                        f"crash-loop breaker at P={n_proc}: shrinking "
                        f"to P'={plan[plan_idx]} (elastic resharded "
                        "resume from the manifest frontier)"
                    )
                    report.shrinks.append(msg)
                    logger.warning("gang: %s", msg)
                else:
                    raise GangCrashLoop(
                        f"gang crash loop: >{cfg.max_restarts} deaths "
                        f"in {cfg.restart_window_s:.0f}s at every "
                        f"process count in {plan}", report,
                    )
            backoff = self.policy.backoff_s(
                min(max(len(window) - 1, 0), 6), self._rng)
            report.restarts += 1
            time.sleep(backoff)
            attempt += 1

    def _finalize(self, report: GangReport) -> None:
        """Coordinator-side post-run recording: checkpoint tree hashes
        plus the supervision summary into ``manifest-gang.json``."""
        try:
            gm = GangManifest(self.run_dir)
        except OSError:
            return
        if os.path.isdir(self.checkpoint_dir):
            gm.record_checkpoints(self.checkpoint_dir, self.years)
        gm.note(
            f"gang supervisor: restarts={report.restarts} "
            f"P={report.processes_initial}->{report.processes_final} "
            f"preempted={report.preempted} "
            f"recovery_wall_s={report.recovery_wall_s:.3f}"
        )
