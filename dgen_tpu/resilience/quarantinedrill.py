"""The quarantine drill: prove, on CPU, that corrupt rows injected at
ingest, at bank load, and MID-run are detected, attributed to exactly
the injected rows, and contained — with the supervised run's parquet
bit-exact against a clean baseline on all non-quarantined rows.

``python -m dgen_tpu.resilience drill --quarantine`` runs it
(tools/check.sh wires the ``--fast`` smoke: the two load-time rounds).

Rounds:

* **ingest** — ``ingest_corrupt_row`` (kind ``corrupt``) damages two
  deterministic agent rows at table build (NaN customer count, an
  out-of-range tariff reference).  Load-time validation must
  quarantine exactly those rows, the run must succeed on the FIRST
  attempt (zero retries — detection beats failure), and every parquet
  partition must be byte-identical to a clean-population baseline run
  under the same quarantine report: containment means the corrupt
  values influenced nothing that survived.
* **bank** — ``bank_corrupt_row@1`` NaNs a profile-bank row at load.
  Validation must quarantine every agent referencing the row, zero the
  row, and again match the pre-quarantined clean baseline byte-for-
  byte.
* **sentinel** (skipped under ``--fast``) — ``bank_corrupt_row@3``
  flips the row MID-run, after a clean exported year.  The health
  sentinel must breach at that year (never exporting it), the
  supervisor must attribute + quarantine exactly the referencing
  agents and resume from the last checkpoint, the pre-breach years
  must stay byte-identical to an uninterrupted clean run, and the
  re-run years must be finite with the quarantined rows absent.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, Optional

import numpy as np

from dgen_tpu.resilience import faults as faults_mod
from dgen_tpu.resilience.drill import compare_run_dirs
from dgen_tpu.resilience.manifest import verify_run_dir
from dgen_tpu.resilience.quarantine import QuarantineReport
from dgen_tpu.resilience.supervisor import RetryPolicy, run_supervised
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


def _make_population(n_agents: int, seed: int = 11):
    from dgen_tpu.io import synth

    return synth.generate_population(
        n_agents, states=["DE", "CA"], seed=seed, pad_multiple=64,
    )


def _make_sim_factory(pop, inputs, cfg, sizing_iters: int = 8,
                      prequarantine: Optional[QuarantineReport] = None):
    from dgen_tpu.models.simulation import Simulation

    def make_sim(rc):
        rc = dataclasses.replace(
            rc, sizing_iters=sizing_iters, guard_retrace=True,
        )
        return Simulation(
            pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
            quarantine=prequarantine,
        )

    return make_sim


def _load_report(run_dir: str) -> QuarantineReport:
    return QuarantineReport.load(os.path.join(run_dir, "quarantine.json"))


def _exported_ids(run_dir: str, year: int) -> np.ndarray:
    import pandas as pd

    p = os.path.join(run_dir, "agent_outputs", f"year={year}.parquet")
    return np.asarray(pd.read_parquet(p, columns=["agent_id"])["agent_id"])


def _all_parquet_finite(run_dir: str) -> bool:
    import pandas as pd

    for sub in ("agent_outputs", "finance_series", "state_hourly"):
        d = os.path.join(run_dir, sub)
        if not os.path.isdir(d):
            continue
        for f in sorted(os.listdir(d)):
            if not f.endswith(".parquet"):
                continue
            df = pd.read_parquet(os.path.join(d, f))
            for col in df.columns:
                v = df[col].values
                if v.dtype == object:
                    v = np.stack(v)
                if v.dtype.kind in "fc" and not np.isfinite(v).all():
                    return False
    return True


def run_quarantine_drill(
    root: str,
    *,
    n_agents: int = 96,
    end_year: int = 2016,
    fast: bool = False,
    policy: Optional[RetryPolicy] = None,
) -> Dict[str, object]:
    """Run the quarantine drill under ``root``; returns the drill
    record (``ok`` plus per-round detail — the bench payload shape)."""
    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.models import scenario as scen

    policy = policy or RetryPolicy(max_retries=3, backoff_base_s=0.01)
    cfg = ScenarioConfig(
        name="qdrill", start_year=2014, end_year=end_year,
        anchor_years=(),
    )
    pop = _make_population(n_agents)
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
    )
    n_real = int(np.sum(np.asarray(pop.table.mask) > 0))
    rounds: Dict[str, dict] = {}
    ok = True

    def supervised(make_sim, run_dir):
        return run_supervised(
            make_sim, RunConfig(), run_dir=run_dir, collect=False,
            policy=policy,
        )

    # ---- round 1: corrupt rows at INGEST --------------------------------
    t0 = time.perf_counter()
    with faults_mod.injected("ingest_corrupt_row@1:corrupt") as reg:
        pop_c = _make_population(n_agents)
    expected_ingest = sorted(
        {int(r) % n_real for r in faults_mod.corrupt_rows()}
    )
    d_corrupt = os.path.join(root, "ingest")
    _, rep1 = supervised(
        _make_sim_factory(pop_c, inputs, cfg), d_corrupt)
    q1 = _load_report(d_corrupt)
    d_base1 = os.path.join(root, "ingest_baseline")
    _, _ = supervised(
        _make_sim_factory(pop, inputs, cfg, prequarantine=q1), d_base1)
    cmp1 = compare_run_dirs(d_base1, d_corrupt)
    verify1 = all(r.ok for r in verify_run_dir(d_corrupt))
    r1_ok = bool(
        reg.fired("ingest_corrupt_row") == 1
        and rep1.succeeded and rep1.retries == 0
        and list(q1.ids) == expected_ingest
        and cmp1["ok"] and verify1
    )
    rounds["ingest"] = {
        "fired": reg.fired("ingest_corrupt_row"),
        "retries": rep1.retries,
        "quarantined_ids": list(q1.ids),
        "expected_ids": expected_ingest,
        "parquet_bit_exact": cmp1["ok"],
        "compared": cmp1["compared"],
        "verify_ok": verify1,
        "wall_s": round(time.perf_counter() - t0, 3),
        "ok": r1_ok,
    }
    ok = ok and r1_ok
    logger.info("quarantine drill ingest: %s", "ok" if r1_ok else "FAILED")

    # ---- round 2: corrupt bank row at LOAD ------------------------------
    t0 = time.perf_counter()
    n_bank = int(np.asarray(pop.profiles.load).shape[0])
    bank_row = int(faults_mod.corrupt_rows()[0]) % n_bank
    keep = np.asarray(pop.table.mask) > 0
    li = np.asarray(pop.table.load_idx)
    expected_bank = sorted(
        int(a) for a in np.asarray(pop.table.agent_id)[
            keep & (li == bank_row)]
    )
    d_bank = os.path.join(root, "bank")
    with faults_mod.injected("bank_corrupt_row@1:corrupt") as reg2:
        _, rep2 = supervised(
            _make_sim_factory(pop, inputs, cfg), d_bank)
    q2 = _load_report(d_bank)
    d_base2 = os.path.join(root, "bank_baseline")
    _, _ = supervised(
        _make_sim_factory(pop, inputs, cfg, prequarantine=q2), d_base2)
    cmp2 = compare_run_dirs(d_base2, d_bank)
    verify2 = all(r.ok for r in verify_run_dir(d_bank))
    r2_ok = bool(
        reg2.fired("bank_corrupt_row") == 1
        and rep2.succeeded and rep2.retries == 0
        and list(q2.ids) == expected_bank
        and q2.bank_rows.get("load") == [bank_row]
        and cmp2["ok"] and verify2
    )
    rounds["bank"] = {
        "fired": reg2.fired("bank_corrupt_row"),
        "retries": rep2.retries,
        "quarantined_ids": list(q2.ids),
        "expected_ids": expected_bank,
        "bank_rows": dict(q2.bank_rows),
        "parquet_bit_exact": cmp2["ok"],
        "compared": cmp2["compared"],
        "verify_ok": verify2,
        "wall_s": round(time.perf_counter() - t0, 3),
        "ok": r2_ok,
    }
    ok = ok and r2_ok
    logger.info("quarantine drill bank: %s", "ok" if r2_ok else "FAILED")

    # ---- round 3: silent MID-run corruption -> sentinel -----------------
    if not fast:
        t0 = time.perf_counter()
        cfg3 = ScenarioConfig(
            name="qdrill-sentinel", start_year=2014,
            end_year=max(end_year, 2018), anchor_years=(),
        )
        inputs3 = scen.uniform_inputs(
            cfg3, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
        )
        d_clean = os.path.join(root, "sentinel_clean")
        _, rep_clean = supervised(
            _make_sim_factory(pop, inputs3, cfg3), d_clean)
        d_sent = os.path.join(root, "sentinel")
        # hits of bank_corrupt_row in attempt 1: #1 = Simulation
        # construction (clean), #2 = before the 2014 step, #3 = before
        # the 2016 step -> the corruption lands AFTER a clean exported
        # year, so only the sentinel can catch it
        with faults_mod.injected("bank_corrupt_row@3:corrupt") as reg3:
            _, rep3 = supervised(
                _make_sim_factory(pop, inputs3, cfg3), d_sent)
        q3 = _load_report(d_sent)
        breach_year_ok = any(
            "year-2016" in d for d in rep3.degradations
        )
        # pre-breach years byte-identical to the uninterrupted clean
        # run; the breached year re-ran under quarantine, so assert
        # finiteness + exact exclusion there instead
        pre = compare_run_dirs(d_clean, d_sent)
        pre_ok = not any(
            "year=2014" in rel for rel in pre["mismatched"]
        )
        excluded = [
            bool(np.isin(q3.ids, _exported_ids(d_sent, y)).any())
            for y in (2016, 2018)
        ]
        verify3 = all(r.ok for r in verify_run_dir(d_sent))
        r3_ok = bool(
            reg3.fired("bank_corrupt_row") == 1
            and rep_clean.retries == 0
            and rep3.succeeded and rep3.retries >= 1
            and breach_year_ok
            and list(q3.ids) == expected_bank
            and not any(excluded)
            and pre_ok
            and _all_parquet_finite(d_sent)
            and verify3
        )
        rounds["sentinel"] = {
            "fired": reg3.fired("bank_corrupt_row"),
            "retries": rep3.retries,
            "degradations": rep3.degradations,
            "quarantined_ids": list(q3.ids),
            "expected_ids": expected_bank,
            "pre_breach_bit_exact": pre_ok,
            "quarantined_absent_post_breach": not any(excluded),
            "verify_ok": verify3,
            "wall_s": round(time.perf_counter() - t0, 3),
            "ok": r3_ok,
        }
        ok = ok and r3_ok
        logger.info(
            "quarantine drill sentinel: %s", "ok" if r3_ok else "FAILED")

    return {
        "ok": ok,
        "n_agents": n_agents,
        "end_year": end_year,
        "fast": fast,
        "rounds": rounds,
    }


if __name__ == "__main__":  # manual runs: python -m ...quarantinedrill
    import tempfile

    rec = run_quarantine_drill(tempfile.mkdtemp(prefix="dgen-qdrill-"))
    print(json.dumps(rec, indent=1))
    raise SystemExit(0 if rec["ok"] else 1)
