"""The serve-fleet drill: shoot at a live multi-replica serving fleet
and prove it self-heals.

``python -m dgen_tpu.resilience drill --serve-fleet`` boots a real
fleet (N replica processes behind the routing front, all on CPU),
drives closed-loop client load through the front, and — mid-load —
**kills** one replica (``serve_replica_kill@k:kill``: ``os._exit``
with requests in flight) and **hangs** another
(``serve_replica_hang@m:hang``: the batcher worker stalls longer than
the front's forward timeout).  The drill passes only if:

* **every client request is eventually answered** — bounded
  503-retries are the one failure mode a client may see (the front
  never surfaces 502/504; terminal failures are retryable 503s with
  Retry-After);
* **answers are bit-identical to a single-replica oracle** — the
  drill computes every request's expected row in-process on one
  engine over the same synthetic population at the same bucket shape
  (``min_bucket == max_batch`` pins one compiled shape fleet-wide, so
  coalescing with strangers cannot perturb a row — docs/serve.md);
* **the fleet returns to full READY strength** — the supervisor
  restarted the killed replica (fast, via the shared AOT compile
  cache) and the hung replica's breaker re-closed after its HALF_OPEN
  probe;
* **the zero-steady-state-compile invariant holds on every replica**
  — each replica's ``/metricz`` reports the RetraceGuard compile/trace
  counts armed after warmup (the dynamic half; the static half is the
  program auditor's J5 fingerprint gate in tools/check.sh), and all
  must be zero, restarted replica included;
* **p99 stays bounded through the failure** — the client-observed
  p99 (retries included) must stay under ``p99_bound_s``.

Fault hit counts include warmup: each warmup bucket execution visits
``query_rows`` once, so a spec like ``serve_replica_kill@4:kill`` with
one bucket fires on the replica's third *served* query.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from dgen_tpu.resilience.faults import KILL_EXIT_CODE
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: what-if variants the drill load mixes in (distinct coalescing keys,
#: same compiled shape)
OVERRIDE_VARIANTS = (
    None,
    {"scale": {"itc_fraction": 0.5}},
    {"set": {"elec_price_escalator": 0.005}},
)


def _request_plan(k: int, n_agents: int, years: List[int]) -> dict:
    """Deterministic request k -> body (the oracle computes the same
    plan, so client answers are comparable row-for-row)."""
    return {
        "agent_ids": [k % n_agents],
        "year": years[k % len(years)],
        "overrides": OVERRIDE_VARIANTS[k % len(OVERRIDE_VARIANTS)],
    }


def _post(port: int, body: dict, timeout: float) -> tuple:
    from dgen_tpu.serve.fleet import http_json

    status, blob, headers = http_json(
        port, "/query", method="POST",
        body=json.dumps(body).encode(), timeout=timeout,
    )
    return status, blob, headers.get("Retry-After")


def _get(port: int, path: str, timeout: float = 5.0) -> Optional[dict]:
    from dgen_tpu.serve.fleet import HTTP_ERRORS, http_json

    try:
        status, blob, _ = http_json(port, path, timeout=timeout)
        if status != 200:
            return None
        return json.loads(blob)
    except HTTP_ERRORS:
        return None


def run_fleet_drill(
    *,
    replicas: int = 2,
    agents: int = 64,
    end_year: int = 2016,
    econ_years: int = 4,
    sizing_iters: int = 6,
    requests: int = 80,
    clients: int = 4,
    bucket: int = 8,
    kill_at: int = 4,
    hang_at: int = 24,
    hang_s: float = 6.0,
    forward_timeout_s: float = 2.5,
    max_client_retries: int = 200,
    p99_bound_s: float = 30.0,
    seed: int = 7,
    layers: bool = False,
) -> Dict[str, object]:
    """Run the drill (module docstring); returns the drill record
    (``ok`` + the numbers a bench payload stamps).

    ``layers=True`` additionally arms the production-throughput stack
    — a provenance-matched answer surface and the shared result cache
    on every replica — and, after the kill/hang load, runs a repeat
    round: request plans first computed BEFORE the kill are re-asked
    twice each through the healed fleet (restarted replica included)
    and must come back bit-identical to the oracle, with the fleet's
    surface-hit and cache-hit counters proving which engine-free path
    answered.  The drill then fails unless all three serving paths
    (surface, cache, engine fall-through) were exercised."""
    import tempfile

    from dgen_tpu.config import FleetConfig
    from dgen_tpu.serve.fleet import ReplicaSupervisor, default_replica_cmd
    from dgen_tpu.serve.server import _rows_to_json

    t_drill0 = time.perf_counter()

    # -- single-replica oracle (also pre-warms the shared compile
    # cache, which is exactly how a production fleet boots fast) ------
    serve_argv = [
        "--agents", str(agents), "--end-year", str(end_year),
        "--seed", str(seed),
        "--econ-years", str(econ_years),
        "--sizing-iters", str(sizing_iters),
        "--max-batch", str(bucket), "--min-bucket", str(bucket),
        "--max-wait-ms", "2",
    ]
    import argparse

    import dgen_tpu.serve.__main__ as serve_cli
    from dgen_tpu.serve.engine import ServeEngine

    # the oracle builds through the SAME population path the replica
    # CLI uses, so "bit-identical to a single-replica run" compares
    # like with like
    sim = serve_cli._build_sim(argparse.Namespace(
        agents=agents, start_year=2014, end_year=end_year, seed=seed,
        econ_years=econ_years, sizing_iters=sizing_iters,
    ))
    oracle = ServeEngine(sim)
    t0 = time.perf_counter()
    oracle.warmup([bucket])
    oracle_warm_s = time.perf_counter() - t0
    n_real = oracle.n_agents
    years = list(oracle.years)

    work_dir = None
    if layers:
        from dgen_tpu.serve.surface import build_surface

        work_dir = tempfile.mkdtemp(prefix="dgen-fleet-layers-")
        surf_dir = f"{work_dir}/surface"
        cache_dir = f"{work_dir}/resultcache"
        build_surface(oracle, surf_dir, bucket)
        serve_argv += ["--surface", surf_dir, "--cache-dir", cache_dir]

    expected: List[dict] = []
    for k in range(requests):
        plan = _request_plan(k, n_real, years)
        out = oracle.query(
            plan["agent_ids"], year=plan["year"],
            overrides=plan["overrides"], bucket=bucket,
        )
        expected.append(_rows_to_json(out, cash_flow=False)[0])

    # -- the fleet, with per-replica fault specs on incarnation 0 -----
    def env_for(index: int, spawn_count: int) -> Optional[dict]:
        if spawn_count != 0:
            return None   # a restarted replica comes back clean
        if index == 0:
            return {"DGEN_TPU_FAULTS":
                    f"serve_replica_kill@{kill_at}:kill"}
        if index == 1 and replicas > 1:
            return {
                "DGEN_TPU_FAULTS":
                    f"serve_replica_hang@{hang_at}:hang",
                "DGEN_TPU_FAULT_HANG_S": str(hang_s),
            }
        return None

    fleet_cfg = FleetConfig(
        n_replicas=replicas, port=0,
        poll_interval_s=0.1,
        request_timeout_s=forward_timeout_s,
        breaker_failures=2, breaker_cooldown_s=1.0,
        retry_after_s=0.0,
        metricz_interval_s=0.25,
    )
    sup = ReplicaSupervisor(
        default_replica_cmd(serve_argv), fleet_cfg, env_for=env_for,
    ).start()
    try:
        rec = _drive_fleet(
            sup, fleet_cfg, expected=expected, n_real=n_real,
            years=years, replicas=replicas, agents=agents,
            requests=requests, clients=clients,
            kill_at=kill_at, hang_at=hang_at, hang_s=hang_s,
            forward_timeout_s=forward_timeout_s,
            max_client_retries=max_client_retries,
            p99_bound_s=p99_bound_s, layers=layers,
        )
    finally:
        # no exception path may leak N serving subprocesses — the CI
        # lint gate runs this drill on every push.  Idempotent: the
        # success path already drained + stopped the fleet.
        sup.stop(drain=False, timeout=10.0)
        if work_dir is not None:
            import shutil

            shutil.rmtree(work_dir, ignore_errors=True)
    rec["oracle_warmup_s"] = round(oracle_warm_s, 3)
    rec["drill_wall_s"] = round(time.perf_counter() - t_drill0, 3)
    logger.info(
        "serve-fleet drill: %s (answered %d/%d, 503-retries %d, "
        "mismatches %d, kill recovery %.2fs, p99 %.2fs)",
        "ok" if rec["ok"] else "FAILED", rec["answered"], requests,
        rec["retries_503"], len(rec["mismatches"]),
        rec["kill"]["recovery_s"] or -1.0, rec["latency_s"]["p99"],
    )
    return rec


def _drive_fleet(
    sup, fleet_cfg, *, expected, n_real, years, replicas, agents,
    requests, clients, kill_at, hang_at, hang_s, forward_timeout_s,
    max_client_retries, p99_bound_s, layers=False,
) -> Dict[str, object]:
    """The fleet-facing half of the drill: load, faults, asserts.
    Runs under run_fleet_drill's finally so the fleet is always torn
    down."""
    from dgen_tpu.serve.fleet import HTTP_ERRORS as http_errors
    from dgen_tpu.serve.front import FleetFront, start_front_in_thread

    booted = sup.wait_ready(timeout=120.0)
    boot_reports = {}
    for h in sup.ready_handles():
        hz = _get(h.port, "/healthz") or {}
        boot_reports[h.index] = hz.get("boot")
    front = FleetFront(sup, fleet_cfg).start()
    srv = start_front_in_thread(front)
    front_port = srv.server_address[1]

    # -- closed-loop load ---------------------------------------------
    answers: Dict[int, dict] = {}
    failures: List[dict] = []
    latencies: List[float] = []
    retries_503 = [0]
    next_k = iter(range(requests))
    next_lock = threading.Lock()
    rec_lock = threading.Lock()

    def client() -> None:
        while True:
            with next_lock:
                k = next(next_k, None)
            if k is None:
                return
            plan = _request_plan(k, n_real, years)
            t0 = time.monotonic()
            status, blob, retry_after = None, b"", None
            for attempt in range(max_client_retries + 1):
                try:
                    status, blob, retry_after = _post(
                        front_port, plan,
                        timeout=2 * forward_timeout_s + 10.0,
                    )
                except http_errors as e:
                    status, blob = -1, repr(e).encode()
                if status == 200:
                    break
                # the contract: the ONLY retryable client-visible
                # failure is 503 (+ Retry-After); anything else is a
                # drill failure recorded below
                if status != 503:
                    break
                with rec_lock:
                    retries_503[0] += 1
                time.sleep(min(float(retry_after or 0.1) or 0.1, 0.5))
            wall = time.monotonic() - t0
            with rec_lock:
                latencies.append(wall)
                if status == 200:
                    answers[k] = json.loads(blob)
                else:
                    failures.append({
                        "k": k, "status": status,
                        "body": blob[:200].decode("utf-8", "replace"),
                    })

    t_load0 = time.perf_counter()
    threads = [
        threading.Thread(target=client, daemon=True,
                         name=f"drill-client-{i}")
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600.0)
    load_wall_s = time.perf_counter() - t_load0

    # -- post-load asserts --------------------------------------------
    # the killed replica must be back: full READY strength
    recovered = sup.wait_ready(timeout=90.0)

    # layered repeat round: plans first computed BEFORE the kill are
    # re-asked twice each through the healed fleet — zero-override
    # plans answer from the surface mmap, override plans' second ask
    # answers from the shared result cache (whichever replica gets it,
    # the restarted one included), all bit-identical to the oracle
    repeat_mismatches: List[int] = []
    repeat_failures = 0
    if layers:
        for k in range(min(12, len(expected))):
            plan = _request_plan(k, n_real, years)
            for _ask in range(2):
                status, blob = None, b""
                for _r in range(60):
                    try:
                        status, blob, _ra = _post(
                            front_port, plan,
                            timeout=2 * forward_timeout_s + 10.0,
                        )
                    except http_errors:
                        status = -1
                    if status not in (503, -1):
                        break
                    time.sleep(0.1)
                if status != 200:
                    repeat_failures += 1
                    continue
                row = (json.loads(blob).get("results") or [None])[0]
                if row != expected[k]:
                    repeat_mismatches.append(k)

    mismatches = []
    for k, got in sorted(answers.items()):
        want_row = expected[k]
        rows = got.get("results") or [None]
        if rows[0] != want_row:
            mismatches.append(k)

    kill_seen = KILL_EXIT_CODE in sup.replicas[0].exit_codes
    hang_fired = 0
    steady_compiles: Dict[str, Optional[int]] = {}
    steady_traces: Dict[str, Optional[int]] = {}
    surface_hits_total = 0
    cache_totals = {"hits": 0, "misses": 0, "stores": 0, "evictions": 0}
    engine_batches_total = 0
    for h in sup.ready_handles():
        mz = _get(h.port, "/metricz") or {}
        steady_compiles[str(h.index)] = mz.get("steady_state_compiles")
        steady_traces[str(h.index)] = mz.get("steady_state_traces")
        hang_fired += int(
            (mz.get("faults_fired") or {}).get("serve_replica_hang", 0))
        surface_hits_total += int(mz.get("surface_hits", 0) or 0)
        engine_batches_total += int(mz.get("batches", 0) or 0)
        for key in cache_totals:
            cache_totals[key] += int(
                (mz.get("result_cache") or {}).get(key, 0) or 0)

    lat = np.asarray(sorted(latencies), dtype=np.float64)
    p50 = float(np.percentile(lat, 50)) if lat.size else 0.0
    p99 = float(np.percentile(lat, 99)) if lat.size else 0.0

    front_mz = front.metricz()

    from dgen_tpu.serve.front import drain_front

    drained = drain_front(front, srv)
    srv.server_close()

    compiles_clean = all(
        c == 0 for c in steady_compiles.values()
    ) and bool(steady_compiles)
    layers_ok = True
    if layers:
        # all three serving paths exercised, bit-exact, and the
        # cache-hit path proven AFTER the kill (the repeat round ran
        # against the healed fleet, restarted replica included)
        layers_ok = bool(
            surface_hits_total > 0
            and cache_totals["hits"] > 0
            and engine_batches_total > 0
            and not repeat_mismatches
            and repeat_failures == 0
        )
    ok = bool(
        booted
        and len(answers) == requests
        and not failures
        and not mismatches
        and recovered
        and kill_seen
        and (hang_fired >= 1 if replicas > 1 else True)
        and compiles_clean
        and p99 <= p99_bound_s
        and layers_ok
    )
    rec = {
        "ok": ok,
        "replicas": replicas,
        "agents": agents,
        "requests": requests,
        "answered": len(answers),
        "mismatches": mismatches,
        "client_failures": failures,
        "retries_503": retries_503[0],
        "booted": booted,
        "recovered_full_strength": recovered,
        "kill": {
            "spec": f"serve_replica_kill@{kill_at}:kill",
            "exit_77_seen": kill_seen,
            "recovery_s": sup.replicas[0].last_recovery_s,
            "restart_boot_wall_s": sup.replicas[0].boot_wall_s,
        },
        "hang": {
            "spec": f"serve_replica_hang@{hang_at}:hang",
            "hang_s": hang_s,
            "fired": hang_fired,
        },
        "steady_state_compiles": steady_compiles,
        "steady_state_traces": steady_traces,
        "layers": (
            {
                "surface_hits": surface_hits_total,
                "result_cache": cache_totals,
                "engine_batches": engine_batches_total,
                "repeat_mismatches": repeat_mismatches,
                "repeat_failures": repeat_failures,
            } if layers else None
        ),
        "latency_s": {
            "p50": round(p50, 3),
            "p99": round(p99, 3),
            "max": round(float(lat.max()) if lat.size else 0.0, 3),
            "p99_bound_s": p99_bound_s,
        },
        "front": {
            k: front_mz.get(k)
            for k in ("requests", "shed", "retries",
                      "forward_failures", "unrouted")
        },
        "boot": boot_reports,
        "load_wall_s": round(load_wall_s, 3),
        "drained": drained,
        "supervisor_events": list(sup.events),
    }
    return rec


def run_scale_drill(
    *,
    agents: int = 64,
    end_year: int = 2016,
    econ_years: int = 4,
    sizing_iters: int = 6,
    bucket: int = 8,
    seed: int = 7,
    ready_timeout_s: float = 180.0,
) -> Dict[str, object]:
    """The autoscale + cache round-trip drill (the tools/check.sh
    cache+autoscale leg): boot a 1-replica fleet with the autoscaler
    armed on a SYNTHETIC occupancy signal, drive it 1 -> 2 -> 1, and
    prove a cache hit byte-identical to the engine answer along the
    way.  Passes only if:

    * sustained synthetic pressure scales the fleet to 2 READY
      replicas (the new replica boots off the shared compile cache and
      is readiness-gated like any other);
    * a what-if query asked twice comes back BYTE-IDENTICAL both
      times and to an in-process engine oracle, with the fleet's
      result-cache hit counter proving the second answer never touched
      the engine;
    * sustained synthetic idleness drains the fleet back to 1 (the
      retired replica exits via SIGTERM drain, is never restarted, and
      its exit is not counted as a death);
    * both scale events land in the fleet ledger.
    """
    import argparse
    import shutil
    import tempfile

    import dgen_tpu.serve.__main__ as serve_cli
    from dgen_tpu.config import FleetConfig
    from dgen_tpu.serve.autoscale import Autoscaler
    from dgen_tpu.serve.engine import ServeEngine
    from dgen_tpu.serve.fleet import (
        STOPPED,
        ReplicaSupervisor,
        default_replica_cmd,
    )
    from dgen_tpu.serve.front import (
        FleetFront,
        drain_front,
        start_front_in_thread,
    )
    from dgen_tpu.serve.server import _rows_to_json
    from dgen_tpu.serve.surface import build_surface

    t0 = time.perf_counter()
    work_dir = tempfile.mkdtemp(prefix="dgen-scale-drill-")
    surf_dir = f"{work_dir}/surface"
    cache_dir = f"{work_dir}/resultcache"

    # in-process oracle over the same population path as the replicas
    sim = serve_cli._build_sim(argparse.Namespace(
        agents=agents, start_year=2014, end_year=end_year, seed=seed,
        econ_years=econ_years, sizing_iters=sizing_iters,
    ))
    oracle = ServeEngine(sim)
    oracle.warmup([bucket])
    build_surface(oracle, surf_dir, bucket)
    years = list(oracle.years)
    overrides = {"scale": {"itc_fraction": 0.5}}
    want = _rows_to_json(
        oracle.query([1], year=years[0], overrides=overrides,
                     bucket=bucket),
        cash_flow=False,
    )[0]

    serve_argv = [
        "--agents", str(agents), "--end-year", str(end_year),
        "--seed", str(seed), "--econ-years", str(econ_years),
        "--sizing-iters", str(sizing_iters),
        "--max-batch", str(bucket), "--min-bucket", str(bucket),
        "--max-wait-ms", "2",
        "--surface", surf_dir, "--cache-dir", cache_dir,
    ]
    cfg = FleetConfig(
        n_replicas=1, port=0, poll_interval_s=0.1,
        request_timeout_s=10.0, retry_after_s=0.0,
        metricz_interval_s=0.2,
        autoscale=True, min_replicas=1, max_replicas=2,
        scale_up_queue_frac=0.5, scale_up_occupancy=0.8,
        scale_up_sustain_s=0.3, scale_down_queue_frac=0.05,
        scale_down_occupancy=0.2, scale_down_sustain_s=0.3,
        scale_cooldown_s=0.5, scale_interval_s=0.05,
    )
    # SYNTHETIC occupancy: the drill scripts the pressure signal so
    # the 1 -> 2 -> 1 round-trip is deterministic (real-signal scaling
    # is exercised by the bench; this leg gates the mechanism)
    phase = {"hot": False}

    def signal_fn():
        if phase["hot"]:
            return {"queue_frac": 0.9, "occupancy": 0.95}
        return {"queue_frac": 0.0, "occupancy": 0.0}

    sup = ReplicaSupervisor(default_replica_cmd(serve_argv), cfg).start()
    scaler = Autoscaler(sup, signal_fn, cfg)
    front = FleetFront(sup, cfg).start()
    srv = None
    try:
        booted = sup.wait_ready(n=1, timeout=ready_timeout_s)
        srv = start_front_in_thread(front)
        front_port = srv.server_address[1]
        scaler.start()

        # cache round 1: miss -> engine -> store (replica 0)
        body = {"agent_ids": [1], "year": years[0],
                "overrides": overrides}
        s1, b1, _ = _post(front_port, body, timeout=60.0)
        ans1 = (json.loads(b1).get("results") or [None])[0] \
            if s1 == 200 else None

        # scale up: sustained synthetic pressure -> 2 READY replicas
        phase["hot"] = True
        scaled_up = False
        deadline = time.monotonic() + ready_timeout_s
        while time.monotonic() < deadline:
            if sup.wait_ready(n=2, timeout=1.0):
                scaled_up = True
                break
        # cache round 2 at full strength: byte-identical, from cache
        s2, b2, _ = _post(front_port, body, timeout=60.0)
        ans2 = (json.loads(b2).get("results") or [None])[0] \
            if s2 == 200 else None
        # let the scrape thread pick the hit counters up before the
        # aggregate read (3x the scrape cadence = the freshness bound)
        time.sleep(3 * cfg.metricz_interval_s)
        mz_up = front.metricz()

        # scale down: sustained synthetic idleness -> back to 1
        phase["hot"] = False
        scaled_down = False
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if sup.live_count() == 1:
                scaled_down = True
                break
            time.sleep(0.1)
        # the retired replica must actually exit (SIGTERM drain), and
        # must not be counted as a death or restarted
        retired = [h for h in sup.replicas if h.state == STOPPED]
        retired_exited = False
        if retired:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if all(h.proc is not None and h.proc.poll() is not None
                       for h in retired):
                    retired_exited = True
                    break
                time.sleep(0.1)
        still_one_ready = sup.wait_ready(n=1, timeout=30.0)

        events = [e["event"] for e in sup.events]
        cache_mz = (mz_up.get("result_cache") or {})
        ok = bool(
            booted
            and scaled_up
            and scaled_down
            and retired_exited
            and still_one_ready
            and s1 == 200 and s2 == 200
            and ans1 is not None and ans1 == want and ans2 == want
            and cache_mz.get("hits", 0) >= 1
            and "autoscale_up" in events
            and "autoscale_down" in events
            and not any(
                h.deaths for h in sup.replicas
            )   # nothing died: growth and retirement only
        )
        rec = {
            "ok": ok,
            "booted": booted,
            "scaled_up": scaled_up,
            "scaled_down": scaled_down,
            "retired_exited": retired_exited,
            "back_to_one_ready": still_one_ready,
            "cache_answer_byte_identical": (
                ans1 == want and ans2 == want),
            "result_cache": cache_mz,
            "surface_hits": mz_up.get("surface_hits"),
            "autoscale_events": scaler.events,
            "scale_ups": scaler.n_scale_up,
            "scale_downs": scaler.n_scale_down,
            "supervisor_events": [
                e for e in sup.events
                if e["event"].startswith(("autoscale", "scale"))
            ],
            "drill_wall_s": round(time.perf_counter() - t0, 3),
        }
    finally:
        scaler.stop()
        if srv is not None:
            drain_front(front, srv)
            srv.server_close()
        sup.stop(drain=False, timeout=10.0)
        shutil.rmtree(work_dir, ignore_errors=True)
    logger.info(
        "serve-scale drill: %s (up=%s down=%s cache_hits=%s)",
        "ok" if rec["ok"] else "FAILED", rec["scaled_up"],
        rec["scaled_down"], (rec["result_cache"] or {}).get("hits"),
    )
    return rec
