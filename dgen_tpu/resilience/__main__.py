"""CLI: ``python -m dgen_tpu.resilience {run,verify,drill}``.

``run``
    A supervised synthetic-population run: bounded retry + checkpoint
    resume + degradation policies, with crash-consistent exports and a
    content-hashed manifest.  ``--faults`` (or ``DGEN_TPU_FAULTS``)
    injects deterministic failures to exercise the recovery paths::

        python -m dgen_tpu.resilience run --agents 512 --end-year 2030 \\
            --run-dir runs/supervised --faults "ckpt_save@3;year_step@4:oom"

``verify``
    Audit any manifested run directory (content hashes, byte counts,
    stale temp files, checkpoint trees)::

        python -m dgen_tpu.resilience verify runs/supervised

    Exit 0 when every manifest verifies; 1 when anything is missing or
    corrupt.

``drill``
    The full fault matrix on a small CPU population — every run-path
    fault site injected mid-run, recovered, and compared bit-exact
    against an uninterrupted baseline (tools/check.sh runs a smoke
    configuration of this).  ``--serve-fleet`` runs the serving-fleet
    drill instead: a real replica fleet behind the routing front with
    a replica killed and a replica hung under closed-loop load,
    asserted self-healing with answers bit-identical to a
    single-replica oracle (docs/serve.md "Fleet operations")::

        python -m dgen_tpu.resilience drill --serve-fleet --replicas 2
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile


def _cmd_run(args) -> int:
    from dgen_tpu.config import RunConfig
    from dgen_tpu.resilience import faults
    from dgen_tpu.resilience.drill import make_synth_runner
    from dgen_tpu.resilience.supervisor import RetryPolicy, run_supervised
    from dgen_tpu.utils import compilecache

    compilecache.enable()
    if args.faults:
        faults.install(faults.FaultRegistry.parse(args.faults))
    else:
        faults.install_from_env()

    make_sim = make_synth_runner(
        n_agents=args.agents, states=tuple(args.states),
        end_year=args.end_year, sizing_iters=args.sizing_iters,
    )
    policy = RetryPolicy(
        max_retries=args.max_retries,
        min_agent_chunk=args.min_chunk,
    )
    try:
        res, report = run_supervised(
            make_sim, RunConfig(), run_dir=args.run_dir,
            checkpoint_dir=args.checkpoint_dir, collect=False,
            policy=policy, resume=args.resume,
        )
    except BaseException as e:  # noqa: BLE001 — CLI boundary
        rep = getattr(e, "supervisor_report", None)
        print(json.dumps({
            "ok": False,
            "error": repr(e),
            "report": rep.to_json() if rep is not None else None,
        }, indent=1))
        return 1
    print(json.dumps({
        "ok": True,
        "run_dir": args.run_dir,
        "years": res.years,
        "report": report.to_json(),
    }, indent=1))
    return 0


def _cmd_verify(args) -> int:
    from dgen_tpu.resilience.manifest import verify_run_dir

    try:
        reports = verify_run_dir(args.run_dir, deep=not args.shallow)
    except FileNotFoundError as e:
        print(f"verify: {e}", file=sys.stderr)
        return 2
    ok = all(r.ok for r in reports)
    print(json.dumps(
        {"ok": ok, "reports": [r.to_json() for r in reports]}, indent=1,
    ))
    return 0 if ok else 1


def _locktrace_verdict(rec: dict) -> dict:
    """Merge the runtime lock sentinel's verdict into a drill record:
    any observed lock-order cycle or contended over-ceiling hold fails
    the drill, with the witness dumped to stderr.  No-op unless the
    drill ran with ``DGEN_TPU_LOCKTRACE=1`` (tools/check.sh arms the
    fleet/gang/serve-scale legs)."""
    from dgen_tpu.utils import locktrace

    if not locktrace.is_armed():
        return rec
    report = locktrace.check()
    rec["locktrace"] = {
        "ok": report["ok"],
        "n_locks": len(report["locks"]),
        "n_edges": report["n_edges"],
        "cycle": report["cycle"],
        "hold_violations": report["hold_violations"],
    }
    if not report["ok"]:
        rec["ok"] = False
        print(locktrace.format_report(report), file=sys.stderr)
    return rec


def _cmd_drill(args) -> int:
    from dgen_tpu.resilience.drill import DRILL_SPECS, run_drill
    from dgen_tpu.utils import compilecache, locktrace

    # arm BEFORE the serving stack is constructed: locks created
    # earlier keep their raw C implementation and go untraced
    locktrace.arm_from_env()
    compilecache.enable()
    end_year = args.end_year or (2018 if args.gang else 2016)
    if args.gang:
        from dgen_tpu.resilience.gangdrill import run_gang_drill

        root = args.root or tempfile.mkdtemp(prefix="dgen-gang-drill-")
        rec = run_gang_drill(
            root,
            processes=args.gang_processes,
            shrink_to=args.gang_shrink,
            total_devices=args.gang_devices or None,
            agents=args.agents,
            end_year=end_year,
            stall=not args.no_gang_stall,
        )
        rec = _locktrace_verdict(rec)
        print(json.dumps(rec, indent=1))
        return 0 if rec["ok"] else 1
    if args.quarantine:
        from dgen_tpu.resilience.quarantinedrill import run_quarantine_drill

        root = args.root or tempfile.mkdtemp(prefix="dgen-qdrill-")
        rec = run_quarantine_drill(
            root, n_agents=args.agents, end_year=end_year,
            fast=args.fast,
        )
        rec = _locktrace_verdict(rec)
        print(json.dumps(rec, indent=1))
        return 0 if rec["ok"] else 1
    if args.serve_scale:
        from dgen_tpu.resilience.fleetdrill import run_scale_drill

        rec = run_scale_drill(agents=args.agents, end_year=end_year)
        rec.pop("supervisor_events", None)
        rec = _locktrace_verdict(rec)
        print(json.dumps(rec, indent=1))
        return 0 if rec["ok"] else 1
    if args.serve_fleet:
        from dgen_tpu.resilience.fleetdrill import run_fleet_drill

        rec = run_fleet_drill(
            replicas=args.replicas, agents=args.agents,
            end_year=end_year, requests=args.requests,
            layers=args.layers,
        )
        # the event/boot detail is for logs, not the summary line
        rec.pop("supervisor_events", None)
        rec = _locktrace_verdict(rec)
        print(json.dumps(rec, indent=1))
        return 0 if rec["ok"] else 1
    root = args.root or tempfile.mkdtemp(prefix="dgen-fault-drill-")
    specs = DRILL_SPECS
    if args.sites:
        wanted = set(args.sites.split(","))
        specs = tuple(s for s in DRILL_SPECS if s[0] in wanted)
        unknown = wanted - {s[0] for s in DRILL_SPECS}
        if unknown:
            print(f"drill: unknown site(s) {sorted(unknown)}",
                  file=sys.stderr)
            return 2
    rec = run_drill(
        root, n_agents=args.agents, end_year=end_year, specs=specs,
    )
    rec = _locktrace_verdict(rec)
    print(json.dumps(rec, indent=1))
    return 0 if rec["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgen_tpu.resilience",
        description="fault-injected, self-healing run supervision "
                    "(docs/resilience.md)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="supervised synthetic run")
    run.add_argument("--agents", type=int, default=512)
    run.add_argument("--states", nargs="*", default=["DE", "CA", "TX"])
    run.add_argument("--end-year", type=int, default=2030)
    run.add_argument("--sizing-iters", type=int, default=8)
    run.add_argument("--run-dir", required=True)
    run.add_argument("--checkpoint-dir", default=None,
                     help="default: <run-dir>/checkpoints")
    run.add_argument("--faults", default=None,
                     help="fault spec (resilience.faults grammar)")
    run.add_argument("--max-retries", type=int, default=4)
    run.add_argument("--min-chunk", type=int, default=128,
                     help="OOM chunk-halving floor")
    run.add_argument("--resume", action="store_true",
                     help="resume an existing run directory")
    run.set_defaults(fn=_cmd_run)

    ver = sub.add_parser("verify", help="audit a run directory")
    ver.add_argument("run_dir")
    ver.add_argument("--shallow", action="store_true",
                     help="existence + byte counts only (no re-hash)")
    ver.set_defaults(fn=_cmd_verify)

    drl = sub.add_parser("drill", help="fault matrix smoke drill")
    drl.add_argument("--agents", type=int, default=96)
    drl.add_argument("--end-year", type=int, default=None,
                     help="last model year (default 2016; 2018 for "
                          "--gang so the stall round has a steady-"
                          "state year to land in)")
    drl.add_argument("--root", default=None,
                     help="drill directory (default: a fresh tempdir)")
    drl.add_argument("--sites", default=None,
                     help="comma list of drill names to run "
                          "(default: the full matrix)")
    drl.add_argument("--quarantine", action="store_true",
                     help="quarantine drill instead: corrupt rows "
                          "injected at ingest, at bank load, and "
                          "mid-run (the health sentinel's case) must "
                          "be detected, attributed to exactly the "
                          "injected rows, and contained — parquet "
                          "bit-exact vs a clean pre-quarantined "
                          "baseline (docs/resilience.md 'Data "
                          "quarantine & health sentinel')")
    drl.add_argument("--fast", action="store_true",
                     help="quarantine drill: load-time rounds only "
                          "(the check.sh smoke tier); skips the "
                          "mid-run sentinel round")
    drl.add_argument("--serve-fleet", action="store_true",
                     help="fleet drill instead: boot a replica fleet, "
                          "kill + hang replicas under closed-loop "
                          "load, assert self-healing + bit-exact "
                          "answers (docs/serve.md)")
    drl.add_argument("--gang", action="store_true",
                     help="gang drill instead: a multi-process CPU/gloo "
                          "jax.distributed gang with a worker "
                          "SIGKILLed mid-year, a worker stalled, and a "
                          "P->P' elastic resharded resume — parquet "
                          "shards byte-identical to an uninterrupted "
                          "baseline, merged-manifest verify clean "
                          "(docs/resilience.md 'Gang runbook'). "
                          "--end-year 2018+ (>= 3 model years) enables "
                          "the stall round")
    drl.add_argument("--gang-processes", type=int, default=4,
                     help="gang drill: worker process count P")
    drl.add_argument("--gang-shrink", type=int, default=2,
                     help="gang drill: elastic-resume process count P' "
                          "(0 = skip the elastic round)")
    drl.add_argument("--gang-devices", type=int, default=0,
                     help="gang drill: total devices across the gang "
                          "(0 = one per worker); kept constant through "
                          "the P->P' shrink so resumes are bit-exact")
    drl.add_argument("--no-gang-stall", action="store_true",
                     help="gang drill: skip the heartbeat-stall round")
    drl.add_argument("--serve-scale", action="store_true",
                     help="autoscale drill instead: a 1-replica fleet "
                          "scaled 1 -> 2 -> 1 by the autoscaler under "
                          "synthetic occupancy, with a result-cache "
                          "hit proven byte-identical to the engine "
                          "answer (docs/serve.md 'Production "
                          "throughput')")
    drl.add_argument("--layers", action="store_true",
                     help="fleet drill: arm the answer surface + "
                          "shared result cache on every replica and "
                          "prove all three serving paths (surface, "
                          "cache, engine) bit-exact through the "
                          "kill, cache hits included")
    drl.add_argument("--replicas", type=int, default=2,
                     help="fleet drill: replica count")
    drl.add_argument("--requests", type=int, default=80,
                     help="fleet drill: client requests")
    drl.set_defaults(fn=_cmd_drill)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
