"""The self-healing run supervisor: bounded retry, checkpoint resume,
and graceful degradation around ``Simulation.run``/sweep runs.

Everything below ``dgen_tpu.resilience`` assumes a process that can die
at any instruction; this module is the layer that turns those deaths
into bounded recovery instead of lost work:

* **classify** — an escaped exception is sorted into ``oom`` /
  ``hostio`` / ``transient`` / ``fatal`` (:func:`classify_error`).
  Fatal errors (programming bugs: ``ValueError``, ``TypeError``,
  assertion failures) re-raise immediately — retrying a bug is noise.
* **retry** — everything else retries under exponential backoff with
  deterministic jitter, bounded by :class:`RetryPolicy.max_retries`.
* **resume** — each retry re-enters from the **crash-consistent resume
  frontier**: the latest valid checkpoint year ``C`` such that every
  model year ``<= C`` is durably exported per the run's
  :class:`~dgen_tpu.resilience.manifest.RunManifest`.  Years after the
  frontier are re-run and re-exported (atomically, over any partial
  leftovers) — exactly the missing years, nothing else.
* **degrade** — classified errors trigger policy responses:

  - ``oom`` → halve ``RunConfig.agent_chunk`` (riding the existing
    ``auto_agent_chunk`` streaming machinery — a smaller chunk is a
    smaller peak working set, at more scan steps) and re-enter;
  - repeated ``hostio`` → fall back to the serialized host-IO oracle
    path (``async_host_io=False``) with a warning stamped into the
    manifest and the exporter's meta.json.

Use :func:`run_supervised` for the batteries-included Simulation path,
or :class:`Supervisor` directly to wrap anything attempt-shaped (the
sweep engine's ``run(resume=True)`` slots straight in).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional

from dgen_tpu.resilience import faults as faults_mod
from dgen_tpu.resilience.manifest import RunManifest
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

# -- error classification ----------------------------------------------------

OOM = "oom"
HOSTIO = "hostio"
TRANSIENT = "transient"
FATAL = "fatal"
#: a numerical-health sentinel breach (models.health.HealthBreachError):
#: the degradation quarantines the attributed agents
#: (RunConfig.quarantine_ids) and re-enters from the resume frontier —
#: the breached year re-runs with the offenders contained
HEALTH = "health"

#: substrings that mark a device allocation failure in XLA/runtime
#: errors (real TPU OOMs raise XlaRuntimeError with RESOURCE_EXHAUSTED;
#: faults.SimulatedOOM carries the same marker by construction)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory")

#: fault sites whose injected errors model host-IO failures
_HOSTIO_SITES = {
    "hostio_fetch", "hostio_io", "ckpt_save", "export_write",
    "export_torn",
}

#: programming errors: retrying cannot help, re-raise immediately.
#: (AssertionError covers the invariant harness and the
#: STATE_KW_BOUND soundness check.)
_FATAL_TYPES = (ValueError, TypeError, KeyError, AttributeError,
                AssertionError, NotImplementedError)


def classify_error(exc: BaseException) -> str:
    """Sort an escaped exception into OOM / HOSTIO / HEALTH /
    TRANSIENT / FATAL (module docstring has the policy attached to
    each class)."""
    msg = f"{type(exc).__name__}: {exc}"
    if any(m in msg for m in _OOM_MARKERS):
        return OOM
    # duck-typed (name + breach payload) so this module stays jax-free
    # for the gang supervisor; models.health.HealthBreachError is the
    # only producer of the shape
    if (
        type(exc).__name__ == "HealthBreachError"
        and hasattr(exc, "breaches")
    ):
        return HEALTH
    if isinstance(exc, faults_mod.FaultError):
        if exc.site in _HOSTIO_SITES:
            return HOSTIO
        return TRANSIENT
    # network/timeout flakes are plain-retry transient; check them
    # BEFORE OSError (both are OSError subclasses)
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    if isinstance(exc, (OSError, IOError)):
        return HOSTIO
    if isinstance(exc, _FATAL_TYPES):
        return FATAL
    return TRANSIENT


# -- policy ------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry/degradation budget.  ``min_agent_chunk`` floors the OOM
    halving (128 = one TPU lane tile; tests on tiny CPU tables pass a
    smaller floor)."""

    max_retries: int = 4
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    jitter_frac: float = 0.1
    min_agent_chunk: int = 128
    #: consecutive-or-cumulative host-IO failures before the serialized
    #: oracle fallback engages
    hostio_failures_before_fallback: int = 2

    def backoff_s(self, retry: int, rng: random.Random) -> float:
        """Exponential backoff with deterministic jitter: retry ``k``
        sleeps ``base * factor**k * (1 + U(0, jitter))`` where U comes
        from the supervisor's seeded RNG — reproducible schedules,
        decorrelated fleets."""
        base = self.backoff_base_s * (self.backoff_factor ** retry)
        return base * (1.0 + self.jitter_frac * rng.random())


@dataclasses.dataclass
class AttemptRecord:
    attempt: int
    error_class: str
    error: str
    backoff_s: float
    degradation: Optional[str] = None
    resumed_from_year: Optional[int] = None


@dataclasses.dataclass
class SupervisorReport:
    """What recovery cost: stamped into bench payloads
    (``fault_drill``) and the exporter's meta.json."""

    attempts: List[AttemptRecord] = dataclasses.field(default_factory=list)
    retries: int = 0
    retries_by_class: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    degradations: List[str] = dataclasses.field(default_factory=list)
    #: wall seconds from the first failure to final success (0.0 for a
    #: clean first attempt)
    recovery_wall_s: float = 0.0
    succeeded: bool = False
    final_agent_chunk: Optional[int] = None
    final_async_host_io: Optional[bool] = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["recovery_wall_s"] = round(self.recovery_wall_s, 4)
        return d


@dataclasses.dataclass
class AttemptContext:
    """Handed to the attempt function each try.  ``resume`` is False
    only on a fresh first attempt; ``effective_chunk`` may be reported
    back by the attempt (the live ``Simulation._agent_chunk``) so the
    OOM degradation can halve an auto-derived chunk it could not see
    in the config."""

    attempt: int
    run_config: Any
    resume: bool
    effective_chunk: Optional[int] = None


class Supervisor:
    """Generic bounded-retry engine (module docstring).  The attempt
    callable gets an :class:`AttemptContext` and returns the run's
    result; escaped exceptions are classified, degraded on, and
    retried under backoff until the policy budget is spent."""

    def __init__(self, policy: Optional[RetryPolicy] = None,
                 seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.policy = policy or RetryPolicy()
        self._rng = random.Random(seed)
        self._sleep = sleep

    # -- degradation ----------------------------------------------------

    def _degrade(self, rc, cls: str, ctx: AttemptContext,
                 hostio_failures: int,
                 exc: Optional[BaseException] = None,
                 ) -> tuple[Any, Optional[str], bool]:
        """The degraded config for the next attempt, a human
        description of what changed (None = plain retry), and a
        give-up flag: True means no degradation can help (e.g. OOM at
        the chunk floor is deterministic — re-running it is noise, not
        resilience), so the caller re-raises instead of retrying."""
        if cls == HEALTH:
            ids = tuple(
                int(a) for a in getattr(exc, "agent_ids", ()) or ()
            )
            if ids:
                merged = tuple(sorted(
                    set(rc.quarantine_ids or ()) | set(ids)
                ))
                if merged != (rc.quarantine_ids or ()):
                    rc = dataclasses.replace(rc, quarantine_ids=merged)
                    return rc, (
                        f"health: quarantined {len(ids)} agent(s) "
                        f"after the year-{getattr(exc, 'year', '?')} "
                        "breach"
                    ), False
                # same offenders breached again THROUGH the quarantine:
                # containment is not working, retrying cannot help
                logger.error(
                    "health breach repeats over already-quarantined "
                    "agents — giving up")
                return rc, None, True
            # unattributed breach (no-consumer pipelined run): plain
            # retry — a deterministic corruption will exhaust the
            # budget and surface, a transient one heals
            return rc, None, False
        if cls == OOM:
            chunk = rc.agent_chunk if rc.agent_chunk else None
            if chunk is None:
                chunk = ctx.effective_chunk or 0
            floor = self.policy.min_agent_chunk
            if chunk and chunk > floor:
                halved = max(floor, chunk // 2)
            elif not chunk:
                # whole-table run OOMed and the attempt reported no
                # chunk: engage streaming at the floor — the smallest
                # working set the policy allows
                halved = floor
            else:
                logger.error(
                    "agent_chunk already at the %d-row floor; OOM "
                    "degradation exhausted — giving up", floor,
                )
                return rc, None, True
            rc = dataclasses.replace(rc, agent_chunk=halved)
            return rc, f"oom: agent_chunk -> {halved}", False
        if cls == HOSTIO and (
            hostio_failures >= self.policy.hostio_failures_before_fallback
            and rc.async_io_enabled
        ):
            rc = dataclasses.replace(rc, async_host_io=False)
            return rc, (
                "hostio: repeated host-IO failure — falling back to the "
                "serialized oracle path (async_host_io=False)"
            ), False
        return rc, None, False

    # -- the loop -------------------------------------------------------

    def run(
        self,
        attempt_fn: Callable[[AttemptContext], Any],
        run_config,
        *,
        resume: bool = False,
        on_degrade: Optional[Callable[[str], None]] = None,
    ) -> tuple[Any, SupervisorReport]:
        """Drive ``attempt_fn`` to success or budget exhaustion.
        Returns ``(result, report)``; re-raises the last error when the
        retry budget is spent or the error is fatal, with the partial
        report attached as ``exc.supervisor_report``."""
        report = SupervisorReport()
        rc = run_config
        hostio_failures = 0
        t_first_failure: Optional[float] = None
        attempt = 0
        while True:
            ctx = AttemptContext(
                attempt=attempt, run_config=rc,
                resume=resume or attempt > 0,
            )
            try:
                result = attempt_fn(ctx)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — classified below
                cls = classify_error(e)
                if t_first_failure is None:
                    t_first_failure = time.perf_counter()
                if cls == HOSTIO:
                    hostio_failures += 1
                rec = AttemptRecord(
                    attempt=attempt, error_class=cls, error=repr(e),
                    backoff_s=0.0,
                )
                report.attempts.append(rec)
                report.retries_by_class[cls] = (
                    report.retries_by_class.get(cls, 0) + 1
                )
                give_up = cls == FATAL or attempt >= self.policy.max_retries
                degradation = None
                if not give_up:
                    rc, degradation, give_up = self._degrade(
                        rc, cls, ctx, hostio_failures, exc=e)
                if give_up:
                    try:
                        e.supervisor_report = report  # type: ignore[attr-defined]
                    except (AttributeError, TypeError):
                        pass  # exotic exception types without a __dict__
                    logger.error(
                        "supervisor giving up after attempt %d (%s): %r",
                        attempt, cls, e,
                    )
                    raise
                if degradation is not None:
                    rec.degradation = degradation
                    report.degradations.append(degradation)
                    logger.warning("supervisor degradation: %s", degradation)
                    if on_degrade is not None:
                        on_degrade(degradation)
                rec.backoff_s = self.policy.backoff_s(attempt, self._rng)
                report.retries += 1
                logger.warning(
                    "supervisor: attempt %d failed (%s: %r); retrying in "
                    "%.3fs", attempt, cls, e, rec.backoff_s,
                )
                self._sleep(rec.backoff_s)
                attempt += 1
                continue
            report.succeeded = True
            if t_first_failure is not None:
                report.recovery_wall_s = (
                    time.perf_counter() - t_first_failure
                )
            report.final_agent_chunk = getattr(rc, "agent_chunk", None)
            report.final_async_host_io = getattr(
                rc, "async_host_io", None)
            return result, report


# -- the batteries-included Simulation path ----------------------------------

def run_supervised(
    make_sim: Callable[[Any], Any],
    run_config=None,
    *,
    run_dir: Optional[str] = None,
    checkpoint_dir: Optional[str] = None,
    export_kw: Optional[Dict[str, Any]] = None,
    collect: bool = True,
    policy: Optional[RetryPolicy] = None,
    seed: int = 0,
    resume: bool = False,
) -> tuple[Any, SupervisorReport]:
    """Run a Simulation under the supervisor with crash-consistent
    exports and (scenario, year) resume.

    Parameters
    ----------
    make_sim : ``(run_config) -> Simulation`` — rebuilt each attempt so
        degradations (halved chunk, serialized host IO) take effect.
    run_dir : export directory; a :class:`RunManifest` ledger and a
        :class:`~dgen_tpu.io.export.RunExporter` are wired when given.
    checkpoint_dir : orbax checkpoint directory (default
        ``<run_dir>/checkpoints`` when ``run_dir`` is given; runs
        without either retry from scratch instead of resuming).
    export_kw : extra RunExporter kwargs (``state_names``,
        ``with_hourly`` surfaces etc.).
    resume : also resume a PRE-EXISTING run directory on the first
        attempt (retries always resume).

    A ``DGEN_TPU_FAULTS`` spec (or ``run_config.faults``) is installed
    before the first attempt unless a registry is already active —
    drills compose with programmatic :func:`faults.injected` use.
    """
    from dgen_tpu.config import RunConfig
    from dgen_tpu.io import checkpoint as ckpt

    rc = run_config or RunConfig()
    # supervised runs escalate sentinel breaches by default: the
    # breach -> attribute -> quarantine -> resume loop is exactly what
    # this supervisor exists for (plain Simulation.run only warns)
    if rc.sentinel_escalate is None:
        rc = dataclasses.replace(rc, sentinel_escalate=True)
    installed: Optional[faults_mod.FaultRegistry] = None
    if faults_mod.active() is None:
        spec = getattr(rc, "faults", None) or os.environ.get(
            "DGEN_TPU_FAULTS", "").strip()
        if spec:
            installed = faults_mod.FaultRegistry.parse(spec)
            faults_mod.install(installed)

    if checkpoint_dir is None and run_dir is not None:
        checkpoint_dir = os.path.join(run_dir, "checkpoints")

    def attempt(ctx: AttemptContext):
        sim = make_sim(ctx.run_config)
        ctx.effective_chunk = sim._agent_chunk or None
        manifest = RunManifest(run_dir) if run_dir is not None else None
        callback = None
        if run_dir is not None:
            from dgen_tpu.io.export import RunExporter

            callback = RunExporter(
                run_dir, sim.host_agent_id, sim.host_mask,
                manifest=manifest, **(export_kw or {}),
            )
        resume_year = None
        do_resume = ctx.resume and checkpoint_dir is not None
        if do_resume:
            # crash-consistent frontier: never resume past a year whose
            # exports are not durably on disk, or the missing years
            # would stay missing forever.  An exporting run with NO
            # durably-complete year (frontier None — killed before the
            # first export landed, or a damaged/absent manifest) must
            # restart from scratch even when checkpoints exist:
            # resuming from an uncapped checkpoint would permanently
            # skip the un-exported early years.
            if manifest is not None and callback is not None:
                frontier = manifest.complete_through(sim.years)
                if frontier is None:
                    do_resume = False
                else:
                    resume_year = ckpt.latest_valid_year(
                        checkpoint_dir, sim.table.n_agents,
                        max_year=frontier,
                    )
            else:
                # no exporter: checkpoints are the only artifact, so
                # the newest valid one is the frontier
                resume_year = ckpt.latest_valid_year(
                    checkpoint_dir, sim.table.n_agents,
                )
            if resume_year is None:
                do_resume = False
        if do_resume:
            logger.info(
                "supervised attempt %d: resuming after year %s",
                ctx.attempt, resume_year,
            )
        res = sim.run(
            callback=callback, collect=collect,
            checkpoint_dir=checkpoint_dir,
            resume=do_resume, resume_year=resume_year,
        )
        return res, sim, callback, manifest

    sup = Supervisor(policy=policy, seed=seed)

    # degradation warnings land in the manifest ledger even when the
    # attempt that triggered them failed before flushing anything else
    def on_degrade(msg: str) -> None:
        if run_dir is not None:
            RunManifest(run_dir).note(f"supervisor degradation: {msg}")

    try:
        (res, sim, exporter, manifest), report = sup.run(
            attempt, rc, resume=resume, on_degrade=on_degrade,
        )
    finally:
        # a registry THIS call armed must not outlive the run — a
        # leftover clause would fire on whatever hits the site next
        # (e.g. a serving process in the same interpreter)
        if installed is not None and faults_mod.active() is installed:
            faults_mod.install(None)
    if manifest is not None and checkpoint_dir is not None:
        manifest.record_checkpoints(checkpoint_dir, sim.years)
    # publish the quarantine ledger: the reasoned report lands as an
    # atomic quarantine.json beside meta.json, is content-hash recorded
    # in the manifest, and its summary is stamped into the exporter's
    # quarantine meta block (beside nonfinite_zeroed)
    rep_q = getattr(sim, "quarantine_report", None)
    if rep_q is not None and run_dir is not None:
        import jax

        if jax.process_index() == 0:
            rep_q.save(os.path.join(run_dir, "quarantine.json"))
            if manifest is not None:
                manifest.record_run_artifact("quarantine.json")
                manifest.flush()
        if exporter is not None:
            exporter.stamp_quarantine(rep_q.summary())
    if exporter is not None:
        exporter.stamp_meta(supervisor=report.to_json())
    return res, report
