"""Bad-data quarantine: ingest/load-time validation of the agent table
and profile banks, with per-agent containment instead of run-wide
poisoning.

The reference pipeline assumes clean Postgres inputs; at synthetic
10M-agent national scale (plus int8/bf16 quantized banks) malformed
rows — nonfinite loads, zero-scale quant rows, out-of-range tariff
references, negative prices — are statistically certain, and a single
NaN agent propagates through the state-level battery-adopter sort and
the Bass-diffusion group aggregates to corrupt *every* agent in its
state.  ``io.export`` only zeroes the symptom at the very end
(``nonfinite_zeroed``), after the damage is done.

This module is the detect/attribute/contain layer in front of the
device program:

* :func:`validate_population` — host-side schema/range/finiteness/
  reference checks over the agent table, the profile banks (including
  int8 quant-scale sidecars) and the tariff bank, producing a
  :class:`QuarantineReport`: per-agent reasons plus the bad bank rows.
* :func:`apply_quarantine` — rewrite quarantined rows to the exact
  inert fills padding agents carry (mask 0, index 0, the
  ``models.agents._PAD_FILLS`` sentinels) and zero unreadable bank
  rows, so quarantined agents contribute **exact zeros** to bills,
  sizing, the adopter sort and the state aggregates.  The mask rides
  the existing ``AgentTable.mask`` data plane — shapes, statics and
  jit groups are untouched, so the committed J5/J6 program
  fingerprints cannot move.
* :class:`QuarantineReport` round-trips through an atomic
  ``quarantine.json`` (recorded in the RunManifest by the run
  supervisor) so a run's provenance names exactly which rows were
  contained and why.

The always-on *numerical-health sentinel* that catches corruption
appearing MID-run (silent data corruption, a flipped bank row) lives in
:mod:`dgen_tpu.models.health`; its supervisor escalation funnels back
into this module via ``RunConfig.quarantine_ids``.

This module is numpy-only at validation time; jax is imported lazily by
:func:`apply_quarantine` (the one function that rebuilds device-bound
leaves), so the serve layer can import the error type without cost.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from dgen_tpu.resilience.atomic import atomic_write_json
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: report schema version (quarantine.json)
_VERSION = 1

#: bound on how many agents one validation/attribution pass will
#: quarantine — a report bigger than this almost certainly means the
#: INPUTS are the wrong file, not that 100k rows each went bad
MAX_QUARANTINE = 65536


class QuarantinedAgentError(Exception):
    """A request addressed a quarantined agent.  The serve layer maps
    this to HTTP 422 (the row exists but its data was contained at
    load); carries the machine-readable reasons."""

    def __init__(self, agent_id: int, reasons: Sequence[str]) -> None:
        super().__init__(
            f"agent {agent_id} is quarantined ({'; '.join(reasons)})"
        )
        self.agent_id = int(agent_id)
        self.reasons = list(reasons)


@dataclasses.dataclass
class QuarantineReport:
    """Reasoned per-agent quarantine decisions + bad bank rows.

    ``records`` maps stable agent id -> ``{"row": int, "reasons":
    [str, ...]}``; ``bank_rows`` maps a ProfileBank field name to the
    sorted bad row indices that :func:`apply_quarantine` must zero
    (every agent referencing them is quarantined, so zeroing is
    output-invariant)."""

    n_agents: int = 0
    records: Dict[int, dict] = dataclasses.field(default_factory=dict)
    bank_rows: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict)
    context: str = "load"

    # -- construction ---------------------------------------------------

    def add(self, agent_id: int, row: int, reason: str) -> None:
        rec = self.records.setdefault(
            int(agent_id), {"row": int(row), "reasons": []}
        )
        if reason not in rec["reasons"]:
            rec["reasons"].append(reason)

    def add_ids(self, ids: Iterable[int], reason: str) -> None:
        """Quarantine agents by stable id alone (operator/config fiat,
        the supervisor's sentinel escalation round-trip)."""
        for a in ids:
            self.add(int(a), -1, reason)

    def add_bank_row(self, field: str, row: int) -> None:
        rows = self.bank_rows.setdefault(field, [])
        if int(row) not in rows:
            rows.append(int(row))
            rows.sort()

    def merge(self, other: "QuarantineReport") -> None:
        for a, rec in other.records.items():
            for reason in rec["reasons"]:
                self.add(int(a), rec.get("row", -1), reason)
        for field, rows in other.bank_rows.items():
            for r in rows:
                self.add_bank_row(field, r)

    # -- queries --------------------------------------------------------

    @property
    def n_quarantined(self) -> int:
        return len(self.records)

    @property
    def ids(self) -> Tuple[int, ...]:
        return tuple(sorted(self.records))

    @property
    def is_clean(self) -> bool:
        return not self.records and not any(self.bank_rows.values())

    def reasons_for(self, agent_id: int) -> List[str]:
        rec = self.records.get(int(agent_id))
        return list(rec["reasons"]) if rec else []

    def reason_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for rec in self.records.values():
            for reason in rec["reasons"]:
                out[reason] = out.get(reason, 0) + 1
        return out

    def summary(self) -> Dict[str, object]:
        """The compact provenance block exporters stamp into meta.json
        beside ``nonfinite_zeroed``."""
        return {
            "context": self.context,
            "n_agents": int(self.n_agents),
            "n_quarantined": self.n_quarantined,
            "reasons": self.reason_counts(),
            "bank_rows": {
                k: list(v) for k, v in self.bank_rows.items() if v
            },
        }

    # -- persistence ----------------------------------------------------

    def to_json(self) -> Dict[str, object]:
        return {
            "version": _VERSION,
            "context": self.context,
            "n_agents": int(self.n_agents),
            "n_quarantined": self.n_quarantined,
            "agents": {
                str(a): self.records[a] for a in sorted(self.records)
            },
            "bank_rows": {
                k: list(v) for k, v in self.bank_rows.items() if v
            },
        }

    def save(self, path: str) -> None:
        """Publish the report atomically (temp + rename): a killed
        writer can never leave truncated JSON at the published path."""
        atomic_write_json(path, self.to_json(), indent=1)

    @classmethod
    def from_json(cls, blob: Dict[str, object]) -> "QuarantineReport":
        rep = cls(
            n_agents=int(blob.get("n_agents", 0)),
            context=str(blob.get("context", "load")),
        )
        for a, rec in (blob.get("agents") or {}).items():
            for reason in rec.get("reasons", ()):
                rep.add(int(a), int(rec.get("row", -1)), reason)
        for field, rows in (blob.get("bank_rows") or {}).items():
            for r in rows:
                rep.add_bank_row(field, int(r))
        return rep

    @classmethod
    def load(cls, path: str) -> "QuarantineReport":
        import json

        with open(path) as f:
            return cls.from_json(json.load(f))


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------

#: per-agent float columns checked for finiteness (and, where listed
#: below, range).  The documented sentinels (nem_kw_limit/switch 1e30,
#: sunset 9999) are FINITE and in-range by design.
_FLOAT_COLS = (
    "customers_in_bin", "load_kwh_per_customer_in_bin",
    "developable_frac", "one_time_charge", "nem_kw_limit",
    "nem_first_year", "nem_sunset_year", "switch_min_kw",
    "switch_max_kw",
)

#: (column, lower, upper) inclusive range checks over finite values;
#: bounds are deliberately loose — this catches corruption (negative
#: loads, 1e38 garbage), not modeling choices
_RANGE_COLS = (
    ("customers_in_bin", 0.0, 1e12),
    ("load_kwh_per_customer_in_bin", 0.0, 1e12),
    ("developable_frac", -1e-6, 1.0 + 1e-6),
    ("one_time_charge", 0.0, 1e9),
)


def quant_sidecar_bad_rows(codes: np.ndarray,
                           scales: np.ndarray) -> np.ndarray:
    """Bad row indices of an int8 quant bank's f32 scale sidecar.

    A NONFINITE or negative scale destroys the row (dequantization is
    ``scale * code``); a ZERO scale is the all-zero-row floor path
    (``models.agents.quantize_rows`` stores 1.0, but an external DGPB
    writer may store 0.0 — dequantization is exact zero either way) and
    is valid **only** while every code in the row is zero: zero scale
    under nonzero codes silently flattens real data to zero."""
    scales = np.asarray(scales)
    codes = np.asarray(codes)
    bad = ~np.isfinite(scales) | (scales < 0)
    zero = np.isfinite(scales) & (scales == 0)
    if np.any(zero):
        nonzero_row = np.any(codes != 0, axis=1)
        bad = bad | (zero & nonzero_row)
    return np.flatnonzero(bad)


def _bad_bank_rows(bank, scales=None, nonneg: bool = True) -> np.ndarray:
    """Row indices of a profile bank that cannot be priced: any
    nonfinite element, any negative element for nonnegative-by-
    construction banks (load shapes, solar CF), or a broken quant
    sidecar."""
    arr = np.asarray(bank)
    if arr.dtype == np.int8:
        # codes themselves are always finite; the sidecar is the risk
        bad = np.zeros(arr.shape[0], dtype=bool)
    else:
        a = arr.astype(np.float32, copy=False)
        bad = ~np.isfinite(a).all(axis=1)
        if nonneg:
            bad |= (np.where(np.isfinite(a), a, 0.0) < 0).any(axis=1)
    if scales is not None:
        bad_s = np.zeros(arr.shape[0], dtype=bool)
        bad_s[quant_sidecar_bad_rows(arr, scales)] = True
        bad |= bad_s
    return np.flatnonzero(bad)


def validate_population(table, profiles=None, tariffs=None,
                        context: str = "load") -> QuarantineReport:
    """Host-side load-time validation (numpy, pre-placement) of an
    agent population: schema/finiteness/range checks on the per-agent
    columns, bank-reference bounds, unusable profile-bank rows
    (including int8 quant sidecars) and unusable tariff rows.  Only
    masked-in rows are validated — padding rows are inert by
    construction.  Returns the reasoned :class:`QuarantineReport`."""
    mask = np.asarray(table.mask) > 0
    ids = np.asarray(table.agent_id)
    rep = QuarantineReport(n_agents=int(mask.sum()), context=context)

    def _refuse(reason: str) -> None:
        raise ValueError(
            f"validation would quarantine more than {MAX_QUARANTINE} "
            f"of {rep.n_agents} agents (first overflow at "
            f"'{reason}'); this is an input-file problem, not row "
            "corruption — refusing to mask it (reasons so far: "
            f"{sorted(rep.reason_counts())[:5]})"
        )

    def flag(bad: np.ndarray, reason: str) -> None:
        rows = np.flatnonzero(bad & mask)
        # bail BEFORE building millions of per-row records: at 10M-agent
        # scale a wholesale-corrupt column must refuse in O(1) wall,
        # not after minutes of pure-python dict churn
        if rep.n_quarantined + rows.size > MAX_QUARANTINE:
            _refuse(reason)
        for r in rows:
            rep.add(int(ids[r]), int(r), reason)

    # 1. finiteness of the per-agent float columns (+ incentive leaves)
    for name in _FLOAT_COLS:
        col = np.asarray(getattr(table, name))
        flag(~np.isfinite(col), f"nonfinite:{name}")
    inc = getattr(table, "incentives", None)
    if inc is not None:
        for f in dataclasses.fields(type(inc)):
            leaf = np.asarray(getattr(inc, f.name))
            if leaf.dtype.kind != "f":
                continue
            flag(
                ~np.isfinite(leaf).all(axis=tuple(range(1, leaf.ndim))),
                f"nonfinite:incentives.{f.name}",
            )

    # 2. gross range checks
    for name, lo, hi in _RANGE_COLS:
        col = np.asarray(getattr(table, name))
        finite = np.isfinite(col)
        flag(finite & ((col < lo) | (col > hi)), f"range:{name}")

    # 3. bank/tariff reference bounds
    bounds = [("state_idx", int(table.n_states)),
              ("sector_idx", int(table.n_sectors))]
    if profiles is not None:
        bounds += [
            ("load_idx", int(np.asarray(profiles.load).shape[0])),
            ("cf_idx", int(np.asarray(profiles.solar_cf).shape[0])),
            ("region_idx", int(np.asarray(profiles.wholesale).shape[0])),
        ]
    if tariffs is not None:
        n_t = int(np.asarray(tariffs.metering).shape[0])
        bounds += [("tariff_idx", n_t), ("tariff_switch_idx", n_t)]
    for name, n in bounds:
        col = np.asarray(getattr(table, name))
        flag((col < 0) | (col >= n), f"index:{name}")

    # 4. unusable profile-bank rows -> quarantine every referencing
    # agent and remember the rows for sanitization
    if profiles is not None:
        for field, idx_name, scales, nonneg in (
            ("load", "load_idx",
             getattr(profiles, "load_scale", None), True),
            ("solar_cf", "cf_idx",
             getattr(profiles, "solar_cf_scale", None), True),
            # real wholesale prices go negative; only nonfinite is bad
            ("wholesale", "region_idx", None, False),
        ):
            bank = np.asarray(getattr(profiles, field))
            bad_rows = _bad_bank_rows(
                bank,
                None if scales is None else np.asarray(scales),
                nonneg=nonneg,
            )
            if bad_rows.size == 0:
                continue
            for r in bad_rows:
                rep.add_bank_row(field, int(r))
            idx = np.asarray(getattr(table, idx_name))
            inb = (idx >= 0) & (idx < bank.shape[0])
            for r in bad_rows:
                flag(inb & (idx == r), f"bank:{field}[{int(r)}]")

    # 5. unusable tariff rows (nonfinite anywhere, negative buy price)
    if tariffs is not None:
        price = np.asarray(tariffs.price, dtype=np.float32)
        bad_t = ~np.isfinite(price).all(axis=(1, 2))
        bad_t |= (np.where(np.isfinite(price), price, 0.0) < 0).any(
            axis=(1, 2))
        for name in ("sell_price", "tier_cap", "fixed_monthly"):
            a = np.asarray(getattr(tariffs, name), dtype=np.float32)
            bad_t |= ~np.isfinite(a).all(
                axis=tuple(range(1, a.ndim)))
        bad_rows = np.flatnonzero(bad_t)
        if bad_rows.size:
            for r in bad_rows:
                rep.add_bank_row("tariff", int(r))
            n_t = price.shape[0]
            for idx_name in ("tariff_idx", "tariff_switch_idx"):
                idx = np.asarray(getattr(table, idx_name))
                inb = (idx >= 0) & (idx < n_t)
                for r in bad_rows:
                    flag(inb & (idx == r), f"tariff:[{int(r)}]")

    if rep.n_quarantined > MAX_QUARANTINE:
        raise ValueError(
            f"validation would quarantine {rep.n_quarantined} of "
            f"{rep.n_agents} agents (> {MAX_QUARANTINE}); this is an "
            "input-file problem, not row corruption — refusing to mask "
            "it (reasons: "
            f"{sorted(rep.reason_counts())[:5]})"
        )
    return rep


# ---------------------------------------------------------------------------
# Containment
# ---------------------------------------------------------------------------

def apply_quarantine(table, profiles, report: QuarantineReport):
    """Contain a report's rows: quarantined agents become padding
    (mask 0, bank indices 0, the ``_PAD_FILLS`` sentinel fills, zeroed
    incentives — the exact layout ``models.agents.pad_table`` gives
    masked rows, so they contribute exact zeros everywhere padding
    already does), and unreadable profile-bank rows are zeroed (quant
    scales to 1.0) so daylight layouts, quantization and whole-bank
    scans stay NaN-free.  Stable ``agent_id`` is preserved — the serve
    layer answers 422 by id.  Returns ``(table, profiles)``; the inputs
    are returned untouched (object identity) for a clean report."""
    if report.is_clean:
        return table, profiles

    import jax.numpy as jnp

    from dgen_tpu.models.agents import _PAD_FILLS

    mask = np.asarray(table.mask)
    q = np.isin(np.asarray(table.agent_id), np.asarray(report.ids)) \
        & (mask > 0)
    if q.any():
        repl = {}
        for f in dataclasses.fields(type(table)):
            if f.name in ("incentives", "n_states", "agent_id", "mask"):
                continue
            col = np.asarray(getattr(table, f.name))
            fill = np.asarray(_PAD_FILLS.get(f.name, 0), dtype=col.dtype)
            shaped = np.broadcast_to(
                q.reshape((-1,) + (1,) * (col.ndim - 1)), col.shape)
            repl[f.name] = jnp.asarray(np.where(shaped, fill, col))
        repl["mask"] = jnp.asarray(
            np.where(q, 0.0, mask).astype(mask.dtype))
        inc = table.incentives
        inc_repl = {}
        for f in dataclasses.fields(type(inc)):
            leaf = np.asarray(getattr(inc, f.name))
            shaped = np.broadcast_to(
                q.reshape((-1,) + (1,) * (leaf.ndim - 1)), leaf.shape)
            inc_repl[f.name] = jnp.asarray(np.where(
                shaped, np.asarray(0, dtype=leaf.dtype), leaf))
        table = dataclasses.replace(
            table, incentives=dataclasses.replace(inc, **inc_repl),
            **repl,
        )

    bank_repl = {}
    for field in ("load", "solar_cf", "wholesale"):
        rows = report.bank_rows.get(field) or []
        if not rows:
            continue
        arr = np.array(np.asarray(getattr(profiles, field)))
        arr[np.asarray(rows, dtype=np.intp)] = 0
        bank_repl[field] = jnp.asarray(arr)
        scale_name = {"load": "load_scale",
                      "solar_cf": "solar_cf_scale"}.get(field)
        if scale_name and getattr(profiles, scale_name, None) is not None:
            sc = np.array(np.asarray(getattr(profiles, scale_name)))
            sc[np.asarray(rows, dtype=np.intp)] = 1.0
            bank_repl[scale_name] = jnp.asarray(sc)
    if bank_repl:
        profiles = dataclasses.replace(profiles, **bank_repl)

    logger.warning(
        "quarantine: contained %d agent(s)%s — reasons %s",
        report.n_quarantined,
        "".join(
            f", zeroed {len(v)} {k} bank row(s)"
            for k, v in report.bank_rows.items()
            if v and k != "tariff"
        ),
        report.reason_counts(),
    )
    return table, profiles
