"""Crash-consistent run manifests: content-hashed, per-year artifact
ledger for a run directory.

A run directory's parquet partitions tell you what *files* exist; they
cannot tell you whether a file is complete, whether a year's surfaces
all landed, or whether a resumed run must re-export anything.  The
manifest answers exactly that:

* every landed artifact gets a per-year entry ``{sha256, bytes}``,
  recorded AFTER the atomic rename published it;
* a year is marked **complete** only once every one of its surfaces is
  recorded — the exporter calls :meth:`RunManifest.mark_year_complete`
  at the end of its per-year write;
* the manifest file itself is written via temp+rename
  (:mod:`dgen_tpu.resilience.atomic`), so a killed run leaves either
  the previous consistent ledger or the new one — never a torn one;
* :meth:`RunManifest.verify` re-hashes the ledger against the
  directory, flagging missing and corrupt (truncated/damaged) files —
  the audit behind ``python -m dgen_tpu.resilience verify``;
* :meth:`RunManifest.complete_through` gives the supervisor the
  resume frontier: the latest model year through which every prior
  year's exports are durably, verifiably on disk.  Resuming after that
  year re-exports exactly the missing years.

Checkpoint entries are recorded post-run (:meth:`record_checkpoints`),
once orbax's own commit protocol has made the steps durable — mid-run
the checkpoint directory's committed steps are themselves the source
of truth, so a crash loses no recoverability by not having stamped
them here yet.

Multi-process (gang) runs keep crash consistency WITHOUT cross-host
coordination on the write path: every process owns a **shard ledger**
(``manifest-p<i>.json``, a :class:`RunManifest` with ``shard=i``)
covering only the artifacts it wrote, and the coordinator-side
:class:`GangManifest` merges them read-side — a year counts complete
only when EVERY process of that year's writing epoch marked it
complete (the host-local-shards-merged-by-a-manifest design).  The
gang supervisor's resume frontier (:meth:`GangManifest.frontier`) is
the merged ``complete_through``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Sequence

from dgen_tpu.resilience.atomic import atomic_write_json

MANIFEST_NAME = "manifest.json"
#: coordinator-side ledger of a gang run (checkpoint hashes + notes;
#: the per-year artifact truth stays in the per-process shard ledgers)
GANG_MANIFEST_NAME = "manifest-gang.json"
_SHARD_RE = re.compile(r"^manifest-p(\d+)\.json$")
_VERSION = 1


def shard_manifest_name(shard: int) -> str:
    """Per-process shard ledger filename of a gang run."""
    return f"manifest-p{int(shard)}.json"


def _part_year(name: str) -> Optional[int]:
    """Model year of a ``year=<Y>[-p<i>].parquet`` (or ``.tmp``)
    surface file; None for anything else."""
    if not name.startswith("year="):
        return None
    tail = name[len("year="):]
    digits = ""
    for ch in tail:
        if ch.isdigit():
            digits += ch
        else:
            break
    return int(digits) if digits else None


def discover_shards(run_dir: str) -> List[int]:
    """Process indices with a shard ledger under ``run_dir``."""
    if not os.path.isdir(run_dir):
        return []
    out = []
    for name in os.listdir(run_dir):
        m = _SHARD_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _hash_tree(root: str) -> tuple[str, int]:
    """(digest, bytes) over a directory tree: per-file sha256 of
    (relpath, size, content hash), folded in sorted order — stable
    across filesystems and listdir orderings."""
    h = hashlib.sha256()
    total = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            rel = os.path.relpath(p, root)
            size = os.path.getsize(p)
            total += size
            h.update(f"{rel}\0{size}\0".encode())
            h.update(_sha256_file(p).encode())
    return h.hexdigest(), total


@dataclasses.dataclass
class VerifyReport:
    """Result of :meth:`RunManifest.verify`."""

    run_dir: str
    #: recorded artifacts whose file is gone
    missing: List[str] = dataclasses.field(default_factory=list)
    #: recorded artifacts whose bytes/hash no longer match (truncation,
    #: torn writes, bit rot)
    corrupt: List[str] = dataclasses.field(default_factory=list)
    #: parquet files present under the known surfaces but absent from
    #: the ledger (a writer died between rename and record — harmless:
    #: resume re-exports the year over them)
    unrecorded: List[str] = dataclasses.field(default_factory=list)
    #: leftover ``*.tmp`` siblings from killed writers
    stale_tmp: List[str] = dataclasses.field(default_factory=list)
    #: checkpoint entries that no longer hash-match
    bad_checkpoints: List[int] = dataclasses.field(default_factory=list)
    years_complete: List[int] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not (self.missing or self.corrupt or self.bad_checkpoints)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d


#: surface directories the exporter writes parquet partitions into —
#: the scan set for :meth:`RunManifest.verify`'s unrecorded check
SURFACE_DIRS = ("agent_outputs", "finance_series", "state_hourly")


class RunManifest:
    """The per-run-directory artifact ledger (module docstring).

    Loading an existing ``manifest.json`` resumes its ledger — a
    re-entered run keeps the completed years' entries and overwrites
    the years it re-exports.

    ``shard``/``n_processes`` turn this into a gang run's per-process
    shard ledger (``manifest-p<shard>.json``): the same recording
    protocol over only this process's artifacts, with each completed
    year stamped with the gang size that wrote it so the coordinator
    merge (:class:`GangManifest`) knows which peers to demand."""

    def __init__(self, run_dir: str, shard: Optional[int] = None,
                 n_processes: Optional[int] = None) -> None:
        self.run_dir = run_dir
        self.shard = shard
        self.n_processes = n_processes
        name = (MANIFEST_NAME if shard is None
                else shard_manifest_name(shard))
        self.path = os.path.join(run_dir, name)
        self._years: Dict[int, dict] = {}
        self._checkpoints: Dict[int, dict] = {}
        self._run_artifacts: Dict[str, dict] = {}
        self.notes: List[str] = []
        if os.path.isfile(self.path):
            try:
                with open(self.path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                # a torn manifest cannot happen via atomic_write; an
                # externally-damaged one is treated as absent (the run
                # re-exports everything — safe, just not minimal)
                doc = {}
            for y, rec in (doc.get("years") or {}).items():
                self._years[int(y)] = rec
            for y, rec in (doc.get("checkpoints") or {}).items():
                self._checkpoints[int(y)] = rec
            self._run_artifacts = dict(doc.get("run_artifacts") or {})
            self.notes = list(doc.get("notes") or [])

    # -- recording ------------------------------------------------------

    def record_artifact(self, year: int, relpath: str) -> None:
        """Hash + record one landed artifact (call AFTER the atomic
        rename published it).  Re-recording a year that was previously
        complete reopens it until :meth:`mark_year_complete`."""
        p = os.path.join(self.run_dir, relpath)
        rec = self._years.setdefault(
            int(year), {"complete": False, "artifacts": {}}
        )
        rec["artifacts"][relpath] = {
            "sha256": _sha256_file(p),
            "bytes": os.path.getsize(p),
        }
        rec["complete"] = False

    def record_run_artifact(self, relpath: str) -> None:
        """Record a year-independent artifact (``agents.parquet``,
        package metadata); verified alongside the per-year entries."""
        p = os.path.join(self.run_dir, relpath)
        self._run_artifacts[relpath] = {
            "sha256": _sha256_file(p),
            "bytes": os.path.getsize(p),
        }

    def mark_year_complete(self, year: int) -> None:
        """Declare every surface of ``year`` recorded, and publish the
        ledger (one atomic write per year).  Shard ledgers also stamp
        the gang size that wrote the year — an elastic P -> P' resume
        re-exports later years at P', and the merge must know each
        year's own epoch."""
        rec = self._years.setdefault(
            int(year), {"complete": False, "artifacts": {}}
        )
        rec["complete"] = True
        if self.n_processes is not None:
            rec["n_processes"] = int(self.n_processes)
        self.flush()

    def record_checkpoints(self, ckpt_dir: str,
                           years: Sequence[int]) -> None:
        """Post-run: hash each committed checkpoint step's directory
        tree into the ledger (orbax's commit protocol is the mid-run
        source of truth; this stamps the audit trail once saves are
        durable)."""
        for y in years:
            step_dir = os.path.join(ckpt_dir, str(int(y)))
            if not os.path.isdir(step_dir):
                continue
            digest, nbytes = _hash_tree(step_dir)
            self._checkpoints[int(y)] = {
                "dir": os.path.relpath(step_dir, self.run_dir)
                if step_dir.startswith(self.run_dir) else step_dir,
                "sha256": digest,
                "bytes": nbytes,
            }
        self.flush()

    def note(self, msg: str) -> None:
        """Append an operational note (degradation warnings stamp
        here) and publish."""
        self.notes.append(msg)
        self.flush()

    def flush(self) -> None:
        os.makedirs(self.run_dir, exist_ok=True)
        atomic_write_json(
            self.path,
            {
                "version": _VERSION,
                **({"shard": int(self.shard)}
                   if self.shard is not None else {}),
                "years": {
                    str(y): self._years[y] for y in sorted(self._years)
                },
                "checkpoints": {
                    str(y): self._checkpoints[y]
                    for y in sorted(self._checkpoints)
                },
                "run_artifacts": {
                    k: self._run_artifacts[k]
                    for k in sorted(self._run_artifacts)
                },
                "notes": self.notes,
            },
            indent=2,
        )

    # -- queries --------------------------------------------------------

    def complete_years(self) -> List[int]:
        return sorted(y for y, r in self._years.items() if r["complete"])

    def artifacts(self, year: int) -> Dict[str, dict]:
        return dict(self._years.get(int(year), {}).get("artifacts", {}))

    def complete_through(self, years: Sequence[int],
                         deep: bool = True) -> Optional[int]:
        """The resume frontier: the largest ``Y`` in ``years`` such
        that every grid year ``<= Y`` is complete and (``deep``)
        verifies against the directory.  ``None`` when even the first
        year is not durably exported."""
        frontier: Optional[int] = None
        for y in years:
            rec = self._years.get(int(y))
            if not rec or not rec["complete"]:
                break
            if deep and not self._year_ok(int(y)):
                break
            frontier = int(y)
        return frontier

    def _year_ok(self, year: int) -> bool:
        for rel, meta in self._years[year]["artifacts"].items():
            p = os.path.join(self.run_dir, rel)
            if not os.path.isfile(p):
                return False
            if os.path.getsize(p) != meta["bytes"]:
                return False
            if _sha256_file(p) != meta["sha256"]:
                return False
        return True

    # -- audit ----------------------------------------------------------

    def verify(self, deep: bool = True) -> VerifyReport:
        """Audit the run directory against the ledger.  ``deep``
        re-hashes every recorded artifact; shallow checks existence and
        byte counts only (cheap triage on huge runs)."""
        rep = VerifyReport(run_dir=self.run_dir)
        recorded = set()
        for rel, meta in self._run_artifacts.items():
            recorded.add(rel)
            p = os.path.join(self.run_dir, rel)
            if not os.path.isfile(p):
                rep.missing.append(rel)
            elif os.path.getsize(p) != meta["bytes"] or (
                deep and _sha256_file(p) != meta["sha256"]
            ):
                rep.corrupt.append(rel)
        for y in sorted(self._years):
            rec = self._years[y]
            year_bad = False
            for rel, meta in rec["artifacts"].items():
                recorded.add(rel)
                p = os.path.join(self.run_dir, rel)
                if not os.path.isfile(p):
                    rep.missing.append(rel)
                    year_bad = True
                    continue
                if os.path.getsize(p) != meta["bytes"] or (
                    deep and _sha256_file(p) != meta["sha256"]
                ):
                    rep.corrupt.append(rel)
                    year_bad = True
            if rec["complete"] and not year_bad:
                rep.years_complete.append(y)
        for y, meta in self._checkpoints.items():
            step_dir = os.path.join(self.run_dir, meta["dir"]) \
                if not os.path.isabs(meta["dir"]) else meta["dir"]
            if not os.path.isdir(step_dir):
                rep.bad_checkpoints.append(y)
                continue
            if deep:
                digest, nbytes = _hash_tree(step_dir)
                if digest != meta["sha256"]:
                    rep.bad_checkpoints.append(y)
        # sweep the surface dirs for files the ledger doesn't know and
        # for killed writers' tmp leftovers
        for d in SURFACE_DIRS:
            root = os.path.join(self.run_dir, d)
            if not os.path.isdir(root):
                continue
            for name in sorted(os.listdir(root)):
                rel = os.path.join(d, name)
                if name.endswith(".tmp"):
                    rep.stale_tmp.append(rel)
                elif name.endswith(".parquet") and rel not in recorded:
                    rep.unrecorded.append(rel)
        return rep


class GangManifest:
    """Coordinator-side merged view over a gang run's per-process
    shard ledgers (module docstring).

    The write path stays embarrassingly parallel — every process only
    ever touches its own ``manifest-p<i>.json`` — and the merge happens
    read-side, on whatever host asks: a year is complete only when the
    ledgers of ALL ``n_processes`` recorded for that year (its writing
    epoch) mark it complete and its artifacts verify.  Checkpoint tree
    hashes and operational notes live in a separate coordinator ledger
    (``manifest-gang.json``), written by the gang supervisor after the
    run — never contended with the workers."""

    def __init__(self, run_dir: str) -> None:
        self.run_dir = run_dir
        self.path = os.path.join(run_dir, GANG_MANIFEST_NAME)
        self.shards: Dict[int, RunManifest] = {
            i: RunManifest(run_dir, shard=i)
            for i in discover_shards(run_dir)
        }
        self._checkpoints: Dict[int, dict] = {}
        self.notes: List[str] = []
        if os.path.isfile(self.path):
            try:
                with open(self.path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                doc = {}
            for y, rec in (doc.get("checkpoints") or {}).items():
                self._checkpoints[int(y)] = rec
            self.notes = list(doc.get("notes") or [])

    # -- merged queries -------------------------------------------------

    def _year_epoch(self, year: int) -> Optional[tuple[int, List[int]]]:
        """(n_processes, shard indices holding the year) of ``year``'s
        writing epoch, or None when no shard recorded it / the epoch
        stamps disagree (a torn mix of gang sizes is not complete)."""
        holders: List[int] = []
        epochs = set()
        for i, m in self.shards.items():
            rec = m._years.get(int(year))
            if rec is None:
                continue
            holders.append(i)
            epochs.add(int(rec.get("n_processes") or 0))
        if not holders or len(epochs) != 1:
            return None
        n = epochs.pop()
        return (n, holders) if n > 0 else None

    def _year_complete(self, year: int, deep: bool = True) -> bool:
        epoch = self._year_epoch(year)
        if epoch is None:
            return False
        n, holders = epoch
        if sorted(holders) != list(range(n)):
            return False   # a peer's shard never landed
        for i in range(n):
            m = self.shards[i]
            rec = m._years[int(year)]
            if not rec.get("complete"):
                return False
            if deep and not m._year_ok(int(year)):
                return False
        return True

    def frontier(self, years: Sequence[int],
                 deep: bool = True) -> Optional[int]:
        """The gang resume frontier: the latest model year through
        which EVERY process's exports are durably, verifiably on disk
        (merged ``complete_through``).  None = restart from scratch."""
        out: Optional[int] = None
        for y in years:
            if not self._year_complete(int(y), deep=deep):
                break
            out = int(y)
        return out

    def complete_years(self, deep: bool = False) -> List[int]:
        ys = sorted({
            y for m in self.shards.values() for y in m._years
        })
        return [y for y in ys if self._year_complete(y, deep=deep)]

    # -- coordinator recording ------------------------------------------

    def record_checkpoints(self, ckpt_dir: str,
                           years: Sequence[int]) -> None:
        """Post-run, coordinator-side: hash each committed checkpoint
        step's tree (the collective orbax saves every process
        contributed shards to) into the coordinator ledger."""
        for y in years:
            step_dir = os.path.join(ckpt_dir, str(int(y)))
            if not os.path.isdir(step_dir):
                continue
            digest, nbytes = _hash_tree(step_dir)
            self._checkpoints[int(y)] = {
                "dir": os.path.relpath(step_dir, self.run_dir)
                if step_dir.startswith(self.run_dir) else step_dir,
                "sha256": digest,
                "bytes": nbytes,
            }
        self.flush()

    def note(self, msg: str) -> None:
        self.notes.append(msg)
        self.flush()

    def prune_after(self, frontier: Optional[int]) -> List[str]:
        """Delete every gang artifact of years BEYOND the resume
        frontier — part files on disk (any epoch's, ledgered or not)
        and the shard-ledger records pointing at them.  The supervisor
        calls this before a relaunch so the re-export (possibly at a
        DIFFERENT gang size) starts clean: a dead P=4 epoch's stale
        ``-p2``/``-p3`` parts would otherwise double rows under a
        P'=2 re-export's concatenation and wedge the merged
        completeness check on mixed epoch stamps forever.  ``frontier``
        None prunes everything (restart from scratch).  Returns the
        removed relpaths."""
        removed: List[str] = []

        def _rm(rel: str) -> None:
            try:
                os.remove(os.path.join(self.run_dir, rel))
                removed.append(rel)
            except OSError:
                pass   # already gone / racing writer: the sweep is
                       # best-effort, the atomic re-export wins anyway

        for m in self.shards.values():
            drop = [
                y for y in m._years
                if frontier is None or y > int(frontier)
            ]
            for y in drop:
                for rel in m._years[y]["artifacts"]:
                    _rm(rel)
                del m._years[y]
            if drop:
                m.flush()
        # unledgered leftovers (a writer killed between rename and
        # record) and stale tmp siblings of the pruned years
        for d in SURFACE_DIRS:
            root = os.path.join(self.run_dir, d)
            if not os.path.isdir(root):
                continue
            for name in sorted(os.listdir(root)):
                year = _part_year(name)
                if year is None:
                    continue
                if frontier is None or year > int(frontier):
                    _rm(os.path.join(d, name))
        return removed

    def flush(self) -> None:
        os.makedirs(self.run_dir, exist_ok=True)
        atomic_write_json(
            self.path,
            {
                "version": _VERSION,
                "checkpoints": {
                    str(y): self._checkpoints[y]
                    for y in sorted(self._checkpoints)
                },
                "notes": self.notes,
            },
            indent=2,
        )

    # -- audit ----------------------------------------------------------

    def verify(self, deep: bool = True) -> VerifyReport:
        """One merged audit over every shard ledger plus the
        coordinator's checkpoint entries: per-shard missing/corrupt
        artifacts, merged years-complete, the unrecorded/stale-tmp
        sweep against the UNION of recorded artifacts (a peer's shard
        parts are not 'unrecorded' just because this ledger didn't
        write them)."""
        rep = VerifyReport(run_dir=self.run_dir)
        recorded = set()
        bad_rels = set()
        for i in sorted(self.shards):
            # per-shard unrecorded/stale sweeps are discarded: a peer's
            # parts are recorded in the PEER's ledger, so only the
            # union sweep below is meaningful
            sub = self.shards[i].verify(deep=deep)
            rep.missing.extend(sub.missing)
            rep.corrupt.extend(sub.corrupt)
            bad_rels.update(sub.missing)
            bad_rels.update(sub.corrupt)
        # union of recorded artifacts across shards, for the sweep
        for m in self.shards.values():
            recorded.update(m._run_artifacts)
            for y in m._years:
                recorded.update(m._years[y]["artifacts"])
        # completeness reuses the per-shard verify verdicts above
        # (every artifact was already existence/size/hash-checked there
        # — a second deep pass would re-hash the whole directory)
        rep.years_complete = [
            y for y in self.complete_years(deep=False)
            if not any(
                rel in bad_rels
                for m in self.shards.values()
                for rel in m._years.get(y, {}).get("artifacts", {})
            )
        ]
        for y, meta in self._checkpoints.items():
            step_dir = os.path.join(self.run_dir, meta["dir"]) \
                if not os.path.isabs(meta["dir"]) else meta["dir"]
            if not os.path.isdir(step_dir):
                rep.bad_checkpoints.append(y)
                continue
            if deep:
                digest, _ = _hash_tree(step_dir)
                if digest != meta["sha256"]:
                    rep.bad_checkpoints.append(y)
        rep.unrecorded = []
        rep.stale_tmp = []
        for d in SURFACE_DIRS:
            root = os.path.join(self.run_dir, d)
            if not os.path.isdir(root):
                continue
            for name in sorted(os.listdir(root)):
                rel = os.path.join(d, name)
                if name.endswith(".tmp"):
                    rep.stale_tmp.append(rel)
                elif name.endswith(".parquet") and rel not in recorded:
                    rep.unrecorded.append(rel)
        return rep


def verify_run_dir(run_dir: str, deep: bool = True) -> List[VerifyReport]:
    """Audit a run directory; gang runs (per-process shard ledgers,
    no single ``manifest.json``) get one MERGED report, and sweep runs
    recurse into per-scenario subdirectories.  Raises FileNotFoundError
    when no manifest exists anywhere under ``run_dir``."""
    reports: List[VerifyReport] = []
    if os.path.isfile(os.path.join(run_dir, MANIFEST_NAME)):
        reports.append(RunManifest(run_dir).verify(deep=deep))
    elif discover_shards(run_dir):
        reports.append(GangManifest(run_dir).verify(deep=deep))
    else:
        for name in sorted(os.listdir(run_dir)):
            sub = os.path.join(run_dir, name)
            if not os.path.isdir(sub):
                continue
            if os.path.isfile(os.path.join(sub, MANIFEST_NAME)):
                reports.append(RunManifest(sub).verify(deep=deep))
            elif discover_shards(sub):
                reports.append(GangManifest(sub).verify(deep=deep))
    if not reports:
        raise FileNotFoundError(
            f"no {MANIFEST_NAME} (or manifest-p*.json shard ledgers) "
            f"under {run_dir} (not a manifested run directory — re-run "
            "under the resilience supervisor or pass an exporter a "
            "RunManifest)"
        )
    return reports
