"""Crash-consistent run manifests: content-hashed, per-year artifact
ledger for a run directory.

A run directory's parquet partitions tell you what *files* exist; they
cannot tell you whether a file is complete, whether a year's surfaces
all landed, or whether a resumed run must re-export anything.  The
manifest answers exactly that:

* every landed artifact gets a per-year entry ``{sha256, bytes}``,
  recorded AFTER the atomic rename published it;
* a year is marked **complete** only once every one of its surfaces is
  recorded — the exporter calls :meth:`RunManifest.mark_year_complete`
  at the end of its per-year write;
* the manifest file itself is written via temp+rename
  (:mod:`dgen_tpu.resilience.atomic`), so a killed run leaves either
  the previous consistent ledger or the new one — never a torn one;
* :meth:`RunManifest.verify` re-hashes the ledger against the
  directory, flagging missing and corrupt (truncated/damaged) files —
  the audit behind ``python -m dgen_tpu.resilience verify``;
* :meth:`RunManifest.complete_through` gives the supervisor the
  resume frontier: the latest model year through which every prior
  year's exports are durably, verifiably on disk.  Resuming after that
  year re-exports exactly the missing years.

Checkpoint entries are recorded post-run (:meth:`record_checkpoints`),
once orbax's own commit protocol has made the steps durable — mid-run
the checkpoint directory's committed steps are themselves the source
of truth, so a crash loses no recoverability by not having stamped
them here yet.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence

from dgen_tpu.resilience.atomic import atomic_write_json

MANIFEST_NAME = "manifest.json"
_VERSION = 1


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _hash_tree(root: str) -> tuple[str, int]:
    """(digest, bytes) over a directory tree: per-file sha256 of
    (relpath, size, content hash), folded in sorted order — stable
    across filesystems and listdir orderings."""
    h = hashlib.sha256()
    total = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            rel = os.path.relpath(p, root)
            size = os.path.getsize(p)
            total += size
            h.update(f"{rel}\0{size}\0".encode())
            h.update(_sha256_file(p).encode())
    return h.hexdigest(), total


@dataclasses.dataclass
class VerifyReport:
    """Result of :meth:`RunManifest.verify`."""

    run_dir: str
    #: recorded artifacts whose file is gone
    missing: List[str] = dataclasses.field(default_factory=list)
    #: recorded artifacts whose bytes/hash no longer match (truncation,
    #: torn writes, bit rot)
    corrupt: List[str] = dataclasses.field(default_factory=list)
    #: parquet files present under the known surfaces but absent from
    #: the ledger (a writer died between rename and record — harmless:
    #: resume re-exports the year over them)
    unrecorded: List[str] = dataclasses.field(default_factory=list)
    #: leftover ``*.tmp`` siblings from killed writers
    stale_tmp: List[str] = dataclasses.field(default_factory=list)
    #: checkpoint entries that no longer hash-match
    bad_checkpoints: List[int] = dataclasses.field(default_factory=list)
    years_complete: List[int] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not (self.missing or self.corrupt or self.bad_checkpoints)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d


#: surface directories the exporter writes parquet partitions into —
#: the scan set for :meth:`RunManifest.verify`'s unrecorded check
SURFACE_DIRS = ("agent_outputs", "finance_series", "state_hourly")


class RunManifest:
    """The per-run-directory artifact ledger (module docstring).

    Loading an existing ``manifest.json`` resumes its ledger — a
    re-entered run keeps the completed years' entries and overwrites
    the years it re-exports."""

    def __init__(self, run_dir: str) -> None:
        self.run_dir = run_dir
        self.path = os.path.join(run_dir, MANIFEST_NAME)
        self._years: Dict[int, dict] = {}
        self._checkpoints: Dict[int, dict] = {}
        self._run_artifacts: Dict[str, dict] = {}
        self.notes: List[str] = []
        if os.path.isfile(self.path):
            try:
                with open(self.path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                # a torn manifest cannot happen via atomic_write; an
                # externally-damaged one is treated as absent (the run
                # re-exports everything — safe, just not minimal)
                doc = {}
            for y, rec in (doc.get("years") or {}).items():
                self._years[int(y)] = rec
            for y, rec in (doc.get("checkpoints") or {}).items():
                self._checkpoints[int(y)] = rec
            self._run_artifacts = dict(doc.get("run_artifacts") or {})
            self.notes = list(doc.get("notes") or [])

    # -- recording ------------------------------------------------------

    def record_artifact(self, year: int, relpath: str) -> None:
        """Hash + record one landed artifact (call AFTER the atomic
        rename published it).  Re-recording a year that was previously
        complete reopens it until :meth:`mark_year_complete`."""
        p = os.path.join(self.run_dir, relpath)
        rec = self._years.setdefault(
            int(year), {"complete": False, "artifacts": {}}
        )
        rec["artifacts"][relpath] = {
            "sha256": _sha256_file(p),
            "bytes": os.path.getsize(p),
        }
        rec["complete"] = False

    def record_run_artifact(self, relpath: str) -> None:
        """Record a year-independent artifact (``agents.parquet``,
        package metadata); verified alongside the per-year entries."""
        p = os.path.join(self.run_dir, relpath)
        self._run_artifacts[relpath] = {
            "sha256": _sha256_file(p),
            "bytes": os.path.getsize(p),
        }

    def mark_year_complete(self, year: int) -> None:
        """Declare every surface of ``year`` recorded, and publish the
        ledger (one atomic write per year)."""
        self._years.setdefault(
            int(year), {"complete": False, "artifacts": {}}
        )["complete"] = True
        self.flush()

    def record_checkpoints(self, ckpt_dir: str,
                           years: Sequence[int]) -> None:
        """Post-run: hash each committed checkpoint step's directory
        tree into the ledger (orbax's commit protocol is the mid-run
        source of truth; this stamps the audit trail once saves are
        durable)."""
        for y in years:
            step_dir = os.path.join(ckpt_dir, str(int(y)))
            if not os.path.isdir(step_dir):
                continue
            digest, nbytes = _hash_tree(step_dir)
            self._checkpoints[int(y)] = {
                "dir": os.path.relpath(step_dir, self.run_dir)
                if step_dir.startswith(self.run_dir) else step_dir,
                "sha256": digest,
                "bytes": nbytes,
            }
        self.flush()

    def note(self, msg: str) -> None:
        """Append an operational note (degradation warnings stamp
        here) and publish."""
        self.notes.append(msg)
        self.flush()

    def flush(self) -> None:
        os.makedirs(self.run_dir, exist_ok=True)
        atomic_write_json(
            self.path,
            {
                "version": _VERSION,
                "years": {
                    str(y): self._years[y] for y in sorted(self._years)
                },
                "checkpoints": {
                    str(y): self._checkpoints[y]
                    for y in sorted(self._checkpoints)
                },
                "run_artifacts": {
                    k: self._run_artifacts[k]
                    for k in sorted(self._run_artifacts)
                },
                "notes": self.notes,
            },
            indent=2,
        )

    # -- queries --------------------------------------------------------

    def complete_years(self) -> List[int]:
        return sorted(y for y, r in self._years.items() if r["complete"])

    def artifacts(self, year: int) -> Dict[str, dict]:
        return dict(self._years.get(int(year), {}).get("artifacts", {}))

    def complete_through(self, years: Sequence[int],
                         deep: bool = True) -> Optional[int]:
        """The resume frontier: the largest ``Y`` in ``years`` such
        that every grid year ``<= Y`` is complete and (``deep``)
        verifies against the directory.  ``None`` when even the first
        year is not durably exported."""
        frontier: Optional[int] = None
        for y in years:
            rec = self._years.get(int(y))
            if not rec or not rec["complete"]:
                break
            if deep and not self._year_ok(int(y)):
                break
            frontier = int(y)
        return frontier

    def _year_ok(self, year: int) -> bool:
        for rel, meta in self._years[year]["artifacts"].items():
            p = os.path.join(self.run_dir, rel)
            if not os.path.isfile(p):
                return False
            if os.path.getsize(p) != meta["bytes"]:
                return False
            if _sha256_file(p) != meta["sha256"]:
                return False
        return True

    # -- audit ----------------------------------------------------------

    def verify(self, deep: bool = True) -> VerifyReport:
        """Audit the run directory against the ledger.  ``deep``
        re-hashes every recorded artifact; shallow checks existence and
        byte counts only (cheap triage on huge runs)."""
        rep = VerifyReport(run_dir=self.run_dir)
        recorded = set()
        for rel, meta in self._run_artifacts.items():
            recorded.add(rel)
            p = os.path.join(self.run_dir, rel)
            if not os.path.isfile(p):
                rep.missing.append(rel)
            elif os.path.getsize(p) != meta["bytes"] or (
                deep and _sha256_file(p) != meta["sha256"]
            ):
                rep.corrupt.append(rel)
        for y in sorted(self._years):
            rec = self._years[y]
            year_bad = False
            for rel, meta in rec["artifacts"].items():
                recorded.add(rel)
                p = os.path.join(self.run_dir, rel)
                if not os.path.isfile(p):
                    rep.missing.append(rel)
                    year_bad = True
                    continue
                if os.path.getsize(p) != meta["bytes"] or (
                    deep and _sha256_file(p) != meta["sha256"]
                ):
                    rep.corrupt.append(rel)
                    year_bad = True
            if rec["complete"] and not year_bad:
                rep.years_complete.append(y)
        for y, meta in self._checkpoints.items():
            step_dir = os.path.join(self.run_dir, meta["dir"]) \
                if not os.path.isabs(meta["dir"]) else meta["dir"]
            if not os.path.isdir(step_dir):
                rep.bad_checkpoints.append(y)
                continue
            if deep:
                digest, nbytes = _hash_tree(step_dir)
                if digest != meta["sha256"]:
                    rep.bad_checkpoints.append(y)
        # sweep the surface dirs for files the ledger doesn't know and
        # for killed writers' tmp leftovers
        for d in SURFACE_DIRS:
            root = os.path.join(self.run_dir, d)
            if not os.path.isdir(root):
                continue
            for name in sorted(os.listdir(root)):
                rel = os.path.join(d, name)
                if name.endswith(".tmp"):
                    rep.stale_tmp.append(rel)
                elif name.endswith(".parquet") and rel not in recorded:
                    rep.unrecorded.append(rel)
        return rep


def verify_run_dir(run_dir: str, deep: bool = True) -> List[VerifyReport]:
    """Audit a run directory; recurses into per-scenario
    subdirectories (a sweep export is one manifest per scenario
    directory).  Raises FileNotFoundError when no manifest exists
    anywhere under ``run_dir``."""
    reports: List[VerifyReport] = []
    if os.path.isfile(os.path.join(run_dir, MANIFEST_NAME)):
        reports.append(RunManifest(run_dir).verify(deep=deep))
    else:
        for name in sorted(os.listdir(run_dir)):
            sub = os.path.join(run_dir, name)
            if os.path.isdir(sub) and os.path.isfile(
                os.path.join(sub, MANIFEST_NAME)
            ):
                reports.append(RunManifest(sub).verify(deep=deep))
    if not reports:
        raise FileNotFoundError(
            f"no {MANIFEST_NAME} under {run_dir} (not a manifested run "
            "directory — re-run under the resilience supervisor or "
            "pass an exporter a RunManifest)"
        )
    return reports
