"""One gang worker: a jax.distributed process of a supervised
multi-host simulation run (``python -m dgen_tpu.resilience.gangworker``).

Launched only by the :class:`~dgen_tpu.resilience.gang.GangSupervisor`
(or an operator reproducing its env contract — see the gang module
docstring).  Per process it:

* pins the platform and brings up ``jax.distributed`` via the standard
  multi-host env (:func:`dgen_tpu.parallel.launch.initialize_multihost`
  — ``DGEN_COORDINATOR`` / ``DGEN_NUM_PROCESSES`` /
  ``DGEN_PROCESS_ID``);
* builds the (deterministic, identical on every process) synthetic
  population and a global mesh over every device of every process;
* resumes from the supervisor-provided manifest frontier: the newest
  checkpoint that restores UNDER THIS TOPOLOGY at or below it
  (:func:`dgen_tpu.parallel.elastic.resume_year_for` — this is what
  makes a P -> P' relaunch elastic);
* exports its OWN addressable shard rows per year, recorded in its
  per-process shard ledger
  (:class:`~dgen_tpu.resilience.manifest.RunManifest` with
  ``shard=process_id``) — completeness is decided coordinator-side by
  the :class:`~dgen_tpu.resilience.manifest.GangManifest` merge;
* heartbeats after every completed year (the supervisor's stall
  detector reads freshness off the file);
* on SIGTERM runs the **synchronized emergency checkpoint barrier**
  (:class:`StopFlag`): a tiny cross-process all-gather at every year
  boundary makes all P workers agree on the save year, so every shard
  exports and checkpoints through the same year before exit.
"""

from __future__ import annotations

import os
import signal

from dgen_tpu.resilience.faults import fault_point
from dgen_tpu.utils.logging import get_logger

logger = get_logger()


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else default


class StopFlag:
    """The synchronized stop barrier.  A local stop request (SIGTERM
    from the supervisor, or the deterministic ``DGEN_GANG_STOP_AFTER``
    drill knob) becomes a GANG-WIDE stop via a tiny cross-process
    all-gather evaluated once per year by every worker — so all P
    processes agree on the same save year, even when only one of them
    received the signal."""

    def __init__(self, stop_after: int | None = None) -> None:
        self.stop_after = stop_after
        self.preempted = False
        self._sigterm = False

    def install(self) -> "StopFlag":
        signal.signal(signal.SIGTERM, self._on_sigterm)
        return self

    def _on_sigterm(self, *_args) -> None:
        self._sigterm = True

    def local(self, year: int) -> bool:
        return self._sigterm or (
            self.stop_after is not None and year >= self.stop_after
        )

    def should_stop(self, year: int, year_idx: int) -> bool:
        """``Simulation.run``'s per-year hook: called by every process
        after the year's exports and checkpoint save were issued.
        Contains a collective — every process must call it once per
        executed year (the run loop guarantees that)."""
        # resilience drill hook: the barrier collective failing (a
        # worker death between the year step and the barrier surfaces
        # here as a gang death; the supervisor relaunches)
        fault_point("gang_barrier")
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([1 if self.local(year) else 0], np.int32)
        )
        stop = bool(np.sum(np.asarray(flags)) > 0)
        if stop:
            self.preempted = True
        return stop


def main() -> int:
    from dgen_tpu.parallel.launch import (
        initialize_multihost,
        pin_platform_from_env,
    )
    from dgen_tpu.resilience.gang import (
        done_path,
        heartbeat_path,
        write_heartbeat,
    )

    from dgen_tpu.resilience import faults

    # per-worker fault arming (drills set DGEN_TPU_FAULTS on chosen
    # workers/incarnations through the supervisor's env_for)
    faults.install_from_env()

    # the SIGTERM flag must be live BEFORE the multi-second distributed
    # bring-up/compile: the supervisor forwards a pending stop within
    # one poll of spawning, and the default disposition would kill a
    # booting worker instead of letting it reach the first stop barrier
    stop = StopFlag(
        stop_after=(_env_int("DGEN_GANG_STOP_AFTER", 0) or None),
    ).install()

    gang_dir = os.environ["DGEN_GANG_DIR"]
    run_dir = os.environ["DGEN_RUN_DIR"]
    index = _env_int("DGEN_PROCESS_ID", 0)
    hb_path = heartbeat_path(gang_dir, index)
    # boot heartbeat (no year yet): the supervisor's boot-timeout
    # grace runs until the first YEAR heartbeat below
    write_heartbeat(hb_path, pid=os.getpid(), phase="boot")

    pin_platform_from_env()
    if not initialize_multihost():
        raise ValueError(
            "gangworker requires the multi-host env (DGEN_COORDINATOR, "
            "DGEN_NUM_PROCESSES, DGEN_PROCESS_ID) — it is launched by "
            "resilience.gang.GangSupervisor, not by hand"
        )

    import jax

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.io.export import RunExporter
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation
    from dgen_tpu.parallel import elastic
    from dgen_tpu.parallel.mesh import default_mesh
    from dgen_tpu.resilience.manifest import RunManifest

    n_proc = jax.process_count()
    assert index == jax.process_index()

    # deterministic, identical world on every process: the table is a
    # pure function of the env knobs, so global-array placement can
    # slice each process's shards out of the same host copy.
    # DGEN_GANG_WORLD=national swaps the tiny io.synth test world for
    # the state-stratified national generator (models.synth) — the
    # pod-scale drill/bench shape (DGEN_AGENTS rows, chunk-deterministic
    # so every process materializes identical bytes)
    cfg = ScenarioConfig(
        name=os.environ.get("DGEN_GANG_NAME", "gang"),
        start_year=_env_int("DGEN_GANG_START_YEAR", 2014),
        end_year=_env_int("DGEN_END_YEAR", 2016),
        anchor_years=(),
    )
    if os.environ.get("DGEN_GANG_WORLD", "") == "national":
        from dgen_tpu.models import synth as national

        spec = national.NationalSpec(
            n_agents=_env_int("DGEN_AGENTS", 10_240),
            seed=_env_int("DGEN_GANG_SEED", 11),
            tariff_mix=os.environ.get("DGEN_GANG_TARIFF_MIX", "mixed"),
        )
        pop = national.generate_world(spec)
    else:
        states = [
            s for s in
            os.environ.get("DGEN_GANG_STATES", "DE,CA").split(",")
            if s
        ]
        pop = synth.generate_population(
            _env_int("DGEN_AGENTS", 96), states=states,
            seed=_env_int("DGEN_GANG_SEED", 11), pad_multiple=64,
        )
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
    )
    rc = RunConfig.from_env(
        sizing_iters=_env_int("DGEN_GANG_SIZING_ITERS", 6),
    )
    # production placement: the 2-D process_count x local-devices grid
    # (parallel.mesh.default_mesh; DGEN_TPU_MESH forces a shape) —
    # row-major placement-identical to the old flat mesh, with the
    # host-axis slice of the (tiny) state reductions grouped for DCN
    mesh = default_mesh()
    sim = Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc, mesh=mesh,
        econ_years=_env_int("DGEN_GANG_ECON_YEARS", 25),
    )

    manifest = RunManifest(run_dir, shard=index, n_processes=n_proc)
    exporter = RunExporter(
        run_dir, agent_id=sim.host_agent_id, mask=sim.host_mask,
        manifest=manifest,
        # topology-invariant artifacts: multi-process shard writes are
        # always full f32, so a P'=1 elastic resume must not suddenly
        # int16-quantize its exports (the shards could then never be
        # compared — or resumed — against the P-process years')
        compact=False,
        meta={"gang": {
            "n_processes": n_proc, "process": index,
            # the PADDED global table size — what a later (possibly
            # different-topology) restore needs to build its template
            "n_agents_padded": int(sim.table.n_agents),
        }},
    )
    # load-time quarantine carries through gang sharding unchanged
    # (deterministic validation of the identical host population on
    # every process -> identical mask); process 0 publishes the ledger
    # and the merged-manifest verify covers it
    rep_q = getattr(sim, "quarantine_report", None)
    if rep_q is not None and not rep_q.is_clean:
        if index == 0:
            rep_q.save(os.path.join(run_dir, "quarantine.json"))
        exporter.stamp_quarantine(rep_q.summary())

    def callback(year: int, year_idx: int, outs) -> None:
        # resilience drill hook: a ``kill`` here is a worker dying
        # mid-year with collectives in flight — the supervisor must
        # tear the whole gang down and relaunch from the frontier
        fault_point("gang_worker_kill")
        exporter(year, year_idx, outs)
        write_heartbeat(
            hb_path, pid=os.getpid(), year=year, year_idx=year_idx,
        )

    ckpt_dir = os.environ.get(
        "DGEN_GANG_CKPT_DIR", os.path.join(run_dir, "checkpoints"))
    raw_frontier = os.environ.get("DGEN_GANG_FRONTIER", "").strip()
    frontier = int(raw_frontier) if raw_frontier else None
    resume_year = elastic.resume_year_for(
        ckpt_dir, sim.table.n_agents, frontier, mesh=mesh,
    ) if os.path.isdir(ckpt_dir) else None
    if resume_year is not None:
        logger.info(
            "gang worker %d/%d: elastic resume after year %d "
            "(frontier %s)", index, n_proc, resume_year, frontier,
        )

    res = sim.run(
        callback=callback, collect=False, checkpoint_dir=ckpt_dir,
        resume=resume_year is not None, resume_year=resume_year,
        should_stop=stop.should_stop,
    )

    from dgen_tpu.resilience.atomic import atomic_write_json

    atomic_write_json(done_path(gang_dir, index), {
        "process": index,
        "n_processes": n_proc,
        "years_run": [int(y) for y in res.years],
        "completed_through": (
            int(res.years[-1]) if res.years
            else (int(resume_year) if resume_year is not None else None)
        ),
        "preempted": stop.preempted,
    })
    print(
        f"gang worker {index}/{n_proc}: "
        f"{len(res.years)} years -> {run_dir}"
        + (" (preempted)" if stop.preempted else "")
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
