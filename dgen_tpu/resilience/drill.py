"""The fault drill: prove, on CPU, that every registered fault site is
retried/resumed by the supervisor and that the recovered run's
artifacts are bit-exact against an uninterrupted run.

This is the executable form of the resilience acceptance contract —
``python -m dgen_tpu.resilience drill`` runs it (tools/check.sh wires a
smoke invocation), the fault-drill bench (``DGEN_TPU_BENCH_FAULTS``)
stamps its timings, and tests/test_resilience.py asserts its pieces
individually.

Per injected site the drill runs a fresh supervised run into its own
directory and checks:

* the fault actually fired (a drill that injects nothing proves
  nothing);
* the supervisor retried and the run succeeded;
* every parquet partition is byte-identical to the clean baseline —
  except under the ``oom`` drill, where the degraded (chunk-halved)
  re-entry runs a different-but-equivalent program, so those years are
  compared numerically (the same tolerance the chunked-vs-whole
  equivalence suite uses);
* ``manifest verify`` passes on the recovered directory.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from dgen_tpu.resilience import faults as faults_mod
from dgen_tpu.resilience.manifest import verify_run_dir
from dgen_tpu.resilience.supervisor import RetryPolicy, run_supervised
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: the drill matrix: every run-path fault site, hit mid-run.
#: (ingest / sweep_scenario / serve_query live off the single-run path
#: and are drilled by tests/test_resilience.py directly.)
DRILL_SPECS = (
    ("year_step", "year_step@2"),
    ("year_step_oom", "year_step@2:oom"),
    ("ckpt_save", "ckpt_save@2"),
    ("hostio_fetch", "hostio_fetch@1"),
    ("hostio_io", "hostio_io@2"),
    ("export_write", "export_write@2"),
    ("export_torn", "export_torn@2:truncate"),
)

#: parquet tolerance for degraded (chunk-halved) re-entries — the same
#: envelope tests/test_simulation.py's chunked-vs-whole checks use
OOM_RTOL = 2e-5
OOM_ATOL = 1e-4


def make_synth_runner(
    n_agents: int = 96,
    states=("DE", "CA"),
    end_year: int = 2016,
    sizing_iters: int = 8,
) -> Callable:
    """``make_sim(run_config) -> Simulation`` over one synthetic
    population (built once; each attempt re-pads/places it under the
    attempt's config — how degradations take effect)."""
    from dgen_tpu.config import ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation

    cfg = ScenarioConfig(
        name="drill", start_year=2014, end_year=end_year, anchor_years=(),
    )
    pop = synth.generate_population(
        n_agents, states=list(states), seed=11, pad_multiple=64,
    )
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
    )

    def make_sim(rc):
        import dataclasses

        rc = dataclasses.replace(rc, sizing_iters=sizing_iters)
        return Simulation(
            pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
        )

    return make_sim


def _parquet_files(run_dir: str) -> List[str]:
    out = []
    for sub in ("agent_outputs", "finance_series", "state_hourly"):
        d = os.path.join(run_dir, sub)
        if os.path.isdir(d):
            out.extend(
                os.path.join(sub, f)
                for f in sorted(os.listdir(d)) if f.endswith(".parquet")
            )
    return out


def compare_run_dirs(clean: str, recovered: str,
                     numeric: bool = False) -> Dict[str, object]:
    """Compare every parquet partition of two run directories.
    ``numeric=False`` demands byte equality; ``numeric=True`` compares
    frame values at the chunked-equivalence tolerance instead (the OOM
    drill's degraded re-entry)."""
    import pandas as pd

    a, b = set(_parquet_files(clean)), set(_parquet_files(recovered))
    rec: Dict[str, object] = {
        "only_in_clean": sorted(a - b),
        "only_in_recovered": sorted(b - a),
        "mismatched": [],
        "compared": len(a & b),
    }
    for rel in sorted(a & b):
        pa, pb = os.path.join(clean, rel), os.path.join(recovered, rel)
        with open(pa, "rb") as fa, open(pb, "rb") as fb:
            if fa.read() == fb.read():
                continue
        if not numeric:
            rec["mismatched"].append(rel)
            continue
        da, db = pd.read_parquet(pa), pd.read_parquet(pb)
        try:
            for col in da.columns:
                va, vb = np.stack(da[col].values), np.stack(db[col].values)
                if va.dtype.kind in "fc":
                    # compact exports are int16-quantized with
                    # per-column scales: two equivalent-but-reordered
                    # programs can land one quantization step apart, so
                    # the bound is the column's quant step plus the
                    # chunked-equivalence envelope
                    atol = max(
                        float(np.max(np.abs(va))) / 32766.0 * 2.0,
                        OOM_ATOL,
                    )
                    np.testing.assert_allclose(
                        va, vb, rtol=OOM_RTOL * 5, atol=atol)
                else:
                    np.testing.assert_array_equal(va, vb)
        except AssertionError:
            rec["mismatched"].append(rel)
    rec["ok"] = not (
        rec["only_in_clean"] or rec["only_in_recovered"]
        or rec["mismatched"]
    )
    return rec


def run_drill(
    root: str,
    *,
    n_agents: int = 96,
    end_year: int = 2016,
    specs=DRILL_SPECS,
    policy: Optional[RetryPolicy] = None,
    make_runner: Optional[Callable] = None,
) -> Dict[str, object]:
    """Run the fault matrix under ``root`` and return the drill record
    (``ok`` plus per-site retries/recovery walls — the bench payload
    shape)."""
    from dgen_tpu.config import RunConfig

    make_sim = make_runner or make_synth_runner(
        n_agents=n_agents, end_year=end_year)
    policy = policy or RetryPolicy(
        max_retries=3, backoff_base_s=0.01, min_agent_chunk=32,
    )
    clean_dir = os.path.join(root, "clean")
    t0 = time.perf_counter()
    res_clean, rep_clean = run_supervised(
        make_sim, RunConfig(), run_dir=clean_dir, collect=False,
        policy=policy,
    )
    clean_wall = time.perf_counter() - t0
    assert rep_clean.retries == 0, "clean baseline must not retry"

    sites: Dict[str, dict] = {}
    ok = True
    for name, spec in specs:
        d = os.path.join(root, name)
        t0 = time.perf_counter()
        with faults_mod.injected(spec) as reg:
            _, report = run_supervised(
                make_sim, RunConfig(), run_dir=d, collect=False,
                policy=policy,
            )
        site = faults_mod.parse_spec(spec)[0].site
        fired = reg.fired(site)
        cmp_rec = compare_run_dirs(
            clean_dir, d, numeric=(":oom" in spec))
        verify_ok = all(r.ok for r in verify_run_dir(d))
        site_ok = bool(
            fired and report.succeeded and report.retries >= 1
            and cmp_rec["ok"] and verify_ok
        )
        ok = ok and site_ok
        sites[name] = {
            "spec": spec,
            "fired": fired,
            "retries": report.retries,
            "degradations": report.degradations,
            "recovery_wall_s": round(report.recovery_wall_s, 3),
            "drill_wall_s": round(time.perf_counter() - t0, 3),
            "parquet": {
                "compared": cmp_rec["compared"],
                "mismatched": cmp_rec["mismatched"],
            },
            "verify_ok": verify_ok,
            "ok": site_ok,
        }
        logger.info(
            "fault drill %s: %s (retries=%d, recovery %.2fs)",
            name, "ok" if site_ok else "FAILED",
            report.retries, report.recovery_wall_s,
        )
    return {
        "ok": ok,
        "n_agents": n_agents,
        "end_year": end_year,
        "clean_wall_s": round(clean_wall, 3),
        "retries_total": sum(s["retries"] for s in sites.values()),
        "recovery_wall_s_total": round(
            sum(s["recovery_wall_s"] for s in sites.values()), 3),
        "sites": sites,
    }
