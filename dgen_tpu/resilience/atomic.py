"""Crash-consistent artifact writes: temp file + ``os.replace``.

The PR-4 exporter proved the pattern on ``meta.json`` (a killed async
writer can never leave truncated JSON behind); this module extends it
to EVERY run artifact — parquet partitions, manifests, package
metadata, converter outputs.  The contract:

* a reader never observes a partially-written file at the final path —
  it sees the previous complete version, or the new complete version;
* a killed writer leaves at most a ``*.tmp`` sibling, which the next
  write (or a ``resilience verify``) identifies as garbage;
* dgenlint rule L11 flags bare ``open(..., 'w')`` / ``to_parquet``
  writes that bypass this helper.

Fault sites (:mod:`dgen_tpu.resilience.faults`): ``export_write``
fires BEFORE the rename (writer died, nothing landed — retried work
re-emits it) and ``export_torn`` AFTER it (torn storage damaged a
landed artifact — the failure mode the content-hashed manifest
exists to catch).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from dgen_tpu.resilience.faults import fault_point


def atomic_write(path: str, write_fn: Callable[[str], None]) -> None:
    """Write ``path`` crash-consistently: ``write_fn(tmp_path)``
    produces the bytes at a temp sibling, then one ``os.replace``
    publishes it.  The temp file is removed on failure."""
    tmp = f"{path}.tmp"
    ok = False
    try:
        write_fn(tmp)
        fault_point("export_write", path=path)
        os.replace(tmp, path)
        ok = True
    finally:
        if not ok and os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    fault_point("export_torn", path=path)


def atomic_write_text(path: str, text: str, **open_kw: Any) -> None:
    def _w(tmp: str) -> None:
        with open(tmp, "w", **open_kw) as f:
            f.write(text)

    atomic_write(path, _w)


def atomic_write_json(path: str, obj: Any, **dump_kw: Any) -> None:
    def _w(tmp: str) -> None:
        with open(tmp, "w") as f:
            json.dump(obj, f, **dump_kw)

    atomic_write(path, _w)


def atomic_write_bytes(path: str, blob: bytes) -> None:
    def _w(tmp: str) -> None:
        with open(tmp, "wb") as f:
            f.write(blob)

    atomic_write(path, _w)


def atomic_to_parquet(df, path: str, **to_parquet_kw: Any) -> None:
    """Parquet partition write via temp+rename — a killed exporter can
    never leave a truncated partition at a ``year=*.parquet`` path for
    ``load_surface`` to trip over."""
    atomic_write(path, lambda tmp: df.to_parquet(tmp, **to_parquet_kw))
