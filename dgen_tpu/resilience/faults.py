"""Deterministic fault injection for the run supervisor's recovery
drills.

Every recovery path in the stack (checkpoint resume, host-IO pipeline
error surfacing, OOM chunk degradation, export re-emission) is only
trustworthy if it is *exercised* — a preempted TPU VM or a killed
writer must not be the first time the code runs.  This module provides
named **fault sites** woven through the production paths; a
:class:`FaultRegistry` (installed from the ``DGEN_TPU_FAULTS`` env
knob, ``RunConfig.faults``, or a test's :func:`injected` context)
deterministically fires failures at chosen hit counts, so every
recovery path is testable on CPU in tier-1 and reproducible run to run.

Spec grammar (``DGEN_TPU_FAULTS``)::

    spec    := clause (";" clause)*
    clause  := site ["@" nth] ["x" times] [":" kind]
    site    := a registered site name (see SITES)
    nth     := 1-based hit index at which the clause starts firing
               (default 1 — the first hit)
    times   := how many consecutive hits fire (default 1)
    kind    := "error" (default) | "oom" | "kill" | "truncate" | "hang"

Examples::

    ckpt_save@2                 fail the 2nd checkpoint save
    year_step@3:oom             simulate device OOM on the 3rd year step
    hostio_io x2                fail the first two io-thread consumes
    export_torn:truncate        damage the first landed export artifact
    ckpt_save@2;hostio_fetch@1  two independent clauses

Kinds:

* ``error`` — raise :class:`FaultError` at the site (a generic
  transient failure; the supervisor classifies it by site).
* ``oom`` — raise :class:`SimulatedOOM`, whose message carries the
  ``RESOURCE_EXHAUSTED`` marker real XLA device OOMs carry, so the
  supervisor's classifier treats simulated and real OOMs identically.
* ``kill`` — ``os._exit`` the process mid-site, with no cleanup, no
  ``finally`` blocks, no atexit: the honest model of a preemption or
  OOM-kill.  Only meaningful under a subprocess drill.
* ``truncate`` — only at artifact sites (``export_torn``): truncate
  the just-landed file to half its bytes, then raise — the model of a
  torn write / partial flush that ``manifest verify`` exists to catch.
* ``hang`` — sleep ``DGEN_TPU_FAULT_HANG_S`` seconds (default 20) at
  the site, then continue normally: the model of a stalled-not-dead
  process (wedged device, paging storm).  Liveness probes stay green;
  only deadline enforcement (the serve layer's request timeout, the
  fleet front's forward timeout + breaker) can route around it.

The uninstalled fast path is one module-global ``None`` check per
site, so production runs pay nothing.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional

#: process exit code used by the ``kill`` kind — distinct from common
#: python/pytest codes so a subprocess drill can assert the death was
#: the injected one
KILL_EXIT_CODE = 77

#: registered fault sites -> where they live / what failing there models
SITES: Dict[str, str] = {
    "year_step": (
        "models.simulation.Simulation.step — the per-year device "
        "program dispatch; ``oom`` here simulates a RESOURCE_EXHAUSTED "
        "raise from the chunk scan"
    ),
    "ckpt_save": (
        "io.checkpoint.Writer.save — the orbax checkpoint write; "
        "``kill`` models a process death mid-save"
    ),
    "hostio_fetch": (
        "io.hostio.HostPipeline fetch stage — the batched device_get "
        "worker dying mid-year"
    ),
    "hostio_io": (
        "io.hostio.HostPipeline io stage — the ordered consume worker "
        "(collect/parquet/orbax) dying mid-year"
    ),
    "export_write": (
        "resilience.atomic.atomic_write, before the rename — a writer "
        "failing/killed before its artifact lands (tmp file only; the "
        "previous artifact, if any, survives intact)"
    ),
    "export_torn": (
        "resilience.atomic.atomic_write, after the rename — torn "
        "storage damaging a landed artifact (``truncate``)"
    ),
    "ingest": (
        "io.ingest._read_csv — a transient input-read failure "
        "(network filesystem flake)"
    ),
    "sweep_scenario": (
        "sweep.driver loop mode — a scenario run dying between "
        "scenarios of a group"
    ),
    "serve_query": (
        "serve.engine.ServeEngine.query_rows — a device failure on "
        "the serving path (the batcher must fail the batch's futures, "
        "never its worker thread)"
    ),
    "serve_replica_kill": (
        "serve.engine.ServeEngine.query_rows — a serving replica "
        "dying mid-query (``kill``: os._exit with requests in flight; "
        "the fleet front must fail over and the supervisor restart it)"
    ),
    "serve_replica_hang": (
        "serve.engine.ServeEngine.query_rows — a serving replica "
        "stalling mid-query (``hang``: the batcher worker sleeps "
        "DGEN_TPU_FAULT_HANG_S seconds, stalling every queued batch; "
        "the front's forward timeout + breaker must route around it)"
    ),
    "front_route": (
        "serve.front.FleetFront._route — a forward attempt to the "
        "chosen replica failing at the routing layer (connect "
        "refused/reset); the front must count it against that "
        "replica's breaker and retry on another replica"
    ),
    "gang_worker_kill": (
        "resilience.gangworker per-year export callback — a gang "
        "worker process dying mid-year (``kill``: preemption/OOM-kill "
        "with collectives in flight); the gang supervisor must tear "
        "down and relaunch the WHOLE gang from the manifest frontier"
    ),
    "gang_heartbeat_stall": (
        "resilience.gang.write_heartbeat — a gang worker stalling "
        "instead of dying (``hang``: wedged device, paging storm); the "
        "process stays alive, so only the supervisor's heartbeat "
        "staleness check can catch it"
    ),
    "gang_barrier": (
        "resilience.gangworker.StopFlag.should_stop — the gang's "
        "synchronized stop/emergency-checkpoint barrier failing (a "
        "collective error at the year boundary); the worker dies and "
        "the supervisor restarts the gang"
    ),
    "surface_load": (
        "serve.surface.AnswerSurface.load — the precomputed answer "
        "surface failing to load/verify at replica boot (``error``: an "
        "unreadable mmap; ``truncate``: the drill truncates table.bin "
        "before the open, modeling torn storage).  The engine must "
        "refuse the surface with a named reason and fall through to "
        "the compiled query path — never serve damaged answers"
    ),
    "ingest_corrupt_row": (
        "models.agents.build_agent_table — malformed rows entering the "
        "agent table at ingest (``corrupt``: NaN customer counts, "
        "negative loads, out-of-range tariff references on the "
        "DGEN_TPU_FAULT_CORRUPT_ROWS rows); load-time validation "
        "(resilience.quarantine) must quarantine exactly those rows"
    ),
    "bank_corrupt_row": (
        "models.simulation — a profile-bank row going bad (``corrupt``: "
        "NaN load row, or a NaN quant scale under int8 banks).  Hit #1 "
        "is Simulation construction (load-time corruption, caught by "
        "validation); later hits fire before a year step (silent "
        "mid-run data corruption, caught only by the health sentinel's "
        "breach -> attribute -> quarantine escalation)"
    ),
}

KINDS = ("error", "oom", "kill", "truncate", "hang", "corrupt")

#: which rows the ``corrupt`` kind damages (deterministic; env-tunable
#: so drills can aim at specific rows)
CORRUPT_ROWS_ENV = "DGEN_TPU_FAULT_CORRUPT_ROWS"
CORRUPT_ROWS_DEFAULT = (3, 17)


def corrupt_rows() -> tuple:
    """Deterministic row indices the ``corrupt`` kind damages (callers
    take them modulo their own row count).  A malformed env spec raises
    — same fail-loud rule as the fault-spec grammar: a drill aimed at
    rows that silently became the defaults proves nothing."""
    raw = os.environ.get(CORRUPT_ROWS_ENV, "").strip()
    if not raw:
        return CORRUPT_ROWS_DEFAULT
    try:
        rows = tuple(int(r) for r in raw.split(",") if r.strip())
    except ValueError as e:
        raise ValueError(
            f"malformed {CORRUPT_ROWS_ENV}={raw!r}: expected a comma "
            "list of row indices"
        ) from e
    return rows or CORRUPT_ROWS_DEFAULT

#: how long a ``hang`` fault stalls its site (seconds); env-tunable so
#: drills can pick a stall longer than the front's forward timeout but
#: short enough to watch the fleet heal inside a smoke budget
HANG_ENV = "DGEN_TPU_FAULT_HANG_S"
HANG_DEFAULT_S = 20.0


def hang_seconds() -> float:
    raw = os.environ.get(HANG_ENV, "").strip()
    try:
        return float(raw) if raw else HANG_DEFAULT_S
    except ValueError:
        return HANG_DEFAULT_S


class FaultError(RuntimeError):
    """An injected failure.  ``site``/``kind``/``hit`` identify which
    clause fired; the supervisor's classifier keys off them."""

    def __init__(self, site: str, kind: str, hit: int) -> None:
        super().__init__(
            f"injected fault at site '{site}' (kind={kind}, hit #{hit})"
        )
        self.site = site
        self.kind = kind
        self.hit = hit


class SimulatedOOM(FaultError):
    """An injected device OOM.  The message carries the
    ``RESOURCE_EXHAUSTED`` marker so :func:`dgen_tpu.resilience.
    supervisor.classify_error` treats it exactly like a real XLA OOM."""

    def __init__(self, site: str, hit: int) -> None:
        FaultError.__init__(self, site, "oom", hit)
        self.args = (
            f"RESOURCE_EXHAUSTED: simulated device OOM injected at site "
            f"'{site}' (hit #{hit})",
        )


@dataclasses.dataclass(frozen=True)
class FaultClause:
    """One parsed spec clause: fire ``kind`` at hits
    ``nth .. nth+times-1`` of ``site``."""

    site: str
    nth: int = 1
    times: int = 1
    kind: str = "error"

    def __post_init__(self) -> None:
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site '{self.site}' (known: "
                f"{', '.join(sorted(SITES))})"
            )
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind '{self.kind}' (known: "
                f"{', '.join(KINDS)})"
            )
        if self.nth < 1 or self.times < 1:
            raise ValueError("nth and times must be >= 1")

    def matches(self, hit: int) -> bool:
        return self.nth <= hit < self.nth + self.times


def parse_spec(spec: str) -> List[FaultClause]:
    """Parse the ``DGEN_TPU_FAULTS`` grammar (module docstring).
    Unknown sites/kinds raise — a typo'd site must fail loudly, not
    silently never fire."""
    clauses: List[FaultClause] = []
    for raw in spec.split(";"):
        tok = raw.strip()
        if not tok:
            continue
        kind = "error"
        if ":" in tok:
            tok, kind = tok.rsplit(":", 1)
            kind = kind.strip()
        times = 1
        if "x" in tok:
            head, _, tail = tok.rpartition("x")
            if tail.strip().isdigit():
                tok, times = head, int(tail)
        nth = 1
        if "@" in tok:
            tok, n = tok.split("@", 1)
            nth = int(n.strip())
        clauses.append(FaultClause(tok.strip(), nth, times, kind))
    return clauses


class FaultRegistry:
    """Thread-safe hit counting + deterministic firing for a parsed
    fault spec.  ``hits`` counts every visit to a site (fired or not),
    so a spec like ``ckpt_save@2`` fires on exactly the second
    checkpoint save of the process, every run."""

    def __init__(self, clauses: List[FaultClause]) -> None:
        self.clauses = list(clauses)
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultRegistry":
        return cls(parse_spec(spec))

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def fired(self, site: str) -> int:
        with self._lock:
            return self._fired.get(site, 0)

    def hit(self, site: str, path: Optional[str] = None) -> int:
        """Count a visit to ``site``; raise/kill/truncate when a clause
        matches.  ``path`` is the landed artifact for truncate sites.
        Returns 1 when a ``corrupt``-kind clause fired (the CALLER owns
        the data mutation — see :func:`corrupt_point`), else 0."""
        if site not in SITES:
            raise ValueError(f"unregistered fault site '{site}'")
        with self._lock:
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            clause = next(
                (c for c in self.clauses
                 if c.site == site and c.matches(n)), None,
            )
            if clause is not None:
                self._fired[site] = self._fired.get(site, 0) + 1
        if clause is None:
            return 0
        if clause.kind == "corrupt":
            # the site's caller applies a deterministic data mutation
            # (NaN rows, garbage references) and continues NORMALLY —
            # the model of bad input data / silent data corruption that
            # only validation or the health sentinel can catch
            return 1
        if clause.kind == "hang":
            # model a stall, not a death: hold the site for the
            # configured wall, then continue NORMALLY — the caller
            # never learns it hung, exactly like a wedged device or a
            # GC/paging stall.  Timeout enforcement is the test.
            time.sleep(hang_seconds())
            return 0
        if clause.kind == "kill":
            # model a preemption/OOM-kill: no cleanup, no finally, no
            # atexit — exactly what the crash-consistent artifact layer
            # must survive
            os._exit(KILL_EXIT_CODE)
        if clause.kind == "oom":
            raise SimulatedOOM(site, n)
        if clause.kind == "truncate":
            if path is not None and os.path.isfile(path):
                size = os.path.getsize(path)
                with open(path, "rb+") as f:
                    f.truncate(max(size // 2, 1))
        raise FaultError(site, clause.kind, n)


#: the process-wide installed registry (None = fault injection off;
#: fault_point is then a single global read)
_active: Optional[FaultRegistry] = None


def install(registry: Optional[FaultRegistry]) -> Optional[FaultRegistry]:
    """Install ``registry`` process-wide; returns the previous one."""
    global _active
    prev, _active = _active, registry
    return prev


def active() -> Optional[FaultRegistry]:
    return _active


def install_from_env(env: str = "DGEN_TPU_FAULTS") -> Optional[FaultRegistry]:
    """Install a registry parsed from ``env`` (no-op when unset/empty).
    Called by the resilience CLI, the supervisor, and the fault-drill
    bench — NOT at import, so library users opt in explicitly."""
    spec = os.environ.get(env, "").strip()
    if not spec:
        return None
    reg = FaultRegistry.parse(spec)
    install(reg)
    return reg


class injected:
    """Context manager installing a registry for the duration of a
    drill/test::

        with faults.injected("ckpt_save@2") as reg:
            ...
        assert reg.fired("ckpt_save") == 1
    """

    def __init__(self, spec: str) -> None:
        self.registry = FaultRegistry.parse(spec)
        self._prev: Optional[FaultRegistry] = None

    def __enter__(self) -> FaultRegistry:
        self._prev = install(self.registry)
        return self.registry

    def __exit__(self, *exc) -> None:
        install(self._prev)


def fault_point(site: str, path: Optional[str] = None) -> None:
    """The per-site hook on the production paths: a no-op (one global
    read) unless a registry is installed."""
    reg = _active
    if reg is not None:
        reg.hit(site, path=path)


def corrupt_point(site: str) -> int:
    """The data-corruption hook: count a visit to ``site`` and return
    1 when a ``corrupt``-kind clause fires there (the caller then
    applies its deterministic mutation and continues), else 0.
    Non-corrupt kinds registered at the site still raise/kill as
    usual.  Uninstalled fast path: one global read."""
    reg = _active
    if reg is None:
        return 0
    return reg.hit(site) or 0
