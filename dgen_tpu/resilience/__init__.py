"""Fault-injected, self-healing run supervision (docs/resilience.md).

Layers, bottom up:

* :mod:`~dgen_tpu.resilience.faults` — deterministic fault injection
  at named production sites (``DGEN_TPU_FAULTS`` spec grammar).
* :mod:`~dgen_tpu.resilience.atomic` — temp+rename artifact writes
  (the PR-4 ``meta.json`` pattern, extended to every run artifact).
* :mod:`~dgen_tpu.resilience.manifest` — content-hashed per-year
  artifact ledger; ``verify`` audits any run directory.
* :mod:`~dgen_tpu.resilience.supervisor` — bounded retry + checkpoint
  resume + graceful degradation around Simulation/sweep runs.
* :mod:`~dgen_tpu.resilience.gang` — the multi-process layer: a
  jax.distributed worker gang supervised as a unit (heartbeats, whole-
  gang teardown/relaunch from the merged shard-ledger frontier, crash-
  loop breaker, elastic P -> P' resharded resume via
  :mod:`dgen_tpu.parallel.elastic`).

CLI: ``python -m dgen_tpu.resilience {run,verify,drill}``
(``drill --gang`` runs the worker-kill / stall / elastic-resume gang
drill).
"""

from dgen_tpu.resilience.atomic import (  # noqa: F401
    atomic_to_parquet,
    atomic_write,
    atomic_write_bytes,
    atomic_write_json,
    atomic_write_text,
)
from dgen_tpu.resilience.faults import (  # noqa: F401
    FaultError,
    FaultRegistry,
    SimulatedOOM,
    fault_point,
    injected,
    install_from_env,
)
from dgen_tpu.resilience.gang import (  # noqa: F401
    GangCrashLoop,
    GangReport,
    GangSupervisor,
)
from dgen_tpu.resilience.manifest import (  # noqa: F401
    GangManifest,
    RunManifest,
    VerifyReport,
    verify_run_dir,
)
from dgen_tpu.resilience.supervisor import (  # noqa: F401
    RetryPolicy,
    Supervisor,
    SupervisorReport,
    classify_error,
    run_supervised,
)
