"""The gang drill: prove, on a CPU/gloo gang, that a multi-process
simulation run survives worker death, worker stall, and a permanent
P -> P' shrink — with artifacts indistinguishable from an
uninterrupted gang.

``python -m dgen_tpu.resilience drill --gang`` runs it (tools/check.sh
wires a 2-process smoke configuration; the bench stamps its timings
under ``DGEN_TPU_BENCH_GANG``).  Rounds:

* **baseline** — a clean P-process gang to completion (the comparison
  oracle; also proves the supervisor adds zero restarts to a healthy
  gang).
* **kill** — one worker SIGKILLed mid-year (``gang_worker_kill@2:kill``
  via ``os._exit``, collectives in flight).  The supervisor must tear
  the whole gang down, relaunch from the merged manifest frontier, and
  finish with every per-process parquet shard **byte-identical** to the
  baseline and a clean merged-manifest verify.
* **stall** — one worker hangs instead of dying
  (``gang_heartbeat_stall@4:hang``); only heartbeat staleness can catch
  it.  Same recovery contract.  (Needs >= 3 model years so the stall
  lands after the steady-state compile; skipped otherwise.)
* **elastic** — the gang is stopped after its first year through the
  synchronized SIGTERM-analogue barrier (``DGEN_GANG_STOP_AFTER`` on
  worker 0 ONLY — the other workers learn of the stop via the barrier),
  then resumed at P' < P workers over the same total device count: the
  orbax checkpoint written at P re-places under the P' mesh
  (parallel.elastic) and the resumed years' rows must be exactly the
  baseline's (the shard files differ in how rows are split across
  processes, so pre-stop years compare byte-for-byte and post-resume
  years compare row-for-row after aligning on agent_id).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from dgen_tpu.config import GangConfig, ScenarioConfig
from dgen_tpu.resilience.gang import GangSupervisor
from dgen_tpu.resilience.manifest import verify_run_dir
from dgen_tpu.resilience.supervisor import RetryPolicy
from dgen_tpu.utils.logging import get_logger

logger = get_logger()

#: per-process surfaces the gang exports (state_hourly is off in the
#: drill configuration)
GANG_SURFACES = ("agent_outputs", "finance_series")


def _parts_by_year(run_dir: str, surface: str) -> Dict[int, List[str]]:
    d = os.path.join(run_dir, surface)
    out: Dict[int, List[str]] = {}
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not name.endswith(".parquet"):
            continue
        year = int(name.split("=")[1].split("-")[0].split(".")[0])
        out.setdefault(year, []).append(name)
    return out


def _read_rows(paths: List[str]):
    import pandas as pd

    df = pd.concat(
        [pd.read_parquet(p) for p in paths], ignore_index=True,
    )
    return df.sort_values("agent_id").reset_index(drop=True)


#: float tolerance for years RECOMPUTED on a different process layout
#: (the elastic P -> P' resume): the restored carry is bit-exact and a
#: same-topology restart is byte-identical (the kill round proves it),
#: but each process's XLA executable re-associates the f32 hour-axis
#: sums differently when its addressable device count changes — the
#: same envelope as the chunked-vs-whole equivalence suite
ELASTIC_RTOL = 5e-5
ELASTIC_ATOL = 1e-3


def compare_gang_run_dirs(baseline: str, other: str,
                          rtol: float = 0.0,
                          atol: float = 0.0) -> Dict[str, object]:
    """Compare two gang run directories surface by surface, year by
    year.  Years whose part SETS match compare byte-for-byte; years
    split differently across processes compare row-for-row after
    aligning on ``agent_id`` (multi-host exports are full f32).
    ``rtol``/``atol`` of 0 demand exact value equality (same-topology
    recovery); the elastic drill passes :data:`ELASTIC_RTOL` /
    :data:`ELASTIC_ATOL` for its recomputed years."""
    rec: Dict[str, object] = {
        "mismatched": [], "year_mismatch": [], "compared": 0,
        "row_compared_years": [],
    }
    for surface in GANG_SURFACES:
        a, b = (_parts_by_year(baseline, surface),
                _parts_by_year(other, surface))
        if set(a) != set(b):
            rec["year_mismatch"].append(
                f"{surface}: {sorted(a)} vs {sorted(b)}")
            continue
        for year in sorted(a):
            rec["compared"] += 1
            pa = [os.path.join(baseline, surface, n) for n in a[year]]
            pb = [os.path.join(other, surface, n) for n in b[year]]
            if a[year] == b[year]:
                same = all(
                    open(x, "rb").read() == open(y, "rb").read()
                    for x, y in zip(pa, pb)
                )
                if same:
                    continue
            # different shard split (or byte mismatch worth explaining):
            # align rows on agent_id and demand exact value equality
            da, db = _read_rows(pa), _read_rows(pb)
            rec["row_compared_years"].append(f"{surface}/{year}")
            try:
                if list(da.columns) != list(db.columns) or len(da) != len(db):
                    raise AssertionError("shape/columns differ")
                for col in da.columns:
                    va = np.stack(da[col].values)
                    vb = np.stack(db[col].values)
                    if va.dtype.kind in "fc" and (rtol or atol):
                        np.testing.assert_allclose(
                            va, vb, rtol=rtol, atol=atol, err_msg=col)
                    elif not np.array_equal(va, vb):
                        raise AssertionError(col)
            except AssertionError as e:
                rec["mismatched"].append(f"{surface}/{year}: {e}")
    rec["ok"] = not (rec["mismatched"] or rec["year_mismatch"])
    return rec


def _checkpoint_bitexact(ckpt_a: str, ckpt_b: str, year: int,
                         n_agents: int) -> bool:
    """Whether two checkpoint directories hold bit-identical carries at
    ``year``.  Restored through a host-array template (the same
    topology-free path every elastic resume uses), so a step written by
    a P=4 gang compares directly against any other layout's."""
    import jax

    from dgen_tpu.io import checkpoint as ckpt

    def raw(d):
        _, carry = ckpt.restore_year(d, n_agents, int(year))
        return [np.asarray(x) for x in jax.tree.leaves(carry)]

    la, lb = raw(ckpt_a), raw(ckpt_b)
    return len(la) == len(lb) and all(
        np.array_equal(a, b) for a, b in zip(la, lb)
    )


def _padded_agents(run_dir: str) -> Optional[int]:
    """The padded global table size a gang run stamped into its meta."""
    import json

    try:
        with open(os.path.join(run_dir, "meta.json")) as f:
            return int(json.load(f)["gang"]["n_agents_padded"])
    except (OSError, KeyError, ValueError, TypeError):
        return None


def _gang(
    run_dir: str,
    config: GangConfig,
    years: List[int],
    worker_env: Dict[str, str],
    env_for=None,
    gang_dir: Optional[str] = None,
    seed: int = 0,
):
    return GangSupervisor(
        run_dir, years, config=config,
        policy=RetryPolicy(backoff_base_s=0.05),
        env_for=env_for, worker_env=worker_env, gang_dir=gang_dir,
        seed=seed,
    )


def run_gang_drill(
    root: str,
    *,
    processes: int = 4,
    shrink_to: int = 2,
    total_devices: Optional[int] = None,
    agents: int = 96,
    end_year: int = 2018,
    sizing_iters: int = 6,
    stall: bool = True,
    stall_timeout_s: float = 25.0,
) -> Dict[str, object]:
    """Run the gang fault matrix under ``root`` and return the drill
    record (``ok`` plus per-round restarts/recovery walls — the bench
    payload shape)."""
    total = total_devices or processes
    scen = ScenarioConfig(
        name="gang", start_year=2014, end_year=end_year, anchor_years=(),
    )
    years = [int(y) for y in scen.model_years]
    worker_env = {
        "DGEN_AGENTS": str(agents),
        "DGEN_END_YEAR": str(end_year),
        "DGEN_GANG_SIZING_ITERS": str(sizing_iters),
    }
    base_cfg = GangConfig(
        n_processes=processes, total_devices=total,
        stall_timeout_s=120.0, max_restarts=3, restart_window_s=600.0,
    )
    rounds: Dict[str, dict] = {}
    ok = True

    def _round(name: str, run_dir: str, report, *, compare: bool = True,
               want_restarts: int = 0, t0: float = 0.0,
               rtol: float = 0.0, atol: float = 0.0) -> dict:
        verify_ok = all(r.ok for r in verify_run_dir(run_dir))
        cmp_rec = (
            compare_gang_run_dirs(
                os.path.join(root, "baseline"), run_dir,
                rtol=rtol, atol=atol)
            if compare else {"ok": True, "compared": 0}
        )
        rec = {
            "restarts": report.restarts,
            "recovery_wall_s": round(report.recovery_wall_s, 3),
            "attempts": [
                {"outcome": a.outcome, "reason": a.reason,
                 "worker": a.worker, "exit_code": a.exit_code}
                for a in report.attempts
            ],
            "shrinks": report.shrinks,
            "completed_through": report.completed_through,
            "parquet": {
                "compared": cmp_rec.get("compared"),
                "mismatched": cmp_rec.get("mismatched", []),
                "row_compared_years": cmp_rec.get(
                    "row_compared_years", []),
            },
            "verify_ok": verify_ok,
            "drill_wall_s": round(time.perf_counter() - t0, 3),
            "ok": bool(
                report.succeeded and verify_ok and cmp_rec["ok"]
                and report.restarts >= want_restarts
            ),
        }
        logger.info("gang drill %s: %s (restarts=%d)", name,
                    "ok" if rec["ok"] else "FAILED", report.restarts)
        return rec

    # --- baseline: clean P-process gang ---
    t0 = time.perf_counter()
    base_dir = os.path.join(root, "baseline")
    rep = _gang(base_dir, base_cfg, years, worker_env).run()
    rounds["baseline"] = _round(
        "baseline", base_dir, rep, compare=False, t0=t0)
    rounds["baseline"]["ok"] = bool(
        rounds["baseline"]["ok"] and rep.restarts == 0
        and not rep.preempted
    )
    ok = ok and rounds["baseline"]["ok"]

    # --- kill: one worker SIGKILLed mid-year ---
    t0 = time.perf_counter()
    kill_dir = os.path.join(root, "kill")
    kill_worker = min(2, processes - 1)

    def kill_env(i: int, attempt: int):
        if i == kill_worker and attempt == 0:
            return {"DGEN_TPU_FAULTS": "gang_worker_kill@2:kill"}
        return None

    rep = _gang(kill_dir, base_cfg, years, worker_env,
                env_for=kill_env, seed=1).run()
    rounds["kill"] = _round(
        "kill", kill_dir, rep, want_restarts=1, t0=t0)
    ok = ok and rounds["kill"]["ok"]

    # --- stall: one worker hangs; only heartbeat staleness catches it
    # (the stall is armed at the 4th heartbeat — after the steady-state
    # compile — so it needs >= 3 model years) ---
    if stall and len(years) >= 3:
        t0 = time.perf_counter()
        stall_dir = os.path.join(root, "stall")
        stall_cfg = GangConfig(
            n_processes=processes, total_devices=total,
            stall_timeout_s=stall_timeout_s,
            max_restarts=3, restart_window_s=600.0,
        )

        def stall_env(i: int, attempt: int):
            if i == min(1, processes - 1) and attempt == 0:
                return {
                    "DGEN_TPU_FAULTS": "gang_heartbeat_stall@4:hang",
                    "DGEN_TPU_FAULT_HANG_S": "600",
                }
            return None

        rep = _gang(stall_dir, stall_cfg, years, worker_env,
                    env_for=stall_env, seed=2).run()
        rounds["stall"] = _round(
            "stall", stall_dir, rep, want_restarts=1, t0=t0)
        stalled = any(
            a.reason == "heartbeat_stall" for a in rep.attempts)
        rounds["stall"]["ok"] = bool(rounds["stall"]["ok"] and stalled)
        ok = ok and rounds["stall"]["ok"]

    # --- elastic: synchronized stop after year 1, resumed at P' < P
    # over the same total device count ---
    if shrink_to:
        t0 = time.perf_counter()
        el_dir = os.path.join(root, "elastic")

        def stop_env(i: int, attempt: int):
            # worker 0 ONLY: the others must learn of the stop via the
            # cross-process barrier, proving the synchronized
            # emergency-checkpoint contract
            if i == 0 and attempt == 0:
                return {"DGEN_GANG_STOP_AFTER": str(years[0])}
            return None

        rep_a = _gang(el_dir, base_cfg, years, worker_env,
                      env_for=stop_env, seed=3).run()
        shrunk_cfg = GangConfig(
            n_processes=shrink_to, total_devices=total,
            stall_timeout_s=120.0, max_restarts=3,
            restart_window_s=600.0,
        )
        rep_b = _gang(el_dir, shrunk_cfg, years, worker_env,
                      seed=4).run()
        # the carry the P' gang resumed FROM must be bit-identical to
        # the uninterrupted baseline's checkpoint at the same year —
        # the restore is exact; only years recomputed on the changed
        # process layout carry the f32 re-association envelope
        n_padded = _padded_agents(el_dir)
        restore_exact = n_padded is not None and _checkpoint_bitexact(
            os.path.join(root, "baseline", "checkpoints"),
            os.path.join(el_dir, "checkpoints"),
            years[0], n_padded,
        )
        rounds["elastic"] = _round(
            "elastic", el_dir, rep_b, t0=t0,
            rtol=ELASTIC_RTOL, atol=ELASTIC_ATOL,
        )
        rounds["elastic"]["stopped_through"] = rep_a.completed_through
        rounds["elastic"]["restore_bitexact"] = restore_exact
        rounds["elastic"]["ok"] = bool(
            rounds["elastic"]["ok"]
            and rep_a.preempted
            and rep_a.completed_through == years[0]
            and not rep_b.preempted
            and restore_exact
            and rounds["elastic"]["parquet"]["row_compared_years"]
        )
        ok = ok and rounds["elastic"]["ok"]

    return {
        "ok": ok,
        "processes": processes,
        "shrink_to": shrink_to,
        "total_devices": total,
        "agents": agents,
        "years": years,
        "restarts_total": sum(r["restarts"] for r in rounds.values()),
        "recovery_wall_s_total": round(
            sum(r["recovery_wall_s"] for r in rounds.values()), 3),
        "rounds": rounds,
    }
