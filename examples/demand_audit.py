"""Demand-charge analysis over a converted reference-schema population.

The adoption hot loop skips demand charges on purpose (the reference's
SKIP_DEMAND_CHARGES parity, financial_functions.py:35); this is the
ANALYSIS path: convert a reference-format pickle whose tariff dicts
carry ``ur_dc_*`` / ``d_flat_*`` structures, size a year, then price
each agent's baseline / PV-only / PV+battery net load through
``dgen_tpu.analysis.demand_charge_audit``.

Runs off the committed golden fixture (tests/fixtures/).
"""

import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pandas as pd

from dgen_tpu.analysis import demand_charge_audit
from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import convert, package
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   os.pardir, "tests", "fixtures")

frame = pd.read_pickle(os.path.join(FIX, "golden_agents.pkl"))
pkg = tempfile.mkdtemp(prefix="dgen_demand_audit_")
convert.from_reference_pickle(
    frame, pkg,
    pd.read_pickle(os.path.join(FIX, "golden_load_profiles.pkl")),
    pd.read_pickle(os.path.join(FIX, "golden_solar_profiles.pkl")),
    wholesale_by_region={"SA": np.full(8760, 0.03)},
)
pop = package.load_population(pkg, pad_multiple=32)

cfg = ScenarioConfig(name="audit", start_year=2014, end_year=2016,
                     anchor_years=())
inputs = scen.uniform_inputs(
    cfg, n_groups=pop.table.n_groups,
    n_regions=np.asarray(pop.profiles.wholesale).shape[0],
    n_states=pop.table.n_states,
)
sim = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                 RunConfig(sizing_iters=8))
carry = sim.init_carry()
_, outs = sim.step(carry, 0, first_year=True)

ya = scen.apply_year(sim.table, sim.inputs, jnp.asarray(0, jnp.int32))
audit = demand_charge_audit(
    sim.table, sim.profiles, pop.tariff_specs,
    ya.load_kwh_per_customer,
    system_kw=outs.system_kw, batt_kw=outs.batt_kw,
    batt_kwh=outs.batt_kwh, batt_rt_eff=ya.batt_rt_eff,
)
assert audit is not None, "golden fixture carries demand tariffs"

m = np.asarray(sim.table.mask) > 0
priced = np.asarray(audit["baseline"])[m] > 0
print(f"{priced.sum()} of {m.sum()} agents carry demand charges")
for k in ("baseline", "pv_only", "with_batt"):
    v = np.asarray(audit[k])[m][priced]
    print(f"  {k:10s}: mean ${v.mean():,.0f}/yr  "
          f"median ${np.median(v):,.0f}/yr")
sav = np.asarray(audit["baseline"] - audit["with_batt"])[m][priced]
print(f"PV+battery demand-charge savings: mean ${sav.mean():,.0f}/yr")

# --- dispatch observability (the reference's per-run dispatch stats,
# batt_dispatch_helpers.py:103-336) over the same sized systems ---
import jax

from dgen_tpu.analysis import dispatch_diagnostics, summarize_dispatch
from dgen_tpu.ops import dispatch as dp
from dgen_tpu.ops.sizing import INV_EFF

load = sim.profiles.load[sim.table.load_idx] * ya.load_kwh_per_customer[:, None]
gen = sim.profiles.solar_cf[sim.table.cf_idx] * (outs.system_kw * INV_EFF)[:, None]
dr = jax.vmap(dp.dispatch_battery)(load, gen, outs.batt_kw, outs.batt_kwh,
                                   ya.batt_rt_eff)
sell = jnp.full_like(load, 0.04)
diags = dispatch_diagnostics(load, gen, dr, sell, batt_kw=outs.batt_kw)
stats = summarize_dispatch(diags, np.asarray(sim.table.mask))
print(f"midday PV-surplus capture: {stats['capture_mid_frac']:.2f} "
      f"(batt absorbed {stats['pv_to_batt_mid_kwh']:,.0f} of "
      f"{stats['surplus_mid_kwh']:,.0f} kWh)")
print(f"bottlenecks: {stats['power_bound_hours']:,.0f} power-bound / "
      f"{stats['soc_bound_hours']:,.0f} headroom-bound agent-hours")
print("DEMAND AUDIT OK")
