"""User-style quickstart: size a synthetic population and run one market
step through dgen_tpu's public API (what a reference user would do)."""
import time

import jax
import jax.numpy as jnp
import numpy as np

import dgen_tpu
from dgen_tpu.io import synth
from dgen_tpu.models import market
from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops import cashflow as cf_ops
from dgen_tpu.ops import sizing

print("dgen_tpu", dgen_tpu.__version__, "| devices:", jax.devices())

# 1. population
pop = synth.generate_population(512, states=["DE", "CA", "TX"], seed=7)
t = pop.table
print(f"agents: {t.n_agents} (mask sum {float(t.mask.sum()):.0f}), "
      f"tariff bank: {pop.tariffs.n_tariffs} tariffs, "
      f"P={pop.tariffs.max_periods} T={pop.tariffs.max_tiers}")

# 2. assemble econ inputs (as the year step will)
load = pop.profiles.load[t.load_idx] * t.load_kwh_per_customer_in_bin[:, None]
gen_per_kw = pop.profiles.solar_cf[t.cf_idx]
ts_sell = pop.profiles.wholesale[t.region_idx]
n = t.n_agents
f32 = jnp.float32
fin = jax.tree.map(lambda x: jnp.broadcast_to(x, (n,)), cf_ops.FinanceParams.example())
envs = sizing.AgentEconInputs(
    load=load, gen_per_kw=gen_per_kw, ts_sell=ts_sell,
    tariff=jax.vmap(lambda k: bill_ops.gather_tariff(pop.tariffs, k))(t.tariff_idx),
    tariff_w=None,
    fin=fin, inc=jax.tree.map(lambda x: x, t.incentives),
    load_kwh_per_customer=t.load_kwh_per_customer_in_bin,
    elec_price_escalator=jnp.full(n, 0.005, f32),
    pv_degradation=jnp.full(n, 0.005, f32),
    system_capex_per_kw=jnp.full(n, 2500.0, f32),
    system_capex_per_kw_combined=jnp.full(n, 2600.0, f32),
    batt_capex_per_kwh_combined=jnp.full(n, 800.0, f32),
    cap_cost_multiplier=jnp.ones(n, f32),
    value_of_resiliency_usd=jnp.zeros(n, f32),
    one_time_charge=jnp.zeros(n, f32),
)

# 3. size the whole fleet on device
t0 = time.time()
res = sizing.size_agents(envs, n_periods=pop.tariffs.max_periods, n_years=25)
jax.block_until_ready(res.npv)
t1 = time.time()
res2 = sizing.size_agents(envs, n_periods=pop.tariffs.max_periods, n_years=25)
jax.block_until_ready(res2.npv)
t2 = time.time()
kw = np.asarray(res.system_kw)
pb = np.asarray(res.payback_period)
print(f"sized {n} agents: compile+run {t1-t0:.1f}s, cached run {t2-t1:.3f}s "
      f"({n/(t2-t1):.0f} agents/sec)")
print(f"system_kw: min {kw.min():.2f} med {np.median(kw):.2f} max {kw.max():.1f}")
print(f"payback:   min {pb.min():.1f} med {np.median(pb):.1f} max {pb.max():.1f}")
print(f"npv finite: {np.isfinite(np.asarray(res.npv)).all()}, "
      f"batt_kwh med {np.median(np.asarray(res.batt_kwh)):.2f}")

# 4. market step: mms -> diffusion -> integer battery allocation
mms_table = jnp.asarray(np.stack([np.exp(-np.arange(302) * 0.1 / 4.0)] * 3))
mms = market.max_market_share(jnp.asarray(pb), t.sector_idx, mms_table)
state = market.MarketState.zeros(n)
out = market.diffusion_step(
    state, mms * t.mask, np.asarray(res.system_kw), jnp.full(n, 2500.0),
    developable_agent_weight=t.developable_agent_weight(t.customers_in_bin),
    bass_p=jnp.full(n, 0.0015), bass_q=jnp.full(n, 0.35),
    teq_yr1=jnp.full(n, 2.0), is_first_year=True,
)
alloc = market.allocate_battery_adopters(
    out.new_adopters, t.group_idx, jnp.full(t.n_groups, 0.25),
    t.agent_id, t.n_groups,
)
na = np.asarray(out.new_adopters)
print(f"diffusion: new adopters total {na.sum():.1f}, share med "
      f"{np.median(np.asarray(out.market_share)):.4f}")
print(f"battery alloc: {np.asarray(alloc).sum():.0f} integer adopters "
      f"(~25% of {na.sum():.0f})")
assert np.all(np.asarray(alloc) == np.round(np.asarray(alloc))), "non-integer alloc"
print("QUICKSTART OK")
