"""National-style run from the reference's own input_data CSVs:
trajectory ingest -> Simulation -> parquet exports + checkpoints.

Mirrors BASELINE.json config #4's shape (national residential-heavy,
biennial years) at reduced agent count. Requires the reference mount at
/root/reference (read-only)."""
import shutil
import tempfile
import time

import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import export as exp
from dgen_tpu.io import synth
from dgen_tpu.io.reference_inputs import (
    scenario_inputs_from_reference,
    wholesale_profile_bank,
)
from dgen_tpu.models.agents import ProfileBank
from dgen_tpu.models.simulation import Simulation

REF = "/root/reference/dgen_os/input_data"

cfg = ScenarioConfig(name="national-ref", start_year=2014, end_year=2040)
states = list(synth.STATES)
inputs, meta = scenario_inputs_from_reference(REF, cfg, states)
print(f"ingested reference trajectories: {sorted(meta['files'])}")
print(f"data sources: {meta['data_sources']}")
print(f"market curves: {meta['market_curves']} "
      "(synthetic_default = NOT dGen's Postgres-only Bass/mms curves; "
      "drop in max_market_curves.csv / bass_params.csv for real ones)")

pop = synth.generate_population(4096, seed=3, n_regions=len(meta["regions"]))
# flat annual sell rate = the reference's own semantics
# (financial_functions.py:372); drop a wholesale_hourly_shape.csv into
# the input root to give it hourly structure
profiles = ProfileBank(
    load=pop.profiles.load,
    solar_cf=pop.profiles.solar_cf,
    wholesale=jnp.asarray(wholesale_profile_bank(meta, REF)),
)

run_dir = tempfile.mkdtemp(prefix="dgen_tpu_run_")
exporter = exp.RunExporter(
    run_dir, agent_id=np.asarray(pop.table.agent_id),
    mask=np.asarray(pop.table.mask), state_names=states,
    meta={"scenario": cfg.name, "market_curves": meta["market_curves"],
          "data_sources": meta["data_sources"]},
)
sim = Simulation(pop.table, profiles, pop.tariffs, inputs, cfg,
                 RunConfig(sizing_iters=10))
t0 = time.time()
res = sim.run(callback=exporter, checkpoint_dir=f"{run_dir}/ckpt")
elapsed = time.time() - t0

m = np.asarray(pop.table.mask)
s = res.summary(m)
n_real = int(m.sum())
print(f"{n_real} agents x {len(res.years)} years in {elapsed:.1f}s "
      f"({n_real * len(res.years) / elapsed:.0f} agent-years/sec)")
for i in (0, len(res.years) // 2, len(res.years) - 1):
    print(f"  {res.years[i]}: {s['system_kw_cum'][i] / 1e6:8.2f} GW cum, "
          f"{s['adopters'][i]:12.0f} adopters, "
          f"{s['batt_kwh_cum'][i] / 1e6:6.2f} GWh storage")

ao = exp.load_surface(run_dir, "agent_outputs")
print(f"agent_outputs: {len(ao)} rows, {len(ao.columns)} cols")
from dgen_tpu.io import checkpoint as ckpt
print(f"latest checkpoint year: {ckpt.latest_year(f'{run_dir}/ckpt')}")

# resume from the checkpoint and confirm it's a no-op (already finished)
res2 = sim.run(checkpoint_dir=f"{run_dir}/ckpt", resume=True)
assert len(res2.agent) == 0 or len(res2.agent["system_kw_cum"]) == 0
shutil.rmtree(run_dir)
assert s["system_kw_cum"][-1] > 0
print("REFERENCE SCENARIO RUN OK")
