"""Delaware residential 2014-2024 — BASELINE.json config #1, the
minimum end-to-end slice (SURVEY.md §7 build order step 4): synthetic
DE population -> multi-year Simulation driver -> adoption curve."""
import time

import jax
import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation

cfg = ScenarioConfig(name="delaware-res", start_year=2014, end_year=2024,
                     anchor_years=())
pop = synth.generate_population(
    1024, states=["DE"], seed=1, sector_weights=(1.0, 0.0, 0.0)
)
inputs = scen.uniform_inputs(
    cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
    overrides={"attachment_rate": jnp.full((pop.table.n_groups,), 0.25)},
)
sim = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                 RunConfig(sizing_iters=10), with_hourly=True)

t0 = time.time()
res = sim.run()
elapsed = time.time() - t0

m = np.asarray(pop.table.mask)
s = res.summary(m)
n_real = int(m.sum())
print(f"{n_real} DE residential agents x {len(res.years)} years "
      f"in {elapsed:.1f}s ({n_real * len(res.years) / elapsed:.0f} agent-years/sec)")
print(f"{'year':>6} {'adopters':>10} {'MW_cum':>8} {'batt_MWh':>9} {'med_payback':>11}")
for i, y in enumerate(res.years):
    print(f"{y:>6} {s['adopters'][i]:>10.0f} {s['system_kw_cum'][i] / 1e3:>8.1f} "
          f"{s['batt_kwh_cum'][i] / 1e3:>9.2f} "
          f"{np.median(res.agent['payback_period'][i][m > 0]):>11.1f}")

h = res.state_hourly_net_mw
de_peak = h[:, synth.STATE_IDX['DE'], :].max(axis=1)
print(f"DE hourly peak net load by year (MW): {np.round(de_peak, 1)}")
assert np.all(np.diff(s["system_kw_cum"]) >= -1e-3)
assert s["batt_kwh_cum"][-1] > 0
print("DELAWARE RUN OK")
