"""Example: an ITC-schedule policy sweep in one process.

Three federal-ITC variants run against ONE synthetic population — one
copy of the agent table and the [·, 8760] profile banks in device
memory, one compiled program per planner group — and the sweep reports
adoption/capacity/NPV deltas against the statutory baseline. The same
pattern sweeps any ScenarioInputs field (price escalators, storage
costs, NEM caps...).

    python examples/run_sweep.py
"""

import time

import jax
import numpy as np
import jax.numpy as jnp

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.sweep import SweepSimulation

print("devices:", jax.devices())

# sized to finish on a CPU dev box in a couple of minutes; on a TPU,
# scale --agents/--end-year up freely (the sweep adds only [Y, S]-sized
# arrays per scenario, so population, not S, is the scaling axis)
cfg = ScenarioConfig(name="itc-sweep", start_year=2014, end_year=2022,
                     anchor_years=())
pop = synth.generate_population(512, states=["CA", "TX", "DE"], seed=7)
years = list(cfg.model_years)
Y = len(years)

# the sweep axis: three ITC worlds — statute, early step-down, none
statute = scen.federal_itc_schedule(years)
stepdown = np.clip(statute - 0.10, 0.0, None)
variants = {
    "statute": statute,
    "stepdown": stepdown,
    "no-itc": np.zeros_like(statute),
}
members = [
    scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
        overrides={"itc_fraction": jnp.asarray(sched)},
    )
    for sched in variants.values()
]

t0 = time.time()
sweep = SweepSimulation(
    pop.table, pop.profiles, pop.tariffs, members, cfg,
    RunConfig(sizing_iters=8),
    labels=list(variants), baseline=0,
)
print("plan:", [(g.mode, g.n_scenarios) for g in sweep.plan.groups],
      f"| bank bytes shared once: {sweep.bank_bytes_shared:,}")
results = sweep.run()
print(f"{len(members)} scenarios x {Y} years in {time.time() - t0:.1f}s")

report = results.delta_report()
for s in report["scenarios"]:
    f = s["final"]
    tag = " (baseline)" if s["is_baseline"] else ""
    print(f"  {s['scenario']:>9}{tag}: adopters {f['adopters']:>10.1f}  "
          f"Δadopters {f['adopters_delta']:>+10.1f}  "
          f"ΔkW {f['system_kw_cum_delta']:>+12.1f}  "
          f"Δfleet-NPV {f['npv_total_delta']:>+14.0f}")
