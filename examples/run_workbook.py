"""Drive a run straight off the reference's scenario workbook
(.xlsm): the artifact the reference's operator edits
(excel/excel_functions.py load_scenario) becomes a runnable
configuration with no Postgres and no hand-exported CSVs.

io.workbook decodes the Main-sheet options (region, markets,
technology, end year, seed) plus all 14 run-mapped trajectory
selectors; the selections pick the matching input_data CSVs through
scenario_inputs_from_reference(prefer=...)."""
import dataclasses as dc
import time

import jax.numpy as jnp
import numpy as np

from dgen_tpu.config import RunConfig
from dgen_tpu.io import synth
from dgen_tpu.io import workbook as wbk
from dgen_tpu.io.reference_inputs import (
    scenario_inputs_from_reference,
    wholesale_profile_bank,
)
from dgen_tpu.models.agents import ProfileBank
from dgen_tpu.models.simulation import Simulation

XLSM = "/root/reference/dgen_os/excel/input_sheet_final.xlsm"
ROOT = "/root/reference/dgen_os/input_data"

cfg, info = wbk.scenario_from_workbook(XLSM)
print(f"workbook scenario: {cfg.name} | region -> {info['states']} | "
      f"markets -> {info['sector_weights']} | storage {cfg.storage_enabled} "
      f"| {cfg.start_year}-{cfg.end_year}")
print(f"trajectory selections: {info['prefer']}")

inputs, meta = scenario_inputs_from_reference(
    ROOT, cfg, list(synth.STATES), prefer=info["prefer"])
picked = {k: meta["files"][k].split("/")[-1]
          for k in ("pv_prices", "financing", "elec_prices")}
print(f"CSV files picked by the workbook's selections: {picked}")

pop = synth.generate_population(
    1024, states=info["states"], seed=info["seed"],
    sector_weights=info["sector_weights"], n_regions=len(meta["regions"]),
)
profiles = ProfileBank(
    load=pop.profiles.load, solar_cf=pop.profiles.solar_cf,
    wholesale=jnp.asarray(wholesale_profile_bank(meta, ROOT)),
)
sim = Simulation(pop.table, profiles, pop.tariffs, inputs, cfg,
                 RunConfig(sizing_iters=10))
t0 = time.time()
res = sim.run()
elapsed = time.time() - t0

m = np.asarray(pop.table.mask)
s = res.summary(m)
n_real = int(m.sum())
print(f"{n_real} agents x {len(res.years)} years in {elapsed:.1f}s "
      f"({n_real * len(res.years) / elapsed:,.0f} agent-years/sec)")
print(f"final: {s['adopters'][-1]:,.0f} adopters, "
      f"{s['system_kw_cum'][-1] / 1e3:,.1f} MW cum")
assert s["system_kw_cum"][-1] > 0
assert np.all(np.diff(s["system_kw_cum"]) >= -1e-3)
print("WORKBOOK RUN OK")
