"""Regression tests for the driver entry points.

Round-1 failure mode: ``dryrun_multichip`` built its mesh from
``jax.devices()`` and picked up the real TPU instead of a virtual CPU
mesh (MULTICHIP_r01.json, rc=1). These tests run the dry run in-process
on the conftest-forced 8-device CPU platform and also verify the
single-chip ``entry()`` contract.
"""

import jax
import numpy as np
import pytest


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_dryrun_uses_cpu_devices():
    import __graft_entry__ as ge

    devs = ge._force_virtual_cpu(8)
    assert len(devs) == 8
    assert all(d.platform == "cpu" for d in devs)


def test_entry_compiles_and_runs():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    carry, outs = out
    assert np.all(np.isfinite(np.asarray(outs.system_kw_cum)))
