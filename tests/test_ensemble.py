"""Ensemble engine tests (ISSUE 20): E=1 zero-width-draw byte parity
with ``Simulation.run``, restart-stable + mode-invariant draws, cohort
entry parity against always-alive oracles, device quantiles vs the
NumPy reference at small E, (member, year) checkpoint resume, and the
steady-state / cross-member retrace guarantees."""

import dataclasses as dc
import os
import shutil

import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.ensemble import (
    COHORT_NEVER,
    DEFAULT_DRAWS,
    CohortSchedule,
    DrawSpec,
    EnsembleSimulation,
    EnsembleStats,
    draw_members,
)
from dgen_tpu.ensemble.cohorts import (
    align_entry,
    alive_mask_np,
    cohort_alive_mask,
    electrified_load_growth,
    potential_mask,
)
from dgen_tpu.ensemble.stats import quantiles_np
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation
from dgen_tpu.sweep import MODE_LOOP, MODE_VMAP

CFG = ScenarioConfig(name="ens-t", start_year=2014, end_year=2016,
                     anchor_years=())
RC = RunConfig(sizing_iters=6)


@pytest.fixture(scope="module")
def pop():
    return synth.generate_population(
        96, states=["DE", "CA"], seed=11, pad_multiple=32
    )


def make_inputs(pop):
    return scen.uniform_inputs(
        CFG, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
    )


def make_ens(pop, inputs, **kw):
    return EnsembleSimulation(
        pop.table, pop.profiles, pop.tariffs, inputs, CFG,
        kw.pop("run_config", RC), **kw,
    )


# ---------------------------------------------------------------------------
# Draws: restart stability, zero-width identity, mean preservation
# ---------------------------------------------------------------------------

def test_zero_draws_return_base_object(pop):
    """The byte-parity hook: a zero-width spec yields the base inputs
    OBJECT, not a numerically-equal copy."""
    inputs = make_inputs(pop)
    members = draw_members(inputs, DrawSpec(), 3, seed=0)
    assert all(m is inputs for m in members)


def test_draws_are_restart_stable_and_order_free(pop):
    inputs = make_inputs(pop)
    a = draw_members(inputs, DEFAULT_DRAWS, 4, seed=123)
    b = draw_members(inputs, DEFAULT_DRAWS, 4, seed=123)
    for ma, mb in zip(a, b):
        for f in dc.fields(ma):
            np.testing.assert_array_equal(
                np.asarray(getattr(ma, f.name)),
                np.asarray(getattr(mb, f.name)),
                err_msg=f.name,
            )
    # member m's draws don't depend on how many siblings exist
    wide = draw_members(inputs, DEFAULT_DRAWS, 8, seed=123)
    np.testing.assert_array_equal(
        np.asarray(a[2].bass_p), np.asarray(wide[2].bass_p)
    )
    # different seeds actually move the draws
    c = draw_members(inputs, DEFAULT_DRAWS, 4, seed=124)
    assert not np.array_equal(
        np.asarray(a[1].bass_p), np.asarray(c[1].bass_p)
    )


def test_draws_perturb_only_drawn_axes(pop):
    inputs = make_inputs(pop)
    (m,) = draw_members(
        inputs, DrawSpec(bass_p_sd=0.2), 1, seed=5
    )
    assert not np.array_equal(
        np.asarray(m.bass_p), np.asarray(inputs.bass_p)
    )
    # undrawn axes are the base arrays; nem_cap_kw is NEVER drawn
    np.testing.assert_array_equal(
        np.asarray(m.bass_q), np.asarray(inputs.bass_q)
    )
    np.testing.assert_array_equal(
        np.asarray(m.nem_cap_kw), np.asarray(inputs.nem_cap_kw)
    )


# ---------------------------------------------------------------------------
# E=1 zero-draw byte parity with Simulation.run
# ---------------------------------------------------------------------------

def test_e1_zero_draw_matches_single_run_byte_exact(pop):
    inputs = make_inputs(pop)
    ref = Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, CFG, RC
    ).run(collect=True)
    ens = make_ens(pop, inputs, n_members=1, draws=DrawSpec())
    assert ens.mode == MODE_LOOP          # E=1 is pinned to the loop
    res = ens.run(collect=True)
    r1 = res[0]
    assert list(r1.years) == list(ref.years)
    for k in ref.agent:
        np.testing.assert_array_equal(
            np.asarray(ref.agent[k]), np.asarray(r1.agent[k]),
            err_msg=k,
        )
    # and the quantile block degenerates to the single trajectory
    band = res.quantiles.band("adopters")
    m = np.asarray(pop.table.mask)
    nat = (ref.agent["number_of_adopters"] * m[None, :]).sum(axis=1)
    np.testing.assert_allclose(band["p50"], nat, rtol=1e-6)
    np.testing.assert_array_equal(band["p10"], band["p90"])


# ---------------------------------------------------------------------------
# Loop-vs-vmap mode invariance
# ---------------------------------------------------------------------------

def test_loop_and_vmap_modes_agree(pop):
    inputs = make_inputs(pop)
    ens_v = make_ens(pop, inputs, n_members=2, seed=3,
                     draws=DEFAULT_DRAWS)
    assert ens_v.mode == MODE_VMAP
    res_v = ens_v.run(collect=True)
    # max_vmap_members=1 caps the planner width below E -> loop mode
    ens_l = make_ens(pop, inputs, n_members=2, seed=3,
                     draws=DEFAULT_DRAWS, max_vmap_members=1)
    assert ens_l.mode == MODE_LOOP
    res_l = ens_l.run(collect=True)
    for m in range(2):
        for k in res_v[m].agent:
            np.testing.assert_allclose(
                np.asarray(res_v[m].agent[k]),
                np.asarray(res_l[m].agent[k]),
                rtol=1e-5, atol=1e-5, err_msg=f"mem{m}:{k}",
            )
    for metric in ("adopters", "system_kw_cum"):
        np.testing.assert_allclose(
            res_v.quantiles.national[metric],
            res_l.quantiles.national[metric],
            rtol=1e-5, atol=1e-3,
        )


# ---------------------------------------------------------------------------
# Quantiles vs the NumPy reference at small E
# ---------------------------------------------------------------------------

def test_device_quantiles_match_numpy_reference(pop):
    inputs = make_inputs(pop)
    E = 4
    ens = make_ens(pop, inputs, n_members=E, seed=9, draws=DEFAULT_DRAWS)
    assert ens.mode == MODE_VMAP          # device-side quantile path
    res = ens.run(collect=True)
    mask = np.asarray(res.host_mask)
    # member curves recomputed from the collected agent outputs
    curves = np.stack([
        (res[m].agent["number_of_adopters"] * mask[None, :]).sum(axis=1)
        for m in range(E)
    ])                                     # [E, Y]
    ref = quantiles_np(curves, res.quantiles.quantiles)  # [Q, Y]
    np.testing.assert_allclose(
        res.quantiles.national["adopters"], ref.transpose(1, 0),
        rtol=1e-5, atol=1e-3,
    )
    # E members, 4 quantile-ordered columns per metric
    assert res.quantiles.n_members == E
    json_rt = EnsembleStats.from_json(res.quantiles.to_json())
    np.testing.assert_allclose(
        json_rt.national["adopters"],
        res.quantiles.national["adopters"], rtol=1e-6,
    )
    frame = res.quantiles.frame()
    assert len(frame) == len(res.quantiles.years) * 3
    assert "adopters" in frame.columns


# ---------------------------------------------------------------------------
# Cohorts: mask oracle, placement alignment, entry parity
# ---------------------------------------------------------------------------

def test_cohort_mask_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    mask = (rng.random(64) > 0.2).astype(np.float32)
    entry = np.where(
        rng.random(64) < 0.3,
        rng.integers(2015, 2020, 64),
        0.0,
    ).astype(np.float32)
    mask[-4:] = 0.0                       # padding rows
    entry[-4:] = COHORT_NEVER
    for year in (2014.0, 2016.0, 2019.0, 2030.0):
        got = np.asarray(cohort_alive_mask(
            jnp.asarray(mask), jnp.asarray(entry),
            jnp.asarray(year, jnp.float32),
        ))
        np.testing.assert_array_equal(
            got, alive_mask_np(mask, entry, year)
        )
    # potential = base-alive OR will-ever-enter
    pot = potential_mask(mask, entry)
    will = ((entry > 0.0) & (entry < COHORT_NEVER)).astype(np.float32)
    np.testing.assert_array_equal(pot, np.maximum(mask, will))
    # after the last entry year the alive mask IS the potential mask
    # (modulo never-alive rows)
    np.testing.assert_array_equal(
        alive_mask_np(pot, entry, 2025.0), pot * (mask + will > 0)
    )


def test_align_entry_routes_through_row_origin():
    entry = np.asarray([0.0, 2016.0, 0.0, 2018.0], np.float32)
    origin = np.asarray([3, -1, 0, 2, 1], np.int64)
    out = align_entry(entry, origin)
    np.testing.assert_array_equal(
        out,
        np.asarray([2018.0, COHORT_NEVER, 0.0, 0.0, 2016.0], np.float32),
    )


def test_cohort_schedule_validates_and_counts():
    e = np.zeros(8, np.float32)
    e[2] = 2016.0
    e[5] = 2016.0
    e[6] = COHORT_NEVER
    cs = CohortSchedule(e)
    assert cs.n_cohort_rows == 2
    assert cs.counts_by_year() == {2016: 2}
    with pytest.raises(ValueError, match="1-D"):
        CohortSchedule(np.zeros((2, 2), np.float32))


def test_cohort_entry_at_start_year_matches_always_alive(pop):
    """Rows scheduled to enter AT the first model year are alive for
    the whole horizon — the run must match a plain always-alive run."""
    inputs = make_inputs(pop)
    ref = Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, CFG, RC
    ).run(collect=True)
    entry = np.zeros(pop.table.n_agents, np.float32)
    alive = np.flatnonzero(np.asarray(pop.table.mask) > 0)
    entry[alive[-16:]] = float(CFG.start_year)
    ens = make_ens(pop, inputs, n_members=1, draws=DrawSpec(),
                   entry_year=entry)
    res = ens.run(collect=True)
    for k in ref.agent:
        np.testing.assert_allclose(
            np.asarray(ref.agent[k]), np.asarray(res[0].agent[k]),
            rtol=1e-6, atol=1e-6, err_msg=k,
        )


def test_cohort_entry_freezes_rows_until_entry_year(pop):
    """Staggered entry: pre-entry rows contribute nothing to the
    national curve, and flip in exactly at their entry year."""
    inputs = make_inputs(pop)
    entry = np.zeros(pop.table.n_agents, np.float32)
    alive = np.flatnonzero(np.asarray(pop.table.mask) > 0)
    cohort = alive[-12:]
    entry[cohort] = 2016.0                # enters at the LAST year
    ens = make_ens(pop, inputs, n_members=2, seed=1,
                   draws=DEFAULT_DRAWS, entry_year=entry)
    res = ens.run(collect=True)
    # recover the cohort's placed positions through host_agent_id
    placed_cohort = np.isin(
        np.asarray(res.host_agent_id), np.asarray(cohort)
    )
    mask_pot = np.asarray(res.host_mask)
    for m in range(2):
        adopters = np.asarray(res[m].agent["number_of_adopters"])
        # year 2014: cohort rows masked out -> exact zeros in the sums
        pre = (adopters[0] * mask_pot * placed_cohort).sum()
        assert pre == 0.0 or np.allclose(pre, 0.0, atol=1e-6)
    # the quantile block was computed against the per-year alive mask:
    # year-0 p50 must equal the alive-only recomputation
    year0_alive = mask_pot * (~placed_cohort)
    curves = np.stack([
        (np.asarray(res[m].agent["number_of_adopters"][0])
         * year0_alive).sum()
        for m in range(2)
    ])
    got = res.quantiles.national["adopters"][0, 1]     # p50, year 0
    np.testing.assert_allclose(
        got, np.quantile(curves, 0.5), rtol=1e-5, atol=1e-3
    )


def test_entry_year_length_mismatch_raises(pop):
    inputs = make_inputs(pop)
    with pytest.raises(ValueError, match="entry_year covers"):
        make_ens(pop, inputs, n_members=1,
                 entry_year=np.zeros(3, np.float32))


def test_electrified_load_growth_compounds_from_start():
    lg = np.ones((3, 2, 3), np.float32)
    out = np.asarray(electrified_load_growth(
        lg, [2020, 2022, 2024], 0.10, sectors=(0,)
    ))
    np.testing.assert_allclose(out[:, :, 0], [[1.0] * 2, [1.1 ** 2] * 2,
                                              [1.1 ** 4] * 2], rtol=1e-6)
    np.testing.assert_array_equal(out[:, :, 1:], lg[:, :, 1:])


# ---------------------------------------------------------------------------
# Checkpoint / resume at (member, year)
# ---------------------------------------------------------------------------

def test_ensemble_resumes_at_member_year_loop(pop, tmp_path):
    from dgen_tpu.io import checkpoint as ckpt

    inputs = make_inputs(pop)
    d = str(tmp_path / "ens-ckpt")
    ens = make_ens(pop, inputs, n_members=2, seed=7,
                   draws=DEFAULT_DRAWS, max_vmap_members=1)
    assert ens.mode == MODE_LOOP
    full = ens.run(collect=True, checkpoint_dir=d)
    # drop member 1's LAST year checkpoint: resume must recompute only
    # (member 1, 2016) and nothing else
    m1 = ckpt.member_dir(d, 1)
    assert ckpt.latest_year(m1) == 2016
    for sub in os.listdir(m1):
        if "2016" in sub:
            shutil.rmtree(os.path.join(m1, sub))
    assert ckpt.latest_year(m1) == 2014

    ens2 = make_ens(pop, inputs, n_members=2, seed=7,
                    draws=DEFAULT_DRAWS, max_vmap_members=1)
    res = ens2.run(collect=True, checkpoint_dir=d, resume=True)
    assert res.runs[0].years == []          # member 0 fully resumed
    assert res.runs[1].years == [2016]      # member 1: one new year
    np.testing.assert_allclose(
        np.asarray(res.runs[1].agent["number_of_adopters"][0]),
        np.asarray(full.runs[1].agent["number_of_adopters"][-1]),
        rtol=1e-6,
    )
    # the stats sidecar restores the full horizon despite the partial
    # re-run — quantiles identical to the uninterrupted run
    np.testing.assert_allclose(
        res.quantiles.national["adopters"],
        full.quantiles.national["adopters"], rtol=1e-6,
    )


def test_ensemble_resumes_vmap_stacked(pop, tmp_path):
    inputs = make_inputs(pop)
    d = str(tmp_path / "ens-ckpt-vmap")
    ens = make_ens(pop, inputs, n_members=2, seed=7, draws=DEFAULT_DRAWS)
    assert ens.mode == MODE_VMAP
    full = ens.run(checkpoint_dir=d)
    ens2 = make_ens(pop, inputs, n_members=2, seed=7,
                    draws=DEFAULT_DRAWS)
    res = ens2.run(checkpoint_dir=d, resume=True)
    assert all(r.years == [] for r in res.runs)  # nothing recomputed
    np.testing.assert_allclose(
        res.quantiles.national["adopters"],
        full.quantiles.national["adopters"], rtol=1e-6,
    )


def test_stale_stats_sidecar_is_ignored(pop, tmp_path):
    """A sidecar from a different (mode, E, quantiles) configuration
    must not poison a resumed run."""
    inputs = make_inputs(pop)
    d = str(tmp_path / "ens-stale")
    os.makedirs(d)
    from dgen_tpu.ensemble.driver import STATS_FILE
    import json

    with open(os.path.join(d, STATS_FILE), "w") as f:
        json.dump({"mode": "loop", "n_members": 99,
                   "quantiles": [0.5]}, f)
    ens = make_ens(pop, inputs, n_members=2, seed=7, draws=DEFAULT_DRAWS)
    res = ens.run(checkpoint_dir=d, resume=True)
    assert not np.isnan(
        res.quantiles.national["adopters"]
    ).any()


# ---------------------------------------------------------------------------
# Retrace guarantees
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ensemble_steady_state_compiles_nothing(pop):
    """RetraceGuard armed: vmap mode must not compile past year 2, and
    loop mode must compile nothing after member 0 (cross-member
    guard). The guards raise inside run() on violation."""
    cfg = ScenarioConfig(name="ens-g", start_year=2014, end_year=2020,
                         anchor_years=())
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
    )
    rc = RunConfig(sizing_iters=6, guard_retrace=True)
    entry = np.zeros(pop.table.n_agents, np.float32)
    alive = np.flatnonzero(np.asarray(pop.table.mask) > 0)
    entry[alive[-8:]] = 2018.0            # mid-horizon cohort entry
    EnsembleSimulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
        n_members=3, seed=2, draws=DEFAULT_DRAWS, entry_year=entry,
    ).run()
    EnsembleSimulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg, rc,
        n_members=2, seed=2, draws=DEFAULT_DRAWS,
        max_vmap_members=1,
    ).run()


# ---------------------------------------------------------------------------
# Planner integration
# ---------------------------------------------------------------------------

def test_plan_budgets_member_axis(pop):
    """plan_sweep's n_members term: a member count that blows the HBM
    model falls back to loop mode instead of a doomed vmap."""
    from dgen_tpu.sweep import plan_sweep

    inputs = make_inputs(pop)
    years = list(CFG.model_years)
    small = plan_sweep(
        [inputs], years, table=pop.table, tariffs=pop.tariffs,
        econ_years=25, sizing_iters=6,
        hbm_bytes=32 * 1024**3, n_members=2,
    )
    assert small.groups[0].mode == MODE_VMAP
    big = plan_sweep(
        [inputs], years, table=pop.table, tariffs=pop.tariffs,
        econ_years=25, sizing_iters=6,
        hbm_bytes=64 * 1024**2, n_members=512,
    )
    assert big.groups[0].mode == MODE_LOOP


def test_env_knobs_set_members_and_seed(pop, monkeypatch):
    from dgen_tpu.ensemble.driver import ENV_MEMBERS, ENV_SEED

    inputs = make_inputs(pop)
    monkeypatch.setenv(ENV_MEMBERS, "3")
    monkeypatch.setenv(ENV_SEED, "42")
    ens = make_ens(pop, inputs)
    assert ens.n_members == 3
    assert ens.seed == 42
