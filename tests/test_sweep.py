"""Sweep engine tests: S-way parity against single runs, planner
grouping/HBM budgeting, static-mismatch errors, cross-scenario retrace
guarantees, per-(scenario, year) checkpoint resume, bank-sharing
accounting, and per-scenario timing contexts."""

import dataclasses as dc
import os

import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation
from dgen_tpu.sweep import (
    MODE_LOOP,
    MODE_VMAP,
    SweepSimulation,
    plan_sweep,
)

#: golden-e2e tolerance (tests/test_golden_e2e.py RTOL) — the sweep
#: acceptance bound; the identical-scenario paths actually reproduce
#: the single run exactly and are pinned tighter below
GOLDEN_RTOL = 1e-3

CFG = ScenarioConfig(name="sweep-t", start_year=2014, end_year=2016,
                     anchor_years=())
RC = RunConfig(sizing_iters=6)


@pytest.fixture(scope="module")
def pop():
    return synth.generate_population(
        96, states=["DE", "CA"], seed=11, pad_multiple=32
    )


def make_inputs(pop, itc=0.30, **overrides):
    Y = len(CFG.model_years)
    ov = {"itc_fraction": jnp.full((Y, 3), itc, jnp.float32)}
    ov.update(overrides)
    return scen.uniform_inputs(
        CFG, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
        overrides=ov,
    )


@pytest.fixture(scope="module")
def single_run(pop):
    inputs = make_inputs(pop)
    sim = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, CFG, RC)
    return inputs, sim.run()


# ---------------------------------------------------------------------------
# ScenarioStack
# ---------------------------------------------------------------------------

def test_stack_validation_names_offending_field(pop):
    from dgen_tpu.models.scenario import (
        ScenarioStackError,
        stack_scenarios,
        validate_scenario_statics,
    )

    a = make_inputs(pop)
    # a different static grid (extra state column in the NEM caps)
    bad_shape = dc.replace(
        a, nem_cap_kw=jnp.concatenate(
            [a.nem_cap_kw, a.nem_cap_kw[:, :1]], axis=1)
    )
    with pytest.raises(ScenarioStackError, match="nem_cap_kw"):
        stack_scenarios([a, bad_shape])
    # a dtype drift is a static mismatch too
    bad_dtype = dc.replace(
        a, itc_fraction=a.itc_fraction.astype(jnp.bfloat16)
    )
    with pytest.raises(ScenarioStackError, match="itc_fraction"):
        validate_scenario_statics([a, bad_dtype])
    with pytest.raises(ScenarioStackError):
        stack_scenarios([])

    stack = stack_scenarios([a, make_inputs(pop, itc=0.0)])
    assert stack.n_scenarios == 2
    assert stack.n_years == len(CFG.model_years)
    # round trip: member 1 comes back leaf-for-leaf
    b1 = stack.scenario(1)
    np.testing.assert_array_equal(
        np.asarray(b1.itc_fraction), 0.0
    )


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

def test_planner_groups_budget_and_errors(pop):
    from dgen_tpu.models.scenario import ScenarioStackError

    years = list(CFG.model_years)
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.1, 0.0)]
    kw = dict(table=pop.table, tariffs=pop.tariffs, econ_years=25,
              sizing_iters=6)

    # ample budget: one vmap group, whole table
    plan = plan_sweep(members, years, hbm_bytes=256 * 1024**3, **kw)
    assert len(plan.groups) == 1
    assert plan.groups[0].mode == MODE_VMAP
    assert plan.groups[0].indices == (0, 1, 2)
    assert plan.agent_chunk == 0

    # starved budget: the vmapped working set cannot fit -> loop mode
    # (enforce_budget=False: this synthetic 8 MiB budget is below even
    # the 128-row chunk floor, which the strict default now REJECTS
    # with a SweepBudgetError — tested separately below)
    plan_small = plan_sweep(members, years, hbm_bytes=8 * 1024**2,
                            enforce_budget=False, **kw)
    assert plan_small.groups[0].mode == MODE_LOOP

    # mid budget: vmap survives but chunked (S x chunk rows bounded).
    # Needs a table larger than the 128-row chunk floor; planning is
    # host-side only, so a bigger population costs nothing here.
    pop_big = synth.generate_population(
        512, states=["DE", "CA"], seed=11, pad_multiple=32)
    members_big = [
        scen.uniform_inputs(
            CFG, n_groups=pop_big.table.n_groups,
            n_regions=pop_big.n_regions)
        for _ in range(3)
    ]
    n = pop_big.table.n_agents
    # budget sized so rows_fit // 3 lands in [128, n): chunked vmap
    mid = plan.per_agent_bytes * 3 * 256
    plan_mid = plan_sweep(
        members_big, years, hbm_bytes=int(mid / 0.8 * 1.05),
        table=pop_big.table, tariffs=pop_big.tariffs,
        econ_years=25, sizing_iters=6)
    assert plan_mid.groups[0].mode == MODE_VMAP
    assert plan_mid.agent_chunk and plan_mid.agent_chunk % 128 == 0
    assert plan_mid.agent_chunk < n

    # unknown budget: width cap decides
    assert plan_sweep(members, years, hbm_bytes=None,
                      max_vmap_scenarios=2, **kw).groups[0].mode == MODE_LOOP
    assert plan_sweep(members, years, hbm_bytes=None,
                      **kw).groups[0].mode == MODE_VMAP

    # multi-device mesh: scenario groups ride the existing shard_map
    # layout unchanged -> loop
    from dgen_tpu.parallel.mesh import make_mesh

    plan_mesh = plan_sweep(members, years, mesh=make_mesh(),
                           hbm_bytes=256 * 1024**3, **kw)
    assert plan_mesh.groups[0].mode == MODE_LOOP
    assert plan_mesh.agent_chunk == 0   # ample budget: whole table

    # a starved mesh budget must still derive a streaming chunk (the
    # loop reuses the single-scenario executable, chunk included) —
    # not pin agent_chunk=0 and OOM where a lone Simulation would not
    from dgen_tpu.models.simulation import auto_agent_chunk

    mesh = make_mesh()
    small = 8 * 1024**2
    plan_mesh_small = plan_sweep(members, years, mesh=mesh,
                                 hbm_bytes=small, enforce_budget=False,
                                 **kw)
    n_local = max(pop.table.n_agents // int(mesh.devices.size), 1)
    expect = auto_agent_chunk(
        n_local, sizing_iters=6, econ_years=25, with_hourly=False,
        hbm_bytes=small)
    assert plan_mesh_small.agent_chunk == expect

    # scenarios whose compile-time net-billing flag differs split into
    # their own group (needs an all-NEM tariff population: the synth
    # default mix references net-billing tariffs, forcing True for all)
    rng = np.random.default_rng(0)
    nem_ids = np.asarray([0, 2, 5], np.int32)
    tidx = jnp.asarray(nem_ids[rng.integers(0, 3, pop.table.n_agents)])
    t_nem = dc.replace(pop.table, tariff_idx=tidx, tariff_switch_idx=tidx)
    years_n = len(years)
    caps = np.full((years_n, pop.table.n_states), 1e30, np.float32)
    caps[1:] = 1e3
    closing = make_inputs(pop, nem_cap_kw=jnp.asarray(caps))
    plan2 = plan_sweep(
        members + [closing], years, table=t_nem, tariffs=pop.tariffs,
        econ_years=25, sizing_iters=6, hbm_bytes=256 * 1024**3)
    assert len(plan2.groups) == 2
    assert {g.net_billing for g in plan2.groups} == {True, False}
    by_flag = {g.net_billing: g.indices for g in plan2.groups}
    assert by_flag[False] == (0, 1, 2)   # open caps: all-NEM skip
    assert by_flag[True] == (3,)         # the closing-cap scenario

    # static mismatch is rejected with the field named
    bad = dc.replace(
        members[0], nem_cap_kw=jnp.concatenate(
            [members[0].nem_cap_kw, members[0].nem_cap_kw[:, :1]], axis=1)
    )
    with pytest.raises(ScenarioStackError, match="nem_cap_kw"):
        plan_sweep(members + [bad], years, hbm_bytes=None, **kw)


# ---------------------------------------------------------------------------
# Parity: sweep vs single runs (the acceptance criteria)
# ---------------------------------------------------------------------------

def test_identical_scenario_sweep_matches_single_run(pop, single_run):
    """S-way sweep of IDENTICAL scenarios == one Simulation.run(),
    within the golden-e2e tolerance (observed: exact), with the banks
    shared rather than re-uploaded per scenario."""
    inputs, res_single = single_run
    sweep = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, [inputs] * 3, CFG, RC,
    )
    assert sweep.plan.groups[0].mode == MODE_VMAP
    res = sweep.run()

    # bank accounting: every per-scenario runner holds the SAME placed
    # bank arrays (one upload for the whole sweep), and the stamped
    # byte count is the banks' real footprint
    for sim in sweep.sims:
        assert sim.profiles is sweep.base.profiles
        assert sim.table is sweep.base.table
        assert sim.tariffs is sweep.base.tariffs
    expected = sum(
        np.asarray(x).nbytes
        for x in (pop.profiles.load, pop.profiles.solar_cf,
                  pop.profiles.wholesale)
    )
    assert res.bank_bytes_shared == expected

    m = np.asarray(pop.table.mask)
    for s in range(3):
        for k in ("system_kw_cum", "number_of_adopters", "npv",
                  "batt_kwh_cum", "payback_period"):
            a = res_single.agent[k] * m
            b = res.runs[s].agent[k] * m
            np.testing.assert_allclose(
                b, a, rtol=GOLDEN_RTOL, atol=1e-4,
                err_msg=f"scenario {s} field {k}",
            )
            # the vmapped program shares every upstream value with the
            # single-scenario program; drift beyond f32 noise means the
            # scenario axis leaked into the economics
            scale = max(float(np.max(np.abs(a))), 1.0)
            assert float(np.max(np.abs(a - b))) / scale < 1e-5, k

    # deltas vs baseline are all ~zero for identical scenarios
    rep = res.delta_report()
    for s_rep in rep["scenarios"]:
        assert abs(s_rep["final"]["system_kw_cum_delta"]) < 1e-3


def test_differing_itc_sweep_matches_independent_runs(pop):
    """A sweep of differing ITC schedules == a Python loop of
    independent Simulation runs, in both execution modes."""
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.0)]
    expected = []
    for inputs in members:
        sim = Simulation(
            pop.table, pop.profiles, pop.tariffs, inputs, CFG, RC)
        expected.append(sim.run())

    for max_vmap in (8, 1):   # vmap mode, then the scenario-major loop
        sweep = SweepSimulation(
            pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
            max_vmap_scenarios=max_vmap,
        )
        want = MODE_VMAP if max_vmap == 8 else MODE_LOOP
        assert sweep.plan.groups[0].mode == want
        res = sweep.run()
        m = np.asarray(pop.table.mask)
        for s in range(2):
            for k in ("system_kw_cum", "number_of_adopters", "npv"):
                a = expected[s].agent[k] * m
                b = res.runs[s].agent[k] * m
                scale = max(float(np.max(np.abs(a))), 1.0)
                assert float(np.max(np.abs(a - b))) / scale < 1e-5, \
                    f"{want} scenario {s} field {k}"
        # the ITC axis actually moved the answer
        s0 = res.runs[0].summary(m)["system_kw_cum"][-1]
        s1 = res.runs[1].summary(m)["system_kw_cum"][-1]
        assert s1 < s0


def test_sweep_steady_state_compiles_once_per_group(pop):
    """RetraceGuard-backed acceptance: with guard_retrace armed, the
    vmapped program may compile only in the first two executed years
    (the first_year True/False pair) and the loop mode may compile
    NOTHING after scenario 0 — a retrace anywhere raises RetraceError
    and fails this test."""
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.1, 0.0)]
    rc = dc.replace(RC, guard_retrace=True)
    for max_vmap in (8, 1):
        sweep = SweepSimulation(
            pop.table, pop.profiles, pop.tariffs, members, CFG, rc,
            max_vmap_scenarios=max_vmap,
        )
        res = sweep.run()
        assert len(res.runs) == 3

    # and explicitly: scenarios after the first share the executable
    from dgen_tpu.lint.guard import RetraceGuard

    sweep = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
        max_vmap_scenarios=1,
    )
    sweep.sims[0].run()   # compiles the program pair
    with RetraceGuard(context="cross-scenario"):
        sweep.sims[1].run()
        sweep.sims[2].run()


def test_vmap_sweep_composes_with_agent_chunk(pop):
    """The vmapped program streams the agent axis through the sizing
    scan exactly like the single-scenario path: a chunked S-way sweep
    matches unchunked independent runs (HBM stays bounded by one
    chunk's [S, C, 8760] working set)."""
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.0)]
    rc_chunk = dc.replace(RC, agent_chunk=64)
    sweep = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, rc_chunk,
    )
    assert sweep.base._agent_chunk == 64
    assert sweep.plan.groups[0].mode == MODE_VMAP
    res = sweep.run()
    m = np.asarray(pop.table.mask)
    n = len(m)
    for s, inputs in enumerate(members):
        ref = Simulation(
            pop.table, pop.profiles, pop.tariffs, inputs, CFG, RC
        ).run()
        for k in ("system_kw_cum", "npv"):
            a = ref.agent[k] * m
            b = res.runs[s].agent[k][:, :n] * m
            scale = max(float(np.max(np.abs(a))), 1.0)
            assert float(np.max(np.abs(a - b))) / scale < 2e-5, (s, k)


# ---------------------------------------------------------------------------
# Checkpoint / resume at (scenario, year)
# ---------------------------------------------------------------------------

def test_sweep_resumes_at_scenario_year(pop, tmp_path):
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.0)]
    d = str(tmp_path / "ckpt")

    # loop mode: pre-complete scenario 0 only (a sweep killed between
    # scenarios); the resumed sweep must skip scenario 0's years and
    # still produce full results for scenario 1
    from dgen_tpu.io import checkpoint as ckpt

    sweep = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
        max_vmap_scenarios=1,
    )
    sweep.sims[0].run(
        checkpoint_dir=ckpt.scenario_dir(d, sweep.labels[0]))
    assert sorted(os.listdir(d)) == [f"scn={sweep.labels[0]}"]
    res = sweep.run(checkpoint_dir=d, resume=True)
    assert res.runs[0].years == []            # fully resumed
    assert len(res.runs[1].years) == len(CFG.model_years)
    m = np.asarray(pop.table.mask)
    assert res.runs[1].summary(m)["system_kw_cum"][-1] > 0

    # vmap mode: the group checkpoints one stacked carry per year and
    # resumes in lockstep
    d2 = str(tmp_path / "ckpt-vmap")
    sweep_v = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
    )
    assert sweep_v.plan.groups[0].mode == MODE_VMAP
    sweep_v.run(checkpoint_dir=d2)
    assert sorted(os.listdir(d2)) == ["scn=group0"]
    res_v = sweep_v.run(checkpoint_dir=d2, resume=True)
    assert res_v.runs[0].years == [] and res_v.runs[1].years == []


def test_checkpoint_scenario_layout_isolated(pop, tmp_path):
    """Per-scenario checkpoint trees don't collide: the same years
    saved under two scenario keys restore independently."""
    from dgen_tpu.io import checkpoint as ckpt
    from dgen_tpu.models.simulation import SimCarry

    d = str(tmp_path)
    c = SimCarry.zeros(8)
    a = dc.replace(c, batt_adopters_cum=c.batt_adopters_cum + 1.0)
    b = dc.replace(c, batt_adopters_cum=c.batt_adopters_cum + 2.0)
    ckpt.save_year(d, 2014, a, scenario="s0")
    ckpt.save_year(d, 2014, b, scenario="s1")
    assert ckpt.latest_year(d, scenario="s0") == 2014
    assert ckpt.latest_year(d) is None        # flat layout untouched
    _, ra = ckpt.restore_year(d, 8, scenario="s0")
    _, rb = ckpt.restore_year(d, 8, scenario="s1")
    assert float(ra.batt_adopters_cum[0]) == 1.0
    assert float(rb.batt_adopters_cum[0]) == 2.0


# ---------------------------------------------------------------------------
# Timing contexts + exports
# ---------------------------------------------------------------------------

def test_timing_ctx_separates_scenario_phases(pop):
    from dgen_tpu.utils import timing

    timing.reset_timings()
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.0)]
    sweep = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
        max_vmap_scenarios=1, labels=["hi", "lo"],
    )
    sweep.run()
    rep = timing.timing_report()
    assert rep["hi:year_step"]["count"] == len(CFG.model_years)
    assert rep["lo:year_step"]["count"] == len(CFG.model_years)
    # ctx filter strips the prefix
    assert timing.timing_report(ctx="hi")["year_step"]["count"] == \
        len(CFG.model_years)
    # unlabeled timers still work
    with timing.timer("bare"):
        pass
    assert "bare" in timing.timing_report()


def test_sweep_export_stamps_scenario_ids(pop, tmp_path):
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.0)]
    sweep = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
        labels=["itc30", "itc0"], baseline=0,
    )
    res = sweep.run()
    out = str(tmp_path / "sweep-out")
    res.export(out)

    import json

    for i, label in enumerate(["itc30", "itc0"]):
        scn_dir = os.path.join(out, f"scenario={label}")
        with open(os.path.join(scn_dir, "meta.json")) as f:
            meta = json.load(f)
        assert meta["scenario"] == label
        assert meta["scenario_index"] == i
        assert meta["sweep_baseline"] == "itc30"
        assert os.path.isdir(os.path.join(scn_dir, "agent_outputs"))
    with open(os.path.join(out, "sweep.json")) as f:
        rep = json.load(f)
    assert rep["baseline"] == "itc30"
    assert rep["bank_bytes_shared"] == res.bank_bytes_shared
    deltas = {s["scenario"]: s["final"] for s in rep["scenarios"]}
    assert deltas["itc30"]["system_kw_cum_delta"] == 0.0
    assert deltas["itc0"]["system_kw_cum_delta"] < 0.0

    # the exported surface round-trips through the standard loader
    from dgen_tpu.io.export import load_surface

    df = load_surface(os.path.join(out, "scenario=itc0"), "agent_outputs")
    assert set(df["year"]) == set(CFG.model_years)


# ---------------------------------------------------------------------------
# Mesh (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sweep_on_mesh_matches_unmeshed(pop):
    """Scenario groups ride the existing shard_map layout unchanged:
    a sweep over the 8-device CPU mesh (scenario-major loop by plan)
    reproduces the unmeshed sweep per agent_id."""
    from dgen_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    members = [make_inputs(pop, itc=v) for v in (0.3, 0.0)]
    sweep_m = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
        mesh=mesh,
    )
    assert all(g.mode == MODE_LOOP for g in sweep_m.plan.groups)
    sweep_u = SweepSimulation(
        pop.table, pop.profiles, pop.tariffs, members, CFG, RC,
    )
    res_m = sweep_m.run()
    res_u = sweep_u.run()

    def by_id(sweep, res, s):
        keep = np.asarray(sweep.base.table.mask) > 0
        ids = np.asarray(sweep.base.table.agent_id)[keep]
        order = np.argsort(ids)
        return res.runs[s].agent["system_kw_cum"][:, keep][:, order]

    for s in range(2):
        np.testing.assert_allclose(
            by_id(sweep_m, res_m, s), by_id(sweep_u, res_u, s),
            rtol=5e-4, atol=1e-3,
        )
