"""Market step: Bass diffusion, mms lookup, anchoring, integer
battery-adopter allocation."""

import numpy as np
import pytest

import jax.numpy as jnp

from dgen_tpu.config import PAYBACK_GRID_N
from dgen_tpu.models import market


def test_bass_inversion_roundtrip():
    """equivalent_time inverts bass_new_adopt_fraction."""
    p = jnp.float32(0.005)
    q = jnp.float32(0.4)
    for t in (1.0, 5.0, 12.0):
        frac = market.bass_new_adopt_fraction(p, q, jnp.float32(t))
        mms = jnp.float32(0.6)
        share = mms * frac
        teq = market.equivalent_time(share, mms, p, q)
        assert float(teq) == pytest.approx(t, rel=5e-4)  # float32


def test_diffusion_monotone_and_capped():
    n = 64
    rng = np.random.default_rng(0)
    state = market.MarketState.zeros(n)
    state = market.MarketState(
        market_share=jnp.asarray(rng.uniform(0, 0.05, n).astype(np.float32)),
        max_market_share=jnp.zeros(n, jnp.float32),
        adopters_cum=jnp.asarray(rng.uniform(0, 10, n).astype(np.float32)),
        market_value=jnp.zeros(n, jnp.float32),
        system_kw_cum=jnp.zeros(n, jnp.float32),
        batt_kw_cum=jnp.zeros(n, jnp.float32),
        batt_kwh_cum=jnp.zeros(n, jnp.float32),
        initial_adopters=jnp.zeros(n, jnp.float32),
        initial_market_share=jnp.zeros(n, jnp.float32),
    )
    mms = jnp.asarray(rng.uniform(0.1, 0.8, n).astype(np.float32))
    out = market.diffusion_step(
        state, mms,
        system_kw=jnp.full(n, 5.0), system_capex_per_kw=jnp.full(n, 3000.0),
        developable_agent_weight=jnp.full(n, 100.0),
        bass_p=jnp.full(n, 0.005), bass_q=jnp.full(n, 0.4),
        teq_yr1=jnp.full(n, 2.0), is_first_year=False,
    )
    ms = np.asarray(out.market_share)
    msly = np.asarray(state.market_share)
    assert np.all(ms >= msly - 1e-7)          # market-share floor
    assert np.all(np.asarray(out.new_adopters) >= 0)
    # market share approaches but respects the shape of mms-scaled Bass
    assert np.all(ms <= np.maximum(np.asarray(mms), msly) + 1e-6)


def test_diffusion_converges_to_mms():
    """Iterating the yearly step drives share toward max market share."""
    n = 4
    state = market.MarketState.zeros(n)
    mms = jnp.full(n, 0.5)
    kw = jnp.full(n, 5.0)
    capex = jnp.full(n, 3000.0)
    w = jnp.full(n, 100.0)
    p, q, teq1 = jnp.full(n, 0.005), jnp.full(n, 0.5), jnp.full(n, 2.0)
    for i in range(40):
        out = market.diffusion_step(state, mms, kw, capex, w, p, q, teq1, i == 0)
        state = market.MarketState(
            market_share=out.market_share,
            max_market_share=mms,
            adopters_cum=out.number_of_adopters,
            market_value=out.market_value,
            system_kw_cum=out.system_kw_cum,
            batt_kw_cum=state.batt_kw_cum,
            batt_kwh_cum=state.batt_kwh_cum,
            initial_adopters=state.initial_adopters,
            initial_market_share=state.initial_market_share,
        )
    assert np.all(np.asarray(state.market_share) > 0.45)
    assert np.all(np.asarray(state.market_share) <= 0.5 + 1e-5)


def test_mms_lookup():
    table = np.zeros((3, PAYBACK_GRID_N), dtype=np.float32)
    table[0] = np.linspace(1.0, 0.0, PAYBACK_GRID_N)
    got = market.max_market_share(
        jnp.asarray([0.0, 30.1, 5.0]), jnp.asarray([0, 0, 0]), jnp.asarray(table)
    )
    assert float(got[0]) == pytest.approx(1.0)
    assert float(got[1]) == pytest.approx(0.0)
    assert 0.0 < float(got[2]) < 1.0


def test_largest_remainders_matches_oracle():
    from tests.oracles import oracle_largest_remainders

    rng = np.random.default_rng(42)
    n, n_groups = 200, 12
    new_adopters = rng.uniform(0, 8, n).astype(np.float32)
    group_idx = rng.integers(0, n_groups, n)
    rates = rng.uniform(0, 0.6, n_groups).astype(np.float32)
    ids = np.arange(n)

    got = np.asarray(
        market.allocate_battery_adopters(
            jnp.asarray(new_adopters), jnp.asarray(group_idx),
            jnp.asarray(rates), jnp.asarray(ids), n_groups,
        )
    )
    want = oracle_largest_remainders(new_adopters, group_idx, rates, ids)
    np.testing.assert_array_equal(got, want)
    # group totals hit the rounded targets exactly
    for g in range(n_groups):
        sel = group_idx == g
        target = int(round(rates[g] * new_adopters[sel].sum()))
        assert int(got[sel].sum()) == target


def test_anchoring_rescales_to_observed():
    n, n_groups = 30, 6
    rng = np.random.default_rng(1)
    kw_cum = rng.uniform(10, 100, n).astype(np.float32)
    group_idx = rng.integers(0, n_groups, n)
    observed = rng.uniform(1000, 5000, n_groups).astype(np.float32)
    is_res = np.ones(n, dtype=np.float32)
    weight = rng.uniform(50, 200, n).astype(np.float32)

    anchored, adopters, share = market.anchor_to_observed(
        jnp.asarray(kw_cum), jnp.asarray(group_idx), jnp.asarray(observed),
        jnp.asarray(is_res), jnp.asarray(weight), n_groups,
    )
    anchored = np.asarray(anchored)
    for g in range(n_groups):
        sel = group_idx == g
        if sel.any():
            assert anchored[sel].sum() == pytest.approx(observed[g], rel=1e-3)
    np.testing.assert_allclose(np.asarray(adopters), anchored / 5.0, rtol=1e-5)


def test_initial_market_shares_apportions_by_weight():
    n, n_groups = 16, 2
    group_idx = jnp.asarray(np.arange(n) % n_groups)
    weight = jnp.asarray(np.linspace(1, 4, n).astype(np.float32))
    start_kw = jnp.asarray([1000.0, 500.0], dtype=jnp.float32)
    z = jnp.zeros(n_groups, jnp.float32)
    state = market.initial_market_shares(
        start_kw, z, z, group_idx, weight, jnp.full(n, 5.0), n_groups
    )
    kw = np.asarray(state.system_kw_cum)
    for g in range(n_groups):
        sel = np.asarray(group_idx) == g
        assert kw[sel].sum() == pytest.approx(float(start_kw[g]), rel=1e-4)


def test_anchor_zero_modeled_capacity_splits_evenly():
    """Edge: a group with zero modeled kW splits the observed total
    1/count per agent (market.py scale fallback); the 5 kW res /
    100 kW non-res adopter heuristic applies (reference
    diffusion_functions_elec.py:126)."""
    from dgen_tpu.models.market import anchor_to_observed

    # 4 agents, one group (0), all res, zero modeled capacity
    kw_cum = jnp.zeros(4, jnp.float32)
    g = jnp.zeros(4, jnp.int32)
    observed = jnp.asarray([800.0], jnp.float32)
    res_mask = jnp.ones(4, bool)
    weight = jnp.full(4, 50.0, jnp.float32)
    a_kw, a_ad, a_sh = anchor_to_observed(
        kw_cum, g, observed, res_mask, weight, 1)
    np.testing.assert_allclose(np.asarray(a_kw), 200.0)      # 800/4
    np.testing.assert_allclose(np.asarray(a_ad), 40.0)       # 200/5 kW
    np.testing.assert_allclose(np.asarray(a_sh), 0.8)        # 40/50


def test_anchor_adopter_size_heuristic_by_sector():
    from dgen_tpu.models.market import anchor_to_observed

    # two groups: agent 0 res, agent 1 com; modeled 100 kW each
    kw_cum = jnp.asarray([100.0, 100.0], jnp.float32)
    g = jnp.asarray([0, 1], jnp.int32)
    observed = jnp.asarray([500.0, 1000.0], jnp.float32)
    res_mask = jnp.asarray([True, False])
    weight = jnp.full(2, 1000.0, jnp.float32)
    a_kw, a_ad, _ = anchor_to_observed(
        kw_cum, g, observed, res_mask, weight, 2)
    np.testing.assert_allclose(np.asarray(a_kw), [500.0, 1000.0])
    # res: 500/5 = 100 adopters; non-res: 1000/100 = 10
    np.testing.assert_allclose(np.asarray(a_ad), [100.0, 10.0])


def test_anchor_zero_weight_gives_zero_share():
    from dgen_tpu.models.market import anchor_to_observed

    kw_cum = jnp.asarray([10.0], jnp.float32)
    a_kw, a_ad, a_sh = anchor_to_observed(
        kw_cum, jnp.zeros(1, jnp.int32), jnp.asarray([50.0], jnp.float32),
        jnp.ones(1, bool), jnp.zeros(1, jnp.float32), 1)
    assert float(a_sh[0]) == 0.0


def _tech_choice_oracle(msl, adl, cpl, mvl, sel, mms, kw, capex, w,
                        p, q, teq_yr1, first, year_step=2.0):
    """Loop-based mirror of the reference's calc_diffusion tech-choice
    path (diffusion_functions_elec.py:162-245) for one agent at a time."""
    n, t = msl.shape
    out_ms = np.zeros_like(msl)
    new_ms = np.zeros_like(msl)
    for i in range(n):
        shares = np.zeros(t)
        for j in range(t):
            mms_fz = max(mms[i, j], 1e-9)
            ratio = 0.0 if msl[i, j] > mms_fz else msl[i, j] / mms_fz
            teq = np.log((1 - ratio) / (1 + ratio * q[i, j] / p[i, j])) / (
                -(p[i, j] + q[i, j]))
            teq2 = teq + (teq_yr1[i, j] if first else year_step)
            f = np.exp(-(p[i, j] + q[i, j]) * teq2)
            naf = (1 - f) / (1 + (q[i, j] / p[i, j]) * f)
            bass = mms[i, j] * naf
            diff = max(msl[i, j], bass) * sel[i, j]      # :290 then :203
            shares[j] = max(diff, msl[i, j])             # :206
        cap = 1.0 - shares[sel[i] == 0].sum()            # :209-227
        for j in range(t):
            if sel[i, j]:
                shares[j] = min(shares[j], cap)
        out_ms[i] = shares
        for j in range(t):
            ns = shares[j] - msl[i, j]
            if shares[j] > mms[i, j]:                    # :230-231
                ns = 0.0
            new_ms[i, j] = ns
    new_ad = np.where(kw == 0.0, 0.0, new_ms * w[:, None])
    return out_ms, new_ms, new_ad


def test_tech_choice_diffusion_matches_reference_semantics():
    from dgen_tpu.models.market import diffusion_step_tech_choice

    rng = np.random.default_rng(7)
    n, t = 48, 3
    msl = rng.uniform(0.0, 0.3, (n, t)).astype(np.float32)
    mms = rng.uniform(0.2, 0.6, (n, t)).astype(np.float32)
    sel = np.zeros((n, t), np.float32)
    sel[np.arange(n), rng.integers(0, t, n)] = 1.0
    kw = rng.uniform(0.0, 10.0, (n, t)).astype(np.float32)
    kw[rng.random((n, t)) < 0.1] = 0.0          # some zero-size options
    capex = rng.uniform(1000, 4000, (n, t)).astype(np.float32)
    w = rng.uniform(10, 500, n).astype(np.float32)
    p = rng.uniform(0.001, 0.01, (n, t)).astype(np.float32)
    q = rng.uniform(0.3, 0.5, (n, t)).astype(np.float32)
    teq1 = rng.uniform(0.0, 4.0, (n, t)).astype(np.float32)
    adl = rng.uniform(0, 50, (n, t)).astype(np.float32)
    cpl = rng.uniform(0, 500, (n, t)).astype(np.float32)
    mvl = rng.uniform(0, 5e5, (n, t)).astype(np.float32)

    for first in (True, False):
        out = diffusion_step_tech_choice(
            *(jnp.asarray(x) for x in (msl, adl, cpl, mvl, sel, mms, kw,
                                       capex, w, p, q, teq1)),
            is_first_year=first,
        )
        o_ms, o_new, o_ad = _tech_choice_oracle(
            msl, adl, cpl, mvl, sel, mms, kw, capex, w, p, q, teq1, first)
        np.testing.assert_allclose(
            np.asarray(out["market_share"]), o_ms, rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(out["new_market_share"]), o_new, rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(out["new_adopters"]), o_ad, rtol=2e-5, atol=1e-3)
        # tech-choice invariant: total share per agent never exceeds 1
        assert np.asarray(out["market_share"]).sum(axis=1).max() <= 1.0 + 1e-5
        # unselected techs hold last year's share exactly
        held = np.asarray(out["market_share"])[sel == 0]
        np.testing.assert_allclose(held, msl[sel == 0], rtol=1e-6)
        # cumulative accounting
        np.testing.assert_allclose(
            np.asarray(out["number_of_adopters"]),
            adl + np.asarray(out["new_adopters"]), rtol=1e-6)
