"""dgenlint-prog tests: every J-rule with a positive (known-bad
program -> finding) and negative (sanctioned idiom -> clean) case via
the fixture programs, suppression at the anchor line, the donation
check against the REAL year_step, the J6 baseline gate failing on an
injected cost regression, CLI plumbing, and — the enforcement
contract — the full entry-point registry auditing green."""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import pytest

from dgen_tpu.lint import prog
from dgen_tpu.lint.prog import baseline as baseline_mod
from dgen_tpu.lint.prog import lower_spec, run_program_rules
from dgen_tpu.lint.prog.registry import build_registry
from dgen_tpu.lint.prog.spec import donated_partition

FIXTURES = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint"
)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fixture(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(FIXTURES, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# J1 — oversized captured constants (+ suppression mechanics)
# ---------------------------------------------------------------------------

def test_j1_positive_and_suppressed():
    flagged, suppressed = _fixture("bad_j1_baked_constant").specs()
    findings = run_program_rules([lower_spec(flagged)])
    assert rules_of(findings) == {"J1"}
    assert "captured constant" in findings[0].message
    # same program, `# dgenlint: disable=J1` at the anchor line
    assert run_program_rules([lower_spec(suppressed)]) == []


# ---------------------------------------------------------------------------
# J2 — dtype drift
# ---------------------------------------------------------------------------

def test_j2_bf16_accumulation_flagged_f32_store_clean():
    bad, clean, _f64 = _fixture("bad_j2_bf16_accum").specs()
    findings = run_program_rules([lower_spec(bad)])
    assert rules_of(findings) == {"J2"}
    assert "bfloat16" in findings[0].message
    assert run_program_rules([lower_spec(clean)]) == []


def test_j2_f64_under_x64():
    from jax.experimental import enable_x64

    _bad, _clean, f64 = _fixture("bad_j2_bf16_accum").specs()
    with enable_x64():
        audit = lower_spec(f64)
    findings = [
        f for f in run_program_rules([audit]) if "float64" in f.message
    ]
    assert findings and findings[0].rule == "J2"


# ---------------------------------------------------------------------------
# J3 — host callbacks in compiled code
# ---------------------------------------------------------------------------

def test_j3_callback_flagged():
    (spec,) = _fixture("bad_j3_host_callback").specs()
    findings = run_program_rules([lower_spec(spec)])
    assert rules_of(findings) == {"J3"}
    assert "debug_callback" in findings[0].message


# ---------------------------------------------------------------------------
# J4 — donation verification
# ---------------------------------------------------------------------------

def test_j4_undonated_and_wrong_target():
    no_donate, wrong_target = _fixture("bad_j4_undonated_carry").specs()
    findings = run_program_rules([lower_spec(no_donate)])
    assert rules_of(findings) == {"J4"}
    assert "NOT donated" in findings[0].message
    findings = run_program_rules([lower_spec(wrong_target)])
    # the carry is still undonated AND the table is wrongly donated
    msgs = " ".join(f.message for f in findings)
    assert "OUTSIDE the declared carry" in msgs


def test_j4_real_year_step_donates_exactly_the_carry():
    """The repo contract, verified on the lowered REAL program: every
    SimCarry leaf donated, nothing else (table/banks/inputs stay
    resident)."""
    spec = next(
        s for s in build_registry("fast")
        if s.spec_id == "year_step@dl0-bf0-nb1-fy0"
    )
    audit = lower_spec(spec)
    assert audit.error is None
    in_ok, in_bad, out_bad = donated_partition(audit)
    assert in_bad == 0 and out_bad == 0
    assert in_ok == 10  # MarketState's 9 leaves + batt_adopters_cum


# ---------------------------------------------------------------------------
# J5 — compile-group fingerprints
# ---------------------------------------------------------------------------

def test_j5_shape_churn_flagged():
    (spec,) = _fixture("bad_j5_shape_churn").specs()
    findings = run_program_rules([lower_spec(spec)])
    assert rules_of(findings) == {"J5"}
    assert "DIFFERENT program" in findings[0].message


def test_j5_real_year_step_steady_state_is_one_program():
    spec = next(
        s for s in build_registry("fast")
        if s.spec_id == "year_step@dl0-bf0-nb1-fy0"
    )
    audit = lower_spec(spec)
    assert audit.steady_fingerprint == audit.fingerprint


# ---------------------------------------------------------------------------
# J6 — the cost-fingerprint regression gate
# ---------------------------------------------------------------------------

def _import_sums_audits():
    specs = [
        s for s in build_registry("fast") if s.entry == "import_sums"
    ]
    return [lower_spec(s, with_cost=True) for s in specs]


@pytest.fixture(scope="module")
def cost_audits():
    return _import_sums_audits()


def _doctored_baseline(audits, **overrides):
    doc = {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "spec": prog.AUDIT_SPEC_VERSION,
        "tolerance": 0.02,
        "entries": {},
    }
    for spec_id, fp in baseline_mod.collect_fingerprints(audits).items():
        doc["entries"][spec_id] = dict(fp, **overrides)
    return doc


def test_j6_gate_fails_on_injected_cost_regression(cost_audits):
    """The acceptance-criterion drill: against a baseline recorded at
    HALF the flops, the current program reads as a 2x cost growth and
    the gate must fail."""
    doc = _doctored_baseline(cost_audits)
    for e in doc["entries"].values():
        e["flops"] = e["flops"] / 2.0
    findings, status = baseline_mod.compare_to_baseline(cost_audits, doc)
    assert findings and all(f.rule == "J6" for f in findings)
    assert any("grew" in f.message for f in findings)
    assert status["note"] is None


def test_j6_gate_flags_shrink_and_const_growth(cost_audits):
    doc = _doctored_baseline(cost_audits)
    for e in doc["entries"].values():
        e["bytes_accessed"] = e["bytes_accessed"] * 2.0   # we "shrank"
        e["const_bytes"] = 0                              # consts "grew"
    findings, _status = baseline_mod.compare_to_baseline(cost_audits, doc)
    msgs = " ".join(f.message for f in findings)
    assert "shrank" in msgs
    assert "captured-constant bytes grew" in msgs


def test_j6_gate_clean_against_faithful_baseline(cost_audits):
    doc = _doctored_baseline(cost_audits)
    findings, status = baseline_mod.compare_to_baseline(cost_audits, doc)
    assert findings == []
    assert status["deltas"]


def test_j6_gate_skips_on_environment_mismatch(cost_audits):
    doc = _doctored_baseline(cost_audits)
    doc["jax"] = "0.0.0-not-this-one"
    for e in doc["entries"].values():
        e["flops"] = 1.0    # wildly wrong, but not comparable
    findings, status = baseline_mod.compare_to_baseline(cost_audits, doc)
    assert findings == []
    assert "skipped" in status["note"]


def test_j6_gate_flags_missing_and_stale_entries(cost_audits):
    doc = _doctored_baseline(cost_audits)
    doc["entries"]["ghost_entry@dl0"] = {"flops": 1.0, "bytes_accessed": 1.0}
    (first_key,) = [k for k in list(doc["entries"]) if "import_sums" in k]
    del doc["entries"][first_key]
    findings, _status = baseline_mod.compare_to_baseline(cost_audits, doc)
    msgs = " ".join(f.message for f in findings)
    assert "no committed cost baseline" in msgs
    assert "no longer produced" in msgs


def test_j6_partial_audit_skips_stale_sweep_and_merges(tmp_path, cost_audits):
    """An --entries subset must neither flag the deselected programs
    as stale nor delete them on --update-baselines."""
    doc = _doctored_baseline(cost_audits)
    doc["entries"]["year_step@dl0-bf0-nb1-fy0"] = {
        "flops": 1.0, "bytes_accessed": 1.0, "const_bytes": 0,
    }
    findings, _status = baseline_mod.compare_to_baseline(
        cost_audits, doc, partial=True
    )
    assert findings == []   # the deselected entry is not "stale"

    path = str(tmp_path / "prog_baseline.json")
    baseline_mod.update_baseline(path, cost_audits)
    with open(path, encoding="utf-8") as f:
        before = json.load(f)
    before["entries"]["year_step@dl0-bf0-nb1-fy0"] = {"flops": 1.0}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(before, f)
    merged = baseline_mod.update_baseline(path, cost_audits, partial=True)
    assert "year_step@dl0-bf0-nb1-fy0" in merged["entries"]

    # ...but a partial merge across environments is refused (the
    # untouched entries would be incomparable with the fresh ones)
    before["jax"] = "0.0.0-not-this-one"
    with open(path, "w", encoding="utf-8") as f:
        json.dump(before, f)
    with pytest.raises(ValueError, match="partial baseline update"):
        baseline_mod.update_baseline(path, cost_audits, partial=True)


def test_j6_cli_entries_subset_gates_green():
    """The documented targeted invocation must pass on a clean tree
    (the full committed baseline contains entries the subset does not
    produce)."""
    findings, status = prog.audit_programs(
        entries=["import_sums"], grid="fast"
    )
    stale = [f for f in findings if "no longer produced" in f.message]
    assert stale == []
    if status["j6"].get("note") is None:   # comparable environment
        assert findings == []


def test_entries_subset_does_not_cost_gate_pulled_in_crossrefs(tmp_path):
    """sweep_loop pulls in year_step for the J5 identity check, but an
    --entries=sweep_loop run must not J6-gate (or, with
    --update-baselines, refresh) year_step's committed fingerprint."""
    path = str(tmp_path / "prog_baseline.json")
    findings, report = prog.audit_programs(
        entries=["sweep_loop"], grid="fast",
        baseline_path=path, update_baselines=True,
    )
    assert findings == [], "\n".join(str(f) for f in findings)
    assert not any(
        "year_step" in k for k in report["j6"]["fingerprints"]
    )


def test_j6_update_baseline_roundtrip(tmp_path, cost_audits):
    path = str(tmp_path / "prog_baseline.json")
    doc = baseline_mod.update_baseline(path, cost_audits)
    with open(path, encoding="utf-8") as f:
        on_disk = json.load(f)
    assert on_disk == doc
    findings, _status = baseline_mod.compare_to_baseline(
        cost_audits, on_disk
    )
    assert findings == []


# ---------------------------------------------------------------------------
# the enforcement contract: the registry audits green
# ---------------------------------------------------------------------------

def test_registry_audits_green():
    """The full entry-point registry (every entry's base grid point)
    lowers and passes J0-J5 — the same invariant `tools/check.sh` and
    the CI fast tier gate at full grid depth with the J6 baseline."""
    findings, report = prog.audit_programs(grid="fast", with_cost=False)
    assert findings == [], "\n".join(str(f) for f in findings)
    expected = {
        "year_step", "year_step_chunked", "sweep_year_step",
        "sweep_loop", "serve_query", "size_agents", "import_sums",
        "bucket_sums",
    }
    assert expected <= set(report["entries"])
    for name, e in report["entries"].items():
        assert e["failed"] == 0, name
        # the one-compile-per-group invariant, statically predicted
        assert e["predicted_compile_groups"] <= e["variants"], name


@pytest.mark.slow
def test_registry_full_grid_with_baseline_gate():
    """Full static-config grid + the committed J6 baseline (skips the
    cost compare automatically under a different jax version)."""
    findings, report = prog.audit_programs(grid="default")
    assert findings == [], "\n".join(str(f) for f in findings)
    assert report["n_programs"] >= 20


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------

def test_cli_list_programs_and_rules():
    out = subprocess.run(
        [sys.executable, "-m", "dgen_tpu.lint", "--list-programs"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0
    assert "year_step" in out.stdout and "import_sums" in out.stdout

    rules = subprocess.run(
        [sys.executable, "-m", "dgen_tpu.lint", "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert rules.returncode == 0
    for rule in ("J1", "J6"):
        assert rule in rules.stdout


def test_cli_unknown_entry_is_usage_error():
    with pytest.raises(ValueError, match="unknown program entries"):
        prog.audit_programs(entries=["nope"], grid="fast")


def test_update_baselines_with_select_excluding_j6_is_an_error():
    """An explicitly requested baseline write must never be a silent
    no-op."""
    with pytest.raises(ValueError, match="update-baselines requires"):
        prog.audit_programs(
            select=["J1"], update_baselines=True, grid="fast"
        )


def test_errored_entry_is_not_reported_as_stale_baseline(cost_audits):
    """A spec that fails to lower is a J0 finding; its committed cost
    gate must not be reported as stale (deleting it would be exactly
    wrong)."""
    from dgen_tpu.lint.prog import ProgramSpec

    doc = _doctored_baseline(cost_audits)
    broken = ProgramSpec(
        entry="import_sums", variant="layout0-bf0",
        build=lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        anchor=("<fixture>", 1), cost=True,
    )
    audit = lower_spec(broken, with_cost=True)
    assert audit.error is not None
    findings, _status = baseline_mod.compare_to_baseline([audit], doc)
    assert not any("no longer produced" in f.message for f in findings)
