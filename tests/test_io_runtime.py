"""Checkpoint/resume and per-year export surfaces."""

import numpy as np
import pytest

import jax.numpy as jnp

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import checkpoint as ckpt
from dgen_tpu.io import export as exp
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import SimCarry, Simulation


def make_sim(with_hourly=False):
    cfg = ScenarioConfig(name="ck", start_year=2014, end_year=2020,
                         anchor_years=())
    pop = synth.generate_population(96, states=["DE", "CA"], seed=2,
                                    pad_multiple=32)
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
        overrides={"attachment_rate": jnp.full((pop.table.n_groups,), 0.3)},
    )
    return Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                      RunConfig(sizing_iters=6), with_hourly=with_hourly), pop


def test_checkpoint_roundtrip(tmp_path):
    c = SimCarry.zeros(32)
    c = SimCarry(
        market=c.market.__class__(
            **{f: c.market.__dict__[f] + i
               for i, f in enumerate(c.market.__dataclass_fields__)}
        ),
        batt_adopters_cum=c.batt_adopters_cum + 7.0,
    )
    ckpt.save_year(str(tmp_path), 2016, c)
    assert ckpt.latest_year(str(tmp_path)) == 2016
    year, restored = ckpt.restore_year(str(tmp_path), 32)
    assert year == 2016
    np.testing.assert_array_equal(
        np.asarray(restored.batt_adopters_cum), np.asarray(c.batt_adopters_cum))
    np.testing.assert_array_equal(
        np.asarray(restored.market.system_kw_cum),
        np.asarray(c.market.system_kw_cum))


def test_checkpoint_overwrite_not_stale(tmp_path):
    # re-running into an existing checkpoint dir must overwrite, not
    # silently keep the previous run's carry (orbax skips existing
    # steps unless forced)
    a = SimCarry.zeros(8)
    b = SimCarry(market=a.market, batt_adopters_cum=a.batt_adopters_cum + 5.0)
    ckpt.save_year(str(tmp_path), 2020, a)
    ckpt.save_year(str(tmp_path), 2020, b)
    _, restored = ckpt.restore_year(str(tmp_path), 8)
    np.testing.assert_array_equal(
        np.asarray(restored.batt_adopters_cum), np.full(8, 5.0))


def test_exporter_rejects_wrong_state_names(tmp_path):
    ex = exp.RunExporter(str(tmp_path), agent_id=np.arange(4),
                         mask=np.ones(4), state_names=["DE", "CA"])
    with pytest.raises(ValueError):
        ex.write_state_hourly(2014, np.zeros((49, 8760), np.float32))


@pytest.mark.slow
def test_resume_matches_uninterrupted(tmp_path):
    sim, pop = make_sim()
    full = sim.run()

    # run years 1-2 with checkpoints, then resume for the rest
    ckdir = str(tmp_path / "ck")
    sim2, _ = make_sim()
    carry = sim2.init_carry()
    for yi in (0, 1):
        carry, _ = sim2.step(carry, yi, first_year=(yi == 0))
        ckpt.save_year(ckdir, sim2.years[yi], carry)

    sim3, _ = make_sim()
    resumed = sim3.run(checkpoint_dir=ckdir, resume=True)

    m = np.asarray(pop.table.mask)
    f = full.summary(m)
    # resumed results only cover years after the checkpoint
    n_resumed = len(resumed.agent["system_kw_cum"])
    assert n_resumed == len(sim.years) - 2
    r_last = (resumed.agent["system_kw_cum"][-1] * m).sum()
    np.testing.assert_allclose(r_last, f["system_kw_cum"][-1], rtol=1e-5)


def test_host_rows_multihost_shard_path():
    """_host_rows must return only the locally-addressable rows (with
    their global indices, deduped across replicated local devices) for
    a non-fully-addressable array — the true multi-host case, simulated
    with a stub since a single-controller test owns every shard."""
    import dataclasses

    full = np.arange(12, dtype=np.float32).reshape(6, 2)

    @dataclasses.dataclass
    class Shard:
        index: tuple
        data: np.ndarray

    class Stub:
        is_fully_addressable = False
        is_fully_replicated = False
        shape = full.shape
        # this process holds rows [2:4) twice (two local devices with a
        # replicated copy) and rows [4:6) once; rows [0:2) are remote
        addressable_shards = [
            Shard((slice(2, 4), slice(None)), full[2:4]),
            Shard((slice(2, 4), slice(None)), full[2:4]),
            Shard((slice(4, 6), slice(None)), full[4:6]),
        ]

    rows, idx = exp._host_rows(Stub())
    np.testing.assert_array_equal(idx, [2, 3, 4, 5])
    np.testing.assert_array_equal(rows, full[2:6])

    # replicated leaf: everything is local
    class Repl(Stub):
        is_fully_replicated = True

        def __array__(self, dtype=None):
            return full

    rows, idx = exp._host_rows(Repl())
    assert idx is None
    np.testing.assert_array_equal(rows, full)

    # plain arrays pass straight through
    rows, idx = exp._host_rows(full)
    assert idx is None and rows is not None


def test_exporter_local_rows_multihost(tmp_path):
    """RunExporter keyed writes stay correct when a process holds only a
    slice of the agent axis: ids come from the global index window and
    padding rows are dropped."""
    import dataclasses

    n = 8
    ids = np.arange(100, 100 + n)
    mask = np.ones(n, np.float32)
    mask[5] = 0.0  # a padding row inside the local window
    ex = exp.RunExporter(str(tmp_path / "run"), agent_id=ids, mask=mask)

    vals = np.arange(n, dtype=np.float32) * 10

    @dataclasses.dataclass
    class Shard:
        index: tuple
        data: np.ndarray

    class Stub:
        is_fully_addressable = False
        is_fully_replicated = False
        shape = (n,)
        addressable_shards = [Shard((slice(4, 8),), vals[4:8])]

    rows, got_ids = ex._local(Stub())
    np.testing.assert_array_equal(got_ids, [104, 106, 107])
    np.testing.assert_array_equal(rows, [40.0, 60.0, 70.0])


def test_exporter_mixed_leaf_shardings(tmp_path):
    """A YearOutputs leaf whose sharding differs from the first leaf's
    (GSPMD may replicate one output while sharding its siblings) must be
    realigned onto the first leaf's rows, not sliced with its index."""
    import dataclasses

    n = 8
    ids = np.arange(100, 100 + n)
    mask = np.ones(n, np.float32)
    mask[5] = 0.0
    ex = exp.RunExporter(str(tmp_path / "run"), agent_id=ids, mask=mask)

    vals = np.arange(n, dtype=np.float32) * 10
    other = np.arange(n, dtype=np.float32) + 0.5

    @dataclasses.dataclass
    class Shard:
        index: tuple
        data: np.ndarray

    class Sharded:
        is_fully_addressable = False
        is_fully_replicated = False
        shape = (n,)
        addressable_shards = [Shard((slice(4, 8),), vals[4:8])]

    class Repl:
        is_fully_addressable = False
        is_fully_replicated = True
        shape = (n,)

        def __array__(self, dtype=None):
            return other

    (r1, r2), got_ids = ex._local_fields([Sharded(), Repl()])
    np.testing.assert_array_equal(got_ids, [104, 106, 107])
    np.testing.assert_array_equal(r1, [40.0, 60.0, 70.0])
    # replicated leaf realigned onto the sharded leaf's surviving rows
    np.testing.assert_array_equal(r2, [4.5, 6.5, 7.5])

    # and the symmetric order: replicated first, sharded second — the
    # second leaf's local window misses rows the first leaf exposes, so
    # the exporter must fail loudly instead of writing misaligned rows
    import pytest

    with pytest.raises(ValueError, match="incompatible"):
        ex._local_fields([Repl(), Sharded()])


def test_deferred_export_survives_midrun_crash(tmp_path):
    """Export-only runs defer each year's callback until the next
    year's step is dispatched; a failure mid-run must still flush the
    last completed year's export (the finally-flush in Simulation.run)
    — otherwise a computed year's parquet partitions vanish."""
    sim, pop = make_sim()
    exporter = exp.RunExporter(
        str(tmp_path / "run"),
        agent_id=np.asarray(pop.table.agent_id),
        mask=np.asarray(pop.table.mask),
    )
    calls = {"n": 0}
    orig_step = sim.step

    def flaky_step(carry, yi, first_year):
        calls["n"] += 1
        if calls["n"] == 3:   # die while dispatching year 3
            raise RuntimeError("injected dispatch failure")
        return orig_step(carry, yi, first_year)

    sim.step = flaky_step
    import pytest

    with pytest.raises(RuntimeError, match="injected"):
        sim.run(callback=exporter, collect=False)

    # years 1 and 2 completed on device; BOTH must be exported (year 1
    # via the in-loop deferred flush, year 2 via the finally flush)
    ao = exp.load_surface(str(tmp_path / "run"), "agent_outputs")
    assert set(ao["year"]) == {2014, 2016}


def test_compact_export_quantization(tmp_path):
    """Compact (default) exports int16-quantize the bulky float columns
    on device and drop energy_value; values must reconstruct within the
    quantization bound (max|x|/65532 per column), cumulative fields must
    stay bit-exact f32, and compact=False must restore the full-f32
    schema including energy_value."""
    sim, pop = make_sim()
    kw = dict(agent_id=np.asarray(pop.table.agent_id),
              mask=np.asarray(pop.table.mask))
    full = exp.RunExporter(str(tmp_path / "full"), compact=False, **kw)
    comp = exp.RunExporter(str(tmp_path / "comp"), compact=True, **kw)

    def both(year, yi, outs):
        full(year, yi, outs)
        comp(year, yi, outs)

    sim.run(callback=both, collect=False)

    ao_f = exp.load_surface(str(tmp_path / "full"), "agent_outputs")
    ao_c = exp.load_surface(str(tmp_path / "comp"), "agent_outputs")
    assert len(ao_f) == len(ao_c)
    for col in exp.AGENT_OUTPUT_FIELDS:
        a, b = ao_f[col].to_numpy(), ao_c[col].to_numpy()
        if col in exp._EXACT_FIELDS:
            np.testing.assert_array_equal(a, b, err_msg=col)
        else:
            tol = max(np.abs(a).max(), 1e-9) / 65532 * 1.01
            np.testing.assert_allclose(a, b, atol=tol, err_msg=col)

    fs_f = exp.load_surface(str(tmp_path / "full"), "finance_series")
    fs_c = exp.load_surface(str(tmp_path / "comp"), "finance_series")
    assert "energy_value" in fs_f.columns
    assert "energy_value" not in fs_c.columns
    cf_f = np.stack(fs_f["cash_flow"].to_numpy())
    cf_c = np.stack(fs_c["cash_flow"].to_numpy())
    # per-column scales: each year column meets its own bound
    col_tol = np.abs(cf_f).max(axis=0) / 65532 * 1.01 + 1e-9
    assert (np.abs(cf_f - cf_c) <= col_tol[None, :]).all()
    # provenance stamped
    assert full.meta["export_compact"] is False
    assert comp.meta["export_compact"] is True


def test_final_year_export_failure_raises():
    """On the SUCCESS path, a failing final-year flush must surface —
    a run must not report success with the last year's partitions
    silently missing (ADVICE r4).  On the failure path the original
    error still wins (covered by the midrun-crash test above)."""
    sim, pop = make_sim()
    n_years = len(sim.years)
    calls = {"n": 0}

    def flaky_exporter(year, yi, outs):
        calls["n"] += 1
        if calls["n"] == n_years:   # the finally-flushed final year
            raise OSError("disk full")

    with pytest.raises(OSError, match="disk full"):
        sim.run(callback=flaky_exporter, collect=False)
    assert calls["n"] == n_years


@pytest.mark.slow
def test_exporter_surfaces(tmp_path):
    sim, pop = make_sim(with_hourly=True)
    exporter = exp.RunExporter(
        str(tmp_path / "run"),
        agent_id=np.asarray(pop.table.agent_id),
        mask=np.asarray(pop.table.mask),
        state_names=list(synth.STATES),
    )
    sim.run(callback=exporter, collect=False)

    ao = exp.load_surface(str(tmp_path / "run"), "agent_outputs")
    n_real = int(np.asarray(pop.table.mask).sum())
    assert len(ao) == n_real * len(sim.years)
    assert set(exp.AGENT_OUTPUT_FIELDS) <= set(ao.columns)
    assert (ao.groupby("year")["system_kw_cum"].sum().diff().dropna() >= -1e-3).all()

    fs = exp.load_surface(str(tmp_path / "run"), "finance_series")
    assert len(fs) == n_real * len(sim.years)
    assert len(fs["cash_flow"].iloc[0]) == 26

    sh = exp.load_surface(str(tmp_path / "run"), "state_hourly")
    assert len(sh) == pop.table.n_states * len(sim.years)
    assert len(sh["net_load_mw"].iloc[0]) == 8760


def test_exporter_stamps_nonfinite_zeroed_count(tmp_path):
    """Compact quantization zeroes non-finite elements; the per-run
    count must land in meta.json so repaired data is visible in the
    run's provenance."""
    import json

    n = 6
    ex = exp.RunExporter(str(tmp_path / "run"), agent_id=np.arange(n),
                         mask=np.ones(n, np.float32), compact=True)
    meta0 = json.load(open(tmp_path / "run" / "meta.json"))
    assert meta0["nonfinite_zeroed"] == 0

    dirty = jnp.asarray([1.0, np.nan, 2.0, np.inf, -np.inf, 3.0],
                        jnp.float32)
    clean = jnp.arange(n, dtype=jnp.float32)
    (rows_d, rows_c), _ = ex._local_fields([dirty, clean],
                                           quant=(True, True))
    # the three non-finite elements came back as exact zeros
    np.testing.assert_allclose(rows_d[[1, 3, 4]], 0.0)
    np.testing.assert_allclose(rows_c, np.arange(n), atol=1e-3)
    ex._flush_meta()
    meta = json.load(open(tmp_path / "run" / "meta.json"))
    assert meta["nonfinite_zeroed"] == 3
