"""RetraceGuard (dgenlint's runtime half): fresh-compile counting,
cache-hit cleanliness, per-year check/reset composition, and the
Simulation.run wiring — a steady-state year that recompiles must fail
the run, and a clean run must pass with the guard armed."""

import jax
import jax.numpy as jnp
import pytest

from dgen_tpu.config import RunConfig
from dgen_tpu.lint.guard import RetraceError, RetraceGuard

from test_simulation import make_sim


def test_cache_hit_is_clean_and_fresh_compile_fails():
    @jax.jit
    def f(x):
        return x * 3.0

    f(jnp.ones(16)).block_until_ready()           # warm the cache
    with RetraceGuard():
        f(jnp.ones(16)).block_until_ready()       # cache hit: clean

    with pytest.raises(RetraceError, match="steadyish"):
        with RetraceGuard(context="steadyish"):
            # new shape -> fresh trace + compile inside the guard
            f(jnp.ones(32)).block_until_ready()


def test_counts_and_check_reset_compose():
    guard = RetraceGuard(max_compiles=10, max_traces=None).start()
    try:
        @jax.jit
        def g(x):
            return x - 0.5

        g(jnp.ones(8)).block_until_ready()
        assert guard.n_compiles >= 1
        assert guard.n_traces >= 1
        guard.check("warmup")        # within budget: resets counters
        assert guard.n_compiles == 0
        g(jnp.ones(8)).block_until_ready()   # cache hit
        assert guard.n_compiles == 0
        guard.check("steady")
    finally:
        guard.stop()


def test_stop_detaches_counting():
    guard = RetraceGuard().start()
    guard.stop()

    @jax.jit
    def h(x):
        return x + 2.0

    h(jnp.ones(8)).block_until_ready()
    assert guard.n_compiles == 0


def test_simulation_steady_state_years_do_not_retrace():
    """The design contract behind the <10-min national run: after the
    first_year=True/False pair compiles, every later year is a cache
    hit. guard_retrace=True turns any violation into a run failure."""
    sim, pop = make_sim(
        n_agents=64, states=("DE",), end_year=2022,
        run_config=RunConfig(sizing_iters=6, guard_retrace=True),
    )
    res = sim.run()
    assert len(res.years) == 5   # 2014..2022 step 2, none rejected


def test_fresh_carry_step_is_donation_safe():
    """year_step donates the carry, so a FRESH SimCarry.zeros carry
    stepped with first_year=False must not trip XLA's 'donate the same
    buffer twice' — MarketState.zeros allocates one buffer per field
    for exactly this reason."""
    sim, pop = make_sim(
        n_agents=64, states=("DE",), end_year=2022,
        run_config=RunConfig(sizing_iters=6),
    )
    carry = sim.init_carry()
    carry, outs = sim.step(carry, 1, first_year=False)
    assert outs.system_kw.shape[0] == pop.table.n_agents


def test_simulation_guard_catches_churning_static_arg():
    """Inject the classic retrace storm — a float static argument that
    drifts every call — and assert the guard names the year."""
    sim, pop = make_sim(
        n_agents=64, states=("DE",), end_year=2020,
        run_config=RunConfig(sizing_iters=6, guard_retrace=True),
    )
    orig = sim._step_kwargs
    state = {"n": 0}

    def churning(first_year):
        kw = orig(first_year)
        state["n"] += 1
        kw["year_step_len"] = kw["year_step_len"] + state["n"] * 1e-6
        return kw

    sim._step_kwargs = churning
    with pytest.raises(RetraceError, match="year 2018"):
        sim.run()
