"""Scenario ingest from the actual reference input_data directory
(mounted read-only): shapes, ranges, and spot-checked values."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import synth
from dgen_tpu.io.reference_inputs import (
    CENSUS_DIVISIONS,
    scenario_inputs_from_reference,
)
from dgen_tpu.models.simulation import Simulation

REF_INPUTS = "/root/reference/dgen_os/input_data"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_INPUTS), reason="reference inputs not mounted"
)


@pytest.fixture(scope="module")
def ref_scenario():
    cfg = ScenarioConfig(name="ref", start_year=2014, end_year=2030,
                         anchor_years=(2014, 2016, 2018))
    states = list(synth.STATES)
    inputs, meta = scenario_inputs_from_reference(REF_INPUTS, cfg, states)
    return cfg, states, inputs, meta


def test_shapes_and_ranges(ref_scenario):
    cfg, states, inputs, meta = ref_scenario
    y = len(cfg.model_years)
    g = len(states) * 3
    assert inputs.pv_capex_per_kw.shape == (y, 3)
    assert inputs.load_growth.shape == (y, len(CENSUS_DIVISIONS), 3)
    assert inputs.observed_kw.shape == (y, g)
    assert inputs.starting_kw.shape == (g,)

    # capex declines over the ATB trajectory and stays positive
    capex = np.asarray(inputs.pv_capex_per_kw)
    assert capex.min() > 100.0
    assert capex[-1].mean() < capex[0].mean()
    # degradation is a small positive fraction
    deg = np.asarray(inputs.pv_degradation)
    assert np.all(deg >= 0.0) and np.all(deg < 0.05)
    # financing sane
    assert np.all(np.asarray(inputs.loan_interest_rate) < 0.25)
    assert np.all(np.asarray(inputs.tax_rate) > 0.0)
    # attachment rates are probabilities
    ar = np.asarray(inputs.attachment_rate)
    assert np.all((ar >= 0.0) & (ar <= 1.0))
    assert ar.max() > 0.05, "some state should have storage attachment"


def test_observed_deployment_spot_value(ref_scenario):
    cfg, states, inputs, meta = ref_scenario
    # CA residential 2014 observed deployment must be large (>1 GW was
    # not yet reached; several hundred MW) and strictly less than 2018
    ca = states.index("CA")
    g = ca * 3 + 0  # res
    y14 = cfg.model_years.index(2014)
    y18 = cfg.model_years.index(2018)
    kw14 = float(np.asarray(inputs.observed_kw)[y14, g])
    kw18 = float(np.asarray(inputs.observed_kw)[y18, g])
    assert kw14 > 1e5, "CA res 2014 should exceed 100 MW"
    assert kw18 > kw14


def test_starting_capacity_matches_csv(ref_scenario):
    cfg, states, inputs, meta = ref_scenario
    import csv
    with open(os.path.join(
            REF_INPUTS, "installed_capacity_mw_by_state_sector.csv")) as f:
        rows = [r for r in csv.DictReader(f)
                if int(r["year"]) == 2014 and r["state_abbr"] == "AZ"
                and r["sector_abbr"] == "com"]
    want_kw = float(rows[0]["observed_capacity_mw"]) * 1000.0
    az = states.index("AZ")
    got = float(np.asarray(inputs.starting_kw)[az * 3 + 1])
    assert got == pytest.approx(want_kw, rel=1e-6)


@pytest.mark.slow
def test_end_to_end_with_reference_inputs(ref_scenario):
    cfg, states, inputs, meta = ref_scenario
    pop = synth.generate_population(
        128, states=["CA", "AZ", "NY"], seed=9, pad_multiple=32,
        n_regions=len(meta["regions"]),
    )
    # wholesale sell-rate base from the reference trajectory
    base = np.asarray(meta["wholesale_base_usd_per_kwh"])
    assert base.shape[0] == len(meta["regions"])
    assert 0.005 < base.mean() < 0.2
    profiles = pop.profiles.__class__(
        load=pop.profiles.load,
        solar_cf=pop.profiles.solar_cf,
        wholesale=jnp.asarray(
            np.broadcast_to(base[:, None], (len(base), 8760)).copy()),
    )
    sim = Simulation(pop.table, profiles, pop.tariffs, inputs, cfg,
                     RunConfig(sizing_iters=6))
    res = sim.run()
    m = np.asarray(pop.table.mask)
    s = res.summary(m)
    assert np.all(np.isfinite(s["system_kw_cum"]))
    assert s["system_kw_cum"][-1] > 0
    # anchor years rescale to observed state totals: CA res agents in
    # 2014 must carry nonzero anchored capacity
    assert s["system_kw_cum"][0] > 0


def test_batt_tech_and_deprec_from_reference(ref_scenario):
    """batt_tech_performance + depreciation_schedules CSVs land on the
    model grid with the file's actual values (FY19: res eff 0.92,
    com/ind 0.829; deprec com year-1 fraction 0.6)."""
    cfg, states, inputs, meta = ref_scenario
    y = len(cfg.model_years)
    assert inputs.batt_eff.shape == (y, 3)
    eff = np.asarray(inputs.batt_eff)
    assert eff[0, 0] == pytest.approx(0.92, abs=1e-6)
    assert eff[0, 1] == pytest.approx(0.829, abs=1e-6)
    life = np.asarray(inputs.batt_lifetime_yrs)
    assert life[0, 0] == pytest.approx(15.0)
    assert life[0, 1] == pytest.approx(10.0)
    sch = np.asarray(inputs.deprec_sch)
    assert sch.shape == (y, 3, 6)
    assert sch[0, 1, 0] == pytest.approx(0.6, abs=1e-6)
    # schedules sum to ~1 (full basis depreciated)
    np.testing.assert_allclose(sch[0, 1].sum(), 1.0, atol=0.02)


def test_nem_caps_compile_when_state_limits_present(tmp_path):
    """With an exported nem_state_limits.csv + the reference's shipped
    peak-demand/CF files, nem_cap_kw comes from data."""
    import shutil

    import pandas as pd

    root = tmp_path / "input_data"
    shutil.copytree(REF_INPUTS, root)
    ref_py = "/root/reference/dgen_os/python"
    for f in ("peak_demand_mw.csv", "cf_during_peak_demand.csv"):
        shutil.copy(os.path.join(ref_py, f), root / f)
    pd.DataFrame([
        {"state_abbr": "CA", "first_year": 2014, "sunset_year": 2050,
         "max_cum_capacity_mw": "", "max_pct_cum_capacity": 5.0},
        {"state_abbr": "OH", "first_year": 2014, "sunset_year": 2050,
         "max_cum_capacity_mw": "", "max_pct_cum_capacity": 5.0},
    ]).to_csv(root / "nem_state_limits.csv", index=False)

    cfg = ScenarioConfig(name="ref", start_year=2014, end_year=2020,
                         anchor_years=())
    states = ["CA", "OH", "TX"]
    inputs, _ = scenario_inputs_from_reference(str(root), cfg, states)
    caps = np.asarray(inputs.nem_cap_kw)
    from dgen_tpu.io.reference_inputs import CENSUS_DIVISIONS

    lg = np.asarray(inputs.load_growth)                    # [Y, R, S]
    # CA: 5% x 51697.29 MW / 0.492661101 (peak_demand_mw.csv,
    # cf_during_peak_demand.csv), scaled by CA's OWN census division's
    # (PAC) res growth — the per-state analogue of the reference's
    # county-average peak-demand tracking (elec.py:813-814)
    pac = CENSUS_DIVISIONS.index("PAC")
    base_ca = 0.05 * 51697.29 / 0.492661101 * 1000.0 * lg[0, pac, 0]
    assert caps[0, 0] == pytest.approx(base_ca, rel=0.01)
    # OH rides ENC growth; with real trajectories the two divisions
    # differ, so the caps' growth paths must differ too (the old
    # global-mean proxy made every state's cap grow identically)
    enc = CENSUS_DIVISIONS.index("ENC")
    ratio_ca = caps[-1, 0] / caps[0, 0]
    ratio_oh = caps[-1, 1] / caps[0, 1]
    np.testing.assert_allclose(
        ratio_ca, lg[-1, pac, 0] / lg[0, pac, 0], rtol=1e-5)
    np.testing.assert_allclose(
        ratio_oh, lg[-1, enc, 0] / lg[0, enc, 0], rtol=1e-5)
    # TX has no limits row -> uncapped
    assert caps[0, 2] > 1e29


def test_wholesale_hourly_shape(tmp_path):
    """Flat by default (the reference's own annual-scalar sell rate,
    financial_functions.py:372); an hourly shape file modulates it."""
    from dgen_tpu.io.reference_inputs import wholesale_profile_bank

    meta = {"wholesale_base_usd_per_kwh": np.asarray([0.04, 0.05]),
            "regions": ["A", "B"]}
    flat = wholesale_profile_bank(meta)
    assert flat.shape == (2, 8760)
    np.testing.assert_allclose(flat[0], 0.04, rtol=1e-6)

    hod = np.arange(8760) % 24
    shape = 1.0 + 0.5 * np.sin(hod / 24 * 2 * np.pi)
    with open(tmp_path / "wholesale_hourly_shape.csv", "w") as f:
        f.write("shape\n")
        f.writelines(f"{v}\n" for v in shape)
    shaped = wholesale_profile_bank(meta, str(tmp_path))
    assert shaped[0].std() > 0.001
    np.testing.assert_allclose(shaped[0].mean(), 0.04, rtol=1e-3)
    np.testing.assert_allclose(shaped[1].mean(), 0.05, rtol=1e-3)


def test_carbon_intensities_from_reference(ref_scenario):
    """carbon_intensities_FY19.csv lands per state-year: AL 2014 is
    0.0004 tCO2/kWh in the file."""
    cfg, states, inputs, meta = ref_scenario
    ci = np.asarray(inputs.carbon_intensity_t_per_kwh)
    assert ci.shape == (len(cfg.model_years), len(states))
    al = states.index("AL")
    assert ci[0, al] == pytest.approx(0.0004, abs=1e-6)
    assert ci.max() < 0.01 and ci.min() >= 0.0


def test_wholesale_trajectory_multiplier(ref_scenario):
    """Wholesale sell rates vary per year (the reference merges them
    per year, elec.py:608): multiplier is 1.0 at the base year and
    moves with the file's trajectory."""
    cfg, states, inputs, meta = ref_scenario
    wm = np.asarray(inputs.wholesale_multiplier)
    assert wm.shape == (len(cfg.model_years), len(meta["regions"]))
    np.testing.assert_allclose(wm[0], 1.0, rtol=1e-5)
    # the trajectory is not flat over the horizon
    assert np.abs(wm - 1.0).max() > 0.01


def test_ba_region_mode():
    """region_kind="ba": retail prices resolve per ReEDS balancing
    area (the reference's native resolution); trajectories stay finite
    and the BA list drives the region axis."""
    cfg = ScenarioConfig(name="ba", start_year=2014, end_year=2020,
                         anchor_years=())
    inputs, meta = scenario_inputs_from_reference(
        REF_INPUTS, cfg, ["CA", "TX"], region_kind="ba")
    regions = meta["regions"]
    assert len(regions) > 9, "BA mode should expose more than the 9 CDs"
    mult = np.asarray(inputs.elec_price_multiplier)   # [Y, R, S]
    assert mult.shape[1] == len(regions)
    assert np.isfinite(mult).all() and (mult > 0).all()
    # per-BA variation exists (census-division mode averages it away)
    assert mult[-1, :, 0].std() > 1e-4
    # wholesale base rates align with the BA axis
    wb = np.asarray(meta["wholesale_base_usd_per_kwh"])
    assert wb.shape[0] == len(regions)
    assert np.isfinite(wb).all() and (wb >= 0).all()
    # load growth in BA mode is the national-mean proxy: every region
    # shares one trajectory (documented fallback, reference_inputs)
    lg = np.asarray(inputs.load_growth)
    assert np.allclose(lg, lg[:, :1, :], rtol=1e-5)
