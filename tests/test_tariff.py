"""Tariff compiler: normalization, padding, schedule expansion."""

import numpy as np
import pytest

from dgen_tpu.ops import tariff as tf


def test_flat_tariff_compiles():
    bank = tf.compile_tariffs([tf.flat_tariff(0.12, fixed=5.0)])
    assert bank.n_tariffs == 1
    assert float(bank.price[0, 0, 0]) == pytest.approx(0.12)
    assert float(bank.fixed_monthly[0]) == pytest.approx(5.0)
    assert int(bank.n_periods[0]) == 1
    # schedule maps every hour to period 0
    assert np.all(np.asarray(bank.hour_period[0]) == 0)


def test_legacy_e_parts_layout():
    """e_prices is [tier][period] (reference legacy layout,
    financial_functions.py:763 ``_build_ur_ec_from_e_parts``)."""
    spec = {
        "e_prices": [[0.10, 0.20], [0.15, 0.25]],   # 2 tiers x 2 periods
        "e_levels": [[300.0, 300.0], [1e38, 1e38]],
        "e_wkday_12by24": np.concatenate(
            [np.zeros((12, 12), int), np.ones((12, 12), int)], axis=1
        ),
    }
    bank = tf.compile_tariffs([spec])
    assert int(bank.n_periods[0]) == 2
    assert int(bank.n_tiers[0]) == 2
    # price[period, tier]
    assert float(bank.price[0, 0, 0]) == pytest.approx(0.10)
    assert float(bank.price[0, 1, 0]) == pytest.approx(0.20)
    assert float(bank.price[0, 0, 1]) == pytest.approx(0.15)
    assert float(bank.tier_cap[0, 0]) == pytest.approx(300.0)
    # afternoon hours map to period 1 on weekdays
    hp = np.asarray(bank.hour_period[0])
    assert hp[14] == 1 and hp[2] == 0


def test_tier_caps_harmonized_to_min_finite():
    spec = {
        "e_prices": [[0.10, 0.20], [0.15, 0.25]],
        "e_levels": [[500.0, 300.0], [1e38, 1e38]],  # differing caps per period
        "e_wkday_12by24": np.zeros((12, 24), int),
    }
    bank = tf.compile_tariffs([spec])
    # harmonized cap = min finite across periods (reference :948-953)
    assert float(bank.tier_cap[0, 0]) == pytest.approx(300.0)
    assert float(bank.tier_cap[0, 1]) == pytest.approx(tf.BIG_CAP)


def test_period_remap_contiguous():
    """Schedules referencing a sparse period set get remapped 0..P-1."""
    wkday = np.zeros((12, 24), int)
    wkday[:, 12:] = 2  # only periods 0 and 2 used out of 3
    spec = {
        "price": [[0.10], [0.99], [0.30]],
        "e_wkday_12by24": wkday,
        "e_wkend_12by24": wkday,
    }
    bank = tf.compile_tariffs([spec])
    assert int(bank.n_periods[0]) == 2
    # period 2 became period 1 with its price preserved
    assert float(bank.price[0, 1, 0]) == pytest.approx(0.30)
    hp = np.asarray(bank.hour_period[0])
    assert set(np.unique(hp)) == {0, 1}


def test_padding_is_inert():
    """A 1-period tariff padded into a 4-period bank bills identically."""
    import jax.numpy as jnp
    from dgen_tpu.ops import bill as bill_ops

    spec = tf.flat_tariff(0.11, fixed=3.0)
    small = tf.compile_tariffs([spec])
    padded = tf.compile_tariffs([spec], max_periods=4, max_tiers=3)
    rng = np.random.default_rng(0)
    net = jnp.asarray(rng.uniform(-1, 2, tf.HOURS).astype(np.float32))
    zs = jnp.zeros(tf.HOURS, dtype=jnp.float32)
    b_small = float(bill_ops.annual_bill(
        net, bill_ops.gather_tariff(small, jnp.asarray(0)), zs, small.max_periods))
    b_pad = float(bill_ops.annual_bill(
        net, bill_ops.gather_tariff(padded, jnp.asarray(0)), zs, padded.max_periods))
    assert b_small == pytest.approx(b_pad, rel=1e-5)


def test_weekend_schedule_differs():
    wkday = np.zeros((12, 24), int)
    wkday[:, 16:21] = 1
    spec = {
        "price": [[0.10], [0.30]],
        "e_wkday_12by24": wkday,
        "e_wkend_12by24": np.zeros((12, 24), int),
    }
    bank = tf.compile_tariffs([spec])
    hp = np.asarray(bank.hour_period[0])
    weekend = tf.hour_weekend_map()
    # weekday evening hours in period 1, weekend evenings period 0
    evening = (np.arange(tf.HOURS) % 24 == 18)
    assert np.all(hp[evening & ~weekend] == 1)
    assert np.all(hp[evening & weekend] == 0)
