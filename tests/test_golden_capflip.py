"""Golden fixture #2: the binding-NEM-cap flip (VERDICT r4 item 6).

The first golden fixture (test_golden_e2e.py) pins a run whose NEM gate
never closes — the static all-NEM fast path.  This one pins the OTHER
regime: a multi-state population whose state capacity caps bind in a
mid-run year, flipping agents from net metering to net billing while
anchor years, the DG-rate switch, incentives, and storage attachment
are all on (reference cap semantics: agent_mutation/elec.py:449-505 —
the cap gate compares LAST step's installed kW to the state cap).

Caps are derived deterministically from an uncapped pre-run (30% of
each state's final capacity), so the flip year is a property of the
fixture, not a hand-tuned constant.  The pinned curves are the
regression contract at 0.1%, same as fixture #1; the flip itself is
asserted through the SAME predicate the driver uses
(simulation._nem_allowed_arrays), evaluated host-side per year.

Rebase intentionally with:
    DGEN_TPU_WRITE_GOLDEN=1 python -m pytest tests/test_golden_capflip.py
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation, _nem_allowed_arrays

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
GOLDEN_PATH = os.path.join(FIXTURES, "golden_capflip.json")
RTOL = 1e-3

pytestmark = pytest.mark.slow

CAP_FRACTION = 0.30   # caps at 30% of the uncapped final state capacity


def _build(caps=None):
    cfg = ScenarioConfig(
        name="capflip", start_year=2014, end_year=2050,
        storage_enabled=True,   # anchor_years stays at its default
    )
    pop = synth.generate_population(
        192, states=["DE", "CA", "TX"], seed=11, pad_multiple=32,
        rate_switch_frac=0.5,
    )
    overrides = {
        "attachment_rate": jnp.full((pop.table.n_groups,), 0.35),
    }
    if caps is not None:
        years = list(cfg.model_years)
        cap_arr = np.tile(np.asarray(caps, np.float32),
                          (len(years), 1))
        overrides["nem_cap_kw"] = jnp.asarray(cap_arr)
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions,
        overrides=overrides,
    )
    sim = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                     RunConfig(sizing_iters=8), with_hourly=True)
    return sim, pop, inputs


def _state_kw_by_year(res, pop):
    """[n_years, n_states] cumulative installed kW from the collected
    per-agent outputs."""
    kw = res.agent["system_kw_cum"] * np.asarray(pop.table.mask)[None, :]
    st = np.asarray(pop.table.state_idx)
    out = np.zeros((kw.shape[0], pop.table.n_states), np.float64)
    for yi in range(kw.shape[0]):
        np.add.at(out[yi], st, kw[yi])
    return out


def _nem_allowed_per_year(pop, inputs, res):
    """Per-year count of NEM-eligible real agents, via the driver's own
    predicate with the cap gate fed LAST year's installed capacity."""
    t = pop.table
    mask = np.asarray(t.mask) > 0
    state_kw = _state_kw_by_year(res, pop)
    years = np.asarray(inputs.years)
    caps = np.asarray(inputs.nem_cap_kw)
    counts = []
    for yi, yr in enumerate(years):
        last = (np.zeros(t.n_states, np.float32) if yi == 0
                else state_kw[yi - 1].astype(np.float32))
        allowed = _nem_allowed_arrays(
            np.asarray(t.state_idx), np.asarray(t.nem_first_year),
            np.asarray(t.nem_sunset_year), np.asarray(t.nem_kw_limit),
            caps[yi], np.float32(yr), last,
        )
        counts.append(int((allowed & mask).sum()))
    return counts


@pytest.fixture(scope="module")
def capflip_run():
    # pre-run uncapped to size the caps deterministically
    sim0, pop, _ = _build()
    res0 = sim0.run(collect=True)
    final_state_kw = _state_kw_by_year(res0, pop)[-1]
    # state ids are GLOBAL; only the three populated states must adopt
    populated = np.zeros(pop.table.n_states, bool)
    populated[np.unique(
        np.asarray(pop.table.state_idx)[np.asarray(pop.table.mask) > 0]
    )] = True
    assert (final_state_kw[populated] > 0).all(), (
        "uncapped pre-run must adopt in every populated state"
    )
    caps = np.where(populated, final_state_kw * CAP_FRACTION, 1e30)

    sim, pop, inputs = _build(caps=caps)
    # the binding-cap configuration must NOT take the static all-NEM
    # shortcut — the flip exercises the mixed-metering bill path
    assert sim._net_billing, (
        "finite caps must defeat the nem_gate_never_closes proof"
    )
    res = sim.run(collect=True)
    return pop, inputs, res


def test_capflip_flips_mid_run(capflip_run):
    pop, inputs, res = capflip_run
    counts = _nem_allowed_per_year(pop, inputs, res)
    # year 0 everyone (eligible) is allowed; some later year the cap
    # binds and the allowed count DROPS — the NM -> net-billing flip
    assert counts[0] > 0
    assert min(counts) < counts[0], (
        f"NEM-allowed counts never decreased ({counts}); the fixture's "
        "caps no longer bind mid-run"
    )
    flip_year_idx = next(
        i for i in range(1, len(counts)) if counts[i] < counts[i - 1]
    )
    assert flip_year_idx >= 1   # binds strictly after the first year
    # adoption must continue after the flip (net-billing economics are
    # worse but nonzero)
    m = np.asarray(pop.table.mask)
    adopters = (res.agent["number_of_adopters"] * m[None, :]).sum(axis=1)
    assert adopters[-1] > adopters[flip_year_idx]


def test_capflip_golden_curves(capflip_run):
    pop, inputs, res = capflip_run
    m = np.asarray(pop.table.mask)
    ids = np.asarray(pop.table.agent_id)
    s = res.summary(m)
    curves = {
        "years": list(map(int, res.years)),
        "nem_allowed": _nem_allowed_per_year(pop, inputs, res),
        "adopters": [round(float(v), 4) for v in s["adopters"]],
        "system_kw_cum": [round(float(v), 3) for v in s["system_kw_cum"]],
        "batt_kwh_cum": [round(float(v), 3) for v in s["batt_kwh_cum"]],
        "cash_flow_total": [
            round(float((cf * m[:, None]).sum()), 2)
            for cf in res.agent["cash_flow"]
        ],
        "adoption_checksum": round(float(
            (res.agent["number_of_adopters"][-1] * m
             * (ids % 97 + 1)).sum()), 3),
        "state_hourly_net_mwh": [
            [round(float(v), 3) for v in row]
            for row in res.state_hourly_net_mw.sum(axis=2)
        ],
    }
    if os.environ.get("DGEN_TPU_WRITE_GOLDEN"):
        with open(GOLDEN_PATH, "w") as f:
            json.dump(curves, f, indent=1)
        pytest.skip("capflip golden curves rebased")
    if not os.path.exists(GOLDEN_PATH):
        pytest.fail(
            "golden_capflip.json missing — generate with "
            "DGEN_TPU_WRITE_GOLDEN=1 python -m pytest "
            "tests/test_golden_capflip.py"
        )
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    assert curves["years"] == golden["years"]
    assert curves["nem_allowed"] == golden["nem_allowed"], (
        "the NEM gate's per-year eligibility counts changed — the cap "
        "gate regressed"
    )
    for key in ("adopters", "system_kw_cum", "batt_kwh_cum",
                "cash_flow_total", "adoption_checksum"):
        np.testing.assert_allclose(
            curves[key], golden[key], rtol=RTOL,
            err_msg=f"{key} drifted >0.1% from the capflip golden curve",
        )
    np.testing.assert_allclose(
        curves["state_hourly_net_mwh"], golden["state_hourly_net_mwh"],
        rtol=RTOL, atol=0.05,
    )
