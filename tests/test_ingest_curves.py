"""Market-curve drop-ins + VOR ingest + the mms never-payback sentinel.

Covers VERDICT r2 items 5 (zero the 30.1 sentinel; accept
max_market_curves.csv / bass_params.csv exports of the reference's
Postgres-only tables, data_functions.py:279,370) and 6 (VOR loader for
the shipped value_of_resiliency CSVs, elec.py:287).
"""

import os

import numpy as np
import pytest

from dgen_tpu.config import PAYBACK_GRID_N, ScenarioConfig
from dgen_tpu.io import ingest
from dgen_tpu.io.reference_inputs import scenario_inputs_from_reference
from dgen_tpu.models.scenario import uniform_inputs

REF_INPUTS = "/root/reference/dgen_os/input_data"


def test_uniform_mms_sentinel_is_zero():
    """Never-payback agents (payback == 30.1 -> grid index 301) must see
    max market share exactly 0 (reference data_functions.py:399-410)."""
    cfg = ScenarioConfig(name="t", start_year=2020, end_year=2024)
    inputs = uniform_inputs(cfg, n_groups=6, n_regions=2)
    mms = np.asarray(inputs.mms_table)
    assert mms.shape[1] == PAYBACK_GRID_N
    np.testing.assert_array_equal(mms[:, -1], 0.0)
    # the curve itself is not degenerate
    assert mms[:, 0].min() > 0.5


def _write_mmc(path):
    # 1-year-resolution curves; loader interpolates to tenths
    rows = ["metric_value,sector_abbr,max_market_share,metric,business_model"]
    for sec, scale in (("res", 1.0), ("com", 0.8), ("ind", 0.6)):
        for pb in range(0, 31):
            share = scale * max(0.0, 1.0 - pb / 30.0)
            rows.append(f"{pb},{sec},{share},payback_period,host_owned")
        # decoy rows that must be filtered out
        rows.append(f"5,{sec},0.99,percent_monthly_bill_savings,host_owned")
        rows.append(f"5,{sec},0.99,payback_period,tpo")
    path.write_text("\n".join(rows) + "\n")


def test_load_max_market_curves(tmp_path):
    p = tmp_path / "max_market_curves.csv"
    _write_mmc(p)
    mms = ingest.load_max_market_curves(str(p))
    assert mms.shape == (3, PAYBACK_GRID_N)
    # interpolation to tenths: payback 4.5 sits between the 4 and 5 rows
    assert mms[0, 45] == pytest.approx(1.0 - 4.5 / 30.0, abs=1e-6)
    # decoys (0.99 at payback 5) filtered
    assert mms[0, 50] == pytest.approx(1.0 - 5.0 / 30.0, abs=1e-6)
    # sector scaling preserved
    assert mms[1, 0] == pytest.approx(0.8, abs=1e-6)
    # sentinel pinned to exactly 0
    np.testing.assert_array_equal(mms[:, -1], 0.0)


def test_load_bass_params(tmp_path):
    p = tmp_path / "bass_params.csv"
    p.write_text(
        "state_abbr,p,q,teq_yr1,sector_abbr,tech\n"
        "CA,0.003,0.5,3.0,res,solar\n"
        "CA,0.001,0.4,1.0,com,solar\n"
        "CA,0.009,0.9,9.0,res,wind\n"   # non-solar: ignored
    )
    out = ingest.load_bass_params(str(p), ["CA", "TX"])
    assert out["bass_p"].shape == (6,)
    assert out["bass_p"][0] == pytest.approx(0.003)
    assert out["bass_q"][0] == pytest.approx(0.5)
    assert out["teq_yr1"][1] == pytest.approx(1.0)
    # TX + CA/ind keep defaults
    assert out["bass_p"][3] == pytest.approx(0.0015)
    assert out["missing"] == 4


def test_load_value_of_resiliency(tmp_path):
    p = tmp_path / "vor.csv"
    p.write_text(
        "state_abbr,sector_abbr,value_of_resiliency_usd\n"
        "AL,com,2763.27\nAL,ind,57996.42\n"
    )
    vor = ingest.load_value_of_resiliency(str(p), ["AL", "AK"])
    assert vor.shape == (6,)
    assert vor[1] == pytest.approx(2763.27)
    assert vor[2] == pytest.approx(57996.42)
    assert vor[0] == 0.0 and vor[3] == 0.0  # res + AK absent


def test_scenario_wiring_dropins(tmp_path):
    """A bare input root with only the drop-ins: curves land in
    ScenarioInputs and meta flags them ingested (VERDICT r2 item 8)."""
    root = tmp_path / "input_data"
    root.mkdir()
    _write_mmc(root / "max_market_curves.csv")
    (root / "bass_params.csv").write_text(
        "state_abbr,p,q,teq_yr1,sector_abbr\nCA,0.002,0.45,2.5,res\n"
    )
    vdir = root / "value_of_resiliency"
    vdir.mkdir()
    (vdir / "vor.csv").write_text(
        "state_abbr,sector_abbr,value_of_resiliency_usd\nCA,com,1000.0\n"
    )
    cfg = ScenarioConfig(name="t", start_year=2020, end_year=2024)
    states = ["CA", "TX"]
    inputs, meta = scenario_inputs_from_reference(str(root), cfg, states)
    assert meta["market_curves"] == {"mms": "ingested", "bass": "ingested"}
    assert float(np.asarray(inputs.bass_p)[0]) == pytest.approx(0.002)
    assert float(np.asarray(inputs.mms_table)[1, 0]) == pytest.approx(0.8)
    y = len(cfg.model_years)
    assert inputs.value_of_resiliency.shape == (y, 6)
    assert float(np.asarray(inputs.value_of_resiliency)[0, 1]) == 1000.0
    # without drop-ins the meta says synthetic
    bare = tmp_path / "bare"
    bare.mkdir()
    _, meta2 = scenario_inputs_from_reference(str(bare), cfg, states)
    assert meta2["market_curves"] == {
        "mms": "synthetic_default", "bass": "synthetic_default"}


@pytest.mark.skipif(
    not os.path.isdir(os.path.join(REF_INPUTS, "value_of_resiliency")),
    reason="reference inputs not mounted",
)
def test_vor_from_shipped_reference_csv():
    """The reference's actual vor_FY20_mid.csv: AL com row carries
    2763.274124 $ (file line 2)."""
    d = os.path.join(REF_INPUTS, "value_of_resiliency")
    path = os.path.join(d, sorted(os.listdir(d))[-1])
    vor = ingest.load_value_of_resiliency(path, ["AL"])
    assert vor[1] == pytest.approx(2763.274124, rel=1e-6)
    assert vor[2] == pytest.approx(57996.42041, rel=1e-6)
