"""Agent-package roundtrip: saved + reloaded populations must produce
identical simulation results."""

import numpy as np
import pytest

import jax.numpy as jnp

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import package, synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation


@pytest.mark.slow
def test_roundtrip_identical_results(tmp_path):
    pop = synth.generate_population(70, states=["DE", "TX"], seed=4,
                                    pad_multiple=32)
    pkg = str(tmp_path / "pkg")
    package.save_population(
        pkg, pop.table, pop.profiles, synth.make_tariff_specs(), synth.STATES
    )
    loaded = package.load_population(pkg, pad_multiple=32)

    assert loaded.table.n_agents == pop.table.n_agents
    np.testing.assert_array_equal(
        np.asarray(loaded.table.state_idx), np.asarray(pop.table.state_idx))
    np.testing.assert_allclose(
        np.asarray(loaded.profiles.load), np.asarray(pop.profiles.load))
    np.testing.assert_allclose(
        np.asarray(loaded.tariffs.price), np.asarray(pop.tariffs.price))

    cfg = ScenarioConfig(name="pkg", start_year=2014, end_year=2018,
                         anchor_years=())
    inputs = scen.uniform_inputs(cfg, n_groups=pop.table.n_groups,
                                 n_regions=pop.n_regions)
    r1 = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                    RunConfig(sizing_iters=6)).run()
    r2 = Simulation(loaded.table, loaded.profiles, loaded.tariffs, inputs,
                    cfg, RunConfig(sizing_iters=6)).run()
    np.testing.assert_allclose(
        r1.agent["system_kw_cum"], r2.agent["system_kw_cum"], rtol=1e-6)
    np.testing.assert_allclose(
        r1.agent["payback_period"], r2.agent["payback_period"], atol=1e-6)


def test_incentives_roundtrip(tmp_path):
    from dgen_tpu.models.agents import build_agent_table
    from dgen_tpu.ops.cashflow import IncentiveParams

    n = 12
    rng = np.random.default_rng(3)
    inc = IncentiveParams(
        cbi_usd_p_w=rng.random((n, 2)).astype(np.float32),
        cbi_max_usd=rng.random((n, 2)).astype(np.float32) * 1e4,
        ibi_frac=rng.random((n, 2)).astype(np.float32) * 0.3,
        ibi_max_usd=rng.random((n, 2)).astype(np.float32) * 1e4,
        pbi_usd_p_kwh=rng.random((n, 2)).astype(np.float32) * 0.05,
        pbi_years=rng.integers(0, 10, (n, 2)).astype(np.int32),
    )
    pop = synth.generate_population(n, states=["DE"], seed=2, pad_multiple=8)
    t = pop.table
    keep = np.asarray(t.mask) > 0
    table = build_agent_table(
        state_idx=np.asarray(t.state_idx)[keep],
        sector_idx=np.asarray(t.sector_idx)[keep],
        region_idx=np.asarray(t.region_idx)[keep],
        tariff_idx=np.asarray(t.tariff_idx)[keep],
        load_idx=np.asarray(t.load_idx)[keep],
        cf_idx=np.asarray(t.cf_idx)[keep],
        customers_in_bin=np.asarray(t.customers_in_bin)[keep],
        load_kwh_per_customer_in_bin=np.asarray(
            t.load_kwh_per_customer_in_bin)[keep],
        developable_frac=np.asarray(t.developable_frac)[keep],
        n_states=t.n_states, incentives=inc, pad_multiple=8,
    )
    pkg = str(tmp_path / "pkg")
    package.save_population(pkg, table, pop.profiles,
                            synth.make_tariff_specs(), synth.STATES)
    loaded = package.load_population(pkg, pad_multiple=8)
    np.testing.assert_allclose(
        np.asarray(loaded.table.incentives.ibi_frac)[:n],
        np.asarray(inc.ibi_frac))
    np.testing.assert_array_equal(
        np.asarray(loaded.table.incentives.pbi_years)[:n],
        np.asarray(inc.pbi_years))


def test_version_check(tmp_path):
    pop = synth.generate_population(16, states=["DE"], seed=1, pad_multiple=8)
    pkg = str(tmp_path / "pkg")
    package.save_population(pkg, pop.table, pop.profiles,
                            synth.make_tariff_specs(), synth.STATES)
    import json, os
    meta_path = os.path.join(pkg, "meta.json")
    meta = json.load(open(meta_path))
    meta["format_version"] = 99
    json.dump(meta, open(meta_path, "w"))
    with pytest.raises(ValueError):
        package.load_population(pkg)
