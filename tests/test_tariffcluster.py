"""Tariff structural clustering (dgen_tpu.ops.tariffcluster) and the
cluster-batched sizing path: corpus analysis, cluster-major layout
round-trips, clustered-vs-unclustered parity (masked rows, the 2x4
mesh), and the one-compile-per-signature retrace contract."""

import json

import numpy as np
import pytest

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation
from dgen_tpu.ops import tariffcluster as tc
from dgen_tpu.ops.tariff import NET_BILLING, NET_METERING, compile_tariffs
from dgen_tpu.parallel.mesh import make_mesh

N = 96
STATES = ("DE", "CA", "TX")


def _bank():
    return compile_tariffs(synth.make_tariff_specs())


def make_sim(n_agents=N, states=STATES, end_year=2016, mesh=None,
             run_config=None, **kw):
    cfg = ScenarioConfig(name="tc", start_year=2014, end_year=end_year,
                         anchor_years=())
    pop = synth.generate_population(
        n_agents, states=list(states), seed=7, pad_multiple=32)
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions)
    sim = Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg,
        run_config or RunConfig(sizing_iters=8), mesh=mesh, **kw)
    return sim, pop


# ---------------------------------------------------------------------------
# corpus analysis
# ---------------------------------------------------------------------------

def test_analyze_bank_structural_keys():
    plan = tc.analyze_bank(_bank())
    # the io.synth corpus: 7 tariffs collapsing to 5 structural
    # signatures (the two flat-NEM rates share one cluster)
    assert plan.n_clusters == 5
    assert set(plan.keys) == {
        (NET_METERING, 1, 1, False),   # flat NEM x2 (incl. DG rate)
        (NET_BILLING, 1, 1, False),    # flat NB
        (NET_METERING, 1, 2, False),   # tiered NEM
        (NET_BILLING, 2, 1, False),    # TOU NB x2
        (NET_METERING, 2, 1, False),   # commercial TOU NEM
    }
    # every tariff maps into its cluster's compact bank
    assert plan.cluster_of_tariff.shape == (7,)
    for t in range(7):
        ci = plan.cluster_of_tariff[t]
        assert plan.local_of_tariff[t] < plan.banks[ci].n_tariffs


def test_compact_banks_are_tight_and_faithful():
    bank = _bank()
    plan = tc.analyze_bank(bank)
    for key, cb in zip(plan.keys, plan.banks):
        m, P, T, _hd = key
        assert cb.price.shape[1:] == (P, T)
        assert int(np.max(np.asarray(cb.metering))) == m
    # a compact bank row reproduces the source tariff's live rates
    for t in range(bank.n_tariffs):
        ci = plan.cluster_of_tariff[t]
        _m, P, T, _hd = plan.keys[ci]
        cb = plan.banks[ci]
        lt = plan.local_of_tariff[t]
        np.testing.assert_array_equal(
            np.asarray(cb.price)[lt],
            np.asarray(bank.price)[t, :P, :T])
        np.testing.assert_array_equal(
            np.asarray(cb.fixed_monthly)[lt],
            np.asarray(bank.fixed_monthly)[t])


# ---------------------------------------------------------------------------
# layout round-trip
# ---------------------------------------------------------------------------

def _random_rows(rng, n, n_tariffs):
    tariff_idx = rng.integers(0, n_tariffs, n).astype(np.int32)
    mask = (rng.random(n) < 0.8).astype(np.float32)
    return tariff_idx, mask


@pytest.mark.parametrize("n_dev", [1, 4])
def test_layout_inverse_permutation_bit_exact(n_dev):
    rng = np.random.default_rng(0)
    plan = tc.analyze_bank(_bank())
    n = 64 * n_dev
    tariff_idx, mask = _random_rows(rng, n, 7)
    layout, gather, valid, ctidx = tc.plan_layout(
        plan, tariff_idx, mask, n_dev, pad_mult=8)
    assert len(gather) == layout.n_dev * layout.local_len
    pos = tc.original_positions(gather, valid, n)

    real = mask > 0
    # dropped source rows are exactly the masked ones
    np.testing.assert_array_equal(pos >= 0, real)
    # gather then inverse-permute restores source order bit-exactly
    x = rng.standard_normal(n).astype(np.float32)
    packed = x[gather]
    np.testing.assert_array_equal(packed[pos[real]], x[real])
    # every laid-out row's tariff belongs to its segment's cluster
    # (real rows) and its compact index is in range (all rows)
    cid_rows = layout.cluster_of_rows()
    for i in range(len(gather)):
        spec = layout.clusters[cid_rows[i]]
        assert ctidx[i] < spec.n_rates
        if valid[i] > 0:
            key = plan.keys[plan.cluster_of_tariff[tariff_idx[gather[i]]]]
            assert key == (spec.metering, spec.n_periods,
                           spec.n_tiers, spec.has_demand)
    # padding filler stays in-shard (compiled gathers never cross
    # device shards)
    local = n // n_dev
    for d in range(n_dev):
        sl = gather[d * layout.local_len:(d + 1) * layout.local_len]
        assert np.all((sl >= d * local) & (sl < (d + 1) * local))


def test_layout_drops_empty_clusters_and_pads_uniformly():
    plan = tc.analyze_bank(_bank())
    # all rows on one tariff -> a single kept cluster
    tariff_idx = np.full(128, 3, dtype=np.int32)
    mask = np.ones(128, dtype=np.float32)
    layout, gather, valid, _ = tc.plan_layout(
        plan, tariff_idx, mask, 4, pad_mult=32)
    assert len(layout.clusters) == 1
    assert layout.clusters[0].n_periods == 2
    assert layout.local_len == 32
    assert valid.sum() == 128
    banks = tc.banks_for_layout(plan, layout)
    assert len(banks) == 1 and banks[0].price.shape[1:] == (2, 1)


# ---------------------------------------------------------------------------
# end-to-end parity: clustered vs unclustered
# ---------------------------------------------------------------------------

def _keyed(sim, res, field="system_kw_cum"):
    keep = np.asarray(sim.table.mask) > 0
    ids = np.asarray(sim.table.agent_id)[keep]
    order = np.argsort(ids)
    return ids[order], res.agent[field][:, keep][:, order]


def _parity(mesh):
    rc = dict(sizing_iters=8)
    sim_c, pop = make_sim(
        mesh=mesh, run_config=RunConfig(cluster_tariffs=True, **rc))
    sim_u, _ = make_sim(mesh=mesh, run_config=RunConfig(**rc))
    assert sim_c._cluster_layout is not None
    assert len(sim_c._cluster_layout.clusters) > 1
    res_c = sim_c.run()
    res_u = sim_u.run()

    for field in ("system_kw_cum", "number_of_adopters", "npv",
                  "batt_kwh_cum"):
        ids_c, v_c = _keyed(sim_c, res_c, field)
        ids_u, v_u = _keyed(sim_u, res_u, field)
        np.testing.assert_array_equal(ids_c, ids_u)
        np.testing.assert_allclose(v_c, v_u, rtol=1e-5, atol=1e-5,
                                   err_msg=field)
    # masked rows (synthetic pad + cluster filler) stay inert
    pad = np.asarray(sim_c.table.mask) == 0.0
    assert pad.any(), "fixture should have masked rows"
    assert np.all(res_c.agent["new_adopters"][:, pad] == 0.0)
    assert np.all(res_c.agent["system_kw_cum"][:, pad] == 0.0)


def test_clustered_matches_unclustered():
    _parity(mesh=None)


@pytest.mark.slow
def test_clustered_matches_unclustered_2x4_mesh():
    mesh = make_mesh(shape=(2, 4))
    assert mesh.devices.size == 8
    _parity(mesh=mesh)


def test_clustered_quarantined_rows_stay_inert():
    """Rows masked before construction (the quarantine path) are
    dropped from the cluster layout entirely — their ids never appear
    on a real row — and the survivors still match the unclustered
    quarantined oracle."""
    import dataclasses

    def build(cluster):
        cfg = ScenarioConfig(name="tcq", start_year=2014, end_year=2016,
                             anchor_years=())
        pop = synth.generate_population(
            N, states=list(STATES), seed=7, pad_multiple=32)
        mask = np.array(np.asarray(pop.table.mask))
        kill = np.nonzero(mask > 0)[0][::7]    # quarantine every 7th
        mask[kill] = 0.0
        table = dataclasses.replace(pop.table, mask=mask)
        inputs = scen.uniform_inputs(
            cfg, n_groups=table.n_groups, n_regions=pop.n_regions)
        sim = Simulation(
            table, pop.profiles, pop.tariffs, inputs, cfg,
            RunConfig(sizing_iters=8, cluster_tariffs=cluster))
        return sim, np.asarray(pop.table.agent_id)[kill]

    sim_c, killed_ids = build(True)
    sim_u, _ = build(False)
    # no real (mask > 0) row of the clustered table carries a
    # quarantined id: the layout drops them, filler slots are masked
    real = np.asarray(sim_c.table.mask) > 0
    assert not np.isin(
        np.asarray(sim_c.table.agent_id)[real], killed_ids).any()

    res_c = sim_c.run()
    res_u = sim_u.run()
    ids_c, v_c = _keyed(sim_c, res_c)
    ids_u, v_u = _keyed(sim_u, res_u)
    np.testing.assert_array_equal(ids_c, ids_u)
    assert not np.isin(ids_c, killed_ids).any()
    np.testing.assert_allclose(v_c, v_u, rtol=1e-5, atol=1e-5)


def test_clustered_steady_years_do_not_retrace():
    """One compiled program per cluster signature, then cache hits:
    guard_retrace=True fails the run if any steady year recompiles."""
    sim, _pop = make_sim(
        end_year=2020,
        run_config=RunConfig(sizing_iters=8, cluster_tariffs=True,
                             guard_retrace=True))
    res = sim.run()
    assert len(res.years) == 4


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_report_cli(capsys):
    rc = tc.main(["--report", "--agents", "256", "--seed", "3",
                  "--tariff-mix", "mixed"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["n_clusters"] >= 5
    assert rep["n_tariffs"] == 8
    assert sum(c["n_agents"] for c in rep["clusters"]) <= rep["n_agents"]
    assert 0.0 < rep["modeled_lane_savings"] < 1.0
