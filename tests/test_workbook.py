"""Scenario-workbook (.xlsm) reader: decode the reference's actual
input artifact and drive per-family trajectory selection through the
ingest (VERDICT r3 item 8 / missing item 4: the workbook's 14 named
ranges become usable without hand-exported CSVs)."""

import os

import pytest

from dgen_tpu.io import workbook as wbk

XLSM = "/root/reference/dgen_os/excel/input_sheet_final.xlsm"
XLSM_2024 = "/root/reference/dgen_os/excel/2024_input_sheet.xlsm"
INPUT_ROOT = "/root/reference/dgen_os/input_data"

needs_ref = pytest.mark.skipif(
    not os.path.exists(XLSM), reason="reference workbook not mounted")


@needs_ref
def test_read_scenario_decodes_reference_workbook():
    ws = wbk.read_scenario(XLSM)
    assert ws.name == "reference"
    assert ws.end_year == 2030
    assert ws.storage_enabled is True          # "Solar + Storage"
    assert ws.region == "Delaware"
    assert ws.markets == "Only Residential"
    assert ws.seed == 1
    assert ws.agent_file == "agent_df_base_res_de_revised"
    # every run-mapped family resolved (table_range_lkup.csv rows);
    # preset choices come from the Value column, user tables from the
    # User Defined column
    assert ws.selections["load_growth"] == "AEO2019 Reference"
    assert ws.selections["pv_prices"] == "pv_price_atb19_mid"
    assert ws.selections["financing"] == "financing_atb_FY19"
    assert set(ws.selections) == set(wbk.SELECTOR_FAMILIES.values())


@needs_ref
def test_scenario_from_workbook_builds_config():
    cfg, info = wbk.scenario_from_workbook(XLSM)
    assert cfg.end_year == 2030 and cfg.storage_enabled
    assert info["states"] == ["DE"]
    assert info["sector_weights"] == (1.0, 0.0, 0.0)
    assert info["prefer"]["elec_prices"] == "ATB19_Mid_Case_retail"


@needs_ref
def test_workbook_selections_drive_ingest_file_choice():
    """The decoded selections must actually pick the named CSVs when
    threaded through scenario_inputs_from_reference(prefer=...)."""
    from dgen_tpu.io import synth
    from dgen_tpu.io.reference_inputs import scenario_inputs_from_reference

    cfg, info = wbk.scenario_from_workbook(XLSM)
    inputs, meta = scenario_inputs_from_reference(
        INPUT_ROOT, cfg, list(synth.STATES), prefer=info["prefer"])
    files = {k: os.path.basename(v) for k, v in meta["files"].items()}
    assert files["pv_prices"] == "pv_price_atb19_mid.csv"
    assert files["financing"] == "financing_atb_FY19.csv"
    assert files["elec_prices"] == "ATB19_Mid_Case_retail.csv"
    # an FY23 selection (the 2024 workbook) picks the FY23 files
    cfg2, info2 = wbk.scenario_from_workbook(XLSM_2024)
    inputs2, meta2 = scenario_inputs_from_reference(
        INPUT_ROOT, cfg2, list(synth.STATES), prefer=info2["prefer"])
    files2 = {k: os.path.basename(v) for k, v in meta2["files"].items()}
    assert files2["financing"] == "financing_atb_FY23.csv"
    assert files2["elec_prices"] == "ATB23_Mid_Case_retail.csv"
    # unmatched preferences (Postgres-only presets like the load-growth
    # name) fall back to defaults instead of failing
    assert "load_growth" in files


@needs_ref
def test_export_drop_ins_round_trip(tmp_path):
    out = wbk.export_drop_ins(XLSM, str(tmp_path))
    assert os.path.exists(out["scenario_options"])
    assert os.path.exists(out["selections"])
    import csv
    import json

    with open(out["scenario_options"]) as f:
        rows = {r["option"]: r["value"] for r in csv.DictReader(f)}
    assert rows["Scenario Name"] == "reference"
    assert rows["Analysis End Year"] == "2030"
    with open(out["selections"]) as f:
        sel = json.load(f)
    assert sel["selections"]["pv_prices"] == "pv_price_atb19_mid"
    assert sel["agent_file"] == "agent_df_base_res_de_revised"


def test_region_and_market_resolution():
    assert wbk.resolve_states("National") is None
    assert wbk.resolve_states("Delaware") == ["DE"]
    assert wbk.resolve_states("ERCOT") == ["TX"]
    assert wbk.resolve_states("TX") == ["TX"]
    with pytest.raises(ValueError):
        wbk.resolve_states("Atlantis")
    assert wbk.resolve_sector_weights("Only Commercial") == (0.0, 1.0, 0.0)
    assert wbk.resolve_sector_weights("All") == (0.7, 0.2, 0.1)
