"""Production-throughput serving tests (ISSUE 15): the precomputed
answer surface (bit-exactness, provenance gating, staleness refusal),
the cross-replica exact result cache (hit bit-exactness, bounds,
cross-instance sharing), the mmap table store, the HTTP connection
pool, the occupancy-driven autoscaler (hysteresis unit matrix with a
stub supervisor + real stub-replica add/retire), and the L12 lint
rule.

The heavier proofs live elsewhere: the full kill+hang fleet drill with
all three serving paths armed is ``drill --serve-fleet --layers``
(slow tier + SERVE_r01.json), and the real 1 -> 2 -> 1 autoscale
round-trip is ``drill --serve-scale`` (tools/check.sh).
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from dgen_tpu.config import FleetConfig, RunConfig, ScenarioConfig, ServeConfig
from dgen_tpu.io import synth
from dgen_tpu.io.mmaptable import MmapTable, MmapTableError, write_table
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation
from dgen_tpu.resilience import faults
from dgen_tpu.serve.autoscale import Autoscaler
from dgen_tpu.serve.batcher import Microbatcher
from dgen_tpu.serve.engine import ServeEngine
from dgen_tpu.serve.resultcache import ResultCache
from dgen_tpu.serve.surface import (
    AnswerSurface,
    StaleSurfaceError,
    SurfaceError,
    build_surface,
    load_and_attach,
    provenance_key,
)

CFG = ScenarioConfig(
    name="surf-test", start_year=2014, end_year=2018, anchor_years=()
)
BUCKET = 8


@pytest.fixture(scope="module")
def engine():
    pop = synth.generate_population(64, seed=3)
    inputs = scen.uniform_inputs(
        CFG, n_groups=pop.table.n_groups, n_regions=pop.n_regions
    )
    sim = Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, CFG, RunConfig(),
        econ_years=4,
    )
    eng = ServeEngine(sim)
    eng.warmup([BUCKET])
    return eng


@pytest.fixture(scope="module")
def surface_dir(engine, tmp_path_factory):
    d = str(tmp_path_factory.mktemp("surface"))
    build_surface(engine, d, BUCKET)
    return d


def _fresh_engine(engine):
    """A second engine over the same sim (fixtures must not keep
    attached layers across tests)."""
    return ServeEngine(engine.sim)


# ---------------------------------------------------------------------------
# io.mmaptable
# ---------------------------------------------------------------------------

def test_mmaptable_roundtrip_truncation_and_tamper(tmp_path):
    d = str(tmp_path / "t")
    cols = {
        "a": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        "b": np.arange(7, dtype=np.int32),
    }
    header = write_table(d, cols, meta={"k": "v"})
    t = MmapTable(d)
    t.verify()
    assert t.meta == {"k": "v"}
    for name, arr in cols.items():
        np.testing.assert_array_equal(t.columns[name], arr)
        assert t.columns[name].dtype == arr.dtype
    # identical columns -> identical content hash, meta-independent
    d2 = str(tmp_path / "t2")
    assert write_table(d2, cols, meta={"other": 1})["content_hash"] \
        == header["content_hash"]
    # truncation is refused at open
    bin_path = os.path.join(d, "table.bin")
    blob = open(bin_path, "rb").read()
    with open(bin_path, "wb") as f:   # deliberate damage, not an artifact
        f.write(blob[: len(blob) // 2])
    with pytest.raises(MmapTableError, match="truncated"):
        MmapTable(d)
    # tamper (same length) passes the open but fails verify()
    with open(bin_path, "wb") as f:
        f.write(blob[:-1] + bytes([blob[-1] ^ 0xFF]))
    with pytest.raises(MmapTableError, match="content hash mismatch"):
        MmapTable(d).verify()
    # missing header is refused with the reason named
    os.remove(os.path.join(d2, "table.json"))
    with pytest.raises(MmapTableError, match="missing header"):
        MmapTable(d2)


# ---------------------------------------------------------------------------
# Answer surface: bit-exactness + provenance gating
# ---------------------------------------------------------------------------

def test_surface_is_bit_exact_vs_engine_per_bucket_shape(
        engine, surface_dir):
    """Every surface answer equals the engine's answer at the
    surface's build bucket — array_equal, every field, every year."""
    surf = AnswerSurface.load(surface_dir, engine)
    rng = np.random.default_rng(0)
    for yi in range(len(engine.years)):
        rows = rng.choice(128, size=5, replace=False).astype(np.int32)
        got = surf.lookup(rows, yi)
        want = engine.query_rows(rows, yi, bucket=BUCKET)
        for f, v in got.items():
            np.testing.assert_array_equal(
                v, want[f],
                err_msg=f"surface {f} differs at year_idx {yi}",
            )
    assert surf.stats()["hits"] == len(engine.years)


def test_surface_staleness_is_refused_with_named_reason(
        engine, surface_dir, tmp_path):
    """A surface built under a different config_hash/git_sha/
    population is refused naming the mismatching field — never served
    stale."""
    import shutil

    for field, value in (
        ("config_hash", "deadbeef0000"),
        ("git_sha", "000000000000"),
        ("population_sha", "feedface"),
        ("n_rows", 999),
    ):
        d = str(tmp_path / f"stale-{field}")
        shutil.copytree(surface_dir, d)
        hpath = os.path.join(d, "table.json")
        header = json.load(open(hpath))
        header["meta"]["provenance"][field] = value
        with open(hpath, "w") as f:   # deliberate tamper, not an artifact
            json.dump(header, f)
        with pytest.raises(StaleSurfaceError, match=field):
            AnswerSurface.load(d, engine)
    # a truncated data file is refused as unusable, not served
    d = str(tmp_path / "torn")
    shutil.copytree(surface_dir, d)
    bin_path = os.path.join(d, "table.bin")
    blob = open(bin_path, "rb").read()
    with open(bin_path, "wb") as f:
        f.write(blob[: len(blob) // 3])
    with pytest.raises(SurfaceError, match="truncated"):
        AnswerSurface.load(d, engine)


def test_surface_refusal_degrades_to_engine_path(engine, surface_dir):
    """load_and_attach never kills boot: an injected load fault (the
    surface_load drill site) leaves the engine serving, with the
    refusal reason visible in serve_stats."""
    eng = _fresh_engine(engine)
    with faults.injected("surface_load:error"):
        reason = load_and_attach(eng, surface_dir)
    assert reason is not None and "surface_load" in reason
    assert eng.surface is None
    assert eng.serve_stats()["surface_refused"] == reason
    # and the engine path still answers
    out = eng.query_rows(np.arange(3, dtype=np.int32), 0, bucket=BUCKET)
    assert out["npv"].shape == (3,)
    # a clean retry attaches
    assert load_and_attach(eng, surface_dir) is None
    assert eng.surface is not None


def test_batcher_surface_fast_path_and_counters(engine, surface_dir):
    """Zero-override queries for covered years answer from the mmap
    without queueing; override queries fall through to the engine."""
    eng = _fresh_engine(engine)
    load_and_attach(eng, surface_dir)
    cfg = ServeConfig(max_batch=BUCKET, min_bucket=BUCKET,
                      max_wait_ms=2.0, port=0)
    bat = Microbatcher(eng, cfg)
    try:
        ids = [3, 9]
        rows = eng.rows_for(ids)
        got = bat.query(ids, year=2016, timeout=60.0)
        want = eng.surface.lookup(rows, eng.year_index(2016))
        for f in got:
            np.testing.assert_array_equal(got[f], want[f])
        stats = bat.stats()
        assert stats["surface_hits"] == 1
        assert stats["batches"] == 0          # never touched the engine
        assert stats["surface"]["hits"] >= 1
        # an override query is NOT surface-eligible: engine path
        bat.query(ids, year=2016,
                  overrides={"scale": {"itc_fraction": 0.5}},
                  timeout=60.0)
        stats = bat.stats()
        assert stats["surface_hits"] == 1 and stats["batches"] == 1
    finally:
        bat.close()


# ---------------------------------------------------------------------------
# Result cache
# ---------------------------------------------------------------------------

def test_result_cache_hits_are_bit_exact_and_shared(
        engine, tmp_path):
    eng = _fresh_engine(engine)
    cache = ResultCache(str(tmp_path / "rc"),
                        provenance_key(eng), max_entries=64)
    eng.attach_result_cache(cache)
    rows = np.array([2, 7, 11], dtype=np.int32)
    key = "ovr-key"
    first = eng.query_rows(rows, 1, bucket=BUCKET, key=key)
    assert cache.stats()["stores"] == 1
    second = eng.query_rows(rows, 1, bucket=BUCKET, key=key)
    assert cache.stats()["hits"] == 1
    for f in first:
        np.testing.assert_array_equal(first[f], second[f], err_msg=f)
    # a SECOND cache instance over the same directory (another replica
    # process) hits the same entry — the cross-replica property
    eng2 = _fresh_engine(engine)
    cache2 = ResultCache(str(tmp_path / "rc"),
                         provenance_key(eng2), max_entries=64)
    eng2.attach_result_cache(cache2)
    third = eng2.query_rows(rows, 1, bucket=BUCKET, key=key)
    assert cache2.stats() == dict(cache2.stats(), hits=1, misses=0)
    for f in first:
        np.testing.assert_array_equal(first[f], third[f], err_msg=f)
    # a different provenance key NEVER aliases (a deploy invalidates)
    cache3 = ResultCache(str(tmp_path / "rc"), "other-version",
                         max_entries=64)
    assert cache3.get(cache3.key(1, key, BUCKET, rows)) is None
    # key=None (the oracle path) bypasses the cache entirely
    eng.query_rows(rows, 1, bucket=BUCKET)
    assert cache.stats()["stores"] == 1


def test_result_cache_is_bounded_lru(tmp_path):
    cache = ResultCache(str(tmp_path / "rc"), "pk", max_entries=3)
    keys = []
    for i in range(5):
        k = cache.key(0, f"k{i}", 4, np.arange(2))
        cache.put(k, {"npv": np.full(2, float(i), np.float32)})
        keys.append(k)
        time.sleep(0.01)   # distinct mtimes order the LRU scan
    assert cache.stats()["evictions"] == 2
    files = [n for n in os.listdir(cache.dir) if n.endswith(".npz")]
    assert len(files) == 3
    # oldest two evicted, newest three alive
    assert cache.get(keys[0]) is None and cache.get(keys[1]) is None
    got = cache.get(keys[4])
    np.testing.assert_array_equal(got["npv"], np.full(2, 4.0, np.float32))
    # a damaged entry is a miss, never a crash
    path = cache._path(keys[4])
    with open(path, "wb") as f:   # deliberate damage, not an artifact
        f.write(b"not an npz")
    assert cache.get(keys[4]) is None


# ---------------------------------------------------------------------------
# HTTP connection pool
# ---------------------------------------------------------------------------

def test_http_pool_reuses_keepalive_connections():
    import http.server

    from dgen_tpu.serve.fleet import HTTPPool

    class H(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            blob = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    port = srv.server_address[1]
    pool = HTTPPool(max_idle=4)
    try:
        for _ in range(4):
            status, blob, _h = pool.request(port, "/x", timeout=10.0)
            assert status == 200 and json.loads(blob) == {"ok": True}
        stats = pool.stats()
        # one handshake, three reuses: the keep-alive win
        assert stats["created"] == 1 and stats["reused"] == 3
        assert stats["idle"] == 1

        # a stale pooled socket (server idle-timed it between uses) is
        # retried ONCE on a fresh connection, transparently: poison
        # the pooled slot with a connection that fails like a
        # server-side close (BadStatusLine on the response read)
        import http.client

        class _Stale:
            sock = None
            timeout = None

            def request(self, *a, **k):
                raise http.client.BadStatusLine("stale socket")

            def close(self):
                pass

        pool._idle[("127.0.0.1", port)] = [_Stale()]
        status, blob, _h = pool.request(port, "/x", timeout=10.0)
        assert status == 200 and json.loads(blob) == {"ok": True}
        assert pool.stats()["stale_retries"] == 1

        # a TIMEOUT on a reused connection is NOT retried: the request
        # was delivered and the replica is hanging — retrying would
        # double the time-to-failover and the hung replica's queue
        class _Hung(_Stale):
            def request(self, *a, **k):
                raise TimeoutError("timed out")

        pool._idle[("127.0.0.1", port)] = [_Hung()]
        with pytest.raises(TimeoutError):
            pool.request(port, "/x", timeout=10.0)
        assert pool.stats()["stale_retries"] == 1   # unchanged

        # a FRESH connection's failure propagates (that IS a replica
        # failure the breaker must see) — no infinite retry loop
        with pytest.raises((OSError, http.client.HTTPException)):
            pool.request(port + 1 if port < 65000 else port - 1, "/x",
                         timeout=0.5)

        pool.drop(port)
        assert pool.stats()["idle"] == 0
    finally:
        pool.close()
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# Autoscaler: hysteresis unit matrix (fake clock, stub supervisor)
# ---------------------------------------------------------------------------

class _Slot:
    def __init__(self, index, state="ready"):
        self.index = index
        self.state = state
        self.deaths = []


class _FakeSup:
    """The supervisor surface the autoscaler touches, no processes."""

    def __init__(self, n=1):
        self.replicas = [_Slot(i) for i in range(n)]
        self.events = []
        self._lock = threading.RLock()

    def _event(self, index, event, **detail):
        self.events.append({"replica": index, "event": event, **detail})

    def live_count(self):
        return sum(1 for h in self.replicas
                   if h.state not in ("stopped", "failed"))

    def add_replica(self):
        self.replicas.append(_Slot(len(self.replicas)))

    def retire_replica(self, index, drain_timeout_s=30.0):
        self.replicas[index].state = "stopped"
        return True


def _scaler(sup, sig, clock, **cfg_kw):
    kw = dict(
        n_replicas=1, port=0, autoscale=True,
        min_replicas=1, max_replicas=3,
        scale_up_queue_frac=0.5, scale_up_occupancy=0.8,
        scale_up_sustain_s=1.0,
        scale_down_queue_frac=0.05, scale_down_occupancy=0.2,
        scale_down_sustain_s=2.0,
        scale_cooldown_s=5.0, scale_interval_s=0.1,
    )
    kw.update(cfg_kw)
    return Autoscaler(sup, sig, FleetConfig(**kw),
                      clock=lambda: clock[0])


def test_autoscaler_hysteresis_matrix():
    clock = [0.0]
    sig = {"queue_frac": 0.0, "occupancy": 0.0}
    sup = _FakeSup(1)
    sc = _scaler(sup, lambda: dict(sig), clock)

    # idle at min: nothing happens, ever
    for t in (0.0, 5.0, 50.0):
        clock[0] = t
        assert sc.tick() is None
    assert sup.live_count() == 1

    # a pressure BLIP shorter than the sustain window does not scale
    sig.update(queue_frac=0.9)
    clock[0] = 100.0
    assert sc.tick() is None          # window opens
    clock[0] = 100.5
    assert sc.tick() is None          # sustained 0.5 < 1.0
    sig.update(queue_frac=0.0, occupancy=0.0)
    clock[0] = 101.0
    assert sc.tick() is None          # blip over: window reset
    sig.update(queue_frac=0.9)
    clock[0] = 101.5
    assert sc.tick() is None          # NEW window — not 1.5s of the old
    # sustained pressure scales up exactly once per window+cooldown
    clock[0] = 102.6
    assert sc.tick() == "up"
    assert sup.live_count() == 2
    # cooldown blocks an immediate second scale-up; the pressure
    # window keeps accumulating through it, so pressure SUSTAINED
    # through the cooldown scales again as soon as it expires
    clock[0] = 104.0
    assert sc.tick() is None          # in cooldown; window reopens here
    clock[0] = 106.0
    assert sc.tick() is None          # still in cooldown (until 107.6)
    clock[0] = 107.8
    assert sc.tick() == "up"          # cooldown over, 3.8s sustained
    assert sup.live_count() == 3
    # max bound: pressure forever, never beyond max_replicas
    clock[0] += 100.0
    assert sc.tick() is None
    clock[0] += 10.0
    assert sc.tick() is None
    assert sup.live_count() == 3

    # occupancy alone (queue empty) also counts as pressure
    clock2 = [0.0]
    sup2 = _FakeSup(1)
    sc2 = _scaler(sup2, lambda: {"queue_frac": 0.0, "occupancy": 0.95},
                  clock2)
    sc2.tick()
    clock2[0] = 1.1
    assert sc2.tick() == "up"

    # idle sustained scales down, LIFO victim, min bound respected
    sig.update(queue_frac=0.0, occupancy=0.0)
    clock[0] += 100.0
    assert sc.tick() is None          # idle window opens
    clock[0] += 2.1
    assert sc.tick() == "down"
    assert sup.replicas[2].state == "stopped"
    assert sup.live_count() == 2
    clock[0] += 100.0
    sc.tick()
    clock[0] += 2.1
    assert sc.tick() == "down"
    assert sup.live_count() == 1
    clock[0] += 100.0
    sc.tick()
    clock[0] += 2.1
    assert sc.tick() is None          # min bound holds
    assert sup.live_count() == 1
    # every action is in the ledger
    ups = [e for e in sup.events if e["event"] == "autoscale_up"]
    downs = [e for e in sup.events if e["event"] == "autoscale_down"]
    assert len(ups) == sc.n_scale_up == 2
    assert len(downs) == sc.n_scale_down == 2


def test_autoscaler_holds_without_fresh_signal_and_between_bands():
    clock = [0.0]
    out = [{"queue_frac": 0.9, "occupancy": 0.9}]
    sup = _FakeSup(1)
    sc = _scaler(sup, lambda: out[0], clock)
    sc.tick()                          # pressure window opens at t=0
    out[0] = None                      # telemetry gap
    clock[0] = 0.5
    assert sc.tick() is None
    out[0] = {"queue_frac": 0.9, "occupancy": 0.9}
    clock[0] = 1.1
    # the gap RESET the window: 1.1s since t=0 but the window restarts
    assert sc.tick() is None
    clock[0] = 2.2
    assert sc.tick() == "up"
    # between the bands (not hot, not idle): both windows reset
    out[0] = {"queue_frac": 0.3, "occupancy": 0.5}
    clock[0] = 100.0
    assert sc.tick() is None
    assert sc._pressure_since is None and sc._idle_since is None


def test_fleet_config_autoscale_validation():
    with pytest.raises(ValueError, match="hysteresis"):
        FleetConfig(autoscale=True, scale_up_queue_frac=0.2,
                    scale_down_queue_frac=0.3)
    with pytest.raises(ValueError, match="boot size"):
        FleetConfig(autoscale=True, n_replicas=5, min_replicas=1,
                    max_replicas=4)
    with pytest.raises(ValueError, match="max_replicas"):
        FleetConfig(min_replicas=3, max_replicas=2)
    cfg = FleetConfig(autoscale=True, n_replicas=2, min_replicas=1,
                      max_replicas=4)
    assert cfg.autoscale and cfg.max_replicas == 4


# ---------------------------------------------------------------------------
# Supervisor elasticity with real stub replicas (no jax)
# ---------------------------------------------------------------------------

_MINI_STUB = '''
import http.server, json, os, signal, sys

portfile = sys.argv[1]


class H(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_GET(self):
        blob = json.dumps({"ready": True}).encode()
        self.send_response(200 if self.path == "/readyz" else 200)
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def log_message(self, *a):
        pass


srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
signal.signal(signal.SIGTERM, lambda *a: os._exit(0))
tmp = portfile + ".tmp"
with open(tmp, "w") as f:
    json.dump({"pid": os.getpid(), "port": srv.server_address[1]}, f)
os.replace(tmp, portfile)
srv.serve_forever()
'''


def test_supervisor_add_and_retire_replica(tmp_path):
    from dgen_tpu.serve.fleet import STOPPED, ReplicaSupervisor

    script = tmp_path / "mini_stub.py"
    script.write_text(_MINI_STUB)

    def cmd_for(index, portfile):
        return [sys.executable, str(script), portfile]

    cfg = FleetConfig(n_replicas=1, port=0, poll_interval_s=0.02,
                      boot_timeout_s=30.0)
    sup = ReplicaSupervisor(cmd_for, cfg,
                            fleet_dir=str(tmp_path / "fleet")).start()
    try:
        assert sup.wait_ready(n=1, timeout=20.0)
        assert sup.live_count() == 1
        # grow: the new slot goes through the normal readiness gate
        h = sup.add_replica()
        assert h.index == 1
        assert sup.wait_ready(n=2, timeout=20.0)
        assert sup.live_count() == 2
        # shrink: SIGTERM drain, STOPPED, reaped, never restarted,
        # never counted as a death
        assert sup.retire_replica(1)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if sup.replicas[1].proc.poll() is not None:
                break
            time.sleep(0.05)
        assert sup.replicas[1].proc.poll() == 0
        time.sleep(0.2)   # several monitor ticks
        assert sup.replicas[1].state == STOPPED
        assert not sup.replicas[1].deaths
        assert sup.live_count() == 1
        assert len(sup.ready_handles()) == 1
        # retiring a stopped slot is a no-op
        assert not sup.retire_replica(1)
        events = [e["event"] for e in sup.events]
        assert "scale_up_spawned" in events
        assert "scale_down_retired" in events
    finally:
        sup.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# dgenlint L12
# ---------------------------------------------------------------------------

def test_l12_flags_unbounded_request_caches_and_supports_suppression():
    from dgen_tpu.lint import lint_paths, lint_source

    fixture = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "lint", "bad_l12_unbounded_cache.py",
    )
    hits = [f for f in lint_paths([fixture]) if f.rule == "L12"]
    # the dict store + the list append in QueryHandler; the bounded
    # twin (popitem + deque(maxlen)) is clean
    assert len(hits) == 2
    assert {h.line for h in hits} == {22, 26}

    src = (
        "class C:\n"
        "    def handle_query(self, body):\n"
        "        self.memo[body['k']] = 1   # dgenlint: disable=L12\n"
    )
    assert [f for f in lint_source(src) if f.rule == "L12"] == []

    # non-request methods accumulate freely (batch drivers etc.)
    src_ok = (
        "class C:\n"
        "    def record_year(self, year, outs):\n"
        "        self.results[year] = outs\n"
    )
    assert [f for f in lint_source(src_ok) if f.rule == "L12"] == []

    # constant keys are configuration, not request data
    src_const = (
        "class C:\n"
        "    def handle_query(self, body):\n"
        "        self.slots['latest'] = body\n"
    )
    assert [f for f in lint_source(src_const) if f.rule == "L12"] == []


def test_serve_layer_is_l12_clean():
    """The enforcement contract tools/check.sh gates on: the serve
    layer's own caches (override LRU, result cache, scrape maps,
    breaker map) are all bounded or pruned."""
    from dgen_tpu.lint import lint_paths

    root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "dgen_tpu", "serve",
    )
    assert lint_paths([root], select=["L12"]) == []
