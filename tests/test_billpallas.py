"""Bucket-sums engine parity: the XLA formulation must reproduce the
direct hourly bill oracle; on TPU the Pallas kernel must match the XLA
formulation (run ``DGEN_TPU_TESTS=1 pytest tests/test_billpallas.py``
on TPU hardware — the default run pins the virtual CPU platform and
skips the kernel test)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.io import synth
from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops import billpallas as bp
from dgen_tpu.ops import sizing
from dgen_tpu.ops.cashflow import FinanceParams


@pytest.fixture(scope="module")
def setup():
    n = 24
    pop = synth.generate_population(n, seed=3, pad_multiple=8)
    t = pop.table
    load = pop.profiles.load[t.load_idx] * t.load_kwh_per_customer_in_bin[:, None]
    gen = pop.profiles.solar_cf[t.cf_idx] * sizing.INV_EFF
    ts = pop.profiles.wholesale[t.region_idx]
    at = jax.vmap(lambda k: bill_ops.gather_tariff(pop.tariffs, k))(t.tariff_idx)
    return pop, load, gen, ts, at


def test_bills_from_sums_matches_annual_bill(setup):
    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(0)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 7))).astype(np.float32)
    )
    s, i, c = bp.bucket_sums(load, gen, sell, bucket, scales, b, impl="xla")
    bills = np.asarray(bp.bills_from_sums(s, i, c, at, p))

    for y in range(scales.shape[1]):
        ref = np.asarray(jax.vmap(
            lambda l, g, tt, sl, sc: bill_ops.annual_bill(l - sc * g, tt, sl, p)
        )(load, gen, at, ts, scales[:, y]))
        np.testing.assert_allclose(bills[:, y], ref, rtol=5e-4, atol=1.0)


def test_zero_scale_is_no_system_bill(setup):
    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    zeros = jnp.zeros((load.shape[0], 1), jnp.float32)
    s, i, c = bp.bucket_sums(load, gen, sell, bucket, zeros, 12 * p, impl="xla")
    bills = np.asarray(bp.bills_from_sums(s, i, c, at, p))[:, 0]
    ref = np.asarray(jax.vmap(
        lambda l, tt, sl: bill_ops.annual_bill(l, tt, sl, p)
    )(load, at, ts))
    np.testing.assert_allclose(bills, ref, rtol=1e-5, atol=0.1)
    # zero scale exports nothing
    assert np.allclose(np.asarray(c)[:, 0], 0.0, atol=1e-3)


def test_sharded_engine_matches_unsharded(setup):
    """The shard_map wrapper (what keeps the Pallas kernel live on
    multi-chip meshes) must be a no-op on results: xla twin on the
    8-device virtual mesh vs plain."""
    from dgen_tpu.parallel.mesh import make_mesh

    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(5)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 6))).astype(np.float32)
    )
    mesh = make_mesh()
    assert mesh.devices.size == 8
    plain = bp.bucket_sums(load, gen, sell, bucket, scales, b, impl="xla")
    sharded = bp.bucket_sums(
        load, gen, sell, bucket, scales, b, impl="xla", mesh=mesh
    )
    for a, bb in zip(plain, sharded):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-5, atol=1e-3
        )
    i_plain = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla")
    i_sharded = bp.import_sums(
        load, gen, sell, bucket, scales, b, impl="xla", mesh=mesh
    )
    for a, bb in zip(i_plain, i_sharded):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-5, atol=1e-3
        )
    # the fused rate-switch pair engine under the mesh (n_in=7 shard
    # plumbing on the pallas path; the xla fallback shards two passes)
    p_plain = bp.import_sums_pair(
        load, gen, sell, bucket, sell, bucket, scales, b, impl="xla")
    p_sharded = bp.import_sums_pair(
        load, gen, sell, bucket, sell, bucket, scales, b, impl="xla",
        mesh=mesh)
    for a, bb in zip(p_plain, p_sharded):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-5, atol=1e-3
        )


@pytest.mark.tpu_hw
@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas kernel parity needs a TPU (set DGEN_TPU_TESTS=1)",
)
def test_pallas_matches_xla_on_tpu(setup):
    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(7)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 9))).astype(np.float32)
    )
    for fn in (bp.bucket_sums, lambda *a, impl: bp.import_sums(*a, impl=impl)):
        outs_x = fn(load, gen, sell, bucket, scales, b, impl="xla")
        # the month-blocked default AND the retained round-3 dot engine
        # must both agree with the XLA twin
        for impl in ("pallas", "pallas_dot"):
            outs_p = fn(load, gen, sell, bucket, scales, b, impl=impl)
            for op, ox in zip(outs_p, outs_x):
                # tolerance covers the engines' different f32
                # accumulation orders + XLA's default TPU matmul
                # precision (~1.5e-3 rel observed); layout/bucketing
                # regressions are orders larger
                np.testing.assert_allclose(
                    np.asarray(op), np.asarray(ox), rtol=5e-3, atol=2.0,
                    err_msg=impl,
                )


@pytest.mark.slow
def test_fast_sizing_matches_oracle(setup):
    pop, load, gen, ts, at = setup
    t = pop.table
    n = t.n_agents
    f32 = jnp.float32
    fin = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n,)), FinanceParams.example()
    )
    envs = sizing.AgentEconInputs(
        load=load, gen_per_kw=pop.profiles.solar_cf[t.cf_idx], ts_sell=ts,
        tariff=at, tariff_w=None, fin=fin, inc=t.incentives,
        load_kwh_per_customer=t.load_kwh_per_customer_in_bin,
        elec_price_escalator=jnp.full(n, 0.005, f32),
        pv_degradation=jnp.full(n, 0.005, f32),
        system_capex_per_kw=jnp.full(n, 2500.0, f32),
        system_capex_per_kw_combined=jnp.full(n, 2600.0, f32),
        batt_capex_per_kwh_combined=jnp.full(n, 800.0, f32),
        cap_cost_multiplier=jnp.ones(n, f32),
        value_of_resiliency_usd=jnp.zeros(n, f32),
        one_time_charge=jnp.zeros(n, f32),
    )
    p = pop.tariffs.max_periods
    rf = sizing.size_agents(envs, n_periods=p, n_years=25, n_iters=10, fast=True)
    rs = sizing.size_agents(envs, n_periods=p, n_years=25, n_iters=10, fast=False)
    # kW* tolerance covers grid-vs-golden-section discretization
    # (2/n_iters^2 of the bracket), not engine disagreement
    np.testing.assert_allclose(
        np.asarray(rf.system_kw), np.asarray(rs.system_kw), rtol=6e-3)
    # NPV is a small difference of large bill flows; bound the error
    # relative to the flow magnitude (f32 cancellation scale), not the
    # net NPV
    flow_scale = 25.0 * np.asarray(rs.first_year_bill_without_system)
    dnpv = np.abs(np.asarray(rf.npv) - np.asarray(rs.npv))
    assert np.all(
        dnpv <= 2e-3 * np.abs(np.asarray(rs.npv)) + 1e-3 * flow_scale + 10.0
    ), f"max npv mismatch {dnpv.max()}"
    np.testing.assert_allclose(
        np.asarray(rf.payback_period), np.asarray(rs.payback_period), atol=0.21)
    # batt bills inherit the kW* grid discretization (bill ~ kW for
    # export-dominated agents); exact engine parity is asserted in
    # test_bills_from_sums_matches_annual_bill
    np.testing.assert_allclose(
        np.asarray(rf.first_year_bill_with_batt),
        np.asarray(rs.first_year_bill_with_batt), rtol=2e-2, atol=5.0)


@pytest.mark.tpu_hw
@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas kernel parity needs a TPU (set DGEN_TPU_TESTS=1)",
)
def test_month_kernel_period_count_corners():
    """The month kernel's P-1 mask + subtraction structure must hold at
    every TOU period count the tariff layer produces — including P=1
    (flat-only populations: zero masks, every bucket the month total)
    and the 5-period upper range."""
    rng_key = jax.random.key(0)
    for p_count in (1, 3, 5):
        n, h, r = 64, 8760, 17
        ks = jax.random.split(jax.random.fold_in(rng_key, p_count), 5)
        load = jax.random.uniform(ks[0], (n, h), jnp.float32, 0.2, 3.0)
        gen = jax.random.uniform(ks[1], (n, h), jnp.float32, 0.0, 1.0)
        sell = jax.random.uniform(ks[2], (n, h), jnp.float32, 0.02, 0.08)
        period = jax.random.randint(ks[3], (n, h), 0, p_count, jnp.int32)
        bucket = bp.hourly_bucket_ids(period, p_count)
        scales = jax.random.uniform(ks[4], (n, r), jnp.float32, 0.1, 6.0)
        nb = 12 * p_count
        for fn in (bp.import_sums, bp.bucket_sums):
            outs_p = fn(load, gen, sell, bucket, scales, nb, impl="pallas")
            outs_x = fn(load, gen, sell, bucket, scales, nb, impl="xla")
            for op, ox in zip(outs_p, outs_x):
                a, b = np.asarray(op), np.asarray(ox)
                scale = max(float(np.max(np.abs(b))), 1.0)
                assert float(np.max(np.abs(a - b))) / scale < 5e-3, (
                    p_count, fn.__name__)
        # the fused rate-switch pair engine shares the net grid but must
        # match two independent single-tariff passes
        sell_b = jax.random.uniform(
            jax.random.fold_in(ks[4], 1), (n, h), jnp.float32, 0.01, 0.05)
        period_b = jax.random.randint(
            jax.random.fold_in(ks[3], 1), (n, h), 0, p_count, jnp.int32)
        bucket_b = bp.hourly_bucket_ids(period_b, p_count)
        pair = bp.import_sums_pair(
            load, gen, sell, bucket, sell_b, bucket_b, scales, nb,
            impl="pallas")
        ref_a = bp.import_sums(load, gen, sell, bucket, scales, nb,
                               impl="xla")
        ref_b = bp.import_sums(load, gen, sell_b, bucket_b, scales, nb,
                               impl="xla")
        for got, want in zip(pair, ref_a + ref_b):
            a, b = np.asarray(got), np.asarray(want)
            scale = max(float(np.max(np.abs(b))), 1.0)
            assert float(np.max(np.abs(a - b))) / scale < 5e-3, p_count


# --------------------------------------------------------------------------
# Daylight-compacted layout (billpallas.DaylightLayout): the candidate
# kernels touch only the union-daylight lanes; night bucket sums are
# candidate-independent and added back. Parity vs the full-hour oracle
# is the correctness contract (ISSUE 2 acceptance: <= 1e-5 relative).
# --------------------------------------------------------------------------

def _layout(setup):
    pop = setup[0]
    lay = bp.daylight_layout(np.asarray(pop.profiles.solar_cf))
    assert lay is not None, "synth solar bank should have night hours"
    return lay


def test_daylight_layout_partitions_the_hour_axis(setup):
    from dgen_tpu.ops.tariff import hour_month_map

    pop = setup[0]
    lay = _layout(setup)
    assert lay.n_lanes < bp.H_MONTHS
    assert all(s % 128 == 0 and s >= 128 for s in lay.seg_lens)
    idx = np.asarray(lay.idx)
    valid = np.asarray(lay.valid)
    night = np.asarray(lay.night)
    day_hours = idx[valid > 0]
    # every hour is exactly day-lane-or-night (no dupes, no gaps)
    assert len(np.unique(day_hours)) == len(day_hours)
    covered = np.zeros(8760, bool)
    covered[day_hours] = True
    np.testing.assert_array_equal(covered, night == 0.0)
    # the compaction premise: the bank is zero on every night hour
    bank = np.asarray(pop.profiles.solar_cf)
    assert np.all(bank[:, night > 0] == 0.0)
    # positional month map holds at every lane — month BOUNDARY hours
    # (hour 743/744, 1415/1416, ...) must land in their own month's
    # segment, where the kernel's static slicing assigns them
    hm = np.asarray(hour_month_map())
    month_of_lane = np.repeat(np.arange(12), np.asarray(lay.seg_lens))
    lanes = np.nonzero(valid > 0)[0]
    np.testing.assert_array_equal(hm[idx[lanes]], month_of_lane[lanes])


def test_daylight_import_sums_parity(setup):
    """Compacted XLA twin vs the full-hour path: identical totals to
    <= 1e-5 relative, across mixed NEM/net-billing tariffs, with
    all-zero-gen agents in the population."""
    pop, load, gen, ts, at = setup
    lay = _layout(setup)
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    # agents whose gen is all-zero (never-generating rows must price
    # identically: their entire year is "night-like" load)
    gen = gen.at[:3].set(0.0)
    rng = np.random.default_rng(0)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 7))).astype(np.float32)
    )

    full = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla")
    comp = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla",
                          layout=lay)
    for a, c in zip(full, comp):
        a, c = np.asarray(a), np.asarray(c)
        scale = max(float(np.max(np.abs(a))), 1.0)
        assert float(np.max(np.abs(a - c))) / scale < 1e-5

    # the fused pair engine, compacted, on a second tariff structure
    at2 = jax.vmap(lambda k: bill_ops.gather_tariff(pop.tariffs, k))(
        pop.table.tariff_switch_idx)
    bucket2 = bp.hourly_bucket_ids(at2.hour_period, p)
    sell2 = bp.sell_rate_hourly(at2, ts)
    full_p = bp.import_sums_pair(
        load, gen, sell, bucket, sell2, bucket2, scales, b, impl="xla")
    comp_p = bp.import_sums_pair(
        load, gen, sell, bucket, sell2, bucket2, scales, b, impl="xla",
        layout=lay)
    for a, c in zip(full_p, comp_p):
        a, c = np.asarray(a), np.asarray(c)
        scale = max(float(np.max(np.abs(a))), 1.0)
        assert float(np.max(np.abs(a - c))) / scale < 1e-5


def test_daylight_sharded_matches_unsharded(setup):
    """The layout's idx/valid/night ride into shard_map as REPLICATED
    inputs (n_repl plumbing) — results must not depend on the mesh."""
    from dgen_tpu.parallel.mesh import make_mesh

    pop, load, gen, ts, at = setup
    lay = _layout(setup)
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(5)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 6))).astype(np.float32)
    )
    mesh = make_mesh()
    plain = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla",
                           layout=lay)
    sharded = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla",
                             mesh=mesh, layout=lay)
    for a, c in zip(plain, sharded):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(c), rtol=1e-5, atol=1e-3)


def test_daylight_sizing_parity(setup):
    """size_agents with a DaylightLayout must reproduce the full-hour
    search: same sized systems, bills, and NPV (the layout only
    re-associates f32 sums)."""
    pop, load, gen, ts, at = setup
    t = pop.table
    n = t.n_agents
    f32 = jnp.float32
    lay = _layout(setup)
    fin = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n,)), FinanceParams.example()
    )
    envs = sizing.AgentEconInputs(
        load=load, gen_per_kw=pop.profiles.solar_cf[t.cf_idx], ts_sell=ts,
        tariff=at, tariff_w=None, fin=fin, inc=t.incentives,
        load_kwh_per_customer=t.load_kwh_per_customer_in_bin,
        elec_price_escalator=jnp.full(n, 0.005, f32),
        pv_degradation=jnp.full(n, 0.005, f32),
        system_capex_per_kw=jnp.full(n, 2500.0, f32),
        system_capex_per_kw_combined=jnp.full(n, 2600.0, f32),
        batt_capex_per_kwh_combined=jnp.full(n, 800.0, f32),
        cap_cost_multiplier=jnp.ones(n, f32),
        value_of_resiliency_usd=jnp.zeros(n, f32),
        one_time_charge=jnp.zeros(n, f32),
    )
    p = pop.tariffs.max_periods
    r0 = sizing.size_agents(envs, n_periods=p, n_years=25, n_iters=8,
                            impl="xla")
    r1 = sizing.size_agents(envs, n_periods=p, n_years=25, n_iters=8,
                            impl="xla", daylight=lay)
    np.testing.assert_allclose(
        np.asarray(r0.system_kw), np.asarray(r1.system_kw), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(r0.first_year_bill_with_system),
        np.asarray(r1.first_year_bill_with_system), rtol=1e-4, atol=0.05)
    np.testing.assert_allclose(
        np.asarray(r0.npv), np.asarray(r1.npv), rtol=1e-3, atol=1.0)


def test_bf16_streams_within_tolerance(setup):
    """bf16 profile-bank streams through the engines (the kernels
    upcast on read): totals within the documented ~1e-3 relative of
    the f32 streams."""
    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(1)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 5))).astype(np.float32)
    )
    full = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla")
    bf = bp.import_sums(
        load.astype(jnp.bfloat16), gen.astype(jnp.bfloat16),
        sell.astype(jnp.bfloat16), bucket, scales, b, impl="xla")
    # bf16 in -> bf16 out: the candidate sums store at bank precision,
    # halving the other O(N*R) HBM term of the streaming chunk
    assert bf[0].dtype == jnp.bfloat16
    for a, c in zip(full, bf):
        a, c = np.asarray(a), np.asarray(c, np.float32)
        scale = max(float(np.max(np.abs(a))), 1.0)
        assert float(np.max(np.abs(a - c))) / scale < 1e-2
    # sell_rate_hourly preserves the bank dtype (the VMEM halving
    # depends on it)
    assert bp.sell_rate_hourly(at, ts.astype(jnp.bfloat16)).dtype == \
        jnp.bfloat16


@pytest.mark.tpu_hw
@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas kernel parity needs a TPU (set DGEN_TPU_TESTS=1)",
)
def test_daylight_pallas_matches_xla_on_tpu(setup):
    """The compacted Pallas month kernel (variable seg_lens) vs the
    compacted XLA twin, single and fused-pair engines."""
    pop, load, gen, ts, at = setup
    lay = _layout(setup)
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(7)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 9))).astype(np.float32)
    )
    outs_x = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla",
                            layout=lay)
    outs_p = bp.import_sums(load, gen, sell, bucket, scales, b,
                            impl="pallas", layout=lay)
    for op, ox in zip(outs_p, outs_x):
        np.testing.assert_allclose(
            np.asarray(op), np.asarray(ox), rtol=5e-3, atol=2.0)
    pair_x = bp.import_sums_pair(
        load, gen, sell, bucket, sell, bucket, scales, b, impl="xla",
        layout=lay)
    pair_p = bp.import_sums_pair(
        load, gen, sell, bucket, sell, bucket, scales, b, impl="pallas",
        layout=lay)
    for op, ox in zip(pair_p, pair_x):
        np.testing.assert_allclose(
            np.asarray(op), np.asarray(ox), rtol=5e-3, atol=2.0)
