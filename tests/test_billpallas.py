"""Bucket-sums engine parity: the XLA formulation must reproduce the
direct hourly bill oracle; on TPU the Pallas kernel must match the XLA
formulation (run ``DGEN_TPU_TESTS=1 pytest tests/test_billpallas.py``
on TPU hardware — the default run pins the virtual CPU platform and
skips the kernel test)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.io import synth
from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops import billpallas as bp
from dgen_tpu.ops import sizing
from dgen_tpu.ops.cashflow import FinanceParams


@pytest.fixture(scope="module")
def setup():
    n = 24
    pop = synth.generate_population(n, seed=3, pad_multiple=8)
    t = pop.table
    load = pop.profiles.load[t.load_idx] * t.load_kwh_per_customer_in_bin[:, None]
    gen = pop.profiles.solar_cf[t.cf_idx] * sizing.INV_EFF
    ts = pop.profiles.wholesale[t.region_idx]
    at = jax.vmap(lambda k: bill_ops.gather_tariff(pop.tariffs, k))(t.tariff_idx)
    return pop, load, gen, ts, at


def test_bills_from_sums_matches_annual_bill(setup):
    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(0)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 7))).astype(np.float32)
    )
    s, i, c = bp.bucket_sums(load, gen, sell, bucket, scales, b, impl="xla")
    bills = np.asarray(bp.bills_from_sums(s, i, c, at, p))

    for y in range(scales.shape[1]):
        ref = np.asarray(jax.vmap(
            lambda l, g, tt, sl, sc: bill_ops.annual_bill(l - sc * g, tt, sl, p)
        )(load, gen, at, ts, scales[:, y]))
        np.testing.assert_allclose(bills[:, y], ref, rtol=5e-4, atol=1.0)


def test_zero_scale_is_no_system_bill(setup):
    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    zeros = jnp.zeros((load.shape[0], 1), jnp.float32)
    s, i, c = bp.bucket_sums(load, gen, sell, bucket, zeros, 12 * p, impl="xla")
    bills = np.asarray(bp.bills_from_sums(s, i, c, at, p))[:, 0]
    ref = np.asarray(jax.vmap(
        lambda l, tt, sl: bill_ops.annual_bill(l, tt, sl, p)
    )(load, at, ts))
    np.testing.assert_allclose(bills, ref, rtol=1e-5, atol=0.1)
    # zero scale exports nothing
    assert np.allclose(np.asarray(c)[:, 0], 0.0, atol=1e-3)


def test_sharded_engine_matches_unsharded(setup):
    """The shard_map wrapper (what keeps the Pallas kernel live on
    multi-chip meshes) must be a no-op on results: xla twin on the
    8-device virtual mesh vs plain."""
    from dgen_tpu.parallel.mesh import make_mesh

    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(5)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 6))).astype(np.float32)
    )
    mesh = make_mesh()
    assert mesh.devices.size == 8
    plain = bp.bucket_sums(load, gen, sell, bucket, scales, b, impl="xla")
    sharded = bp.bucket_sums(
        load, gen, sell, bucket, scales, b, impl="xla", mesh=mesh
    )
    for a, bb in zip(plain, sharded):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-5, atol=1e-3
        )
    i_plain = bp.import_sums(load, gen, sell, bucket, scales, b, impl="xla")
    i_sharded = bp.import_sums(
        load, gen, sell, bucket, scales, b, impl="xla", mesh=mesh
    )
    for a, bb in zip(i_plain, i_sharded):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-5, atol=1e-3
        )
    # the fused rate-switch pair engine under the mesh (n_in=7 shard
    # plumbing on the pallas path; the xla fallback shards two passes)
    p_plain = bp.import_sums_pair(
        load, gen, sell, bucket, sell, bucket, scales, b, impl="xla")
    p_sharded = bp.import_sums_pair(
        load, gen, sell, bucket, sell, bucket, scales, b, impl="xla",
        mesh=mesh)
    for a, bb in zip(p_plain, p_sharded):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-5, atol=1e-3
        )


@pytest.mark.tpu_hw
@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas kernel parity needs a TPU (set DGEN_TPU_TESTS=1)",
)
def test_pallas_matches_xla_on_tpu(setup):
    pop, load, gen, ts, at = setup
    p = pop.tariffs.max_periods
    b = 12 * p
    bucket = bp.hourly_bucket_ids(at.hour_period, p)
    sell = bp.sell_rate_hourly(at, ts)
    rng = np.random.default_rng(7)
    scales = jnp.asarray(
        np.abs(rng.normal(2.0, 1.5, (load.shape[0], 9))).astype(np.float32)
    )
    for fn in (bp.bucket_sums, lambda *a, impl: bp.import_sums(*a, impl=impl)):
        outs_x = fn(load, gen, sell, bucket, scales, b, impl="xla")
        # the month-blocked default AND the retained round-3 dot engine
        # must both agree with the XLA twin
        for impl in ("pallas", "pallas_dot"):
            outs_p = fn(load, gen, sell, bucket, scales, b, impl=impl)
            for op, ox in zip(outs_p, outs_x):
                # tolerance covers the engines' different f32
                # accumulation orders + XLA's default TPU matmul
                # precision (~1.5e-3 rel observed); layout/bucketing
                # regressions are orders larger
                np.testing.assert_allclose(
                    np.asarray(op), np.asarray(ox), rtol=5e-3, atol=2.0,
                    err_msg=impl,
                )


@pytest.mark.slow
def test_fast_sizing_matches_oracle(setup):
    pop, load, gen, ts, at = setup
    t = pop.table
    n = t.n_agents
    f32 = jnp.float32
    fin = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n,)), FinanceParams.example()
    )
    envs = sizing.AgentEconInputs(
        load=load, gen_per_kw=pop.profiles.solar_cf[t.cf_idx], ts_sell=ts,
        tariff=at, tariff_w=None, fin=fin, inc=t.incentives,
        load_kwh_per_customer=t.load_kwh_per_customer_in_bin,
        elec_price_escalator=jnp.full(n, 0.005, f32),
        pv_degradation=jnp.full(n, 0.005, f32),
        system_capex_per_kw=jnp.full(n, 2500.0, f32),
        system_capex_per_kw_combined=jnp.full(n, 2600.0, f32),
        batt_capex_per_kwh_combined=jnp.full(n, 800.0, f32),
        cap_cost_multiplier=jnp.ones(n, f32),
        value_of_resiliency_usd=jnp.zeros(n, f32),
        one_time_charge=jnp.zeros(n, f32),
    )
    p = pop.tariffs.max_periods
    rf = sizing.size_agents(envs, n_periods=p, n_years=25, n_iters=10, fast=True)
    rs = sizing.size_agents(envs, n_periods=p, n_years=25, n_iters=10, fast=False)
    # kW* tolerance covers grid-vs-golden-section discretization
    # (2/n_iters^2 of the bracket), not engine disagreement
    np.testing.assert_allclose(
        np.asarray(rf.system_kw), np.asarray(rs.system_kw), rtol=6e-3)
    # NPV is a small difference of large bill flows; bound the error
    # relative to the flow magnitude (f32 cancellation scale), not the
    # net NPV
    flow_scale = 25.0 * np.asarray(rs.first_year_bill_without_system)
    dnpv = np.abs(np.asarray(rf.npv) - np.asarray(rs.npv))
    assert np.all(
        dnpv <= 2e-3 * np.abs(np.asarray(rs.npv)) + 1e-3 * flow_scale + 10.0
    ), f"max npv mismatch {dnpv.max()}"
    np.testing.assert_allclose(
        np.asarray(rf.payback_period), np.asarray(rs.payback_period), atol=0.21)
    # batt bills inherit the kW* grid discretization (bill ~ kW for
    # export-dominated agents); exact engine parity is asserted in
    # test_bills_from_sums_matches_annual_bill
    np.testing.assert_allclose(
        np.asarray(rf.first_year_bill_with_batt),
        np.asarray(rs.first_year_bill_with_batt), rtol=2e-2, atol=5.0)


@pytest.mark.tpu_hw
@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas kernel parity needs a TPU (set DGEN_TPU_TESTS=1)",
)
def test_month_kernel_period_count_corners():
    """The month kernel's P-1 mask + subtraction structure must hold at
    every TOU period count the tariff layer produces — including P=1
    (flat-only populations: zero masks, every bucket the month total)
    and the 5-period upper range."""
    rng_key = jax.random.key(0)
    for p_count in (1, 3, 5):
        n, h, r = 64, 8760, 17
        ks = jax.random.split(jax.random.fold_in(rng_key, p_count), 5)
        load = jax.random.uniform(ks[0], (n, h), jnp.float32, 0.2, 3.0)
        gen = jax.random.uniform(ks[1], (n, h), jnp.float32, 0.0, 1.0)
        sell = jax.random.uniform(ks[2], (n, h), jnp.float32, 0.02, 0.08)
        period = jax.random.randint(ks[3], (n, h), 0, p_count, jnp.int32)
        bucket = bp.hourly_bucket_ids(period, p_count)
        scales = jax.random.uniform(ks[4], (n, r), jnp.float32, 0.1, 6.0)
        nb = 12 * p_count
        for fn in (bp.import_sums, bp.bucket_sums):
            outs_p = fn(load, gen, sell, bucket, scales, nb, impl="pallas")
            outs_x = fn(load, gen, sell, bucket, scales, nb, impl="xla")
            for op, ox in zip(outs_p, outs_x):
                a, b = np.asarray(op), np.asarray(ox)
                scale = max(float(np.max(np.abs(b))), 1.0)
                assert float(np.max(np.abs(a - b))) / scale < 5e-3, (
                    p_count, fn.__name__)
        # the fused rate-switch pair engine shares the net grid but must
        # match two independent single-tariff passes
        sell_b = jax.random.uniform(
            jax.random.fold_in(ks[4], 1), (n, h), jnp.float32, 0.01, 0.05)
        period_b = jax.random.randint(
            jax.random.fold_in(ks[3], 1), (n, h), 0, p_count, jnp.int32)
        bucket_b = bp.hourly_bucket_ids(period_b, p_count)
        pair = bp.import_sums_pair(
            load, gen, sell, bucket, sell_b, bucket_b, scales, nb,
            impl="pallas")
        ref_a = bp.import_sums(load, gen, sell, bucket, scales, nb,
                               impl="xla")
        ref_b = bp.import_sums(load, gen, sell_b, bucket_b, scales, nb,
                               impl="xla")
        for got, want in zip(pair, ref_a + ref_b):
            a, b = np.asarray(got), np.asarray(want)
            scale = max(float(np.max(np.abs(b))), 1.0)
            assert float(np.max(np.abs(a - b))) / scale < 5e-3, p_count
