"""Gang supervision: shard-ledger merge/frontier, elastic resharded
restore, the jax-free GangSupervisor state machine (stub workers), the
multi-process async host-IO opt-in, and the real CPU/gloo gang drills
(slow tier).  docs/resilience.md "Gang runbook"."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from dgen_tpu.config import GangConfig, RunConfig
from dgen_tpu.resilience import faults
from dgen_tpu.resilience.gang import (
    GangCrashLoop,
    GangSupervisor,
    done_path,
    heartbeat_path,
)
from dgen_tpu.resilience.manifest import (
    GangManifest,
    RunManifest,
    discover_shards,
    verify_run_dir,
)
from dgen_tpu.resilience.supervisor import RetryPolicy

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# config + env plumbing
# ---------------------------------------------------------------------------

def test_gang_config_validation():
    cfg = GangConfig(n_processes=4, total_devices=4, shrink_plan=(2, 1))
    assert cfg.devices_for(4) == 1
    assert cfg.devices_for(2) == 2
    assert cfg.devices_for(3) == 1   # indivisible -> per-process value
    with pytest.raises(ValueError):
        GangConfig(n_processes=0)
    with pytest.raises(ValueError):
        GangConfig(n_processes=2, shrink_plan=(2,))   # not < P
    with pytest.raises(ValueError):
        GangConfig(n_processes=4, shrink_plan=(1, 2))  # not decreasing
    with pytest.raises(ValueError):
        GangConfig(n_processes=4, shrink_plan=(2, 2))  # duplicate
    with pytest.raises(ValueError, match="total_devices"):
        # a shrink entry that can't keep the global mesh constant must
        # fail at construction, not at the relaunch that needed it
        GangConfig(n_processes=4, total_devices=4, shrink_plan=(3,))
    with pytest.raises(ValueError):
        GangConfig(stall_timeout_s=0)


def test_gang_config_from_env(monkeypatch):
    monkeypatch.setenv("DGEN_TPU_GANG_PROCESSES", "8")
    monkeypatch.setenv("DGEN_TPU_GANG_TOTAL_DEVICES", "8")
    monkeypatch.setenv("DGEN_TPU_GANG_SHRINK_PLAN", "4,2")
    monkeypatch.setenv("DGEN_TPU_GANG_STALL_TIMEOUT_S", "33")
    cfg = GangConfig.from_env()
    assert cfg.n_processes == 8
    assert cfg.shrink_plan == (4, 2)
    assert cfg.stall_timeout_s == 33.0
    assert cfg.devices_for(2) == 4


def test_async_io_default_on(monkeypatch):
    """Async host IO is default-on for single- AND multi-process runs
    (the opt-in gate is gone): one resolved decision, one kill switch.
    There is no separate multi-process property anymore — the run gate
    only adds the collect=True serialization (models.simulation)."""
    monkeypatch.delenv("DGEN_TPU_ASYNC_IO", raising=False)
    rc = RunConfig()
    assert rc.async_io_enabled is True           # on unless killed
    assert not hasattr(rc, "async_io_multiprocess_optin")
    monkeypatch.setenv("DGEN_TPU_ASYNC_IO", "0")
    assert RunConfig().async_io_enabled is False  # the kill switch
    monkeypatch.delenv("DGEN_TPU_ASYNC_IO", raising=False)
    assert RunConfig(async_host_io=True).async_io_enabled
    assert not RunConfig(async_host_io=False).async_io_enabled


def test_gang_fault_sites_registered():
    for site in ("gang_worker_kill", "gang_heartbeat_stall",
                 "gang_barrier"):
        assert site in faults.SITES
    spec = faults.parse_spec(
        "gang_worker_kill@2:kill;gang_heartbeat_stall@4:hang")
    assert spec[0].site == "gang_worker_kill" and spec[0].kind == "kill"
    assert spec[1].nth == 4 and spec[1].kind == "hang"


# ---------------------------------------------------------------------------
# shard ledgers + the GangManifest merge
# ---------------------------------------------------------------------------

def _touch(run_dir, rel, data=b"x"):
    p = os.path.join(run_dir, rel)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "wb") as f:  # dgenlint: disable=L11 — test fixture
        f.write(data)
    return p


def _shard_year(run_dir, shard, n_proc, year, complete=True):
    m = RunManifest(run_dir, shard=shard, n_processes=n_proc)
    rel = os.path.join("agent_outputs", f"year={year}-p{shard}.parquet")
    _touch(run_dir, rel, f"{year}-{shard}".encode())
    m.record_artifact(year, rel)
    if complete:
        m.mark_year_complete(year)
    else:
        m.flush()
    return m


def test_gang_frontier_requires_every_shard(tmp_path):
    run_dir = str(tmp_path)
    years = [2014, 2016, 2018]
    # both shards complete 2014; only shard 0 completes 2016
    for s in (0, 1):
        _shard_year(run_dir, s, 2, 2014)
    _shard_year(run_dir, 0, 2, 2016)
    assert discover_shards(run_dir) == [0, 1]
    gm = GangManifest(run_dir)
    assert gm.frontier(years) == 2014
    # shard 1 lands 2016 -> frontier advances
    _shard_year(run_dir, 1, 2, 2016)
    assert GangManifest(run_dir).frontier(years) == 2016
    # recorded-but-not-complete (the killed-mid-export shape) holds it
    _shard_year(run_dir, 0, 2, 2018)
    _shard_year(run_dir, 1, 2, 2018, complete=False)
    assert GangManifest(run_dir).frontier(years) == 2016


def test_gang_frontier_none_means_restart_from_scratch(tmp_path):
    """No durably-complete year (or no ledgers at all) -> frontier None
    -> the supervisor relaunches from scratch rather than resuming past
    un-exported years — and the resume plan prunes the dead attempt's
    partial artifacts so the scratch restart starts clean."""
    run_dir = str(tmp_path / "run")
    years = [2014, 2016]
    sup = GangSupervisor(run_dir, years, config=GangConfig(platform=""))
    assert sup._resume_plan() is None       # directory doesn't exist
    os.makedirs(run_dir)
    assert sup._resume_plan() is None       # no shard ledgers
    _shard_year(run_dir, 0, 2, 2014)        # half a gang's year only
    assert sup._resume_plan() is None
    # the partial shard was pruned for the from-scratch restart
    assert not os.listdir(os.path.join(run_dir, "agent_outputs"))


def test_gang_frontier_elastic_epoch(tmp_path):
    """Years written after a P -> P' shrink are complete with only the
    P' shards — each year's completeness is judged against its OWN
    writing epoch, stamped in the ledgers."""
    run_dir = str(tmp_path)
    years = [2014, 2016]
    for s in (0, 1, 2, 3):
        _shard_year(run_dir, s, 4, 2014)
    for s in (0, 1):
        _shard_year(run_dir, s, 2, 2016)
    assert GangManifest(run_dir).frontier(years) == 2016


def test_gang_manifest_verify_merged(tmp_path):
    run_dir = str(tmp_path)
    for s in (0, 1):
        _shard_year(run_dir, s, 2, 2014)
    rep = GangManifest(run_dir).verify()
    assert rep.ok and rep.years_complete == [2014]
    assert not rep.unrecorded   # peer parts are NOT 'unrecorded'
    # verify_run_dir routes gang directories to the merged report
    reports = verify_run_dir(run_dir)
    assert len(reports) == 1 and reports[0].ok
    # damage one shard's artifact -> corrupt + year no longer complete
    p = os.path.join(run_dir, "agent_outputs", "year=2014-p1.parquet")
    with open(p, "wb") as f:  # dgenlint: disable=L11 — test damage
        f.write(b"torn")
    rep = GangManifest(run_dir).verify()
    assert not rep.ok and rep.corrupt
    assert rep.years_complete == []
    # a stray unledgered part shows up in the sweep (advisory)
    _touch(run_dir, os.path.join("agent_outputs", "year=9-p9.parquet"))
    rep = GangManifest(run_dir).verify()
    assert any("year=9" in u for u in rep.unrecorded)


def test_gang_prune_after_clears_dead_epoch(tmp_path):
    """A dead epoch's partial parts must be pruned before a relaunch
    at a different gang size: stale ``-p2``/``-p3`` parts would double
    rows under load_surface and the mixed epoch stamps would wedge the
    merged completeness check forever."""
    run_dir = str(tmp_path)
    years = [2014, 2016]
    for s in range(4):
        _shard_year(run_dir, s, 4, 2014)
    # the P=4 gang died mid-2016: two shards recorded (incomplete),
    # one landed unledgered (killed between rename and record)
    _shard_year(run_dir, 0, 4, 2016, complete=False)
    _shard_year(run_dir, 2, 4, 2016, complete=False)
    _touch(run_dir, os.path.join("agent_outputs",
                                 "year=2016-p3.parquet"))
    gm = GangManifest(run_dir)
    assert gm.frontier(years) == 2014
    removed = gm.prune_after(2014)
    assert any("2016" in r for r in removed)
    names = os.listdir(os.path.join(run_dir, "agent_outputs"))
    assert all("year=2016" not in n for n in names)
    # a P'=2 re-export of 2016 then completes cleanly (no mixed epochs,
    # no duplicate rows)
    for s in (0, 1):
        _shard_year(run_dir, s, 2, 2016)
    gm = GangManifest(run_dir)
    assert gm.frontier(years) == 2016
    assert gm.verify().ok
    # frontier None = restart from scratch: everything goes
    gm.prune_after(None)
    assert GangManifest(run_dir).frontier(years) is None
    assert not os.listdir(os.path.join(run_dir, "agent_outputs"))


# ---------------------------------------------------------------------------
# elastic resume planning (corrupt-checkpoint walk under the gang path)
# ---------------------------------------------------------------------------

def test_elastic_resume_year_walks_past_corrupt(tmp_path):
    from dgen_tpu.io import checkpoint as ckpt
    from dgen_tpu.models.simulation import SimCarry
    from dgen_tpu.parallel import elastic

    n = 64
    cd = str(tmp_path / "ckpt")
    with ckpt.Writer(cd) as w:
        for y in (2014, 2016):
            w.save(y, SimCarry.zeros(n))
    # no frontier -> restart from scratch, no checkpoint consulted
    assert elastic.resume_year_for(cd, n, None) is None
    # frontier caps the resume even when newer checkpoints exist
    assert elastic.resume_year_for(cd, n, 2014) == 2014
    assert elastic.resume_year_for(cd, n, 2016) == 2016
    # damage the newest step: the walk must fall back to 2014
    step = os.path.join(cd, "2016")
    for root, _, files in os.walk(step):
        for f in files:
            p = os.path.join(root, f)
            if os.path.getsize(p) > 0:
                with open(p, "r+b") as fh:  # dgenlint: disable=L11
                    fh.truncate(max(os.path.getsize(p) // 2, 1))
    assert elastic.resume_year_for(cd, n, 2016) == 2014


def test_elastic_validate_topology_names_fix(tmp_path):
    import jax

    from dgen_tpu.parallel import elastic
    from dgen_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(n_devices=len(jax.devices()))
    with pytest.raises(ValueError, match="pad_table"):
        elastic.validate_topology(len(jax.devices()) + 1, mesh)
    elastic.validate_topology(len(jax.devices()) * 4, mesh)  # divides


# ---------------------------------------------------------------------------
# the supervisor state machine, with jax-free stub workers
# ---------------------------------------------------------------------------

_STUB = textwrap.dedent("""
    import json, os, sys, time
    gd = os.environ["DGEN_GANG_DIR"]
    i = os.environ["DGEN_PROCESS_ID"]

    def w(path, obj):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)

    hb = os.path.join(gd, f"worker-{i}.hb.json")
    w(hb, {"t": time.time(), "phase": "boot"})
    mode = os.environ.get("STUB_MODE", "ok")
    if mode == "die":
        sys.exit(3)
    w(hb, {"t": time.time(), "year": 2014, "year_idx": 0})
    if mode == "stall":
        time.sleep(120)
    w(os.path.join(gd, f"worker-{i}.done.json"),
      {"process": int(i), "completed_through": 2016,
       "preempted": os.environ.get("STUB_PREEMPT") == "1"})
""")


def _stub_supervisor(tmp_path, env_for=None, **cfg_over):
    kw = dict(
        n_processes=2, platform="", poll_interval_s=0.05,
        boot_timeout_s=10.0, stall_timeout_s=0.8,
        restart_window_s=30.0,
    )
    kw.update(cfg_over)
    cfg = GangConfig(**kw)
    return GangSupervisor(
        str(tmp_path / "run"), [2014, 2016],
        cmd_for=lambda i, n: [sys.executable, "-c", _STUB],
        config=cfg, policy=RetryPolicy(backoff_base_s=0.01),
        env_for=env_for, gang_dir=str(tmp_path / "gang"),
    )


def test_stub_gang_clean_run(tmp_path):
    rep = _stub_supervisor(tmp_path).run()
    assert rep.succeeded and not rep.preempted
    assert rep.restarts == 0
    assert rep.completed_through == 2016


def test_stub_gang_death_restarts_whole_gang(tmp_path):
    def env_for(i, attempt):
        if i == 1 and attempt == 0:
            return {"STUB_MODE": "die"}
        return None

    sup = _stub_supervisor(tmp_path, env_for=env_for)
    rep = sup.run()
    assert rep.succeeded and rep.restarts == 1
    assert rep.attempts[0].outcome == "died"
    assert rep.attempts[0].reason == "worker_exit"
    assert rep.attempts[0].worker == 1
    assert rep.attempts[0].exit_code == 3
    assert rep.attempts[1].outcome == "complete"
    assert rep.recovery_wall_s > 0


def test_stub_gang_stall_detected_by_heartbeat(tmp_path):
    """A worker that is alive but silent: only heartbeat staleness can
    catch it — and the supervisor must SIGKILL and relaunch.  (With no
    year-over-year gap measured yet, the adaptive stall bound falls
    back to boot_timeout_s — kept small here.)"""
    def env_for(i, attempt):
        if i == 0 and attempt == 0:
            return {"STUB_MODE": "stall"}
        return None

    rep = _stub_supervisor(
        tmp_path, env_for=env_for, boot_timeout_s=2.0).run()
    assert rep.succeeded and rep.restarts == 1
    assert rep.attempts[0].reason == "heartbeat_stall"
    assert rep.attempts[0].worker == 0


def test_stub_gang_crash_loop_breaker(tmp_path):
    sup = _stub_supervisor(
        tmp_path, env_for=lambda i, a: {"STUB_MODE": "die"},
        max_restarts=1,
    )
    with pytest.raises(GangCrashLoop) as exc:
        sup.run()
    rep = exc.value.gang_report
    assert not rep.succeeded
    assert rep.restarts >= 1
    assert all(a.outcome == "died" for a in rep.attempts)


def test_stub_gang_breaker_shrinks_then_succeeds(tmp_path):
    """The crash-loop breaker at P falls through to the shrink plan:
    the gang resumes at P' instead of dying."""
    def env_for(i, attempt):
        # die whenever launched at 2 processes; succeed at 1
        return {"STUB_MODE": "die"} if i == 1 else None

    sup = _stub_supervisor(
        tmp_path, env_for=env_for, max_restarts=1, shrink_plan=(1,),
    )
    rep = sup.run()
    assert rep.succeeded
    assert rep.processes_initial == 2 and rep.processes_final == 1
    assert rep.shrinks and "P'=1" in rep.shrinks[0]


def test_stub_gang_preempted_stop(tmp_path):
    def env_for(i, attempt):
        return {"STUB_PREEMPT": "1"} if i == 0 else None

    rep = _stub_supervisor(tmp_path, env_for=env_for).run()
    assert rep.succeeded and rep.preempted


def test_heartbeat_and_done_paths(tmp_path):
    from dgen_tpu.resilience.gang import read_json, write_heartbeat

    hb = heartbeat_path(str(tmp_path), 3)
    write_heartbeat(hb, year=2016, pid=123)
    doc = read_json(hb)
    assert doc["year"] == 2016 and doc["pid"] == 123
    assert done_path(str(tmp_path), 3).endswith("worker-3.done.json")
    assert read_json(done_path(str(tmp_path), 3)) is None


# ---------------------------------------------------------------------------
# elastic resharded restore: P=2 -> P'=1, bit-exact (fast tier)
# ---------------------------------------------------------------------------

def test_resharded_restore_2to1_bitexact(tmp_path):
    """An orbax checkpoint written COLLECTIVELY by a 2-process gloo
    gang restores bit-exactly in a single process under a different
    sharding — the elastic-restore primitive the gang's P -> P' resume
    rides (parallel.elastic)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ckpt_dir = str(tmp_path / "ckpt")
    n = 64

    script = textwrap.dedent(f"""
        import sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        from dgen_tpu.utils import compat
        compat.set_cpu_device_count(1)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            coordinator_address="127.0.0.1:{port}",
            num_processes=2, process_id=pid,
        )
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec

        from dgen_tpu.io import checkpoint as ckpt
        from dgen_tpu.models.simulation import SimCarry
        from dgen_tpu.parallel.mesh import AGENT_AXIS, make_mesh

        mesh = make_mesh()
        assert mesh.devices.size == 2
        sh = NamedSharding(mesh, PartitionSpec(AGENT_AXIS))
        zeros = SimCarry.zeros({n})
        leaves, treedef = jax.tree.flatten(zeros)
        filled = []
        for k, leaf in enumerate(leaves):
            h = (np.arange(leaf.size, dtype=np.float64)
                 .reshape(leaf.shape) * (k + 1) + k).astype(leaf.dtype)
            filled.append(jax.make_array_from_callback(
                h.shape, sh, lambda idx, h=h: h[idx]))
        carry = jax.tree.unflatten(treedef, filled)
        ckpt.save_year({ckpt_dir!r}, 2014, carry)
        print(f"P{{pid}}_SAVED")
    """)
    env = {**os.environ, "PYTHONUNBUFFERED": "1"}
    env.pop("XLA_FLAGS", None)
    logs = [open(tmp_path / f"p{pid}.log", "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid)],
            stdout=logs[pid], stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO_ROOT,
        )
        for pid in (0, 1)
    ]
    try:
        for p in procs:
            p.wait(timeout=300)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    for pid, p in enumerate(procs):
        out = (tmp_path / f"p{pid}.log").read_text()
        assert p.returncode == 0, f"p{pid}: {out[-3000:]}"
        assert f"P{pid}_SAVED" in out

    # restore in THIS (single-controller, 8-device conftest) process:
    # host restore and mesh restore must both be bit-exact
    import jax

    from dgen_tpu.models.simulation import SimCarry
    from dgen_tpu.parallel import elastic
    from dgen_tpu.parallel.mesh import make_mesh

    def expected_leaves():
        leaves, _ = jax.tree.flatten(SimCarry.zeros(n))
        return [
            (np.arange(leaf.size, dtype=np.float64)
             .reshape(leaf.shape) * (k + 1) + k).astype(leaf.dtype)
            for k, leaf in enumerate(leaves)
        ]

    year, carry = elastic.restore_resharded(ckpt_dir, n, mesh=None)
    assert year == 2014
    got = [np.asarray(x) for x in jax.tree.leaves(carry)]
    for g, e in zip(got, expected_leaves()):
        np.testing.assert_array_equal(g, e)

    mesh = make_mesh()
    year, carry = elastic.restore_resharded(ckpt_dir, n, mesh=mesh)
    assert year == 2014
    first = jax.tree.leaves(carry)[0]
    assert not first.is_fully_replicated   # really landed sharded
    for g, e in zip(
        [np.asarray(x) for x in jax.tree.leaves(carry)],
        expected_leaves(),
    ):
        np.testing.assert_array_equal(g, e)


# ---------------------------------------------------------------------------
# real CPU/gloo gang drills (slow tier; check.sh runs the smoke form)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gang_drill_kill_and_elastic(tmp_path):
    """The gang drill at its smallest real shape: 2-process gang,
    worker killed mid-year (byte-identical recovery vs baseline,
    merged-manifest verify), then the synchronized stop + P=2 -> P'=1
    elastic resharded resume over the same 2-device global mesh."""
    from dgen_tpu.resilience.gangdrill import run_gang_drill

    rec = run_gang_drill(
        str(tmp_path), processes=2, shrink_to=1, total_devices=2,
        agents=48, end_year=2016, stall=False,
    )
    assert rec["ok"], json.dumps(rec, indent=1)
    assert rec["rounds"]["kill"]["restarts"] >= 1
    assert rec["rounds"]["kill"]["parquet"]["mismatched"] == []
    el = rec["rounds"]["elastic"]
    assert el["stopped_through"] == 2014
    assert el["parquet"]["row_compared_years"]
    assert el["verify_ok"]


@pytest.mark.slow
def test_multiprocess_async_io_parity(tmp_path):
    """The async host-IO pipeline on a 2-process gang — engaged by
    DEFAULT now (RunConfig.async_host_io=None, no opt-in) — writes
    byte-identical parquet shards and an equal restored carry vs the
    serialized oracle."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = textwrap.dedent(f"""
        import os, sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        from dgen_tpu.utils import compat
        compat.set_cpu_device_count(2)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            coordinator_address="127.0.0.1:{port}",
            num_processes=2, process_id=pid,
        )
        import numpy as np

        from dgen_tpu.config import RunConfig, ScenarioConfig
        from dgen_tpu.io import synth
        from dgen_tpu.io.export import RunExporter
        from dgen_tpu.models import scenario as scen
        from dgen_tpu.models.simulation import Simulation
        from dgen_tpu.parallel.mesh import make_mesh

        base = {str(tmp_path)!r}
        cfg = ScenarioConfig(name="par", start_year=2014, end_year=2016,
                             anchor_years=())
        pop = synth.generate_population(
            48, states=["DE", "CA"], seed=7, pad_multiple=64)
        inputs = scen.uniform_inputs(
            cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions)

        def run(tag, async_io):
            rd = os.path.join(base, tag)
            sim = Simulation(
                pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                RunConfig(sizing_iters=6, async_host_io=async_io),
                mesh=make_mesh(),
            )
            exp = RunExporter(rd, agent_id=sim.host_agent_id,
                              mask=sim.host_mask)
            sim.run(callback=exp, collect=False,
                    checkpoint_dir=os.path.join(rd, "ckpt"))
            return sim

        sim = run("async", None)   # None = the default -> pipeline on
        run("sync", False)
        # this process's shard parts must be byte-identical
        for surface in ("agent_outputs", "finance_series"):
            for year in (2014, 2016):
                name = f"year={{year}}-p{{pid}}.parquet"
                pa = os.path.join(base, "async", surface, name)
                pb = os.path.join(base, "sync", surface, name)
                with open(pa, "rb") as fa, open(pb, "rb") as fb:
                    assert fa.read() == fb.read(), (surface, year)
        # restored carries agree too — host-template restores (no
        # sharding) read the full array file-side, so each process can
        # compare the whole carry without a cross-process fetch
        from dgen_tpu.io import checkpoint as ckpt
        totals = []
        for tag in ("async", "sync"):
            y, c = ckpt.restore_year(
                os.path.join(base, tag, "ckpt"), sim.table.n_agents,
                2016)
            totals.append(np.asarray(c.market.system_kw_cum))
        assert np.array_equal(totals[0], totals[1])
        print(f"P{{pid}}_PARITY_OK")
    """)
    env = {**os.environ, "PYTHONUNBUFFERED": "1"}
    env.pop("DGEN_TPU_ASYNC_IO", None)   # prove the un-opted default
    env.pop("XLA_FLAGS", None)
    env.pop("DGEN_TPU_FAULTS", None)
    logs = [open(tmp_path / f"p{pid}.log", "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid)],
            stdout=logs[pid], stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO_ROOT,
        )
        for pid in (0, 1)
    ]
    try:
        for p in procs:
            p.wait(timeout=900)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    for pid, p in enumerate(procs):
        out = (tmp_path / f"p{pid}.log").read_text()
        assert p.returncode == 0, f"p{pid}: {out[-3000:]}"
        assert f"P{pid}_PARITY_OK" in out
    # the async run's meta carries the pipeline provenance
    with open(tmp_path / "async" / "meta.json") as f:
        meta = json.load(f)
    assert meta["async_io"] is True
    with open(tmp_path / "sync" / "meta.json") as f:
        meta = json.load(f)
    assert meta["async_io"] is False
