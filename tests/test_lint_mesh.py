"""dgenlint-mesh tests (rules J7-J10): the injected-resharding drill
(a deliberate all-gather of a [N, 8760] stream fails J7/J8 with the
offending op named), the replicated-bank and over-budget fixtures, the
J10 per-mesh-shape fingerprint gate, baseline merge semantics for the
``mesh`` section, the 2-D hosts x devices mesh helpers (placement
identity + execution parity), and — the enforcement contract — the
repo-clean fast-tier mesh audit that check.sh/CI gate at full depth."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.lint import prog
from dgen_tpu.lint.prog import baseline as baseline_mod
from dgen_tpu.lint.prog import lower_spec, run_program_rules
from dgen_tpu.parallel import mesh as mesh_mod

FIXTURES = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint"
)


def _fixture(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(FIXTURES, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# parallel.mesh: the 2-D hosts x devices grid
# ---------------------------------------------------------------------------

def test_parse_mesh_shape():
    assert mesh_mod.parse_mesh_shape("1x8") == (1, 8)
    assert mesh_mod.parse_mesh_shape("2x4") == (2, 4)
    with pytest.raises(ValueError, match="bad mesh shape"):
        mesh_mod.parse_mesh_shape("8")
    with pytest.raises(ValueError, match="bad mesh shape"):
        mesh_mod.parse_mesh_shape("2x0")


def test_make_mesh_shapes_and_agent_spec():
    m1 = mesh_mod.make_mesh(shape=(1, 8))
    m2 = mesh_mod.make_mesh(shape=(2, 4))
    assert m1.axis_names == (mesh_mod.AGENT_AXIS,)
    assert m2.axis_names == (mesh_mod.HOST_AXIS, mesh_mod.AGENT_AXIS)
    assert mesh_mod.mesh_shape_of(m1) == (1, 8)
    assert mesh_mod.mesh_shape_of(m2) == (2, 4)
    # the agent dim spans BOTH axes of a 2-D grid
    s2 = mesh_mod.agent_spec(m2, ndim=2)
    assert s2[0] == (mesh_mod.HOST_AXIS, mesh_mod.AGENT_AXIS)
    # row-major device order: placement is identical to the 1-D mesh
    assert [d.id for d in m2.devices.flat] == [
        d.id for d in m1.devices.flat
    ]
    with pytest.raises(ValueError, match="needs 16 devices"):
        mesh_mod.make_mesh(shape=(2, 8))


def test_2d_mesh_execution_parity():
    """A sharded computation over the 2-D grid executes and matches the
    single-device result — the 2-D mesh is a real run topology, not
    just an audit artifact."""
    from jax.sharding import NamedSharding

    x = np.arange(64 * 16, dtype=np.float32).reshape(64, 16)
    ref = x.sum(axis=1) * 2.0

    @jax.jit
    def f(a):
        return a.sum(axis=1) * 2.0

    for shape in ((1, 8), (2, 4)):
        mesh = mesh_mod.make_mesh(shape=shape)
        xs = jax.device_put(
            x, NamedSharding(mesh, mesh_mod.agent_spec(mesh, 2))
        )
        np.testing.assert_allclose(np.asarray(f(xs)), ref, rtol=1e-6)


def test_year_step_runs_on_2d_mesh():
    """One REAL year step executes over the 2x4 hosts x devices mesh
    and matches the meshless program at f32 re-association tolerance
    (the audited topology actually runs)."""
    from dgen_tpu.lint.prog.registry import _mesh_world, _world
    from dgen_tpu.models.simulation import SimCarry, year_step

    def step(sim):
        kw = sim.step_kwargs(False)
        kw["net_billing"] = True
        carry = SimCarry.zeros(sim.table.n_agents)
        _, out = year_step(
            sim.table, sim.profiles, sim.tariffs, sim.inputs, carry,
            jnp.asarray(1, jnp.int32), **kw
        )
        return np.asarray(out.npv), np.asarray(out.system_kw)

    npv_ref, kw_ref = step(_world(False, False))
    npv_2d, kw_2d = step(_mesh_world((2, 4)))
    np.testing.assert_allclose(npv_2d, npv_ref, rtol=2e-5, atol=1e-4)
    np.testing.assert_allclose(kw_2d, kw_ref, rtol=2e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# J7/J8 — the injected-resharding drill
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def resharded_audits():
    bad, clean = _fixture("bad_j7_resharding").specs()
    return lower_spec(bad), lower_spec(clean)


def test_j8_injected_allgather_flagged(resharded_audits):
    bad, clean = resharded_audits
    assert bad.error is None and clean.error is None
    findings = run_program_rules([bad])
    assert "J8" in rules_of(findings)
    msgs = " ".join(f.message for f in findings)
    assert "f32[64,8760]" in msgs        # the offending global tensor
    assert run_program_rules([clean]) == []


def test_j7_new_collective_fails_gate(resharded_audits):
    """The acceptance-criterion drill: against a mesh baseline recorded
    BEFORE the resharding (no all-gather), the gate must fail and name
    the new op with its operand shape."""
    bad, _clean = resharded_audits
    doc = {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "spec": prog.AUDIT_SPEC_VERSION,
        "tolerance": 0.02,
        "entries": {},
        "mesh": {
            bad.spec.spec_id: {
                "mesh_shape": [1, 2],
                "program_hash": bad.fingerprint,   # hash unchanged
                "collectives": {},                 # ...but no gathers
                "comm_bytes": 0,
                "peak_bytes": 1,
            },
        },
    }
    findings, status = baseline_mod.compare_mesh_to_baseline([bad], doc)
    j7 = [f for f in findings if f.rule == "J7"]
    assert j7, findings
    msgs = " ".join(f.message for f in j7)
    assert "NEW collective" in msgs and "all-gather" in msgs
    assert "f32[64,8760]" in msgs        # operand/result shape named
    assert status["note"] is None


def test_j7_comm_drift_and_vanished_collective(resharded_audits):
    bad, _clean = resharded_audits
    fp = baseline_mod.collect_mesh_fingerprints([bad])
    doc = {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "spec": prog.AUDIT_SPEC_VERSION,
        "tolerance": 0.02,
        "entries": {},
        "mesh": fp,
    }
    # faithful baseline: clean
    assert baseline_mod.compare_mesh_to_baseline([bad], doc)[0] == []
    # double the recorded comm bytes -> "shrank" drift fires
    doc2 = json.loads(json.dumps(doc))
    for e in doc2["mesh"].values():
        for c in e["collectives"].values():
            c["comm_bytes"] *= 2
    findings, _ = baseline_mod.compare_mesh_to_baseline([bad], doc2)
    assert any("shrank" in f.message for f in findings)
    # a recorded collective kind the program no longer emits
    doc3 = json.loads(json.dumps(doc))
    for e in doc3["mesh"].values():
        e["collectives"]["collective-permute"] = {
            "count": 2, "comm_bytes": 512,
        }
    findings, _ = baseline_mod.compare_mesh_to_baseline([bad], doc3)
    assert any("no longer appears" in f.message for f in findings)


def test_j10_hash_change_fails_gate(resharded_audits):
    bad, _clean = resharded_audits
    fp = baseline_mod.collect_mesh_fingerprints([bad])
    for e in fp.values():
        e["program_hash"] = "not-the-hash"
    doc = {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "spec": prog.AUDIT_SPEC_VERSION,
        "tolerance": 0.02, "entries": {}, "mesh": fp,
    }
    findings, _ = baseline_mod.compare_mesh_to_baseline([bad], doc)
    j10 = [f for f in findings if f.rule == "J10"]
    assert j10 and "fingerprint changed" in j10[0].message


def test_j7_gate_skips_on_environment_mismatch(resharded_audits):
    bad, _clean = resharded_audits
    doc = {
        "jax": "0.0.0-not-this-one",
        "platform": jax.default_backend(),
        "spec": prog.AUDIT_SPEC_VERSION,
        "tolerance": 0.02, "entries": {}, "mesh": {},
    }
    findings, status = baseline_mod.compare_mesh_to_baseline([bad], doc)
    assert findings == []
    assert "skipped" in status["note"]


# ---------------------------------------------------------------------------
# J8 — replicated bank
# ---------------------------------------------------------------------------

def test_j8_replicated_bank_flagged():
    bad, clean = _fixture("bad_j8_replicated_bank").specs()
    findings = run_program_rules([lower_spec(bad)])
    assert "J8" in rules_of(findings)
    assert any("UNSHARDED" in f.message for f in findings)
    clean_findings = run_program_rules([lower_spec(clean)])
    assert [f for f in clean_findings if f.rule == "J8"] == []


# ---------------------------------------------------------------------------
# J9 — static per-device memory gate
# ---------------------------------------------------------------------------

def test_j9_overbudget_and_model_mismatch():
    (spec,) = _fixture("bad_j9_overbudget").specs()
    audit = lower_spec(spec)
    assert audit.error is None
    findings = run_program_rules([audit], j9_budget_bytes=1 << 20)
    j9 = [f for f in findings if f.rule == "J9"]
    msgs = " ".join(f.message for f in j9)
    assert "exceeds the" in msgs          # budget gate
    assert "under-counts" in msgs         # planner cross-check
    # a realistic budget keeps the budget gate quiet; the tiny
    # model_bytes still trips the cross-check
    findings = run_program_rules([audit], j9_budget_bytes=16 << 30)
    msgs = " ".join(f.message for f in findings if f.rule == "J9")
    assert "exceeds the" not in msgs


def test_j9_gates_on_aval_estimate_lower_bound():
    """Backends without memory_analysis still gate: the aval x
    sharding estimate (temp unknown) is a LOWER BOUND, and a lower
    bound over budget is over budget."""
    from dgen_tpu.lint.prog.meshaudit import MeshInfo
    from dgen_tpu.lint.prog.spec import ProgramAudit

    (spec,) = _fixture("bad_j9_overbudget").specs()
    audit = lower_spec(spec)
    est = MeshInfo(
        shape=audit.mesh.shape, n_devices=audit.mesh.n_devices,
        global_n=audit.mesh.global_n, collectives=[],
        replicated_global=[], outputs_unsharded=[],
        memory={"available": False, "estimated": True, "temp": None,
                "argument": 4 << 20, "output": 1 << 20},
    )
    assert est.peak_bytes == 5 << 20 and est.peak_is_lower_bound
    doctored = ProgramAudit(
        spec=audit.spec, jaxpr=audit.jaxpr, args_info=audit.args_info,
        fingerprint=audit.fingerprint, steady_fingerprint=None,
        const_bytes=0, oversized_consts=[], cost_analysis=None,
        mesh=est,
    )
    findings = run_program_rules(
        [doctored], select=["J9"], j9_budget_bytes=1 << 20
    )
    assert findings and "LOWER BOUND" in findings[0].message


def test_j7_stale_sweep_ignores_custom_shape_seeds(resharded_audits):
    """A deliberately merged custom-shape seed (--mesh-shapes ...
    --update-baselines) must not read as staleness on the next
    default-grid run; a same-shape ghost key still does."""
    bad, _clean = resharded_audits
    fp = baseline_mod.collect_mesh_fingerprints([bad])
    fp["ghost@mesh4x2"] = {
        "mesh_shape": [4, 2], "program_hash": "x",
        "collectives": {}, "comm_bytes": 0, "peak_bytes": 1,
    }
    fp["ghost@mesh1x2"] = {
        "mesh_shape": [1, 2], "program_hash": "x",
        "collectives": {}, "comm_bytes": 0, "peak_bytes": 1,
    }
    doc = {
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "spec": prog.AUDIT_SPEC_VERSION,
        "tolerance": 0.02, "entries": {}, "mesh": fp,
    }
    findings, _ = baseline_mod.compare_mesh_to_baseline([bad], doc)
    msgs = [f.message for f in findings]
    assert not any("mesh4x2" in m for m in msgs)   # custom seed kept
    assert any("ghost@mesh1x2" in m for m in msgs)  # real staleness


def test_j9_real_year_step_within_model_envelope():
    """The planner's _per_agent_step_bytes prediction holds for the
    real mesh-tier year step (the cross-check that validates
    auto_agent_chunk's budget math against the compiler)."""
    from dgen_tpu.lint.prog.registry import build_mesh_registry

    spec = next(
        s for s in build_mesh_registry(grid="fast")
        if s.entry == "year_step"
    )
    audit = lower_spec(spec)
    assert audit.error is None and audit.mesh is not None
    assert run_program_rules([audit], select=["J9"]) == []
    temp = audit.mesh.memory.get("temp")
    assert temp and audit.mesh.model_bytes
    # the compiler's measured temp stays inside the modeled envelope
    assert temp <= audit.mesh.model_bytes * 3.0


# ---------------------------------------------------------------------------
# baseline mesh-section merge semantics
# ---------------------------------------------------------------------------

def test_update_baseline_preserves_mesh_section(tmp_path,
                                                resharded_audits):
    bad, clean = resharded_audits
    path = str(tmp_path / "prog_baseline.json")
    # seed: entries (none cost-marked here) + mesh section
    baseline_mod.update_baseline(path, [], mesh_audits=[bad])
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    assert bad.spec.spec_id in doc["mesh"]
    # a cost-only refresh (mesh tier did not run) must carry the mesh
    # section over verbatim
    baseline_mod.update_baseline(path, [])
    with open(path, encoding="utf-8") as f:
        doc2 = json.load(f)
    assert doc2["mesh"] == doc["mesh"]
    # a partial mesh refresh merges instead of replacing
    baseline_mod.update_baseline(
        path, [], mesh_audits=[clean], mesh_partial=True,
    )
    with open(path, encoding="utf-8") as f:
        doc3 = json.load(f)
    assert bad.spec.spec_id in doc3["mesh"]
    assert clean.spec.spec_id in doc3["mesh"]
    # a FULL mesh refresh replaces same-shape keys but preserves
    # deliberately seeded custom-shape gates (foreign mesh_shape)
    with open(path, encoding="utf-8") as f:
        doc_c = json.load(f)
    doc_c["mesh"]["custom@mesh4x2"] = {
        "mesh_shape": [4, 2], "program_hash": "x",
        "collectives": {}, "comm_bytes": 0, "peak_bytes": 1,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc_c, f)
    baseline_mod.update_baseline(path, [], mesh_audits=[clean])
    with open(path, encoding="utf-8") as f:
        doc4 = json.load(f)
    assert bad.spec.spec_id not in doc4["mesh"]        # same shape: replaced
    assert "custom@mesh4x2" in doc4["mesh"]            # custom seed kept


# ---------------------------------------------------------------------------
# the enforcement contract: the mesh registry audits green
# ---------------------------------------------------------------------------

def test_mesh_registry_audits_green_fast():
    """The fast-tier mesh grid (the 2x4 hosts x devices shape) lowers,
    compiles, and passes J7-J10 against the committed baseline — the
    invariant `tools/check.sh` and CI gate at full grid depth."""
    findings, report = prog.audit_programs(
        grid="fast", with_cost=False, mesh=True,
    )
    assert findings == [], "\n".join(str(f) for f in findings)
    mesh_ids = set(report["mesh"])
    assert {
        "year_step@mesh2x4", "year_step_chunked@mesh2x4",
        "sweep_year_step@mesh2x4", "serve_query@mesh2x4",
        "size_agents@mesh2x4", "import_sums@mesh2x4",
        "bucket_sums@mesh2x4",
    } <= mesh_ids
    # the agent table stays sharded: the year step's comm stays in the
    # small reduction/gather class, no [N, 8760]-scale collective
    ys = report["mesh"]["year_step@mesh2x4"]
    assert ys["comm_bytes"] < 64 * 1024
    assert ys["peak_bytes"] and ys["peak_bytes"] < 64 * 2**20


@pytest.mark.slow
def test_mesh_registry_full_grid():
    """Full mesh grid (1x8 + the 2-D 2x4) with the committed baseline
    gate — every entry under >= 2 mesh shapes, J7-J10 enforced."""
    findings, report = prog.audit_programs(mesh=True)
    assert findings == [], "\n".join(str(f) for f in findings)
    shapes = {tuple(m["shape"]) for m in report["mesh"].values()}
    assert {(1, 8), (2, 4)} <= shapes
