"""Bad-data quarantine + numerical-health sentinel
(dgen_tpu.resilience.quarantine / dgen_tpu.models.health / the
supervisor's breach -> attribute -> quarantine -> resume loop)."""

import dataclasses
import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.config import RunConfig, ScenarioConfig
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation
from dgen_tpu.resilience import faults
from dgen_tpu.resilience.quarantine import (
    QuarantinedAgentError,
    QuarantineReport,
    apply_quarantine,
    quant_sidecar_bad_rows,
    validate_population,
)

N = 96
STATES = ["DE", "CA"]


def _pop(seed=11, n=N):
    return synth.generate_population(
        n, states=STATES, seed=seed, pad_multiple=64)


def _sim_parts(pop, end_year=2016):
    cfg = ScenarioConfig(
        name="q", start_year=2014, end_year=end_year, anchor_years=())
    inputs = scen.uniform_inputs(
        cfg, n_groups=pop.table.n_groups, n_regions=pop.n_regions)
    return cfg, inputs


def _make_sim(pop, cfg, inputs, rc=None, **kw):
    return Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, cfg,
        rc or RunConfig(sizing_iters=8), **kw,
    )


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------

def test_clean_population_validates_clean():
    pop = _pop()
    rep = validate_population(pop.table, pop.profiles, pop.tariffs)
    assert rep.is_clean
    assert rep.n_agents == N
    assert rep.summary()["n_quarantined"] == 0


def test_validation_flags_nonfinite_and_bad_references():
    pop = _pop()
    t = pop.table
    cust = np.array(np.asarray(t.customers_in_bin))
    cust[5] = np.nan
    lk = np.array(np.asarray(t.load_kwh_per_customer_in_bin))
    lk[7] = -1e4                       # negative load
    ti = np.array(np.asarray(t.tariff_idx))
    ti[9] = 999999                     # out-of-range tariff ref
    bad = dataclasses.replace(
        t, customers_in_bin=jnp.asarray(cust),
        load_kwh_per_customer_in_bin=jnp.asarray(lk),
        tariff_idx=jnp.asarray(ti),
    )
    rep = validate_population(bad, pop.profiles, pop.tariffs)
    assert rep.ids == (5, 7, 9)
    assert "nonfinite:customers_in_bin" in rep.reasons_for(5)
    assert "range:load_kwh_per_customer_in_bin" in rep.reasons_for(7)
    assert "index:tariff_idx" in rep.reasons_for(9)
    # padding rows are never validated
    assert all(r["row"] < t.n_agents for r in rep.records.values())


def test_validation_flags_bad_bank_row_and_referencing_agents():
    pop = _pop()
    load = np.array(np.asarray(pop.profiles.load))
    load[2] = np.nan
    profiles = dataclasses.replace(pop.profiles, load=jnp.asarray(load))
    rep = validate_population(pop.table, profiles, pop.tariffs)
    assert rep.bank_rows["load"] == [2]
    keep = np.asarray(pop.table.mask) > 0
    expected = sorted(
        int(a) for a in np.asarray(pop.table.agent_id)[
            keep & (np.asarray(pop.table.load_idx) == 2)]
    )
    assert list(rep.ids) == expected
    for a in expected:
        assert "bank:load[2]" in rep.reasons_for(a)


def test_quant_sidecar_zero_scale_all_zero_row_is_valid():
    # PR 12's floor path: an all-zero load row may carry scale 0.0
    # (quantize_rows stores 1.0; an external writer may store 0.0 —
    # dequantization is exact zero either way)
    codes = np.zeros((3, 8), np.int8)
    codes[1, :] = 5
    scales = np.asarray([0.0, 2.0, 1.0], np.float32)
    assert quant_sidecar_bad_rows(codes, scales).size == 0
    # zero scale under NONZERO codes flattens real data -> bad
    scales2 = np.asarray([0.0, 0.0, 1.0], np.float32)
    assert quant_sidecar_bad_rows(codes, scales2).tolist() == [1]
    # nonfinite / negative scales destroy the row
    scales3 = np.asarray([np.nan, 2.0, -1.0], np.float32)
    assert quant_sidecar_bad_rows(codes, scales3).tolist() == [0, 2]


def test_validation_refuses_wholesale_corruption_masquerade():
    # > MAX_QUARANTINE rows bad means the INPUT FILE is wrong; masking
    # it as quarantine would hide a pipeline bug
    from dgen_tpu.resilience import quarantine as q

    pop = _pop()
    cust = np.array(np.asarray(pop.table.customers_in_bin))
    cust[:] = np.nan
    bad = dataclasses.replace(
        pop.table, customers_in_bin=jnp.asarray(cust))
    old = q.MAX_QUARANTINE
    q.MAX_QUARANTINE = 10
    try:
        with pytest.raises(ValueError, match="refusing"):
            validate_population(bad, pop.profiles, pop.tariffs)
    finally:
        q.MAX_QUARANTINE = old


# ---------------------------------------------------------------------------
# containment
# ---------------------------------------------------------------------------

def test_apply_quarantine_clean_report_is_identity():
    pop = _pop()
    rep = QuarantineReport(n_agents=N)
    t2, p2 = apply_quarantine(pop.table, pop.profiles, rep)
    assert t2 is pop.table and p2 is pop.profiles


def test_apply_quarantine_makes_rows_inert_padding():
    pop = _pop()
    rep = QuarantineReport(n_agents=N)
    rep.add(4, 4, "test")
    rep.add_bank_row("load", 1)
    t2, p2 = apply_quarantine(pop.table, pop.profiles, rep)
    assert np.asarray(t2.mask)[4] == 0.0
    assert np.asarray(t2.agent_id)[4] == 4          # id preserved
    assert np.asarray(t2.customers_in_bin)[4] == 0.0
    assert np.asarray(t2.nem_kw_limit)[4] >= 1e29   # pad sentinel
    assert np.asarray(t2.switch_min_kw)[4] >= 1e29
    assert np.asarray(t2.tariff_idx)[4] == 0
    assert np.all(np.asarray(p2.load)[1] == 0.0)
    # dtypes/shapes unchanged -> same compiled program
    for f in dataclasses.fields(type(pop.table)):
        if f.name == "n_states":
            continue
        a, b = getattr(pop.table, f.name), getattr(t2, f.name)
        for la, lb in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            assert la.shape == lb.shape and la.dtype == lb.dtype


def test_report_roundtrips_through_json(tmp_path):
    rep = QuarantineReport(n_agents=5, context="load")
    rep.add(3, 3, "nonfinite:customers_in_bin")
    rep.add(3, 3, "index:tariff_idx")
    rep.add_bank_row("load", 2)
    p = str(tmp_path / "quarantine.json")
    rep.save(p)
    back = QuarantineReport.load(p)
    assert back.ids == (3,)
    assert back.reasons_for(3) == rep.reasons_for(3)
    assert back.bank_rows == {"load": [2]}
    assert back.n_agents == 5


def test_ingest_corruption_contained_bit_exact_vs_prequarantined():
    """The containment theorem: a corrupted-then-quarantined run is
    BIT-IDENTICAL to a clean run with the same rows pre-quarantined —
    the corrupt values influenced nothing that survived."""
    pop = _pop()
    with faults.injected("ingest_corrupt_row@1:corrupt") as reg:
        pop_c = _pop()
    assert reg.fired("ingest_corrupt_row") == 1
    cfg, inputs = _sim_parts(pop)
    sim_c = _make_sim(pop_c, cfg, inputs)
    assert sim_c.quarantine_report.ids == (3, 17)
    res_c = sim_c.run()
    rep = sim_c.quarantine_report
    sim_b = _make_sim(pop, cfg, inputs, quarantine=rep)
    res_b = sim_b.run()
    for k in res_c.agent:
        np.testing.assert_array_equal(res_c.agent[k], res_b.agent[k])


def test_quarantine_ids_config_round_trip():
    pop = _pop()
    cfg, inputs = _sim_parts(pop)
    rc = RunConfig(sizing_iters=8, quarantine_ids=(2, 11))
    sim = _make_sim(pop, cfg, inputs, rc=rc)
    assert set(sim.quarantine_report.ids) == {2, 11}
    assert "config:quarantine_ids" in sim.quarantine_report.reasons_for(2)
    assert np.asarray(sim.table.mask)[2] == 0.0


def test_validate_kill_switch(monkeypatch):
    monkeypatch.setenv("DGEN_TPU_VALIDATE", "0")
    assert not RunConfig().validate_enabled
    monkeypatch.setenv("DGEN_TPU_SENTINEL", "0")
    assert not RunConfig().sentinel_enabled
    monkeypatch.delenv("DGEN_TPU_VALIDATE")
    monkeypatch.delenv("DGEN_TPU_SENTINEL")
    assert RunConfig().validate_enabled
    assert RunConfig().sentinel_enabled
    assert RunConfig(validate_inputs=False, health_sentinel=False) \
        .validate_enabled is False


# ---------------------------------------------------------------------------
# the health sentinel
# ---------------------------------------------------------------------------

def test_sentinel_clean_run_reports_clean():
    pop = _pop()
    cfg, inputs = _sim_parts(pop)
    sim = _make_sim(pop, cfg, inputs)
    sim.run()
    assert sim.health_report is not None
    assert sim.health_report["clean"]


def test_health_summary_counts_masked_rows_only():
    from dgen_tpu.models import health

    class Outs:
        pass

    n = 8
    outs = Outs()
    for name, _, _ in health.HEALTH_CHECKS:
        setattr(outs, name, jnp.zeros(n, jnp.float32))
    # poison a PADDING row (mask 0) and a real row
    outs.npv = jnp.asarray(
        [np.nan, 0, 0, 0, 0, 0, 0, np.nan], jnp.float32)
    mask = jnp.asarray([1, 1, 1, 1, 1, 1, 1, 0], jnp.float32)
    s = np.asarray(health.health_summary(outs, mask))
    checks = health.check_host(s)
    assert checks == [{"leaf": "npv", "nonfinite": 1,
                       "out_of_bounds": 0}]
    # gross bound breach (finite garbage) counts too
    outs.npv = jnp.asarray([1e30] + [0.0] * 7, jnp.float32)
    checks = health.check_host(
        np.asarray(health.health_summary(outs, mask)))
    assert checks == [{"leaf": "npv", "nonfinite": 0,
                       "out_of_bounds": 1}]


def test_sentinel_breach_sync_path_attributes_exactly():
    """Mid-run bank corruption on the serialized path: the breach
    names the year and exactly the referencing agents."""
    from dgen_tpu.models.health import HealthBreachError

    pop = _pop()
    cfg, inputs = _sim_parts(pop)
    rc = RunConfig(
        sizing_iters=8, sentinel_escalate=True, async_host_io=False)
    sim = _make_sim(pop, cfg, inputs, rc=rc)
    with faults.injected("bank_corrupt_row@2:corrupt"):
        with pytest.raises(HealthBreachError) as ei:
            sim.run()
    err = ei.value
    assert err.year == 2016
    keep = np.asarray(pop.table.mask) > 0
    li = np.asarray(pop.table.load_idx)
    expected = sorted(
        int(a) for a in np.asarray(pop.table.agent_id)[keep & (li == 3)])
    assert list(err.agent_ids) == expected
    assert any(b["leaf"] == "npv" for b in err.breaches)
    assert sim._health_breaches          # recorded before the raise


def test_sentinel_breach_async_pipeline_path():
    """The async host-IO path: the summary rides the batched fetch
    (HealthConsumer) and the breach surfaces from the pipeline."""
    from dgen_tpu.models.health import HealthBreachError

    pop = _pop()
    cfg, inputs = _sim_parts(pop)
    rc = RunConfig(
        sizing_iters=8, sentinel_escalate=True, async_host_io=True)
    sim = _make_sim(pop, cfg, inputs, rc=rc)
    with faults.injected("bank_corrupt_row@2:corrupt"):
        with pytest.raises(HealthBreachError) as ei:
            sim.run(collect=True)
    assert ei.value.year == 2016
    assert len(ei.value.agent_ids) > 0


class _CaptureHandler(logging.Handler):
    """The repo logger sets propagate=False, so caplog misses it;
    capture by attaching a handler directly."""

    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


def _captured_dgen_log():
    h = _CaptureHandler()
    logging.getLogger("dgen_tpu").addHandler(h)
    return h


def test_sentinel_warn_only_by_default():
    """Plain (unsupervised) runs WARN on a breach instead of dying —
    escalation is the supervisor's contract."""
    pop = _pop()
    cfg, inputs = _sim_parts(pop)
    sim = _make_sim(
        pop, cfg, inputs,
        rc=RunConfig(sizing_iters=8, async_host_io=False))
    h = _captured_dgen_log()
    try:
        with faults.injected("bank_corrupt_row@2:corrupt"):
            sim.run()
    finally:
        logging.getLogger("dgen_tpu").removeHandler(h)
    assert sim.health_report is not None
    assert not sim.health_report["clean"]
    assert 2016 in sim.health_report["breaches"]
    assert any("health sentinel" in m for m in h.messages)


def test_classify_and_degrade_health():
    from dgen_tpu.models.health import HealthBreachError
    from dgen_tpu.resilience.supervisor import (
        HEALTH,
        AttemptContext,
        Supervisor,
        classify_error,
    )

    err = HealthBreachError(
        2016, 1, [{"leaf": "npv", "nonfinite": 3, "out_of_bounds": 0}],
        agent_rows=(4, 7), agent_ids=(4, 7),
    )
    assert classify_error(err) == HEALTH
    sup = Supervisor()
    rc = RunConfig(quarantine_ids=(2,))
    ctx = AttemptContext(attempt=0, run_config=rc, resume=False)
    rc2, desc, give_up = sup._degrade(rc, HEALTH, ctx, 0, exc=err)
    assert not give_up
    assert rc2.quarantine_ids == (2, 4, 7)
    assert "quarantined 2 agent(s)" in desc
    # the same offenders breaching THROUGH the quarantine = give up
    _, _, give_up2 = sup._degrade(rc2, HEALTH, ctx, 0, exc=err)
    assert give_up2


def test_supervised_breach_quarantines_and_recovers(tmp_path):
    """End-to-end mini sentinel loop: mid-run corruption -> breach ->
    attributed quarantine -> resume from the last checkpoint -> clean
    finish with quarantine.json + meta stamped."""
    from dgen_tpu.resilience.supervisor import run_supervised

    pop = _pop()
    cfg, inputs = _sim_parts(pop, end_year=2018)

    def make_sim(rc):
        rc = dataclasses.replace(rc, sizing_iters=8)
        return _make_sim(pop, cfg, inputs, rc=rc)

    run_dir = str(tmp_path / "run")
    with faults.injected("bank_corrupt_row@3:corrupt") as reg:
        res, report = run_supervised(
            make_sim, RunConfig(), run_dir=run_dir, collect=False,
        )
    assert reg.fired("bank_corrupt_row") == 1
    assert report.succeeded and report.retries >= 1
    assert any("health: quarantined" in d for d in report.degradations)
    q = json.load(open(os.path.join(run_dir, "quarantine.json")))
    keep = np.asarray(pop.table.mask) > 0
    li = np.asarray(pop.table.load_idx)
    expected = sorted(
        int(a) for a in np.asarray(pop.table.agent_id)[keep & (li == 3)])
    assert sorted(int(a) for a in q["agents"]) == expected
    meta = json.load(open(os.path.join(run_dir, "meta.json")))
    assert meta["quarantine"]["n_quarantined"] == len(expected)
    assert "config:quarantine_ids" in meta["quarantine"]["reasons"]
    # the breached year re-ran: its export excludes the quarantined ids
    import pandas as pd

    ids_2016 = pd.read_parquet(
        os.path.join(run_dir, "agent_outputs", "year=2016.parquet"),
        columns=["agent_id"],
    )["agent_id"].to_numpy()
    assert not np.isin(expected, ids_2016).any()
    # manifest verifies (quarantine.json is ledgered)
    from dgen_tpu.resilience.manifest import verify_run_dir

    assert all(r.ok for r in verify_run_dir(run_dir))


# ---------------------------------------------------------------------------
# serve: 422 for quarantined agents
# ---------------------------------------------------------------------------

def test_serve_answers_422_for_quarantined_agent():
    from dgen_tpu.serve.engine import ServeEngine

    pop = _pop()
    cfg, inputs = _sim_parts(pop)
    rc = RunConfig(sizing_iters=8, quarantine_ids=(7,))
    sim = _make_sim(pop, cfg, inputs, rc=rc)
    eng = ServeEngine(sim)
    with pytest.raises(QuarantinedAgentError) as ei:
        eng.rows_for([7])
    assert ei.value.agent_id == 7
    assert ei.value.reasons == ["config:quarantine_ids"]
    # unknown ids still read as 400-shaped KeyErrors
    with pytest.raises(KeyError):
        eng.rows_for([10 ** 9])
    # healthy ids still resolve
    assert eng.rows_for([1]).shape == (1,)


# ---------------------------------------------------------------------------
# invariants satellite: offending agent indices
# ---------------------------------------------------------------------------

def test_check_finite_names_offending_agent_rows():
    from dgen_tpu.utils.invariants import (
        InvariantViolation,
        check_finite,
        nonfinite_rows,
    )

    arr = np.zeros((6, 3), np.float32)
    arr[2, 1] = np.nan
    arr[5, 0] = np.inf
    assert nonfinite_rows(arr).tolist() == [2, 5]
    assert nonfinite_rows(arr, k=1).tolist() == [2]
    with pytest.raises(InvariantViolation, match=r"agent rows: \[2, 5\]"):
        check_finite({"x": arr}, context="t")


# ---------------------------------------------------------------------------
# export satellite: WARNING + per-leaf breakdown
# ---------------------------------------------------------------------------

def test_export_nonfinite_warning_and_per_leaf_breakdown(tmp_path):
    from dgen_tpu.io import export as exp

    n = 6
    ex = exp.RunExporter(
        str(tmp_path / "run"), agent_id=np.arange(n),
        mask=np.ones(n, np.float32), compact=True,
    )
    dirty = jnp.asarray([1.0, np.nan, 2.0, np.inf, -np.inf, 3.0],
                        jnp.float32)
    clean = jnp.arange(n, dtype=jnp.float32)
    h = _captured_dgen_log()
    try:
        ex._local_fields(
            [dirty, clean], quant=(True, True),
            names=("npv", "system_kw"), year=2016,
        )
    finally:
        logging.getLogger("dgen_tpu").removeHandler(h)
    assert any("'npv'" in m and "2016" in m for m in h.messages)
    ex._flush_meta()
    meta = json.load(open(tmp_path / "run" / "meta.json"))
    assert meta["nonfinite_zeroed"] == 3
    assert meta["quarantine"]["nonfinite_zeroed_by_field"] == {"npv": 3}
    # a stamped report summary MERGES with the breakdown
    ex.stamp_quarantine({"n_quarantined": 2, "reasons": {"x": 2}})
    meta = json.load(open(tmp_path / "run" / "meta.json"))
    assert meta["quarantine"]["n_quarantined"] == 2
    assert meta["quarantine"]["nonfinite_zeroed_by_field"] == {"npv": 3}


# ---------------------------------------------------------------------------
# the full drill (slow tier; check.sh runs the --fast smoke)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_full_quarantine_drill(tmp_path):
    from dgen_tpu.resilience.quarantinedrill import run_quarantine_drill

    rec = run_quarantine_drill(str(tmp_path), n_agents=96)
    assert rec["ok"], json.dumps(rec, indent=1)
    assert set(rec["rounds"]) == {"ingest", "bank", "sentinel"}
    assert rec["rounds"]["ingest"]["parquet_bit_exact"]
    assert rec["rounds"]["sentinel"]["retries"] >= 1
