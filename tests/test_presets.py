"""BASELINE.json preset registry: every config builds, and the small
one runs end to end with all three export surfaces (VERDICT r3 item 2:
the five benchmark configs exist as runnable presets)."""

import json
import os

import numpy as np
import pytest

from dgen_tpu import presets


def test_registry_covers_baseline_configs():
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BASELINE.json")) as f:
        base = json.load(f)
    assert len(presets.PRESETS) == len(base["configs"]) == 5
    # every BASELINE config line is carried verbatim by exactly one preset
    carried = {p.baseline_config for p in presets.PRESETS.values()}
    assert carried == set(base["configs"])


@pytest.mark.parametrize("name", sorted(presets.PRESETS))
def test_presets_build(name):
    sim, pop, meta = presets.build(name, n_agents=256)
    p = presets.PRESETS[name]
    assert sim.scenario.storage_enabled == p.storage_enabled
    assert sim.with_hourly == p.with_hourly
    assert list(sim.years)[0] == p.start_year
    # reference mount present in CI: trajectories must be ingested
    if os.path.isdir(presets.REFERENCE_INPUT_ROOT):
        assert meta["data_sources"], meta
    # sector mix respected (res-only presets carry no com/ind agents)
    if p.sector_weights[1] == 0.0:
        keep = np.asarray(pop.table.mask) > 0
        assert np.all(np.asarray(pop.table.sector_idx)[keep] == 0)


@pytest.mark.slow
def test_delaware_preset_runs_with_exports(tmp_path):
    rec = presets.run_preset(
        "delaware-res", n_agents=96, run_dir=str(tmp_path / "run"))
    assert rec["years"] == 6 and rec["agents"] == 96
    assert rec["total_s"] > 0 and rec["export_overlapped_s"] >= 0

    from dgen_tpu.io.export import load_surface

    run_dir = str(tmp_path / "run")
    agent = load_surface(run_dir, "agent_outputs")
    assert len(agent) == 96 * 6
    assert len(load_surface(run_dir, "finance_series")) == 96 * 6
    assert len(load_surface(run_dir, "state_hourly")) > 0
    with open(os.path.join(run_dir, "meta.json")) as f:
        meta = json.load(f)
    assert meta["preset"] == "delaware-res"
    assert "baseline_config" in meta and "data_sources" in meta
