"""dgenlint unit tests: every rule L1-L8 with at least one positive
(known-bad snippet -> finding) and one negative (idiomatic code ->
clean), suppression comments, jit-reachability scoping, the bad-snippet
fixture files, the CLI exit codes, and — the enforcement contract —
the dgen_tpu codebase itself linting clean."""

import os
import subprocess
import sys

import pytest

from dgen_tpu import lint
from dgen_tpu.lint import lint_paths, lint_source

FIXTURES = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint"
)

JIT_HEADER = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "import numpy as np\n"
)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# L1 — host syncs
# ---------------------------------------------------------------------------

def test_l1_positive_host_sync_in_jit():
    src = JIT_HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    a = np.asarray(x)\n"
        "    b = float(jnp.sum(x))\n"
        "    c = x.item()\n"
        "    return a, b, c\n"
    )
    hits = [f for f in lint_source(src) if f.rule == "L1"]
    assert len(hits) == 3
    assert {h.line for h in hits} == {6, 7, 8}


def test_l1_negative_host_code_and_literals():
    src = JIT_HEADER + (
        "def compile_bank(spec):\n"          # host-side: not jit-reachable
        "    return np.asarray(spec['price'])\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    scale = float('inf')\n"          # literal: allowed
        "    n = int(x.shape[0])\n"           # static shape math: allowed
        "    return x * scale + n\n"
    )
    assert "L1" not in rules_of(lint_source(src))


def test_l1_reaches_through_helper_calls():
    """A helper only CALLED from jitted code is still jit-reachable."""
    src = JIT_HEADER + (
        "def helper(x):\n"
        "    return x.tolist()\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return helper(x)\n"
    )
    hits = [f for f in lint_source(src) if f.rule == "L1"]
    assert [h.line for h in hits] == [5]


# ---------------------------------------------------------------------------
# L2 — Python control flow on arrays
# ---------------------------------------------------------------------------

def test_l2_positive_if_on_array():
    src = JIT_HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    if jnp.any(x > 0):\n"
        "        return x\n"
        "    return -x\n"
    )
    assert "L2" in rules_of(lint_source(src))


def test_l2_negative_static_branch():
    src = JIT_HEADER + (
        "@jax.jit\n"
        "def f(x, *, first_year):\n"
        "    if first_year:\n"               # static kwarg: fine
        "        return x\n"
        "    if x.ndim > 1:\n"               # shape attr: fine
        "        return x[0]\n"
        "    return -x\n"
    )
    assert "L2" not in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# L3 — float64 hygiene
# ---------------------------------------------------------------------------

def test_l3_positive_f64_device_array_and_jit_widening():
    src = JIT_HEADER + (
        "TABLE = jnp.zeros((4, 4), dtype=jnp.float64)\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.astype(np.float64)\n"
    )
    hits = [f for f in lint_source(src) if f.rule == "L3"]
    assert len(hits) == 2


def test_l3_negative_host_f64_and_f32_device():
    src = JIT_HEADER + (
        "def normalize(spec):\n"             # host ingest: f64 is fine
        "    return np.asarray(spec, dtype=np.float64)\n"
        "BANK = jnp.zeros((4, 4), dtype=jnp.float32)\n"
    )
    assert "L3" not in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# L4 — data-dependent shapes
# ---------------------------------------------------------------------------

def test_l4_positive_dynamic_shape():
    src = JIT_HEADER + (
        "@jax.jit\n"
        "def f(mask):\n"
        "    return jnp.zeros(jnp.sum(mask))\n"
    )
    assert "L4" in rules_of(lint_source(src))


def test_l4_negative_static_shapes():
    src = JIT_HEADER + (
        "N_STATES = 51\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = jnp.zeros(x.shape[0])\n"
        "    b = jnp.zeros((N_STATES, 8760))\n"
        "    c = jnp.zeros_like(x)\n"
        "    return a, b, c\n"
    )
    assert "L4" not in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# L5 — layering
# ---------------------------------------------------------------------------

def test_l5_positive_ops_importing_models():
    src = "from dgen_tpu.models import market\n"
    hits = lint_source(src, modname="dgen_tpu.ops.badkernel")
    assert "L5" in rules_of(hits)


def test_l5_positive_models_importing_store():
    src = "from dgen_tpu.io.store import open_store\n"
    hits = lint_source(src, modname="dgen_tpu.models.badmodel")
    assert "L5" in rules_of(hits)


def test_l5_relative_imports_resolve():
    """Relative imports resolve against the right package for both a
    package __init__ (its own modname IS the package) and a plain
    module (drop the final segment first)."""
    # dgen_tpu/ops/__init__.py: `from ..models import market`
    hits = lint_source(
        "from ..models import market\n",
        filename="ops/__init__.py", modname="dgen_tpu.ops",
    )
    assert "L5" in rules_of(hits)
    # dgen_tpu/models/badmod.py: `from ..io.store import open_store`
    hits = lint_source(
        "from ..io.store import open_store\n",
        filename="models/badmod.py", modname="dgen_tpu.models.badmod",
    )
    assert "L5" in rules_of(hits)
    # level-1 inside the same package is NOT a cross-layer import
    hits = lint_source(
        "from . import tariff\n",
        filename="ops/__init__.py", modname="dgen_tpu.ops",
    )
    assert "L5" not in rules_of(hits)


def test_l5_negative_allowed_imports():
    # ops -> parallel/utils is allowed; models -> io.checkpoint is too
    src = (
        "from dgen_tpu.parallel.mesh import AGENT_AXIS\n"
        "from dgen_tpu.utils import timing\n"
    )
    assert "L5" not in rules_of(
        lint_source(src, modname="dgen_tpu.ops.goodkernel"))
    src2 = "from dgen_tpu.io import checkpoint\n"
    assert "L5" not in rules_of(
        lint_source(src2, modname="dgen_tpu.models.goodmodel"))


# ---------------------------------------------------------------------------
# L6 — Pallas block shapes
# ---------------------------------------------------------------------------

_PALLAS_HEADER = (
    "from jax.experimental import pallas as pl\n"
    "import jax.numpy as jnp\n"
)


def test_l6_positive_misaligned_blockspec():
    src = _PALLAS_HEADER + (
        "HOURS = 8760\n"
        "S1 = pl.BlockSpec((8, HOURS), lambda i: (i, 0))\n"   # lane
        "S2 = pl.BlockSpec((12, 128), lambda i: (i, 0))\n"    # sublane
    )
    hits = [f for f in lint_source(src) if f.rule == "L6"]
    assert {h.line for h in hits} == {4, 5}


def test_l6_positive_f64_in_pallas_module():
    src = _PALLAS_HEADER + (
        "def kernel(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...].astype(jnp.float64)\n"
    )
    assert "L6" in rules_of(lint_source(src))


def test_l6_negative_aligned_and_dynamic():
    src = _PALLAS_HEADER + (
        "H_PAD = 8832\n"
        "MONTH_SLOT = 768\n"
        "H_MONTHS = 12 * MONTH_SLOT\n"        # folded: 9216 % 128 == 0
        "def build(r_pad):\n"
        "    a = pl.BlockSpec((1, 1, H_PAD), lambda i: (i, 0, 0))\n"
        "    b = pl.BlockSpec((1, 1, H_MONTHS), lambda i: (i, 0, 0))\n"
        "    c = pl.BlockSpec((1, r_pad, 128), lambda i: (i, 0, 0))\n"
        "    return a, b, c\n"
    )
    assert "L6" not in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# L7 — carry donation
# ---------------------------------------------------------------------------

def test_l7_positive_missing_donation():
    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def year_step(table, carry, n):\n"
        "    return carry\n"
        "@jax.jit\n"
        "def other_step(carry):\n"
        "    return carry\n"
    )
    hits = [f for f in lint_source(src) if f.rule == "L7"]
    assert len(hits) == 2


def test_l7_negative_donated_or_no_carry():
    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnames=('carry',))\n"
        "def year_step(table, carry):\n"
        "    return carry\n"
        "@jax.jit\n"
        "def stateless(x):\n"
        "    return x\n"
    )
    assert "L7" not in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# L8 — debug leftovers
# ---------------------------------------------------------------------------

def test_l8_positive_debug_in_jit():
    src = JIT_HEADER + (
        "import pdb\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    jax.debug.print('x {}', x)\n"
        "    print('tracing')\n"
        "    return x\n"
    )
    hits = [f for f in lint_source(src) if f.rule == "L8"]
    assert len(hits) == 3  # import pdb + jax.debug.print + print


def test_l8_negative_host_print():
    src = JIT_HEADER + (
        "def main():\n"
        "    print('summary')\n"             # host entrypoint: fine
    )
    assert "L8" not in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# suppression + scoping mechanics
# ---------------------------------------------------------------------------

def test_suppression_comment_disables_one_rule():
    src = JIT_HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(jnp.sum(x))  # dgenlint: disable=L1\n"
    )
    assert lint_source(src) == []


def test_suppression_is_rule_specific():
    src = JIT_HEADER + (
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(jnp.sum(x))  # dgenlint: disable=L2\n"
    )
    assert "L1" in rules_of(lint_source(src))


def test_file_level_suppression():
    src = (
        "# dgenlint: disable-file=L5\n"
        "from dgen_tpu.models import market\n"
    )
    assert lint_source(src, modname="dgen_tpu.ops.legacy") == []


def test_select_unknown_rule_raises():
    with pytest.raises(ValueError):
        lint_source("x = 1\n", select=["L99"])


def test_jit_wrapper_assignment_marks_root():
    """``f = jax.jit(g)`` makes g jit-reachable."""
    src = JIT_HEADER + (
        "def g(x):\n"
        "    return x.item()\n"
        "g_fast = jax.jit(g)\n"
    )
    assert "L1" in rules_of(lint_source(src))


# ---------------------------------------------------------------------------
# fixtures, codebase, CLI
# ---------------------------------------------------------------------------

def test_bad_fixture_files_each_trigger_their_rule():
    findings = lint_paths([FIXTURES])
    got = rules_of(findings)
    for rule in ("L1", "L2", "L3", "L4", "L6", "L7", "L8", "L10", "L11"):
        assert rule in got, f"{rule} not triggered by its fixture"


def test_codebase_is_clean():
    """The enforcement contract: the repo lints clean, so any new
    finding is a regression introduced by the change under review."""
    findings = lint_paths()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_exit_codes_and_output():
    bad = subprocess.run(
        [sys.executable, "-m", "dgen_tpu.lint", FIXTURES],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert bad.returncode == 1
    assert "L1" in bad.stdout and "findings" in bad.stderr

    rules = subprocess.run(
        [sys.executable, "-m", "dgen_tpu.lint", "--list-rules"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert rules.returncode == 0
    for rule in ("L1", "L8"):
        assert rule in rules.stdout
