"""Launch harness: state binning, shard command emission, env plumbing,
distributed persistence, and the federal ITC schedule
(cluster-orchestration analogues, SURVEY.md §2.6 L7)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from dgen_tpu.models.scenario import federal_itc_schedule
from dgen_tpu.parallel.launch import (
    bin_states,
    initialize_multihost,
    shard_commands,
    shard_states_from_env,
)

# the subprocess launch tests are multi-minute (each boots fresh jax
# processes) and carry the slow mark individually; the pure-unit tests
# below run in tier-1
slow = pytest.mark.slow


def test_bin_states_size_ordering():
    sizes = {"CA": 5000, "TX": 4000, "NY": 3000, "DE": 100, "VT": 50,
             "RI": 60, "WY": 40, "FL": 2500}
    bins = bin_states(sizes, n_bins=4)
    assert len(bins.bins) == 4
    assert sorted(bins.flat()) == sorted(sizes)
    # biggest states land in the last bin (the reference's large_states
    # bin gets the beefiest machine shape, submit_all.sh)
    assert "CA" in bins.bins[-1]
    assert "WY" in bins.bins[0]


def test_shard_commands_env_round_trip(monkeypatch):
    bins = bin_states({"CA": 10, "DE": 1, "TX": 8}, n_bins=2)
    cmds = shard_commands(bins, entry="run")
    assert len(cmds) == 2
    assert all("DGEN_SHARD_INDEX=" in c and "DGEN_SHARD_STATES=" in c
               for c in cmds)
    # simulate the launched task's env and read the state list back
    states_str = cmds[1].split("DGEN_SHARD_STATES=")[1].split(" ")[0]
    monkeypatch.setenv("DGEN_SHARD_STATES", states_str)
    got = shard_states_from_env()
    assert got == bins.bins[1]


def test_initialize_multihost_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("DGEN_COORDINATOR", raising=False)
    assert initialize_multihost() is False


def test_initialize_multihost_names_missing_env_var(monkeypatch):
    """A coordinator with no peer-count/rank env must fail with a
    ValueError naming the missing variable (not a bare KeyError) —
    operators debugging a half-configured launch read the message, not
    the traceback."""
    monkeypatch.setenv("DGEN_COORDINATOR", "127.0.0.1:1234")
    monkeypatch.delenv("DGEN_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("DGEN_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="DGEN_NUM_PROCESSES"):
        initialize_multihost()
    monkeypatch.setenv("DGEN_NUM_PROCESSES", "2")
    with pytest.raises(ValueError, match="DGEN_PROCESS_ID"):
        initialize_multihost()
    # a non-integer value gets the same friendly treatment
    monkeypatch.setenv("DGEN_PROCESS_ID", "zero")
    with pytest.raises(ValueError, match="DGEN_PROCESS_ID"):
        initialize_multihost()
    # empty string counts as missing, not as int("") noise
    monkeypatch.setenv("DGEN_PROCESS_ID", "")
    with pytest.raises(ValueError, match="DGEN_PROCESS_ID"):
        initialize_multihost()


def test_federal_itc_schedule_values():
    years = [2014, 2020, 2024, 2033, 2034, 2036]
    sch = federal_itc_schedule(years)
    assert sch.shape == (6, 3)
    np.testing.assert_allclose(sch[0], 0.30)
    np.testing.assert_allclose(sch[1], 0.26)
    np.testing.assert_allclose(sch[2], 0.30)
    np.testing.assert_allclose(sch[3], 0.26)
    np.testing.assert_allclose(sch[4], 0.22)
    np.testing.assert_allclose(sch[5], [0.0, 0.10, 0.10])


@slow
def test_distributed_run_persists_and_resumes(tmp_path):
    """A jax.distributed-initialized mesh run must write checkpoints
    plus all three parquet surfaces, and resume across a process
    restart — the behavior the reference gets from always-persisted
    per-task outputs (dgen_model.py:459-462). Runs in a subprocess
    because jax.distributed is process-global state."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = textwrap.dedent(f"""
        import os, sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
        jax.distributed.initialize(
            coordinator_address="127.0.0.1:{port}",
            num_processes=1, process_id=0,
        )
        assert jax.process_count() == 1 and len(jax.devices()) == 8
        import numpy as np
        import jax.numpy as jnp
        from dgen_tpu.config import RunConfig, ScenarioConfig
        from dgen_tpu.io import synth
        from dgen_tpu.io.export import RunExporter
        from dgen_tpu.models import scenario as scen
        from dgen_tpu.models.simulation import Simulation
        from dgen_tpu.parallel.launch import run_with_recovery
        from dgen_tpu.parallel.mesh import make_mesh

        run_dir = {str(tmp_path / "run")!r}
        cfg = ScenarioConfig(name="dist", start_year=2014, end_year=2018,
                             anchor_years=())
        pop = synth.generate_population(96, states=["DE", "CA"], seed=3,
                                        pad_multiple=64)
        inputs = scen.uniform_inputs(cfg, n_groups=pop.table.n_groups,
                                     n_regions=pop.n_regions)

        def build():
            return Simulation(pop.table, pop.profiles, pop.tariffs,
                              inputs, cfg, RunConfig(sizing_iters=6),
                              mesh=make_mesh(), with_hourly=True)

        phase = sys.argv[1]
        sim = build()
        exporter = RunExporter(
            run_dir, agent_id=sim.host_agent_id, mask=sim.host_mask)
        if phase == "first":
            res = run_with_recovery(sim, run_dir + "/ckpt",
                                    callback=exporter, collect=False)
            assert len(res.years) == 3
            print("FIRST_OK")
        else:
            # restart: drop the final year's checkpoint so the resumed
            # run must actually re-execute 2018 from the 2016 carry
            from dgen_tpu.io import checkpoint as ckpt
            assert ckpt.latest_year(run_dir + "/ckpt") == 2018
            import orbax.checkpoint as ocp
            with ocp.CheckpointManager(run_dir + "/ckpt") as mgr:
                mgr.delete(2018)
            res = sim.run(checkpoint_dir=run_dir + "/ckpt", resume=True,
                          callback=exporter)
            assert res.years == [2018], res.years
            # sharded restore really lands on the mesh
            _, carry = ckpt.restore_year(
                run_dir + "/ckpt", sim.table.n_agents, 2018,
                sharding=sim._shard)
            assert not carry.market.market_share.is_fully_replicated
            print("RESUME_OK")
    """)
    env = {**os.environ, "PYTHONUNBUFFERED": "1"}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for phase in ("first", "resume"):
        proc = subprocess.run(
            [sys.executable, "-c", script, phase],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=repo_root,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert f"{phase.upper()}_OK" in proc.stdout

    # all three surfaces exist and reassemble
    from dgen_tpu.io.export import load_surface

    run_dir = str(tmp_path / "run")
    agent = load_surface(run_dir, "agent_outputs")
    assert set(agent["year"]) == {2014, 2016, 2018}
    assert (agent.groupby("year").size() == 96).all()
    fin = load_surface(run_dir, "finance_series")
    assert len(fin) == 3 * 96
    hourly = load_surface(run_dir, "state_hourly")
    assert len(hourly["state"].unique()) > 0


@slow
def test_two_process_distributed_run_persists_shards(tmp_path):
    """TRUE multi-process run: two jax.distributed processes (4 CPU
    devices each, gloo collectives) over one 8-device global mesh,
    WITH agent-axis chunking — the national configuration: the
    shard-major streaming year step (simulation._to_chunks) plus hourly
    rematerialization run under jax.process_count() > 1. Exercises the
    real multi-host surfaces end to end — global-array placement from
    host copies, shard_map'd kernels over remote meshes, orbax
    collective checkpointing, and the exporter's addressable-shard
    parquet parts — and pins the per-agent results against a
    single-process UNCHUNKED reference run."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    # shared between the subprocess script and the host-side reference
    # run, so the parity comparison cannot drift: 8 states -> one whole
    # state per device, so BOTH processes hold real agents (fewer
    # states would pack every agent onto process 0's devices)
    STATES = ["DE", "CA", "TX", "NY", "FL", "WA", "CO", "IL"]
    N_AGENTS, SEED, PAD, ITERS = 96, 3, 64, 6

    script = textwrap.dedent(f"""
        import sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 4)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            coordinator_address="127.0.0.1:{port}",
            num_processes=2, process_id=pid,
        )
        assert jax.process_count() == 2 and len(jax.devices()) == 8
        from dgen_tpu.config import RunConfig, ScenarioConfig
        from dgen_tpu.io import synth
        from dgen_tpu.io.export import RunExporter
        from dgen_tpu.models import scenario as scen
        from dgen_tpu.models.simulation import Simulation
        from dgen_tpu.parallel.mesh import make_mesh

        run_dir = {str(tmp_path / "run")!r}
        cfg = ScenarioConfig(name="mp", start_year=2014, end_year=2018,
                             anchor_years=())
        pop = synth.generate_population(
            {N_AGENTS}, states={STATES!r}, seed={SEED},
            pad_multiple={PAD})
        inputs = scen.uniform_inputs(cfg, n_groups=pop.table.n_groups,
                                     n_regions=pop.n_regions)
        sim = Simulation(pop.table, pop.profiles, pop.tariffs,
                         inputs, cfg,
                         RunConfig(sizing_iters={ITERS}, agent_chunk=4),
                         mesh=make_mesh(), with_hourly=True)
        assert sim._agent_chunk == 4, sim._agent_chunk
        exporter = RunExporter(
            run_dir, agent_id=sim.host_agent_id, mask=sim.host_mask)
        res = sim.run(callback=exporter, collect=False,
                      checkpoint_dir=run_dir + "/ckpt")
        assert len(res.years) == 3
        from dgen_tpu.io import checkpoint as ckpt
        assert ckpt.latest_year(run_dir + "/ckpt") == 2018
        print(f"P{{pid}}_OK")
    """)
    env = {**os.environ, "PYTHONUNBUFFERED": "1"}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # file-backed output (no pipe-buffer deadlock between coordinated
    # processes) + kill on any failure so neither leaks holding the
    # coordinator port
    logs = [open(tmp_path / f"p{pid}.log", "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid)],
            stdout=logs[pid], stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo_root,
        )
        for pid in (0, 1)
    ]
    try:
        for p in procs:
            p.wait(timeout=900)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    for pid, p in enumerate(procs):
        out = (tmp_path / f"p{pid}.log").read_text()
        assert p.returncode == 0, f"p{pid}: {out[-3000:]}"
        assert f"P{pid}_OK" in out

    # per-process parquet parts with disjoint agents that union to all
    import pandas as pd

    run_dir = str(tmp_path / "run")
    part = {
        pid: pd.read_parquet(
            os.path.join(run_dir, "agent_outputs",
                         f"year=2014-p{pid}.parquet"))
        for pid in (0, 1)
    }
    ids0, ids1 = set(part[0]["agent_id"]), set(part[1]["agent_id"])
    assert ids0 and ids1 and not (ids0 & ids1), "shards must be disjoint"
    assert len(ids0 | ids1) == 96

    # state-hourly (replicated surface) written once, by process 0
    from dgen_tpu.io.export import load_surface

    hourly = load_surface(run_dir, "state_hourly")
    assert len(hourly) > 0

    # per-agent parity against a single-process reference run
    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation

    cfg = ScenarioConfig(name="mp", start_year=2014, end_year=2018,
                         anchor_years=())
    pop = synth.generate_population(
        N_AGENTS, states=STATES, seed=SEED, pad_multiple=PAD)
    inputs = scen.uniform_inputs(cfg, n_groups=pop.table.n_groups,
                                 n_regions=pop.n_regions)
    sim_ref = Simulation(pop.table, pop.profiles, pop.tariffs, inputs,
                         cfg, RunConfig(sizing_iters=ITERS))
    res_ref = sim_ref.run()
    agent = load_surface(run_dir, "agent_outputs")
    y0 = agent[agent["year"] == 2014].set_index("agent_id").sort_index()
    keep = np.asarray(pop.table.mask) > 0
    ref_kw = res_ref.agent["system_kw_cum"][0][keep]
    ref_ids = np.asarray(pop.table.agent_id)[keep]
    order = np.argsort(ref_ids)
    np.testing.assert_allclose(
        y0["system_kw_cum"].to_numpy(),
        ref_kw[order], rtol=5e-4, atol=1e-3,
    )


@slow
def test_launch_main_executes_shard_commands(tmp_path):
    """The flagship L7 entrypoint (``python -m dgen_tpu.parallel.launch``)
    must actually run: two single-process shards launched EXACTLY as
    ``shard_commands`` emits them (env-prefixed shell lines, the
    submit_all.sh analogue), each producing a run dir with provenance
    meta and all three parquet surfaces."""
    bins = bin_states({"DE": 1.0, "CA": 10.0}, n_bins=2)
    cmds = shard_commands(bins)
    assert len(cmds) == 2
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    for i, cmd in enumerate(cmds):
        run_dir = str(tmp_path / f"shard_{i}")
        env = {
            **os.environ,
            # in-process platform pin (site hooks override JAX_PLATFORMS)
            "DGEN_PLATFORM": "cpu",
            "DGEN_AGENTS": "48",
            "DGEN_END_YEAR": "2016",
            "DGEN_RUN_DIR": run_dir,
            "PYTHONUNBUFFERED": "1",
        }
        env.pop("XLA_FLAGS", None)  # single device: fastest CI shape
        proc = subprocess.run(
            cmd, shell=True, capture_output=True, text=True,
            timeout=900, env=env, cwd=repo_root,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert f"shard {i}" in proc.stdout

        # provenance meta stamped up front (VERDICT r3 item 4)
        import json

        with open(os.path.join(run_dir, "meta.json")) as f:
            meta = json.load(f)
        assert meta["shard"] == i
        assert meta["states"] == bins.bins[i]
        assert meta["n_processes"] == 1 and meta["distributed"] is False
        assert "market_curves" in meta and "data_sources" in meta

        from dgen_tpu.io.export import load_surface

        agent = load_surface(run_dir, "agent_outputs")
        assert set(agent["year"]) == {2014, 2016}
        assert len(load_surface(run_dir, "finance_series")) == len(agent)
        # recovery wiring left a resumable checkpoint behind
        from dgen_tpu.io import checkpoint as ckpt

        assert ckpt.latest_year(os.path.join(run_dir, "ckpt")) == 2016


@slow
def test_launch_main_two_process_coordinator(tmp_path):
    """``main()`` through the DGEN_COORDINATOR/DGEN_NUM_PROCESSES env
    contract: two real processes bring up jax.distributed (gloo), run
    the same launch entrypoint, and persist disjoint per-process
    parquet shards plus coordinator-written meta."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    run_dir = str(tmp_path / "run")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {
        **os.environ,
        # in-process platform pin: the site hook pins its own platform
        # at interpreter startup, so with plain JAX_PLATFORMS env the
        # default backend stays non-cpu and process_count() reads 1
        "DGEN_PLATFORM": "cpu",
        "DGEN_CPU_DEVICES": "4",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "DGEN_COORDINATOR": f"127.0.0.1:{port}",
        "DGEN_NUM_PROCESSES": "2",
        "DGEN_SHARD_STATES": "DE,CA,TX,NY,FL,WA,CO,IL",
        "DGEN_AGENTS": "96",
        "DGEN_END_YEAR": "2016",
        "DGEN_RUN_DIR": run_dir,
        "PYTHONUNBUFFERED": "1",
    }
    base_env.pop("XLA_FLAGS", None)  # the legacy count flag, if inherited
    logs = [open(tmp_path / f"p{pid}.log", "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "dgen_tpu.parallel.launch"],
            stdout=logs[pid], stderr=subprocess.STDOUT, text=True,
            env={**base_env, "DGEN_PROCESS_ID": str(pid)}, cwd=repo_root,
        )
        for pid in (0, 1)
    ]
    try:
        for p in procs:
            p.wait(timeout=900)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    for pid, p in enumerate(procs):
        out = (tmp_path / f"p{pid}.log").read_text()
        assert p.returncode == 0, f"p{pid}: {out[-3000:]}"
        assert "shard 0" in out

    import json

    with open(os.path.join(run_dir, "meta.json")) as f:
        meta = json.load(f)
    assert meta["distributed"] is True and meta["n_processes"] == 2

    import pandas as pd

    part = {
        pid: pd.read_parquet(
            os.path.join(run_dir, "agent_outputs",
                         f"year=2014-p{pid}.parquet"))
        for pid in (0, 1)
    }
    ids0, ids1 = set(part[0]["agent_id"]), set(part[1]["agent_id"])
    assert ids0 and ids1 and not (ids0 & ids1)
    assert len(ids0 | ids1) == 96


@slow
def test_run_with_recovery_resumes_after_crash(tmp_path):
    """A mid-run crash resumes from the last checkpoint on retry
    (the maxRetryCount analogue, but checkpoint-granular)."""
    import jax.numpy as jnp

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation
    from dgen_tpu.parallel.launch import run_with_recovery

    cfg = ScenarioConfig(name="rec", start_year=2014, end_year=2020,
                         anchor_years=())
    pop = synth.generate_population(32, states=["DE"], seed=1, pad_multiple=8)
    inputs = scen.uniform_inputs(cfg, n_groups=pop.table.n_groups,
                                 n_regions=pop.n_regions)
    sim = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                     RunConfig(sizing_iters=6))

    calls = {"n": 0}
    orig_step = sim.step

    def flaky_step(carry, yi, first_year):
        calls["n"] += 1
        if calls["n"] == 3:  # die inside year 3 of attempt 1
            raise RuntimeError("injected crash")
        return orig_step(carry, yi, first_year)

    sim.step = flaky_step
    res = run_with_recovery(sim, str(tmp_path / "ckpt"), max_retries=2)
    # attempt 1 ran years 1-2 then died; attempt 2 resumes after the
    # last DURABLE checkpoint (orbax saves are async, so the year-2
    # save may not have committed before the crash)
    assert res.years[0] in (2016, 2018)
    assert res.years[-1] == 2020

    # clean reference run matches the recovered tail
    sim2 = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                      RunConfig(sizing_iters=6))
    res2 = sim2.run()
    i = res2.years.index(res.years[0])
    np.testing.assert_allclose(
        res.agent["system_kw_cum"][0], res2.agent["system_kw_cum"][i],
        rtol=1e-5)
