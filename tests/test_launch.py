"""Launch harness: state binning, shard command emission, env plumbing,
and the federal ITC schedule (cluster-orchestration analogues,
SURVEY.md §2.6 L7)."""

import numpy as np

from dgen_tpu.models.scenario import federal_itc_schedule
from dgen_tpu.parallel.launch import (
    bin_states,
    initialize_multihost,
    shard_commands,
    shard_states_from_env,
)


def test_bin_states_size_ordering():
    sizes = {"CA": 5000, "TX": 4000, "NY": 3000, "DE": 100, "VT": 50,
             "RI": 60, "WY": 40, "FL": 2500}
    bins = bin_states(sizes, n_bins=4)
    assert len(bins.bins) == 4
    assert sorted(bins.flat()) == sorted(sizes)
    # biggest states land in the last bin (the reference's large_states
    # bin gets the beefiest machine shape, submit_all.sh)
    assert "CA" in bins.bins[-1]
    assert "WY" in bins.bins[0]


def test_shard_commands_env_round_trip(monkeypatch):
    bins = bin_states({"CA": 10, "DE": 1, "TX": 8}, n_bins=2)
    cmds = shard_commands(bins, entry="run")
    assert len(cmds) == 2
    assert all("DGEN_SHARD_INDEX=" in c and "DGEN_SHARD_STATES=" in c
               for c in cmds)
    # simulate the launched task's env and read the state list back
    states_str = cmds[1].split("DGEN_SHARD_STATES=")[1].split(" ")[0]
    monkeypatch.setenv("DGEN_SHARD_STATES", states_str)
    got = shard_states_from_env()
    assert got == bins.bins[1]


def test_initialize_multihost_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("DGEN_COORDINATOR", raising=False)
    assert initialize_multihost() is False


def test_federal_itc_schedule_values():
    years = [2014, 2020, 2024, 2033, 2034, 2036]
    sch = federal_itc_schedule(years)
    assert sch.shape == (6, 3)
    np.testing.assert_allclose(sch[0], 0.30)
    np.testing.assert_allclose(sch[1], 0.26)
    np.testing.assert_allclose(sch[2], 0.30)
    np.testing.assert_allclose(sch[3], 0.26)
    np.testing.assert_allclose(sch[4], 0.22)
    np.testing.assert_allclose(sch[5], [0.0, 0.10, 0.10])


def test_run_with_recovery_resumes_after_crash(tmp_path):
    """A mid-run crash resumes from the last checkpoint on retry
    (the maxRetryCount analogue, but checkpoint-granular)."""
    import jax.numpy as jnp

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation
    from dgen_tpu.parallel.launch import run_with_recovery

    cfg = ScenarioConfig(name="rec", start_year=2014, end_year=2020,
                         anchor_years=())
    pop = synth.generate_population(32, states=["DE"], seed=1, pad_multiple=8)
    inputs = scen.uniform_inputs(cfg, n_groups=pop.table.n_groups,
                                 n_regions=pop.n_regions)
    sim = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                     RunConfig(sizing_iters=6))

    calls = {"n": 0}
    orig_step = sim.step

    def flaky_step(carry, yi, first_year):
        calls["n"] += 1
        if calls["n"] == 3:  # die inside year 3 of attempt 1
            raise RuntimeError("injected crash")
        return orig_step(carry, yi, first_year)

    sim.step = flaky_step
    res = run_with_recovery(sim, str(tmp_path / "ckpt"), max_retries=2)
    # attempt 1 ran years 1-2 then died; attempt 2 resumes after the
    # last DURABLE checkpoint (orbax saves are async, so the year-2
    # save may not have committed before the crash)
    assert res.years[0] in (2016, 2018)
    assert res.years[-1] == 2020

    # clean reference run matches the recovered tail
    sim2 = Simulation(pop.table, pop.profiles, pop.tariffs, inputs, cfg,
                      RunConfig(sizing_iters=6))
    res2 = sim2.run()
    i = res2.years.index(res.years[0])
    np.testing.assert_allclose(
        res.agent["system_kw_cum"][0], res2.agent["system_kw_cum"][i],
        rtol=1e-5)
