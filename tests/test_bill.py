"""Bill engine vs the NumPy oracle across tariff styles."""

import numpy as np
import pytest

import jax.numpy as jnp

from dgen_tpu.io import synth
from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops import tariff as tariff_ops

HOURS = tariff_ops.HOURS


def _net_load(seed=0):
    rng = np.random.default_rng(seed)
    load = 1.0 + 0.5 * np.sin(np.arange(HOURS) / 24.0) + 0.2 * rng.random(HOURS)
    gen = np.zeros(HOURS)
    hod = np.arange(HOURS) % 24
    day = (hod > 6) & (hod < 18)
    gen[day] = 3.0 * np.sin(np.pi * (hod[day] - 6) / 12.0)
    return (load - gen).astype(np.float32)


def _bank():
    return synth.make_tariff_bank()


@pytest.mark.parametrize("k", range(6))
def test_annual_bill_matches_oracle(k):
    from tests.oracles import oracle_annual_bill

    bank = _bank()
    net = _net_load(seed=k)
    ts_sell = np.full(HOURS, 0.04, dtype=np.float32)

    at = bill_ops.gather_tariff(bank, jnp.asarray(k))
    got = float(
        bill_ops.annual_bill(
            jnp.asarray(net), at, jnp.asarray(ts_sell), bank.max_periods
        )
    )
    want = oracle_annual_bill(
        net_load=net,
        hour_period=np.asarray(bank.hour_period)[k],
        price=np.asarray(bank.price)[k],
        tier_cap=np.asarray(bank.tier_cap)[k],
        fixed_monthly=float(bank.fixed_monthly[k]),
        metering=int(bank.metering[k]),
        ts_sell=ts_sell,
        sell_price=np.asarray(bank.sell_price)[k],
    )
    assert got == pytest.approx(want, rel=1e-4), f"tariff {k}"


def test_tier_cap_binds():
    """Monthly energy crossing the tier-1 cap is billed at tier-2."""
    bank = _bank()  # tariff 2: tiers at 0.10/0.16, cap 500
    k = 2
    # constant 1 kW import -> ~730 kWh/month
    net = np.ones(HOURS, dtype=np.float32)
    at = bill_ops.gather_tariff(bank, jnp.asarray(k))
    got = float(bill_ops.annual_bill(jnp.asarray(net), at, jnp.zeros(HOURS), bank.max_periods))
    # expected: per month, 500*0.10 + (hours-500)*0.16 + fixed 12
    expect = 0.0
    for m in range(12):
        h = tariff_ops.MONTH_HOURS[m + 1] - tariff_ops.MONTH_HOURS[m]
        expect += 500 * 0.10 + (h - 500) * 0.16 + 12.0
    assert got == pytest.approx(expect, rel=1e-5)


def test_net_metering_credits_exports_at_retail():
    bank = _bank()
    k = 0  # flat NEM @ 0.12, fixed 10
    net = np.ones(HOURS, dtype=np.float32)
    net[: HOURS // 2] = -1.0  # export half the year
    at = bill_ops.gather_tariff(bank, jnp.asarray(k))
    got = float(bill_ops.annual_bill(jnp.asarray(net), at, jnp.zeros(HOURS), bank.max_periods))
    # signed monthly sums: first half-year months net negative (credited),
    # second half positive — exact mirror -> energy charges cancel
    assert got == pytest.approx(12 * 10.0, abs=1e-2)


def test_net_billing_asymmetry():
    """Net billing buys at retail, sells at the TS rate."""
    bank = _bank()
    k = 1  # flat NB @ 0.13, fixed 8
    net = np.ones(HOURS, dtype=np.float32)
    net[: HOURS // 2] = -1.0
    ts_sell = np.full(HOURS, 0.05, dtype=np.float32)
    at = bill_ops.gather_tariff(bank, jnp.asarray(k))
    got = float(bill_ops.annual_bill(jnp.asarray(net), at, jnp.asarray(ts_sell), bank.max_periods))
    imports = float(np.maximum(net, 0).sum())
    exports = float(np.maximum(-net, 0).sum())
    want = imports * 0.13 - exports * 0.05 + 12 * 8.0
    assert got == pytest.approx(want, rel=1e-4)


def test_bill_series_escalation_and_degradation():
    bank = _bank()
    k = 1
    rng = np.random.default_rng(0)
    load = rng.uniform(0.5, 2.0, HOURS).astype(np.float32)
    gen = np.zeros(HOURS, dtype=np.float32)
    hod = np.arange(HOURS) % 24
    gen[(hod > 7) & (hod < 17)] = 2.0
    at = bill_ops.gather_tariff(bank, jnp.asarray(k))
    ts_sell = np.full(HOURS, 0.03, dtype=np.float32)

    bills_w, bills_wo = bill_ops.bill_series(
        jnp.asarray(load), jnp.asarray(gen), at, jnp.asarray(ts_sell),
        inflation=jnp.asarray(0.025), escalation=jnp.asarray(0.01),
        degradation=jnp.asarray(0.005), n_periods=bank.max_periods, n_years=5,
    )
    bills_w, bills_wo = np.asarray(bills_w), np.asarray(bills_wo)
    # no-system bill grows at the combined nominal escalation
    ratio = bills_wo[1:] / bills_wo[:-1]
    np.testing.assert_allclose(ratio, (1.025 * 1.01), rtol=1e-5)
    # with-system bill is lower, and the gap narrows as PV degrades
    savings = bills_wo - bills_w
    deflated = savings / bills_wo
    assert np.all(savings > 0)
    assert deflated[-1] < deflated[0]


def test_vmapped_bill_over_agents():
    import jax

    bank = _bank()
    n = 8
    rng = np.random.default_rng(1)
    nets = rng.uniform(-1, 2, (n, HOURS)).astype(np.float32)
    idxs = jnp.asarray(np.arange(n) % bank.n_tariffs)
    ts_sell = jnp.zeros((n, HOURS), dtype=jnp.float32)

    def one(net, k, ts):
        at = bill_ops.gather_tariff(bank, k)
        return bill_ops.annual_bill(net, at, ts, bank.max_periods)

    out = jax.vmap(one)(jnp.asarray(nets), idxs, ts_sell)
    assert out.shape == (n,)
    assert np.all(np.isfinite(np.asarray(out)))
