"""Hardware validation of the HBM auto-chunk model (VERDICT r4 item 8):
over the net_billing x with_hourly x rate_switch grid, the model's
chosen chunk must run a chunked year step on the real chip without
exhausting memory, and the end-of-run modeled-vs-actual check must
produce a record.

Opt-in (DGEN_TPU_TESTS=1) — the default suite pins the virtual CPU
platform where memory_stats and the HBM envelope don't exist.
"""

import os

import numpy as np
import pytest

pytestmark = [pytest.mark.tpu_hw, pytest.mark.slow]

if os.environ.get("DGEN_TPU_TESTS", "") in ("", "0", "false"):
    pytest.skip("needs the real TPU (DGEN_TPU_TESTS=1)",
                allow_module_level=True)


GRID = [
    # (net_billing via binding caps, with_hourly, rate_switch, agents)
    (False, False, False, 65536),
    (False, True, False, 65536),
    (False, False, True, 65536),
    (False, True, True, 49152),
    (True, False, False, 32768),
    (True, True, False, 32768),
    (True, False, True, 32768),
    (True, True, True, 32768),
]


def _build(nb: bool, hourly: bool, rs: bool, n: int):
    import dataclasses as dc

    import jax.numpy as jnp
    import numpy as _np

    from dgen_tpu.config import RunConfig, ScenarioConfig
    from dgen_tpu.io import synth
    from dgen_tpu.models import scenario as scen
    from dgen_tpu.models.simulation import Simulation

    cfg = ScenarioConfig(name="hbm", start_year=2014, end_year=2016,
                         anchor_years=())
    pop = synth.generate_population(
        n, seed=5, pad_multiple=256,
        rate_switch_frac=0.5 if rs else 0.0,
    )
    table = pop.table
    if not nb:
        # the default synth bank mixes metering styles; the all-NEM
        # static skip needs every referenced tariff (incl. switch
        # targets) on net metering — remap onto the NEM tariff ids
        rng = _np.random.default_rng(0)
        nem_ids = _np.asarray([0, 2, 5], _np.int32)   # synth NEM tariffs
        tidx = jnp.asarray(nem_ids[rng.integers(0, 3, table.n_agents)])
        # keep the rate-switch flag by switching BETWEEN NEM tariffs
        sw = jnp.asarray(nem_ids[rng.integers(0, 3, table.n_agents)]) \
            if rs else tidx
        table = dc.replace(table, tariff_idx=tidx, tariff_switch_idx=sw)
    overrides = {"attachment_rate": jnp.full((table.n_groups,), 0.3)}
    if nb:
        years = list(cfg.model_years)
        caps = _np.full((len(years), table.n_states), 1e30, _np.float32)
        caps[1:, ::2] = 0.0
        overrides["nem_cap_kw"] = jnp.asarray(caps)
    inputs = scen.uniform_inputs(
        cfg, n_groups=table.n_groups, n_regions=pop.n_regions,
        overrides=overrides,
    )
    sim = Simulation(
        table, pop.profiles, pop.tariffs, inputs, cfg,
        RunConfig(sizing_iters=10, agent_chunk=None),  # auto chunk
        with_hourly=hourly,
    )
    return sim


@pytest.mark.parametrize("nb,hourly,rs,n", GRID)
def test_auto_chunk_survives_on_hardware(nb, hourly, rs, n):
    sim = _build(nb, hourly, rs, n)
    assert sim._net_billing == nb
    assert sim._rate_switch == rs
    # the grid populations are sized to exceed each config's whole-table
    # envelope so the chunk model actually engages
    assert sim._agent_chunk > 0, (
        f"population {n} should exceed the whole-table envelope for "
        f"nb={nb} hourly={hourly} rs={rs}"
    )
    res = sim.run(collect=False)   # OOM here = the model chose wrong
    assert len(res.years) == 2
    check = getattr(sim, "hbm_check", None)
    assert check is not None, "end-of-run modeled-vs-actual check missing"
    assert check["modeled_step_bytes"] > 0
    # device_peak_bytes is None on tunneled devices (no memory_stats);
    # surviving the run at the model-chosen chunk is the hard check,
    # the peak/model ratio is extra calibration signal when available
    print(f"nb={nb} hourly={hourly} rs={rs} n={n} "
          f"chunk={check['agent_chunk']} "
          f"peak/model={check['peak_over_model']}")
