"""Golden parity against the reference's OWN executable bill spec.

The reference ships a pure-NumPy, PySAM-free bill engine —
``bill_calculator`` (reference tariff_functions.py:701, "Deprecated...
kept for reference") — which SURVEY.md §4 names as the independent
numerical oracle for the bill math. These tests import it straight from
the reference mount and assert :func:`dgen_tpu.ops.bill.annual_bill`
reproduces it on randomized compiled tariffs x load/gen profiles for
both metering styles, converting the engine's correctness claim from
"self-consistent" to "reference-faithful".

Scope note: the oracle's ``tiered_calc_vec`` (tariff_functions.py:679)
prices the bracket containing the monthly total as
``(v - L[t-1]) * p[t] + L[t-1] * p[t-1]`` — for 3+ tiers this drops the
revenue of tiers below t-1, where SSC (and this repo) accumulate every
tier cumulatively. The randomized tariffs here therefore use <= 2 tiers,
where the two formulas coincide exactly; multi-tier accumulation is
covered by tests/test_bill.py against hand-computed cases.
"""

import importlib.util
import os
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.ops import bill as bill_ops
from dgen_tpu.ops.tariff import (
    BIG_CAP,
    NET_BILLING,
    NET_METERING,
    compile_tariffs,
)

REF_TF = "/root/reference/dgen_os/python/tariff_functions.py"

# environment-bound: needs the reference repo mounted at /root/reference
pytestmark = pytest.mark.skipif(
    not os.path.exists(REF_TF),
    reason="reference mount not present (oracle parity needs "
           "/root/reference)",
)


@pytest.fixture(scope="module")
def ref_tf():
    spec = importlib.util.spec_from_file_location("ref_tariff_functions", REF_TF)
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except ImportError as e:  # pragma: no cover - env without requests
        pytest.skip(f"reference tariff_functions not importable: {e}")
    return mod


def _random_spec(rng, metering):
    """A randomized raw tariff spec within the oracle's exact-parity
    envelope (<= 2 tiers; see module docstring)."""
    n_p = int(rng.integers(1, 5))
    n_t = int(rng.integers(1, 3))
    price = rng.uniform(0.05, 0.45, (n_p, n_t))
    # tiers must be increasing in price for realism (not required)
    price = np.sort(price, axis=1)
    spec = {
        "price": price.tolist(),
        "fixed_charge": float(rng.uniform(0.0, 30.0)),
        "metering": metering,
        "e_wkday_12by24": rng.integers(0, n_p, (12, 24)).tolist(),
        "e_wkend_12by24": rng.integers(0, n_p, (12, 24)).tolist(),
    }
    if n_t > 1:
        spec["tier_cap"] = [float(rng.uniform(150.0, 700.0)), BIG_CAP]
    return spec


def _oracle_inputs(bank, k, ref_tf):
    """Build the reference Tariff/Export_Tariff stand-ins from one
    compiled bank row (true extents, padding stripped)."""
    p = int(bank.n_periods[k])
    t = int(bank.n_tiers[k])
    price = np.asarray(bank.price[k, :p, :t], dtype=np.float64)   # [P, T]
    caps = np.asarray(bank.tier_cap[k, :t], dtype=np.float64)     # [T]
    tariff = types.SimpleNamespace(
        e_prices=price.T.copy(),                                  # [T, P]
        e_levels=np.tile(caps[:, None], (1, p)),                  # [T, P]
        e_tou_8760=np.asarray(bank.hour_period[k], dtype=np.int64).copy(),
        fixed_charge=float(bank.fixed_monthly[k]),
    )
    export_nem = ref_tf.Export_Tariff(full_retail_nem=True)
    return tariff, export_nem


def _profiles(rng, n):
    """(load, gen) pairs with meaningful export hours."""
    hours = np.arange(8760)
    hod = hours % 24
    solar = np.clip(np.sin((hod - 6) / 12 * np.pi), 0.0, None)
    season = 1.0 + 0.3 * np.sin(hours / 8760 * 2 * np.pi)
    out = []
    for _ in range(n):
        load = rng.uniform(0.3, 1.5) * (
            0.6 + 0.5 * rng.random(8760)
        ) * season
        gen = rng.uniform(1.0, 4.0) * solar * (0.7 + 0.3 * rng.random(8760))
        out.append((load.astype(np.float32), gen.astype(np.float32)))
    return out


def test_nem_bills_match_reference_oracle(ref_tf):
    rng = np.random.default_rng(11)
    specs = [_random_spec(rng, NET_METERING) for _ in range(10)]
    bank = compile_tariffs(specs)
    profiles = _profiles(rng, 10)

    for k, (load, gen) in enumerate(profiles):
        net = load - gen
        at = bill_ops.gather_tariff(bank, jnp.int32(k))
        got = float(bill_ops.annual_bill(
            jnp.asarray(net), at, jnp.zeros(8760, jnp.float32),
            bank.max_periods,
        ))
        tariff, export_nem = _oracle_inputs(bank, k, ref_tf)
        want, _ = ref_tf.bill_calculator(net.astype(np.float64), tariff, export_nem)
        assert got == pytest.approx(want, rel=2e-4, abs=1.5), (
            f"tariff {k}: NEM bill {got} vs oracle {want}"
        )


def test_net_billing_bills_match_reference_oracle(ref_tf):
    rng = np.random.default_rng(23)
    specs = [_random_spec(rng, NET_BILLING) for _ in range(10)]
    bank = compile_tariffs(specs)
    profiles = _profiles(rng, 10)

    for k, (load, gen) in enumerate(profiles):
        net = load - gen
        sell = float(rng.uniform(0.02, 0.10))
        at = bill_ops.gather_tariff(bank, jnp.int32(k))
        got = float(bill_ops.annual_bill(
            jnp.asarray(net), at, jnp.full(8760, sell, jnp.float32),
            bank.max_periods,
        ))
        tariff, _ = _oracle_inputs(bank, k, ref_tf)
        export = ref_tf.Export_Tariff()
        export.set_constant_sell_price(sell)
        want, _ = ref_tf.bill_calculator(net.astype(np.float64), tariff, export)
        assert got == pytest.approx(want, rel=2e-4, abs=1.5), (
            f"tariff {k}: net-billing bill {got} vs oracle {want}"
        )


def test_no_system_bill_matches_reference_oracle(ref_tf):
    """Pure-consumption bills (the counterfactual side of every energy
    value) must agree too, including tier crossings."""
    rng = np.random.default_rng(37)
    specs = [_random_spec(rng, NET_METERING) for _ in range(6)]
    bank = compile_tariffs(specs)
    profiles = _profiles(rng, 6)

    for k, (load, _) in enumerate(profiles):
        at = bill_ops.gather_tariff(bank, jnp.int32(k))
        got = float(bill_ops.annual_bill(
            jnp.asarray(load), at, jnp.zeros(8760, jnp.float32),
            bank.max_periods,
        ))
        tariff, export_nem = _oracle_inputs(bank, k, ref_tf)
        want, _ = ref_tf.bill_calculator(load.astype(np.float64), tariff, export_nem)
        assert got == pytest.approx(want, rel=2e-4, abs=1.0)
