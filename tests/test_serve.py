"""Serving engine tests (dgen_tpu.serve): bucket-coalescing parity,
steady-state compile stability (RetraceGuard), backpressure, scenario
overrides, the timing histogram, the L10 lint rule, and the HTTP
front-end.

The parity contract under test is the microbatcher's: an agent's
answer is BIT-IDENTICAL whether its request ran alone or coalesced
with strangers into the same padded bucket (per-row math; padding rows
are inert). Across DIFFERENT bucket shapes XLA may re-associate f32
reductions, so cross-shape answers agree to ~1e-6 relative — asserted
separately, with the tolerance documented in docs/serve.md.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dgen_tpu.config import RunConfig, ScenarioConfig, ServeConfig
from dgen_tpu.io import synth
from dgen_tpu.models import scenario as scen
from dgen_tpu.models.simulation import Simulation
from dgen_tpu.serve import (
    Microbatcher,
    OverrideError,
    QueueFullError,
    ServeEngine,
    apply_overrides,
    override_key,
)

CFG = ScenarioConfig(
    name="serve-test", start_year=2014, end_year=2020, anchor_years=()
)
SERVE_CFG = ServeConfig(
    max_batch=8, min_bucket=1, max_wait_ms=50.0, max_queue=32, port=0
)


@pytest.fixture(scope="module")
def engine():
    pop = synth.generate_population(192, seed=3)
    inputs = scen.uniform_inputs(
        CFG, n_groups=pop.table.n_groups, n_regions=pop.n_regions
    )
    sim = Simulation(
        pop.table, pop.profiles, pop.tariffs, inputs, CFG, RunConfig(),
        econ_years=6,
    )
    eng = ServeEngine(sim)
    eng.warmup(SERVE_CFG.buckets)
    return eng


# ---------------------------------------------------------------------------
# Parity: coalesced bucket vs the direct single-shot program
# ---------------------------------------------------------------------------

def test_coalesced_bucket_is_bit_exact_vs_single_shot(engine):
    """Three concurrent single-agent requests coalesce into one padded
    bucket; each answer must be bit-exact with the same agent run
    alone through the direct program at that bucket shape."""
    ids = [5, 17, 100]
    bat = Microbatcher(
        engine, ServeConfig(max_batch=8, min_bucket=1, max_wait_ms=200.0,
                            max_queue=32, port=0),
    )
    try:
        futs = [bat.submit([i], year=2016) for i in ids]
        got = [f.result(60.0) for f in futs]
    finally:
        bat.close()
    stats = bat.stats()
    # the deadline flush coalesced all three into ONE padded bucket
    assert stats["batches"] == 1
    assert stats["rows"] == 3
    assert stats["batch_occupancy"] == pytest.approx(3 / 4)
    for j, i in enumerate(ids):
        direct = engine.query([i], year=2016, bucket=4)
        for f in ("system_kw", "npv", "payback_period", "cash_flow",
                  "first_year_bill_with_system", "bill_savings_y1",
                  "batt_kw", "batt_kwh"):
            np.testing.assert_array_equal(
                got[j][f][0], direct[f][0],
                err_msg=f"bucket-path {f} differs for agent {i}",
            )
        assert int(got[j]["agent_id"][0]) == i


def test_cross_shape_drift_is_f32_reassociation_only(engine):
    """Across DIFFERENT compiled bucket shapes XLA may re-associate
    f32 reductions; answers agree to ~1e-6 rel (docs/serve.md)."""
    ids = [5, 17, 100]
    exact = engine.query(ids, year=2016)            # direct shape [3]
    padded = engine.query(ids, year=2016, bucket=8)
    for f in ("system_kw", "npv", "payback_period", "bill_savings_y1"):
        np.testing.assert_allclose(
            exact[f], padded[f], rtol=1e-5, atol=1e-4,
        )


def test_padding_rows_are_inert(engine):
    """The same request padded into different-occupancy buckets of the
    SAME shape is bit-identical (what coalescing relies on)."""
    a = engine.query([7], year=2014, bucket=8)
    b = engine.query([7, 33, 64, 101], year=2014, bucket=8)
    for f in ("system_kw", "npv", "cash_flow"):
        np.testing.assert_array_equal(a[f][0], b[f][0])


# ---------------------------------------------------------------------------
# Steady-state compile stability
# ---------------------------------------------------------------------------

def test_steady_state_compiles_nothing_after_warmup(engine):
    """One compile per bucket size, all paid at warmup: steady-state
    traffic across agents, years, bucket sizes AND override variants
    must compile and trace nothing (RetraceGuard budget 0)."""
    from dgen_tpu.lint.guard import RetraceGuard

    bat = Microbatcher(engine, SERVE_CFG)
    try:
        with RetraceGuard(context="serve steady state"):
            for b in SERVE_CFG.buckets:
                engine.query_rows(
                    np.arange(b, dtype=np.int32), year_idx=1, bucket=None
                )
            bat.query([3], year=2018, timeout=60.0)
            bat.query([9, 12], year=2014,
                      overrides={"scale": {"itc_fraction": 0.0}},
                      timeout=60.0)
            bat.query([9, 12], year=2014,
                      overrides={"set": {"itc_fraction": 0.26}},
                      timeout=60.0)
    finally:
        bat.close()


# ---------------------------------------------------------------------------
# Microbatcher: backpressure, validation, lifecycle
# ---------------------------------------------------------------------------

def test_backpressure_rejects_over_limit_queue(engine):
    bat = Microbatcher(
        engine,
        ServeConfig(max_batch=8, max_wait_ms=1000.0, max_queue=2, port=0),
        start=False,   # worker never drains: deterministic queue state
    )
    f1 = bat.submit([1], year=2014)
    f2 = bat.submit([2], year=2014)
    with pytest.raises(QueueFullError, match="back off"):
        bat.submit([3], year=2014)
    assert bat.stats()["rejected"] == 1
    assert bat.stats()["queue_depth"] == 2
    bat.close()
    # close() fails queued futures instead of leaving callers hung
    for f in (f1, f2):
        with pytest.raises(RuntimeError, match="closed"):
            f.result(1.0)


def test_submit_validates_on_caller_thread(engine):
    bat = Microbatcher(engine, SERVE_CFG, start=False)
    try:
        with pytest.raises(KeyError, match="unknown agent_id"):
            bat.submit([10**9], year=2014)
        with pytest.raises(KeyError, match="not on the model grid"):
            bat.submit([1], year=1999)
        with pytest.raises(KeyError, match="not on the model grid"):
            bat.submit([1], year=2016.7)   # no silent truncation
        with pytest.raises(ValueError, match="max_batch"):
            bat.submit(list(range(9)), year=2014)
        with pytest.raises(OverrideError, match="unknown ScenarioInputs"):
            bat.submit([1], overrides={"set": {"no_such_field": 1.0}})
        with pytest.raises(ValueError, match="empty"):
            bat.submit([], year=2014)
        assert bat.stats()["queue_depth"] == 0
    finally:
        bat.close()


def test_concurrent_producers_lose_and_duplicate_nothing(engine):
    """N producer threads race M submits each through one batcher:
    every accepted request resolves exactly once with its own agent's
    row, and the queue accounting balances — requests == resolved,
    rejected == observed rejections, final depth 0.  This is the
    runtime contract behind the dgenlint C1/C4 audit of submit()'s
    admission path."""
    n_threads, per_thread = 8, 24
    bat = Microbatcher(
        engine,
        ServeConfig(max_batch=8, min_bucket=1, max_wait_ms=5.0,
                    max_queue=64, port=0),
    )
    futures = {}      # agent_id -> Future (ids are globally unique)
    fut_lock = threading.Lock()
    rejections = []
    barrier = threading.Barrier(n_threads)

    def produce(t):
        barrier.wait()   # maximal contention on the first submit
        for k in range(per_thread):
            aid = t * per_thread + k
            while True:
                try:
                    f = bat.submit([aid], year=2016)
                except QueueFullError:
                    rejections.append(aid)
                    time.sleep(0.002)
                    continue
                with fut_lock:
                    assert aid not in futures, f"duplicate accept {aid}"
                    futures[aid] = f
                break

    threads = [threading.Thread(target=produce, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120.0)
    try:
        total = n_threads * per_thread
        assert len(futures) == total
        for aid, f in futures.items():
            got = f.result(60.0)
            assert list(got["agent_id"]) == [aid]
    finally:
        bat.close()
    stats = bat.stats()
    total = n_threads * per_thread
    assert stats["requests"] == total     # every accept resolved once
    assert stats["rows"] == total         # no lost or duplicated rows
    # list.append is GIL-atomic, so the rejection tally is exact
    assert stats["rejected"] == len(rejections)
    assert stats["queue_depth"] == 0
    assert stats["batches"] >= total // 8


# ---------------------------------------------------------------------------
# Scenario overrides
# ---------------------------------------------------------------------------

def test_overrides_change_answers_not_programs(engine):
    ids = [5, 17, 100]
    base = engine.query(ids, year=2016, bucket=8)
    noitc = engine.query(
        ids, year=2016, overrides={"scale": {"itc_fraction": 0.0}},
        bucket=8,
    )
    # zeroing the ITC can only hurt NPV (and strictly hurts any agent
    # with nonzero capex)
    assert np.all(noitc["npv"] <= base["npv"] + 1e-6)
    assert np.any(noitc["npv"] < base["npv"] - 1.0)

    # variants are pytree-compatible with the base inputs
    v = apply_overrides(
        engine.sim.inputs, {"set": {"itc_fraction": 0.26}}
    )
    leaf = v.itc_fraction
    assert leaf.shape == engine.sim.inputs.itc_fraction.shape
    assert leaf.dtype == engine.sim.inputs.itc_fraction.dtype
    np.testing.assert_allclose(np.asarray(leaf), 0.26)

    with pytest.raises(OverrideError, match="unknown override op"):
        apply_overrides(engine.sim.inputs, {"replace": {"x": 1}})
    with pytest.raises(OverrideError, match="does not fit"):
        # itc_fraction is [Y, 3]; a length-2 vector cannot broadcast
        apply_overrides(
            engine.sim.inputs, {"set": {"itc_fraction": [1.0, 2.0]}}
        )
    # integer trajectory fields reject lossy what-ifs instead of
    # silently truncating (loan_term_yrs is int32)
    with pytest.raises(OverrideError, match="lossy integer"):
        apply_overrides(
            engine.sim.inputs, {"set": {"loan_term_yrs": 12.7}}
        )
    with pytest.raises(OverrideError, match="lossy integer"):
        # loan_term_yrs is all 20s; 20 * 0.77 = 15.4 lands off-grid
        apply_overrides(
            engine.sim.inputs, {"scale": {"loan_term_yrs": 0.77}}
        )
    # an exactly-representable integer scale is accepted (20 -> 10)
    half = apply_overrides(
        engine.sim.inputs, {"scale": {"loan_term_yrs": 0.5}}
    )
    np.testing.assert_array_equal(np.asarray(half.loan_term_yrs), 10)
    v15 = apply_overrides(
        engine.sim.inputs, {"set": {"loan_term_yrs": 15}}
    )
    assert v15.loan_term_yrs.dtype == engine.sim.inputs.loan_term_yrs.dtype
    np.testing.assert_array_equal(np.asarray(v15.loan_term_yrs), 15)

    # canonical key: dict order does not split coalescing groups
    k1 = override_key({"scale": {"a": 1.0, "b": 2.0}})
    k2 = override_key({"scale": {"b": 2.0, "a": 1.0}})
    assert k1 == k2
    assert override_key(None) == override_key({}) == ""

    # the resolved variant is cached (same placed arrays per key)
    i1 = engine.inputs_for({"scale": {"itc_fraction": 0.5}})
    i2 = engine.inputs_for({"scale": {"itc_fraction": 0.5}})
    assert i1 is i2


# ---------------------------------------------------------------------------
# Timing histogram (utils.timing)
# ---------------------------------------------------------------------------

def test_log_histogram_percentiles_and_report():
    from dgen_tpu.utils import timing

    timing.reset_timings()
    try:
        h = timing.LogHistogram()
        for v in [0.001] * 90 + [0.1] * 9 + [2.0]:
            h.record(v)
        snap = h.snapshot()
        assert snap["count"] == 100
        # bucket resolution is the growth factor (sqrt2 ~ ±19%)
        assert snap["p50"] == pytest.approx(0.001, rel=0.5)
        assert snap["p99"] == pytest.approx(0.1, rel=0.5)
        assert snap["max"] == pytest.approx(2.0)
        assert snap["p50"] <= snap["p90"] <= snap["p99"] <= snap["max"]
        # empty histogram is all zeros, no division error
        assert timing.LogHistogram().snapshot()["p99"] == 0.0

        # observe() + timing_report percentiles, with ctx filtering
        for ms in (1, 1, 1, 50):
            timing.observe("req", ms / 1e3, ctx="serveA")
        rep = timing.timing_report(ctx="serveA")
        assert rep["req"]["count"] == 4
        assert "p99" in rep["req"] and "p50" in rep["req"]
        assert rep["req"]["p50"] <= rep["req"]["p99"]
        assert timing.timing_report(ctx="other") == {}
        # global report sees the prefixed key
        assert "serveA:req" in timing.timing_report()
    finally:
        timing.reset_timings()


# ---------------------------------------------------------------------------
# dgenlint L10
# ---------------------------------------------------------------------------

def test_l10_flags_request_path_jit_and_supports_suppression():
    from dgen_tpu.lint import lint_paths, lint_source

    fixture = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "lint", "bad_l10_request_jit.py",
    )
    hits = [f for f in lint_paths([fixture]) if f.rule == "L10"]
    assert len(hits) == 3   # do_POST, handle_query, on_request

    src = (
        "import jax\n"
        "def handle_query(x):\n"
        "    return jax.jit(lambda y: y)(x)"
        "  # dgenlint: disable=L10\n"
    )
    assert [f for f in lint_source(src) if f.rule == "L10"] == []

    # non-request functions building jits at init are fine
    src_ok = (
        "import jax\n"
        "def build_programs():\n"
        "    return jax.jit(lambda y: y)\n"
    )
    assert [f for f in lint_source(src_ok) if f.rule == "L10"] == []

    # a call-form-decorated def NESTED in a handler is one defect,
    # reported exactly once (not once per AST branch)
    src_nested = (
        "import jax\n"
        "from functools import partial\n"
        "def handle_query(x):\n"
        "    @partial(jax.jit, static_argnames=('n',))\n"
        "    def inner(y, n):\n"
        "        return y * n\n"
        "    return inner(x, n=2)\n"
    )
    assert len(
        [f for f in lint_source(src_nested) if f.rule == "L10"]
    ) == 1

    # a handler DECORATED with jit evaluates the decorator once at def
    # time, not per request — not a finding
    src_decorated = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def handle_query(x, n):\n"
        "    return x * n\n"
    )
    assert [f for f in lint_source(src_decorated) if f.rule == "L10"] == []


def test_serve_layer_is_l10_clean():
    """The enforcement contract tools/check.sh gates on."""
    from dgen_tpu.lint import lint_paths

    root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "dgen_tpu", "serve",
    )
    assert lint_paths([root], select=["L10"]) == []


# ---------------------------------------------------------------------------
# Provenance stamps (io.export, reused by /healthz)
# ---------------------------------------------------------------------------

def test_provenance_stamp_and_config_hash():
    from dgen_tpu.io.export import config_hash, git_sha, provenance_stamp

    h1 = config_hash(RunConfig(), CFG)
    assert isinstance(h1, str) and len(h1) == 12
    # deterministic, config-sensitive
    assert h1 == config_hash(RunConfig(), CFG)
    assert h1 != config_hash(RunConfig(sizing_iters=8), CFG)
    assert config_hash() is None

    sha = git_sha()
    assert sha is None or (isinstance(sha, str) and len(sha) == 12)

    stamp = provenance_stamp(RunConfig())
    assert set(stamp) == {"git_sha", "config_hash", "jax_backend",
                          "n_devices"}


def test_run_exporter_meta_carries_provenance(tmp_path):
    from dgen_tpu.io.export import RunExporter

    exp = RunExporter(
        str(tmp_path / "run"),
        agent_id=np.arange(4), mask=np.ones(4, np.float32),
    )
    meta = json.load(open(tmp_path / "run" / "meta.json"))
    for k in ("git_sha", "jax_backend", "n_devices"):
        assert k in meta
    assert exp.meta["n_agents"] == 4


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def http_app(engine):
    from dgen_tpu.serve.server import ServeApp, start_in_thread

    app = ServeApp(engine, SERVE_CFG)   # warmup is a cache hit
    srv = start_in_thread(app)
    port = srv.server_address[1]
    yield app, f"http://127.0.0.1:{port}"
    srv.shutdown()
    srv.server_close()
    app.close()


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read())


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def test_healthz_serves_provenance(http_app):
    _app, base = http_app
    code, h = _get(f"{base}/healthz")
    assert code == 200 and h["status"] == "ok"
    for k in ("git_sha", "config_hash", "jax_backend", "n_agents",
              "warm_buckets", "uptime_s"):
        assert k in h
    # every configured bucket program is warm before traffic
    assert set(h["buckets"]) <= set(h["warm_buckets"])


def test_query_endpoint_matches_engine(engine, http_app):
    _app, base = http_app
    body = {"agent_ids": [5, 17], "year": 2016,
            "overrides": {"scale": {"itc_fraction": 0.5}},
            "cash_flow": True}
    code, r = _post(f"{base}/query", body)
    assert code == 200 and r["year"] == 2016
    direct = engine.query(
        [5, 17], year=2016, overrides=body["overrides"], bucket=2,
    )
    for j, row in enumerate(r["results"]):
        assert row["agent_id"] == body["agent_ids"][j]
        # JSON round-trips f32 through double exactly
        assert row["npv"] == pytest.approx(float(direct["npv"][j]))
        assert row["system_kw"] == pytest.approx(
            float(direct["system_kw"][j]))
        assert len(row["cash_flow"]) == direct["cash_flow"].shape[1]
    # cash_flow is omitted unless asked for
    _code, r2 = _post(
        f"{base}/query", {"agent_ids": [5], "year": 2016})
    assert "cash_flow" not in r2["results"][0]


def test_http_error_paths(http_app):
    _app, base = http_app
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"{base}/query", {"agent_ids": [10**9]})
    assert e.value.code == 400
    assert "unknown agent_id" in json.loads(e.value.read())["error"]
    # non-integral ids are rejected, never truncated onto a neighbor
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"{base}/query", {"agent_ids": [17.9]})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"{base}/query", {"agent_ids": []})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{base}/nope")
    assert e.value.code == 404


def test_http_keepalive_survives_refusals(http_app):
    """Refusal paths must not desync a keep-alive connection: a POST
    to a bad route (body read then 404) and an oversize POST (413 +
    Connection: close) both leave the next request answerable."""
    import http.client

    _app, base = http_app
    host, port = base.replace("http://", "").split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    # 404 WITH a body: body is drained, connection stays usable
    conn.request("POST", "/queryy", body=b'{"agent_ids": [5]}')
    r = conn.getresponse()
    assert r.status == 404 and not r.will_close
    r.read()
    conn.request("POST", "/query", body=json.dumps(
        {"agent_ids": [5], "year": 2016}).encode())
    r = conn.getresponse()
    assert r.status == 200
    assert json.loads(r.read())["results"][0]["agent_id"] == 5
    conn.close()
    # oversize body: refused unread, connection explicitly closed
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    conn.request("POST", "/query", body=b"",
                 headers={"Content-Length": str(2 << 20)})
    r = conn.getresponse()
    assert r.status == 413 and r.will_close
    conn.close()


def test_metricz_reports_latency_and_occupancy(http_app):
    _app, base = http_app
    # ensure at least one served request
    _post(f"{base}/query", {"agent_ids": [3, 4, 5]})
    code, m = _get(f"{base}/metricz")
    assert code == 200
    assert m["requests"] >= 1 and m["batches"] >= 1
    assert 0.0 < m["batch_occupancy"] <= 1.0
    assert m["latency_ms"]["p50"] <= m["latency_ms"]["p99"]
    assert m["queue_depth"] == 0
    assert m["buckets"] == list(SERVE_CFG.buckets)
