"""utils.invariants coverage: the allow_nonfinite allowlist path and
the dtype-drift branch of check_transform (previously untested), plus
the shape/leaf-set branches and the clean path."""

import jax.numpy as jnp
import numpy as np
import pytest

from dgen_tpu.utils.invariants import (
    InvariantViolation,
    check_finite,
    check_transform,
)


def _tree(**overrides):
    base = {
        "market_share": jnp.zeros(8, jnp.float32),
        "system_kw_cum": jnp.ones(8, jnp.float32),
        "adopters": jnp.zeros(8, jnp.int32),
    }
    base.update(overrides)
    return base


def test_clean_transform_passes():
    check_transform(_tree(), _tree(), context="clean")


def test_dtype_drift_is_caught():
    # numpy leaf: jnp would silently clamp f64 to f32 under the x64
    # default, which is exactly the widening the harness must SEE when
    # a host-fetched carry drifts
    drifted = _tree(system_kw_cum=np.ones(8, np.float64))
    with pytest.raises(InvariantViolation, match="dtype"):
        check_transform(_tree(), drifted, context="year 2020")


def test_dtype_drift_message_names_the_leaf():
    drifted = _tree(adopters=jnp.zeros(8, jnp.float32))
    with pytest.raises(InvariantViolation, match="adopters"):
        check_transform(_tree(), drifted)


def test_shape_change_is_caught():
    grown = _tree(market_share=jnp.zeros(16, jnp.float32))
    with pytest.raises(InvariantViolation, match="shape"):
        check_transform(_tree(), grown)


def test_leaf_set_change_is_caught():
    after = _tree()
    after["new_column"] = jnp.zeros(8, jnp.float32)
    with pytest.raises(InvariantViolation, match="leaf set"):
        check_transform(_tree(), after)
    before = _tree()
    missing = _tree()
    del missing["adopters"]
    with pytest.raises(InvariantViolation, match="leaf set"):
        check_transform(before, missing)


def test_check_finite_flags_nan_and_counts():
    bad = _tree(market_share=jnp.array(
        [0.0, jnp.nan, jnp.inf, 0.0, 0.0, 0.0, 0.0, 0.0], jnp.float32))
    with pytest.raises(InvariantViolation, match="2 non-finite"):
        check_finite(bad, context="year 2020")


def test_allow_nonfinite_substring_allowlist():
    """The allowlist matches by leaf-path SUBSTRING (mirroring the
    reference's column exception list) and exempts only those leaves."""
    bad = _tree(
        market_share=jnp.full(8, jnp.nan, jnp.float32),
        system_kw_cum=jnp.full(8, jnp.inf, jnp.float32),
    )
    # both leaves allowlisted -> clean
    check_finite(bad, allow_nonfinite=("market_share", "system_kw"),
                 context="allowlisted")
    # only one allowlisted -> the other still raises, and the message
    # names the non-exempt leaf
    with pytest.raises(InvariantViolation, match="system_kw_cum"):
        check_finite(bad, allow_nonfinite=("market_share",))


def test_allow_nonfinite_ignores_int_leaves():
    """Integer leaves have no non-finite values; the float check must
    not trip on them regardless of the allowlist."""
    t = _tree(adopters=jnp.full(8, 2**31 - 1, jnp.int32))
    check_finite(t, allow_nonfinite=())


def test_check_transform_accepts_numpy_and_mixed_trees():
    """The harness runs host-side on fetched carries: numpy leaves are
    first-class."""
    before = {"a": np.zeros(4, np.float32)}
    after = {"a": np.zeros(4, np.float32)}
    check_transform(before, after)
    with pytest.raises(InvariantViolation, match="dtype"):
        check_transform(before, {"a": np.zeros(4, np.float64)})
